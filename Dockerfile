# Container image for the vtpu-service control plane (the reference ships
# vc-scheduler / vc-controller-manager / vc-webhook-manager images via its
# installer; the rebuild packs the combined daemon + CLI into one image).
FROM python:3.12-slim

WORKDIR /opt/volcano-tpu
COPY pyproject.toml README.md ./
COPY volcano_tpu ./volcano_tpu
RUN pip install --no-cache-dir . && mkdir -p /var/lib/vtpu

VOLUME /var/lib/vtpu
EXPOSE 11250
ENTRYPOINT ["vtpu-service"]
CMD ["--bind-address", "0.0.0.0", "--listen-port", "11250", \
     "--state-path", "/var/lib/vtpu/state.ckpt"]
