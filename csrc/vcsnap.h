// Public C ABI of the native library (snapshot serializer kernels +
// the reclaim engine).  Included by BOTH vcsnap.cc and the smoke test
// so signature drift is a compile error instead of runtime UB.
#pragma once
#include <cstdint>

extern "C" {

int vcsnap_version();
void vcsnap_pack_bits(const int32_t* indices, const int64_t* offsets,
                      int64_t rows, int32_t words, uint32_t* out);
void vcsnap_scatter_f32(const int32_t* slots, const float* values,
                        const int64_t* offsets, int64_t rows,
                        int32_t width, float* out);
void vcsnap_gather_rows_f32(const float* src, const int32_t* order,
                            int64_t rows, int32_t width, float* out);
void vcsnap_less_equal(const float* l, const float* rhs, const float* eps,
                       const uint8_t* scalar_slot, int64_t rows,
                       int32_t r, uint8_t* out);

// Multi-array wire frame (remote-solver snapshot codec; see vcsnap.cc).
int64_t vcsnap_frame_bytes(const uint8_t* ndims, const int64_t* nbytes,
                           int32_t n, int64_t manifest_len);
void vcsnap_frame_pack(const uint8_t* dtypes, const uint8_t* ndims,
                       const int64_t* dims_flat, const int64_t* nbytes,
                       const uint8_t* const* srcs, int32_t n,
                       const uint8_t* manifest, int64_t manifest_len,
                       uint8_t* out);
int32_t vcsnap_frame_info(const uint8_t* buf, int64_t len,
                          int64_t* manifest_off, int64_t* manifest_len);
int32_t vcsnap_frame_unpack(const uint8_t* buf, int64_t len,
                            uint8_t* dtypes, uint8_t* ndims,
                            int64_t* dims_flat, int64_t* data_off,
                            int64_t* nbytes);

// Delta records (protocol v2 remote-solver frames; see vcsnap.cc).
int64_t vcsnap_delta_check(const int64_t* desc, int64_t desc_len,
                           int64_t rows, int64_t row_bytes,
                           int64_t payload_bytes,
                           int64_t mirror_gen, int64_t base_gen);
int32_t vcsnap_delta_apply(uint8_t* dst, int64_t rows, int64_t row_bytes,
                           const int64_t* desc, int64_t desc_len,
                           const uint8_t* payload, int64_t payload_bytes,
                           int64_t mirror_gen, int64_t base_gen);

void* vcreclaim_ctx_new(
    const long long* node_ptr, const long long* node_rows,
    int16_t* p_status, const int32_t* p_job,
    const float* req, const uint8_t* req_empty, const uint8_t* critical,
    const int32_t* j_minav, int32_t* j_ready_base,
    int32_t* j_cnt_alloc, int32_t* j_cnt_run, int32_t* j_cnt_releasing,
    float* j_alloc_res, const int32_t* q_of_job,
    const uint8_t* q_reclaimable, float* q_alloc,
    const float* q_deserved, const uint8_t* q_has_deserved,
    float* fi, float* n_releasing,
    const int32_t* tiers, long long tiers_len,
    const float* eps, const uint8_t* scalar_slot,
    const uint8_t* alive, const float* init_req_base,
    long long Nn, long long R,
    long long st_running, long long st_releasing,
    float* n_pipelined, int32_t* n_ntasks, const int32_t* n_maxtasks,
    long long* pipe_node, int32_t* j_cnt_pending, long long* j_waiting,
    long long* j_version, long long* q_version, long long Qn,
    const int32_t* j_prio, const int32_t* j_rank,
    const int32_t* p_node,
    const float* total_res, const int32_t* job_order,
    long long job_order_len, long long reclaim_gated);
void vcreclaim_ctx_free(void* ctx);
long long vcreclaim_step(
    void* ctx_p, long long prow, long long qid,
    long long* cursor,
    const uint8_t* anym, const uint8_t* feas, const uint8_t* stat,
    const uint8_t* slots,
    long long* out_evicted, long long* out_n_evicted,
    long long max_evicted);
long long vcreclaim_drive_mq(
    void* ctx_p, long long has_pred,
    const long long* qs_ids, long long n_queues,
    const double* q_create, const int32_t* q_uid_rank,
    const uint8_t* q_named, long long qorder_has_prop,
    int8_t* q_overused, uint8_t* out_q_dropped,
    const long long* job_ids, long long n_jobs,
    const long long* job_qslot,
    const long long* task_ptr, const long long* task_rows,
    long long* task_cursor, const int32_t* row_maskidx,
    long long n_masks,
    unsigned long long* anym_ptrs, unsigned long long* feas_ptrs,
    unsigned long long* stat_ptrs, unsigned long long* slots_ptrs,
    unsigned long long* initreq_ptrs,
    const long long* mask_qids,
    long long* mask_cursors,
    long long* out_evicted, long long* out_n_evicted, long long max_ev,
    long long* out_pipe_rows, long long* out_pipe_nodes,
    long long* out_n_pipe,
    long long* out_touched, long long* out_n_touched,
    long long max_touched,
    long long* out_yield_job, uint8_t* out_job_dropped);

}  // extern "C"
