// Sanitizer smoke test for the native library (run via `make test`):
// exercises the snapshot serializer entry points and the reclaim engine
// (ctx build, single step, full drive) on a small synthetic cluster,
// under ASAN/TSAN builds.  Asserts behavioral basics — the exhaustive
// semantics checks live in the Python fuzz harness
// (tests/test_evict_oracle.py); this binary exists to run the C code
// under the sanitizers without the CPython/LD_PRELOAD interceptor
// fights.
//
// Build+run:  make test   (links vcsnap.cc directly, ASAN flags)

#undef NDEBUG
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

#include "vcsnap.h"

enum { ST_PENDING = 1 << 0, ST_RUNNING = 1 << 5, ST_RELEASING = 1 << 7 };

static void smoke_serializer() {
  // CSR bit pack: rows {0:[1,33]}, {1:[2]}.
  std::vector<int32_t> idx = {1, 33, 2};
  std::vector<int64_t> off = {0, 2, 3};
  std::vector<uint32_t> bits(2 * 2, 0);
  vcsnap_pack_bits(idx.data(), off.data(), 2, 2, bits.data());
  assert(bits[0] == (1u << 1) && bits[1] == (1u << 1));
  assert(bits[2] == (1u << 2) && bits[3] == 0);
  // CSR scatter: row 0 slot 1 = 7.5.
  std::vector<int32_t> slots = {1};
  std::vector<float> vals = {7.5f};
  std::vector<int64_t> soff = {0, 1};
  std::vector<float> dense(1 * 3, 0.0f);
  vcsnap_scatter_f32(slots.data(), vals.data(), soff.data(), 1, 3,
                     dense.data());
  assert(dense[1] == 7.5f && dense[0] == 0.0f);
  // Row gather; -1 rows are skipped (the Python wrapper provides a
  // zeroed out-buffer, so skipped == zero row).
  std::vector<float> srcm = {1, 2, 3, 4};
  std::vector<int32_t> order = {1, -1};
  std::vector<float> gout(2 * 2, 0.0f);
  vcsnap_gather_rows_f32(srcm.data(), order.data(), 2, 2, gout.data());
  assert(gout[0] == 3 && gout[1] == 4 && gout[2] == 0 && gout[3] == 0);
  // Epsilon LessEqual rows.
  std::vector<float> l = {1000, 500, 2000, 500};
  std::vector<float> rhs = {1500, 600};
  std::vector<float> eps = {10, 10};
  std::vector<uint8_t> ss = {0, 0};
  std::vector<uint8_t> ok(2, 2);
  vcsnap_less_equal(l.data(), rhs.data(), eps.data(), ss.data(), 2, 2,
                    ok.data());
  assert(ok[0] == 1 && ok[1] == 0);
  std::printf("serializer kernels OK\n");
}

// Multi-queue drive: victims in queue 0; reclaimers split between
// queues 1 and 2.  The cross-queue round-robin must place all six
// reclaimers (one eviction each) and drop both queues via the
// drained-top-job quirk.
static void smoke_drive_mq() {
  const long long N = 4, R = 2, P = 14, J = 14, Q = 3;
  std::vector<long long> node_ptr = {0, 2, 4, 6, 8};
  std::vector<long long> node_rows = {0, 1, 2, 3, 4, 5, 6, 7};
  std::vector<int16_t> p_status(P, ST_RUNNING);
  for (int i = 8; i < 14; ++i) p_status[i] = ST_PENDING;
  std::vector<int32_t> p_job(P);
  for (int i = 0; i < 14; ++i) p_job[i] = i;
  std::vector<float> req(P * R);
  for (int i = 0; i < 14; ++i) {
    req[i * R + 0] = 4000.0f;
    req[i * R + 1] = 1.0e9f;
  }
  std::vector<uint8_t> req_empty(P, 0), critical(P, 0);
  std::vector<int32_t> j_minav(J, 1);
  std::vector<int32_t> j_ready(J, 0), j_alloc(J, 0), j_run(J, 0),
      j_rel(J, 0), j_pend(J, 0);
  for (int i = 0; i < 8; ++i) { j_ready[i] = 1; j_alloc[i] = 1;
                                j_run[i] = 1; }
  for (int i = 8; i < 14; ++i) j_pend[i] = 1;
  std::vector<float> j_alloc_res(J * R, 0.0f);
  for (int i = 0; i < 8; ++i) {
    j_alloc_res[i * R] = 4000.0f;
    j_alloc_res[i * R + 1] = 1.0e9f;
  }
  std::vector<int32_t> q_of_job(J, 0);
  for (int i = 8; i < 11; ++i) q_of_job[i] = 1;
  for (int i = 11; i < 14; ++i) q_of_job[i] = 2;
  std::vector<uint8_t> q_rec = {1, 1, 1};
  std::vector<float> q_alloc = {32000.0f, 8.0e9f, 0.0f, 0.0f,
                                0.0f, 0.0f};
  std::vector<float> q_des = {0.0f, 0.0f, 1.0e12f, 1.0e12f,
                              1.0e12f, 1.0e12f};
  std::vector<uint8_t> q_has = {1, 1, 1};
  std::vector<float> fi(N * R, 0.0f), n_rel(N * R, 0.0f);
  std::vector<int32_t> tiers = {0, 1, -1, 2, -1};
  std::vector<float> eps = {10.0f, 1.0e7f};
  std::vector<uint8_t> scalar_slot = {0, 0};
  std::vector<uint8_t> alive(N, 1);
  std::vector<float> init_req = req;
  std::vector<float> n_pip(N * R, 0.0f);
  std::vector<int32_t> n_ntasks = {2, 2, 2, 2};
  std::vector<int32_t> n_maxtasks = {0, 0, 0, 0};
  std::vector<long long> pipe_node(P, -1);
  std::vector<long long> j_wait(J, 0), j_ver(J, 0), q_ver(Q, 0);
  std::vector<int32_t> j_prio(J, 100);
  for (int i = 8; i < 14; ++i) j_prio[i] = 10000;
  std::vector<int32_t> j_rank(J);
  for (int i = 0; i < 14; ++i) j_rank[i] = i;
  std::vector<int32_t> p_node(P, -1);
  for (int i = 0; i < 8; ++i) p_node[i] = i / 2;
  std::vector<float> total_res = {32000.0f, 8.0e9f};
  std::vector<int32_t> job_order = {0, 2};

  void* ctx = vcreclaim_ctx_new(
      node_ptr.data(), node_rows.data(), p_status.data(), p_job.data(),
      req.data(), req_empty.data(), critical.data(), j_minav.data(),
      j_ready.data(), j_alloc.data(), j_run.data(), j_rel.data(),
      j_alloc_res.data(), q_of_job.data(), q_rec.data(), q_alloc.data(),
      q_des.data(), q_has.data(), fi.data(), n_rel.data(), tiers.data(),
      (long long)tiers.size(), eps.data(), scalar_slot.data(),
      alive.data(), init_req.data(), N, R, ST_RUNNING, ST_RELEASING,
      n_pip.data(), n_ntasks.data(), n_maxtasks.data(), pipe_node.data(),
      j_pend.data(), j_wait.data(), j_ver.data(), q_ver.data(), Q,
      j_prio.data(), j_rank.data(), p_node.data(), total_res.data(),
      job_order.data(), (long long)job_order.size(), 1);
  assert(ctx != nullptr);

  std::vector<long long> qs_ids = {1, 2};
  std::vector<double> q_create = {1.0, 2.0};
  std::vector<int32_t> q_uid_rank = {0, 1};
  std::vector<uint8_t> q_named(Q * R, 1);
  std::vector<int8_t> q_over = {-1, -1};
  std::vector<uint8_t> q_dropped = {0, 0};
  std::vector<long long> job_ids = {8, 9, 10, 11, 12, 13};
  std::vector<long long> job_qslot = {0, 0, 0, 1, 1, 1};
  std::vector<long long> task_ptr = {0, 1, 2, 3, 4, 5, 6};
  std::vector<long long> task_rows = {8, 9, 10, 11, 12, 13};
  std::vector<long long> task_cur(6, 0);
  std::vector<int32_t> row_maskidx(P, 0);
  std::vector<uint8_t> anym(N, 1), feas(N, 1), ones(N, 1),
      slots_mask(N, 1);
  unsigned long long anym_p[1] = {(unsigned long long)anym.data()};
  unsigned long long feas_p[1] = {(unsigned long long)feas.data()};
  unsigned long long stat_p[1] = {(unsigned long long)ones.data()};
  unsigned long long slot_p[1] = {
      (unsigned long long)slots_mask.data()};
  std::vector<float> ireq8 = {4000.0f, 1.0e9f};
  unsigned long long ireq_p[1] = {(unsigned long long)ireq8.data()};
  std::vector<long long> mask_qids = {1};
  long long mask_cur[1] = {0};
  std::vector<long long> evicted(P), pipe_rows(P), pipe_nodes(P),
      touched(2 * P);
  long long n_ev = 0, n_pipe = 0, n_touch = 0, yield_job = -1;
  std::vector<uint8_t> dropped(6, 0);
  long long rc = vcreclaim_drive_mq(
      ctx, 1, qs_ids.data(), 2, q_create.data(), q_uid_rank.data(),
      q_named.data(), 1, q_over.data(), q_dropped.data(),
      job_ids.data(), 6, job_qslot.data(),
      task_ptr.data(), task_rows.data(), task_cur.data(),
      row_maskidx.data(), 1, anym_p, feas_p, stat_p, slot_p, ireq_p,
      mask_qids.data(), mask_cur, evicted.data(), &n_ev, P,
      pipe_rows.data(), pipe_nodes.data(), &n_pipe, touched.data(),
      &n_touch, 2 * P, &yield_job, dropped.data());
  std::printf("drive_mq: rc=%lld evicted=%lld pipelined=%lld "
              "qdrop=%d,%d over=%d,%d\n",
              rc, n_ev, n_pipe, (int)q_dropped[0], (int)q_dropped[1],
              (int)q_over[0], (int)q_over[1]);
  assert(rc == 0);
  assert(n_pipe == 6);   // every reclaimer placed, across both queues
  assert(n_ev == 6);     // one victim each
  assert(q_over[0] == 0 && q_over[1] == 0);
  assert(q_dropped[0] == 1 && q_dropped[1] == 1);
  vcreclaim_ctx_free(ctx);
  std::printf("drive_mq smoke OK\n");
}

// Crafted-frame regression for the vcsnap_frame_unpack bounds checks:
// every `off + X > len` comparison was rewritten `X > len - off`
// because a hostile nb near INT64_MAX wrapped the addition (signed
// overflow, UB) into a PASSING check.  Under the UBSan test build the
// OLD form traps here; the new form must reject every corruption with
// -1 and still accept the valid frame.
static void smoke_hostile_frames() {
  // Valid 2-array frame via the real packer: f32[3] + int8[5].
  std::vector<float> a0 = {1.0f, 2.0f, 3.0f};
  std::vector<uint8_t> a1 = {1, 2, 3, 4, 5};
  uint8_t dtypes[2] = {0, 6};  // kVcsnapDtypes: 0 = f32, 6 = uint8
  uint8_t ndims[2] = {1, 1};
  int64_t dims_flat[2] = {3, 5};
  int64_t nbytes[2] = {12, 5};
  const uint8_t* srcs[2] = {
      reinterpret_cast<const uint8_t*>(a0.data()), a1.data()};
  const char* man = "{\"op\":\"x\"}";
  int64_t mlen = 10;
  int64_t total = vcsnap_frame_bytes(ndims, nbytes, 2, mlen);
  std::vector<uint8_t> frame(static_cast<size_t>(total), 0);
  vcsnap_frame_pack(dtypes, ndims, dims_flat, nbytes, srcs, 2,
                    reinterpret_cast<const uint8_t*>(man), mlen,
                    frame.data());
  uint8_t out_dt[2], out_nd[2];
  int64_t out_dims[16], out_off[2], out_nb[2];
  assert(vcsnap_frame_unpack(frame.data(), total, out_dt, out_nd,
                             out_dims, out_off, out_nb) == 0);
  assert(out_nb[0] == 12 && out_nb[1] == 5);
  assert(std::memcmp(frame.data() + out_off[1], a1.data(), 5) == 0);

  // Locate array 0's header: it starts right after the aligned
  // manifest; its nb field sits at header + 8 + 8*nd.
  int64_t hdr0 = (16 + mlen + 7) & ~int64_t(7);
  int64_t nb_at = hdr0 + 8 + 8 * 1;

  // (1) nb near INT64_MAX: the old `off + nb > len` wrapped negative
  // and accepted; the rewritten `nb > len - off` must reject (the
  // dtype-width equality also rejects — both layers must hold).
  std::vector<uint8_t> evil = frame;
  int64_t huge = INT64_MAX - 4;
  std::memcpy(evil.data() + nb_at, &huge, 8);
  assert(vcsnap_frame_unpack(evil.data(), total, out_dt, out_nd,
                             out_dims, out_off, out_nb) == -1);

  // (2) nb consistent with a hostile dim that claims the whole frame:
  // dim = total (so elems*size passes the equality for int8 only if
  // nb == total) — data would run past the end; must reject.
  evil = frame;
  int64_t dim_at = hdr0 + 8;
  // Rewrite array 0 as int8[total] with nb = total.
  evil[hdr0] = 6;  // int8
  std::memcpy(evil.data() + dim_at, &total, 8);
  std::memcpy(evil.data() + nb_at, &total, 8);
  assert(vcsnap_frame_unpack(evil.data(), total, out_dt, out_nd,
                             out_dims, out_off, out_nb) == -1);

  // (3) negative nb must reject.
  evil = frame;
  int64_t neg = -8;
  std::memcpy(evil.data() + nb_at, &neg, 8);
  assert(vcsnap_frame_unpack(evil.data(), total, out_dt, out_nd,
                             out_dims, out_off, out_nb) == -1);

  // (4) truncated frame: headers intact, last data segment cut short.
  assert(vcsnap_frame_unpack(frame.data(), total - 4, out_dt, out_nd,
                             out_dims, out_off, out_nb) == -1);

  // (5) negative dim must reject (elems guard).
  evil = frame;
  int64_t negdim = -3;
  std::memcpy(evil.data() + dim_at, &negdim, 8);
  assert(vcsnap_frame_unpack(evil.data(), total, out_dt, out_nd,
                             out_dims, out_off, out_nb) == -1);

  std::printf("hostile-frame unpack OK\n");
}

// Crafted-delta regression (protocol v2, ISSUE 10): the delta record
// validator must reject every corruption a hostile peer can encode —
// truncated descriptors/payloads, overlapping or unsorted row ranges,
// bounds near INT64_MAX (where additive checks would wrap, UB under
// the UBSan build), and a base-generation mismatch (which must fall
// back to a full frame, never patch the wrong mirror) — and the apply
// must leave the mirror untouched on every rejection.
static void smoke_delta_records() {
  const int64_t rows = 8, row_bytes = 4;
  uint8_t mirror[8 * 4];
  uint8_t orig[8 * 4];
  for (int i = 0; i < 32; ++i) mirror[i] = orig[i] = (uint8_t)i;
  // Valid delta: rows [1,3) and [5,6) replaced.
  int64_t desc[] = {2, 1, 3, 5, 6};
  uint8_t payload[3 * 4];
  for (int i = 0; i < 12; ++i) payload[i] = (uint8_t)(100 + i);
  assert(vcsnap_delta_check(desc, 5, rows, row_bytes, 12, 7, 7) == 3);
  assert(vcsnap_delta_apply(mirror, rows, row_bytes, desc, 5, payload,
                            12, 7, 7) == 0);
  assert(mirror[0] == 0);                      // row 0 untouched
  assert(mirror[4] == 100 && mirror[11] == 107);   // rows 1-2 patched
  assert(std::memcmp(mirror + 12, orig + 12, 8) == 0);  // rows 3-4
  assert(mirror[20] == 108 && mirror[23] == 111);  // row 5 patched
  std::memcpy(mirror, orig, 32);

  // (1) ack/base-generation mismatch: the mirror holds gen 7, the
  // delta claims base 6 — must report -2 and touch nothing.
  assert(vcsnap_delta_check(desc, 5, rows, row_bytes, 12, 7, 6) == -2);
  assert(vcsnap_delta_apply(mirror, rows, row_bytes, desc, 5, payload,
                            12, 7, 6) == -2);
  assert(std::memcmp(mirror, orig, 32) == 0);

  // (2) truncated descriptor: n_ranges claims more pairs than ride.
  int64_t trunc[] = {2, 1, 3};
  assert(vcsnap_delta_check(trunc, 3, rows, row_bytes, 12, 7, 7) == -1);
  // n_ranges near INT64_MAX: `1 + 2 * n` would wrap; the division-form
  // check must reject without the multiply ever happening.
  int64_t huge_n[] = {INT64_MAX - 1, 1, 3};
  assert(vcsnap_delta_check(huge_n, 3, rows, row_bytes, 12, 7, 7) == -1);

  // (3) truncated payload: ranges sum to 3 rows but only 2 rows ride.
  assert(vcsnap_delta_check(desc, 5, rows, row_bytes, 8, 7, 7) == -1);
  // Payload not a whole number of rows.
  assert(vcsnap_delta_check(desc, 5, rows, row_bytes, 11, 7, 7) == -1);

  // (4) overlapping ranges ([1,4) then [3,6)) and unsorted ranges.
  int64_t overlap[] = {2, 1, 4, 3, 6};
  assert(vcsnap_delta_check(overlap, 5, rows, row_bytes, 24, 7, 7)
         == -1);
  int64_t unsorted[] = {2, 5, 6, 1, 3};
  assert(vcsnap_delta_check(unsorted, 5, rows, row_bytes, 12, 7, 7)
         == -1);

  // (5) hostile bounds near INT64_MAX: stop past rows, start/stop both
  // huge (s >= e and e > rows must each reject without `s + X`
  // arithmetic), empty and negative ranges.
  int64_t huge_e[] = {1, 0, INT64_MAX - 2};
  assert(vcsnap_delta_check(huge_e, 3, rows, row_bytes, 4, 7, 7) == -1);
  int64_t huge_se[] = {1, INT64_MAX - 2, INT64_MAX - 2};
  assert(vcsnap_delta_check(huge_se, 3, rows, row_bytes, 0, 7, 7) == -1);
  int64_t empty_r[] = {1, 2, 2};
  assert(vcsnap_delta_check(empty_r, 3, rows, row_bytes, 0, 7, 7) == -1);
  int64_t neg[] = {1, -1, 2};
  assert(vcsnap_delta_check(neg, 3, rows, row_bytes, 12, 7, 7) == -1);

  // (6) zero-range delta (a pure "nothing changed" record) is valid.
  int64_t none[] = {0};
  assert(vcsnap_delta_check(none, 1, rows, row_bytes, 0, 7, 7) == 0);
  assert(vcsnap_delta_apply(mirror, rows, row_bytes, none, 1, payload,
                            0, 7, 7) == 0);
  assert(std::memcmp(mirror, orig, 32) == 0);

  // (7) zero-width rows (row_bytes 0): only an empty payload passes.
  assert(vcsnap_delta_check(desc, 5, rows, 0, 0, 7, 7) == 3);
  assert(vcsnap_delta_check(desc, 5, rows, 0, 4, 7, 7) == -1);

  std::printf("delta records OK\n");
}

int main() {
  std::printf("vcsnap_version=%d\n", vcsnap_version());
  smoke_serializer();
  smoke_hostile_frames();
  smoke_delta_records();

  // Cluster: 4 nodes x 2 slots; queue 0 = "victim" (reclaimable),
  // queue 1 = "premium".  Rows 0-7: running victims (job per row, queue
  // 0); rows 8-11: pending premium reclaimers (job 8+, queue 1).
  const long long N = 4, R = 2, P = 12, J = 12, Q = 2;
  std::vector<long long> node_ptr = {0, 2, 4, 6, 8};
  std::vector<long long> node_rows = {0, 1, 2, 3, 4, 5, 6, 7};
  std::vector<int16_t> p_status(P, ST_RUNNING);
  for (int i = 8; i < 12; ++i) p_status[i] = ST_PENDING;
  std::vector<int32_t> p_job(P);
  for (int i = 0; i < 12; ++i) p_job[i] = i;
  std::vector<float> req(P * R);
  for (int i = 0; i < 12; ++i) {
    req[i * R + 0] = 4000.0f;  // 4 cpu each
    req[i * R + 1] = 1.0e9f;
  }
  std::vector<uint8_t> req_empty(P, 0), critical(P, 0);
  std::vector<int32_t> j_minav(J, 1);
  std::vector<int32_t> j_ready(J, 0), j_alloc(J, 0), j_run(J, 0),
      j_rel(J, 0), j_pend(J, 0);
  for (int i = 0; i < 8; ++i) { j_ready[i] = 1; j_alloc[i] = 1;
                                j_run[i] = 1; }
  for (int i = 8; i < 12; ++i) j_pend[i] = 1;
  std::vector<float> j_alloc_res(J * R, 0.0f);
  for (int i = 0; i < 8; ++i) {
    j_alloc_res[i * R] = 4000.0f;
    j_alloc_res[i * R + 1] = 1.0e9f;
  }
  std::vector<int32_t> q_of_job(J, 0);
  for (int i = 8; i < 12; ++i) q_of_job[i] = 1;
  std::vector<uint8_t> q_rec = {1, 1};
  std::vector<float> q_alloc = {32000.0f, 8.0e9f, 0.0f, 0.0f};
  // victim queue deserved 0 (sheds everything); premium huge.
  std::vector<float> q_des = {0.0f, 0.0f, 1.0e12f, 1.0e12f};
  std::vector<uint8_t> q_has = {1, 1};
  std::vector<float> fi(N * R, 0.0f), n_rel(N * R, 0.0f);
  // tiers: [gang, conformance] | [proportion]
  std::vector<int32_t> tiers = {0, 1, -1, 2, -1};
  std::vector<float> eps = {10.0f, 1.0e7f};
  std::vector<uint8_t> scalar_slot = {0, 0};
  std::vector<uint8_t> alive(N, 1);
  std::vector<float> init_req = req;  // same as req
  std::vector<float> n_pip(N * R, 0.0f);
  std::vector<int32_t> n_ntasks = {2, 2, 2, 2};
  std::vector<int32_t> n_maxtasks = {0, 0, 0, 0};
  std::vector<long long> pipe_node(P, -1);
  std::vector<long long> j_wait(J, 0), j_ver(J, 0), q_ver(Q, 0);
  std::vector<int32_t> j_prio(J, 100);
  for (int i = 8; i < 12; ++i) j_prio[i] = 10000;
  std::vector<int32_t> j_rank(J);
  for (int i = 0; i < 12; ++i) j_rank[i] = i;
  std::vector<int32_t> p_node(P, -1);
  for (int i = 0; i < 8; ++i) p_node[i] = i / 2;
  std::vector<float> total_res = {32000.0f, 8.0e9f};
  std::vector<int32_t> job_order = {0, 2};  // priority, drf

  void* ctx = vcreclaim_ctx_new(
      node_ptr.data(), node_rows.data(), p_status.data(), p_job.data(),
      req.data(), req_empty.data(), critical.data(), j_minav.data(),
      j_ready.data(), j_alloc.data(), j_run.data(), j_rel.data(),
      j_alloc_res.data(), q_of_job.data(), q_rec.data(), q_alloc.data(),
      q_des.data(), q_has.data(), fi.data(), n_rel.data(), tiers.data(),
      (long long)tiers.size(), eps.data(), scalar_slot.data(),
      alive.data(), init_req.data(), N, R, ST_RUNNING, ST_RELEASING,
      n_pip.data(), n_ntasks.data(), n_maxtasks.data(), pipe_node.data(),
      j_pend.data(), j_wait.data(), j_ver.data(), q_ver.data(), Q,
      j_prio.data(), j_rank.data(), p_node.data(), total_res.data(),
      job_order.data(), (long long)job_order.size(), 1);
  assert(ctx != nullptr);

  // ---- single step: reclaimer row 8 should evict a victim on node 0
  // and pipeline there.
  std::vector<uint8_t> anym(N, 1), feas(N, 1), ones(N, 1),
      slots_mask(N, 1);
  long long cursor = 0;
  std::vector<long long> evicted(P);
  long long n_ev = 0;
  long long node = vcreclaim_step(
      ctx, 8, 1, &cursor, anym.data(), feas.data(), ones.data(),
      slots_mask.data(), evicted.data(), &n_ev, P);
  std::printf("step: node=%lld evicted=%lld\n", node, n_ev);
  assert(node == 0);
  assert(n_ev == 1);
  assert(p_status[evicted[0]] == ST_RELEASING);
  // Step does not pipeline (the Python side does); do it here by hand.
  fi[node * R] -= req[8 * R];
  fi[node * R + 1] -= req[8 * R + 1];
  j_pend[8] -= 1;

  // ---- drive: the remaining reclaimers 9-11 drain through the C
  // round-robin (single-queue degenerate case of the MQ driver).
  std::vector<long long> job_ids = {9, 10, 11};
  std::vector<long long> job_qslot = {0, 0, 0};
  std::vector<long long> task_ptr = {0, 1, 2, 3};
  std::vector<long long> task_rows = {9, 10, 11};
  std::vector<long long> task_cur(3, 0);
  std::vector<int32_t> row_maskidx(P, 0);
  unsigned long long anym_p[1] = {(unsigned long long)anym.data()};
  unsigned long long feas_p[1] = {(unsigned long long)feas.data()};
  unsigned long long stat_p[1] = {(unsigned long long)ones.data()};
  unsigned long long slot_p[1] = {
      (unsigned long long)slots_mask.data()};
  std::vector<float> ireq8 = {4000.0f, 1.0e9f};
  unsigned long long ireq_p[1] = {(unsigned long long)ireq8.data()};
  std::vector<long long> qs_ids1 = {1};
  std::vector<double> q_create1 = {1.0};
  std::vector<int32_t> q_rank1 = {0};
  std::vector<uint8_t> q_named1(Q * R, 1);
  std::vector<int8_t> q_over1 = {-1};
  std::vector<uint8_t> q_drop1 = {0};
  std::vector<long long> mask_qids1 = {1};
  long long mask_cur[1] = {0};
  long long n_ev2 = 0, n_pipe = 0, n_touch = 0, yield_job = -1;
  std::vector<long long> pipe_rows(P), pipe_nodes(P), touched(2 * P);
  std::vector<uint8_t> dropped(3, 0);
  long long rc = vcreclaim_drive_mq(
      ctx, 1, qs_ids1.data(), 1, q_create1.data(), q_rank1.data(),
      q_named1.data(), 1, q_over1.data(), q_drop1.data(),
      job_ids.data(), 3, job_qslot.data(),
      task_ptr.data(), task_rows.data(),
      task_cur.data(), row_maskidx.data(), 1, anym_p, feas_p, stat_p,
      slot_p, ireq_p, mask_qids1.data(), mask_cur,
      evicted.data(), &n_ev2, P,
      pipe_rows.data(), pipe_nodes.data(), &n_pipe, touched.data(),
      &n_touch, 2 * P, &yield_job, dropped.data());
  std::printf("drive: rc=%lld evicted=%lld pipelined=%lld\n", rc, n_ev2,
              n_pipe);
  assert(rc == 0);
  assert(n_pipe == 3);   // all three reclaimers placed
  assert(n_ev2 == 3);    // one victim each
  vcreclaim_ctx_free(ctx);

  smoke_drive_mq();
  std::printf("vcsnap smoke OK\n");
  return 0;
}
