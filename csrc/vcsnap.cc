// vcsnap: native snapshot serializer for the volcano_tpu scheduler.
//
// This is the rebuild's C++ side of the host<->device bridge (SURVEY.md
// section 2.1 "Scheduler cache" / BASELINE north star): the hot marshalling
// loops that flatten the session snapshot (Tasks x Nodes x Queues) into the
// dense arrays consumed by the JAX solver.  The reference relies on compiled
// Go for its cache/snapshot path (pkg/scheduler/cache/cache.go:652-730);
// here the per-row packing/scatter loops run as C++ over columnar CSR
// buffers prepared by the Python store, parallelized over row chunks.
//
// Exposed as a plain C ABI consumed via ctypes (volcano_tpu/native.py);
// every function writes into caller-allocated NumPy buffers, so no memory
// management crosses the boundary.
//
// Build: make -C csrc          (produces libvcsnap.so next to this file)
//        make -C csrc asan     (AddressSanitizer build, libvcsnap_asan.so)

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <functional>
#include <thread>
#include <vector>

namespace {

// Run fn(begin, end) over [0, n) in parallel chunks.  Small inputs stay
// single-threaded to avoid thread-spawn overhead dominating.
void parallel_for(int64_t n, int64_t grain,
                  const std::function<void(int64_t, int64_t)>& fn) {
  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 1;
  int64_t chunks = std::min<int64_t>(hw, (n + grain - 1) / grain);
  if (chunks <= 1) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> threads;
  int64_t per = (n + chunks - 1) / chunks;
  threads.reserve(static_cast<size_t>(chunks));
  for (int64_t c = 0; c < chunks; ++c) {
    int64_t b = c * per;
    int64_t e = std::min(n, b + per);
    if (b >= e) break;
    threads.emplace_back(fn, b, e);
  }
  for (auto& t : threads) t.join();
}

}  // namespace

extern "C" {

int vcsnap_version() { return 1; }

// CSR bitset pack: for each row i, set bits idx[off[i]..off[i+1]) in
// out[i * words .. (i+1) * words).  `out` must be zero-initialized by the
// caller (NumPy zeros).  Indices >= words*32 are ignored defensively.
void vcsnap_pack_bits(const int32_t* idx, const int64_t* off, int64_t rows,
                      int32_t words, uint32_t* out) {
  const int64_t max_bit = static_cast<int64_t>(words) * 32;
  parallel_for(rows, 4096, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      uint32_t* row = out + i * words;
      for (int64_t k = off[i]; k < off[i + 1]; ++k) {
        int64_t bit = idx[k];
        if (bit < 0 || bit >= max_bit) continue;
        row[bit >> 5] |= (1u << (bit & 31));
      }
    }
  });
}

// CSR slot scatter: for each row i, out[i * r + slot[k]] = val[k] for
// k in off[i]..off[i+1).  `out` zero-initialized by the caller.
void vcsnap_scatter_f32(const int32_t* slot, const float* val,
                        const int64_t* off, int64_t rows, int32_t r,
                        float* out) {
  parallel_for(rows, 4096, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      float* row = out + static_cast<int64_t>(i) * r;
      for (int64_t k = off[i]; k < off[i + 1]; ++k) {
        int32_t s = slot[k];
        if (s < 0 || s >= r) continue;
        row[s] = val[k];
      }
    }
  });
}

// Row gather with padding: out[i] = src[order[i]] for i < n; rows with
// order[i] < 0 are left zeroed.  Row width r floats.
void vcsnap_gather_rows_f32(const float* src, const int32_t* order, int64_t n,
                            int32_t r, float* out) {
  parallel_for(n, 8192, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      int32_t s = order[i];
      if (s < 0) continue;
      std::memcpy(out + i * r, src + static_cast<int64_t>(s) * r,
                  sizeof(float) * static_cast<size_t>(r));
    }
  });
}

// Epsilon-tolerant Resource.LessEqual over row pairs
// (resource_info.go:286-320): per slot `l < r or |l-r| < eps`, extended
// scalar slots requesting <= one quantum always pass.  l is [rows, r],
// rhs a single [r] row (the common fit-check shape); out[i] in {0,1}.
void vcsnap_less_equal(const float* l, const float* rhs, const float* eps,
                       const uint8_t* scalar_slot, int64_t rows, int32_t r,
                       uint8_t* out) {
  parallel_for(rows, 8192, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      const float* row = l + i * r;
      uint8_t ok = 1;
      for (int32_t s = 0; s < r; ++s) {
        float lv = row[s], rv = rhs[s];
        bool slot_ok = (lv < rv) || (std::abs(lv - rv) < eps[s]);
        if (scalar_slot[s] && lv <= eps[s]) slot_ok = true;
        if (!slot_ok) {
          ok = 0;
          break;
        }
      }
      out[i] = ok;
    }
  });
}

}  // extern "C"
