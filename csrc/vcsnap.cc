// vcsnap: native snapshot serializer for the volcano_tpu scheduler.
//
// This is the rebuild's C++ side of the host<->device bridge (SURVEY.md
// section 2.1 "Scheduler cache" / BASELINE north star): the hot marshalling
// loops that flatten the session snapshot (Tasks x Nodes x Queues) into the
// dense arrays consumed by the JAX solver.  The reference relies on compiled
// Go for its cache/snapshot path (pkg/scheduler/cache/cache.go:652-730);
// here the per-row packing/scatter loops run as C++ over columnar CSR
// buffers prepared by the Python store, parallelized over row chunks.
//
// Exposed as a plain C ABI consumed via ctypes (volcano_tpu/native.py);
// every function writes into caller-allocated NumPy buffers, so no memory
// management crosses the boundary.
//
// Build: make -C csrc          (produces libvcsnap.so next to this file)
//        make -C csrc asan     (AddressSanitizer build, libvcsnap_asan.so)

#include <algorithm>
#include <cmath>
#include <cstdint>
#include "vcsnap.h"
#include <queue>
#include <vector>
#include <cstring>
#include <functional>
#include <thread>
#include <vector>

namespace {

// Run fn(begin, end) over [0, n) in parallel chunks.  Small inputs stay
// single-threaded to avoid thread-spawn overhead dominating.
void parallel_for(int64_t n, int64_t grain,
                  const std::function<void(int64_t, int64_t)>& fn) {
  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 1;
  int64_t chunks = std::min<int64_t>(hw, (n + grain - 1) / grain);
  if (chunks <= 1) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> threads;
  int64_t per = (n + chunks - 1) / chunks;
  threads.reserve(static_cast<size_t>(chunks));
  for (int64_t c = 0; c < chunks; ++c) {
    int64_t b = c * per;
    int64_t e = std::min(n, b + per);
    if (b >= e) break;
    threads.emplace_back(fn, b, e);
  }
  for (auto& t : threads) t.join();
}

// Wire-frame layout helpers (see the frame codec below).
inline int64_t vcsnap_align8(int64_t v) { return (v + 7) & ~int64_t{7}; }
inline int64_t vcsnap_header_bytes(uint8_t ndim) {
  return vcsnap_align8(8 + 8 * static_cast<int64_t>(ndim) + 8);
}

// Frame-codec wire constants + dtype table.  These MUST mirror the
// Python side (cache/snapwire.py: WIRE_MAGIC / WIRE_VERSION /
// WIRE_MAX_DIMS / _DTYPES, code = list index); tools/vclint's schema
// cross-checker parses both sides and fails the green-gate on any
// drift (VCL301/VCL302).  The dtype table extends APPEND-ONLY — codes
// are wire format.
struct VcsnapDtype { uint8_t code; const char* name; int32_t size; };
constexpr uint32_t kVcsnapMagic = 0x4E534356u;
constexpr uint32_t kVcsnapVersion = 1u;
constexpr int32_t kVcsnapMaxDims = 8;
constexpr VcsnapDtype kVcsnapDtypes[] = {
    {0, "float32", 4}, {1, "float64", 8}, {2, "int8", 1},
    {3, "int16", 2},   {4, "int32", 4},   {5, "int64", 8},
    {6, "uint8", 1},   {7, "uint16", 2},  {8, "uint32", 4},
    {9, "uint64", 8},  {10, "bool", 1},
};
constexpr int32_t kVcsnapNDtypes =
    static_cast<int32_t>(sizeof(kVcsnapDtypes) / sizeof(kVcsnapDtypes[0]));

// Delta-frame record tags (protocol v2, ISSUE 10).  These MUST mirror
// cache/snapwire.py REC_FULL / REC_SAME / REC_DELTA — vclint's VCL305
// cross-checker parses both sides and fails the green-gate on drift
// (same class as kVcsnapDtypes).  Values are wire format between the
// scheduler and the solver child; extend APPEND-ONLY.
constexpr int32_t kVcsnapRecFull = 0;
constexpr int32_t kVcsnapRecSame = 1;
constexpr int32_t kVcsnapRecDelta = 2;
// Reference the tags so -Werror=unused stays green until a native
// decoder consumes them (the tag dispatch lives python-side; the C++
// names exist as the vclint-checked wire contract anchor).
static_assert(kVcsnapRecFull == 0 && kVcsnapRecSame == 1 &&
                  kVcsnapRecDelta == 2,
              "delta record tags are wire format");

}  // namespace

extern "C" {

int vcsnap_version() { return 1; }

// CSR bitset pack: for each row i, set bits idx[off[i]..off[i+1]) in
// out[i * words .. (i+1) * words).  `out` must be zero-initialized by the
// caller (NumPy zeros).  Indices >= words*32 are ignored defensively.
void vcsnap_pack_bits(const int32_t* idx, const int64_t* off, int64_t rows,
                      int32_t words, uint32_t* out) {
  const int64_t max_bit = static_cast<int64_t>(words) * 32;
  parallel_for(rows, 4096, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      uint32_t* row = out + i * words;
      for (int64_t k = off[i]; k < off[i + 1]; ++k) {
        int64_t bit = idx[k];
        if (bit < 0 || bit >= max_bit) continue;
        row[bit >> 5] |= (1u << (bit & 31));
      }
    }
  });
}

// CSR slot scatter: for each row i, out[i * r + slot[k]] = val[k] for
// k in off[i]..off[i+1).  `out` zero-initialized by the caller.
void vcsnap_scatter_f32(const int32_t* slot, const float* val,
                        const int64_t* off, int64_t rows, int32_t r,
                        float* out) {
  parallel_for(rows, 4096, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      float* row = out + static_cast<int64_t>(i) * r;
      for (int64_t k = off[i]; k < off[i + 1]; ++k) {
        int32_t s = slot[k];
        if (s < 0 || s >= r) continue;
        row[s] = val[k];
      }
    }
  });
}

// Row gather with padding: out[i] = src[order[i]] for i < n; rows with
// order[i] < 0 are left zeroed.  Row width r floats.
void vcsnap_gather_rows_f32(const float* src, const int32_t* order, int64_t n,
                            int32_t r, float* out) {
  parallel_for(n, 8192, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      int32_t s = order[i];
      if (s < 0) continue;
      std::memcpy(out + i * r, src + static_cast<int64_t>(s) * r,
                  sizeof(float) * static_cast<size_t>(r));
    }
  });
}

// Epsilon-tolerant Resource.LessEqual over row pairs
// (resource_info.go:286-320): per slot `l < r or |l-r| < eps`, extended
// scalar slots requesting <= one quantum always pass.  l is [rows, r],
// rhs a single [r] row (the common fit-check shape); out[i] in {0,1}.
void vcsnap_less_equal(const float* l, const float* rhs, const float* eps,
                       const uint8_t* scalar_slot, int64_t rows, int32_t r,
                       uint8_t* out) {
  parallel_for(rows, 8192, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      const float* row = l + i * r;
      uint8_t ok = 1;
      for (int32_t s = 0; s < r; ++s) {
        float lv = row[s], rv = rhs[s];
        bool slot_ok = (lv < rv) || (std::abs(lv - rv) < eps[s]);
        if (scalar_slot[s] && lv <= eps[s]) slot_ok = true;
        if (!slot_ok) {
          ok = 0;
          break;
        }
      }
      out[i] = ok;
    }
  });
}

// ---------------------------------------------------------------------------
// Multi-array wire frame (the remote-solver snapshot codec).
//
// The north-star bridge (BASELINE.json; the cache.go:492-554 RPC-boundary
// analog): the scheduler-store process ships the per-cycle solver inputs to
// a separate device-owning solver process as ONE contiguous frame, and the
// assignment vectors come back the same way.  Layout (little-endian):
//
//   [0]  u32 magic 'VCSN'   [4] u32 version (1)   [8] u32 n_arrays
//   [12] u32 manifest_len   [16] manifest bytes (caller-opaque, e.g. JSON)
//   then per array, 8-byte aligned:
//     u8 dtype  u8 ndim  6 pad bytes  i64 dims[ndim]  i64 nbytes
//     data (8-byte aligned)
//
// Parsing returns offsets into the frame so the reader can view array data
// zero-copy.  The pack is one parallel memcpy pass.

int64_t vcsnap_frame_bytes(const uint8_t* ndims, const int64_t* nbytes,
                           int32_t n, int64_t manifest_len) {
  int64_t total = vcsnap_align8(16 + manifest_len);
  for (int32_t i = 0; i < n; ++i) {
    total += vcsnap_header_bytes(ndims[i]) + vcsnap_align8(nbytes[i]);
  }
  return total;
}

void vcsnap_frame_pack(const uint8_t* dtypes, const uint8_t* ndims,
                       const int64_t* dims_flat, const int64_t* nbytes,
                       const uint8_t* const* srcs, int32_t n,
                       const uint8_t* manifest, int64_t manifest_len,
                       uint8_t* out) {
  uint32_t head[4] = {kVcsnapMagic, kVcsnapVersion,
                      static_cast<uint32_t>(n),
                      static_cast<uint32_t>(manifest_len)};
  std::memcpy(out, head, 16);
  if (manifest_len) std::memcpy(out + 16, manifest, manifest_len);
  int64_t off = vcsnap_align8(16 + manifest_len);
  int64_t dim_off = 0;
  // First lay down headers and record data offsets, then copy the data
  // segments in parallel (the large arrays dominate).
  std::vector<int64_t> data_off(static_cast<size_t>(n));
  for (int32_t i = 0; i < n; ++i) {
    out[off] = dtypes[i];
    out[off + 1] = ndims[i];
    std::memset(out + off + 2, 0, 6);
    std::memcpy(out + off + 8, dims_flat + dim_off, 8 * ndims[i]);
    std::memcpy(out + off + 8 + 8 * ndims[i], nbytes + i, 8);
    off += vcsnap_header_bytes(ndims[i]);
    data_off[static_cast<size_t>(i)] = off;
    off += vcsnap_align8(nbytes[i]);
    dim_off += ndims[i];
  }
  parallel_for(n, 1, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      if (nbytes[i]) {
        std::memcpy(out + data_off[static_cast<size_t>(i)], srcs[i],
                    static_cast<size_t>(nbytes[i]));
      }
    }
  });
}

int32_t vcsnap_frame_info(const uint8_t* buf, int64_t len,
                          int64_t* manifest_off, int64_t* manifest_len) {
  if (len < 16) return -1;
  uint32_t head[4];
  std::memcpy(head, buf, 16);
  if (head[0] != kVcsnapMagic || head[1] != kVcsnapVersion) return -1;
  if (manifest_off) *manifest_off = 16;
  if (manifest_len) *manifest_len = static_cast<int64_t>(head[3]);
  if (16 + static_cast<int64_t>(head[3]) > len) return -1;
  return static_cast<int32_t>(head[2]);
}

// Parses headers into caller buffers sized from vcsnap_frame_info's count:
// dtypes[n], ndims[n], dims_flat[n*8] (max 8 dims), data_off[n], nbytes[n].
// Returns 0 on success, -1 on malformed input (truncated frame / dim
// overflow) — the reader must treat the frame as hostile until this
// validates it.
int32_t vcsnap_frame_unpack(const uint8_t* buf, int64_t len, uint8_t* dtypes,
                            uint8_t* ndims, int64_t* dims_flat,
                            int64_t* data_off, int64_t* nbytes) {
  int64_t moff = 0, mlen = 0;
  int32_t n = vcsnap_frame_info(buf, len, &moff, &mlen);
  if (n < 0) return -1;
  int64_t off = vcsnap_align8(16 + mlen);
  // Bounds checks below are written as `X > len - off`, never
  // `off + X > len`: a hostile header can put a value near INT64_MAX
  // in an additive position, and `off + X` would wrap (signed
  // overflow, UB) into a PASSING comparison.  `off` stays within
  // [0, len + 7] throughout (the +7 from align8 rounding), so
  // `len - off` never overflows and a negative difference rejects.
  for (int32_t i = 0; i < n; ++i) {
    if (16 > len - off) return -1;
    uint8_t nd = buf[off + 1];
    if (nd > kVcsnapMaxDims) return -1;
    if (8 + 8 * static_cast<int64_t>(nd) + 8 > len - off) return -1;
    uint8_t dt = buf[off];
    if (dt >= kVcsnapNDtypes) return -1;
    dtypes[i] = dt;
    ndims[i] = nd;
    std::memcpy(dims_flat + i * 8, buf + off + 8, 8 * nd);
    int64_t elems = 1;
    for (uint8_t d = 0; d < nd; ++d) {
      int64_t dim = dims_flat[i * 8 + d];
      // A well-formed array's byte length fits the frame, so any dim
      // pushing the element product past `len` marks a hostile header
      // (and guards the multiply against overflow).
      if (dim < 0 || (dim > 0 && elems > len / dim)) return -1;
      elems *= dim;
    }
    int64_t nb;
    std::memcpy(&nb, buf + off + 8 + 8 * nd, 8);
    if (nb < 0) return -1;
    // Shape x dtype width must equal the declared byte length, or a
    // reader's zero-copy view would bleed into the next array's bytes.
    if (nb != elems * kVcsnapDtypes[dt].size) return -1;
    off += vcsnap_header_bytes(nd);
    if (nb < 0 || nb > len - off) return -1;
    data_off[i] = off;
    nbytes[i] = nb;
    off += vcsnap_align8(nb);
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Delta records (protocol v2, ISSUE 10): a solve frame may ship only the
// rows of an array that changed since the mirrored base frame the receiver
// already holds.  The wire descriptor is an int64 vector
//
//   [n_ranges, s0, e0, s1, e1, ...]
//
// of half-open [start, stop) row ranges, strictly ascending and
// non-overlapping, and the payload is the changed rows concatenated in
// range order.  The descriptor and the generation token arrive off the
// wire and are HOSTILE until validated; rows / row_bytes / payload_bytes /
// mirror_gen come from the receiver's own mirror state and are trusted.
//
// Bounds discipline (the vcsnap_frame_unpack rule): no additive or
// multiplicative expression ever mixes a hostile value into arithmetic
// that could wrap (signed overflow, UB) into a PASSING comparison —
// counts are checked in division form, each range bound is compared
// directly against trusted limits, and the per-range row sum is bounded
// by `rows` before it accumulates (disjoint ranges within [0, rows)).

// Returns the summed payload rows (>= 0), -1 on a malformed descriptor
// (truncated, out-of-bounds, unsorted / overlapping / empty ranges,
// payload length mismatch), -2 when the receiver's mirror generation is
// not the delta's base (reconnect / child restart / token mismatch — the
// caller must fall back to a full frame, never solve stale).
int64_t vcsnap_delta_check(const int64_t* desc, int64_t desc_len,
                           int64_t rows, int64_t row_bytes,
                           int64_t payload_bytes,
                           int64_t mirror_gen, int64_t base_gen) {
  if (mirror_gen != base_gen) return -2;
  if (desc_len < 1) return -1;
  int64_t n = desc[0];
  // `1 + 2 * n > desc_len` would wrap on a hostile count near
  // INT64_MAX; the division form rejects without touching it.
  if (n < 0 || n > (desc_len - 1) / 2) return -1;
  int64_t total = 0;
  int64_t prev_stop = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t s = desc[1 + 2 * i];
    int64_t e = desc[2 + 2 * i];
    if (s < prev_stop || s >= e || e > rows) return -1;
    total += e - s;  // disjoint within [0, rows): total <= rows
    prev_stop = e;
  }
  if (row_bytes <= 0) return payload_bytes != 0 ? -1 : total;
  // `total * row_bytes == payload_bytes` in division form: the product
  // of two trusted-positive values still has no business existing when
  // a corrupt length could make the comparison the only guard.
  if (payload_bytes % row_bytes != 0 ||
      total != payload_bytes / row_bytes)
    return -1;
  return total;
}

// Validates, then scatters the payload rows into the caller's writable
// mirror array.  Returns 0 on success or the vcsnap_delta_check error;
// dst is untouched on any rejection.
int32_t vcsnap_delta_apply(uint8_t* dst, int64_t rows, int64_t row_bytes,
                           const int64_t* desc, int64_t desc_len,
                           const uint8_t* payload, int64_t payload_bytes,
                           int64_t mirror_gen, int64_t base_gen) {
  int64_t total = vcsnap_delta_check(desc, desc_len, rows, row_bytes,
                                     payload_bytes, mirror_gen, base_gen);
  if (total < 0) return static_cast<int32_t>(total);
  int64_t n = desc[0];
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t s = desc[1 + 2 * i];
    int64_t e = desc[2 + 2 * i];
    int64_t nb = (e - s) * row_bytes;
    std::memcpy(dst + s * row_bytes, payload + off,
                static_cast<size_t>(nb));
    off += nb;
  }
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Reclaim step engine (pkg/scheduler/actions/reclaim/reclaim.go:136-175 +
// session_plugins.go:110-193 tier intersection), driven per-reclaimer from
// volcano_tpu/fastpath_evict.py.  One call walks nodes from the persistent
// cursor, collects cross-queue Running candidates, narrows them through the
// tiered Reclaimable plugins (gang / conformance / proportion — encoded in
// `tiers`), validates, evicts victims in order until the reclaimed sum
// covers the request, and reports the pipeline node.  All cluster state is
// mutated in place through the caller's numpy buffers; evicted rows are
// returned so the Python side can keep its caches/event trail coherent.
// ---------------------------------------------------------------------------

extern "C" {

static const float VC_MIN_MILLI_SCALAR = 10.0f;


// Resource.less on dense slot vectors (api/resource.py:182-199), with the
// allocation's scalar DICT ENTRY SET modelled explicitly: Resource.sub
// keeps zeroed entries in the dict (and adds the subtrahend's keys), so
// "scalars is None" and "which keys exist" cannot be derived from values.
// a_has: the dict is non-None; a_entry[k]: slot k has a dict entry.
static bool vc_res_less(const float* a, bool a_has,
                        const uint8_t* a_entry, const float* b,
                        int64_t R, const uint8_t* scalar_slot) {
  if (!(a[0] < b[0])) return false;
  if (!(a[1] < b[1])) return false;
  bool b_any = false;
  for (int64_t k = 2; k < R; ++k)
    if (scalar_slot[k] && b[k] != 0.0f) b_any = true;
  if (!a_has) {
    if (b_any) {
      for (int64_t k = 2; k < R; ++k)
        if (scalar_slot[k] && b[k] != 0.0f && b[k] <= VC_MIN_MILLI_SCALAR)
          return false;
    }
    return true;
  }
  if (!b_any) return false;
  // Iterate the allocation's ENTRIES (rr.scalars.get(name, 0) == b[k]).
  for (int64_t k = 2; k < R; ++k)
    if (scalar_slot[k] && a_entry[k] && !(a[k] < b[k])) return false;
  return true;
}

// Resource.less_equal_strict(d, a) on dense vectors (resource.py:201-212).
static bool vc_res_le_strict(const float* d, const float* a, int64_t R,
                             const uint8_t* scalar_slot) {
  if (!(d[0] <= a[0])) return false;
  if (!(d[1] <= a[1])) return false;
  for (int64_t k = 2; k < R; ++k)
    if (scalar_slot[k] && d[k] != 0.0f && !(d[k] <= a[k])) return false;
  return true;
}

// Epsilon-tolerant Resource.less_equal (resource_info.go:286-320) of l vs r.
static bool vc_le(const float* l, const float* r, const float* eps,
                  const uint8_t* scalar_slot, int64_t R) {
  for (int64_t k = 0; k < R; ++k) {
    float lv = l[k], rv = r[k];
    bool ok = (lv < rv) || (std::abs(lv - rv) < eps[k]);
    if (scalar_slot[k] && lv <= eps[k]) ok = true;
    if (!ok) return false;
  }
  return true;
}

// Plugin ids in the `tiers` encoding (-1 = tier boundary).
enum { VC_PLUGIN_GANG = 0, VC_PLUGIN_CONFORMANCE = 1,
       VC_PLUGIN_PROPORTION = 2 };

#define VC_MAX_CAND 512

// Per-action context: every stable pointer captured once so the per-
// reclaimer call marshals only what varies (ctypes arg overhead was
// measurable at 20k reclaimers per cycle).
struct VcReclaimCtx {
  const long long* node_ptr; const long long* node_rows;
  int16_t* p_status; const int32_t* p_job;
  const float* req; const uint8_t* req_empty; const uint8_t* critical;
  const int32_t* j_minav; int32_t* j_ready_base;
  int32_t* j_cnt_alloc; int32_t* j_cnt_run; int32_t* j_cnt_releasing;
  float* j_alloc_res; const int32_t* q_of_job;
  const uint8_t* q_reclaimable; float* q_alloc;
  const float* q_deserved; const uint8_t* q_has_deserved;
  float* fi; float* n_releasing;
  const int32_t* tiers; long long tiers_len;
  const float* eps; const uint8_t* scalar_slot;
  const uint8_t* alive; const float* init_req_base;
  long long Nn, R, st_running, st_releasing;
  // ---- driver mode (vcreclaim_drive) ----
  float* n_pipelined;          // [N,R]
  int32_t* n_ntasks;           // [N]
  const int32_t* n_maxtasks;   // [N]
  long long* pipe_node;        // [P]
  int32_t* j_cnt_pending;      // [J]
  long long* j_waiting;        // [J]
  long long* j_version;        // [J]
  long long* q_version;        // [Q]
  long long Qn;
  const int32_t* j_prio;       // [J]
  const int32_t* j_rank;       // [J] (create, uid) rank
  const int32_t* p_node;       // [P]
  const float* total_res;      // [R]
  const int32_t* job_order;    // encoding: 0=priority 1=gang 2=drf
  long long job_order_len;
  uint8_t reclaim_gated;       // proportion sits in first reclaim tier
};

void* vcreclaim_ctx_new(
    const long long* node_ptr, const long long* node_rows,
    int16_t* p_status, const int32_t* p_job,
    const float* req, const uint8_t* req_empty, const uint8_t* critical,
    const int32_t* j_minav, int32_t* j_ready_base,
    int32_t* j_cnt_alloc, int32_t* j_cnt_run, int32_t* j_cnt_releasing,
    float* j_alloc_res, const int32_t* q_of_job,
    const uint8_t* q_reclaimable, float* q_alloc,
    const float* q_deserved, const uint8_t* q_has_deserved,
    float* fi, float* n_releasing,
    const int32_t* tiers, long long tiers_len,
    const float* eps, const uint8_t* scalar_slot,
    const uint8_t* alive, const float* init_req_base,
    long long Nn, long long R,
    long long st_running, long long st_releasing,
    float* n_pipelined, int32_t* n_ntasks, const int32_t* n_maxtasks,
    long long* pipe_node, int32_t* j_cnt_pending, long long* j_waiting,
    long long* j_version, long long* q_version, long long Qn,
    const int32_t* j_prio, const int32_t* j_rank,
    const int32_t* p_node,
    const float* total_res, const int32_t* job_order,
    long long job_order_len, long long reclaim_gated) {
  VcReclaimCtx* c = new VcReclaimCtx{
      node_ptr, node_rows, p_status, p_job, req, req_empty, critical,
      j_minav, j_ready_base, j_cnt_alloc, j_cnt_run, j_cnt_releasing,
      j_alloc_res, q_of_job, q_reclaimable, q_alloc, q_deserved,
      q_has_deserved, fi, n_releasing, tiers, tiers_len, eps,
      scalar_slot, alive, init_req_base, Nn, R, st_running, st_releasing,
      n_pipelined, n_ntasks, n_maxtasks, pipe_node, j_cnt_pending,
      j_waiting, j_version, q_version, Qn, j_prio, j_rank, p_node,
      total_res, job_order, job_order_len, (uint8_t)reclaim_gated};
  return c;
}

void vcreclaim_ctx_free(void* ctx) {
  delete static_cast<VcReclaimCtx*>(ctx);
}

// Returns the node the reclaimer pipelined on, or -1.  Victim rows evicted
// along the walk (including on nodes that ultimately could not cover the
// request — reclaim.go's evictions are immediate and unwrapped) land in
// out_evicted.
static long long vc_walk_one(
    const VcReclaimCtx& C, long long prow, long long qid,
    long long* cursor,
    const uint8_t* anym, const uint8_t* feas, const uint8_t* stat,
    const uint8_t* slots,
    long long* out_evicted, long long* out_n_evicted,
    long long max_evicted) {
  const long long Nn = C.Nn, R = C.R;
  const long long* node_ptr = C.node_ptr;
  const long long* node_rows = C.node_rows;
  int16_t* p_status = C.p_status;
  const int32_t* p_job = C.p_job;
  const float* req = C.req;
  const uint8_t* req_empty = C.req_empty;
  const uint8_t* critical = C.critical;
  const int32_t* j_minav = C.j_minav;
  int32_t* j_ready_base = C.j_ready_base;
  int32_t* j_cnt_alloc = C.j_cnt_alloc;
  int32_t* j_cnt_run = C.j_cnt_run;
  int32_t* j_cnt_releasing = C.j_cnt_releasing;
  float* j_alloc_res = C.j_alloc_res;
  const int32_t* q_of_job = C.q_of_job;
  const uint8_t* q_reclaimable = C.q_reclaimable;
  float* q_alloc = C.q_alloc;
  const float* q_deserved = C.q_deserved;
  const uint8_t* q_has_deserved = C.q_has_deserved;
  float* fi = C.fi;
  float* n_releasing = C.n_releasing;
  const int32_t* tiers = C.tiers;
  const long long tiers_len = C.tiers_len;
  const float* eps = C.eps;
  const uint8_t* scalar_slot = C.scalar_slot;
  const uint8_t* alive = C.alive;
  const float* init_req = C.init_req_base + prow * R;
  const long long st_running = C.st_running, st_releasing = C.st_releasing;
  int64_t cand[VC_MAX_CAND];
  uint8_t in_victims[VC_MAX_CAND];
  uint8_t in_sel[VC_MAX_CAND];
  // Scratch for per-call plugin state (small: candidates per node).
  int64_t gang_jobs[VC_MAX_CAND];
  int32_t gang_cnt[VC_MAX_CAND];
  int64_t prop_qs[VC_MAX_CAND];
  float prop_alloc[VC_MAX_CAND * 8];  // R <= 8 supported
  uint8_t prop_entry[VC_MAX_CAND * 8];
  uint8_t prop_has[VC_MAX_CAND];
  float reclaimed[8];
  float vsum[8];
  if (R > 8) return -2;  // unsupported width; caller falls back

  // NOTE: out_n_evicted is owned by the caller (vcreclaim_batch
  // accumulates across turns); do not reset it here.
  long long n = *cursor;
  bool advancing = true;
  for (; n < Nn; ++n) {
    if (!(anym[n] && feas[n] && alive[n]
          && (stat == nullptr || (stat[n] && slots[n])))) {
      if (advancing) *cursor = n + 1;
      continue;
    }
    advancing = false;
    // ---- candidates: cross-queue Running tasks of reclaimable queues,
    // in resident (insertion) order.
    int64_t nc = 0;
    for (int64_t p = node_ptr[n]; p < node_ptr[n + 1]; ++p) {
      int64_t r = node_rows[p];
      if (p_status[r] != (int16_t)st_running || req_empty[r]) continue;
      int32_t jr = p_job[r];
      if (jr < 0) continue;
      int32_t vq = q_of_job[jr];
      if (vq == (int32_t)qid || vq < 0 || !q_reclaimable[vq]) continue;
      if (nc >= VC_MAX_CAND) return -2;  // degenerate node: fall back
      cand[nc++] = r;
    }
    if (nc == 0) continue;
    // ---- tiered Reclaimable intersection (session_plugins.go:110-193,
    // incl. the Go nil-slice quirk: an initialized-empty carried set
    // keeps poisoning later tiers).
    bool init = false;
    for (int64_t i = 0; i < nc; ++i) in_victims[i] = 0;
    int64_t n_victims = 0;
    int64_t t = 0;
    while (t < tiers_len) {
      // one tier: ids until -1
      for (; t < tiers_len && tiers[t] != -1; ++t) {
        int32_t plugin = tiers[t];
        // sel over the ORIGINAL candidates (session passes the full
        // preemptees list to every plugin fn).
        if (plugin == VC_PLUGIN_GANG) {
          int64_t ng = 0;
          for (int64_t i = 0; i < nc; ++i) {
            int32_t jr = p_job[cand[i]];
            int32_t cnt = -1;
            int64_t gslot = -1;
            for (int64_t g = 0; g < ng; ++g)
              if (gang_jobs[g] == jr) { gslot = g; break; }
            if (gslot < 0) {
              gslot = ng++;
              gang_jobs[gslot] = jr;
              gang_cnt[gslot] = j_ready_base[jr];
            }
            cnt = gang_cnt[gslot];
            int32_t minav = j_minav[jr];
            if (minav <= cnt - 1 || minav == 1) {
              gang_cnt[gslot] = cnt - 1;
              in_sel[i] = 1;
            } else {
              in_sel[i] = 0;
            }
          }
        } else if (plugin == VC_PLUGIN_CONFORMANCE) {
          for (int64_t i = 0; i < nc; ++i)
            in_sel[i] = critical[cand[i]] ? 0 : 1;
        } else if (plugin == VC_PLUGIN_PROPORTION) {
          int64_t nq = 0;
          for (int64_t i = 0; i < nc; ++i) {
            in_sel[i] = 0;
            int32_t jr = p_job[cand[i]];
            int32_t vq = q_of_job[jr];
            if (vq < 0) continue;
            if (!q_has_deserved[vq]) continue;
            int64_t qslot = -1;
            for (int64_t q = 0; q < nq; ++q)
              if (prop_qs[q] == vq) { qslot = q; break; }
            if (qslot < 0) {
              qslot = nq++;
              prop_qs[qslot] = vq;
              bool has = false;
              for (int64_t k = 0; k < R; ++k) {
                float v = q_alloc[vq * R + k];
                prop_alloc[qslot * 8 + k] = v;
                // FastCycle._res: dict entries are the NONZERO slots.
                bool entry = scalar_slot[k] && v != 0.0f;
                prop_entry[qslot * 8 + k] = entry ? 1 : 0;
                if (entry) has = true;
              }
              prop_has[qslot] = has ? 1 : 0;
            }
            float* alloc = prop_alloc + qslot * 8;
            uint8_t* entry = prop_entry + qslot * 8;
            const float* vreq = req + cand[i] * R;
            if (vc_res_less(alloc, prop_has[qslot] != 0, entry, vreq, R,
                            scalar_slot))
              continue;
            // Resource.sub: cpu/mem always; scalars only when the dict
            // exists (None -> early return, resource.py:132-134), and
            // the subtrahend's keys join the entry set (:135-136).
            alloc[0] -= vreq[0];
            alloc[1] -= vreq[1];
            if (prop_has[qslot]) {
              for (int64_t k = 2; k < R; ++k) {
                if (!scalar_slot[k]) continue;
                alloc[k] -= vreq[k];
                if (vreq[k] != 0.0f) entry[k] = 1;
              }
            }
            if (vc_res_le_strict(q_deserved + vq * R, alloc, R,
                                 scalar_slot))
              in_sel[i] = 1;
          }
        } else {
          continue;  // unknown plugin: no reclaimable fn registered
        }
        // intersect / initialize the carried victim set
        if (!init) {
          n_victims = 0;
          for (int64_t i = 0; i < nc; ++i) {
            in_victims[i] = in_sel[i];
            if (in_sel[i]) ++n_victims;
          }
          init = true;
        } else {
          n_victims = 0;
          for (int64_t i = 0; i < nc; ++i) {
            in_victims[i] = in_victims[i] && in_sel[i];
            if (in_victims[i]) ++n_victims;
          }
        }
      }
      ++t;  // skip tier separator
      if (n_victims > 0) break;   // first tier boundary with victims
      if (init) break;            // initialized-empty: poisoned
    }
    if (n_victims == 0) continue;
    // ---- validate_victims: FutureIdle + victims must cover the task.
    const float* fi_n = fi + n * R;
    for (int64_t k = 0; k < R; ++k) vsum[k] = fi_n[k];
    for (int64_t i = 0; i < nc; ++i)
      if (in_victims[i]) {
        const float* vreq = req + cand[i] * R;
        for (int64_t k = 0; k < R; ++k) vsum[k] += vreq[k];
      }
    if (!vc_le(init_req, vsum, eps, scalar_slot, R)) continue;
    // ---- evict victims in order until the reclaimed sum covers
    // (reclaim.go:160-175; evictions stand even if it never does).
    for (int64_t k = 0; k < R; ++k) reclaimed[k] = 0.0f;
    bool covered = false;
    for (int64_t i = 0; i < nc && !covered; ++i) {
      if (!in_victims[i]) continue;
      int64_t r = cand[i];
      const float* vreq = req + r * R;
      // session-level evict bookkeeping (fastpath_evict EvictState.evict)
      p_status[r] = (int16_t)st_releasing;
      for (int64_t k = 0; k < R; ++k) {
        n_releasing[n * R + k] += vreq[k];
        fi[n * R + k] += vreq[k];
      }
      int32_t jr = p_job[r];
      if (jr >= 0) {
        j_cnt_alloc[jr] -= 1;
        j_cnt_run[jr] -= 1;
        j_cnt_releasing[jr] += 1;
        j_ready_base[jr] -= 1;
        for (int64_t k = 0; k < R; ++k) j_alloc_res[jr * R + k] -= vreq[k];
        int32_t vq = q_of_job[jr];
        if (vq >= 0)
          for (int64_t k = 0; k < R; ++k) q_alloc[vq * R + k] -= vreq[k];
      }
      if (*out_n_evicted < max_evicted)
        out_evicted[(*out_n_evicted)++] = r;
      for (int64_t k = 0; k < R; ++k) reclaimed[k] += vreq[k];
      covered = vc_le(init_req, reclaimed, eps, scalar_slot, R);
    }
    if (covered) return n;  // caller pipelines the task here
  }
  return -1;
}


long long vcreclaim_step(
    void* ctx_p, long long prow, long long qid,
    long long* cursor,
    const uint8_t* anym, const uint8_t* feas, const uint8_t* stat,
    const uint8_t* slots,
    long long* out_evicted, long long* out_n_evicted,
    long long max_evicted) {
  const VcReclaimCtx& C = *static_cast<VcReclaimCtx*>(ctx_p);
  *out_n_evicted = 0;
  return vc_walk_one(C, prow, qid, cursor, anym, feas, stat, slots,
                     out_evicted, out_n_evicted, max_evicted);
}

// ---- batch mode helpers -------------------------------------------------

// In-scope evictable sum at one node (fresh walk over residents).
static bool vc_scope_ev(const VcReclaimCtx& C, long long qid, long long n,
                        float* ev_out) {
  for (long long k = 0; k < C.R; ++k) ev_out[k] = 0.0f;
  bool any = false;
  for (long long p = C.node_ptr[n]; p < C.node_ptr[n + 1]; ++p) {
    long long r = C.node_rows[p];
    if (C.p_status[r] != (int16_t)C.st_running || C.req_empty[r]) continue;
    int32_t jr = C.p_job[r];
    if (jr < 0) continue;
    int32_t vq = C.q_of_job[jr];
    if (vq == (int32_t)qid || vq < 0 || !C.q_reclaimable[vq]) continue;
    const float* vreq = C.req + r * C.R;
    for (long long k = 0; k < C.R; ++k) {
      ev_out[k] += vreq[k];
      if (ev_out[k] > 1e-6f) any = true;
    }
  }
  return any;
}

// The live job-order key in doubles (fastpath_evict._job_key with the
// (create, uid) tail replaced by the precomputed rank).  Component
// arithmetic matches the Python float math bit-for-bit: float32 inputs
// widened to double, same divisions.
static void vc_job_key(const VcReclaimCtx& C, long long jr, double* out) {
  long long o = 0;
  for (long long i = 0; i < C.job_order_len; ++i) {
    int32_t id = C.job_order[i];
    if (id == 0) {  // priority
      out[o++] = -(double)C.j_prio[jr];
    } else if (id == 1) {  // gang: ready jobs order last
      out[o++] = (C.j_ready_base[jr] >= C.j_minav[jr]) ? 1.0 : 0.0;
    } else if (id == 2) {  // drf share
      double s = 0.0;
      for (long long k = 0; k < C.R; ++k) {
        double t = (double)C.total_res[k];
        double a = (double)C.j_alloc_res[jr * C.R + k];
        double v = t > 0.0 ? a / t : (a > 0.0 ? 1.0 : 0.0);
        if (v > s) s = v;
      }
      out[o++] = s;
    }
  }
  out[o++] = (double)C.j_rank[jr];
}

// proportion's reclaim-possible veto: some OTHER reclaimable queue still
// at/above its deserved share (fastpath_evict._reclaim_possible).
static bool vc_reclaim_possible(const VcReclaimCtx& C, long long qid) {
  if (!C.reclaim_gated) return true;
  for (long long qi = 0; qi < C.Qn; ++qi) {
    if (qi == qid || !C.q_reclaimable[qi] || !C.q_has_deserved[qi])
      continue;
    if (vc_res_le_strict(C.q_deserved + qi * C.R, C.q_alloc + qi * C.R,
                         C.R, C.scalar_slot))
      return true;
  }
  return false;
}

// ---- reclaim driver shared structures ----------------------------------

struct VcKey {
  double v[8];
  int len;
  long long jr;
  bool operator<(const VcKey& o) const {
    // std::priority_queue is a MAX-heap; invert for min-pop.
    for (int i = 0; i < len; ++i) {
      if (v[i] < o.v[i]) return false;
      if (v[i] > o.v[i]) return true;
    }
    return false;
  }
};

// Per-profile mask set registered by the Python driver.
struct VcMaskSet {
  uint8_t* anym;
  uint8_t* feas;
  const uint8_t* stat;   // may be the shared all-ones array
  uint8_t* slots;        // mutable when has_pred
  const float* init_req; // representative request vector
  long long cursor;
};


// ---- multi-queue reclaim driver ----------------------------------------
//
// The full cross-queue round-robin of fastpath_evict._reclaim_loop
// (reclaim.go:84-130): a lazy min-ordered QUEUE heap with live keys
// (share when proportion orders queues, then creation time, then uid
// rank), each turn popping one job from the queue's own lazy job heap
// and running one task's cursor walk.  Queue drop/re-push semantics
// mirror the Python loop exactly: overused (memoized at first
// evaluation, q_overused in/out), empty job heap, or a drained top job
// drop the queue; a consumed turn re-pushes it.  Yields (-3/-5) hand
// one job back to Python, which re-enters with dropped queues/jobs
// filtered out.

struct VcQKey {
  double v[3];
  int len;
  long long slot;  // local queue slot
  bool operator<(const VcQKey& o) const {
    // std::priority_queue is a MAX-heap; invert for min-pop.
    for (int i = 0; i < len; ++i) {
      if (v[i] < o.v[i]) return false;
      if (v[i] > o.v[i]) return true;
    }
    return false;
  }
};

// fastpath_evict._queue_share: max over the deserved Resource's NAMED
// slots of share(alloc, deserved) with 0/0 -> 0 and x/0 -> 1
// (api/helpers.go:46-59).  q_named marks the named slots (cpu/memory
// always; scalars the deserved dict carries, zero-valued included).
static double vc_queue_share(const VcReclaimCtx& C, const uint8_t* q_named,
                             long long qi) {
  if (!C.q_has_deserved[qi]) return 0.0;
  double s = 0.0;
  for (long long k = 0; k < C.R; ++k) {
    if (!q_named[qi * C.R + k]) continue;
    double a = (double)C.q_alloc[qi * C.R + k];
    double d = (double)C.q_deserved[qi * C.R + k];
    double v = (d == 0.0) ? (a == 0.0 ? 0.0 : 1.0) : a / d;
    if (v > s) s = v;
  }
  return s;
}

long long vcreclaim_drive_mq(
    void* ctx_p, long long has_pred,
    // queues (local slots; qs_ids maps to global queue ids)
    const long long* qs_ids, long long n_queues,
    const double* q_create, const int32_t* q_uid_rank,
    const uint8_t* q_named,        // [Qn * R], global-indexed
    long long qorder_has_prop,
    int8_t* q_overused,            // [n_queues] memo: -1 unknown / 0 / 1
    uint8_t* out_q_dropped,        // [n_queues]
    // jobs + tasks (job-major across all queues)
    const long long* job_ids, long long n_jobs,
    const long long* job_qslot,    // [n_jobs] local queue slot per job
    const long long* task_ptr, const long long* task_rows,
    long long* task_cursor,
    const int32_t* row_maskidx,
    // mask sets (per (queue scope, profile)); mask_qids = the GLOBAL
    // queue id whose evictable scope each set was built against
    long long n_masks,
    unsigned long long* anym_ptrs, unsigned long long* feas_ptrs,
    unsigned long long* stat_ptrs, unsigned long long* slots_ptrs,
    unsigned long long* initreq_ptrs,
    const long long* mask_qids,
    long long* mask_cursors,
    // outputs
    long long* out_evicted, long long* out_n_evicted, long long max_ev,
    long long* out_pipe_rows, long long* out_pipe_nodes,
    long long* out_n_pipe,
    long long* out_touched, long long* out_n_touched,
    long long max_touched,
    long long* out_yield_job, uint8_t* out_job_dropped) {
  const VcReclaimCtx& C = *static_cast<VcReclaimCtx*>(ctx_p);
  *out_n_evicted = 0;
  *out_n_pipe = 0;
  *out_n_touched = 0;
  *out_yield_job = -1;
  if (C.job_order_len + 1 > 8) return -4;  // VcKey buffer bound
  std::vector<VcMaskSet> masks((size_t)n_masks);
  for (long long i = 0; i < n_masks; ++i) {
    masks[i].anym = (uint8_t*)anym_ptrs[i];
    masks[i].feas = (uint8_t*)feas_ptrs[i];
    masks[i].stat = (const uint8_t*)stat_ptrs[i];
    masks[i].slots = (uint8_t*)slots_ptrs[i];
    masks[i].init_req = (const float*)initreq_ptrs[i];
    masks[i].cursor = mask_cursors[i];
  }
  auto make_jkey = [&](long long ji) {
    VcKey k;
    vc_job_key(C, job_ids[ji], k.v);
    k.len = (int)C.job_order_len + 1;
    k.jr = ji;
    return k;
  };
  auto make_qkey = [&](long long slot) {
    VcQKey k;
    int o = 0;
    long long qid = qs_ids[slot];
    if (qorder_has_prop) k.v[o++] = vc_queue_share(C, q_named, qid);
    k.v[o++] = q_create[slot];
    k.v[o++] = (double)q_uid_rank[slot];
    k.len = o;
    k.slot = slot;
    return k;
  };
  // Per-queue job heaps.
  std::vector<std::priority_queue<VcKey>> jheaps((size_t)n_queues);
  for (long long ji = 0; ji < n_jobs; ++ji)
    jheaps[(size_t)job_qslot[ji]].push(make_jkey(ji));
  std::priority_queue<VcQKey> qheap;
  for (long long slot = 0; slot < n_queues; ++slot)
    qheap.push(make_qkey(slot));
  // Mask refresh at a node for EVERY set, each against its OWN queue's
  // evictable scope (victims exclude the reclaimer's queue, so one
  // queue's eviction changes every other queue's sums too).  The
  // node-resident scan depends only on the set's queue, so it runs
  // once per DISTINCT queue, not once per (queue, profile) set.
  // Scratch hoisted out of the per-node lambda: zero steady-state
  // allocations in the hot refresh.
  std::vector<long long> seen_q;
  std::vector<float> ev_by_q;
  std::vector<uint8_t> any_by_q;
  seen_q.reserve((size_t)n_queues);
  ev_by_q.reserve((size_t)n_queues * 8);
  any_by_q.reserve((size_t)n_queues);
  auto refresh_node = [&](long long n_r) {
    seen_q.clear();
    ev_by_q.clear();
    any_by_q.clear();
    const float* fi_n = C.fi + n_r * C.R;
    for (long long mset = 0; mset < n_masks; ++mset) {
      long long qy = mask_qids[mset];
      long long qslot = -1;
      for (size_t s = 0; s < seen_q.size(); ++s)
        if (seen_q[s] == qy) { qslot = (long long)s; break; }
      if (qslot < 0) {
        qslot = (long long)seen_q.size();
        seen_q.push_back(qy);
        float ev_tmp[8];
        bool any = vc_scope_ev(C, qy, n_r, ev_tmp);
        any_by_q.push_back(any ? 1 : 0);
        for (long long k = 0; k < 8; ++k)
          ev_by_q.push_back(k < C.R ? ev_tmp[k] : 0.0f);
      }
      const float* ev_q = ev_by_q.data() + qslot * 8;
      float tot[8];
      for (long long k = 0; k < C.R; ++k) tot[k] = fi_n[k] + ev_q[k];
      masks[mset].anym[n_r] = any_by_q[(size_t)qslot];
      masks[mset].feas[n_r] =
          vc_le(masks[mset].init_req, tot, C.eps, C.scalar_slot, C.R)
              ? 1 : 0;
      if (has_pred)
        masks[mset].slots[n_r] =
            (C.n_maxtasks[n_r] <= 0
             || C.n_ntasks[n_r] < C.n_maxtasks[n_r]) ? 1 : 0;
    }
    if (*out_n_touched < max_touched)
      out_touched[(*out_n_touched)++] = n_r;
  };
  long long rc = 0;
  while (!qheap.empty()) {
    VcQKey qtop = qheap.top();
    qheap.pop();
    VcQKey qfresh = make_qkey(qtop.slot);
    bool stale = false;
    for (int i = 0; i < qfresh.len; ++i)
      if (qfresh.v[i] != qtop.v[i]) { stale = true; break; }
    if (stale) { qheap.push(qfresh); continue; }
    long long slot = qtop.slot;
    long long qid = qs_ids[slot];
    // Overused verdict, frozen at first evaluation (the Python
    // closure's per-pass memo).
    if (q_overused[slot] < 0) {
      bool over = C.q_has_deserved[qid] &&
          !vc_le(C.q_alloc + qid * C.R, C.q_deserved + qid * C.R,
                 C.eps, C.scalar_slot, C.R);
      q_overused[slot] = over ? 1 : 0;
    }
    if (q_overused[slot]) { out_q_dropped[slot] = 1; continue; }
    auto& jheap = jheaps[(size_t)slot];
    // Lazy job pop (stale keys re-push).
    long long ji = -1;
    while (!jheap.empty()) {
      VcKey top = jheap.top();
      jheap.pop();
      VcKey fresh = make_jkey(top.jr);
      bool jstale = false;
      for (int i = 0; i < fresh.len; ++i)
        if (fresh.v[i] != top.v[i]) { jstale = true; break; }
      if (jstale) { jheap.push(fresh); continue; }
      ji = top.jr;
      break;
    }
    if (ji < 0) { out_q_dropped[slot] = 1; continue; }
    long long base = task_ptr[ji];
    long long ntask = task_ptr[ji + 1] - base;
    if (task_cursor[ji] >= ntask) {
      // Drained top job kills the queue (the reclaim.go empty-tasks
      // `continue` skips the queue re-push — a faithful quirk).
      out_job_dropped[ji] = 1;
      out_q_dropped[slot] = 1;
      continue;
    }
    long long prow = task_rows[base + task_cursor[ji]];
    int32_t mi = row_maskidx[prow];
    if (mi < 0) {
      // Python turn needed: heap state is reconstructed on re-entry
      // from the dropped flags + task cursors (keys are live).
      *out_yield_job = ji;
      rc = -3;
      break;
    }
    task_cursor[ji] += 1;
    if (!vc_reclaim_possible(C, qid)) {
      // Turn consumed; job drops, queue re-enters.
      out_job_dropped[ji] = 1;
      qheap.push(make_qkey(slot));
      continue;
    }
    VcMaskSet& M = masks[mi];
    long long before_ev = *out_n_evicted;
    long long node = vc_walk_one(
        C, prow, qid, &M.cursor, M.anym, M.feas,
        has_pred ? M.stat : nullptr, M.slots,
        out_evicted, out_n_evicted, max_ev);
    for (long long i = before_ev; i < *out_n_evicted; ++i)
      refresh_node(C.p_node[out_evicted[i]]);
    if (node == -2) {
      // Mid-walk bail: resume WALK-ONLY in Python (rc -5).
      task_cursor[ji] -= 1;
      *out_yield_job = ji;
      rc = -5;
      break;
    }
    if (node >= 0) {
      const float* req_r = C.req + prow * C.R;
      for (long long k = 0; k < C.R; ++k) {
        C.n_pipelined[node * C.R + k] += req_r[k];
        C.fi[node * C.R + k] -= req_r[k];
      }
      C.pipe_node[prow] = node;
      C.n_ntasks[node] += 1;
      int32_t pj = C.p_job[prow];
      if (pj >= 0) {
        C.j_version[pj] += 1;
        C.j_waiting[pj] += 1;
        C.j_cnt_pending[pj] -= 1;
        for (long long k = 0; k < C.R; ++k)
          C.j_alloc_res[pj * C.R + k] += req_r[k];
        int32_t qi2 = C.q_of_job[pj];
        if (qi2 >= 0) {
          for (long long k = 0; k < C.R; ++k)
            C.q_alloc[qi2 * C.R + k] += req_r[k];
          C.q_version[qi2] += 1;
        }
      }
      out_pipe_rows[*out_n_pipe] = prow;
      out_pipe_nodes[*out_n_pipe] = node;
      ++*out_n_pipe;
      refresh_node(node);
      jheap.push(make_jkey(ji));  // assigned: job re-enters
    } else {
      out_job_dropped[ji] = 1;    // walk failed: job drops
    }
    qheap.push(make_qkey(slot));  // turn complete: queue re-enters
  }
  for (long long i = 0; i < n_masks; ++i) mask_cursors[i] = masks[i].cursor;
  return rc;
}

}  // extern "C"
