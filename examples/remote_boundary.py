"""Demo: scheduler side effects crossing a process boundary.

The reference scheduler's binds, evictions, and PodGroup status writes
are API-server RPCs; this framework keeps that boundary pluggable.
This demo runs the remote side-effect service (normally
``python -m volcano_tpu.cache.remote --port 18476`` in its own process
or pod) and a scheduler wired to it with all three drop-ins — then
submits a gang job and shows the binds and status landing remotely.

Production equivalent:

    # terminal 1 — the control-plane process
    python -m volcano_tpu.cache.remote --port 18476
    # terminal 2 — the scheduler
    vtpu-service --remote-binder http://127.0.0.1:18476 \
                 --remote-evictor http://127.0.0.1:18476 \
                 --remote-status-updater http://127.0.0.1:18476

Failure semantics match the reference: failed bind batches re-enter
Pending with exponential backoff (errTasks), failed evictions revert
the victim to Running for the next cycle, and failed status batches
re-mark their PodGroups dirty so the next close rewrites them.

Run:  python examples/remote_boundary.py
"""

import threading
import time

from volcano_tpu.api import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup
from volcano_tpu.cache import ClusterStore
from volcano_tpu.cache.remote import (
    HttpBinder,
    HttpEvictor,
    HttpStatusUpdater,
    RemoteBindService,
)
from volcano_tpu.scheduler import Scheduler


def main() -> None:
    # The "control plane" (its own OS process in production).
    svc = RemoteBindService(port=0)
    threading.Thread(target=svc.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{svc.port}"
    print(f"remote side-effect service on {url}")

    store = ClusterStore()
    store.binder = HttpBinder(url)
    store.evictor = HttpEvictor(url)
    store.status_updater = HttpStatusUpdater(url)
    store.async_bind = True

    for i in range(3):
        store.add_node(Node(name=f"n{i}",
                            allocatable={"cpu": "8", "memory": "16Gi"}))
    store.add_pod_group(PodGroup(name="demo", min_member=3))
    for k in range(3):
        store.add_pod(Pod(
            name=f"demo-{k}",
            annotations={GROUP_NAME_ANNOTATION: "demo"},
            containers=[{"cpu": "2", "memory": "2Gi"}],
        ))

    Scheduler(store).run_once()
    store.flush_binds(timeout=10)

    print("remote bind table:", HttpBinder(url).binds())
    print("remote podgroup status:", HttpStatusUpdater(url).pod_groups())
    assert len(HttpBinder(url).binds()) == 3
    assert (HttpStatusUpdater(url).pod_groups()
            ["default/demo"]["phase"] == "Running")
    print("ok: gang bound and status written across the boundary")
    store.close()  # stop the bind-dispatcher thread pinning the store
    svc.shutdown()


if __name__ == "__main__":
    main()
    time.sleep(0.05)  # let daemon threads drain before interpreter exit
