"""Benchmark suite: the five BASELINE.md configurations.

Select with BENCH_CONFIG=1..5, or the default "north" — the NORTH-STAR
shape itself (10k nodes x 100k pending pods, plain binpack+predicates,
gang 8): the driver-recorded number is the headline metric, lane split
included in the stderr comment.  Each config prints ONE JSON line
{"metric", "value", "unit", "vs_baseline"} on stdout; details go to
stderr.

Configs (BASELINE.json.configs):
  1. 3-replica gang Job end-to-end through the full service (admission ->
     job controller -> PodGroup -> scheduler -> bind -> simulated kubelet),
     the rebuild's `example/job.yaml on kind`.
  2. Synthetic 1k x 10k binpack+predicates, single queue.
  3. DRF multi-queue fairness: 5k nodes, 4 weighted queues, mixed gang sizes.
  4. Preempt + reclaim: 10k nodes fully occupied by low-priority victims,
     20k pending high-priority pods.
  5. Hyperscale bin-pack with inter-pod affinity / topology spread
     (full 50k x 500k when BENCH_FULL=1; 10k x 100k otherwise — the
     north-star shape).

The north-star budget is 100 ms OpenSession->Bind at 10k x 100k on one TPU
chip; vs_baseline = budget/measured with the budget scaled linearly by task
count (>= 1.0 means on budget at the measured scale).

Configs 2/3/5/north additionally report a `pipelined` metric (ISSUE 1
double-buffered sessions): steady-state cycle time amortized over >= 5
consecutive cycles on one store, each committing the previous cycle's
asynchronously-dispatched solve while dispatching the next — the plain
metric stays the synchronous loop, comparable to BENCH_r01-r05.  Both
JSON lines carry the per-lane split in a "lanes" tail.

Env knobs: BENCH_NODES/BENCH_PODS/BENCH_GANG/BENCH_REPEATS override config
defaults; BENCH_PIPELINE=0 skips the pipelined pass, BENCH_PIPE_CYCLES
sets the steady-state cycle count (min 5).  BENCH_TOPK A/Bs the
two-phase device solve in one run: the selected config executes twice —
"(shortlist on)" then "(shortlist off)" — emitting both JSON tails (a
numeric BENCH_TOPK > 1 also pins VOLCANO_TPU_TOPK for the on-pass); the
device_coarse/device_fine sub-lanes and the shortlist-fallback counts
ride the lane/fallback tails.  Every config additionally writes a
Perfetto-loadable trace file (flight-recorder cycles, BENCH_TRACE_DIR;
default /tmp/vtpu_bench_traces) and reports staleness-drop totals plus
per-lane p50/p95 (steady-state cycles only) in the machine-readable
JSON tail.

BENCH_HOST=1 (ISSUE 8) A/Bs the incremental host lanes in one run: the
selected config executes three times — "(incremental on)",
"(incremental off)" (full-rebuild derive, no host-lane caches), and
"(incremental fallback)" (VOLCANO_TPU_DIRTY_CAP=1, so every cycle
exercises the dirty-overflow fallback) — each emitting plain +
pipelined JSON tails whose `host_lanes_ms` field sums the host lanes
(derive+order+encode+commit+close+enqueue+feed+backfill) and whose
`lane_p50`/`lane_p95` tails carry the steady-state distribution.

BENCH_MESH=<devices> (ISSUE 7) A/Bs the mesh-native sharded solve in
one run: the process forces a virtual CPU platform with that many host
devices (must be set at startup — the flag is baked into XLA client
init), then the selected config executes twice — "(mesh on)" with every
store's ``solve_mesh`` set (node axis + count tensors sharded, sharded
devsnap, shard-local two-phase rankings) and "(mesh off)" plain — each
emitting its JSON tail with the usual lane split, plus one extra
"mesh winner-reduce" JSON line microbenching the cross-chip reduction
(the two-stage shard-local top-k vs the global top-k on the same
sharded plane).  Host-device simulation quantifies the decomposition;
the real win is the per-chip memory/compute split on a TPU slice.

BENCH_COMPOSED=1 (ISSUE 12) runs the authoritative north-star
composition: one "(plain)" synchronous pass (the BENCH_r05-comparable
row) followed by one "(composed)" pipelined steady state with the mesh
(BENCH_COMPOSED_MESH devices, virtual-CPU-forced unless
BENCH_COMPOSED_VIRTUAL=0), VOLCANO_TPU_DEVINCR, VOLCANO_TPU_INCREMENTAL
and a BENCH_COMPOSED_FRAC (default 5%) churn feed all engaged together,
ending with the null-delta probe.  The "composed" JSON tail carries the
engagement proof (mesh shards, devincr warm/full/skip, incremental
derive modes, plain-vs-composed ratio, knob matrix); every tail now
also reports compile/warmup separately from steady state (compile_ms +
warmup_cycles_ms).

BENCH_WIRE=1 (ISSUE 10) A/Bs the remote-solver transport in one run:
an in-process ``SolverServer`` thread serves solves over the REAL
loopback TCP stack (the solve shares this process's jit cache, so the
A/B isolates wire costs, not compile variance), every benched store
gets its own ``RemoteSolver`` client, and the selected config executes
three times — "(wire delta)" (``VOLCANO_TPU_WIRE=1``: delta solve
frames against the child's per-connection mirror), "(wire full)"
(``VOLCANO_TPU_WIRE=0``: classic v1 full frames), and
"(wire fallback)" (``VOLCANO_TPU_WIRE=fallback``: the delta machinery
runs but every frame voids the cache first, exercising the full-frame
fallback path).  The pipelined feed re-pends only BENCH_WIRE_FRAC of
the bound rows (default 5%, the steady-state churn shape), and each
pipelined JSON tail carries a "wire" section: per-kind frame counts
and bytes over the steady-state cycles, bytes/cycle (the number the
BASELINE "Remote wire" A/B compares), and fallback counts by reason.
"""

import copy
import json
import os
import re
import sys
import time
from contextlib import contextmanager

NORTH_STAR_MS = 100.0
NORTH_STAR_PODS = 100000

# BENCH_TOPK A/B driver state: suffix appended to every emitted metric
# name, so one run carries both "(shortlist on)"/"(shortlist off)" JSON
# tails (see main()).
_MODE_SUFFIX = ""
# BENCH_MESH A/B driver state: the jax.sharding.Mesh the benched stores
# dispatch over ("(mesh on)" pass), or None for the plain pass.
_MESH = None
# BENCH_DEVINCR driver state (ISSUE 9): the fraction of bound rows the
# pipelined feed re-pends per cycle (1.0 = everything — the classic
# steady-state loop; the devincr A/B uses a sparse fraction so the
# dirty set looks like production churn, not a full re-pend), and
# whether to append a null-delta probe (feed off for two cycles,
# asserting the skip path) to the pipelined pass.
_FEED_FRACTION = 1.0
_DEVINCR_PROBE = False

# BENCH_WIRE driver state (ISSUE 10): the in-process solver server's
# loopback port; when set, every benched store solves through its own
# RemoteSolver client and the pipelined tail carries wire telemetry.
_REMOTE_PORT = None

# The HOST lanes whose serial sum floors the pipelined cycle (ISSUE 8):
# everything the cycle thread does besides the device dispatch/fetch.
HOST_LANES = ("derive", "order", "encode", "commit", "close", "enqueue",
              "feed", "backfill")


def _host_lane_sum_ms(lanes) -> float:
    return sum(lanes.get(k, 0.0) for k in HOST_LANES) * 1e3


@contextmanager
def _twophase_env(on: bool, topk: int = 0):
    """Pin the two-phase knobs for one A/B pass (ops/wave.py reads them
    per call, so flipping works within one process; each mode compiles
    its own jit specialization)."""
    keys = ("VOLCANO_TPU_TWOPHASE", "VOLCANO_TPU_TOPK")
    old = {k: os.environ.get(k) for k in keys}
    os.environ["VOLCANO_TPU_TWOPHASE"] = "1" if on else "0"
    if on and topk > 1:
        os.environ["VOLCANO_TPU_TOPK"] = str(topk)
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _attach_remote(store):
    """BENCH_WIRE: point the store at the in-process solver server over
    loopback TCP; returns the client (caller closes it)."""
    if _REMOTE_PORT is None:
        return None
    from volcano_tpu.solver_service import RemoteSolver

    client = RemoteSolver(f"127.0.0.1:{_REMOTE_PORT}")
    store.remote_solver = client
    return client


# Audit tail (ISSUE 13): the bench loops stash the benched store's
# auditor stats here; _emit folds them into the next JSON tail (every
# tail carries the audited-cycles count + measured overhead).
_AUDIT_TAIL = None

# Journey tail (ISSUE 18): same stash pattern for the pod-journey log —
# every pipelined tail carries ttb_p50/p95/p99 and the gang
# time-to-full-bind percentiles.
_JOURNEY_TAIL = None


def _collect_audit(store):
    global _AUDIT_TAIL
    a = getattr(store, "auditor", None)
    if a is not None and a.enabled:
        _AUDIT_TAIL = a.audit_stats()


def _collect_journey(store):
    global _JOURNEY_TAIL
    jr = getattr(store, "journey", None)
    if jr is not None:
        _JOURNEY_TAIL = jr.stats()


def _emit(metric, value_ms, n_pods, extra="", budget_ms=None, lanes=None,
          records=None, fallbacks=None, rebalance=None, devincr=None,
          wire=None, preempt=None, compile_ms=None, warmup_cycles=None,
          composed=None, endurance=None, pool=None, shards=None,
          topology=None):
    global _AUDIT_TAIL, _JOURNEY_TAIL
    metric = metric + _MODE_SUFFIX
    if budget_ms is None:
        budget_ms = NORTH_STAR_MS * (n_pods / NORTH_STAR_PODS)
    payload = {
        "metric": metric,
        "value": round(value_ms, 2),
        "unit": "ms",
        "vs_baseline": round(
            budget_ms / value_ms if value_ms > 0 else 0.0, 4
        ),
    }
    if compile_ms is not None:
        # Compile/warmup time reported SEPARATELY from steady-state
        # (ISSUE 12 satellite: the r05 tail carried a 17.4 s cycle-2
        # jit spike inside cycles_ms, polluting the distribution —
        # steady-state numbers now NEVER include warmup cycles, and
        # this field is where the jit cost lives).
        payload["compile_ms"] = round(compile_ms, 1)
    if warmup_cycles is not None:
        payload["warmup_cycles_ms"] = [
            round(t * 1e3, 1) for t in warmup_cycles
        ]
    if composed:
        # BENCH_COMPOSED tail (ISSUE 12): the authoritative north-star
        # composition — which lanes engaged and what each mode counted.
        payload["composed"] = dict(composed)
    if rebalance:
        # BENCH_REBALANCE tail: frag-score before/after + plan stats
        # (docs/rebalance.md).
        payload["rebalance"] = dict(rebalance)
    if preempt:
        # BENCH_PREEMPT tail: what-if plan outcomes, evictions,
        # convergence + zero-lost-pods proof (docs/preempt_reclaim.md).
        payload["preempt"] = dict(preempt)
    if topology:
        # BENCH_TOPOLOGY tail (ISSUE 20): best-block fit before the
        # defrag wave, gang contiguity after it, placement-outcome
        # counts + zero-lost-pods proof (docs/topology.md).
        payload["topology"] = dict(topology)
    if fallbacks:
        # Two-phase shortlist-fallback rescores over the measured
        # cycles, by reason (docs/metrics.md).
        payload["shortlist_fallbacks"] = dict(fallbacks)
    if devincr:
        # Device-incremental decisions over the measured cycles
        # (warm/full/skip counts + static-plane hits, ISSUE 9).
        payload["devincr"] = dict(devincr)
    if wire:
        # Remote-solver transport telemetry over the steady-state
        # cycles (ISSUE 10): per-kind frame counts/bytes, bytes/cycle,
        # and fallback reasons.
        payload["wire"] = dict(wire)
    if endurance:
        # BENCH_ENDURANCE tail (ISSUE 13): cycles survived, anomaly
        # verdict, fault-wave counts, p99s vs budgets, audit overhead
        # (docs/observability.md).
        payload["endurance"] = dict(endurance)
    if pool:
        # BENCH_POOL tail (ISSUE 15): hedge dispatches/wins, failovers,
        # per-replica frame counts, device-lane percentiles, lost-pod
        # and anomaly verdicts per pool size (docs/tuning.md).
        payload["pool"] = dict(pool)
    if shards:
        # BENCH_SHARDS tail (ISSUE 16): binds/sec + conflict rate +
        # per-shard lane splits per shard count, plus the contention
        # phase's zero-lost-pods verdict (docs/sharding.md).
        payload["shards"] = dict(shards)
    if _AUDIT_TAIL is not None:
        # Runtime-auditor block (ISSUE 13): sampled cycles + measured
        # overhead ride every tail, so any bench row doubles as an
        # audit-overhead datapoint.
        payload["audit"] = _AUDIT_TAIL
        _AUDIT_TAIL = None
    if _JOURNEY_TAIL is not None:
        # Pod-journey block (ISSUE 18): time-to-bind percentiles + gang
        # time-to-full-bind over the benched store's journey log.
        payload["journey"] = _JOURNEY_TAIL
        _JOURNEY_TAIL = None
    if lanes:
        # Lane split rides in the JSON tail so the driver's BENCH_rXX
        # artifacts carry the per-mode breakdown, not just the total.
        payload["lanes"] = {
            k: round(v * 1e3, 1)
            for k, v in sorted(lanes.items(), key=lambda kv: -kv[1])
            if v >= 5e-4
        }
        # Host-lane serial sum (incl. the pipelined feed lane, ISSUE 8
        # satellite — the accounting must sum to the cycle time):
        # the number the BENCH_HOST incremental A/B compares.
        payload["host_lanes_ms"] = round(_host_lane_sum_ms(lanes), 2)
    if records:
        # Flight-recorder tail (ISSUE 3): staleness-drop totals by
        # reason and per-lane p50/p95 over the steady-state cycles, so
        # BENCH_r*.json captures the distribution, not just the best.
        drops = {}
        for rec in records:
            for reason, n in rec.drop_reasons.items():
                drops[reason] = drops.get(reason, 0) + n
        payload["drops"] = drops
        payload["lane_p50"], payload["lane_p95"] = _lane_pctl(records)
        _write_trace(metric, records)
    print(json.dumps(payload))
    if extra:
        print(f"# {extra}", file=sys.stderr)


def _lane_pctl(records):
    """Per-lane p50/p95 milliseconds over the given cycle records."""
    by_lane = {}
    for rec in records:
        for lane, sec in rec.lanes.items():
            by_lane.setdefault(lane, []).append(sec * 1e3)

    def pct(vals, q):
        vals = sorted(vals)
        i = min(int(q * (len(vals) - 1) + 0.5), len(vals) - 1)
        return round(vals[i], 2)

    p50 = {k: pct(v, 0.50) for k, v in by_lane.items()}
    p95 = {k: pct(v, 0.95) for k, v in by_lane.items()}
    return p50, p95


def _write_trace(metric, records):
    """One Perfetto trace file per emitted config/mode (chrome://tracing
    or ui.perfetto.dev; see docs/tracing.md)."""
    from volcano_tpu.obs import export

    out_dir = os.environ.get("BENCH_TRACE_DIR",
                             "/tmp/vtpu_bench_traces")
    try:
        os.makedirs(out_dir, exist_ok=True)
        slug = re.sub(r"[^a-z0-9]+", "-",
                      metric.lower()).strip("-")[:80]
        path = export.write_trace(
            os.path.join(out_dir, f"trace_{slug}.json"), records
        )
        print(f"# trace: {path}", file=sys.stderr)
    except OSError as err:  # trace files are best-effort
        print(f"# trace write failed: {err}", file=sys.stderr)


def _cycle_bench(make_store, conf, repeats, warm_store=None):
    """Measure one full scheduling cycle (OpenSession -> Bind) steady-state:
    warm-up compiles, then fresh stores of the same shape hit the jit cache."""
    from volcano_tpu.scheduler import Scheduler

    # Bind dispatch is async in production (the reference's goroutine
    # binds are not part of its e2e cycle latency either); binds are
    # flushed after timing before counting.  BENCH_SYNC_BIND=1 keeps the
    # binder calls inside the timed cycle — the control run quantifying
    # the measurement-boundary change.
    async_bind = os.environ.get("BENCH_SYNC_BIND") != "1"
    store = warm_store if warm_store is not None else make_store(0)
    store.async_bind = async_bind
    if _MESH is not None:
        store.solve_mesh = _MESH
    client = _attach_remote(store)
    binder = store.binder
    t0 = time.perf_counter()
    Scheduler(store, conf_str=conf).run_once()
    warm_s = time.perf_counter() - t0
    store.flush_binds()
    bound = len(binder.binds)
    evicted = len(getattr(store.evictor, "evicts", []))
    if client is not None:
        client.close()

    times = []
    lanes_best = None
    records = []
    for r in range(repeats):
        store_r = make_store(r + 1)
        store_r.async_bind = async_bind
        if _MESH is not None:
            store_r.solve_mesh = _MESH
        client_r = _attach_remote(store_r)
        sched_r = Scheduler(store_r, conf_str=conf)
        t0 = time.perf_counter()
        sched_r.run_once()
        times.append(time.perf_counter() - t0)
        if times[-1] == min(times):
            lanes_best = getattr(store_r, "last_cycle_lanes", None)
        # Flight-recorder records survive the store close (plain list
        # of plain records); one timed cycle each -> the repeat set IS
        # the steady-state distribution.
        records.extend(store_r.flight.recent())
        store_r.flush_binds()
        _collect_audit(store_r)
        _collect_journey(store_r)
        # The dispatcher thread's callbacks pin the store; stop it so the
        # repeat's full mirror is actually freed.
        store_r.close()
        if client_r is not None:
            client_r.close()
        del store_r, sched_r
    e2e_ms = min(times) * 1e3 if times else warm_s * 1e3
    return e2e_ms, bound, evicted, warm_s, times, lanes_best, records


def _pipelined_bench(make_store, conf, cycles=None):
    """Steady-state pipelined cycle time (ISSUE 1 double-buffered
    sessions), amortized over >= 5 consecutive cycles on ONE store.

    Every cycle commits the previous cycle's dispatched solve at its top
    and dispatches a fresh one from allocate; the workload feed
    (store.cycle_feed) re-pends the rows the commit just bound, so the
    backlog is constant and each cycle does commit(N-1) + dispatch(N) —
    the device round trip of session N overlapping cycle N's close and
    cycle N+1's derive/order/encode.  The first two cycles (compile +
    pipeline fill) are warm-up; the amortized mean over the rest is the
    steady-state number the north-star target reads."""
    import numpy as np

    from volcano_tpu.api import TaskStatus
    from volcano_tpu.scheduler import Scheduler

    st_bound = int(TaskStatus.Bound)
    if cycles is None:
        cycles = max(int(os.environ.get("BENCH_PIPE_CYCLES", 5)), 5)
    store = make_store(0)
    store.async_bind = os.environ.get("BENCH_SYNC_BIND") != "1"
    store.pipeline = True
    if _MESH is not None:
        # Pipelined dispatch works under a mesh (ISSUE 7): the parked
        # InflightSolve's arrays live sharded across the chips.
        store.solve_mesh = _MESH
    client = _attach_remote(store)
    fed = {"total": 0}

    def feed(fc):
        m = fc.m
        rows = np.flatnonzero(
            (m.p_status[:fc.Pn] == st_bound) & m.p_alive[:fc.Pn]
        )
        if _FEED_FRACTION < 1.0 and len(rows):
            # Sparse steady-state churn (BENCH_DEVINCR): re-pend only a
            # fraction of the bound rows, so the per-cycle dirty set
            # looks like production (a few hundred rows), not a full
            # backlog re-pend.
            rows = rows[:max(1, int(len(rows) * _FEED_FRACTION))]
        if len(rows):
            fed["total"] += len(rows)
            fc._unbind_rows(rows)

    store.cycle_feed = feed
    sched = Scheduler(store, conf_str=conf)
    # Warm-up cycles are timed INDIVIDUALLY so compile/jit spikes are
    # reported per cycle in the warmup_cycles_ms tail, never inside
    # the steady-state cycles_ms (ISSUE 12 satellite).
    warm_cycles = []

    def _warm_once():
        t0 = time.perf_counter()
        sched.run_once()
        warm_cycles.append(time.perf_counter() - t0)

    _warm_once()  # warm-up: compile + first dispatch (no commit yet)
    _warm_once()  # pipeline fill: first commit lands
    if _DEVINCR_PROBE or client is not None:
        # Device-incremental / wire A/B: the warm-shortlist kernel
        # compiles on its FIRST warm-eligible cycle (the pending set
        # stabilizes a couple of cycles after the backlog first
        # commits); keep that compile out of the measured steady
        # state, in every mode (the extra cycles are mode-symmetric —
        # without this the A/B's first mode eats the compile alone).
        for _ in range(3):
            _warm_once()
    warm_s = sum(warm_cycles)
    # Steady-state seam reset: the re-pend feed keeps the backlog
    # constant, but the two warm-up cycles already accumulated
    # two-phase shortlist-fallback counts (cold jit, first fill) —
    # reset the per-store accumulator here so the emitted fallback tail
    # covers exactly the steady-state cycles and the shortlist-on/off
    # pipelined rows stay comparable.  (The epoch-keyed class planes
    # deliberately survive: the feed mutates pods, not nodes.)
    store._shortlist_fb = {}
    # Wire-telemetry seam (BENCH_WIRE): counters to this point cover
    # warm-up (incl. the connection's first, necessarily-full frame);
    # the steady-state delta is what the A/B compares.
    wire0 = None
    if client is not None:
        wire0 = (dict(client.frame_counts), dict(client.frame_bytes),
                 dict(client.wire_fallbacks))
    times = []
    lane_acc = {}
    for _ in range(cycles):
        t0 = time.perf_counter()
        sched.run_once()
        times.append(time.perf_counter() - t0)
        for k, v in (store.last_cycle_lanes or {}).items():
            lane_acc[k] = lane_acc.get(k, 0.0) + v
    amortized_ms = sum(times) / len(times) * 1e3
    lanes = {k: v / len(times) for k, v in lane_acc.items()}
    wire = None
    if client is not None:
        counts0, bytes0, fb0 = wire0
        frames = {k: client.frame_counts[k] - counts0.get(k, 0)
                  for k in client.frame_counts}
        wbytes = {k: client.frame_bytes[k] - bytes0.get(k, 0)
                  for k in client.frame_bytes}
        wire = {
            "frames": frames,
            "bytes": wbytes,
            "bytes_per_cycle": round(sum(wbytes.values()) / cycles),
            "fallbacks": {
                k: v - fb0.get(k, 0)
                for k, v in client.wire_fallbacks.items()
                if v - fb0.get(k, 0)
            },
        }
    store.flush_binds()
    bound_per_cycle = fed["total"] // max(cycles + 1, 1)
    # Steady-state flight records only (the two warm-up cycles carry
    # compile + pipeline-fill time and would skew the percentiles).
    records = store.flight.recent()[-len(times):]
    fallbacks = dict(getattr(store, "_shortlist_fb", {}) or {})
    devincr = None
    dv = getattr(store, "_devincr_cache", None)
    if dv is not None:
        devincr = dict(dv.counts)
        devincr["static_hits"] = dv.static_hits
        devincr["static_builds"] = dv.static_builds
    if _DEVINCR_PROBE:
        # Null-delta probe (ISSUE 9): feed off, backlog committed, ONE
        # pending-but-unschedulable gang keeping the pending set
        # non-empty (an empty set early-outs before any solve and would
        # prove nothing).  With the lane on, idle cycles must complete
        # WITHOUT a solve dispatch (the skip proof); with it off, every
        # cycle re-dispatches the futile solve — measured, not assumed.
        from volcano_tpu.api import (
            GROUP_NAME_ANNOTATION as _GNA,
            Pod as _Pod,
            PodGroup as _PodGroup,
        )

        store.cycle_feed = None
        sched.run_once()  # commits the last dispatched solve
        store.add_pod_group(_PodGroup(name="bench-nullprobe",
                                      min_member=1))
        store.add_pod(_Pod(
            name="bench-nullprobe-0",
            annotations={_GNA: "bench-nullprobe"},
            containers=[{"cpu": "900000", "memory": "900000Gi"}],
        ))
        sched.run_once()  # dispatches the (failing) probe solve
        sched.run_once()  # commits its empty result
        seq0 = store._solve_seq
        skip0 = dv.counts["skip"] if dv is not None else 0
        t0 = time.perf_counter()
        probe_n = 2
        for _ in range(probe_n):
            sched.run_once()
        probe_ms = (time.perf_counter() - t0) / probe_n * 1e3
        if devincr is None:
            devincr = {}
        devincr["null_delta_cycle_ms"] = round(probe_ms, 3)
        devincr["null_delta_dispatches"] = store._solve_seq - seq0
        if dv is not None:
            devincr["null_delta_skips"] = dv.counts["skip"] - skip0
    _collect_audit(store)
    _collect_journey(store)
    store.close()
    if client is not None:
        client.close()
    return (amortized_ms, bound_per_cycle, warm_s, times, lanes, records,
            fallbacks, devincr, wire, warm_cycles)


def _emit_pipelined(label, mk, conf, n_pods):
    if os.environ.get("BENCH_PIPELINE", "1") == "0":
        return
    (amortized_ms, bound, warm_s, times, lanes, records,
     fallbacks, devincr, wire, warm_cycles) = _pipelined_bench(mk, conf)
    _emit(
        f"{label} (pipelined steady-state, amortized {len(times)} cycles)",
        amortized_ms, n_pods,
        f"warmup={warm_s:.2f}s bound_per_cycle={bound} "
        f"pods/s={bound / (amortized_ms / 1e3):.0f} "
        f"cycles_ms={[round(t * 1e3, 1) for t in times]}"
        + _lane_note(lanes),
        lanes=lanes,
        records=records,
        fallbacks=fallbacks,
        devincr=devincr,
        wire=wire,
        compile_ms=warm_s * 1e3,
        warmup_cycles=warm_cycles,
    )


def _lane_note(lanes) -> str:
    if not lanes:
        return ""
    parts = [f"{k}={v * 1e3:.0f}ms" for k, v in
             sorted(lanes.items(), key=lambda kv: -kv[1]) if v >= 5e-4]
    return " lanes[" + " ".join(parts) + "]"


CONF_BASE = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""

CONF_PREEMPT = """
actions: "enqueue, allocate, preempt, reclaim, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def config_1():
    """End-to-end 3-replica gang job through the full control plane."""
    from volcano_tpu.controllers.apis import Job, TaskSpec
    from volcano_tpu.service import Service

    # Prewarm the solver jit on the same padded shape bucket so the
    # measured latency is steady-state control-plane time, not XLA compile.
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.synth import synthetic_cluster

    warm = synthetic_cluster(n_nodes=2, n_pods=3, gang_size=3)
    Scheduler(warm).run_once()

    svc = Service(simulate=True, schedule_period=0.01,
                  controller_period=0.005)
    for i in range(2):
        from volcano_tpu.api import Node

        svc.store.add_node(
            Node(name=f"node-{i}",
                 allocatable={"cpu": "8", "memory": "16Gi", "pods": 64})
        )
    job = Job(
        name="test-job",
        min_available=3,
        tasks=[TaskSpec(
            name="worker", replicas=3,
            containers=[{"cpu": "1", "memory": "1Gi"}],
        )],
    )
    svc.start(http_port=0)
    try:
        t0 = time.perf_counter()
        svc.admitted.add_batch_job(job)
        deadline = t0 + 60.0
        while time.perf_counter() < deadline:
            pods = [
                p for p in svc.store.pods.values()
                if p.owner_job == job.key and p.phase == "Running"
            ]
            if len(pods) >= 3:
                break
            time.sleep(0.002)
        else:
            raise RuntimeError("job did not reach Running in 60s")
        e2e_ms = (time.perf_counter() - t0) * 1e3
    finally:
        svc.stop()
    # Budget: the reference on kind needs >= one 1 s schedule period plus
    # controller reconcile latency before pods run; call it 2 s.
    _emit("gang job submit->3 pods Running (full control plane)", e2e_ms, 3,
          "pods_running=3", budget_ms=2000.0)


def config_2(n_nodes, n_pods, gang, repeats):
    from volcano_tpu.synth import synthetic_cluster

    build_t0 = time.perf_counter()
    store = synthetic_cluster(n_nodes=n_nodes, n_pods=n_pods, gang_size=gang)
    build_s = time.perf_counter() - build_t0
    e2e_ms, bound, _, warm_s, times, lanes, recs = _cycle_bench(
        lambda r: synthetic_cluster(n_nodes=n_nodes, n_pods=n_pods,
                                    gang_size=gang, seed=r),
        CONF_BASE, repeats, warm_store=store,
    )
    _emit(
        f"OpenSession->Bind e2e @ {n_nodes} nodes x {n_pods} pending pods "
        f"(gang {gang})",
        e2e_ms, n_pods,
        f"warmup={warm_s:.2f}s bound={bound} "
        f"pods/s={bound / (e2e_ms / 1e3):.0f} build={build_s:.2f}s "
        f"cycles_ms={[round(t * 1e3, 1) for t in times]}"
        + _lane_note(lanes),
        lanes=lanes,
        records=recs,
        compile_ms=warm_s * 1e3,
    )
    _emit_pipelined(
        f"OpenSession->Bind e2e @ {n_nodes} nodes x {n_pods} pending pods "
        f"(gang {gang})",
        lambda r: synthetic_cluster(n_nodes=n_nodes, n_pods=n_pods,
                                    gang_size=gang, seed=r),
        CONF_BASE, n_pods,
    )


def config_3(repeats):
    from volcano_tpu.synth import synthetic_cluster

    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    n_pods = int(os.environ.get("BENCH_PODS", 50000))
    mk = lambda r: synthetic_cluster(
        n_nodes=n_nodes, n_pods=n_pods, n_queues=4,
        queue_weights=(1, 2, 4, 8), gang_sizes=(2, 4, 8, 16), seed=r,
    )
    e2e_ms, bound, _, warm_s, times, lanes, recs = _cycle_bench(
        mk, CONF_BASE, repeats)
    _emit(
        f"DRF multi-queue e2e @ {n_nodes} nodes x {n_pods} pods, 4 queues",
        e2e_ms, n_pods,
        f"warmup={warm_s:.2f}s bound={bound} "
        f"cycles_ms={[round(t * 1e3, 1) for t in times]}"
        + _lane_note(lanes),
        lanes=lanes,
        records=recs,
        compile_ms=warm_s * 1e3,
    )
    _emit_pipelined(
        f"DRF multi-queue e2e @ {n_nodes} nodes x {n_pods} pods, 4 queues",
        mk, CONF_BASE, n_pods,
    )


def config_4(repeats):
    from volcano_tpu.synth import preempt_cluster

    n_nodes = int(os.environ.get("BENCH_NODES", 10000))
    n_pending = int(os.environ.get("BENCH_PODS", 20000))
    mk = lambda r: preempt_cluster(n_nodes=n_nodes, n_pending=n_pending,
                                   seed=r)
    e2e_ms, bound, evicted, warm_s, times, lanes, recs = _cycle_bench(
        mk, CONF_PREEMPT, repeats)
    # No pipelined row: the preempt/reclaim actions mutate node capacity
    # AFTER the allocate dispatch, so every overlapped commit would hit
    # the staleness guard's re-validation — the plain number IS the
    # honest one for this config.
    _emit(
        f"preempt+reclaim e2e @ {n_nodes} nodes oversubscribed, "
        f"{n_pending} pending high-pri pods",
        e2e_ms, n_pending,
        f"warmup={warm_s:.2f}s bound={bound} evicted={evicted} "
        f"cycles_ms={[round(t * 1e3, 1) for t in times]}"
        + _lane_note(lanes),
        lanes=lanes,
        records=recs,
        compile_ms=warm_s * 1e3,
    )


def config_5(repeats):
    from volcano_tpu.synth import synthetic_cluster

    full = os.environ.get("BENCH_FULL") == "1"
    n_nodes = int(os.environ.get("BENCH_NODES", 50000 if full else 10000))
    n_pods = int(os.environ.get("BENCH_PODS", 500000 if full else 100000))
    mk = lambda r: synthetic_cluster(
        n_nodes=n_nodes, n_pods=n_pods, gang_size=8, zones=16,
        affinity_fraction=0.05, anti_affinity_fraction=0.05,
        spread_fraction=0.1, seed=r,
    )
    e2e_ms, bound, _, warm_s, times, lanes, recs = _cycle_bench(
        mk, CONF_BASE, repeats)
    _emit(
        f"hyperscale binpack+affinity e2e @ {n_nodes} nodes x "
        f"{n_pods} pods",
        e2e_ms, n_pods,
        f"warmup={warm_s:.2f}s bound={bound} "
        f"cycles_ms={[round(t * 1e3, 1) for t in times]}"
        + _lane_note(lanes),
        lanes=lanes,
        records=recs,
        compile_ms=warm_s * 1e3,
    )
    _emit_pipelined(
        f"hyperscale binpack+affinity e2e @ {n_nodes} nodes x "
        f"{n_pods} pods",
        mk, CONF_BASE, n_pods,
    )


def config_north(repeats):
    """The north-star shape, plain: 10k nodes x 100k pods, gang 8."""
    from volcano_tpu.synth import synthetic_cluster

    n_nodes = int(os.environ.get("BENCH_NODES", 10000))
    n_pods = int(os.environ.get("BENCH_PODS", 100000))
    mk = lambda r: synthetic_cluster(
        n_nodes=n_nodes, n_pods=n_pods, gang_size=8, zones=16, seed=r,
    )
    e2e_ms, bound, _, warm_s, times, lanes, recs = _cycle_bench(
        mk, CONF_BASE, repeats)
    _emit(
        f"OpenSession->Bind e2e @ {n_nodes} nodes x {n_pods} pending "
        f"pods (north star, plain)",
        e2e_ms, n_pods,
        f"warmup={warm_s:.2f}s bound={bound} "
        f"pods/s={bound / (e2e_ms / 1e3):.0f} "
        f"cycles_ms={[round(t * 1e3, 1) for t in times]}"
        + _lane_note(lanes),
        lanes=lanes,
        records=recs,
        compile_ms=warm_s * 1e3,
    )
    _emit_pipelined(
        f"OpenSession->Bind e2e @ {n_nodes} nodes x {n_pods} pending "
        f"pods (north star)",
        mk, CONF_BASE, n_pods,
    )


def config_rebalance():
    """BENCH_REBALANCE: fragmented-cluster defragmentation (ISSUE 5).

    BENCH_NODES worker nodes (4 cpu) each stranded by a 3-cpu filler,
    an equal count of 3-cpu spill nodes, and a high-priority gang of
    BENCH_NODES/2 whole-node tasks that allocate+backfill alone can
    never place.  Measures the planning+commit cycle and the cycles to
    full convergence (gang bound, every filler re-bound), and emits a
    frag-score-before/after tail (docs/rebalance.md)."""
    import time as _t

    from volcano_tpu.api import (
        GROUP_NAME_ANNOTATION,
        Node,
        Pod,
        PodGroup,
        PriorityClass,
    )
    from volcano_tpu.cache import ClusterStore, FakeBinder
    from volcano_tpu.framework import (
        REBALANCE_SCHEDULER_CONF,
        parse_scheduler_conf,
    )
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.sim import ClusterSimulator

    workers = int(os.environ.get("BENCH_NODES", 64))
    gang = max(workers // 2, 1)
    os.environ["VOLCANO_TPU_REBALANCE_DRAIN_CAP"] = str(workers)

    store = ClusterStore(binder=FakeBinder())
    store.add_priority_class(PriorityClass(name="bench-high", value=100))
    for i in range(workers):
        store.add_node(Node(name=f"w{i}", allocatable={
            "cpu": "4", "memory": "16Gi", "pods": 110}))
        store.add_node(Node(name=f"s{i}", allocatable={
            "cpu": "3", "memory": "16Gi", "pods": 110}))
    for i in range(workers):
        store.add_pod_group(PodGroup(name=f"bf{i}", min_member=1))
        store.add_pod(Pod(
            name=f"bfill{i}",
            annotations={GROUP_NAME_ANNOTATION: f"bf{i}"},
            containers=[{"cpu": "3", "memory": "1Gi"}],
        ))
    sched = Scheduler(store, conf_str=REBALANCE_SCHEDULER_CONF)
    sim = ClusterSimulator(store, grace_steps=2)
    sched.run_once()
    sim.step()
    store.add_pod_group(PodGroup(
        name="benchgang", min_member=gang, priority_class="bench-high"))
    for i in range(gang):
        store.add_pod(Pod(
            name=f"bg{i}",
            annotations={GROUP_NAME_ANNOTATION: "benchgang"},
            containers=[{"cpu": "4", "memory": "1Gi"}],
        ))

    def frag_now():
        """Mean frag score vs the gang's whole-node profile on live
        planes (one FastCycle derive + the planner kernel)."""
        import jax
        import numpy as np

        from volcano_tpu.fastpath import FastCycle
        from volcano_tpu.ops.rebalance import frag_scores

        cyc = FastCycle(store, parse_scheduler_conf(
            REBALANCE_SCHEDULER_CONF))
        with store._lock:
            cyc.derive()
        prof = np.zeros((1, cyc.R), np.float32)
        prof[0, 0] = 4000.0  # the gang task: 4 cpu (milli)
        prof[0, 1] = float(1 << 30)  # 1Gi
        fs = frag_scores(cyc.n_idle.astype(np.float32),
                         cyc.n_alloc.astype(np.float32), cyc.n_ready,
                         np.zeros_like(cyc.n_idle), prof, cyc.eps)
        (frag,) = jax.device_get((fs.frag,))
        alive = cyc.n_alive
        return float(frag[alive].mean()) if alive.any() else 0.0

    from volcano_tpu.metrics import metrics as _metrics

    def _evictions_total():
        return sum(_metrics.rebalance_evictions.data.values())

    ev_before = _evictions_total()
    frag_before = frag_now()
    t0 = _t.perf_counter()
    sched.run_once()  # plans + commits the migration wave
    plan_cycle_ms = (_t.perf_counter() - t0) * 1e3
    converged_cycles = 0
    for _ in range(24):
        converged_cycles += 1
        sim.step()
        sched.run_once()
        bound = sum(1 for p in store.pods.values()
                    if p.name.startswith("bg") and p.node_name)
        if bound >= gang:
            break
    frag_after = frag_now()
    ledger = store.migrations
    _emit(
        f"Rebalance plan+commit cycle @ {2 * workers} nodes, "
        f"{gang}-task gang",
        plan_cycle_ms, gang,
        f"converged_in={converged_cycles} cycles "
        f"plans={ledger.committed_plans if ledger else 0} "
        f"frag {frag_before:.3f} -> {frag_after:.3f}",
        budget_ms=NORTH_STAR_MS,
        lanes=store.last_cycle_lanes,
        rebalance={
            "frag_before": round(frag_before, 4),
            "frag_after": round(frag_after, 4),
            "gang": gang,
            "evictions": int(_evictions_total() - ev_before),
            "committed_plans": (ledger.committed_plans
                                if ledger else 0),
            "converged_cycles": converged_cycles,
        },
    )
    store.close()


def config_topology():
    """BENCH_TOPOLOGY: fragmented-fabric contiguous gang placement
    (ISSUE 20, docs/topology.md).

    ``synth.fabric_cluster`` at the acceptance shape: 2 racks x 2 ICI
    slices of 16 nodes, every slice stranded by 2 Running fillers, and
    a pending 32-task require-contiguous gang no single block can host
    (each slice fits 28 of 32).  Measures the cycle that pregates the
    gang AND plans+commits the slice-defrag wave, then the cycles to
    full contiguous convergence (gang bound in one block, every filler
    re-bound).  The tail carries the best-block fit before the wave vs
    the gang's contiguity after it, the placement-outcome counters,
    and the zero-lost-pods proof."""
    import time as _t

    import numpy as np

    from volcano_tpu.api.spec import FABRIC_RACK, FABRIC_SLICE
    from volcano_tpu.cache import FakeBinder
    from volcano_tpu.framework import (
        REBALANCE_SCHEDULER_CONF,
        parse_scheduler_conf,
    )
    from volcano_tpu.metrics import metrics as _metrics
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.sim import ClusterSimulator
    from volcano_tpu.synth import fabric_cluster

    racks = int(os.environ.get("BENCH_TOPO_RACKS", 2))
    slices = int(os.environ.get("BENCH_TOPO_SLICES", 2))
    slice_nodes = int(os.environ.get("BENCH_TOPO_SLICE_NODES", 16))
    gang = int(os.environ.get("BENCH_GANG", 32))
    n_nodes = racks * slices * slice_nodes
    n_fillers = racks * slices * 2
    os.environ["VOLCANO_TPU_REBALANCE_DRAIN_CAP"] = str(n_nodes)

    store = fabric_cluster(racks=racks, slices_per_rack=slices,
                           nodes_per_slice=slice_nodes, gang_tasks=gang,
                           binder=FakeBinder())
    sched = Scheduler(store, conf_str=REBALANCE_SCHEDULER_CONF)
    sim = ClusterSimulator(store, grace_steps=2)

    def best_block_fit():
        """Fraction of the gang's pending demand the best single
        fabric block can host right now (the contiguity ceiling,
        kernel-scored on live planes)."""
        import jax

        from volcano_tpu.fastpath import FastCycle
        from volcano_tpu.ops import topology as topo

        pending = sum(1 for p in store.pods.values()
                      if p.name.startswith("fabgang")
                      and not p.node_name)
        if not pending:
            return 1.0
        cyc = FastCycle(store, parse_scheduler_conf(
            REBALANCE_SCHEDULER_CONF))
        with store._lock:
            cyc.derive()
        _, block, n_blocks = topo.fabric_planes(store.mirror)
        if not n_blocks:
            return 0.0
        prof = np.zeros((1, cyc.R), np.float32)
        prof[0, 0] = 2000.0  # the gang task: 2 cpu (milli)
        prof[0, 1] = float(1 << 30)  # 1Gi
        cnt = np.array([pending], np.int32)
        bid = np.full((len(cyc.n_idle),), -1, np.int32)
        bid[:cyc.Nn] = block[:cyc.Nn]
        bf = topo.gang_block_fit(
            cyc.n_idle.astype(np.float32), cyc.n_ready, cyc.n_ntasks,
            cyc.n_maxtasks, bid, prof, cnt, cyc.eps,
            n_blocks=int(n_blocks))
        (score,) = jax.device_get((bf.score,))
        return float(score.max()) / float(pending)

    def gang_contiguity():
        """Largest single-block share of the gang's BOUND members
        (0 while the pregate holds everything back)."""
        per_block = {}
        bound = 0
        for p in store.pods.values():
            if not p.name.startswith("fabgang") or not p.node_name:
                continue
            bound += 1
            n = store.nodes.get(p.node_name)
            labels = (getattr(n, "labels", None)
                      or getattr(getattr(n, "node", None), "labels", {})
                      or {})
            key = (labels.get(FABRIC_RACK), labels.get(FABRIC_SLICE))
            per_block[key] = per_block.get(key, 0) + 1
        return (max(per_block.values()) / bound) if bound else 0.0

    def _placements(outcome):
        return _metrics.topology_placements.data.get(
            (("outcome", outcome),), 0.0)

    def _fillers_bound():
        return sum(1 for p in store.pods.values()
                   if p.name.startswith("filler-") and p.node_name)

    ev0 = sum(_metrics.rebalance_evictions.data.values())
    inf0 = _placements("infeasible")
    cont0 = _placements("contiguous")
    fit_before = best_block_fit()
    t0 = _t.perf_counter()
    sched.run_once()  # pregates the gang + plans/commits the wave
    plan_cycle_ms = (_t.perf_counter() - t0) * 1e3
    converged_cycles = 0
    for _ in range(24):
        converged_cycles += 1
        sim.step()
        sched.run_once()
        bound = sum(1 for p in store.pods.values()
                    if p.name.startswith("fabgang") and p.node_name)
        if bound >= gang and _fillers_bound() >= n_fillers:
            break
    ledger = store.migrations
    contig_after = gang_contiguity()
    _emit(
        f"Topology defrag plan+commit cycle @ {n_nodes} nodes, "
        f"{gang}-task require-contiguous gang",
        plan_cycle_ms, gang,
        f"converged_in={converged_cycles} cycles "
        f"fit_before={fit_before:.3f} contiguity_after={contig_after:.3f}",
        budget_ms=NORTH_STAR_MS,
        lanes=store.last_cycle_lanes,
        topology={
            "fit_before": round(fit_before, 4),
            "contiguity_after": round(contig_after, 4),
            "gang": gang,
            "infeasible_transitions": int(_placements("infeasible")
                                          - inf0),
            "contiguous_placements": int(_placements("contiguous")
                                         - cont0),
            "committed_plans": (ledger.committed_plans
                                if ledger else 0),
            "evictions": int(sum(
                _metrics.rebalance_evictions.data.values()) - ev0),
            "converged_cycles": converged_cycles,
            "lost_pods": n_fillers - _fillers_bound(),
        },
    )
    store.close()


def config_preempt():
    """BENCH_PREEMPT: device-native priority-tier preemption (ISSUE 11,
    docs/preempt_reclaim.md).

    BENCH_NODES worker nodes each fully occupied by a Running
    low-priority batch pod (one single-member PodGroup per node — the
    disruption budgets bite per group), plus a Pending high-priority
    serving gang of BENCH_NODES/2 whole-node tasks.  Allocate alone can
    never place the gang; the preempt lane plans victims via the
    jitted kernel, proves the wave with a what-if solve, and commits.
    Measures the plan+commit cycle and cycles to convergence through
    the eviction grace window, and emits a "preempt" JSON tail (plans,
    evictions, restores, zero-lost-pods) the run-e2e smoke asserts
    device-lane engagement from."""
    import time as _t

    from volcano_tpu.cache import ClusterStore, FakeBinder, FakeEvictor
    from volcano_tpu.metrics import metrics as _metrics
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.sim import ClusterSimulator

    conf = """
actions: "enqueue, allocate, preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""
    workers = int(os.environ.get("BENCH_NODES", 64))
    gang = max(workers // 2, 1)
    os.environ.setdefault("VOLCANO_TPU_EVICT_DEVICE", "1")
    os.environ["VOLCANO_TPU_EVICT_CAP"] = str(workers)

    store = ClusterStore(binder=FakeBinder(), evictor=FakeEvictor())
    ClusterSimulator.priority_tier_workload(
        store, workers=workers, serving_tasks=gang)
    sched = Scheduler(store, conf_str=conf)
    sim = ClusterSimulator(store, grace_steps=2)

    def _plans():
        return {
            k[0][1] + "/" + k[1][1]: int(v)
            for k, v in _metrics.whatif_plans.data.items()
        }

    def _evictions():
        return int(sum(_metrics.preempt_evictions.data.values()))

    ev_before = _evictions()
    n_logical = len(store.pods)
    t0 = _t.perf_counter()
    sched.run_once()  # plans + proves + commits the preempt wave
    plan_cycle_ms = (_t.perf_counter() - t0) * 1e3
    converged_cycles = 0
    bound = 0
    for _ in range(24):
        converged_cycles += 1
        sim.step()
        sched.run_once()
        bound = sum(1 for p in store.pods.values()
                    if p.name.startswith("serving-") and p.node_name)
        if bound >= gang:
            break
    restored = sum(1 for p in store.pods.values() if "-mig" in p.uid)
    ledger = store.migrations
    _emit(
        f"Preempt plan+prove+commit cycle @ {workers} nodes, "
        f"{gang}-task serving gang over batch",
        plan_cycle_ms, gang,
        f"converged_in={converged_cycles} cycles bound={bound} "
        f"evictions={_evictions() - ev_before} restored={restored}",
        budget_ms=NORTH_STAR_MS,
        lanes=store.last_cycle_lanes,
        preempt={
            "gang": gang,
            "gang_bound": bound,
            "plans": _plans(),
            "evictions": int(_evictions() - ev_before),
            "restored": restored,
            "committed_plans": (ledger.committed_plans
                                if ledger else 0),
            "converged_cycles": converged_cycles,
            "pods_before": n_logical,
            "pods_after": len(store.pods),
            "lost_pods": n_logical - len(store.pods),
        },
    )
    store.close()


def config_composed():
    """BENCH_COMPOSED=1 (ISSUE 12): the authoritative north-star run.

    Every fast lane built since PR 6 — mesh-sharded solve, persistent
    device incrementality (``VOLCANO_TPU_DEVINCR``), incremental host
    lanes (``VOLCANO_TPU_INCREMENTAL``), pipelined double-buffered
    sessions, and a steady sparse churn feed — engaged TOGETHER in one
    configuration at the north-star shape, instead of each A/B'd in
    isolation.  Two passes:

    - "(plain)": the synchronous single-device cycle, directly
      comparable to the BENCH_r05 272 ms row;
    - "(composed)": pipelined steady state with the mesh, both
      incrementality lanes, and a ``BENCH_COMPOSED_FRAC`` (default 5%)
      churn feed, ending with the null-delta probe.

    The composed JSON tail carries the engagement proof the e2e smoke
    asserts: mesh shard count, devincr warm/full/skip counts,
    host-incremental derive modes (delta counted from the metrics
    registry), the plain-vs-composed ratio, and the knob matrix.

    ``BENCH_COMPOSED_MESH`` (default 4) sizes the mesh;
    ``BENCH_COMPOSED_VIRTUAL=0`` skips the virtual-CPU platform force
    for real multi-chip hosts (the default forces it, like BENCH_MESH —
    it must happen before anything touches jax)."""
    global _MODE_SUFFIX, _MESH, _FEED_FRACTION, _DEVINCR_PROBE

    try:
        n_dev = max(0, int(os.environ.get("BENCH_COMPOSED_MESH", "4")))
    except ValueError:
        n_dev = 4
    mesh = None
    if n_dev >= 2:
        if os.environ.get("BENCH_COMPOSED_VIRTUAL", "1") != "0":
            from volcano_tpu.virtualcpu import force_virtual_cpu_platform

            force_virtual_cpu_platform(n_dev)
            from volcano_tpu.parallel import make_mesh

            mesh = make_mesh(n_dev, platform="cpu")
        else:
            from volcano_tpu.parallel import make_mesh

            try:
                mesh = make_mesh(n_dev)
            except RuntimeError as err:
                print(f"# composed: no mesh ({err}); single device",
                      file=sys.stderr)
    # Pin the composed knob matrix explicitly (docs/tuning.md "Composed
    # profile"): every lane ON — the point is the interaction, not the
    # A/B.
    os.environ["VOLCANO_TPU_TWOPHASE"] = "1"
    os.environ["VOLCANO_TPU_INCREMENTAL"] = "1"
    os.environ["VOLCANO_TPU_DEVINCR"] = "1"
    try:
        frac = float(os.environ.get("BENCH_COMPOSED_FRAC", "0.05"))
    except ValueError:
        frac = 0.05
    n_nodes = int(os.environ.get("BENCH_NODES", 10000))
    n_pods = int(os.environ.get("BENCH_PODS", 100000))
    repeats = int(os.environ.get("BENCH_REPEATS", 3))
    from volcano_tpu.synth import synthetic_cluster

    mk = lambda r: synthetic_cluster(
        n_nodes=n_nodes, n_pods=n_pods, gang_size=8, zones=16, seed=r,
    )
    label = (f"OpenSession->Bind e2e @ {n_nodes} nodes x {n_pods} "
             f"pending pods (north star")

    # ---- pass 1: plain — the r05-comparable synchronous cycle.
    _MESH = None
    _MODE_SUFFIX = ""
    plain_ms, bound, _, warm_s, times, lanes, recs = _cycle_bench(
        mk, CONF_BASE, repeats)
    _emit(
        label + ", plain)", plain_ms, n_pods,
        f"warmup={warm_s:.2f}s bound={bound} "
        f"cycles_ms={[round(t * 1e3, 1) for t in times]}"
        + _lane_note(lanes),
        lanes=lanes, records=recs, compile_ms=warm_s * 1e3,
    )

    # ---- pass 2: composed — everything on, one pipelined steady state.
    from volcano_tpu.metrics import metrics as _metrics

    def _derive_modes():
        return {
            dict(k).get("mode", "?"): int(v)
            for k, v in _metrics.host_incremental_derives.data.items()
        }

    derives0 = _derive_modes()
    _MESH = mesh
    _FEED_FRACTION = min(max(frac, 0.0), 1.0)
    _DEVINCR_PROBE = True
    try:
        (amortized_ms, bound_pc, warm_s, times, lanes, records,
         fallbacks, devincr, wire, warm_cycles) = _pipelined_bench(
            mk, CONF_BASE)
    finally:
        _MESH = None
        _FEED_FRACTION = 1.0
        _DEVINCR_PROBE = False
    derives1 = _derive_modes()
    comp = {
        "mesh_shards": int(mesh.devices.size) if mesh is not None else 1,
        "feed_fraction": _round_frac(frac),
        "plain_ms": round(plain_ms, 2),
        "pipelined_ms": round(amortized_ms, 2),
        "speedup_vs_plain": round(plain_ms / amortized_ms, 2)
        if amortized_ms > 0 else 0.0,
        "incremental_derives": {
            m: derives1.get(m, 0) - derives0.get(m, 0)
            for m in set(derives0) | set(derives1)
        },
        "knobs": {
            "VOLCANO_TPU_MESH": (int(mesh.devices.size)
                                 if mesh is not None else 0),
            "VOLCANO_TPU_TWOPHASE": 1,
            "VOLCANO_TPU_INCREMENTAL": 1,
            "VOLCANO_TPU_DEVINCR": 1,
            "pipeline": 1,
            "wire": "remote" if _REMOTE_PORT is not None else "local",
        },
    }
    _emit(
        label + f", composed, {len(times)} steady cycles)",
        amortized_ms, n_pods,
        f"warmup={warm_s:.2f}s bound_per_cycle={bound_pc} "
        f"plain={plain_ms:.1f}ms composed={amortized_ms:.1f}ms "
        f"cycles_ms={[round(t * 1e3, 1) for t in times]}"
        + _lane_note(lanes),
        lanes=lanes, records=records, fallbacks=fallbacks,
        devincr=devincr, wire=wire, compile_ms=warm_s * 1e3,
        warmup_cycles=warm_cycles, composed=comp,
    )


ENDURANCE_CONF = """
actions: "enqueue, allocate, backfill, preempt, rebalance"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def _restart_pool_member(servers, idx, victim, reason):
    """Kill + restart pool member ``idx`` (the ISSUE 15 fault legs):
    sever the replica's live connection FIRST (the server's conn
    thread exits on the dead socket and releases the established
    tuple), drop the listener, rebind the same port with a bounded
    retry, carry the straggler hook over, and respawn the serve
    thread.  When the kernel keeps the old tuple a fresh ephemeral
    port is still a faithful child restart — the replica is RETARGETED
    so its next reconnect dials the new port instead of the dead one
    (the heal assertions depend on the reconnect actually landing)."""
    import threading as _threading

    from volcano_tpu.solver_service import SolverServer

    vport = servers[idx].port
    with victim._lock:
        victim._close_locked(reason)
    servers[idx].shutdown()
    ns = None
    for _attempt in range(50):
        try:
            ns = SolverServer(port=vport)
            break
        except OSError:
            time.sleep(0.1)
    if ns is None:
        ns = SolverServer(port=0)
        victim.port = ns.port
    ns.solve_delay_fn = servers[idx].solve_delay_fn
    servers[idx] = ns
    _threading.Thread(target=ns.serve_forever, daemon=True).start()
    return ns


def config_endurance():
    """BENCH_ENDURANCE=1 (ISSUE 13): the compressed-hours survival gate.

    A pipelined steady state at 2k nodes x 20k pods (10k x 100k with
    ``BENCH_FULL=1``) under sustained churn PLUS scheduled fault waves
    — node flaps, solver-child kills (connection severed + server
    restarted: reconnect -> full frame -> deltas re-engage), periodic
    high-priority preempt gangs, full pod lifecycle churn
    (delete-running + re-add) that drives real pod-table compactions —
    with the runtime auditor ON (``VOLCANO_TPU_AUDIT_SAMPLE``,
    harness default 16) and SLO budgets declared from a calibration
    window.  Phases:

    1. warm-up (compile + pipeline fill, untimed),
    2. calibration (10 cycles: declares cycle/device p99 budgets at
       ``BENCH_ENDURANCE_BUDGET_MULT`` x the observed median, unless
       ``VOLCANO_TPU_SLO_*`` pinned them),
    3. audit-overhead A/B (churn-only: auditor off then on,
       ``audit_overhead_pct`` in the tail — the <2% envelope),
    4. endurance (``BENCH_ENDURANCE_CYCLES``, default 300, faults on).

    The JSON tail carries cycles survived, the anomaly verdict,
    fault-wave counts, steady p50/p99 vs the declared budgets, and the
    audit overhead; the process **exits nonzero on any anomaly** —
    this is the gate hack/run-endurance.sh and the e2e smoke call.
    """
    import threading as _threading

    import numpy as _np

    from volcano_tpu.api import (
        GROUP_NAME_ANNOTATION,
        Pod,
        PodGroup,
        PriorityClass,
        TaskStatus,
    )
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.sim import ClusterSimulator
    from volcano_tpu.synth import synthetic_cluster

    full = os.environ.get("BENCH_FULL") == "1"
    n_nodes = int(os.environ.get("BENCH_NODES",
                                 10000 if full else 2000))
    n_pods = int(os.environ.get("BENCH_PODS",
                                100000 if full else 20000))
    cycles = max(int(os.environ.get("BENCH_ENDURANCE_CYCLES", "300")),
                 40)
    try:
        frac = float(os.environ.get("BENCH_ENDURANCE_FRAC", "0.05"))
    except ValueError:
        frac = 0.05
    try:
        del_frac = float(os.environ.get(
            "BENCH_ENDURANCE_DELETE_FRAC", "0.005"))
    except ValueError:
        del_frac = 0.005
    # Sampled audits every 16th cycle by default (denser than the
    # production 64: the gate's whole point is coverage per wall-hour).
    os.environ.setdefault("VOLCANO_TPU_AUDIT_SAMPLE", "16")
    # The gate exists to EXPOSE fast-path failures: a silent
    # object-session fallback would absorb exactly the breakage the
    # fault waves exist to provoke.
    os.environ["VOLCANO_TPU_FALLBACK"] = "never"

    store = synthetic_cluster(n_nodes=n_nodes, n_pods=n_pods,
                              gang_size=8, zones=16, seed=0)
    store.pipeline = True
    store.async_bind = True
    auditor = store.auditor
    st_bound = int(TaskStatus.Bound)
    st_running = int(TaskStatus.Running)

    # Solver child(ren) over real loopback TCP, so the kill wave severs
    # real connections (BENCH_ENDURANCE_WIRE=0 keeps the in-process
    # solver; the kill wave then no-ops).  BENCH_ENDURANCE_POOL=<n>
    # (>= 2) is the pool leg (ISSUE 15): n servers behind a SolverPool,
    # a mild straggler on replica 0 with tight hedge knobs so hedges
    # fire regularly, and kill waves that hit RANDOM pool members — so
    # some kills land mid-hedge.  Default 1 keeps the historic
    # single-connection harness byte-for-byte.
    server = client = None
    servers = []
    pool_n = 1
    try:
        pool_n = max(1, int(os.environ.get("BENCH_ENDURANCE_POOL",
                                           "1")))
    except ValueError:
        pool_n = 1
    # Sharded-control-plane leg (ISSUE 16): BENCH_ENDURANCE_SHARDS=<n>
    # (>= 2) runs the whole gate — churn + flaps + preempt waves +
    # compactions + solver kills — with n cycle shards over the one
    # store, each with its own solver lane.  The shared node pool plus
    # the churn feed makes same-node races between shards routine; the
    # zero-anomaly verdict is then the optimistic commit protocol's
    # endurance proof.  Mutually exclusive with the pool leg (each
    # shard owns exactly one connection).
    try:
        shards_n = max(1, int(os.environ.get("BENCH_ENDURANCE_SHARDS",
                                             "1")))
    except ValueError:
        shards_n = 1
    if shards_n > 1:
        pool_n = 1
    shard_clients = []
    shard_servers = []
    wire_on = os.environ.get("BENCH_ENDURANCE_WIRE", "1") != "0"
    if wire_on and pool_n > 1:
        import random as _random

        from volcano_tpu.solver_pool import SolverPool
        from volcano_tpu.solver_service import SolverServer

        os.environ.setdefault("VOLCANO_TPU_POOL_HEDGE_P99_MULT", "2.0")
        os.environ.setdefault("VOLCANO_TPU_POOL_HEDGE_MIN_MS", "20")
        for k in range(pool_n):
            srv = SolverServer(port=0)
            if k == 0:
                # Mild periodic straggle: enough to trigger hedges,
                # small enough to keep the calibrated budgets honest.
                srv.solve_delay_fn = (
                    lambda i: 0.06 if i % 7 == 0 else 0.0)
            _threading.Thread(target=srv.serve_forever,
                              daemon=True).start()
            servers.append(srv)
        client = SolverPool([f"127.0.0.1:{s.port}" for s in servers])
        store.remote_solver = client
        _kill_rng = _random.Random(5)
    elif wire_on:
        from volcano_tpu.solver_service import RemoteSolver, SolverServer

        server = SolverServer(port=0)
        _threading.Thread(target=server.serve_forever,
                          daemon=True).start()
        client = RemoteSolver(f"127.0.0.1:{server.port}")
        store.remote_solver = client
        # Extra solver lanes for shards 1..n-1 (the wire protocol is
        # strict request/reply per connection; shard 0 keeps `client`
        # and stays the kill wave's victim).
        for _ in range(shards_n - 1):
            srv = SolverServer(port=0)
            _threading.Thread(target=srv.serve_forever,
                              daemon=True).start()
            shard_servers.append(srv)
            shard_clients.append(RemoteSolver(f"127.0.0.1:{srv.port}"))

    # Steady churn feed: re-pend a fraction of the freshly-bound rows.
    def feed(fc):
        m = fc.m
        rows = _np.flatnonzero(
            (m.p_status[:fc.Pn] == st_bound) & m.p_alive[:fc.Pn]
        )
        if len(rows):
            fc._unbind_rows(rows[:max(1, int(len(rows) * frac))])

    store.cycle_feed = feed
    wave_queue = "default"
    if shards_n > 1:
        from volcano_tpu.api import Queue
        from volcano_tpu.shard import ShardedScheduler, stable_shard

        sched = ShardedScheduler(store, conf_str=ENDURANCE_CONF,
                                 shards=shards_n)
        if client is not None:
            sched.shards[0].remote_solver = client
            for ctx, cl in zip(sched.shards[1:], shard_clients):
                ctx.remote_solver = cl
        # The preempt waves must land in a queue OWNED BY the evictor
        # shard (shard 0): evict actions run only there under the
        # sharded plane (docs/sharding.md), so a wave gang homed
        # elsewhere would pend forever and the gate would measure a
        # stall, not the protocol.
        qi = 0
        while stable_shard(f"endur-q{qi}", shards_n) != 0:
            qi += 1
        wave_queue = f"endur-q{qi}"
        store.add_queue(Queue(name=wave_queue, weight=4))
    else:
        sched = Scheduler(store, conf_str=ENDURANCE_CONF)
    sim = ClusterSimulator(store, grace_steps=1)

    def one_cycle():
        t0 = time.perf_counter()
        sched.run_once()
        dt = time.perf_counter() - t0
        store.flush_binds()
        sim.step()
        return dt

    # Scenario helpers shared by every phase -------------------------
    from volcano_tpu.api import PodPhase

    clone_seq = 0
    wave_seq = 0
    d_per_cycle = max(1, int(n_pods * del_frac))
    wave_cpu = os.environ.get("BENCH_ENDURANCE_WAVE_CPU", "40")

    def _lifecycle_churn(n):
        """Full pod lifecycle: delete n Running pods (tombstones ->
        real compactions) and re-add fresh clones into their gangs, so
        the backlog holds and the add/delete conservation flows run."""
        nonlocal clone_seq
        # Snapshot under the store lock (the async bind dispatcher
        # mutates `pods` concurrently; the lockdep leg enforces this).
        with store._lock:
            running = [p for p in store.pods.values()
                       if int(p.task_status()) == st_running
                       and not p.deleting][:n]
        for pod in running:
            store.delete_pod(pod)
            clone_seq += 1
            clone = copy.copy(pod)
            clone.uid = f"{pod.uid}-e{clone_seq}"
            clone.name = f"{pod.name}-e{clone_seq}"
            clone.node_name = None
            clone.deleting = False
            clone.exit_code = 0
            clone.phase = PodPhase.Pending
            store.add_pod(clone)

    def _submit_wave():
        """One high-priority 4-task gang of large pods: places only by
        evicting batch residents (victim-selection -> what-if ->
        ledger-restore under load)."""
        nonlocal wave_seq
        wave_seq += 1
        gname = f"endur-hi{wave_seq}"
        store.add_pod_group(PodGroup(
            name=gname, min_member=4, priority_class="endur-hi",
            queue=wave_queue))
        for t in range(4):
            store.add_pod(Pod(
                name=f"{gname}-{t}",
                annotations={GROUP_NAME_ANNOTATION: gname},
                containers=[{"cpu": wave_cpu, "memory": "8Gi"}],
                priority=1000,
            ))
        return gname

    def _teardown_wave(gname):
        with store._lock:  # snapshot: binds land concurrently
            members = [p for p in store.pods.values()
                       if (p.annotations or {}).get(
                           GROUP_NAME_ANNOTATION) == gname]
        for p in members:
            store.delete_pod(p)
        if f"default/{gname}" in store.pod_groups:
            store.delete_pod_group(f"default/{gname}")

    def _flip_node(name, ready):
        ni = store.nodes.get(name)
        if ni is None or ni.node is None:
            return
        spec = ni.node
        spec.ready = ready
        store.update_node(spec)

    # ---- phase 1: warm-up (compile + pipeline fill) -----------------
    # Includes one wave gang shape-identical to the endurance waves:
    # the wave solver compiles per shape bucket, so the preempt /
    # victim-selection / what-if kernels jit HERE, not inside the
    # calibrated SLO window.
    warm_cycles = [one_cycle() for _ in range(3)]
    store.add_priority_class(PriorityClass(name="endur-hi", value=1000))
    warm_gang = _submit_wave()
    warm_cycles.extend(one_cycle() for _ in range(6))

    # ---- phase 2: calibration + budget declaration ------------------
    # Calibrate UNDER the endurance load shape — lifecycle churn
    # running and a wave gang pending — or the declared budget would
    # describe a steady state the endurance phase never runs in.
    calib = []
    for _ in range(12):
        _lifecycle_churn(d_per_cycle)
        calib.append(one_cycle())
    _teardown_wave(warm_gang)
    try:
        mult = float(os.environ.get("BENCH_ENDURANCE_BUDGET_MULT",
                                    "25"))
    except ValueError:
        mult = 25.0
    calib_ms = sorted(t * 1e3 for t in calib)
    # Median of the loaded calibration window — the tail would let one
    # calibration-time jit spike inflate the budget into vacuity.
    cycle_budget = calib_ms[len(calib_ms) // 2] * mult
    if not os.environ.get("VOLCANO_TPU_SLO_CYCLE_P99_MS"):
        # 10% allowed violations: fault-recovery cycles (reconnect +
        # full frame, flap-forced full derives) are EXPECTED to spike;
        # the budget catches sustained regression, not single faults.
        auditor.slo.declare("cycle", cycle_budget, allowed_frac=0.10)
    # The device lane stays tracked-but-unbudgeted unless the operator
    # pins VOLCANO_TPU_SLO_DEVICE_P99_MS: on CPU hosts its tail is
    # dominated by genuine jit recompiles (one-time on real chips with
    # the persistent compile cache), which would flake the gate.

    # ---- phase 3: audit-overhead A/B (churn only, no faults) --------
    # Interleaved off/on pairs with per-pair order swap, scored by the
    # median PAIRWISE delta: consecutive-block drift, 2-cycle
    # periodicity, and single OS/jit hiccups would each swamp a
    # sub-2% effect measured any cruder way.
    ab_n = max(int(os.environ.get("BENCH_ENDURANCE_AB_CYCLES", "15")),
               5)
    t_off, t_on = [], []
    for k in range(ab_n):
        for on_first in ((k % 2 == 0), not (k % 2 == 0)):
            auditor.set_enabled(on_first)
            _lifecycle_churn(d_per_cycle)
            (t_on if on_first else t_off).append(one_cycle())
    auditor.set_enabled(True)
    deltas = sorted(on - off for on, off in zip(t_on, t_off))
    med_off = sorted(t_off)[len(t_off) // 2]
    overhead_pct = (deltas[len(deltas) // 2] / med_off * 100.0
                    if med_off > 0 else 0.0)
    # The in-process truth: the auditor times its own passes; the
    # endurance phase below reports that directly too.
    overhead_ms0 = auditor.audit_stats()["overhead_ms"]

    # ---- phase 3b: journey-overhead A/B (ISSUE 18) ------------------
    # Same interleaved-pairs design, toggling the pod-journey log
    # instead of the auditor: detaching the store/mirror handles is the
    # journey's kill switch, so the off leg pays exactly one getattr
    # per seam.  Scored identically (median pairwise delta / median
    # off), with one refinement: each leg takes the MIN of two cycles.
    # The journey's steady-state cost is microseconds against cycles
    # whose one-sided spikes (gc, jit warms, tombstone derives) are
    # milliseconds — a single-sample leg couples those spikes straight
    # into the pairwise delta, and min-of-two filters them without
    # biasing a genuine per-cycle cost (which both samples would pay).
    jr = store.journey
    t_joff, t_jon = [], []
    if jr is not None:
        for k in range(ab_n):
            for on_leg in ((k % 2 == 0), not (k % 2 == 0)):
                store.journey = jr if on_leg else None
                store.mirror.journey = jr if on_leg else None
                leg = []
                for _ in range(2):
                    _lifecycle_churn(d_per_cycle)
                    leg.append(one_cycle())
                (t_jon if on_leg else t_joff).append(min(leg))
        store.journey = jr
        store.mirror.journey = jr
        # Close the blind window: pods that moved while the journey was
        # detached re-adopt via a bulk resync (synthetic roots), so the
        # conservation check at the end stays airtight.
        with store._lock:
            m = store.mirror
            resync_pairs = [(m.p_uid[i], int(m.p_status[i]))
                            for i in range(len(m.p_uid))
                            if m.p_alive[i] and m.p_uid[i]]
        jr.pod_resync(resync_pairs)
    jdeltas = sorted(on - off for on, off in zip(t_jon, t_joff))
    med_joff = sorted(t_joff)[len(t_joff) // 2] if t_joff else 0.0
    journey_overhead_pct = (
        jdeltas[len(jdeltas) // 2] / med_joff * 100.0
        if med_joff > 0 else 0.0)

    # ---- phase 4: endurance (faults on) -----------------------------
    from volcano_tpu.metrics import metrics as _metrics

    # The in-process truth (the audit_stats idiom): the journey times
    # its own capture entry points, so the endurance phase also reports
    # capture time as a fraction of total cycle time directly —
    # immune to the A/B's noise floor.
    jcap0 = store.journey.capture_ns if store.journey is not None else 0
    flap_every = max(cycles // 10, 20)
    wave_every = max(cycles // 4, 25)
    kill_at = {cycles // 2, (3 * cycles) // 4}
    with store._lock:  # compact_gen is lock-guarded mirror state
        compact0 = store.mirror.compact_gen
    node_names = [f"node-{i:06d}" for i in range(n_nodes)]
    flaps = kills = 0
    flapped = None  # (name, restore_at_cycle)
    wave_groups = []  # (group_name, teardown_at)
    times = []
    for i in range(cycles):
        if i % flap_every == flap_every - 1 and flapped is None:
            name = node_names[(i // flap_every) % n_nodes]
            _flip_node(name, False)
            flapped = (name, i + 5)
            flaps += 1
        if flapped is not None and i >= flapped[1]:
            _flip_node(flapped[0], True)
            flapped = None
        if i % wave_every == wave_every - 1:
            wave_groups.append((_submit_wave(), i + wave_every // 2))
        for gname, teardown in list(wave_groups):
            if i >= teardown:
                _teardown_wave(gname)
                wave_groups.remove((gname, teardown))
        if i in kill_at and servers:
            # Pool leg (ISSUE 15): kill/restart a RANDOM member — the
            # straggler + tight hedge knobs keep hedges in flight, so
            # some kills land mid-hedge.  The severed replica's reply
            # rides the lost-reply machinery (or the hedge winner
            # commits in its place); its reconnect ships a full frame
            # and deltas re-engage per replica.
            kills += 1
            idx = _kill_rng.randrange(len(servers))
            _restart_pool_member(servers, idx,
                                 client.replicas[idx].client,
                                 "endurance-kill")
        elif i in kill_at and server is not None:
            # Solver-child kill: restart the server AND sever the live
            # connection, so the per-connection wire mirror + devincr
            # caches die with it; the client reconnect must heal to a
            # full frame before deltas re-engage.
            kills += 1
            port = server.port
            # Sever the live connection FIRST (the server's conn
            # thread exits on the dead socket and releases the
            # established tuple), then drop the listener and rebind.
            with client._lock:
                client._close_locked("endurance-kill")
            server.shutdown()
            from volcano_tpu.solver_service import SolverServer

            server = None
            for _attempt in range(20):
                try:
                    server = SolverServer(port=port)
                    break
                except OSError:
                    time.sleep(0.1)
            if server is None:
                # The old tuple is stuck in the kernel: a fresh
                # ephemeral port + fresh client is still a faithful
                # child restart (full reconnect, empty mirror).
                server = SolverServer(port=0)
                client.close()
                from volcano_tpu.solver_service import RemoteSolver

                client = RemoteSolver(f"127.0.0.1:{server.port}")
                store.remote_solver = client
                if shards_n > 1:
                    # Shard 0 resolves its lane from its own context,
                    # not the store slot (docs/sharding.md).
                    sched.shards[0].remote_solver = client
            _threading.Thread(target=server.serve_forever,
                              daemon=True).start()
        _lifecycle_churn(d_per_cycle)
        times.append(one_cycle())

    # ---- verdict + tail ---------------------------------------------
    store.cycle_feed = None
    # Journey conservation (ISSUE 18): every pod the mirror says is
    # bound-ish must have a complete, orphan-free journey.  Violations
    # land as journey-orphan / journey-incomplete anomalies in the
    # auditor ring and fail the gate like any other anomaly.
    jviol = 0
    bound_checked = 0
    if store.journey is not None:
        bound_mask = (int(TaskStatus.Allocated) | int(TaskStatus.Binding)
                      | int(TaskStatus.Bound) | int(TaskStatus.Running)
                      | int(TaskStatus.Succeeded))
        with store._lock:
            m = store.mirror
            bound_uids = [m.p_uid[i] for i in range(len(m.p_uid))
                          if m.p_alive[i] and m.p_uid[i]
                          and int(m.p_status[i]) & bound_mask]
        bound_checked = len(bound_uids)
        for a in store.journey.conservation_check(bound_uids):
            jviol += 1
            auditor.report(a)
    anoms = auditor.total_anomalies()
    with auditor._lock:
        by_reason = dict(auditor.anomaly_counts)
    slo = auditor.slo.snapshot()
    times_ms = sorted(t * 1e3 for t in times)

    def pct(q):
        return round(times_ms[min(int(q * (len(times_ms) - 1) + 0.5),
                                  len(times_ms) - 1)], 2)

    ledger = store.migrations
    with store._lock:  # lock-guarded store/mirror state for the tail
        shard_table = store.shard_table
        compact_gen = store.mirror.compact_gen
    endurance = {
        "cycles": cycles,
        "anomalies": anoms,
        "anomalies_by_reason": by_reason,
        "cycle_p50_ms": pct(0.50),
        "cycle_p99_ms": pct(0.99),
        "cycle_budget_ms": round(cycle_budget, 2),
        "slo": slo,
        "audit_overhead_pct": round(overhead_pct, 2),
        # Direct in-process measure over the endurance phase: the
        # auditor's own timed passes / the phase's wall time — the
        # stable <2%-envelope number (the A/B above corroborates it
        # against anything the timers cannot see).
        "audit_overhead_direct_pct": round(
            (auditor.audit_stats()["overhead_ms"] - overhead_ms0)
            / max(sum(times) * 1e3, 1e-9) * 100.0, 3),
        "node_flaps": flaps,
        "preempt_waves": wave_seq,
        "preempt_evictions": int(sum(
            _metrics.preempt_evictions.data.values())),
        "solver_kills": kills,
        "compactions": compact_gen - compact0,
        "pods_deleted": clone_seq,
        "ledger_restored": (ledger.restored_pods
                            if ledger is not None else 0),
        "wire": ({"frames": dict(client.frame_counts),
                  "fallbacks": dict(client.wire_fallbacks)}
                 if client is not None else None),
        # Pool leg (ISSUE 15): per-replica health + hedge/failover
        # totals, so the gate's tail proves random-member kills healed
        # with the pool still hedging.  (client is None under
        # BENCH_ENDURANCE_WIRE=0 regardless of the pool knob.)
        "pool": (client.health_snapshot()
                 if pool_n > 1 and client is not None else None),
        # Sharded leg (ISSUE 16): conflict/steal totals + per-shard
        # cycle counts, so the gate's tail proves the optimistic
        # protocol actually raced (conflicts > 0 under this schedule)
        # and still conserved every pod.
        "shards": (
            {
                "n": shards_n,
                "conflicts": int(sum(
                    _metrics.shard_conflicts.data.values())),
                "steals": int(sum(
                    _metrics.shard_steals.data.values())),
                "per_shard": [ctx.debug_snapshot()
                              for ctx in sched.shards],
                "table": shard_table.snapshot(),
            } if shards_n > 1 else None),
        # Journey leg (ISSUE 18): capture volume, the conservation
        # verdict over every bound-ish pod, and the measured capture
        # overhead — the interleaved journey-off A/B delta AND the
        # self-timed capture fraction of the endurance phase (the
        # in-process truth; the A/B's resolution floor is the host's
        # cycle jitter).  The <2% gate reads journey_direct_pct.
        "journey": ({
            **store.journey.stats(),
            "bound_pods_checked": bound_checked,
            "conservation_violations": jviol,
            "journey_overhead_pct": round(journey_overhead_pct, 2),
            "journey_direct_pct": (round(
                (store.journey.capture_ns - jcap0) / 1e6
                / sum(times_ms) * 100.0, 3) if times_ms else 0.0),
        } if store.journey is not None else None),
    }
    _collect_audit(store)
    _collect_journey(store)
    _emit(
        f"Endurance @ {n_nodes} nodes x {n_pods} pods "
        f"({cycles} churn cycles, faults on)",
        pct(0.50), n_pods,
        f"anomalies={anoms} flaps={flaps} waves={wave_seq} kills={kills} "
        f"compactions={endurance['compactions']} "
        f"overhead={overhead_pct:.2f}% warmup={sum(warm_cycles):.2f}s",
        lanes=store.last_cycle_lanes,
        records=store.flight.recent(),
        endurance=endurance,
        compile_ms=sum(warm_cycles) * 1e3,
    )
    store.close()
    if client is not None:
        client.close()
    for cl in shard_clients:
        cl.close()
    if server is not None:
        server.shutdown()
        time.sleep(0.2)
    for srv in servers + shard_servers:
        srv.shutdown()
    if servers or shard_servers:
        time.sleep(0.2)
    if anoms:
        print(f"# ENDURANCE FAILED: {anoms} anomalies "
              f"({by_reason})", file=sys.stderr)
        raise SystemExit(1)


def config_pool():
    """BENCH_POOL=1 (ISSUE 15): solver replica pool A/B — pool sizes
    {1,2,3} over in-process ``SolverServer``s with an injected
    straggler + kill fault schedule.

    Every server straggles (``BENCH_POOL_STRAGGLE_MS``, default 250 ms,
    on every ``BENCH_POOL_STRAGGLE_EVERY``-th solve, default 5) so
    health-scored routing alone cannot dodge the tail — the pool=2/3
    rows isolate what HEDGING buys.  Mid-run, a random replica is
    killed and restarted (connection severed + listener rebound), so
    every row also pays one lost-reply re-place; the tail proves the
    kill cost exactly that (zero lost pods, failover counted, the
    killed replica's deltas re-engaged after its full-frame reconnect).

    Per size, one JSON row: steady pipelined cycle p50 plus a "pool"
    tail — hedge dispatches/wins, failovers, per-replica frame counts,
    the killed replica's post-restart frames, device-lane p50/p99 (the
    acceptance number: pool=2 hedging must cut device p99 >= 20% vs
    pool=1 under this schedule), lost pods, and the anomaly verdict.
    """
    import threading as _threading

    import numpy as _np

    from volcano_tpu.api import TaskStatus
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.solver_pool import SolverPool
    from volcano_tpu.solver_service import SolverServer
    from volcano_tpu.synth import synthetic_cluster

    n_nodes = int(os.environ.get("BENCH_NODES", 256))
    n_pods = int(os.environ.get("BENCH_PODS", 2048))
    cycles = max(int(os.environ.get("BENCH_POOL_CYCLES", "40")), 20)
    straggle_s = float(os.environ.get("BENCH_POOL_STRAGGLE_MS",
                                      "250")) / 1e3
    straggle_every = max(
        int(os.environ.get("BENCH_POOL_STRAGGLE_EVERY", "5")), 2)
    sizes = [int(s) for s in os.environ.get(
        "BENCH_POOL_SIZES", "1,2,3").split(",") if s.strip()]
    # The straggler delays are real wall time; hedge past a tight
    # deadline so the A/B exercises the lane (operators tune these in
    # docs/tuning.md "Solver replica pool").
    os.environ.setdefault("VOLCANO_TPU_POOL_HEDGE_P99_MULT", "3.0")
    os.environ.setdefault("VOLCANO_TPU_POOL_HEDGE_MIN_MS", "25")
    st_bound = int(TaskStatus.Bound)

    def _spawn(k):
        servers = []
        for _ in range(k):
            server = SolverServer(port=0)
            server.solve_delay_fn = (
                lambda i: straggle_s if i % straggle_every == 0
                else 0.0)
            _threading.Thread(target=server.serve_forever,
                              daemon=True).start()
            servers.append(server)
        return servers

    for size in sizes:
        servers = _spawn(size)
        pool = SolverPool(
            [f"127.0.0.1:{s.port}" for s in servers], size=size)
        store = synthetic_cluster(n_nodes=n_nodes, n_pods=n_pods,
                                  gang_size=4, seed=3)
        store.pipeline = True
        store.async_bind = os.environ.get("BENCH_SYNC_BIND") != "1"
        store.remote_solver = pool

        def feed(fc):
            m = fc.m
            rows = _np.flatnonzero(
                (m.p_status[:fc.Pn] == st_bound) & m.p_alive[:fc.Pn]
            )
            if len(rows):
                fc._unbind_rows(rows[:max(1, len(rows) // 20)])

        store.cycle_feed = feed
        sched = Scheduler(store, conf_str=CONF_BASE)
        warm = []
        for _ in range(4):
            t0 = time.perf_counter()
            sched.run_once()
            warm.append(time.perf_counter() - t0)
        kill_at = cycles // 2
        killed = 0
        post_kill0 = None
        times = []
        for i in range(cycles):
            if i == kill_at:
                # Kill + restart the CURRENT PRIMARY mid-stream — the
                # member carrying the in-flight allocate stream, the
                # case the acceptance bar pins (the severed reply costs
                # at most one cycle's lost-reply re-place, or a
                # mid-hedge rescue + failover).  A random member can be
                # sitting idle under health-scored routing, making the
                # kill free and the failover assertion vacuous.  The
                # tail snapshots its frame counters so deltas provably
                # re-engage afterwards.
                with pool._lock:
                    killed = pool._primary
                victim = pool.replicas[killed].client
                _restart_pool_member(servers, killed, victim,
                                     "pool-kill")
                post_kill0 = dict(victim.frame_counts)
            t0 = time.perf_counter()
            sched.run_once()
            times.append(time.perf_counter() - t0)
        store.cycle_feed = None
        for _ in range(3):
            sched.run_once()
        store.flush_binds()
        m = store.mirror
        lost = sum(
            1 for r in range(m.n_pods)
            if m.p_uid[r] is not None and m.p_alive[r]
            and int(m.p_status[r]) != st_bound
        )
        recs = store.flight.recent()[-len(times):]
        dev = sorted(
            rec.lanes.get("device", 0.0) * 1e3 for rec in recs)

        def pct(q):
            return round(dev[min(int(q * (len(dev) - 1) + 0.5),
                                 len(dev) - 1)], 2)

        h = pool.health_snapshot()
        kc = pool.replicas[killed].client.frame_counts
        drops = {}
        for rec in recs:
            for reason, n in rec.drop_reasons.items():
                drops[reason] = drops.get(reason, 0) + n
        tail = {
            "size": size,
            "straggle_ms": round(straggle_s * 1e3, 1),
            "straggle_every": straggle_every,
            "hedge_dispatches": h["hedge_dispatches"],
            "hedge_wins": h["hedge_wins"],
            "failovers": h["failovers"],
            "per_replica_frames": pool.per_replica_frames(),
            "killed_replica": killed,
            "post_kill_frames": {
                k: kc[k] - (post_kill0 or {}).get(k, 0)
                for k in kc
            },
            "device_p50_ms": pct(0.50),
            "device_p99_ms": pct(0.99),
            "lost_reply_rows": drops.get("lost-reply", 0),
            "lost_pods": lost,
            "anomalies": store.auditor.total_anomalies(),
        }
        _collect_audit(store)
        _collect_journey(store)
        times_ms = sorted(t * 1e3 for t in times)
        _emit(
            f"Solver pool A/B @ {n_nodes} nodes x {n_pods} pods "
            f"(pool={size}, straggler "
            f"{straggle_s * 1e3:.0f}ms/{straggle_every})",
            times_ms[len(times_ms) // 2], n_pods,
            f"device_p99={tail['device_p99_ms']}ms "
            f"hedges={tail['hedge_dispatches']} "
            f"wins={tail['hedge_wins']} "
            f"failovers={tail['failovers']} lost_pods={lost}",
            lanes=store.last_cycle_lanes,
            records=recs,
            pool=tail,
            compile_ms=sum(warm) * 1e3,
        )
        store.close()
        pool.close()
        for s in servers:
            s.shutdown()
        time.sleep(0.2)


def config_shards():
    """BENCH_SHARDS=1,2,4 (ISSUE 16): sharded control plane A/B — N
    cycle threads over one logical cluster, each shard fronted by its
    own in-process ``SolverServer`` with an injected solve delay
    (``BENCH_SHARDS_SOLVE_MS``, default 30 ms) so the device round trip
    dominates and the pipelined overlap is what the A/B measures: N
    shards keep N solves in flight, so binds/sec scales with N until
    the lock-serialized host cycle saturates.

    Per shard count, three phases over fresh stores:

    - **drain** (conflict-free partition): queues confined to disjoint
      node zones by selector, no churn — every shard count must bind
      the SAME total with zero cross-shard conflicts (hack/run-e2e.sh
      asserts both);
    - **throughput**: steady churn feed over the same partitioned
      store, cycles driven round-robin for ``BENCH_SHARDS_SECS`` —
      binds/sec is the headline (the acceptance bar: >= 1.6x at
      shards=2 vs shards=1).  The overlap being measured is the
      PIPELINED solve (each shard's device round trip cooks while its
      siblings' cycles run), so a single driving thread suffices and
      keeps the number free of lock-barging noise;
    - **contention**: a deliberately tight shared node pool under
      aggressive churn, so same-node races between shards are routine
      — the verdict is conflict-voided rows re-placing at ZERO lost
      pods with the conservation auditor clean.

    One JSON row per shard count: binds/sec, conflict rate, and
    per-shard lane tails (cycles / binds / device p50, split by the
    ``@sN`` session-uid suffix).
    """
    import threading as _threading

    import numpy as _np

    from volcano_tpu.api import TaskStatus
    from volcano_tpu.metrics import metrics as _metrics
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.shard import ShardedScheduler
    from volcano_tpu.solver_service import RemoteSolver, SolverServer
    from volcano_tpu.synth import synthetic_cluster

    sizes = [int(s) for s in os.environ.get(
        "BENCH_SHARDS", "1,2,4").split(",") if s.strip()]
    n_nodes = int(os.environ.get("BENCH_NODES", 64))
    n_pods = int(os.environ.get("BENCH_PODS", 512))
    n_queues = max(int(os.environ.get("BENCH_SHARDS_QUEUES", "8")),
                   max(sizes))
    solve_s = float(os.environ.get("BENCH_SHARDS_SOLVE_MS", "30")) / 1e3
    secs = max(float(os.environ.get("BENCH_SHARDS_SECS", "6")), 1.0)
    # The throughput window produces hundreds of cycles across shards;
    # the ring must retain the whole window for the binds/sec count
    # and the per-shard splits.
    os.environ.setdefault("VOLCANO_TPU_FLIGHT_CYCLES", "8192")
    os.environ.setdefault("VOLCANO_TPU_AUDIT_SAMPLE", "8")
    st_bound = int(TaskStatus.Bound)

    def _conflicts():
        return int(sum(_metrics.shard_conflicts.data.values()))

    def _partitioned_store():
        """Queues confined to disjoint node zones by selector: the
        feasible sets never overlap across queues, so NO shard split
        of this workload can race — the drain/throughput phases
        measure pure scaling, with the commit gate provably quiet."""
        from volcano_tpu.api import (GROUP_NAME_ANNOTATION, Node, Pod,
                                     PodGroup, Queue)
        from volcano_tpu.cache import ClusterStore

        store = ClusterStore()
        for i in range(n_nodes):
            z = i % n_queues
            store.add_node(Node(
                name=f"node-{i:04d}",
                allocatable={"cpu": "64", "memory": "256Gi",
                             "pods": 256},
                labels={"zone": f"z{z}"},
            ))
        for q in range(n_queues):
            store.add_queue(Queue(name=f"shq-{q}", weight=1))
        g = made = 0
        while made < n_pods:
            q = g % n_queues
            size = min(4, n_pods - made) or 1
            pg = PodGroup(name=f"pg-{g:05d}", min_member=size,
                          queue=f"shq-{q}")
            store.add_pod_group(pg)
            for k in range(size):
                store.add_pod(Pod(
                    name=f"pg-{g:05d}-{k}",
                    annotations={GROUP_NAME_ANNOTATION: pg.name},
                    containers=[{"cpu": "2", "memory": "4Gi"}],
                    node_selector={"zone": f"z{q}"},
                ))
                made += 1
            g += 1
        return store

    def _mk(size, store):
        """Scheduler + one solver lane per shard over ``store`` (the
        wire protocol is strict request/reply per connection, so
        concurrent in-flight shards each need their own client)."""
        store.pipeline = True
        store.async_bind = os.environ.get("BENCH_SYNC_BIND") != "1"
        servers, clients = [], []
        for _ in range(max(size, 1)):
            srv = SolverServer(port=0)
            srv.solve_delay_fn = lambda i: solve_s
            _threading.Thread(target=srv.serve_forever,
                              daemon=True).start()
            servers.append(srv)
            clients.append(RemoteSolver(f"127.0.0.1:{srv.port}"))
        if size <= 1:
            store.remote_solver = clients[0]
            sched = Scheduler(store, conf_str=CONF_BASE,
                              schedule_period=0.0)
        else:
            sched = ShardedScheduler(store, conf_str=CONF_BASE,
                                     schedule_period=0.0, shards=size)
            for ctx, cl in zip(sched.shards, clients):
                ctx.remote_solver = cl
        return store, sched, servers, clients

    def _teardown(store, servers, clients):
        store.close()
        for c in clients:
            c.close()
        for s in servers:
            s.shutdown()
        time.sleep(0.2)

    def _bound(store):
        m = store.mirror
        return int(_np.count_nonzero(
            m.p_alive[:m.n_pods]
            & (m.p_status[:m.n_pods] == st_bound)))

    st_pending = int(TaskStatus.Pending)

    def _lost(store):
        m = store.mirror
        return sum(
            1 for r in range(m.n_pods)
            if m.p_uid[r] is not None and m.p_alive[r]
            and int(m.p_status[r]) != st_bound
        )

    def _lost_strict(store):
        """Pods that vanished from BOTH states — the conservation
        failure a voided commit could cause.  On the deliberately
        oversubscribed contention pool, Pending leftovers are the
        expected backlog, not a loss."""
        m = store.mirror
        return sum(
            1 for r in range(m.n_pods)
            if m.p_uid[r] is not None and m.p_alive[r]
            and int(m.p_status[r]) not in (st_bound, st_pending)
        )

    def _pending(store):
        m = store.mirror
        return int(_np.count_nonzero(
            m.p_alive[:m.n_pods]
            & (m.p_status[:m.n_pods] == st_pending)))

    def _last_seq(store):
        recs = store.flight.recent()
        return recs[-1].seq if recs else 0

    baseline_rate = None
    for size in sizes:
        c0 = _conflicts()
        # ---- phase 1: drain (conflict-free partition) ---------------
        store, sched, servers, clients = _mk(size, _partitioned_store())
        rounds = 0
        while rounds < 40 and _bound(store) < n_pods:
            sched.run_once()
            rounds += 1
        store.flush_binds()
        drain = {
            "rounds": rounds,
            "bound": _bound(store),
            "conflicts": _conflicts() - c0,
        }

        # ---- phase 2: throughput (steady churn) ---------------------
        def feed(fc):
            m = fc.m
            rows = _np.flatnonzero(
                (m.p_status[:fc.Pn] == st_bound) & m.p_alive[:fc.Pn]
            )
            if len(rows):
                fc._unbind_rows(rows[:max(1, len(rows) // 8)])

        store.cycle_feed = feed
        for _ in range(6):
            sched.run_once()  # warm the churn shapes before timing
        c1 = _conflicts()
        seq0 = _last_seq(store)
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < secs:
            sched.run_once()
        elapsed = time.perf_counter() - t0
        recs = [r for r in store.flight.recent() if r.seq > seq0]
        binds = sum(r.pods_bound for r in recs)
        rate = binds / max(elapsed, 1e-9)
        if baseline_rate is None:
            baseline_rate = rate
        per_shard = {}
        for r in recs:
            k = (r.session.rsplit("@", 1)[1]
                 if "@" in r.session else "s0")
            d = per_shard.setdefault(
                k, {"cycles": 0, "binds": 0, "_dev": []})
            d["cycles"] += 1
            d["binds"] += r.pods_bound
            d["_dev"].append(r.lanes.get("device", 0.0) * 1e3)
        for d in per_shard.values():
            dev = sorted(d.pop("_dev"))
            d["device_p50_ms"] = (
                round(dev[len(dev) // 2], 2) if dev else 0.0)
        thr_conflicts = _conflicts() - c1
        store.cycle_feed = None
        for _ in range(3):
            sched.run_once()
        store.flush_binds()
        lost_ab = _lost(store)
        anoms_ab = store.auditor.total_anomalies()
        cycle_ms = sorted(r.duration_s * 1e3 for r in recs)
        p50 = cycle_ms[len(cycle_ms) // 2] if cycle_ms else 0.0
        _teardown(store, servers, clients)

        # ---- phase 3: contention (tight shared pool, forced races) --
        c2 = _conflicts()
        steals0 = int(sum(_metrics.shard_steals.data.values()))
        store2, sched2, servers2, clients2 = _mk(
            size, synthetic_cluster(
                n_nodes=max(6, n_nodes // 10),
                n_pods=max(96, n_pods // 4), gang_size=4,
                n_queues=n_queues, node_cpu="16", seed=7))

        def feed2(fc):
            m = fc.m
            rows = _np.flatnonzero(
                (m.p_status[:fc.Pn] == st_bound) & m.p_alive[:fc.Pn]
            )
            if len(rows):
                fc._unbind_rows(rows[:max(1, len(rows) // 3)])

        for _ in range(4):
            sched2.run_once()
        store2.cycle_feed = feed2
        t1 = time.perf_counter()
        while time.perf_counter() - t1 < max(secs / 2, 2.0):
            sched2.run_once()
        store2.cycle_feed = None
        for _ in range(4):
            sched2.run_once()
        store2.flush_binds()
        contention = {
            "conflicts": _conflicts() - c2,
            "steals": int(sum(
                _metrics.shard_steals.data.values())) - steals0,
            "pending_backlog": _pending(store2),
            "lost_pods": _lost_strict(store2),
            "anomalies": store2.auditor.total_anomalies(),
        }
        _collect_audit(store2)
        _collect_journey(store2)

        tail = {
            "shards": size,
            "solve_ms": round(solve_s * 1e3, 1),
            "drain": drain,
            "binds_per_sec": round(rate, 1),
            "speedup_vs_shard1": (
                round(rate / baseline_rate, 3) if baseline_rate else None),
            "throughput_conflicts": thr_conflicts,
            "conflict_rate": round(thr_conflicts / max(binds, 1), 5),
            "per_shard": per_shard,
            "lost_pods": lost_ab,
            "anomalies": anoms_ab,
            "contention": contention,
        }
        _emit(
            f"Sharded control plane @ {n_nodes} nodes x {n_pods} pods "
            f"(shards={size}, solve {solve_s * 1e3:.0f}ms)",
            p50, n_pods,
            f"binds/sec={tail['binds_per_sec']} "
            f"speedup={tail['speedup_vs_shard1']} "
            f"conflicts={thr_conflicts} "
            f"contention_lost={contention['lost_pods']} "
            f"contention_anoms={contention['anomalies']}",
            records=recs,
            shards=tail,
        )
        _teardown(store2, servers2, clients2)


def _round_frac(f):
    return round(min(max(f, 0.0), 1.0), 4)


def _emit_mesh_microbench(mesh):
    """One JSON line quantifying the cross-chip reduce of the sharded
    selection: the two-stage shard-local top-k (winner reduction over
    [U, shards*K] (score, node id) pairs) vs the global top-k, both on
    the SAME node-sharded score plane at the config's node count."""
    import numpy as np

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import volcano_tpu.ops.wave as wave

    n_nodes = int(os.environ.get("BENCH_NODES", 10000))
    np_pad = 1 << max(0, (n_nodes - 1).bit_length())
    n_dev = int(mesh.devices.size)
    if np_pad % n_dev:
        return
    u_rows = 256
    k = wave.shortlist_size(np_pad)
    rng = np.random.default_rng(0)
    scores = rng.normal(size=(u_rows, np_pad)).astype(np.float32)
    sharded = jax.device_put(scores, NamedSharding(mesh, P(None, "nodes")))
    two = jax.jit(lambda x: wave._topk_nodes(x, k, n_dev))
    glb = jax.jit(lambda x: wave._topk_nodes(x, k, 1))

    def best_of(fn, arg, n=5):
        fn(arg).block_until_ready()  # compile + warm
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            fn(arg).block_until_ready()
            ts.append(time.perf_counter() - t0)
        return min(ts) * 1e3

    reduce_ms = best_of(two, sharded)
    global_ms = best_of(glb, sharded)
    print(json.dumps({
        "metric": f"mesh winner-reduce microbench{_MODE_SUFFIX}",
        "value": round(reduce_ms, 3),
        "unit": "ms",
        "mesh": {
            "devices": n_dev,
            "n_nodes_padded": np_pad,
            "profiles": u_rows,
            "shortlist_k": k,
            "shard_local_topk_ms": round(reduce_ms, 3),
            "global_topk_ms": round(global_ms, 3),
        },
    }))


def _run_selected(raw, repeats):
    if raw == "north":
        config_north(repeats)
        return
    config = int(raw)
    if config == 1:
        config_1()
    elif config == 2:
        config_2(
            int(os.environ.get("BENCH_NODES", 1000)),
            int(os.environ.get("BENCH_PODS", 10000)),
            int(os.environ.get("BENCH_GANG", 4)),
            repeats,
        )
    elif config == 3:
        config_3(repeats)
    elif config == 4:
        config_4(repeats)
    elif config == 5:
        config_5(repeats)
    else:
        raise SystemExit(f"unknown BENCH_CONFIG={config}")


def main():
    global _MODE_SUFFIX, _MESH, _FEED_FRACTION, _DEVINCR_PROBE
    global _REMOTE_PORT
    raw = os.environ.get("BENCH_CONFIG", "north")
    # min-of-5 by default: shared-host / TPU-tunnel latency varies 2x+
    # between runs, and the minimum is the stable estimator.
    repeats = int(os.environ.get("BENCH_REPEATS", 5))
    if os.environ.get("BENCH_REBALANCE"):
        # Fragmented-cluster defragmentation lane (ISSUE 5): its own
        # scenario, not a mode of the five configs.
        config_rebalance()
        return
    if os.environ.get("BENCH_TOPOLOGY"):
        # Topology-aware gang placement lane (ISSUE 20): fragmented
        # fabric + slice-defrag convergence, not a mode of the configs.
        config_topology()
        return
    if os.environ.get("BENCH_PREEMPT"):
        # Device-native priority-tier preemption lane (ISSUE 11): its
        # own fragmented-priority scenario, not a mode of the configs.
        config_preempt()
        return
    if os.environ.get("BENCH_COMPOSED"):
        # The authoritative north-star composition (ISSUE 12): mesh +
        # device incrementality + incremental host lanes + pipelining
        # + steady churn, engaged together in one run.
        config_composed()
        return
    if os.environ.get("BENCH_ENDURANCE"):
        # The compressed-hours survival gate (ISSUE 13): churn + fault
        # waves with the runtime auditor on; exits nonzero on any
        # anomaly (hack/run-endurance.sh, docs/observability.md).
        config_endurance()
        return
    if os.environ.get("BENCH_POOL"):
        # Solver replica pool A/B (ISSUE 15): pool sizes {1,2,3} under
        # an injected straggler + kill schedule; the pool tails carry
        # hedge/failover counts and device-lane p50/p99 per size.
        config_pool()
        return
    if os.environ.get("BENCH_SHARDS"):
        # Sharded control plane A/B (ISSUE 16): shard counts {1,2,4}
        # over one logical cluster; the shard tails carry binds/sec,
        # conflict rate, and per-shard lane splits.
        config_shards()
        return
    mesh_raw = os.environ.get("BENCH_MESH")
    if mesh_raw:
        # Mesh A/B (ISSUE 7): force the virtual multi-device CPU host
        # BEFORE anything touches jax, then run the config mesh-on and
        # mesh-off plus the winner-reduce microbench.
        try:
            n_dev = max(2, int(mesh_raw))
        except ValueError:
            n_dev = 4
        from volcano_tpu.virtualcpu import force_virtual_cpu_platform

        force_virtual_cpu_platform(n_dev)
        from volcano_tpu.parallel import make_mesh

        for on in (True, False):
            _MODE_SUFFIX = " (mesh on)" if on else " (mesh off)"
            _MESH = make_mesh(n_dev, platform="cpu") if on else None
            if on:
                _emit_mesh_microbench(_MESH)
            _run_selected(raw, repeats)
        _MODE_SUFFIX = ""
        _MESH = None
        return
    host = os.environ.get("BENCH_HOST")
    if host:
        # Incremental host-lane A/B (ISSUE 8): the selected config runs
        # three times — "(incremental on)", "(incremental off)" (every
        # derive takes the proven full-rebuild path and no host-lane
        # cache is consulted), and "(incremental fallback)" (tracking
        # stays ON but VOLCANO_TPU_DIRTY_CAP=1 overflows every cycle,
        # so the dirty-cap fallback is EXERCISED and measured, not just
        # dodged).  Each pass emits the usual plain + pipelined rows;
        # the pipelined row's host_lanes_ms + lane_p50/p95 tails carry
        # the per-lane p50/p95 across steady-state cycles.
        modes = (
            ("on", {"VOLCANO_TPU_INCREMENTAL": "1"}),
            ("off", {"VOLCANO_TPU_INCREMENTAL": "0"}),
            ("fallback", {"VOLCANO_TPU_INCREMENTAL": "1",
                          "VOLCANO_TPU_DIRTY_CAP": "1"}),
        )
        keys = {k for _, env in modes for k in env}
        old = {k: os.environ.get(k) for k in keys}
        try:
            for mode, env in modes:
                for k in keys:
                    os.environ.pop(k, None)
                os.environ.update(env)
                _MODE_SUFFIX = f" (incremental {mode})"
                _run_selected(raw, repeats)
        finally:
            _MODE_SUFFIX = ""
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        return
    wire_ab = os.environ.get("BENCH_WIRE")
    if wire_ab:
        # Remote-wire transport A/B (ISSUE 10): an in-process solver
        # server thread serves every mode over real loopback TCP (the
        # solve shares this process's jit cache — the A/B isolates
        # wire costs), the pipelined feed re-pends BENCH_WIRE_FRAC of
        # the bound rows (default 5%, production-churn shape), and the
        # selected config runs three times — "(wire delta)"
        # (VOLCANO_TPU_WIRE=1), "(wire full)" (=0, classic v1 frames),
        # "(wire fallback)" (=fallback, every frame exercises the
        # forced full-frame path).  Each pipelined row's "wire" tail
        # carries steady-state frame counts/bytes + bytes_per_cycle:
        # the delta-vs-full ratio is the headline the BASELINE "Remote
        # wire" section records.
        import threading

        from volcano_tpu.solver_service import SolverServer

        try:
            frac = float(os.environ.get("BENCH_WIRE_FRAC", "0.05"))
        except ValueError:
            frac = 0.05
        server = SolverServer(port=0)
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        _REMOTE_PORT = server.port
        _FEED_FRACTION = min(max(frac, 0.0), 1.0)
        modes = (
            ("delta", {"VOLCANO_TPU_WIRE": "1"}),
            ("full", {"VOLCANO_TPU_WIRE": "0"}),
            ("fallback", {"VOLCANO_TPU_WIRE": "fallback"}),
        )
        old_wire = os.environ.get("VOLCANO_TPU_WIRE")
        try:
            for mode, env in modes:
                os.environ.update(env)
                _MODE_SUFFIX = f" (wire {mode})"
                _run_selected(raw, repeats)
        finally:
            _MODE_SUFFIX = ""
            _REMOTE_PORT = None
            _FEED_FRACTION = 1.0
            if old_wire is None:
                os.environ.pop("VOLCANO_TPU_WIRE", None)
            else:
                os.environ["VOLCANO_TPU_WIRE"] = old_wire
            server.shutdown()
            # Let the per-connection daemon threads observe their
            # closed sockets before interpreter teardown starts
            # unloading XLA under them.
            time.sleep(0.2)
        return
    dev = os.environ.get("BENCH_DEVINCR")
    if dev:
        # Device-lane incremental A/B (ISSUE 9): the selected config
        # runs three times — "(devincr on)" (persistent static planes +
        # warm shortlists + null-delta skips), "(devincr off)"
        # (VOLCANO_TPU_DEVINCR=0: every solve re-evaluates statics and
        # re-ranks all N), and "(devincr fallback)" (the lane is ON but
        # VOLCANO_TPU_DIRTY_CAP=1 overflows tracking every cycle, so
        # the proven full-recompute fallback is EXERCISED and measured,
        # not just dodged).  The pipelined feed re-pends only
        # BENCH_DEVINCR_FRAC of the bound rows (default 5%) so the
        # steady-state dirty set looks like production churn, and each
        # pipelined pass ends with a null-delta probe (two feed-less
        # cycles that must skip the dispatch wholesale).
        try:
            frac = float(os.environ.get("BENCH_DEVINCR_FRAC", "0.05"))
        except ValueError:
            frac = 0.05
        modes = (
            ("on", {"VOLCANO_TPU_DEVINCR": "1"}),
            ("off", {"VOLCANO_TPU_DEVINCR": "0"}),
            ("fallback", {"VOLCANO_TPU_DEVINCR": "1",
                          "VOLCANO_TPU_DIRTY_CAP": "1"}),
        )
        keys = {k for _, env in modes for k in env}
        old = {k: os.environ.get(k) for k in keys}
        _FEED_FRACTION = min(max(frac, 0.0), 1.0)
        _DEVINCR_PROBE = True
        try:
            for mode, env in modes:
                for k in keys:
                    os.environ.pop(k, None)
                os.environ.update(env)
                _MODE_SUFFIX = f" (devincr {mode})"
                _run_selected(raw, repeats)
        finally:
            _MODE_SUFFIX = ""
            _FEED_FRACTION = 1.0
            _DEVINCR_PROBE = False
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        return
    ab = os.environ.get("BENCH_TOPK")
    if ab:
        # A/B the two-phase solve in ONE run: the selected config runs
        # twice — shortlist on (BENCH_TOPK > 1 also pins
        # VOLCANO_TPU_TOPK to it; any other value keeps the adaptive
        # default) then shortlist off — emitting both JSON tails with a
        # mode suffix, so one BENCH_r*.json captures the lane-split
        # delta the two-phase solve buys.
        try:
            topk = int(ab)
        except ValueError:
            topk = 0
        for on in (True, False):
            _MODE_SUFFIX = " (shortlist on)" if on else " (shortlist off)"
            with _twophase_env(on, topk):
                _run_selected(raw, repeats)
        _MODE_SUFFIX = ""
        return
    _run_selected(raw, repeats)


if __name__ == "__main__":
    main()
