"""Benchmark suite: the five BASELINE.md configurations.

Select with BENCH_CONFIG=1..5, or the default "north" — the NORTH-STAR
shape itself (10k nodes x 100k pending pods, plain binpack+predicates,
gang 8): the driver-recorded number is the headline metric, lane split
included in the stderr comment.  Each config prints ONE JSON line
{"metric", "value", "unit", "vs_baseline"} on stdout; details go to
stderr.

Configs (BASELINE.json.configs):
  1. 3-replica gang Job end-to-end through the full service (admission ->
     job controller -> PodGroup -> scheduler -> bind -> simulated kubelet),
     the rebuild's `example/job.yaml on kind`.
  2. Synthetic 1k x 10k binpack+predicates, single queue.
  3. DRF multi-queue fairness: 5k nodes, 4 weighted queues, mixed gang sizes.
  4. Preempt + reclaim: 10k nodes fully occupied by low-priority victims,
     20k pending high-priority pods.
  5. Hyperscale bin-pack with inter-pod affinity / topology spread
     (full 50k x 500k when BENCH_FULL=1; 10k x 100k otherwise — the
     north-star shape).

The north-star budget is 100 ms OpenSession->Bind at 10k x 100k on one TPU
chip; vs_baseline = budget/measured with the budget scaled linearly by task
count (>= 1.0 means on budget at the measured scale).

Env knobs: BENCH_NODES/BENCH_PODS/BENCH_GANG/BENCH_REPEATS override config
defaults.
"""

import json
import os
import sys
import time

NORTH_STAR_MS = 100.0
NORTH_STAR_PODS = 100000


def _emit(metric, value_ms, n_pods, extra="", budget_ms=None):
    if budget_ms is None:
        budget_ms = NORTH_STAR_MS * (n_pods / NORTH_STAR_PODS)
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value_ms, 2),
                "unit": "ms",
                "vs_baseline": round(
                    budget_ms / value_ms if value_ms > 0 else 0.0, 4
                ),
            }
        )
    )
    if extra:
        print(f"# {extra}", file=sys.stderr)


def _cycle_bench(make_store, conf, repeats, warm_store=None):
    """Measure one full scheduling cycle (OpenSession -> Bind) steady-state:
    warm-up compiles, then fresh stores of the same shape hit the jit cache."""
    from volcano_tpu.scheduler import Scheduler

    # Bind dispatch is async in production (the reference's goroutine
    # binds are not part of its e2e cycle latency either); binds are
    # flushed after timing before counting.  BENCH_SYNC_BIND=1 keeps the
    # binder calls inside the timed cycle — the control run quantifying
    # the measurement-boundary change.
    async_bind = os.environ.get("BENCH_SYNC_BIND") != "1"
    store = warm_store if warm_store is not None else make_store(0)
    store.async_bind = async_bind
    binder = store.binder
    t0 = time.perf_counter()
    Scheduler(store, conf_str=conf).run_once()
    warm_s = time.perf_counter() - t0
    store.flush_binds()
    bound = len(binder.binds)
    evicted = len(getattr(store.evictor, "evicts", []))

    times = []
    lanes_best = None
    for r in range(repeats):
        store_r = make_store(r + 1)
        store_r.async_bind = async_bind
        sched_r = Scheduler(store_r, conf_str=conf)
        t0 = time.perf_counter()
        sched_r.run_once()
        times.append(time.perf_counter() - t0)
        if times[-1] == min(times):
            lanes_best = getattr(store_r, "last_cycle_lanes", None)
        store_r.flush_binds()
        # The dispatcher thread's callbacks pin the store; stop it so the
        # repeat's full mirror is actually freed.
        store_r.close()
        del store_r, sched_r
    e2e_ms = min(times) * 1e3 if times else warm_s * 1e3
    return e2e_ms, bound, evicted, warm_s, times, lanes_best


def _lane_note(lanes) -> str:
    if not lanes:
        return ""
    parts = [f"{k}={v * 1e3:.0f}ms" for k, v in
             sorted(lanes.items(), key=lambda kv: -kv[1]) if v >= 5e-4]
    return " lanes[" + " ".join(parts) + "]"


CONF_BASE = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""

CONF_PREEMPT = """
actions: "enqueue, allocate, preempt, reclaim, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def config_1():
    """End-to-end 3-replica gang job through the full control plane."""
    from volcano_tpu.controllers.apis import Job, TaskSpec
    from volcano_tpu.service import Service

    # Prewarm the solver jit on the same padded shape bucket so the
    # measured latency is steady-state control-plane time, not XLA compile.
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.synth import synthetic_cluster

    warm = synthetic_cluster(n_nodes=2, n_pods=3, gang_size=3)
    Scheduler(warm).run_once()

    svc = Service(simulate=True, schedule_period=0.01,
                  controller_period=0.005)
    for i in range(2):
        from volcano_tpu.api import Node

        svc.store.add_node(
            Node(name=f"node-{i}",
                 allocatable={"cpu": "8", "memory": "16Gi", "pods": 64})
        )
    job = Job(
        name="test-job",
        min_available=3,
        tasks=[TaskSpec(
            name="worker", replicas=3,
            containers=[{"cpu": "1", "memory": "1Gi"}],
        )],
    )
    svc.start(http_port=0)
    try:
        t0 = time.perf_counter()
        svc.admitted.add_batch_job(job)
        deadline = t0 + 60.0
        while time.perf_counter() < deadline:
            pods = [
                p for p in svc.store.pods.values()
                if p.owner_job == job.key and p.phase == "Running"
            ]
            if len(pods) >= 3:
                break
            time.sleep(0.002)
        else:
            raise RuntimeError("job did not reach Running in 60s")
        e2e_ms = (time.perf_counter() - t0) * 1e3
    finally:
        svc.stop()
    # Budget: the reference on kind needs >= one 1 s schedule period plus
    # controller reconcile latency before pods run; call it 2 s.
    _emit("gang job submit->3 pods Running (full control plane)", e2e_ms, 3,
          "pods_running=3", budget_ms=2000.0)


def config_2(n_nodes, n_pods, gang, repeats):
    from volcano_tpu.synth import synthetic_cluster

    build_t0 = time.perf_counter()
    store = synthetic_cluster(n_nodes=n_nodes, n_pods=n_pods, gang_size=gang)
    build_s = time.perf_counter() - build_t0
    e2e_ms, bound, _, warm_s, times, lanes = _cycle_bench(
        lambda r: synthetic_cluster(n_nodes=n_nodes, n_pods=n_pods,
                                    gang_size=gang, seed=r),
        CONF_BASE, repeats, warm_store=store,
    )
    _emit(
        f"OpenSession->Bind e2e @ {n_nodes} nodes x {n_pods} pending pods "
        f"(gang {gang})",
        e2e_ms, n_pods,
        f"warmup={warm_s:.2f}s bound={bound} "
        f"pods/s={bound / (e2e_ms / 1e3):.0f} build={build_s:.2f}s "
        f"cycles_ms={[round(t * 1e3, 1) for t in times]}"
        + _lane_note(lanes),
    )


def config_3(repeats):
    from volcano_tpu.synth import synthetic_cluster

    n_nodes = int(os.environ.get("BENCH_NODES", 5000))
    n_pods = int(os.environ.get("BENCH_PODS", 50000))
    mk = lambda r: synthetic_cluster(
        n_nodes=n_nodes, n_pods=n_pods, n_queues=4,
        queue_weights=(1, 2, 4, 8), gang_sizes=(2, 4, 8, 16), seed=r,
    )
    e2e_ms, bound, _, warm_s, times, lanes = _cycle_bench(mk, CONF_BASE, repeats)
    _emit(
        f"DRF multi-queue e2e @ {n_nodes} nodes x {n_pods} pods, 4 queues",
        e2e_ms, n_pods,
        f"warmup={warm_s:.2f}s bound={bound} "
        f"cycles_ms={[round(t * 1e3, 1) for t in times]}"
        + _lane_note(lanes),
    )


def config_4(repeats):
    from volcano_tpu.synth import preempt_cluster

    n_nodes = int(os.environ.get("BENCH_NODES", 10000))
    n_pending = int(os.environ.get("BENCH_PODS", 20000))
    mk = lambda r: preempt_cluster(n_nodes=n_nodes, n_pending=n_pending,
                                   seed=r)
    e2e_ms, bound, evicted, warm_s, times, lanes = _cycle_bench(
        mk, CONF_PREEMPT, repeats)
    _emit(
        f"preempt+reclaim e2e @ {n_nodes} nodes oversubscribed, "
        f"{n_pending} pending high-pri pods",
        e2e_ms, n_pending,
        f"warmup={warm_s:.2f}s bound={bound} evicted={evicted} "
        f"cycles_ms={[round(t * 1e3, 1) for t in times]}"
        + _lane_note(lanes),
    )


def config_5(repeats):
    from volcano_tpu.synth import synthetic_cluster

    full = os.environ.get("BENCH_FULL") == "1"
    n_nodes = int(os.environ.get("BENCH_NODES", 50000 if full else 10000))
    n_pods = int(os.environ.get("BENCH_PODS", 500000 if full else 100000))
    mk = lambda r: synthetic_cluster(
        n_nodes=n_nodes, n_pods=n_pods, gang_size=8, zones=16,
        affinity_fraction=0.05, anti_affinity_fraction=0.05,
        spread_fraction=0.1, seed=r,
    )
    e2e_ms, bound, _, warm_s, times, lanes = _cycle_bench(mk, CONF_BASE, repeats)
    _emit(
        f"hyperscale binpack+affinity e2e @ {n_nodes} nodes x "
        f"{n_pods} pods",
        e2e_ms, n_pods,
        f"warmup={warm_s:.2f}s bound={bound} "
        f"cycles_ms={[round(t * 1e3, 1) for t in times]}"
        + _lane_note(lanes),
    )


def config_north(repeats):
    """The north-star shape, plain: 10k nodes x 100k pods, gang 8."""
    from volcano_tpu.synth import synthetic_cluster

    n_nodes = int(os.environ.get("BENCH_NODES", 10000))
    n_pods = int(os.environ.get("BENCH_PODS", 100000))
    mk = lambda r: synthetic_cluster(
        n_nodes=n_nodes, n_pods=n_pods, gang_size=8, zones=16, seed=r,
    )
    e2e_ms, bound, _, warm_s, times, lanes = _cycle_bench(
        mk, CONF_BASE, repeats)
    _emit(
        f"OpenSession->Bind e2e @ {n_nodes} nodes x {n_pods} pending "
        f"pods (north star, plain)",
        e2e_ms, n_pods,
        f"warmup={warm_s:.2f}s bound={bound} "
        f"pods/s={bound / (e2e_ms / 1e3):.0f} "
        f"cycles_ms={[round(t * 1e3, 1) for t in times]}"
        + _lane_note(lanes),
    )


def main():
    raw = os.environ.get("BENCH_CONFIG", "north")
    # min-of-5 by default: shared-host / TPU-tunnel latency varies 2x+
    # between runs, and the minimum is the stable estimator.
    repeats = int(os.environ.get("BENCH_REPEATS", 5))
    if raw == "north":
        config_north(repeats)
        return
    config = int(raw)
    if config == 1:
        config_1()
    elif config == 2:
        config_2(
            int(os.environ.get("BENCH_NODES", 1000)),
            int(os.environ.get("BENCH_PODS", 10000)),
            int(os.environ.get("BENCH_GANG", 4)),
            repeats,
        )
    elif config == 3:
        config_3(repeats)
    elif config == 4:
        config_4(repeats)
    elif config == 5:
        config_5(repeats)
    else:
        raise SystemExit(f"unknown BENCH_CONFIG={config}")


if __name__ == "__main__":
    main()
