"""Benchmark: full scheduling cycle (OpenSession -> Bind) on synthetic
clusters.

Default configuration is BASELINE.md config 2 (1k nodes x 10k pending pods,
binpack + predicates, single queue), overridable via BENCH_NODES/BENCH_PODS/
BENCH_GANG.  The north-star budget is 100 ms OpenSession->Bind at 10k x 100k
on one TPU chip (BASELINE.json); vs_baseline reports budget/measured scaled
by problem size relative to the north-star config (so >= 1.0 means on track
at the measured scale).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import time


def main():
    n_nodes = int(os.environ.get("BENCH_NODES", 1000))
    n_pods = int(os.environ.get("BENCH_PODS", 10000))
    gang = int(os.environ.get("BENCH_GANG", 4))
    repeats = int(os.environ.get("BENCH_REPEATS", 3))

    from volcano_tpu.cache import FakeBinder
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.synth import synthetic_cluster

    conf = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""

    build_t0 = time.perf_counter()
    store = synthetic_cluster(n_nodes=n_nodes, n_pods=n_pods, gang_size=gang)
    build_s = time.perf_counter() - build_t0
    binder = store.binder  # FakeBinder by default

    sched = Scheduler(store, conf_str=conf)

    # Warm-up cycle: compiles the solver and binds the pods.
    t0 = time.perf_counter()
    sched.run_once()
    warm_s = time.perf_counter() - t0
    bound_first = len(binder.binds)

    # Steady-state cycles on fresh stores (rebinding the same snapshot shape
    # hits the jit cache).
    times = []
    for r in range(repeats):
        store_r = synthetic_cluster(
            n_nodes=n_nodes, n_pods=n_pods, gang_size=gang, seed=r + 1
        )
        sched_r = Scheduler(store_r, conf_str=conf)
        t0 = time.perf_counter()
        sched_r.run_once()
        times.append(time.perf_counter() - t0)
        del store_r, sched_r

    e2e_ms = min(times) * 1e3
    pods_per_sec = bound_first / (e2e_ms / 1e3) if e2e_ms > 0 else 0.0

    # Budget scaling: north star is 100 ms at 10k x 100k; scale the budget
    # linearly with task count (the dominant dimension of the sequential
    # scan) for smaller configs.
    budget_ms = 100.0 * (n_pods / 100000.0)
    vs_baseline = budget_ms / e2e_ms if e2e_ms > 0 else 0.0

    print(
        json.dumps(
            {
                "metric": (
                    f"OpenSession->Bind e2e @ {n_nodes} nodes x "
                    f"{n_pods} pending pods (gang {gang})"
                ),
                "value": round(e2e_ms, 2),
                "unit": "ms",
                "vs_baseline": round(vs_baseline, 4),
            }
        )
    )
    print(
        f"# details: warmup={warm_s:.2f}s bound={bound_first} "
        f"pods/s={pods_per_sec:.0f} build={build_s:.2f}s "
        f"cycles_ms={[round(t * 1e3, 1) for t in times]}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
