# Repo-native developer tooling (not shipped in the volcano-tpu wheel).
