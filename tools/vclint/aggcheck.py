"""Persistent cycle-aggregate cache contract (VCL50x).

ISSUE 8 made the host lanes incremental: aggregate planes, orderings,
and encodings persist across cycles and are refreshed by deltas or
reused on content matches.  Every such cache is only correct while its
inputs hold still — and the mirror's ``mutation_seq`` / ``epoch`` /
``compact_gen`` (plus the dirty set they drive) are the ONLY versioning
machinery writers are required to maintain.  This analyzer turns the
"key your cache on the mirror versions" convention (previously just the
``_epoch_cached`` idiom) into a checked contract:

- **VCL501**: an ``_epoch_cached(...)`` call whose key expression never
  references ``epoch`` — the cache would survive node-table churn.
- **VCL502**: a registered persistent cache whose accessor functions
  never reference one of its DECLARED invalidation tokens (see
  ``CACHE_REGISTRY``), or a registry entry no code accesses anymore.
- **VCL503**: a persistent-cache-shaped attribute (``_*_cache`` /
  ``_cycle_aggr``) on a store/mirror receiver that is not registered —
  new caches must declare their invalidation story here.

The token check is a UNION over every function that reads or writes the
slot (across the scanned files), plus ONE level of locally-defined
helpers those functions call (key builders like ``_encode_cache_key``
and contract-carrying classes like ``CycleAggregates`` count toward
their callers): the contract is "somewhere in the cache's read/write
surface, each declared version token participates", which catches the
real failure mode — a cache added or refactored without any keying at
all — without trying to prove key-tuple shapes.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Sequence, Set, Tuple

from . import astcache
from .findings import Finding

# Slot -> invalidation tokens that must appear in the union of its
# accessor functions.  Tokens are identifier/attribute names: mirror
# version counters (mutation_seq / epoch / compact_gen and the derived
# content versions term_members_total / pod_obj_gen / j_cond_sig), the
# dirty-set consumer, or the content-diff helper for caches that
# re-validate by comparing their full key columns every cycle.
CACHE_REGISTRY: Dict[str, Set[str]] = {
    # Job-order rank: content-diffed key columns (rank_from_cols
    # compares every column, so no explicit version is needed).
    "_job_rank_cache": {"rank_from_cols"},
    # Pending-task order: row ids pin compact_gen; set/order content is
    # compared by array equality.
    "_pending_order_cache": {"compact_gen"},
    # Encode-lane profile/affinity structures: row ids (compact_gen),
    # node planes (epoch), and the append-only membership tables.
    "_encode_cache": {"compact_gen", "epoch", "term_members_total"},
    # Commit-path object arrays: rows (compact_gen), record slots
    # (pod_obj_gen); the name list is append-only (tail extension).
    "_objarr_cache": {"compact_gen", "pod_obj_gen"},
    # Feed-lane unbind gather: row ids only (specs immutable per row).
    "_unbind_gather_cache": {"compact_gen"},
    # Close-lane gang gauges: revalidated against the persisted
    # condition signatures.
    "_close_gang_cache": {"j_cond_sig"},
    # Mesh plane cache: epoch-keyed placements, voided on compaction.
    "_mesh_plane_cache": {"compact_gen", "epoch"},
    # The persistent aggregate planes themselves: keyed on
    # (node_liveness_gen, compact_gen) — liveness is the only node
    # property the resident predicate reads — and refreshed from the
    # consumed dirty set.
    "_cycle_aggr": {"node_liveness_gen", "compact_gen",
                    "consume_pod_dirty"},
    # Device-lane incremental context (ISSUE 9, ops/devincr.py): the
    # persistent [U, C] static planes + warm-shortlist candidates +
    # null-delta skip proof.  Keys assembled in
    # FastCycle._devincr_prepare / _null_delta_token: node churn
    # (epoch / node_liveness_gen), row renumbering (compact_gen), plus
    # content tokens (class-table sig, profile generation, cnt0 hash).
    "_devincr_cache": {"epoch", "node_liveness_gen", "compact_gen"},
}

# Files whose cache accesses are analyzed (the incremental host-lane
# surface).
SCAN_FILES: Sequence[str] = (
    "volcano_tpu/fastpath.py",
    "volcano_tpu/fastpath_incr.py",
    "volcano_tpu/cache/store.py",
    # Solver-pool surface (ISSUE 15): the pool deliberately holds NO
    # cache-shaped slots — per-replica wire caches live inside each
    # RemoteSolver and the hedge's frozen frame dies with its handle —
    # but scanning the file keeps that true (a future pool-held
    # ``_*_cache`` must register its invalidation story here).
    "volcano_tpu/solver_pool.py",
)

# Cache-shaped attributes that are deliberately NOT persistent (cycle-
# or object-lifetime memos): exempt from VCL503.
CYCLE_LOCAL = {
    "_obj_arr_cache",   # per-FastCycle memo of the store-level arrays
    "_tier_opts_cache",  # per-cycle config memo (config is immutable)
}

_CACHE_SHAPE = re.compile(r"^_[a-z0-9_]*_cache$")
_RECEIVERS = {"store", "m", "mirror", "self"}


def _receiver_name(node: ast.AST):
    """Leaf receiver name of an attribute chain (``self.store.x`` ->
    ``store``; ``m.x`` -> ``m``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _idents(tree: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for sub in ast.walk(tree):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.add(sub.attr)
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.add(sub.value)
    return out


def _functions(tree: ast.Module):
    """Yield (qualname, node) for every function/method, including
    nested defs (attributed to their outermost function)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    yield f"{node.name}.{sub.name}", sub


def _local_defs(tree: ast.Module) -> Dict[str, Set[str]]:
    """Bare name -> identifier set, for top-level functions, methods
    (by method name), and classes (the whole class body) — the one-hop
    helper expansion for the token union."""
    out: Dict[str, Set[str]] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, set()).update(_idents(node))
        elif isinstance(node, ast.ClassDef):
            out.setdefault(node.name, set()).update(_idents(node))
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    out.setdefault(sub.name, set()).update(_idents(sub))
    return out


def _accessor_tokens(fn: ast.AST, local_defs: Dict[str, Set[str]]
                     ) -> Set[str]:
    """Identifiers of ``fn`` plus those of locally-defined helpers it
    calls (one hop)."""
    toks = _idents(fn)
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call):
            leaf = getattr(sub.func, "id", None) or getattr(
                sub.func, "attr", None)
            if leaf and leaf in local_defs:
                toks |= local_defs[leaf]
    return toks


def _slot_accesses(fn: ast.AST) -> Iterable[Tuple[str, int]]:
    """(slot, line) for cache-shaped attribute accesses + getattr calls
    on store/mirror-shaped receivers inside ``fn``."""
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Attribute):
            name = sub.attr
            if (_CACHE_SHAPE.match(name) or name == "_cycle_aggr"):
                recv = _receiver_name(sub.value)
                if recv in _RECEIVERS:
                    yield name, sub.lineno
        elif isinstance(sub, ast.Call):
            leaf = getattr(sub.func, "id", None)
            if leaf == "getattr" and len(sub.args) >= 2:
                recv = _receiver_name(sub.args[0])
                arg = sub.args[1]
                if (recv in _RECEIVERS and isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    name = arg.value
                    if _CACHE_SHAPE.match(name) or name == "_cycle_aggr":
                        yield name, sub.lineno


def analyze_files(sources: Sequence[Tuple[str, str]]) -> List[Finding]:
    """``sources``: [(rel_path, text)].  Returns raw findings (caller
    applies suppressions via ``findings.finish``)."""
    findings: List[Finding] = []
    # slot -> list of (path, line); slot -> union of accessor idents.
    accesses: Dict[str, List[Tuple[str, int]]] = {}
    tokens_seen: Dict[str, Set[str]] = {}
    epoch_cached_slots: Set[str] = set()

    for rel, src in sources:
        try:
            tree = astcache.parse(src)
        except SyntaxError as err:
            findings.append(Finding(
                "VCL001", rel, err.lineno or 1,
                f"aggcheck could not parse: {err.msg}",
            ))
            continue
        local_defs = _local_defs(tree)
        for qual, fn in _functions(tree):
            fn_idents = None
            for slot, line in _slot_accesses(fn):
                accesses.setdefault(slot, []).append((rel, line))
                if fn_idents is None:
                    fn_idents = _accessor_tokens(fn, local_defs)
                tokens_seen.setdefault(slot, set()).update(fn_idents)
        # VCL501: _epoch_cached key expressions must reference epoch.
        for sub in ast.walk(tree):
            if not isinstance(sub, ast.Call):
                continue
            leaf = getattr(sub.func, "id", None) or getattr(
                sub.func, "attr", None)
            if leaf != "_epoch_cached" or len(sub.args) < 3:
                continue
            attr_arg = sub.args[1]
            if isinstance(attr_arg, ast.Constant) and isinstance(
                    attr_arg.value, str):
                epoch_cached_slots.add(attr_arg.value)
            key_idents = _idents(sub.args[2])
            if "epoch" not in key_idents:
                findings.append(Finding(
                    "VCL501", rel, sub.lineno,
                    "_epoch_cached key does not reference the mirror "
                    "epoch — the cache would survive node-table churn",
                ))

    # VCL502: declared tokens must appear in the accessor union; stale
    # registry entries are findings too (first scanned file, line 1).
    for slot, required in CACHE_REGISTRY.items():
        sites = accesses.get(slot)
        if not sites:
            findings.append(Finding(
                "VCL502", SCAN_FILES[0] if sources else "?", 1,
                f"registered persistent cache {slot} is never accessed "
                "(stale CACHE_REGISTRY entry)",
            ))
            continue
        missing = required - tokens_seen.get(slot, set())
        if missing:
            rel, line = sites[0]
            findings.append(Finding(
                "VCL502", rel, line,
                f"persistent cache {slot} accessors never reference "
                f"declared invalidation token(s) "
                f"{sorted(missing)} — the cache can go stale across "
                "mirror versions",
            ))

    # VCL503: cache-shaped slots on persistent receivers must register.
    for slot, sites in accesses.items():
        if slot in CACHE_REGISTRY or slot in CYCLE_LOCAL \
                or slot in epoch_cached_slots:
            continue
        rel, line = sites[0]
        findings.append(Finding(
            "VCL503", rel, line,
            f"persistent cache attribute {slot} is not registered in "
            "aggcheck.CACHE_REGISTRY (declare its mutation_seq/epoch/"
            "compact_gen invalidation story)",
        ))
    return findings
