"""Finding model + suppression handling shared by every vclint analyzer.

Finding codes (see docs/static_analysis.md for the full catalog):

- VCL0xx  annotation / suppression hygiene
- VCL1xx  lock discipline (``# guarded-by`` / ``# holds`` contracts)
- VCL2xx  device hot-path hygiene (host syncs, donation, retrace)
- VCL3xx  schema <-> C++ ABI drift (wire codec, ctypes bindings)
- VCL4xx  metrics <-> docs drift (registry vs docs/metrics.md)
- VCL5xx  persistent cycle-aggregate cache contract (keyed on the
          mirror's mutation_seq/epoch/compact_gen machinery)
- VCL6xx  anomaly-catalog drift (runtime-auditor reasons vs
          docs/observability.md)
- VCL70x  writer-triad discipline (dynamic-column mutators must mark
          dirty, declare an audit flow, and bump mutation_seq)
- VCL71x  tuning-knob drift (VOLCANO_TPU_* env reads vs docs/tuning.md)

Suppression convention: a finding is silenced by a trailing comment on
the SAME line it is reported at, or by a comment-only line DIRECTLY
above it::

    x = self._events          # vclint: disable=VCL101 -- cycle-thread read

    # vclint: disable=VCL101 -- cycle-thread read; drain reconciles
    x = self._events

The ``-- reason`` part is mandatory; a reasonless suppression is itself
reported (VCL002) and cannot be suppressed.  ``disable=all`` silences
every code on the line.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# Codes that may never be suppressed (suppression hygiene itself —
# VCL705 lives here so a reasonless writer-exemption cannot be silenced
# by a second annotation).
UNSUPPRESSABLE = {"VCL001", "VCL002", "VCL705"}

CODE_TITLES = {
    "VCL001": "malformed vclint annotation",
    "VCL002": "suppression without a reason",
    "VCL101": "unguarded read of a guarded attribute",
    "VCL102": "unguarded write of a guarded attribute",
    "VCL103": "lock-order inversion",
    "VCL104": "guarded-by names an unknown lock",
    "VCL105": "call to a lock-requiring method without the lock",
    "VCL201": "implicit host sync in a device hot path",
    "VCL202": "use of a buffer after donation",
    "VCL203": "jit retrace hazard",
    "VCL301": "wire dtype table drift (python vs C++)",
    "VCL302": "frame-codec constant drift (python vs C++)",
    "VCL303": "ctypes binding drift vs C prototype",
    "VCL304": "schema column declaration drift",
    "VCL401": "metric series missing from docs/metrics.md",
    "VCL402": "documented metric series missing from the registry",
    "VCL403": "metric kind drift (docs vs registry)",
    "VCL501": "_epoch_cached key missing the mirror epoch",
    "VCL502": "persistent cache missing its declared invalidation",
    "VCL503": "unregistered persistent cycle-aggregate cache",
    "VCL601": "anomaly reason missing from docs/observability.md",
    "VCL602": "catalogued anomaly reason never emitted",
    "VCL603": "anomaly reason is not a string literal",
    "VCL701": "dynamic-column writer never marks the dirty set",
    "VCL702": "dynamic-column writer declares no audit flow",
    "VCL703": "dynamic-column writer never bumps mutation_seq",
    "VCL704": "unregistered writer-shaped function",
    "VCL705": "writer exemption without a reason",
    "VCL710": "env knob read but undocumented in docs/tuning.md",
    "VCL711": "documented knob never read by the runtime",
}


@dataclass
class Finding:
    code: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""

    def render(self) -> str:
        tag = " (suppressed: %s)" % self.reason if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.code} {self.message}{tag}"


_SUPPRESS_RE = re.compile(
    r"#\s*vclint:\s*disable=([A-Za-z0-9,\s]+?)"
    r"(?:--\s*(.*?))?\s*$"
)


@dataclass
class Suppressions:
    """Per-file map of line -> (codes, reason, comment_only)."""

    by_line: Dict[int, Tuple[Set[str], str, bool]] = field(
        default_factory=dict)
    comment_lines: Set[int] = field(default_factory=set)
    errors: List[Tuple[int, str]] = field(default_factory=list)

    @classmethod
    def scan(cls, source: str) -> "Suppressions":
        out = cls()
        for lineno, text in enumerate(source.splitlines(), start=1):
            if text.lstrip().startswith("#"):
                out.comment_lines.add(lineno)
            if "vclint:" not in text or "disable=" not in text:
                continue
            m = _SUPPRESS_RE.search(text)
            if m is None:
                out.errors.append(
                    (lineno, "unparseable vclint suppression comment")
                )
                continue
            codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
            reason = (m.group(2) or "").strip()
            if not codes:
                out.errors.append((lineno, "suppression lists no codes"))
                continue
            if not reason:
                out.errors.append(
                    (lineno,
                     "suppression carries no '-- <reason>' justification")
                )
                continue
            comment_only = text.lstrip().startswith("#")
            out.by_line[lineno] = (codes, reason, comment_only)
        return out

    def apply(self, finding: Finding) -> Finding:
        """Mark the finding suppressed when a matching comment covers its
        line — same line, or a comment-only line directly above (never
        for UNSUPPRESSABLE codes)."""
        if finding.code in UNSUPPRESSABLE:
            return finding
        hit = self.by_line.get(finding.line)
        if hit is None:
            # Walk up through the contiguous comment block directly
            # above the finding line; a comment-only disable anywhere in
            # it covers the statement below.
            lineno = finding.line - 1
            while lineno in self.comment_lines:
                cand = self.by_line.get(lineno)
                if cand is not None and cand[2]:
                    hit = cand
                    break
                lineno -= 1
        if hit is None:
            return finding
        codes, reason, _comment_only = hit
        if "all" in codes or finding.code in codes:
            finding.suppressed = True
            finding.reason = reason
        return finding

    def hygiene_findings(self, path: str) -> List[Finding]:
        return [
            Finding("VCL002", path, lineno, msg)
            for lineno, msg in self.errors
        ]


def finish(path: str, source: str,
           raw: List[Finding]) -> List[Finding]:
    """Apply the file's suppressions to raw findings and append the
    suppression-hygiene findings."""
    sup = Suppressions.scan(source)
    out = [sup.apply(f) for f in raw]
    out.extend(sup.hygiene_findings(path))
    out.sort(key=lambda f: (f.path, f.line, f.code))
    return out
