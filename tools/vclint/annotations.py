"""The lock-annotation source of truth, shared by static and runtime.

This module owns the ``# guarded-by:`` / ``# holds:`` /
``# vclint: class-holds:`` parsing layer that ``lockcheck`` (VCL1xx,
static) and ``volcano_tpu/obs/lockdep.py`` (runtime enforcement,
``VOLCANO_TPU_LOCKDEP=1``) both consume — one parser, one regex set,
one file list, so the two checkers can never disagree about what an
annotation means.

Deliberately self-contained: stdlib only, no imports from the rest of
``tools.vclint`` (no ``findings``), so the runtime side can load it by
file path even when ``tools`` is not an importable package (an
installed ``volcano_tpu`` without the repo checkout still degrades
gracefully — lockdep disables itself, it never guesses).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# Files under the lock-discipline analysis (the concurrency surface of
# the pipelined scheduler: shared store state, the mirror, the in-flight
# solve handle, the remote-solver client, the flight-recorder ring the
# HTTP debug handlers read cross-thread).  Runtime lockdep enforces the
# same set: ``enable_lockdep`` wraps the guarded attributes of exactly
# these files' classes.
LOCK_FILES = [
    "volcano_tpu/cache/store.py",
    "volcano_tpu/cache/mirror.py",
    "volcano_tpu/cache/bindqueue.py",
    "volcano_tpu/pipeline.py",
    "volcano_tpu/scheduler.py",
    "volcano_tpu/shard.py",
    "volcano_tpu/solver_service.py",
    "volcano_tpu/solver_pool.py",
    "volcano_tpu/fastpath.py",
    "volcano_tpu/fastpath_evict.py",
    "volcano_tpu/whatif.py",
    "volcano_tpu/ops/devsnap.py",
    "volcano_tpu/obs/recorder.py",
    "volcano_tpu/obs/audit.py",
    "volcano_tpu/obs/slo.py",
]

# The framework's cross-object locks (ISSUE 2): guarded-by may name one
# of these even when the annotated class does not create it (the mirror's
# state is guarded by its owning store's _lock).
KNOWN_LOCKS = {"_lock", "_events_lock", "_bind_fail_lock",
               "_record_walk_lock"}

_GUARDED_RE = re.compile(
    r"#\s*guarded-by:\s*([A-Za-z_]\w*)\s*(\(any-receiver\))?"
)
_HOLDS_RE = re.compile(r"#\s*holds:\s*([A-Za-z_]\w*(?:\s*,\s*[A-Za-z_]\w*)*)")
_CLASS_HOLDS_RE = re.compile(r"#\s*vclint:\s*class-holds:\s*([A-Za-z_]\w*)")

EXEMPT_METHODS = {"__init__", "__new__", "__del__", "__repr__"}


@dataclass
class GuardedAttr:
    lock: str
    any_receiver: bool
    line: int


@dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    guarded: Dict[str, GuardedAttr] = field(default_factory=dict)
    class_holds: Set[str] = field(default_factory=set)
    created_locks: Set[str] = field(default_factory=set)
    # method name -> declared holds set
    holds: Dict[str, Set[str]] = field(default_factory=dict)


@dataclass
class FileModel:
    path: str
    tree: ast.Module
    lines: List[str]
    classes: List[ClassInfo] = field(default_factory=list)
    # module-level function name -> holds set
    fn_holds: Dict[str, Set[str]] = field(default_factory=dict)
    annotation_errors: List[Tuple[int, str]] = field(default_factory=list)


def _attr_chain(node: ast.AST) -> Optional[str]:
    """Dotted name of a Name/Attribute chain, or None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _holds_for_def(lines: List[str], node) -> Set[str]:
    """Parse ``# holds:`` from the def line, its decorators, or the line
    directly above."""
    out: Set[str] = set()
    candidates = [node.lineno]
    for dec in getattr(node, "decorator_list", []):
        candidates.append(dec.lineno)
    first = min(candidates)
    candidates.append(first - 1)
    for lineno in candidates:
        if 1 <= lineno <= len(lines):
            m = _HOLDS_RE.search(lines[lineno - 1])
            if m:
                out.update(
                    s.strip() for s in m.group(1).split(",") if s.strip()
                )
    return out


def _is_lock_factory(value: ast.AST) -> bool:
    """True for ``threading.Lock()`` / ``RLock()`` / ``Condition()``."""
    if not isinstance(value, ast.Call):
        return False
    name = _attr_chain(value.func) or ""
    return name.split(".")[-1] in ("Lock", "RLock", "Condition")


def build_model(path: str, source: str,
                tree: Optional[ast.Module] = None) -> FileModel:
    if tree is None:
        tree = ast.parse(source)
    lines = source.splitlines()
    model = FileModel(path=path, tree=tree, lines=lines)

    # guarded-by comment lines (line -> (lock, any_receiver)); each must
    # attach to an attribute assignment on that line.
    ann_lines: Dict[int, Tuple[str, bool]] = {}
    for lineno, text in enumerate(lines, start=1):
        m = _GUARDED_RE.search(text)
        if m:
            ann_lines[lineno] = (m.group(1), bool(m.group(2)))

    consumed: Set[int] = set()

    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            h = _holds_for_def(lines, node)
            if h:
                model.fn_holds[node.name] = h
        if not isinstance(node, ast.ClassDef):
            continue
        info = ClassInfo(name=node.name, node=node)
        # class-holds markers inside the class source range.
        end = getattr(node, "end_lineno", node.lineno)
        for lineno in range(node.lineno, end + 1):
            m = _CLASS_HOLDS_RE.search(lines[lineno - 1])
            if m:
                info.class_holds.add(m.group(1))
        # Attribute annotations + created locks: scan every statement of
        # the class body and its methods.
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                targets = (
                    sub.targets if isinstance(sub, ast.Assign)
                    else [sub.target]
                )
                value = sub.value
                for tgt in targets:
                    attr = None
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        attr = tgt.attr
                    elif isinstance(tgt, ast.Name):
                        attr = tgt.id
                    if attr is None:
                        continue
                    if value is not None and _is_lock_factory(value):
                        info.created_locks.add(attr)
                    # Annotation on the assignment line, or on a
                    # comment-only line directly above it.
                    ann_line = sub.lineno
                    ann = ann_lines.get(ann_line)
                    if ann is None and sub.lineno >= 2 \
                            and lines[sub.lineno - 2].lstrip() \
                            .startswith("#"):
                        ann_line = sub.lineno - 1
                        ann = ann_lines.get(ann_line)
                    if ann is not None:
                        lock, any_recv = ann
                        info.guarded[attr] = GuardedAttr(
                            lock, any_recv, sub.lineno
                        )
                        consumed.add(ann_line)
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                h = _holds_for_def(lines, sub)
                if h:
                    info.holds[sub.name] = h
        model.classes.append(info)

    for lineno, (lock, _any) in ann_lines.items():
        if lineno not in consumed:
            model.annotation_errors.append(
                (lineno,
                 f"guarded-by: {lock} does not attach to an attribute "
                 "assignment on this line")
            )
    return model
