"""Anomaly-catalog drift analyzer (VCL6xx): obs/audit.py ↔ docs.

The runtime auditor (``volcano_tpu/obs/audit.py``, ISSUE 13) emits
structured anomalies whose ``reason`` strings are the operator-facing
contract: alerts route on them, the endurance gate greps for them, and
docs/observability.md catalogs what each one means and what to do
about it.  Nothing kept the catalog honest — a new anomaly class added
to the auditor (or one renamed/removed) silently rotted the docs, the
exact failure mode VCL401 closes for metrics.  Same pattern here:

- **VCL601** — an ``Anomaly("reason", ...)`` constructed in the audit
  surface has no row in the docs catalog (reported at the call).
- **VCL602** — a catalog row names a reason the audit surface never
  constructs (reported at the table row).
- **VCL603** — an ``Anomaly(...)`` call whose reason is not a string
  literal: the catalog check (and alert routing) needs static names.

Extraction is pure AST: every ``Anomaly(...)`` call in the scanned
files contributes its first argument.  Docs extraction matches the
markdown table rows ``| `reason` | ...`` inside
docs/observability.md's anomaly-catalog section (the whole file is
scanned; only backticked first-cell rows whose cell looks like a
kebab-case reason participate, so SLO/endpoint tables elsewhere in the
file do not collide).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Sequence, Tuple

from . import astcache
from .findings import Finding

# Files whose Anomaly(...) constructions define the emitted set.
SCAN_FILES: Sequence[str] = (
    "volcano_tpu/obs/audit.py",
    "volcano_tpu/obs/slo.py",
    "volcano_tpu/obs/lockdep.py",
    "volcano_tpu/obs/journey.py",
)

_DOC_ROW_RE = re.compile(r"^\|\s*`([a-z][a-z0-9-]*)`\s*\|")


def emitted_reasons(path: str, src: str
                    ) -> Tuple[Dict[str, int], List[Finding]]:
    """reason -> first lineno for every ``Anomaly(<literal>, ...)``
    call in ``src``; VCL603 for non-literal reasons."""
    findings: List[Finding] = []
    try:
        tree = astcache.parse(src)
    except SyntaxError as err:
        return {}, [Finding(
            "VCL001", path, err.lineno or 1,
            f"audit surface does not parse: {err.msg}",
        )]
    reasons: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "Anomaly"):
            continue
        if not node.args:
            findings.append(Finding(
                "VCL603", path, node.lineno,
                "Anomaly() constructed without a reason argument",
            ))
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            reasons.setdefault(arg.value, node.lineno)
        else:
            findings.append(Finding(
                "VCL603", path, node.lineno,
                "Anomaly() reason is not a string literal (the "
                "catalog drift check needs static names)",
            ))
    return reasons, findings


def documented_reasons(doc_src: str) -> Dict[str, int]:
    """reason -> lineno for every anomaly-catalog table row."""
    out: Dict[str, int] = {}
    for lineno, text in enumerate(doc_src.splitlines(), start=1):
        m = _DOC_ROW_RE.match(text.strip())
        if m:
            out.setdefault(m.group(1), lineno)
    return out


def analyze(sources: Sequence[Tuple[str, str]], doc_path: str,
            doc_src: str) -> List[Finding]:
    findings: List[Finding] = []
    emitted: Dict[str, Tuple[str, int]] = {}
    for path, src in sources:
        reasons, fs = emitted_reasons(path, src)
        findings.extend(fs)
        for reason, lineno in reasons.items():
            emitted.setdefault(reason, (path, lineno))
    docs = documented_reasons(doc_src)
    for reason, (path, lineno) in sorted(emitted.items()):
        if reason not in docs:
            findings.append(Finding(
                "VCL601", path, lineno,
                f"anomaly reason '{reason}' is not catalogued in "
                f"{doc_path}",
            ))
    for reason, lineno in sorted(docs.items()):
        if reason not in emitted:
            findings.append(Finding(
                "VCL602", doc_path, lineno,
                f"catalogued anomaly reason '{reason}' is never "
                "emitted by the audit surface",
            ))
    return findings
