"""Lock-discipline analyzer (VCL1xx).

Annotation convention (docs/static_analysis.md):

- ``# guarded-by: <lock>`` on the line of an attribute assignment
  declares that every ``self.<attr>`` access must happen with ``<lock>``
  held.  ``# guarded-by: <lock> (any-receiver)`` extends the check to
  accesses through ANY receiver expression in the analyzed file set
  (for attributes with a unique name that other modules reach into).
- ``# holds: <lock>[, <lock2>]`` on (or directly above) a ``def`` line
  declares the method runs with those locks already held by its caller
  (the Clang ``REQUIRES()`` analog).  Callers inside the analyzed file
  set are checked at every call site (VCL105).
- ``# vclint: class-holds: <lock>`` anywhere in a class body declares
  every method of the class runs under the lock (used for ``FastCycle``,
  whose single entry point ``run_cycle_fast`` wraps the whole cycle in
  ``with store._lock``).
- A ``*_locked``-suffixed method is assumed to hold every lock guarding
  the attributes it touches (the caller-is-responsible convention).

A lock is "held" inside ``with <expr>.<lockname>:`` for any receiver
expression — ``with self._lock:``, ``with store._lock:`` and
``with self._store._lock:`` all count for ``_lockname``.

Lock-order inversions (VCL103) are detected over the KNOWN_LOCKS set:
nested ``with`` acquisitions (including one level of intra-class call
propagation: a method that acquires B, called while A is held, records
the edge A->B) must not produce both A->B and B->A.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import astcache
# The annotation-parsing layer is shared with the runtime lockdep
# (volcano_tpu/obs/lockdep.py) via tools/vclint/annotations.py — one
# parser, so static and runtime agree byte-for-byte.  Re-exported names
# keep this module's public surface unchanged.
from .annotations import (EXEMPT_METHODS, KNOWN_LOCKS, ClassInfo,  # noqa: F401
                          FileModel, GuardedAttr, _attr_chain,
                          build_model as _build_model)
from .findings import Finding


def build_model(path: str, source: str) -> FileModel:
    return _build_model(path, source, tree=astcache.parse(source))


class _MethodChecker(ast.NodeVisitor):
    """Walk one function body tracking the held-lock set."""

    def __init__(self, model: FileModel, cls: Optional[ClassInfo],
                 base_held: Set[str], guarded: Dict[str, GuardedAttr],
                 any_recv_guarded: Dict[str, GuardedAttr],
                 holds_registry: Dict[str, Set[str]],
                 acquires_of: Dict[str, Set[str]],
                 findings: List[Finding],
                 edges: List[Tuple[str, str, int]]):
        self.model = model
        self.cls = cls
        self.held = set(base_held)
        self.guarded = guarded
        self.any_recv_guarded = any_recv_guarded
        self.holds_registry = holds_registry
        self.acquires_of = acquires_of
        self.findings = findings
        self.edges = edges

    # ------------------------------------------------------------ helpers

    def _lock_of_with_item(self, item: ast.withitem) -> Optional[str]:
        name = _attr_chain(item.context_expr)
        if name is None:
            return None
        leaf = name.split(".")[-1]
        if leaf in KNOWN_LOCKS or leaf.endswith("lock") \
                or leaf.endswith("_cv") or leaf.endswith("cond"):
            return leaf
        return None

    # ------------------------------------------------------------ visits

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            lock = self._lock_of_with_item(item)
            if lock is not None:
                for prior in self.held:
                    if prior != lock:
                        self.edges.append((prior, lock, node.lineno))
                # Re-entrant acquisition of an already-held lock (RLock
                # under class-holds/holds) must not drop it from the
                # held set at block exit.
                if lock not in self.held:
                    acquired.append(lock)
        self.held.update(acquired)
        for stmt in node.body:
            self.visit(stmt)
        for lock in acquired:
            self.held.discard(lock)
        # context expressions themselves (rare attribute reads)
        for item in node.items:
            self.visit(item.context_expr)

    def visit_FunctionDef(self, node) -> None:
        # Nested defs (closures) inherit the lexical held set only if
        # called inline; be conservative and skip their bodies (the
        # enclosing hot registries never nest guarded access in
        # closures).
        return

    visit_AsyncFunctionDef = visit_FunctionDef

    def _guard_of(self, node: ast.Attribute):
        attr = node.attr
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and attr in self.guarded):
            return self.guarded[attr]
        if attr in self.any_recv_guarded:
            return self.any_recv_guarded[attr]
        return None

    def _flag_access(self, node: ast.Attribute, write: bool) -> None:
        guard = self._guard_of(node)
        if guard is not None and guard.lock not in self.held:
            code = "VCL102" if write else "VCL101"
            verb = "write to" if write else "read of"
            self.findings.append(Finding(
                code, self.model.path, node.lineno,
                f"{verb} '{node.attr}' (guarded-by {guard.lock}) "
                f"without holding {guard.lock}",
            ))

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # ``self.items[k] = v`` loads the attribute AST-wise but mutates
        # the guarded container: report it as a write.
        if isinstance(node.ctx, (ast.Store, ast.Del)) \
                and isinstance(node.value, ast.Attribute) \
                and self._guard_of(node.value) is not None:
            self._flag_access(node.value, write=True)
            self.visit(node.slice)
            return
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self._flag_access(
            node, write=isinstance(node.ctx, (ast.Store, ast.Del))
        )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        callee = None
        if isinstance(node.func, ast.Attribute):
            callee = node.func.attr
        elif isinstance(node.func, ast.Name):
            callee = node.func.id
        if callee is not None:
            required = self.holds_registry.get(callee)
            if required is not None:
                missing = required - self.held
                if missing:
                    self.findings.append(Finding(
                        "VCL105", self.model.path, node.lineno,
                        f"call to {callee}() requires "
                        f"{', '.join(sorted(required))} but "
                        f"{', '.join(sorted(missing))} is not held",
                    ))
            # one-level intra-class acquisition propagation for ordering
            # (``self.X()`` receivers only: an attr-name match through an
            # arbitrary receiver — ``self._sock.close()`` vs our own
            # ``close`` — is a different object's method)
            is_self_call = (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
            )
            acq = self.acquires_of.get(callee) if is_self_call else None
            if acq:
                for prior in self.held:
                    for lock in acq:
                        if prior != lock:
                            self.edges.append(
                                (prior, lock, node.lineno)
                            )
        self.generic_visit(node)


def _method_acquires(cls: ClassInfo) -> Dict[str, Set[str]]:
    """Locks each method of the class acquires lexically, propagated one
    fixpoint through intra-class self.X() calls."""
    direct: Dict[str, Set[str]] = {}
    calls: Dict[str, Set[str]] = {}
    for sub in cls.node.body:
        if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        acq: Set[str] = set()
        callees: Set[str] = set()
        for n in ast.walk(sub):
            if isinstance(n, ast.With):
                for item in n.items:
                    name = _attr_chain(item.context_expr)
                    if name is None:
                        continue
                    leaf = name.split(".")[-1]
                    if leaf in KNOWN_LOCKS or leaf.endswith("lock") \
                            or leaf.endswith("_cv") \
                            or leaf.endswith("cond"):
                        acq.add(leaf)
            elif isinstance(n, ast.Call):
                if (isinstance(n.func, ast.Attribute)
                        and isinstance(n.func.value, ast.Name)
                        and n.func.value.id == "self"):
                    callees.add(n.func.attr)
        direct[sub.name] = acq
        calls[sub.name] = callees
    # fixpoint (class method graphs are tiny)
    changed = True
    while changed:
        changed = False
        for name, callees in calls.items():
            for c in callees:
                extra = direct.get(c, set()) - direct[name]
                if extra:
                    direct[name] |= extra
                    changed = True
    return direct


def analyze_files(paths_sources: List[Tuple[str, str]]) -> List[Finding]:
    """Run the lock-discipline analysis over the file set.  Returns RAW
    findings (suppressions are applied by the caller per file)."""
    findings: List[Finding] = []
    models: List[FileModel] = []
    for path, source in paths_sources:
        try:
            models.append(build_model(path, source))
        except SyntaxError as err:
            findings.append(Finding(
                "VCL001", path, err.lineno or 1,
                f"file does not parse: {err.msg}",
            ))
    # Cross-file registries -------------------------------------------
    # any-receiver guarded attributes (unique names only).
    any_recv: Dict[str, GuardedAttr] = {}
    seen_attr: Dict[str, int] = {}
    holds_registry: Dict[str, Set[str]] = {}
    holds_conflict: Set[str] = set()
    for model in models:
        for lineno, msg in model.annotation_errors:
            findings.append(Finding("VCL001", model.path, lineno, msg))
        for cls in model.classes:
            for attr, guard in cls.guarded.items():
                if guard.lock not in cls.created_locks \
                        and guard.lock not in KNOWN_LOCKS:
                    findings.append(Finding(
                        "VCL104", model.path, guard.line,
                        f"'{attr}' is guarded-by '{guard.lock}' but no "
                        "such lock is created in the class or listed in "
                        "KNOWN_LOCKS",
                    ))
                if guard.any_receiver:
                    seen_attr[attr] = seen_attr.get(attr, 0) + 1
                    any_recv[attr] = guard
            for name, req in cls.holds.items():
                if name in holds_registry and holds_registry[name] != req:
                    holds_conflict.add(name)
                holds_registry[name] = set(req)
        for name, req in model.fn_holds.items():
            if name in holds_registry and holds_registry[name] != req:
                holds_conflict.add(name)
            holds_registry[name] = set(req)
    for name in holds_conflict:
        holds_registry.pop(name, None)
    for attr, count in seen_attr.items():
        if count > 1:
            any_recv.pop(attr, None)

    edge_paths: Dict[Tuple[str, str], Tuple[str, int]] = {}

    for model in models:
        edges: List[Tuple[str, str, int]] = []
        for cls in model.classes:
            acquires_of = _method_acquires(cls)
            for sub in cls.node.body:
                if not isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if sub.name in EXEMPT_METHODS:
                    continue
                base = set(cls.class_holds)
                base |= cls.holds.get(sub.name, set())
                if sub.name.endswith("_locked"):
                    # Caller-is-responsible convention: assumed to hold
                    # the locks guarding this class's own state.
                    base |= {g.lock for g in cls.guarded.values()}
                    base |= cls.created_locks
                checker = _MethodChecker(
                    model, cls, base, cls.guarded, any_recv,
                    holds_registry, acquires_of, findings, edges,
                )
                for stmt in sub.body:
                    checker.visit(stmt)
        # module-level functions
        for sub in model.tree.body:
            if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            base = set(model.fn_holds.get(sub.name, set()))
            checker = _MethodChecker(
                model, None, base, {}, any_recv, holds_registry, {},
                findings, edges,
            )
            for stmt in sub.body:
                checker.visit(stmt)
        for a, b, lineno in edges:
            edge_paths.setdefault((a, b), (model.path, lineno))

    # Lock-order inversions over KNOWN_LOCKS -------------------------
    known_edges = {
        (a, b) for (a, b) in edge_paths
        if a in KNOWN_LOCKS and b in KNOWN_LOCKS
    }
    reported: Set[Tuple[str, str]] = set()
    for a, b in sorted(known_edges):
        if (b, a) in known_edges and (b, a) not in reported:
            reported.add((a, b))
            pa, la = edge_paths[(a, b)]
            pb, lb = edge_paths[(b, a)]
            findings.append(Finding(
                "VCL103", pa, la,
                f"lock-order inversion: {a} -> {b} here but "
                f"{b} -> {a} at {pb}:{lb}",
            ))
    return findings
