"""Schema <-> C++ ABI cross-checker (VCL3xx).

Four independent comparisons, all static (nothing is imported or run):

- **VCL301 wire dtype table**: ``cache/snapwire.py _DTYPES`` (code =
  list index) vs ``csrc/vcsnap.cc kVcsnapDtypes`` (code/name/width).
  Count, order, names and element widths must agree — the u8 dtype code
  is wire format between the scheduler and the solver process.
- **VCL302 frame constants**: ``WIRE_MAGIC`` / ``WIRE_VERSION`` /
  ``WIRE_MAX_DIMS`` in snapwire.py vs ``kVcsnapMagic`` /
  ``kVcsnapVersion`` / ``kVcsnapMaxDims`` in vcsnap.cc.
- **VCL303 ctypes bindings**: every ``lib.<fn>.argtypes`` declaration in
  ``volcano_tpu/native.py _bind`` vs the C prototype in
  ``csrc/vcsnap.h``.  Arity must match exactly and each position must be
  type-compatible (``c_void_p`` matches any pointer — the reclaim
  engine's raw-address hot path).  This is the actual Python<->C++ call
  ABI; a drifted 47-argument ``vcreclaim_ctx_new`` binding corrupts
  memory silently.
- **VCL304 schema column table**: ``arrays/schema.py WIRE_COLUMNS`` vs
  the NodeArrays/TaskArrays/JobArrays/QueueArrays NamedTuple field lists
  (1:1, same order), with every declared dtype present in the wire dtype
  table.
- **VCL305 delta record tags**: ``cache/snapwire.py REC_*`` (protocol
  v2 delta solve frames, ISSUE 10) vs ``csrc/vcsnap.cc kVcsnapRec*``.
  The tag values are wire format between the scheduler and the solver
  child — count, names and values must agree 1:1 in both directions,
  exactly the drift class the dtype table check covers.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from . import astcache
from .findings import Finding

# numpy dtype name -> element width (the static mirror of np.dtype(x).
# itemsize for the wire-transportable set).
NP_WIDTH = {
    "float32": 4, "float64": 8, "int8": 1, "int16": 2, "int32": 4,
    "int64": 8, "uint8": 1, "uint16": 2, "uint32": 4, "uint64": 8,
    "bool": 1, "bool_": 1,
}


# ------------------------------------------------------------ python side


def parse_snapwire(source: str) -> Tuple[
        List[str], Dict[str, int], Dict[str, Tuple[int, int]],
        Optional[int]]:
    """(_DTYPES names in order, WIRE_* constants, REC_* delta record
    tags as name -> (value, line), _DTYPES line)."""
    tree = astcache.parse(source)
    names: List[str] = []
    consts: Dict[str, int] = {}
    recs: Dict[str, Tuple[int, int]] = {}
    line: Optional[int] = None
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            tname = node.targets[0].id
            if tname == "_DTYPES" and isinstance(node.value, ast.List):
                line = node.lineno
                for el in node.value.elts:
                    # np.dtype(np.float32) -> "float32"
                    if isinstance(el, ast.Call) and el.args:
                        inner = el.args[0]
                        leaf = None
                        if isinstance(inner, ast.Attribute):
                            leaf = inner.attr
                        elif isinstance(inner, ast.Name):
                            leaf = inner.id
                        if leaf is not None:
                            names.append(leaf.rstrip("_"))
            elif tname.startswith("WIRE_") and isinstance(
                    node.value, ast.Constant):
                consts[tname] = int(node.value.value)
            elif tname.startswith("REC_") and isinstance(
                    node.value, ast.Constant):
                recs[tname] = (int(node.value.value), node.lineno)
    return names, consts, recs, line


def parse_wire_columns(source: str) -> Tuple[
        List[Tuple[str, str, str, int]], Dict[str, List[str]],
        Optional[int]]:
    """(WIRE_COLUMNS rows, NamedTuple class -> ordered ndarray fields,
    WIRE_COLUMNS line)."""
    tree = astcache.parse(source)
    rows: List[Tuple[str, str, str, int]] = []
    line: Optional[int] = None
    classes: Dict[str, List[str]] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            bases = {b.id for b in node.bases if isinstance(b, ast.Name)}
            if "NamedTuple" not in bases:
                continue
            fields = []
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    ann = stmt.annotation
                    leaf = ann.attr if isinstance(ann, ast.Attribute) \
                        else (ann.id if isinstance(ann, ast.Name) else "")
                    if leaf == "ndarray":
                        fields.append(stmt.target.id)
            classes[node.name] = fields
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if not any(isinstance(t, ast.Name)
                       and t.id == "WIRE_COLUMNS" for t in targets):
                continue
            line = node.lineno
            value = node.value
            if isinstance(value, (ast.Tuple, ast.List)):
                for el in value.elts:
                    if isinstance(el, ast.Tuple) and len(el.elts) == 4:
                        vals = [
                            e.value for e in el.elts
                            if isinstance(e, ast.Constant)
                        ]
                        if len(vals) == 4:
                            rows.append(tuple(vals))  # type: ignore
    return rows, classes, line


# --------------------------------------------------------------- C++ side


_CC_DTYPE_ROW = re.compile(
    r"\{\s*(\d+)\s*,\s*\"(\w+)\"\s*,\s*(\d+)\s*\}"
)
_CC_CONST = re.compile(
    r"constexpr\s+\w+(?:\d+_t)?\s+(kVcsnap\w+)\s*=\s*(0[xX][0-9a-fA-F]+|\d+)u?\s*;"
)


def parse_vcsnap_cc(source: str) -> Tuple[
        List[Tuple[int, str, int]], Dict[str, int], Optional[int]]:
    """(kVcsnapDtypes rows, kVcsnap* integer constants, table line)."""
    consts: Dict[str, int] = {}
    for m in _CC_CONST.finditer(source):
        consts[m.group(1)] = int(m.group(2), 0)
    rows: List[Tuple[int, str, int]] = []
    line: Optional[int] = None
    m = re.search(r"kVcsnapDtypes\[\]\s*=\s*\{(.*?)\};", source, re.S)
    if m:
        line = source[:m.start()].count("\n") + 1
        for rm in _CC_DTYPE_ROW.finditer(m.group(1)):
            rows.append((int(rm.group(1)), rm.group(2), int(rm.group(3))))
    return rows, consts, line


_PROTO_RE = re.compile(
    r"^\s*([A-Za-z_][\w\s\*]*?)\s+(vcsnap_\w+|vcreclaim_\w+)\s*\(([^;]*?)\)\s*;",
    re.M | re.S,
)


def parse_header_protos(source: str) -> Dict[str, Tuple[str, List[str], int]]:
    """name -> (return type, [normalized param types], line)."""
    out: Dict[str, Tuple[str, List[str], int]] = {}
    for m in _PROTO_RE.finditer(source):
        ret = " ".join(m.group(1).split())
        name = m.group(2)
        argsrc = m.group(3).strip()
        line = source[:m.start()].count("\n") + 2
        params: List[str] = []
        if argsrc and argsrc != "void":
            for part in argsrc.split(","):
                part = " ".join(part.split())
                # strip the parameter name (last identifier not part of
                # the type when the decl has one beyond the type tokens)
                part = re.sub(r"\b[A-Za-z_]\w*$", "", part).strip()
                params.append(_norm_ctype(part))
        out[name] = (_norm_ctype(ret), params, line)
    return out


def _norm_ctype(t: str) -> str:
    t = t.replace("const", " ").replace("unsigned long long",
                                        "uint64").strip()
    t = " ".join(t.split())
    t = t.replace("long long", "int64")
    ptr = t.count("*")
    base = t.replace("*", "").strip()
    base = {
        "int": "int32", "float": "float32", "double": "float64",
        "char": "int8", "void": "void", "uint8_t": "uint8",
        "uint16_t": "uint16", "uint32_t": "uint32", "uint64_t": "uint64",
        "int8_t": "int8", "int16_t": "int16", "int32_t": "int32",
        "int64_t": "int64", "uint64": "uint64", "int64": "int64",
    }.get(base, base)
    return base + "*" * ptr


# ctypes expression -> normalized type, for the _bind argtypes lists.
_NDPTR_DTYPE = {
    "_i32p": "int32*", "_i64p": "int64*", "_u32p": "uint32*",
    "_u8p": "uint8*", "_f32p": "float32*", "_f64p": "float64*",
    "_i16p": "int16*", "_i8p": "int8*",
}
_CTYPES_SCALAR = {
    "c_int": "int32", "c_int32": "int32", "c_int64": "int64",
    "c_longlong": "int64", "c_uint64": "uint64", "c_double": "float64",
    "c_float": "float32", "c_void_p": "void*", "c_uint8": "uint8",
    "c_char_p": "int8*",
}


def _eval_ctype_expr(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Normalized type of one ctypes argtype expression."""
    if isinstance(node, ast.Name):
        if node.id in _NDPTR_DTYPE:
            return _NDPTR_DTYPE[node.id]
        if node.id in aliases:
            return aliases[node.id]
        return None
    if isinstance(node, ast.Attribute):
        return _CTYPES_SCALAR.get(node.attr)
    if isinstance(node, ast.Call):
        # ctypes.POINTER(inner)
        leaf = node.func.attr if isinstance(node.func, ast.Attribute) \
            else (node.func.id if isinstance(node.func, ast.Name) else "")
        if leaf == "POINTER" and node.args:
            inner = _eval_ctype_expr(node.args[0], aliases)
            return None if inner is None else inner + "*"
        if leaf == "ndpointer" and node.args:
            a = node.args[0]
            dn = a.attr if isinstance(a, ast.Attribute) \
                else (a.id if isinstance(a, ast.Name) else "")
            return (dn.rstrip("_") + "*") if dn in NP_WIDTH or \
                dn.rstrip("_") in NP_WIDTH else None
        return None
    return None


def _eval_argtypes_list(node: ast.AST,
                        aliases: Dict[str, str]) -> Optional[List[str]]:
    """Evaluate an argtypes expression: list/tuple literals plus the
    ``[vp] * 20 + [vp, ll]`` list-arithmetic idiom."""
    if isinstance(node, (ast.List, ast.Tuple)):
        out: List[str] = []
        for el in node.elts:
            t = _eval_ctype_expr(el, aliases)
            if t is None:
                return None
            out.append(t)
        return out
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.Add):
            l = _eval_argtypes_list(node.left, aliases)
            r = _eval_argtypes_list(node.right, aliases)
            if l is None or r is None:
                return None
            return l + r
        if isinstance(node.op, ast.Mult):
            l = _eval_argtypes_list(node.left, aliases)
            if l is not None and isinstance(node.right, ast.Constant):
                return l * int(node.right.value)
            if isinstance(node.left, ast.Constant):
                r = _eval_argtypes_list(node.right, aliases)
                if r is not None:
                    return r * int(node.left.value)
    return None


def parse_native_bindings(source: str) -> Tuple[
        Dict[str, Tuple[Optional[str], Optional[List[str]], int]],
        List[Tuple[int, str]]]:
    """From _bind(): fn name -> (restype, argtypes, line); plus parse
    errors."""
    tree = astcache.parse(source)
    out: Dict[str, Tuple[Optional[str], Optional[List[str]], int]] = {}
    errors: List[Tuple[int, str]] = []
    bind = None
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == "_bind":
            bind = node
            break
    if bind is None:
        return out, [(1, "native.py has no _bind function")]
    aliases: Dict[str, str] = {}
    for stmt in bind.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            tgt = stmt.targets[0]
            if isinstance(tgt, ast.Name):
                t = _eval_ctype_expr(stmt.value, aliases)
                if t is not None:
                    aliases[tgt.id] = t
                continue
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Attribute)
                    and isinstance(tgt.value.value, ast.Name)
                    and tgt.value.value.id == "lib"):
                continue
            fn = tgt.value.attr
            slot = tgt.attr
            restype, argtypes, line = out.get(fn, (None, None, stmt.lineno))
            if slot == "restype":
                restype = _eval_ctype_expr(stmt.value, aliases)
                if restype is None:
                    errors.append(
                        (stmt.lineno,
                         f"unrecognized restype expression for {fn}")
                    )
            elif slot == "argtypes":
                argtypes = _eval_argtypes_list(stmt.value, aliases)
                if argtypes is None:
                    errors.append(
                        (stmt.lineno,
                         f"unrecognized argtypes expression for {fn}")
                    )
            out[fn] = (restype, argtypes, stmt.lineno)
    return out, errors


def _compatible(py: str, c: str) -> bool:
    if py == c:
        return True
    # raw-address hot path: void* carries any pointer
    if py == "void*" and c.endswith("*"):
        return True
    if c == "void*" and py.endswith("*"):
        return True
    # uint8* carries opaque byte buffers on both sides
    pair = {py, c}
    if pair == {"uint8*", "int8*"}:
        return True
    return False


# ----------------------------------------------------------------- driver


def analyze(snapwire_path: str, snapwire_src: str,
            schema_path: str, schema_src: str,
            cc_path: str, cc_src: str,
            header_path: str, header_src: str,
            native_path: str, native_src: str) -> List[Finding]:
    findings: List[Finding] = []

    # ---- VCL301: dtype table --------------------------------------
    py_dtypes, py_consts, py_recs, py_line = parse_snapwire(snapwire_src)
    cc_rows, cc_consts, cc_line = parse_vcsnap_cc(cc_src)
    if not py_dtypes:
        findings.append(Finding(
            "VCL301", snapwire_path, 1,
            "could not parse _DTYPES (wire dtype table missing?)",
        ))
    if not cc_rows:
        findings.append(Finding(
            "VCL301", cc_path, 1,
            "could not parse kVcsnapDtypes (wire dtype table missing?)",
        ))
    if py_dtypes and cc_rows:
        if len(py_dtypes) != len(cc_rows):
            findings.append(Finding(
                "VCL301", cc_path, cc_line or 1,
                f"dtype table length drift: python {len(py_dtypes)} "
                f"codes vs C++ {len(cc_rows)}",
            ))
        for i, (code, name, width) in enumerate(cc_rows):
            if code != i:
                findings.append(Finding(
                    "VCL301", cc_path, cc_line or 1,
                    f"kVcsnapDtypes row {i} declares code {code}: codes "
                    "must be dense list indexes",
                ))
                continue
            if i >= len(py_dtypes):
                continue
            pyname = py_dtypes[i]
            if pyname != name:
                findings.append(Finding(
                    "VCL301", cc_path, cc_line or 1,
                    f"dtype code {i} is {pyname!r} in python but "
                    f"{name!r} in C++",
                ))
            expect = NP_WIDTH.get(pyname)
            if expect is not None and expect != width:
                findings.append(Finding(
                    "VCL301", cc_path, cc_line or 1,
                    f"dtype code {i} ({name}) has width {width} in C++ "
                    f"but numpy itemsize is {expect}",
                ))

    # ---- VCL302: frame constants ----------------------------------
    pairs = [
        ("WIRE_MAGIC", "kVcsnapMagic"),
        ("WIRE_VERSION", "kVcsnapVersion"),
        ("WIRE_MAX_DIMS", "kVcsnapMaxDims"),
    ]
    for py_name, cc_name in pairs:
        pv = py_consts.get(py_name)
        cv = cc_consts.get(cc_name)
        if pv is None:
            findings.append(Finding(
                "VCL302", snapwire_path, 1,
                f"{py_name} is not declared in the wire codec",
            ))
        if cv is None:
            findings.append(Finding(
                "VCL302", cc_path, 1,
                f"{cc_name} is not declared in the frame codec",
            ))
        if pv is not None and cv is not None and pv != cv:
            findings.append(Finding(
                "VCL302", cc_path, cc_line or 1,
                f"{py_name}=0x{pv:X} (python) != {cc_name}=0x{cv:X} "
                "(C++)",
            ))

    # ---- VCL305: delta record tags ---------------------------------
    # REC_FULL <-> kVcsnapRecFull etc.: the tag byte is wire format of
    # the protocol-v2 delta solve frames (ISSUE 10), shared between the
    # python codec and the C++ validator exactly like the dtype codes.
    cc_recs = {k: v for k, v in cc_consts.items()
               if k.startswith("kVcsnapRec")}
    if not py_recs:
        findings.append(Finding(
            "VCL305", snapwire_path, 1,
            "could not parse REC_* delta record tags (protocol v2 "
            "table missing?)",
        ))
    if not cc_recs:
        findings.append(Finding(
            "VCL305", cc_path, 1,
            "could not parse kVcsnapRec* delta record tags (protocol "
            "v2 table missing?)",
        ))
    if py_recs and cc_recs:
        py_to_cc = {
            name: "kVcsnapRec" + "".join(
                p.title() for p in name[len("REC_"):].split("_")
            )
            for name in py_recs
        }
        for name, (value, rline) in sorted(py_recs.items()):
            cc_name = py_to_cc[name]
            cv = cc_recs.get(cc_name)
            if cv is None:
                findings.append(Finding(
                    "VCL305", snapwire_path, rline,
                    f"delta record tag {name} has no C++ counterpart "
                    f"{cc_name} in vcsnap.cc",
                ))
            elif cv != value:
                findings.append(Finding(
                    "VCL305", snapwire_path, rline,
                    f"delta record tag drift: {name}={value} (python) "
                    f"!= {cc_name}={cv} (C++)",
                ))
        known_cc = set(py_to_cc.values())
        for cc_name in sorted(cc_recs):
            if cc_name not in known_cc:
                findings.append(Finding(
                    "VCL305", cc_path, 1,
                    f"C++ delta record tag {cc_name} has no python "
                    "counterpart REC_* in snapwire.py",
                ))

    # ---- VCL303: ctypes bindings vs header prototypes --------------
    protos = parse_header_protos(header_src)
    bindings, bind_errors = parse_native_bindings(native_src)
    for lineno, msg in bind_errors:
        findings.append(Finding("VCL303", native_path, lineno, msg))
    for fn, (restype, argtypes, line) in sorted(bindings.items()):
        proto = protos.get(fn)
        if proto is None:
            findings.append(Finding(
                "VCL303", native_path, line,
                f"{fn} is bound in native.py but has no prototype in "
                "vcsnap.h",
            ))
            continue
        c_ret, c_params, _hline = proto
        if argtypes is not None:
            if len(argtypes) != len(c_params):
                findings.append(Finding(
                    "VCL303", native_path, line,
                    f"{fn} binds {len(argtypes)} argtypes but the C "
                    f"prototype takes {len(c_params)} parameters",
                ))
            else:
                for i, (py_t, c_t) in enumerate(zip(argtypes, c_params)):
                    if not _compatible(py_t, c_t):
                        findings.append(Finding(
                            "VCL303", native_path, line,
                            f"{fn} argument {i}: ctypes {py_t} vs C "
                            f"{c_t}",
                        ))
        if restype is not None and c_ret != "void" \
                and not _compatible(restype, c_ret):
            findings.append(Finding(
                "VCL303", native_path, line,
                f"{fn} restype {restype} vs C return type {c_ret}",
            ))

    # ---- VCL304: schema column table -------------------------------
    rows, classes, wc_line = parse_wire_columns(schema_src)
    if not rows:
        findings.append(Finding(
            "VCL304", schema_path, 1,
            "WIRE_COLUMNS is missing or empty",
        ))
    else:
        declared: Dict[str, List[Tuple[str, str, int]]] = {}
        max_dims = py_consts.get("WIRE_MAX_DIMS", 8)
        for group, fieldname, dtype, ndim in rows:
            declared.setdefault(group, []).append(
                (fieldname, dtype, ndim)
            )
            if not isinstance(ndim, int) or not 1 <= ndim <= max_dims:
                findings.append(Finding(
                    "VCL304", schema_path, wc_line or 1,
                    f"{group}.{fieldname} declares ndim {ndim!r} "
                    f"outside the wire range 1..{max_dims}",
                ))
            if dtype not in NP_WIDTH:
                findings.append(Finding(
                    "VCL304", schema_path, wc_line or 1,
                    f"{group}.{fieldname} declares non-wire dtype "
                    f"{dtype!r}",
                ))
            if py_dtypes and dtype not in py_dtypes:
                findings.append(Finding(
                    "VCL304", schema_path, wc_line or 1,
                    f"{group}.{fieldname} dtype {dtype!r} is not in the "
                    "wire dtype table (snapwire._DTYPES)",
                ))
        for group, fields in classes.items():
            if group == "ClusterArrays" or not fields:
                continue
            got = [f for f, _d, _n in declared.get(group, [])]
            if got != fields:
                findings.append(Finding(
                    "VCL304", schema_path, wc_line or 1,
                    f"WIRE_COLUMNS for {group} lists {got} but the "
                    f"NamedTuple declares {fields} (order-sensitive)",
                ))
        for group in declared:
            if group not in classes:
                findings.append(Finding(
                    "VCL304", schema_path, wc_line or 1,
                    f"WIRE_COLUMNS names unknown group {group!r}",
                ))
    return findings
