"""vclint driver: run the analyzer families over the repo.

``python -m tools.vclint`` exits 0 only when the committed tree carries
zero unsuppressed findings — it is the first leg of the pre-snapshot
green-gate (``hack/run-checks.sh``), ahead of the csrc ASAN/TSAN smoke
and the tier-1 pytest suite.

The driver reads every file once into a shared source cache and every
family parses through ``astcache`` (one AST per distinct source no
matter how many families consume it).  ``--only <family>`` runs a
single family; ``--jobs N`` runs families concurrently (they share the
read-only caches).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

from . import (aggcheck, anomalycheck, hotpath, knobcheck, lockcheck,
               metricscheck, schemacheck, writercheck)
# The lock-discipline file set lives with the annotation parser
# (tools/vclint/annotations.py) so the runtime lockdep enforces the
# exact same surface; re-exported here for compatibility.
from .annotations import LOCK_FILES  # noqa: F401
from .findings import Finding, finish

REPO_ROOT = Path(__file__).resolve().parent.parent.parent

# Metrics-drift surface: every series in the registry must have a row
# in the docs table and vice versa (VCL401/402/403).
METRICS_FILES = {
    "metrics": "volcano_tpu/metrics/metrics.py",
    "doc": "docs/metrics.md",
}

# Anomaly-catalog surface (VCL601/602/603): every Anomaly reason the
# runtime auditor can emit must have a docs/observability.md catalog
# row and vice versa.
ANOMALY_DOC = "docs/observability.md"

# Tuning-knob surface (VCL710/711): every VOLCANO_TPU_* env read in
# volcano_tpu/ must have a docs/tuning.md row and vice versa.
KNOB_DOC = "docs/tuning.md"

SCHEMA_FILES = {
    "snapwire": "volcano_tpu/cache/snapwire.py",
    "schema": "volcano_tpu/arrays/schema.py",
    "cc": "csrc/vcsnap.cc",
    "header": "csrc/vcsnap.h",
    "native": "volcano_tpu/native.py",
}


class _Sources:
    """Read-once file cache shared by every family (and safe to share
    across ``--jobs`` workers: entries are immutable strings)."""

    def __init__(self, root: Path):
        self.root = root
        self._text: Dict[str, str] = {}

    def text(self, rel: str) -> str:
        src = self._text.get(rel)
        if src is None:
            src = (self.root / rel).read_text()
            self._text[rel] = src
        return src

    def pairs(self, rels, missing_msg: str
              ) -> Tuple[List[Tuple[str, str]], List[Finding]]:
        out, missing = [], []
        for rel in rels:
            try:
                out.append((rel, self.text(rel)))
            except OSError:
                missing.append(Finding("VCL001", rel, 1, missing_msg))
        return out, missing


def _finish_grouped(sources, raw) -> List[Finding]:
    by_file: Dict[str, List[Finding]] = {rel: [] for rel, _ in sources}
    for f in raw:
        by_file.setdefault(f.path, []).append(f)
    out: List[Finding] = []
    for rel, src in sources:
        out.extend(finish(rel, src, by_file.get(rel, [])))
    return out


# ---------------------------------------------------------------- families

def _run_lock(cache: _Sources) -> List[Finding]:
    sources, missing = cache.pairs(
        LOCK_FILES, "lock-discipline file set names a missing file")
    raw = lockcheck.analyze_files(sources)
    return missing + _finish_grouped(sources, raw)


def _run_hotpath(cache: _Sources) -> List[Finding]:
    out: List[Finding] = []
    for rel, entries in hotpath.HOT_REGISTRY.items():
        try:
            src = cache.text(rel)
        except OSError:
            out.append(Finding(
                "VCL001", rel, 1, "hot registry names a missing file"))
            continue
        out.extend(finish(rel, src, hotpath.analyze_file(
            rel, src, entries)))
    return out


def _run_schema(cache: _Sources) -> List[Finding]:
    try:
        texts = {k: cache.text(rel) for k, rel in SCHEMA_FILES.items()}
    except OSError as err:
        return [Finding(
            "VCL001", str(err.filename or "?"), 1,
            f"schema cross-check input unreadable: {err}",
        )]
    raw = schemacheck.analyze(
        SCHEMA_FILES["snapwire"], texts["snapwire"],
        SCHEMA_FILES["schema"], texts["schema"],
        SCHEMA_FILES["cc"], texts["cc"],
        SCHEMA_FILES["header"], texts["header"],
        SCHEMA_FILES["native"], texts["native"],
    )
    return _finish_grouped(
        [(rel, texts[k]) for k, rel in SCHEMA_FILES.items()], raw)


def _run_agg(cache: _Sources) -> List[Finding]:
    sources, missing = cache.pairs(
        aggcheck.SCAN_FILES, "aggregate-cache scan set names a missing file")
    raw = aggcheck.analyze_files(sources)
    return missing + _finish_grouped(sources, raw)


def _run_metrics(cache: _Sources) -> List[Finding]:
    try:
        m_src = cache.text(METRICS_FILES["metrics"])
        d_src = cache.text(METRICS_FILES["doc"])
    except OSError as err:
        return [Finding(
            "VCL001", str(err.filename or "?"), 1,
            f"metrics-drift input unreadable: {err}",
        )]
    raw = metricscheck.analyze(
        METRICS_FILES["metrics"], m_src, METRICS_FILES["doc"], d_src,
    )
    return _finish_grouped(
        [(METRICS_FILES["metrics"], m_src),
         (METRICS_FILES["doc"], d_src)], raw)


def _run_anomaly(cache: _Sources) -> List[Finding]:
    sources, missing = cache.pairs(
        anomalycheck.SCAN_FILES,
        "anomaly-catalog scan set names a missing file")
    try:
        doc = cache.text(ANOMALY_DOC)
    except OSError as err:
        missing.append(Finding(
            "VCL001", ANOMALY_DOC, 1,
            f"anomaly-catalog doc unreadable: {err}",
        ))
        return missing
    raw = anomalycheck.analyze(sources, ANOMALY_DOC, doc)
    return missing + _finish_grouped(sources + [(ANOMALY_DOC, doc)], raw)


def _tree_sources(cache: _Sources) -> List[Tuple[str, str]]:
    out = []
    for rel in writercheck.iter_py_files(cache.root):
        try:
            out.append((rel, cache.text(rel)))
        except OSError:
            pass  # racing deletion; the tree glob is re-derived per run
    return out


def _run_writer(cache: _Sources) -> List[Finding]:
    sources = _tree_sources(cache)
    raw = writercheck.analyze_files(sources)
    return _finish_grouped(sources, raw)


def _run_knob(cache: _Sources) -> List[Finding]:
    sources = _tree_sources(cache)
    try:
        doc = cache.text(KNOB_DOC)
    except OSError as err:
        return [Finding(
            "VCL001", KNOB_DOC, 1,
            f"tuning-knob doc unreadable: {err}",
        )]
    raw = knobcheck.analyze(sources, KNOB_DOC, doc)
    return _finish_grouped(sources + [(KNOB_DOC, doc)], raw)


FAMILIES: Dict[str, Callable[[_Sources], List[Finding]]] = {
    "lock": _run_lock,
    "hotpath": _run_hotpath,
    "schema": _run_schema,
    "agg": _run_agg,
    "metrics": _run_metrics,
    "anomaly": _run_anomaly,
    "writer": _run_writer,
    "knob": _run_knob,
}


def run(root: Path = REPO_ROOT, verbose: bool = False,
        out=sys.stdout, jobs: int = 1,
        only: Optional[str] = None) -> int:
    cache = _Sources(root)
    names = [only] if only else list(FAMILIES)
    all_findings: List[Finding] = []

    if jobs > 1 and len(names) > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=jobs) as pool:
            futures = [pool.submit(FAMILIES[n], cache) for n in names]
            for fut in futures:  # family order, not completion order
                all_findings.extend(fut.result())
    else:
        for n in names:
            all_findings.extend(FAMILIES[n](cache))

    open_findings = [f for f in all_findings if not f.suppressed]
    suppressed = [f for f in all_findings if f.suppressed]
    for f in open_findings:
        print(f.render(), file=out)
    if verbose:
        for f in suppressed:
            print(f.render(), file=out)
    print(
        f"vclint: {len(open_findings)} finding(s), "
        f"{len(suppressed)} suppressed "
        f"({len(LOCK_FILES)} lock files, "
        f"{sum(len(v) for v in hotpath.HOT_REGISTRY.values())} hot "
        f"functions, {len(aggcheck.CACHE_REGISTRY)} keyed caches, "
        f"{len(writercheck.WRITER_REGISTRY)} registered writers, "
        "1 schema/ABI surface, 1 metrics/docs surface, "
        "1 anomaly-catalog surface, 1 tuning-knob surface)",
        file=out,
    )
    return 1 if open_findings else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="vclint",
        description="repo-native static analysis: lock discipline, "
        "device hot-path hygiene, schema<->C++ ABI drift, writer "
        "triad discipline, docs drift",
    )
    parser.add_argument("--root", default=str(REPO_ROOT),
                        help="repo root (default: auto-detected)")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="also print suppressed findings")
    parser.add_argument("--jobs", type=int, default=1, metavar="N",
                        help="run analyzer families in N threads")
    parser.add_argument("--only", choices=sorted(FAMILIES),
                        help="run a single analyzer family")
    args = parser.parse_args(argv)
    if args.jobs < 1:
        parser.error("--jobs must be >= 1")
    return run(Path(args.root), verbose=args.verbose, jobs=args.jobs,
               only=args.only)


if __name__ == "__main__":
    sys.exit(main())
