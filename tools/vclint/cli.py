"""vclint driver: run the three analyzer families over the repo.

``python -m tools.vclint`` exits 0 only when the committed tree carries
zero unsuppressed findings — it is the first leg of the pre-snapshot
green-gate (``hack/run-checks.sh``), ahead of the csrc ASAN/TSAN smoke
and the tier-1 pytest suite.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List

from . import (aggcheck, anomalycheck, hotpath, lockcheck, metricscheck,
               schemacheck)
from .findings import Finding, finish

REPO_ROOT = Path(__file__).resolve().parent.parent.parent

# Files under the lock-discipline analysis (the concurrency surface of
# the pipelined scheduler: shared store state, the mirror, the in-flight
# solve handle, the remote-solver client, the flight-recorder ring the
# HTTP debug handlers read cross-thread).
LOCK_FILES = [
    "volcano_tpu/cache/store.py",
    "volcano_tpu/cache/mirror.py",
    "volcano_tpu/cache/bindqueue.py",
    "volcano_tpu/pipeline.py",
    "volcano_tpu/scheduler.py",
    "volcano_tpu/shard.py",
    "volcano_tpu/solver_service.py",
    "volcano_tpu/solver_pool.py",
    "volcano_tpu/fastpath.py",
    "volcano_tpu/fastpath_evict.py",
    "volcano_tpu/whatif.py",
    "volcano_tpu/ops/devsnap.py",
    "volcano_tpu/obs/recorder.py",
    "volcano_tpu/obs/audit.py",
    "volcano_tpu/obs/slo.py",
]

# Metrics-drift surface: every series in the registry must have a row
# in the docs table and vice versa (VCL401/402/403).
METRICS_FILES = {
    "metrics": "volcano_tpu/metrics/metrics.py",
    "doc": "docs/metrics.md",
}

# Anomaly-catalog surface (VCL601/602/603): every Anomaly reason the
# runtime auditor can emit must have a docs/observability.md catalog
# row and vice versa.
ANOMALY_DOC = "docs/observability.md"

SCHEMA_FILES = {
    "snapwire": "volcano_tpu/cache/snapwire.py",
    "schema": "volcano_tpu/arrays/schema.py",
    "cc": "csrc/vcsnap.cc",
    "header": "csrc/vcsnap.h",
    "native": "volcano_tpu/native.py",
}


def _read(rel: str, root: Path) -> str:
    return (root / rel).read_text()


def run(root: Path = REPO_ROOT, verbose: bool = False,
        out=sys.stdout) -> int:
    all_findings: List[Finding] = []

    # ---- lock discipline (two-pass: cross-file registries) ----------
    sources = []
    for rel in LOCK_FILES:
        path = root / rel
        if path.is_file():
            sources.append((rel, path.read_text()))
        else:
            all_findings.append(Finding(
                "VCL001", rel, 1,
                "lock-discipline file set names a missing file",
            ))
    raw = lockcheck.analyze_files(sources)
    by_file = {rel: [] for rel, _ in sources}
    for f in raw:
        by_file.setdefault(f.path, []).append(f)
    for rel, src in sources:
        all_findings.extend(finish(rel, src, by_file.get(rel, [])))

    # ---- hot-path hygiene ------------------------------------------
    for rel, entries in hotpath.HOT_REGISTRY.items():
        path = root / rel
        if not path.is_file():
            all_findings.append(Finding(
                "VCL001", rel, 1,
                "hot registry names a missing file",
            ))
            continue
        src = path.read_text()
        all_findings.extend(finish(rel, src, hotpath.analyze_file(
            rel, src, entries
        )))

    # ---- schema <-> ABI --------------------------------------------
    try:
        texts = {k: _read(rel, root) for k, rel in SCHEMA_FILES.items()}
    except OSError as err:
        all_findings.append(Finding(
            "VCL001", str(err.filename or "?"), 1,
            f"schema cross-check input unreadable: {err}",
        ))
    else:
        raw3 = schemacheck.analyze(
            SCHEMA_FILES["snapwire"], texts["snapwire"],
            SCHEMA_FILES["schema"], texts["schema"],
            SCHEMA_FILES["cc"], texts["cc"],
            SCHEMA_FILES["header"], texts["header"],
            SCHEMA_FILES["native"], texts["native"],
        )
        by_path = {}
        for f in raw3:
            by_path.setdefault(f.path, []).append(f)
        for key, rel in SCHEMA_FILES.items():
            all_findings.extend(finish(
                rel, texts[key], by_path.get(rel, [])
            ))

    # ---- persistent cycle-aggregate cache contract (VCL50x) --------
    agg_sources = []
    for rel in aggcheck.SCAN_FILES:
        path = root / rel
        if path.is_file():
            agg_sources.append((rel, path.read_text()))
        else:
            all_findings.append(Finding(
                "VCL001", rel, 1,
                "aggregate-cache scan set names a missing file",
            ))
    raw5 = aggcheck.analyze_files(agg_sources)
    by_file5 = {}
    for f in raw5:
        by_file5.setdefault(f.path, []).append(f)
    for rel, src in agg_sources:
        all_findings.extend(finish(rel, src, by_file5.get(rel, [])))

    # ---- metrics <-> docs drift ------------------------------------
    try:
        m_src = _read(METRICS_FILES["metrics"], root)
        d_src = _read(METRICS_FILES["doc"], root)
    except OSError as err:
        all_findings.append(Finding(
            "VCL001", str(err.filename or "?"), 1,
            f"metrics-drift input unreadable: {err}",
        ))
    else:
        raw4 = metricscheck.analyze(
            METRICS_FILES["metrics"], m_src, METRICS_FILES["doc"], d_src,
        )
        by_path4 = {}
        for f in raw4:
            by_path4.setdefault(f.path, []).append(f)
        for key, rel in METRICS_FILES.items():
            src4 = m_src if key == "metrics" else d_src
            all_findings.extend(finish(rel, src4, by_path4.get(rel, [])))

    # ---- anomaly catalog <-> docs drift ----------------------------
    anom_sources = []
    for rel in anomalycheck.SCAN_FILES:
        path = root / rel
        if path.is_file():
            anom_sources.append((rel, path.read_text()))
        else:
            all_findings.append(Finding(
                "VCL001", rel, 1,
                "anomaly-catalog scan set names a missing file",
            ))
    try:
        anom_doc = _read(ANOMALY_DOC, root)
    except OSError as err:
        all_findings.append(Finding(
            "VCL001", ANOMALY_DOC, 1,
            f"anomaly-catalog doc unreadable: {err}",
        ))
    else:
        raw6 = anomalycheck.analyze(anom_sources, ANOMALY_DOC, anom_doc)
        by_path6 = {}
        for f in raw6:
            by_path6.setdefault(f.path, []).append(f)
        for rel, src6 in anom_sources + [(ANOMALY_DOC, anom_doc)]:
            all_findings.extend(finish(
                rel, src6, by_path6.get(rel, [])
            ))

    # ---- report -----------------------------------------------------
    open_findings = [f for f in all_findings if not f.suppressed]
    suppressed = [f for f in all_findings if f.suppressed]
    for f in open_findings:
        print(f.render(), file=out)
    if verbose:
        for f in suppressed:
            print(f.render(), file=out)
    print(
        f"vclint: {len(open_findings)} finding(s), "
        f"{len(suppressed)} suppressed "
        f"({len(sources)} lock files, "
        f"{sum(len(v) for v in hotpath.HOT_REGISTRY.values())} hot "
        f"functions, {len(aggcheck.CACHE_REGISTRY)} keyed caches, "
        "1 schema/ABI surface, 1 metrics/docs surface, "
        "1 anomaly-catalog surface)",
        file=out,
    )
    return 1 if open_findings else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="vclint",
        description="repo-native static analysis: lock discipline, "
        "device hot-path hygiene, schema<->C++ ABI drift",
    )
    parser.add_argument("--root", default=str(REPO_ROOT),
                        help="repo root (default: auto-detected)")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="also print suppressed findings")
    args = parser.parse_args(argv)
    return run(Path(args.root), verbose=args.verbose)


if __name__ == "__main__":
    sys.exit(main())
