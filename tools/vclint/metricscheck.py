"""Metrics-drift analyzer (VCL4xx): docs/metrics.md ↔ the registry.

``docs/metrics.md`` documents every Prometheus series ``vtpu-service``
exposes.  Nothing kept the table honest: a new series added to
``volcano_tpu/metrics/metrics.py`` (or one renamed/removed) silently
rotted the docs.  This analyzer cross-checks the two 1:1:

- **VCL401** — a series constructed in the ``Metrics`` registry has no
  row in docs/metrics.md (reported at the constructor call).
- **VCL402** — a docs/metrics.md row names a series the registry does
  not construct (reported at the table row).
- **VCL403** — the documented kind (Histogram/Gauge/Counter) disagrees
  with the constructed series type.

Registry extraction is pure AST: every ``_Histogram(...)`` /
``_Gauge(...)`` / ``_Counter(...)`` call inside ``Metrics.__init__``
whose first argument is an f-string over the local ``ns`` prefix (or a
plain string literal) contributes one series.  Docs extraction matches
the markdown table rows ``| `name` | Kind | ...``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from . import astcache
from .findings import Finding

_SERIES_CTORS = {
    "_Histogram": "Histogram",
    "_Gauge": "Gauge",
    "_Counter": "Counter",
}

_DOC_ROW_RE = re.compile(
    r"^\|\s*`([A-Za-z_:][A-Za-z0-9_:]*)`\s*\|\s*(\w+)\s*\|"
)


def registry_series(metrics_path: str,
                    metrics_src: str) -> Tuple[Dict[str, Tuple[str, int]],
                                               List[Finding]]:
    """name -> (kind, lineno) for every series the Metrics registry
    constructs."""
    findings: List[Finding] = []
    try:
        tree = astcache.parse(metrics_src)
    except SyntaxError as err:
        return {}, [Finding(
            "VCL001", metrics_path, err.lineno or 1,
            f"metrics registry does not parse: {err.msg}",
        )]

    init = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Metrics":
            for sub in node.body:
                if (isinstance(sub, ast.FunctionDef)
                        and sub.name == "__init__"):
                    init = sub
            break
    if init is None:
        return {}, [Finding(
            "VCL001", metrics_path, 1,
            "metrics registry has no Metrics.__init__ to analyze",
        )]

    # Local string prefixes (``ns = "volcano"``).
    prefixes: Dict[str, str] = {}
    for node in ast.walk(init):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            prefixes[node.targets[0].id] = node.value.value

    def literal_name(arg) -> str:
        """Resolve a plain-string or {ns}-f-string series name; '' when
        the expression is not statically resolvable."""
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
        if isinstance(arg, ast.JoinedStr):
            parts = []
            for v in arg.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                elif (isinstance(v, ast.FormattedValue)
                        and isinstance(v.value, ast.Name)
                        and v.value.id in prefixes):
                    parts.append(prefixes[v.value.id])
                else:
                    return ""
            return "".join(parts)
        return ""

    series: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(init):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in _SERIES_CTORS):
            continue
        kind = _SERIES_CTORS[node.func.id]
        if not node.args:
            findings.append(Finding(
                "VCL001", metrics_path, node.lineno,
                f"{node.func.id}() constructed without a name argument",
            ))
            continue
        name = literal_name(node.args[0])
        if not name:
            findings.append(Finding(
                "VCL001", metrics_path, node.lineno,
                f"{node.func.id}() name is not statically resolvable "
                "(the metrics-drift check needs a literal)",
            ))
            continue
        series[name] = (kind, node.lineno)
    return series, findings


def documented_series(doc_src: str) -> Dict[str, Tuple[str, int]]:
    """name -> (kind, lineno) for every docs/metrics.md table row."""
    out: Dict[str, Tuple[str, int]] = {}
    for lineno, text in enumerate(doc_src.splitlines(), start=1):
        m = _DOC_ROW_RE.match(text.strip())
        if m:
            out[m.group(1)] = (m.group(2), lineno)
    return out


def analyze(metrics_path: str, metrics_src: str,
            doc_path: str, doc_src: str) -> List[Finding]:
    series, findings = registry_series(metrics_path, metrics_src)
    docs = documented_series(doc_src)
    for name, (kind, lineno) in sorted(series.items()):
        doc = docs.get(name)
        if doc is None:
            findings.append(Finding(
                "VCL401", metrics_path, lineno,
                f"series '{name}' is not documented in {doc_path}",
            ))
        elif doc[0] != kind:
            findings.append(Finding(
                "VCL403", doc_path, doc[1],
                f"series '{name}' documented as {doc[0]} but "
                f"constructed as {kind}",
            ))
    for name, (_kind, lineno) in sorted(docs.items()):
        if name not in series:
            findings.append(Finding(
                "VCL402", doc_path, lineno,
                f"documented series '{name}' does not exist in the "
                "Metrics registry",
            ))
    return findings
