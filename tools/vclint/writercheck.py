"""Writer-discipline analyzer (VCL70x): the mirror mutation quad.

The rebuild replaces Go's compiler-enforced invariants with a Python
convention that four PRs stacked up: every mutator of the mirror's
dynamic pod state must

1. **mark the dirty set** (``mark_pods_dirty`` / ``mark_pod_dirty`` /
   ``mark_pods_overflow``) so the incremental host lanes (ISSUE 8)
   refresh the touched rows,
2. **declare its conservation-audit flow** (``_audit_flow`` /
   ``flow_rows`` / the store-edge ``flow_added``/``flow_removed``, or
   ``reanchor`` for bulk re-derives) so the runtime auditor's
   double-entry census (ISSUE 13) reconciles,
3. **bump ``mutation_seq``** so the pipelined staleness guard and the
   cross-shard optimistic commit gate (ISSUE 16) see the move, and
4. **capture the pod journey** (``pod_event`` / ``pod_rows`` /
   ``pod_resync`` / the fast path's ``_journey_event`` /
   ``_journey_rows``) so the per-pod timeline (ISSUE 18) stays
   conserved — a writer that moves a pod's status without recording it
   is exactly the ``journey-orphan`` the endurance gate hunts.

Until now nothing checked the quad statically — a new writer missing
one leg is a silent lost-pod / stale-commit bug the endurance harness
only catches probabilistically.  This family turns the quad into a
registry-backed contract over the whole ``volcano_tpu/`` tree:

- **VCL701** — a registered writer's closure never marks the dirty set.
- **VCL702** — a registered writer's closure never declares an audit
  flow.
- **VCL703** — a registered writer's closure never bumps
  ``mutation_seq``.
- **VCL704** — a writer-shaped function (one that stores into the
  dynamic pod columns ``p_status``/``p_node``/``p_alive``, directly or
  through a one-level local alias) is neither registered in
  ``WRITER_REGISTRY`` nor annotated ``# vclint: writer-exempt --
  reason``.
- **VCL705** — a ``writer-exempt`` annotation without a ``-- reason``
  (unsuppressable, like VCL002).
- **VCL706** — a registered writer's closure never captures a pod
  journey event (the fourth leg).

Like aggcheck, each writer's evidence closure is the function itself
plus ONE level of locally-defined helpers it calls — key helpers like
``_audit_flow_rows`` count toward their callers.  A quad leg a writer
deliberately delegates (``_backfill``'s caller stamps the sequence;
``EvictState.evict`` relies on the owning action) is waived IN the
registry with the contract spelled out, so the delegation is a
reviewed, greppable decision rather than a silent hole.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from . import astcache
from .findings import Finding

# The mirror's dynamic pod columns: the state the triad protects.
# Static spec columns (p_prio, p_feat, affinity ranges, ...) are
# append-only per row and carry no cross-cycle mutation story.
DYN_COLS = {"p_status", "p_node", "p_alive"}

DIRTY_CALLS = {"mark_pods_dirty", "mark_pod_dirty", "mark_pods_overflow"}
AUDIT_CALLS = {"_audit_flow", "_audit_flow_rows", "flow", "flow_added",
               "flow_removed", "flow_rows", "reanchor"}
JOURNEY_CALLS = {"pod_event", "pod_rows", "pod_resync", "pod_restored",
                 "repeat_rows", "_journey_event", "_journey_rows"}
SEQ_ATTR = "mutation_seq"

# Every known mutator of the dynamic pod columns, with its triad
# contract.  A leg is either "self" (the evidence must appear in the
# writer's one-hop closure) or a waiver string documenting WHO
# satisfies the leg instead — the registry is the reviewed record of
# every delegation.
WRITER_REGISTRY: Dict[str, Dict[str, str]] = {
    # -- mirror store-edge writers (all four legs local) --------------
    "volcano_tpu/cache/mirror.py::StoreMirror.upsert_pod": {
        "dirty": "self", "audit": "self", "journey": "self",
        "seq": "self",
    },
    "volcano_tpu/cache/mirror.py::StoreMirror.remove_pod": {
        "dirty": "self", "audit": "self", "journey": "self",
        "seq": "self",
    },
    "volcano_tpu/cache/mirror.py::StoreMirror.set_pod_state": {
        "dirty": "self", "audit": "self", "journey": "self",
        "seq": "self",
    },
    "volcano_tpu/cache/mirror.py::StoreMirror.upsert_node": {
        "dirty": "self",
        "audit": "orphan adopt moves p_node only -- no status "
                 "transition, the per-status census is unchanged",
        "journey": "nodes carry no pod journey -- the orphan adopt "
                   "moves p_node only, no pod status transition to "
                   "record",
        "seq": "self",
    },
    "volcano_tpu/cache/mirror.py::StoreMirror.resync_status": {
        # Bulk re-derive: mark_pods_overflow voids the whole dirty
        # mask; reanchor voids the census compare; pod_resync adopts
        # the record truth journey-side.
        "dirty": "self", "audit": "self", "journey": "self",
        "seq": "self",
    },
    "volcano_tpu/cache/mirror.py::StoreMirror.maybe_compact": {
        "dirty": "compact_gen bump forces the aggregate consumer to "
                 "full-rebuild; the fresh zero mask is exactly right",
        "audit": "row renumbering preserves the per-status census "
                 "exactly (only tombstones drop); the attached auditor "
                 "survives the swap",
        "journey": "the journey is uid-keyed, so timelines survive row "
                   "renumbering untouched; the attached handle rides "
                   "the swap like the auditor's",
        "seq": "self",
    },
    # -- fast-path commit/unbind/backfill -----------------------------
    "volcano_tpu/fastpath.py::FastCycle._commit": {
        "dirty": "self", "audit": "self", "journey": "self",
        "seq": "self",
    },
    "volcano_tpu/fastpath.py::FastCycle._unbind_rows": {
        "dirty": "self", "audit": "self", "journey": "self",
        "seq": "self",
    },
    "volcano_tpu/fastpath.py::FastCycle._backfill": {
        "dirty": "self", "audit": "self", "journey": "self",
        "seq": "run_cycle_fast stamps mutation_seq when _backfill "
               "reports bound rows (disjoint rows from the solve, one "
               "stamp per action)",
    },
    # -- eviction machinery -------------------------------------------
    "volcano_tpu/fastpath_evict.py::EvictState.evict": {
        "dirty": "self", "audit": "self", "journey": "self",
        "seq": "the owning action stamps mutation_seq once per batch "
               "(fastpath action loop / whatif.commit_plan / "
               "FastEvictor flush)",
    },
    "volcano_tpu/fastpath_evict.py::EvictState.unevict": {
        "dirty": "self", "audit": "self", "journey": "self",
        "seq": "the owning action stamps mutation_seq once per batch "
               "(rollback inside the planner, or the flush revert "
               "path, which stamps after its unevicts)",
    },
    "volcano_tpu/whatif.py::commit_plan": {
        "dirty": "delegates to EvictState.evict, which marks each "
                 "victim row",
        "audit": "delegates to EvictState.evict, which declares the "
                 "running->releasing flow per victim",
        "journey": "self",
        "seq": "self",
    },
}

_EXEMPT_RE = re.compile(r"#\s*vclint:\s*writer-exempt"
                        r"(?:\s*--\s*(\S[^\n]*))?")


def _call_leaf(node: ast.Call) -> Optional[str]:
    return getattr(node.func, "id", None) or getattr(node.func, "attr",
                                                    None)


def _leg_facts(fn: ast.AST) -> Dict[str, bool]:
    """Which quad legs the function's own body satisfies."""
    dirty = audit = journey = seq = False
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call):
            leaf = _call_leaf(sub)
            if leaf in DIRTY_CALLS:
                dirty = True
            elif leaf in AUDIT_CALLS:
                audit = True
            elif leaf in JOURNEY_CALLS:
                journey = True
        elif isinstance(sub, ast.AugAssign):
            if isinstance(sub.target, ast.Attribute) \
                    and sub.target.attr == SEQ_ATTR:
                seq = True
        elif isinstance(sub, ast.Assign):
            for tgt in sub.targets:
                if isinstance(tgt, ast.Attribute) \
                        and tgt.attr == SEQ_ATTR:
                    seq = True
    return {"dirty": dirty, "audit": audit, "journey": journey,
            "seq": seq}


def _functions(tree: ast.Module):
    """(qualname, node) for top-level functions and class methods."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    yield f"{node.name}.{sub.name}", sub


def _local_facts(tree: ast.Module) -> Dict[str, Dict[str, bool]]:
    """Bare name -> leg facts, the one-hop helper table (aggcheck
    idiom: methods register under their bare name)."""
    out: Dict[str, Dict[str, bool]] = {}
    for qual, fn in _functions(tree):
        bare = qual.rsplit(".", 1)[-1]
        facts = _leg_facts(fn)
        prev = out.get(bare)
        if prev is None:
            out[bare] = facts
        else:
            for k, v in facts.items():
                prev[k] = prev[k] or v
    return out


def _closure_facts(fn: ast.AST,
                   local_facts: Dict[str, Dict[str, bool]]
                   ) -> Dict[str, bool]:
    """Leg facts of ``fn`` plus those of locally-defined helpers it
    calls (one hop)."""
    facts = _leg_facts(fn)
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call):
            leaf = _call_leaf(sub)
            helper = local_facts.get(leaf) if leaf else None
            if helper:
                for k, v in helper.items():
                    facts[k] = facts[k] or v
    return facts


def _dynamic_write_sites(fn: ast.AST) -> List[Tuple[str, int]]:
    """(column, line) for every store into a dynamic pod column inside
    ``fn`` — direct attribute subscripts/rebinds, plus subscript stores
    through a one-level local alias of a dynamic column."""
    aliases: Set[str] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Assign):
            base = sub.value
            if isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Attribute) and base.attr in DYN_COLS:
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name):
                        aliases.add(tgt.id)
    sites: List[Tuple[str, int]] = []
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Subscript) \
                and isinstance(sub.ctx, (ast.Store, ast.Del)):
            if isinstance(sub.value, ast.Attribute) \
                    and sub.value.attr in DYN_COLS:
                sites.append((sub.value.attr, sub.lineno))
            elif isinstance(sub.value, ast.Name) \
                    and sub.value.id in aliases:
                sites.append((sub.value.id, sub.lineno))
        elif isinstance(sub, ast.Attribute) \
                and isinstance(sub.ctx, ast.Store) \
                and sub.attr in DYN_COLS:
            sites.append((sub.attr, sub.lineno))
    return sites


def _exemption_for_def(lines: List[str], node
                       ) -> Tuple[bool, Optional[int]]:
    """(is_exempt, reasonless_line).  Looks at the def line, its
    decorators, and the line directly above (the # holds: idiom)."""
    candidates = [node.lineno]
    for dec in getattr(node, "decorator_list", []):
        candidates.append(dec.lineno)
    candidates.append(min(candidates) - 1)
    for lineno in candidates:
        if 1 <= lineno <= len(lines):
            m = _EXEMPT_RE.search(lines[lineno - 1])
            if m:
                if not (m.group(1) or "").strip():
                    return False, lineno
                return True, None
    return False, None


_CTOR_EXEMPT = {"__init__", "__new__", "__del__"}


def analyze_files(sources: Sequence[Tuple[str, str]]) -> List[Finding]:
    """``sources``: [(rel_path, text)] over the whole volcano_tpu tree.
    Returns raw findings (caller applies suppressions)."""
    findings: List[Finding] = []
    # qualified name ("rel::Class.method") -> (fn node, lines, facts)
    seen: Dict[str, Tuple[ast.AST, int]] = {}
    closure: Dict[str, Dict[str, bool]] = {}

    for rel, src in sources:
        try:
            tree = astcache.parse(src)
        except SyntaxError as err:
            findings.append(Finding(
                "VCL001", rel, err.lineno or 1,
                f"writercheck could not parse: {err.msg}",
            ))
            continue
        lines = src.splitlines()
        local_facts = _local_facts(tree)
        # Reasonless writer-exempt markers anywhere in the file: the
        # annotation is load-bearing, so a reasonless one is hygiene
        # breakage even when it attaches to nothing (VCL705).
        flagged_lines: Set[int] = set()
        for qual, fn in _functions(tree):
            key = f"{rel}::{qual}"
            seen[key] = (fn, fn.lineno)
            if key in WRITER_REGISTRY:
                closure[key] = _closure_facts(fn, local_facts)
                continue
            if fn.name in _CTOR_EXEMPT:
                # The object is not published yet (same exemption the
                # lock checker grants).
                continue
            sites = _dynamic_write_sites(fn)
            if not sites:
                continue
            exempt, reasonless = _exemption_for_def(lines, fn)
            if reasonless is not None:
                flagged_lines.add(reasonless)
                findings.append(Finding(
                    "VCL705", rel, reasonless,
                    "writer-exempt annotation carries no '-- reason' "
                    "justification",
                ))
                continue
            if exempt:
                continue
            col, lineno = sites[0]
            findings.append(Finding(
                "VCL704", rel, lineno,
                f"{qual} writes dynamic pod column '{col}' but is not "
                "registered in writercheck.WRITER_REGISTRY (declare "
                "its dirty-mark/audit-flow/mutation_seq triad) and "
                "carries no '# vclint: writer-exempt -- reason'",
            ))
        # VCL705 for reasonless markers not adjacent to any def.
        for lineno, text in enumerate(lines, start=1):
            m = _EXEMPT_RE.search(text)
            if m and not (m.group(1) or "").strip() \
                    and lineno not in flagged_lines:
                findings.append(Finding(
                    "VCL705", rel, lineno,
                    "writer-exempt annotation carries no '-- reason' "
                    "justification",
                ))

    # Registered writers: resolve and verify each "self" leg.
    leg_codes = {"dirty": "VCL701", "audit": "VCL702", "seq": "VCL703",
                 "journey": "VCL706"}
    leg_what = {
        "dirty": "never marks the dirty set "
                 "(mark_pods_dirty/mark_pod_dirty/mark_pods_overflow)",
        "audit": "never declares a conservation-audit flow "
                 "(_audit_flow/flow_rows/flow_added/flow_removed/"
                 "reanchor)",
        "seq": "never bumps mutation_seq",
        "journey": "never captures a pod-journey event "
                   "(pod_event/pod_rows/pod_resync/_journey_event/"
                   "_journey_rows)",
    }
    for key, legs in sorted(WRITER_REGISTRY.items()):
        entry = seen.get(key)
        if entry is None:
            rel = key.split("::", 1)[0]
            findings.append(Finding(
                "VCL001", rel, 1,
                f"writer registry names a missing function: {key}",
            ))
            continue
        _fn, lineno = entry
        facts = closure.get(key, {})
        for leg, policy in legs.items():
            if policy != "self":
                continue  # waived in-registry with a documented reason
            if not facts.get(leg):
                rel = key.split("::", 1)[0]
                qual = key.split("::", 1)[1]
                findings.append(Finding(
                    leg_codes[leg], rel, lineno,
                    f"registered writer {qual} {leg_what[leg]} in its "
                    "one-hop closure",
                ))
    return findings


def iter_py_files(root) -> Iterable[str]:
    """Relative paths of every volcano_tpu Python source under root."""
    base = root / "volcano_tpu"
    for path in sorted(base.rglob("*.py")):
        yield str(path.relative_to(root))
