"""Device hot-path hygiene analyzer (VCL2xx).

Operates on a registry of HOT FUNCTIONS — the solve/commit lanes whose
wall-clock is the scheduler's cycle time.  Three checks:

- **VCL201 implicit host sync**: values that dataflow from a device call
  (the jit entry points in ``DEVICE_FNS``, or attributes of their
  results) must not be consumed by host-forcing operations —
  ``float()``/``int()``/``bool()``/``len()``, ``np.asarray``-family
  calls, ``.item()``/``.tolist()``/``.any()``/``.all()``, iteration, or
  a bare ``if``/``while`` test.  The sanctioned sync is
  ``jax.device_get`` (its result is host memory and untainted);
  ``copy_to_host_async`` starts a transfer without blocking and is
  allowed.  Registry entries may also mark PARAMETERS as device-resident
  (``ops/devsnap.py`` planes arrive through arguments, not calls).
- **VCL202 use-after-donation**: a function jitted with
  ``donate_argnums`` invalidates the buffers at those positions; reading
  the same expression after the call is UB unless it was reassigned
  first (the idiom ``buf = donated_fn(buf, ...)`` is fine).
- **VCL203 jit retrace hazard**: every ``static_argnames`` entry must
  name a parameter of the jitted function, and call sites must not pass
  obviously-unhashable values (list/dict/set displays, ``np.*`` array
  results) as static arguments — both retrace (or crash) on every call.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import astcache
from .findings import Finding

# Call leaf names whose results are device-resident (taint sources).
DEVICE_FNS = {
    "solve_wave", "_solve_wave", "sharded_solve_wave",
    "sharded_solve_wave_cycle", "sharded_solve", "device_put",
    "_scatter_rows", "_scatter_cnt0", "_scatter_profile_tables",
    "solve_fn", "solve_async", "_coarse_shortlist", "frag_scores",
    # Mesh-native sharded solve (ISSUE 7): the shard-local ranking /
    # winner-reduction helper and the cycle's mesh dispatch both return
    # device values.
    "_topk_nodes", "_solve_mesh_dispatch",
    # Device-incremental lane (ISSUE 9): the static-plane producer,
    # the warm-shortlist kernel, and the DeviceIncremental services
    # that return their cached device results.
    "_static_planes", "_warm_shortlist", "static_planes", "shortlist",
    # Victim-selection kernel (ISSUE 11): eligibility/order/evictable
    # planes come back device-resident; jax.device_get is the one
    # sanctioned fetch before the host-side greedy runs.
    "victim_scores",
    # Hierarchical block->shard->global selection (ISSUE 12): the
    # merge helper returns device id planes.
    "_merge_block_cands",
    # Topology kernels (ISSUE 20): per-block gang-fit and fabric
    # fragmentation planes come back device-resident; jax.device_get
    # is the sanctioned fetch before host-side block selection.
    "gang_block_fit", "fabric_frag",
}

# Call leaf names that force a device->host sync when fed a device value.
SYNC_CALL_FNS = {
    "float", "int", "bool", "len", "asarray", "array",
    "ascontiguousarray", "_np", "bincount", "flatnonzero",
    "count_nonzero",
}

# Method names that force a sync on a device value.
SYNC_METHODS = {"item", "tolist", "any", "all", "min", "max", "sum",
                "astype"}

# The sanctioned fetch: results are host memory (clears taint).
SANCTIONED_FETCH = {"device_get", "block_until_ready"}

# Methods that are safe on a device value (no sync).
SAFE_METHODS = {"copy_to_host_async", "_replace", "addressable_shards"}


@dataclass
class HotEntry:
    """One registry row: a function to analyze.

    ``qualname`` is ``func`` or ``Class.method``; ``device_params`` lists
    dotted parameter paths that arrive device-resident (e.g.
    ``nodes.taint_bits``) — reads through them count as device values.
    """

    qualname: str
    device_params: Tuple[str, ...] = ()


# module path (repo-relative) -> entries.  This is the hot registry the
# tentpole prescribes; extend it when a new lane joins the cycle's
# critical path.
HOT_REGISTRY: Dict[str, List[HotEntry]] = {
    "volcano_tpu/fastpath.py": [
        HotEntry("FastCycle._allocate"),
        HotEntry("FastCycle._dispatch_async"),
        # Mesh dispatch lane (ISSUE 7): wraps sharded_solve_wave_cycle
        # on the cycle thread for both the sync and pipelined paths.
        HotEntry("FastCycle._solve_mesh_dispatch"),
        HotEntry("FastCycle._commit_inflight"),
        HotEntry("FastCycle._commit"),
        HotEntry("FastCycle._solve_inputs"),
        # Two-phase sub-lane/fallback bookkeeping sits between the
        # dispatch and the commit on every cycle.
        HotEntry("FastCycle._record_twophase_lanes"),
        HotEntry("FastCycle._count_shortlist_fb"),
        # Rebalance lane (ISSUE 5): the frag-score kernel dispatch and
        # the pipelined plan commit sit on the cycle thread; an
        # implicit sync here stalls every cycle the lane runs.  (The
        # what-if dispatch/commit bodies moved to volcano_tpu/whatif.py
        # in ISSUE 11 — see that file's entries below.)
        HotEntry("FastCycle._rebalance"),
        HotEntry("FastCycle._plan_rebalance"),
        HotEntry("FastCycle._commit_inflight_plan"),
        # Topology gates (ISSUE 20): the pregate + block-fit dispatch
        # run before every solve round, the post-solve gate on both
        # the sync and pipelined commit paths, the bias builder inside
        # _solve_inputs — all on the cycle thread.
        HotEntry("FastCycle._topo_block_fit"),
        HotEntry("FastCycle._topology_pregate"),
        HotEntry("FastCycle._topo_node_bias"),
        HotEntry("FastCycle._topology_gate"),
    ],
    "volcano_tpu/whatif.py": [
        # The what-if engine (ISSUE 11): hypothetical-solve dispatch,
        # pipelined plan commit, verdict + eviction commit, and the
        # preempt/reclaim planners that dispatch the victim kernel —
        # all on the cycle thread.
        HotEntry("whatif_inputs"),
        HotEntry("dispatch_plan"),
        HotEntry("commit_inflight_plan"),
        HotEntry("apply_plan"),
        HotEntry("commit_plan"),
        HotEntry("_plan_evict"),
        HotEntry("_plan_evict_gang"),
        HotEntry("run_evict_action"),
    ],
    "volcano_tpu/ops/victim.py": [
        # The jitted victim-selection kernel (a VCL201 taint source)
        # and the host-only greedy selection over its fetched planes.
        HotEntry("victim_scores"),
        HotEntry("select_victims"),
        HotEntry("fit_counts"),
        HotEntry("queue_shares"),
    ],
    "volcano_tpu/ops/wave.py": [
        # The devsnap planes (allocatable/max_tasks/ready/label_bits/
        # taint_bits) and the two-phase class planes arrive
        # device-resident from FastCycle._solve_inputs.
        HotEntry("solve_wave", device_params=(
            "nodes.allocatable", "nodes.max_tasks", "nodes.ready",
            "nodes.label_bits", "nodes.taint_bits",
            "node_classes.class_id", "node_classes.label_bits",
            "node_classes.taint_bits", "node_classes.ready",
        )),
    ],
    "volcano_tpu/ops/devincr.py": [
        # Device-incremental services (ISSUE 9): they juggle the
        # persistent device planes on the cycle thread — an implicit
        # sync here (fetching a cached plane back just to inspect it)
        # would stall every steady-state dispatch.
        HotEntry("DeviceIncremental.static_planes"),
        HotEntry("DeviceIncremental.shortlist"),
    ],
    "volcano_tpu/ops/devsnap.py": [
        HotEntry("DeviceSnapshot.node_planes"),
        HotEntry("DeviceSnapshot.class_tables"),
        # Mesh-aware placement helpers (ISSUE 7): commit planes/deltas
        # with the node-axis sharding on the cycle thread.
        HotEntry("DeviceSnapshot._put_plane"),
        HotEntry("DeviceSnapshot._put_delta"),
    ],
    "volcano_tpu/fastpath_incr.py": [
        # Incremental host-lane delta scatters (ISSUE 8): host-only
        # numpy by contract — registered so a device value leaking into
        # the derive refresh trips VCL201 instead of a per-cycle sync.
        HotEntry("CycleAggregates.refresh"),
        HotEntry("CycleAggregates._apply_delta"),
        HotEntry("CycleAggregates._scatter_side"),
        HotEntry("CycleAggregates.live_status_counts"),
        HotEntry("_build_aggregates"),
        HotEntry("rank_from_cols"),
        HotEntry("_lex_searchsorted"),
    ],
    "volcano_tpu/ops/nodeclass.py": [
        # Host-only by contract (numpy planes in, numpy planes out);
        # registered so an accidental device value reaching the class
        # builder trips VCL201 instead of a silent per-cycle sync.
        HotEntry("build_node_classes"),
    ],
    "volcano_tpu/ops/rebalance.py": [
        # The jitted frag-score kernel and the host-only greedy drain
        # selection (fetched numpy in by contract, like the class
        # builder above).
        HotEntry("frag_scores"),
        HotEntry("select_drain_set"),
    ],
    "volcano_tpu/ops/topology.py": [
        # The jitted block-fit/frag kernels (VCL201 taint sources) and
        # the host-only selection + bias builders over fetched planes.
        HotEntry("gang_block_fit"),
        HotEntry("fabric_frag"),
        HotEntry("select_block"),
        HotEntry("contig_bias"),
    ],
    "volcano_tpu/parallel/mesh.py": [
        HotEntry("shard_wave_inputs"),
        HotEntry("sharded_solve_wave_cycle"),
    ],
    "volcano_tpu/pipeline.py": [
        HotEntry("InflightSolve.fetch"),
        HotEntry("InflightPlan.fetch"),
    ],
}


# ---- VCL204: chunk-budget routing of full-N device temporaries ------
# A jitted function in these files that materializes a fresh device
# array whose LEADING dimension is a parameter's ``.shape[0]`` (a
# full-N node plane / full-P pod plane temporary) must appear in
# ``CHUNK_BUDGET_REGISTRY`` — registration records that its peak
# footprint is bounded by a reviewed chunk/budget mechanism (the
# lax.map profile streams and DOM_MM_MAX_MB size gate in ops/wave.py,
# the devsnap delta-scatter budget, pow2-padded fixed planes in the
# victim/rebalance kernels).  A NEW device fn declaring [N, *] planes
# trips VCL204 until it routes through the chunk-budget machinery and
# is registered here — the scale-tier guard: at 100k nodes x 1M pods
# an unbudgeted full-N temporary is the difference between fitting a
# chip and OOMing it.
BUDGET_FILES = {
    "volcano_tpu/ops/wave.py",
    "volcano_tpu/ops/devsnap.py",
    "volcano_tpu/ops/devincr.py",
    "volcano_tpu/ops/victim.py",
    "volcano_tpu/ops/rebalance.py",
    "volcano_tpu/ops/topology.py",
}
CHUNK_BUDGET_REGISTRY: Dict[str, Set[str]] = {
    "volcano_tpu/ops/wave.py": {
        # Profile axes stream through lax.map in COARSE_CHUNK rows;
        # the [N, D] domain one-hot sits behind the DOM_MM_MAX_MB
        # size gate; conflict buffers behind the keyspace gate.
        "_solve_wave", "_coarse_shortlist", "_warm_shortlist",
        "_static_planes",
    },
    "volcano_tpu/ops/victim.py": {
        # Planes are pow2-padded to the _solve_inputs buckets — fixed
        # [N]-bounded state, no [N, N]-class temporaries.
        "victim_scores",
    },
    "volcano_tpu/ops/rebalance.py": {
        "frag_scores",
    },
    "volcano_tpu/ops/topology.py": {
        # Node/profile/block axes are pow2-padded to the
        # _topo_block_fit buckets — fixed [N]- and [B, U]-bounded
        # state, no [N, N]-class temporaries.
        "gang_block_fit", "fabric_frag",
    },
}

_ARRAY_CREATE_FNS = {"zeros", "ones", "full", "empty"}


def _shape0_param_root(node: ast.AST, params: Set[str]):
    """The parameter name when ``node`` is ``<param>[.attrs...].shape[0]``
    (optionally wrapped in ``int(...)``), else None."""
    if isinstance(node, ast.Call) and _leaf_name(node.func) == "int" \
            and len(node.args) == 1:
        node = node.args[0]
    if not isinstance(node, ast.Subscript):
        return None
    sl = node.slice
    if isinstance(sl, ast.Index):  # pragma: no cover - py<3.9 form
        sl = sl.value
    if not (isinstance(sl, ast.Constant) and sl.value == 0):
        return None
    base = node.value
    if not (isinstance(base, ast.Attribute) and base.attr == "shape"):
        return None
    root = _dotted(base.value)
    if root is None:
        return None
    head = root.split(".")[0]
    return head if head in params else None


def check_chunk_budget(path: str, tree: ast.Module,
                       jits: Dict[str, JitInfo]) -> List[Finding]:
    """VCL204: unchunked full-N temporaries in unregistered jitted fns
    of the solve-lane files (see BUDGET_FILES)."""
    findings: List[Finding] = []
    if path not in BUDGET_FILES:
        return findings
    allowed = CHUNK_BUDGET_REGISTRY.get(path, set())
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        info = jits.get(fn.name)
        if info is None or fn.name in allowed:
            continue
        params = set(info.params)
        size_vars: Set[str] = set()
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                if _shape0_param_root(stmt.value, params) is not None:
                    size_vars.add(stmt.targets[0].id)
        if not size_vars:
            continue
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            if _leaf_name(call.func) not in _ARRAY_CREATE_FNS \
                    or not call.args:
                continue
            shape = call.args[0]
            first = None
            if isinstance(shape, (ast.Tuple, ast.List)) and shape.elts:
                first = shape.elts[0]
            elif isinstance(shape, ast.Name):
                first = shape
            if isinstance(first, ast.Name) and first.id in size_vars:
                findings.append(Finding(
                    "VCL204", path, call.lineno,
                    f"jitted fn {fn.name} materializes a full-"
                    f"{first.id} temporary outside the chunk-budget "
                    "registry (route it through the chunk/budget "
                    "machinery and register it in "
                    "CHUNK_BUDGET_REGISTRY)",
                ))
    return findings


@dataclass
class JitInfo:
    """A function jitted in the analyzed module."""

    name: str
    params: List[str]
    static_argnames: List[str]
    donate_argnums: List[int]
    line: int


def _leaf_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _expr_key(node: ast.AST) -> str:
    """Source-level key for an expression (ctx-insensitive, so a Store
    and a Load of the same subscript compare equal)."""
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - malformed synthetic nodes
        return ast.dump(node)


def _const_tuple(node: ast.AST) -> List[str]:
    """String elements of a tuple/list literal of constants."""
    out: List[str] = []
    if isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
    elif isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.append(node.value)
    return out


def _const_ints(node: ast.AST) -> List[int]:
    out: List[int] = []
    if isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.append(el.value)
    elif isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.append(node.value)
    return out


def collect_jits(tree: ast.Module) -> Dict[str, JitInfo]:
    """Find ``@jax.jit`` / ``@partial(jax.jit, ...)`` functions and their
    static/donate declarations."""
    out: Dict[str, JitInfo] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            static: List[str] = []
            donate: List[int] = []
            is_jit = False
            if isinstance(dec, ast.Call):
                callee = _dotted(dec.func) or ""
                if callee.endswith("partial") and dec.args:
                    inner = _dotted(dec.args[0]) or ""
                    if inner.endswith("jit"):
                        is_jit = True
                elif callee.endswith("jit"):
                    is_jit = True
                if is_jit:
                    for kw in dec.keywords:
                        if kw.arg == "static_argnames":
                            static = _const_tuple(kw.value)
                        elif kw.arg == "donate_argnums":
                            donate = _const_ints(kw.value)
            elif (_dotted(dec) or "").endswith("jit"):
                is_jit = True
            if is_jit:
                # Keyword-only params count: ``*, n_blocks`` statics
                # (ops/topology.gang_block_fit) are legal jit statics.
                params = [a.arg for a in
                          node.args.args + node.args.kwonlyargs]
                out[node.name] = JitInfo(
                    node.name, params, static, donate, node.lineno
                )
                break
    return out


def check_jit_declarations(path: str,
                           jits: Dict[str, JitInfo]) -> List[Finding]:
    """VCL203 structural check: static_argnames must name real params."""
    findings: List[Finding] = []
    for info in jits.values():
        for name in info.static_argnames:
            if name not in info.params:
                findings.append(Finding(
                    "VCL203", path, info.line,
                    f"static_argnames entry '{name}' is not a parameter "
                    f"of {info.name} (drifted signature retraces or "
                    "fails on every call)",
                ))
        for pos in info.donate_argnums:
            if pos >= len(info.params):
                findings.append(Finding(
                    "VCL203", path, info.line,
                    f"donate_argnums position {pos} is out of range for "
                    f"{info.name} ({len(info.params)} parameters)",
                ))
    return findings


class _HotChecker(ast.NodeVisitor):
    """Per-function taint walk (statement order = lexical order; the hot
    lanes are straight-line code with simple loops, which this models
    faithfully enough to be load-bearing)."""

    def __init__(self, path: str, entry: HotEntry,
                 jits: Dict[str, JitInfo], findings: List[Finding]):
        self.path = path
        self.entry = entry
        self.jits = jits
        self.findings = findings
        self.tainted: Set[str] = set(entry.device_params)
        self.donated: Dict[str, int] = {}  # dotted expr -> line donated

    # -------------------------------------------------------------- taint

    def _is_tainted(self, node: ast.AST) -> bool:
        # A call to a device fn used inline is tainted.
        if isinstance(node, ast.Call):
            leaf = _leaf_name(node.func)
            if leaf in DEVICE_FNS:
                return True
            if leaf in SANCTIONED_FETCH:
                return False
            return False
        if isinstance(node, ast.Subscript):
            return self._is_tainted(node.value)
        dotted = _dotted(node)
        if dotted is None:
            return False
        if dotted in self.tainted:
            return True
        # attribute of a tainted value (result.assigned)
        parts = dotted.split(".")
        for i in range(1, len(parts)):
            if ".".join(parts[:i]) in self.tainted:
                return True
        return False

    def _taint_targets(self, targets: Sequence[ast.AST]) -> None:
        for tgt in targets:
            if isinstance(tgt, ast.Tuple):
                self._taint_targets(tgt.elts)
                continue
            dotted = _dotted(tgt)
            if dotted is not None:
                self.tainted.add(dotted)

    def _untaint_targets(self, targets: Sequence[ast.AST]) -> None:
        for tgt in targets:
            if isinstance(tgt, ast.Tuple):
                self._untaint_targets(tgt.elts)
                continue
            dotted = _dotted(tgt)
            if dotted is not None:
                self.tainted.discard(dotted)
                self.donated.pop(dotted, None)

    # ------------------------------------------------------------- visits

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        value_tainted = self._is_tainted(node.value)
        # donation bookkeeping: donated exprs reassigned by this very
        # statement (buf = donated_fn(buf, ...)) are fresh again.
        if value_tainted:
            self._taint_targets(node.targets)
        else:
            self._untaint_targets(node.targets)
        for tgt in node.targets:
            dotted = _dotted(tgt) or (
                _dotted(tgt.value) if isinstance(tgt, ast.Subscript)
                else None
            )
            if dotted is not None:
                self.donated.pop(dotted, None)
            self.donated.pop(_expr_key(tgt), None)

    def visit_Call(self, node: ast.Call) -> None:
        leaf = _leaf_name(node.func)
        info = self.jits.get(leaf) if leaf else None
        # -------- VCL201: host-sync calls on tainted args
        if leaf in SYNC_CALL_FNS:
            for arg in node.args:
                if self._is_tainted(arg):
                    self.findings.append(Finding(
                        "VCL201", self.path, node.lineno,
                        f"{leaf}() on a device value forces an implicit "
                        "host sync in a hot function (fetch via "
                        "jax.device_get at the sanctioned sync point)",
                    ))
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in SYNC_METHODS
                and self._is_tainted(node.func.value)):
            self.findings.append(Finding(
                "VCL201", self.path, node.lineno,
                f".{node.func.attr}() on a device value forces an "
                "implicit host sync in a hot function",
            ))
        # -------- VCL203: unhashable static args at call sites
        if info is not None and info.static_argnames:
            for kw in node.keywords:
                if kw.arg in info.static_argnames:
                    bad = None
                    if isinstance(kw.value, (ast.List, ast.Dict, ast.Set)):
                        bad = "an unhashable literal"
                    elif isinstance(kw.value, ast.Call):
                        cleaf = _dotted(kw.value.func) or ""
                        if cleaf.startswith("np.") \
                                or cleaf.startswith("numpy."):
                            bad = "a numpy array expression"
                    if bad is not None:
                        self.findings.append(Finding(
                            "VCL203", self.path, node.lineno,
                            f"static argument '{kw.arg}' of {leaf} is "
                            f"{bad}: unhashable statics fail or retrace "
                            "every call",
                        ))
        self.generic_visit(node)
        # -------- VCL202: donation bookkeeping AFTER visiting children,
        # so the donated argument's own occurrence at the call site is
        # not flagged as a use-after-donation.
        if info is not None and info.donate_argnums:
            for pos in info.donate_argnums:
                if pos < len(node.args):
                    arg = node.args[pos]
                    key = _dotted(arg) or _expr_key(arg)
                    self.donated[key] = node.lineno

    def _check_use(self, node: ast.AST, what: str) -> None:
        key = _dotted(node) or (
            _expr_key(node) if isinstance(node, ast.Subscript) else None
        )
        if key is not None and key in self.donated:
            self.findings.append(Finding(
                "VCL202", self.path, node.lineno,
                f"{what} '{key}' after it was donated at line "
                f"{self.donated[key]} (donate_argnums invalidates the "
                "buffer)",
            ))

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.ctx, ast.Load):
            self._check_use(node, "read of")
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self._check_use(node, "read of")

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, ast.Load):
            self._check_use(node, "read of")
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        if self._is_tainted(node.iter):
            self.findings.append(Finding(
                "VCL201", self.path, node.lineno,
                "iteration over a device value forces a per-element "
                "host sync in a hot function",
            ))
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        if self._is_tainted(node.test):
            self.findings.append(Finding(
                "VCL201", self.path, node.lineno,
                "branching on a device value forces an implicit host "
                "sync in a hot function",
            ))
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        if self._is_tainted(node.test):
            self.findings.append(Finding(
                "VCL201", self.path, node.lineno,
                "looping on a device value forces an implicit host sync "
                "in a hot function",
            ))
        self.generic_visit(node)

    def visit_FunctionDef(self, node) -> None:
        return  # closures analyzed separately if registered

    visit_AsyncFunctionDef = visit_FunctionDef


def _find_function(tree: ast.Module, qualname: str):
    parts = qualname.split(".")
    scope = tree.body
    target = None
    for i, part in enumerate(parts):
        target = None
        for node in scope:
            if isinstance(node, ast.ClassDef) and node.name == part:
                scope = node.body
                target = node
                break
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == part:
                target = node
                break
        if target is None:
            return None
        if isinstance(target, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and i == len(parts) - 1:
            return target
    return target if isinstance(
        target, (ast.FunctionDef, ast.AsyncFunctionDef)) else None


def analyze_file(path: str, source: str,
                 entries: Sequence[HotEntry]) -> List[Finding]:
    """Run the hot-path checks for the registered functions of one file.
    Returns RAW findings (suppressions applied by the caller)."""
    findings: List[Finding] = []
    try:
        tree = astcache.parse(source)
    except SyntaxError as err:
        return [Finding("VCL001", path, err.lineno or 1,
                        f"file does not parse: {err.msg}")]
    jits = collect_jits(tree)
    findings.extend(check_jit_declarations(path, jits))
    findings.extend(check_chunk_budget(path, tree, jits))
    for entry in entries:
        fn = _find_function(tree, entry.qualname)
        if fn is None:
            findings.append(Finding(
                "VCL001", path, 1,
                f"hot-registry entry {entry.qualname} not found "
                "(registry drifted from the code)",
            ))
            continue
        checker = _HotChecker(path, entry, jits, findings)
        for stmt in fn.body:
            checker.visit(stmt)
    return findings
