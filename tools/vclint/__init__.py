"""vclint: repo-native static analysis for the volcano-tpu tree.

Three analyzer families (see docs/static_analysis.md):

- lock discipline over ``# guarded-by`` / ``# holds`` annotations
  (VCL1xx, ``tools/vclint/lockcheck.py``),
- device hot-path hygiene over a registry of solve/commit-lane
  functions (VCL2xx, ``tools/vclint/hotpath.py``),
- schema <-> C++ ABI drift between the Python wire codec / ctypes
  bindings and ``csrc/vcsnap.{h,cc}`` (VCL3xx,
  ``tools/vclint/schemacheck.py``).

Entry point: ``python -m tools.vclint`` (wired into
``hack/run-checks.sh``, the pre-snapshot green-gate).
"""

from .findings import Finding  # noqa: F401
