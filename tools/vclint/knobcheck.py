"""Tuning-knob drift analyzer (VCL71x): env reads ↔ docs/tuning.md.

Every ``VOLCANO_TPU_*`` environment variable the runtime reads is an
operator-facing knob: docs/tuning.md is its contract (default +
meaning), the same way docs/metrics.md is the metrics contract (VCL401)
and docs/observability.md the anomaly contract (VCL601).  ~50 getenv
sites had accumulated with nothing keeping the table honest; this
family closes the loop both ways:

- **VCL710** — a ``VOLCANO_TPU_*`` env read in ``volcano_tpu/`` has no
  row in docs/tuning.md (reported at the read site).
- **VCL711** — a documented knob row names a variable the runtime never
  reads (reported at the table row) — unless listed in ``DOC_ONLY``
  with the reason it lives outside the package.

Extraction is AST-based: a string literal matching ``VOLCANO_TPU_*``
counts as a *read* when it appears as a call argument (``environ.get``,
``getenv``, and the repo's ``_env_int``/``_env_on``-style wrappers), as
an ``environ[...]`` subscript, or in a membership test against the
environment.  Literals in other positions (dict keys for
``/debug/health``'s armed-verifier listing, docstrings) do not count.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Sequence, Tuple

from . import astcache
from .findings import Finding

_KNOB_RE = re.compile(r"^VOLCANO_TPU_[A-Z0-9_]+$")
_DOC_ROW_RE = re.compile(r"^\|\s*`(VOLCANO_TPU_[A-Z0-9_]+)`\s*\|")

# Documented knobs deliberately read OUTSIDE volcano_tpu/ — the reason
# is part of the entry so the allowance stays reviewable.
DOC_ONLY: Dict[str, str] = {
    # Read by tests/test_evict_oracle.py and hack/run-fuzz-nightly.sh:
    # the differential-fuzz seed count is a harness knob, not a runtime
    # one, but operators tune it from the same table.
    "VOLCANO_TPU_FUZZ_SEEDS": "fuzz-harness knob (tests/, hack/)",
}


def env_reads(path: str, src: str) -> Dict[str, int]:
    """knob -> first lineno for every env read in ``src``."""
    try:
        tree = astcache.parse(src)
    except SyntaxError:
        return {}
    out: Dict[str, int] = {}

    def _note(node: ast.AST) -> None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and _KNOB_RE.match(node.value):
            out.setdefault(node.value, node.lineno)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            for arg in node.args:
                _note(arg)
            for kw in node.keywords:
                _note(kw.value)
        elif isinstance(node, ast.Subscript):
            _note(node.slice)
        elif isinstance(node, ast.Compare):
            # "VOLCANO_TPU_X" in os.environ
            _note(node.left)
        elif isinstance(node, (ast.Tuple, ast.List)):
            # Knob tables: obs/slo.py's (lane, env-var) rows are read
            # through a loop, so the literal never appears as a direct
            # call argument.
            for elt in node.elts:
                _note(elt)
    return out


def documented_knobs(doc_src: str) -> Dict[str, int]:
    """knob -> first lineno for every docs/tuning.md table row."""
    out: Dict[str, int] = {}
    for lineno, text in enumerate(doc_src.splitlines(), start=1):
        m = _DOC_ROW_RE.match(text.strip())
        if m:
            out.setdefault(m.group(1), lineno)
    return out


def analyze(sources: Sequence[Tuple[str, str]], doc_path: str,
            doc_src: str) -> List[Finding]:
    findings: List[Finding] = []
    read: Dict[str, Tuple[str, int]] = {}
    for path, src in sources:
        for knob, lineno in env_reads(path, src).items():
            read.setdefault(knob, (path, lineno))
    docs = documented_knobs(doc_src)
    for knob, (path, lineno) in sorted(read.items()):
        if knob not in docs:
            findings.append(Finding(
                "VCL710", path, lineno,
                f"env knob '{knob}' is read here but has no row in "
                f"{doc_path}",
            ))
    for knob, lineno in sorted(docs.items()):
        if knob not in read and knob not in DOC_ONLY:
            findings.append(Finding(
                "VCL711", doc_path, lineno,
                f"documented knob '{knob}' is never read by "
                "volcano_tpu/ (stale row, or add a DOC_ONLY entry "
                "with the out-of-package reader)",
            ))
    return findings
