"""Shared AST parse cache (ISSUE 17 gate-speed satellite).

The gate used to re-parse the same sources once per analyzer family —
``fastpath.py`` alone is ~4k lines and sits in the lock, hot-path,
aggregate-cache, and writer-discipline file sets.  Every family now
parses through this memo, so each distinct source text is parsed
exactly once per process no matter how many families (or ``--jobs``
workers) consume it.

Trees are treated as immutable by every consumer (pure ``ast.walk``
reads), so sharing one tree across concurrently-running families is
safe.  Keyed by the source text itself: the repo's file reads are
already deduplicated by the driver, and fixture tests feed small
synthetic strings, so the memo stays tiny; a cap guards pathological
long-lived processes.
"""

from __future__ import annotations

import ast
import threading
from typing import Dict

_MAX_ENTRIES = 512

_lock = threading.Lock()
_memo: Dict[str, ast.Module] = {}


def parse(source: str) -> ast.Module:
    """``ast.parse`` with memoization.  Raises SyntaxError like
    ``ast.parse`` (failures are never cached)."""
    with _lock:
        tree = _memo.get(source)
    if tree is not None:
        return tree
    tree = ast.parse(source)
    with _lock:
        if len(_memo) >= _MAX_ENTRIES:
            _memo.clear()
        _memo[source] = tree
    return tree
