"""Cluster simulator: the framework's "kind".

The reference tests multi-node behavior on kind (Kubernetes-in-Docker,
hack/run-e2e-kind.sh); this simulator plays the kubelet's role against the
in-memory store so full job lifecycles (submit -> enqueue -> bind -> run ->
complete/fail -> policies) can be exercised hermetically at any scale
(SURVEY.md 4.3).
"""

from __future__ import annotations

import copy
import logging
from typing import Callable, Dict, Optional

from .api import Pod, PodPhase
from .cache import ClusterStore

log = logging.getLogger(__name__)


class ClusterSimulator:
    """Steps pod lifecycles: bound pods start running; deleting pods
    terminate (through an optional Terminating grace window); optional
    completion/failure injection.

    ``grace_steps``: eviction grace period in kubelet ticks.  A deleting
    pod passes through Terminating for that many steps before the delete
    lands and its capacity frees — the real capacity-not-yet-free window
    migration e2e must exercise (a rebalance eviction's node stays
    charged until termination completes, exactly as a kubelet honors
    terminationGracePeriodSeconds).  0 (the default) keeps the historic
    instant-delete behavior.
    """

    def __init__(self, store: ClusterStore, grace_steps: int = 0):
        self.store = store
        self.grace_steps = max(int(grace_steps), 0)
        # uid -> remaining Terminating ticks for deleting pods.
        self._terminating: Dict[str, int] = {}

    def step(
        self,
        complete: Optional[Callable[[Pod], Optional[int]]] = None,
    ) -> Dict[str, int]:
        """One kubelet tick.

        ``complete(pod)`` may return an exit code for running pods: 0 ->
        Succeeded, nonzero -> Failed, None -> keep running.
        Returns counts of transitions applied (``terminating`` counts
        deleting pods still inside their grace window this tick).
        """
        started = finished = deleted = terminating = 0
        # Snapshot under the store lock (`pods` is a guarded attribute
        # — the async bind dispatcher mutates it concurrently), then
        # step unlocked: the per-pod transitions below go through the
        # store's public API, which takes the lock itself.
        with self.store._lock:
            pods = list(self.store.pods.values())
        if self._terminating:  # skip the O(pods) set on the common path
            live = {p.uid for p in pods}
            for uid in list(self._terminating):
                if uid not in live:  # deleted out-of-band
                    del self._terminating[uid]
        for pod in pods:
            if pod.deleting:
                left = self._terminating.get(pod.uid)
                if left is None:
                    left = self.grace_steps
                if left > 0:
                    # Still Terminating: capacity stays charged.
                    self._terminating[pod.uid] = left - 1
                    terminating += 1
                    continue
                # Termination completes: the pod object goes away.
                self._terminating.pop(pod.uid, None)
                self.store.delete_pod(pod)
                deleted += 1
                continue
            if pod.phase == PodPhase.Pending and pod.node_name:
                updated = copy.copy(pod)
                updated.phase = PodPhase.Running
                self.store.update_pod(updated)
                started += 1
                continue
            if pod.phase == PodPhase.Running and complete is not None:
                code = complete(pod)
                if code is None:
                    continue
                updated = copy.copy(pod)
                updated.exit_code = int(code)
                updated.phase = (
                    PodPhase.Succeeded if code == 0 else PodPhase.Failed
                )
                self.store.update_pod(updated)
                finished += 1
        return {
            "started": started,
            "finished": finished,
            "deleted": deleted,
            "terminating": terminating,
        }

    @staticmethod
    def priority_tier_workload(store: ClusterStore, workers: int = 4,
                               node_cpu: str = "4", batch_cpu: str = "4",
                               serving_tasks: int = 2,
                               serving_cpu: str = "4",
                               serving_priority: int = 1000,
                               batch_priority: int = 10,
                               namespace: str = "default"
                               ) -> Dict[str, object]:
        """Populate ``store`` with the priority-tiered production mix
        the preempt acceptance e2e needs (ISSUE 11,
        docs/preempt_reclaim.md): ``workers`` nodes each fully occupied
        by a Running low-priority batch pod (one single-member PodGroup
        per node, so per-group disruption budgets bite), plus a Pending
        high-priority serving gang of ``serving_tasks`` whole-node
        tasks that cannot bind until batch capacity is preempted.
        Driven with ``ClusterSimulator(store, grace_steps=N)`` the
        evicted batch pods pass through Terminating, so the serving
        gang exercises the real capacity-not-yet-free preemption
        window before it binds.

        Returns ``{"serving_group", "batch_groups", "nodes"}`` name
        lists for assertions."""
        from .api import (
            GROUP_NAME_ANNOTATION,
            Node,
            Pod,
            PodGroup,
            PodGroupPhase,
            PriorityClass,
        )

        store.add_priority_class(
            PriorityClass(name="tier-serving", value=serving_priority))
        store.add_priority_class(
            PriorityClass(name="tier-batch", value=batch_priority))
        nodes = []
        for i in range(workers):
            name = f"tier-n{i}"
            store.add_node(Node(name=name, allocatable={
                "cpu": node_cpu, "memory": "16Gi", "pods": 110}))
            nodes.append(name)
        batch_groups = []
        for i in range(workers):
            gname = f"batch{i}"
            store.add_pod_group(PodGroup(
                name=gname, namespace=namespace, min_member=1,
                priority_class="tier-batch"))
            store.pod_groups[
                f"{namespace}/{gname}"
            ].status.phase = PodGroupPhase.Running.value
            store.add_pod(Pod(
                name=f"batch-{i}", namespace=namespace,
                annotations={GROUP_NAME_ANNOTATION: gname},
                containers=[{"cpu": batch_cpu, "memory": "1Gi"}],
                phase=PodPhase.Running, node_name=f"tier-n{i}",
                priority=batch_priority,
            ))
            batch_groups.append(f"{namespace}/{gname}")
        store.add_pod_group(PodGroup(
            name="serving", namespace=namespace,
            min_member=serving_tasks, priority_class="tier-serving"))
        for i in range(serving_tasks):
            store.add_pod(Pod(
                name=f"serving-{i}", namespace=namespace,
                annotations={GROUP_NAME_ANNOTATION: "serving"},
                containers=[{"cpu": serving_cpu, "memory": "1Gi"}],
                priority=serving_priority,
            ))
        return {
            "serving_group": f"{namespace}/serving",
            "batch_groups": batch_groups,
            "nodes": nodes,
        }

    def fail_pod(self, uid: str, exit_code: int = 1) -> None:
        """Inject a pod failure (fault injection; the reference's e2e kills
        pods to trigger policies, job_error_handling.go:145-276)."""
        with self.store._lock:
            pod = self.store.pods[uid]
        updated = copy.copy(pod)
        updated.exit_code = exit_code
        updated.phase = PodPhase.Failed
        self.store.update_pod(updated)

    def fail_node(self, name: str) -> None:
        """Mark a node NotReady (device-unhealthy / node-failure injection).

        The update flows through the store so the job controller raises
        DeviceUnhealthy requests for resident pods; the pods themselves then
        fail on the next tick (the kubelet on a dead device cannot report
        success)."""
        node_info = self.store.nodes.get(name)
        if node_info is None or node_info.node is None:
            return
        spec = node_info.node
        spec.ready = False
        self.store.update_node(spec)
        with self.store._lock:
            resident = list(self.store.pods.values())
        for pod in resident:
            if pod.node_name == name and pod.phase == PodPhase.Running:
                self.fail_pod(pod.uid, exit_code=255)
