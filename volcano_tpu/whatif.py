"""The what-if engine: hypothetical solves, proven atomically.

Rebalance (ISSUE 5) introduced the expensive trick this module now owns
for every eviction-shaped action: patch the cycle arrays to a
hypothetical cluster, run the *exact* allocate jit over it (profile
dedup, devsnap planes, two-phase shortlists, mesh sharding all intact),
judge the verdict, and commit — evictions through the
``fastpath_evict`` machinery, restores through the shared
``MigrationLedger`` — only when the solve PROVED the outcome.  A plan
mutates nothing until commit, so rejecting (or stale-voiding) one is
free.

Three actions ride the engine (docs/preempt_reclaim.md):

- ``rebalance`` — drain fragmented nodes; victims re-enter the solve
  and must all re-place (capacity-neutral defragmentation).
- ``preempt`` — a starved higher-priority gang drains same-queue
  lower-priority victims (``ops/victim.py`` selects them under
  disruption budgets); victims do NOT re-enter the solve — they are
  restored as Pending by the ledger and wait their turn (zero lost
  pods unconditionally).
- ``reclaim`` — a gang in an under-deserved queue drains victims from
  OTHER queues that are ``Reclaimable`` and over their deserved share,
  never below deserved.

Pipelined stores park the what-if as ``pipeline.InflightPlan`` and
commit at the next cycle's top behind the staleness guard: ANY
``mutation_seq``/``epoch``/``compact_gen``/node-count drift voids the
plan wholesale.  The engine is mesh-aware — the hypothetical patches
touch only the per-cycle host planes (idle / ntasks / resident /
queue / readiness vectors), never the device-resident devsnap planes,
so the sharded dispatch path (``FastCycle._solve_mesh_dispatch``)
carries it unchanged.  Single-connection remote-solver deployments keep
the engine off (the plan solve would contend with the allocate lane on
the one strict request/reply connection); preempt/reclaim then fall
back to the host walk.  A solver *pool* (ISSUE 15,
``solver_pool.SolverPool``) lifts that: plan solves offload to an idle
non-primary replica and overlap the allocate lane — the staleness
guard and ``InflightPlan`` commit path are unchanged, and a lost plan
reply voids the plan (it mutated nothing; outcome ``lost-reply``).

Every function here runs on the cycle thread inside ``FastCycle.run``
(under ``run_cycle_fast``'s store lock).
"""

from __future__ import annotations

import logging
import os
from typing import Dict, NamedTuple, Optional, Tuple

import numpy as np

from .metrics import metrics

log = logging.getLogger(__name__)

F = np.float32
I = np.int32

ACTIONS = ("preempt", "reclaim", "rebalance")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def evict_device_enabled() -> bool:
    """Master switch for the device-native preempt/reclaim lanes.
    ``VOLCANO_TPU_EVICT_DEVICE=0`` restores the host-side victim walk
    (``fastpath_evict``) bind-for-bind."""
    return os.environ.get("VOLCANO_TPU_EVICT_DEVICE", "1") != "0"


def evict_cap() -> int:
    """Max victims one preempt/reclaim wave may take."""
    return max(1, _env_int("VOLCANO_TPU_EVICT_CAP", 64))


def whatif_offload_on(remote) -> bool:
    """True when ``remote`` is a solver pool with an idle non-primary
    replica that can take a plan-proving solve right now (ISSUE 15).
    A plain ``RemoteSolver`` has no offload capacity by construction."""
    avail = getattr(remote, "whatif_replica_available", None)
    return avail is not None and bool(avail())


def evict_device_on(store) -> bool:
    """True when this store's preempt/reclaim run the plan-prove-commit
    device lane.  Single-connection remote-solver deployments keep the
    host walk (the plan solve would contend for the one connection); a
    solver pool with an idle non-primary replica offloads the plan
    solve there instead; a mesh is fine (the engine dispatches through
    the sharded path)."""
    if not evict_device_enabled():
        return False
    remote = getattr(store, "remote_solver", None)
    return remote is None or whatif_offload_on(remote)


class WhatIfPlan(NamedTuple):
    """One hypothetical eviction wave, action-agnostic.

    ``resolve_victims`` decides the solve's task set: True re-enters the
    victims as pending rows alongside the gang (rebalance — every
    victim must re-place), False solves the gang alone (preempt /
    reclaim — victims restore as Pending and wait)."""

    action: str                  # "preempt" | "reclaim" | "rebalance"
    gang_job: int                # mirror job row of the starved gang
    gang_uid: str                # its PodGroup uid (events / ledger)
    gang_rows: np.ndarray        # [G] pending mirror rows entering the solve
    victim_rows: np.ndarray      # [V] running mirror rows to evict
    victim_jobs: np.ndarray      # [V] mirror job rows of the victims
    drain_nodes: np.ndarray      # [K] node rows drained (rebalance; else [])
    need: int                    # gang tasks outstanding at plan time
    frag_before: float           # mean frag score (rebalance; else 0.0)
    budgets: Dict[str, int]      # group uid -> victims this plan takes
    resolve_victims: bool        # victims re-enter the what-if solve


# --------------------------------------------------------------- ordering


def plan_task_order(plan: WhatIfPlan):
    """(solve_jobs, task_rows, victims-in-solve-order) for a plan's
    what-if solve: the starved gang's pending rows first (it is the
    point of the wave), then — only when the plan re-solves its victims
    — the victims job-contiguously, the order the assignment vector is
    aligned to."""
    if not plan.resolve_victims or not len(plan.victim_rows):
        return ([plan.gang_job], plan.gang_rows.astype(np.int64),
                np.zeros(0, np.int64))
    vorder = np.argsort(plan.victim_jobs, kind="stable")
    vr = plan.victim_rows[vorder]
    task_rows = np.concatenate(
        [plan.gang_rows, vr]).astype(np.int64)
    solve_jobs = [plan.gang_job]
    seen = {plan.gang_job}
    for j in plan.victim_jobs[vorder].tolist():
        if j not in seen:
            seen.add(j)
            solve_jobs.append(int(j))
    return solve_jobs, task_rows, vr


# ----------------------------------------------------------- input patch


# holds: _lock
def whatif_inputs(cyc, plan: WhatIfPlan):
    """Solver inputs for the hypothetically drained cluster: the
    drained victims' capacity returns to idle, their rows leave the
    resident set (ports / affinity counts / task slots), their jobs'
    ready counts drop and their queues' allocations shrink by the
    drained members.  When the plan re-solves its victims (rebalance),
    queue-deserved gating is lifted for the VICTIM queues only — a
    victim's re-placement frees exactly what it claims, so
    re-arbitrating its share would veto a capacity-neutral move; the
    starved gang's placement is a genuinely new allocation and keeps
    the live lane's gating either way (a share-capped gang must not
    trigger an eviction wave the live allocate would then veto).
    Everything else (devsnap planes, two-phase shortlists, profile
    dedup) rides ``FastCycle._solve_inputs`` unchanged, so the plan
    solve hits the same jit as the live allocate lane."""
    m = cyc.m
    # Deferred aggregate scatters must land on the REAL q_alloc before
    # it is copied, or they would be lost to the patch.
    cyc._flush_aggr()
    solve_jobs, task_rows, vr = plan_task_order(plan)
    vnode = m.p_node[:cyc.Pn][plan.victim_rows].astype(np.int64)
    er, si, v = m.c_req.gather(plan.victim_rows)
    idle_patch = cyc.n_idle.copy()
    np.add.at(idle_patch, (vnode[er], si), v)
    ntasks_patch = cyc.n_ntasks - np.bincount(
        vnode, minlength=cyc.Nn).astype(I)
    ready_patch = cyc.j_ready_base.copy()
    np.add.at(ready_patch, plan.victim_jobs, -1)
    resident_patch = cyc.resident.copy()
    resident_patch[plan.victim_rows] = False
    deserved_patch = cyc.q_deserved.copy()
    q_alloc_patch = cyc.q_alloc.copy()
    vq = cyc.q_of_job[plan.victim_jobs]
    vq_ok = vq >= 0
    if vq_ok.any():
        if plan.resolve_victims:
            deserved_patch[np.unique(vq[vq_ok])] = 3.0e38
        # Un-charge the drained victims so a gang sharing a victim's
        # queue is not double-gated against allocations the eviction
        # itself returns (and, for rebalance, that the solve will
        # re-charge on re-placement).
        er_q = vq_ok[er]
        np.add.at(q_alloc_patch,
                  (vq[er][er_q], si[er_q]), -v[er_q])
    saved = (cyc.n_idle, cyc.n_ntasks, cyc.j_ready_base,
             cyc.resident, cyc.q_deserved, cyc.q_alloc)
    (cyc.n_idle, cyc.n_ntasks, cyc.j_ready_base, cyc.resident,
     cyc.q_deserved, cyc.q_alloc) = (
        idle_patch, ntasks_patch, ready_patch, resident_patch,
        deserved_patch, q_alloc_patch)
    # The what-if's encode must not POLLUTE the allocate lane's encode
    # cache: its task rows differ, so caching its entry would (a) evict
    # the live entry and (b) bump the profile generation — needlessly
    # invalidating the device-incremental static planes and warm
    # candidates (ISSUE 9) on every cycle that plans a wave.
    # Save/restore both slots; the what-if entry would never hit for
    # the live lane anyway.
    store = cyc.store
    saved_cache = store._encode_cache
    saved_gen = getattr(store, "_encode_gen", 0)
    try:
        inputs, pid, profiles, ncls = cyc._solve_inputs(
            solve_jobs, task_rows, slim=True)
    finally:
        (cyc.n_idle, cyc.n_ntasks, cyc.j_ready_base,
         cyc.resident, cyc.q_deserved, cyc.q_alloc) = saved
        store._encode_cache = saved_cache
        store._encode_gen = saved_gen
    return inputs, pid, profiles, ncls


# ------------------------------------------------------ dispatch / commit


# holds: _lock
def dispatch_plan(cyc, plan: WhatIfPlan) -> None:
    """Run (or pipeline) the plan's what-if solve.  Mesh stores ride
    ``FastCycle._solve_mesh_dispatch`` — the patch touches only host
    planes, so the sharded devsnap path carries the hypothetical
    cluster unchanged."""
    from .ops.wave import solve_wave
    from .parallel.mesh import mesh_from_env

    m = cyc.m
    store = cyc.store
    # No lanes= here: the action:<name> span already accumulates the
    # lane seconds; a second accumulation would double-count.
    with cyc.tracer.span(
            "whatif_solve", cat="whatif",
            args={"action": plan.action, "gang": plan.gang_uid,
                  "victims": len(plan.victim_rows),
                  "need": plan.need}):
        inputs, pid, profiles, ncls = whatif_inputs(cyc, plan)
        remote = getattr(store, "remote_solver", None)
        if remote is not None:
            # What-if offload (ISSUE 15): the plan solve ships to an
            # idle non-primary pool replica, overlapping the allocate
            # lane's in-flight solve instead of contending for the
            # single connection.  The child rebuilds node classes from
            # the frame; plan frames carry no devincr section.
            try:
                payload = remote.solve_whatif_async(inputs, pid,
                                                    profiles)
            except (OSError, ConnectionError, ValueError,
                    RuntimeError):
                # Every offload candidate died between the lane's
                # availability gate and this dispatch: the plan
                # mutated nothing — void it, let the pool's health
                # probes heal, and re-plan next cycle.
                log.warning(
                    "what-if offload dispatch failed; plan voided "
                    "(action=%s gang=%s)", plan.action, plan.gang_uid,
                    exc_info=True,
                )
                count_plan(cyc, plan.action, "lost-reply",
                           gang=plan.gang_uid,
                           victims=len(plan.victim_rows))
                return
            if cyc._pipeline_on:
                from .pipeline import InflightPlan

                store._solve_seq += 1
                store._inflight_plan = InflightPlan(
                    payload, plan, m.mutation_seq, m.epoch,
                    m.compact_gen, cyc.Nn, plan_id=store._solve_seq,
                    kind="remote",
                )
                return
            try:
                res = payload.fetch()
            except (OSError, ConnectionError, ValueError):
                # Lost plan reply (replica died mid-solve): the plan
                # mutated nothing — drop it and re-plan next cycle.
                count_plan(cyc, plan.action, "lost-reply",
                           gang=plan.gang_uid,
                           victims=len(plan.victim_rows))
                return
            assigned = np.asarray(res.assigned)
            never_ready = np.asarray(res.never_ready)
        else:
            mesh = mesh_from_env(store)
            if mesh is not None:
                payload = cyc._solve_mesh_dispatch(
                    mesh, inputs, pid, profiles, ncls)
            else:
                payload = solve_wave(*inputs, pid=pid,
                                     profiles=profiles,
                                     taint_any=cyc._taint_any,
                                     node_classes=ncls)
            if cyc._pipeline_on:
                from .pipeline import InflightPlan

                for arr in (payload.assigned, payload.never_ready):
                    try:
                        arr.copy_to_host_async()
                    except AttributeError:
                        pass
                store._solve_seq += 1
                store._inflight_plan = InflightPlan(
                    payload, plan, m.mutation_seq, m.epoch,
                    m.compact_gen, cyc.Nn, plan_id=store._solve_seq,
                )
                return
            import jax

            assigned, never_ready = jax.device_get(
                (payload.assigned, payload.never_ready)
            )
    apply_plan(cyc, plan, np.asarray(assigned),
               np.asarray(never_ready))


# holds: _lock
def commit_inflight_plan(cyc) -> None:
    """Land (or void) the previous cycle's pipelined what-if plan.  A
    whole-cluster what-if has no per-row salvage, so ANY drift —
    mutation counter, node-table epoch, compaction generation, node
    count — voids the plan wholesale (it mutated nothing; the planner
    re-forms against fresh state)."""
    from .pipeline import take_inflight_plan

    inflight = take_inflight_plan(cyc.store)
    if inflight is None:
        return
    m = cyc.m
    plan = inflight.plan
    with cyc.tracer.span(
            "whatif_commit", cat="whatif", lanes=cyc.lanes,
            lane=plan.action,
            args={"plan_id": inflight.plan_id,
                  "action": plan.action, "gang": plan.gang_uid,
                  "victims": len(plan.victim_rows)}):
        if (m.mutation_seq != inflight.mutation_seq
                or m.epoch != inflight.epoch
                or m.compact_gen != inflight.compact_gen
                or cyc.Nn != inflight.n_nodes):
            inflight.abandon()
            count_plan(cyc, plan.action, "stale-voided",
                       gang=plan.gang_uid,
                       victims=len(plan.victim_rows))
            return
        try:
            assigned, never_ready = inflight.fetch()
        except (OSError, ConnectionError, ValueError):
            if inflight.kind != "remote":
                raise
            # The offloaded plan solve's reply died with its replica
            # (ISSUE 15).  A plan mutates nothing until commit, so
            # this is free: drop it and let the planner re-form
            # against fresh state — the pool's health scoring routes
            # the next offload to a live replica.
            log.warning(
                "offloaded what-if plan reply lost; plan voided "
                "(action=%s gang=%s)", plan.action, plan.gang_uid,
                exc_info=True,
            )
            count_plan(cyc, plan.action, "lost-reply",
                       gang=plan.gang_uid,
                       victims=len(plan.victim_rows))
            return
        apply_plan(cyc, plan, assigned, never_ready)


# holds: _lock
def apply_plan(cyc, plan: WhatIfPlan, assigned: np.ndarray,
               never_ready: np.ndarray) -> None:
    """Judge the what-if verdict and commit iff the solve proved the
    wave's point: the gang reaches ready, and — when the plan re-solves
    its victims — every victim re-places and the gain clears the
    rebalance threshold."""
    from .actions.rebalance import min_gain

    m = cyc.m
    _, task_rows, vr_sorted = plan_task_order(plan)
    assigned = assigned[:len(task_rows)].astype(np.int64)
    G = len(plan.gang_rows)
    # The gang must still be the pending work the plan targeted (a
    # pipelined solve landing just above may have bound, or a delete
    # removed rows during the overlap).
    gr = plan.gang_rows
    from .api import TaskStatus

    st_pending = int(TaskStatus.Pending)
    if not bool((m.p_alive[gr]
                 & (m.p_status[gr] == st_pending)).all()):
        count_plan(cyc, plan.action, "stale-voided",
                   gang=plan.gang_uid,
                   victims=len(plan.victim_rows))
        return
    gang_assigned = int((assigned[:G] >= 0).sum())
    victims_ok = (bool((assigned[G:] >= 0).all())
                  if len(assigned) > G else True)
    gang_ready = (
        not bool(never_ready[0])
        and cyc.j_ready_base[plan.gang_job] + gang_assigned
        >= int(m.j_minav[plan.gang_job])
    )
    floor = min_gain() if plan.action == "rebalance" else 1
    if not (victims_ok and gang_ready and gang_assigned >= floor):
        count_plan(cyc, plan.action, "rejected-no-gain",
                   gang=plan.gang_uid, need=plan.need,
                   victims=len(plan.victim_rows),
                   gang_placed=gang_assigned,
                   frag=round(plan.frag_before, 4))
        # The identical plan would re-form (and re-fail) next cycle;
        # cool down until the cluster has had time to move.
        set_backoff(cyc.store, plan.action, plan.gang_uid,
                    cyc.REBALANCE_REJECT_BACKOFF)
        return
    if plan.resolve_victims:
        victim_nodes = assigned[G:]
    else:
        vr_sorted = plan.victim_rows.astype(np.int64)
        victim_nodes = np.full(len(vr_sorted), -1, np.int64)
    commit_plan(cyc, plan, vr_sorted, victim_nodes)


# holds: _lock
def commit_plan(cyc, plan: WhatIfPlan, victim_rows: np.ndarray,
                victim_nodes: np.ndarray) -> None:
    """Execute a proven plan: evict every victim through the
    ``fastpath_evict`` machinery (flushed to the store at cycle end,
    exactly as host-walk evictions are) and register each restore with
    the shared migration ledger so no pod is ever lost."""
    from .actions.rebalance import ledger_of, max_unavailable_of
    from .api import TaskStatus

    m = cyc.m
    store = cyc.store
    st_running = int(TaskStatus.Running)
    # Exact commit re-check behind the staleness guard: victims must
    # still be the Running residents the plan drained.
    ok = (m.p_alive[victim_rows]
          & (m.p_status[victim_rows] == st_running))
    if not bool(ok.all()):
        count_plan(cyc, plan.action, "stale-voided",
                   gang=plan.gang_uid, victims=len(victim_rows))
        return
    ledger = ledger_of(store)
    # Budget re-check at commit time, against the ledger's live
    # cross-action disrupted counts: preempt, reclaim and rebalance
    # share one disruption-budget pool per PodGroup.
    for uid, n_new in plan.budgets.items():
        row = m.j_row.get(uid, -1)
        pg = m.j_pg[row] if row >= 0 else None
        if (ledger.disrupted(store, uid) + n_new
                > max_unavailable_of(pg)):
            count_plan(cyc, plan.action, "rejected-budget",
                       gang=plan.gang_uid, victims=len(victim_rows))
            return
    ev = cyc._evict_machinery()
    st = ev.st
    events = []
    reason = ("Rebalance" if plan.action == "rebalance"
              else plan.action.capitalize())
    for row, tgt in zip(victim_rows.tolist(),
                        victim_nodes.tolist()):
        st.evict(int(row), None)
        st.evicted_rows.append(int(row))
        tgt_name = (m.n_name[int(tgt)]
                    if 0 <= int(tgt) < cyc.Nn else "")
        # Journey: the victim's timeline shows the planned target so
        # the later restore stitch reads as one migration.
        cyc._journey_event(int(row), "migration-planned",
                           detail=tgt_name)
        ledger.register(m.p_uid[row],
                        m.j_uid[int(cyc.jobr[row])], tgt_name,
                        action=plan.action,
                        for_gang=plan.gang_uid)
        events.append((
            f"Pod/{m.p_key[row]}", reason,
            f"evicted for gang {plan.gang_uid} "
            f"({plan.action} what-if plan"
            + (f", planned node {tgt_name})" if tgt_name else ")"),
        ))
    ledger.committed_plans += 1
    # Evictions moved mirror state: an overlapping solve dispatch must
    # re-validate (same stamp the host-walk actions apply).  Eviction
    # COUNTERS are bumped at the cycle-end evictor DISPATCH
    # (EvictState.flush), not here — a failed dispatch reverts the
    # victim, and a counter bumped at commit would overstate evictions
    # that never happened.
    m.mutation_seq += 1
    store.record_events_deferred(events)
    count_plan(cyc, plan.action, "committed", gang=plan.gang_uid,
               need=plan.need, victims=len(victim_rows),
               drain_nodes=len(plan.drain_nodes),
               frag=round(plan.frag_before, 4))


# ------------------------------------------------------------ accounting


def count_plan(cyc, action: str, outcome: str, **info) -> None:
    """Fold a plan outcome into the counter series and the cycle's
    flight-recorder accounting.  A cycle can see TWO outcomes — a
    pipelined plan voiding at the top AND a same-cycle re-plan — so
    earlier outcomes are preserved under ``prior`` (the record and the
    Prometheus counters must agree on totals).  Rebalance keeps its
    historical ``volcano_rebalance_plans_total`` series alongside the
    engine-wide ``volcano_whatif_plans_total``."""
    metrics.whatif_plans.inc(action=action, outcome=outcome)
    if action == "rebalance":
        metrics.rebalance_plans.inc(outcome=outcome)
        key = "rebalance"
        d = {"outcome": outcome}
    else:
        key = "whatif"
        d = {"action": action, "outcome": outcome}
    d.update(info)
    existing = cyc.stats.get(key)
    if existing is not None:
        d["prior"] = existing.pop("prior", []) + [existing]
    cyc.stats[key] = d


# --------------------------------------------------- streaks / backoffs


def _streak_maps(store) -> Tuple[dict, dict]:
    streaks = getattr(store, "_whatif_streaks", None)
    if streaks is None:
        streaks = store._whatif_streaks = {}
    backoff = getattr(store, "_whatif_backoff", None)
    if backoff is None:
        backoff = store._whatif_backoff = {}
    return streaks, backoff


def update_streaks(store, action: str, uids) -> Tuple[dict, dict]:
    """Per-(action, gang) starvation streaks + rejection backoffs,
    mirroring the rebalance lane's: a gang must stay starved across
    consecutive passes (pipelined cycles see starvation one commit
    behind), and a rejected plan cools the gang down instead of
    re-paying the kernel + what-if every cycle.  Leaving the starved
    set clears both."""
    streaks, backoff = _streak_maps(store)
    live = {(action, uid) for uid in uids}
    for key in list(streaks):
        if key[0] == action and key not in live:
            del streaks[key]
    for key in live:
        streaks[key] = streaks.get(key, 0) + 1
    for key in list(backoff):
        if key[0] != action:
            continue
        if key not in live:
            del backoff[key]
        elif backoff[key] > 0:
            backoff[key] -= 1
    return streaks, backoff


def set_backoff(store, action: str, uid: str, passes: int) -> None:
    if action == "rebalance":
        # The rebalance lane keeps its historical per-uid backoff map
        # (cleared by its own streak bookkeeping).
        backoff = getattr(store, "_rebalance_backoff", None)
        if backoff is None:
            backoff = store._rebalance_backoff = {}
        backoff[uid] = passes
        return
    _, backoff = _streak_maps(store)
    backoff[(action, uid)] = passes


# ------------------------------------------------------------- planners


def _starved_candidates(cyc):
    """Session job rows that are schedulable-but-unready gangs (same
    gate the rebalance planner uses)."""
    m = cyc.m
    srows = np.asarray(cyc.session_jobs, np.int64)
    if not len(srows):
        return srows
    mask = (
        (cyc.j_phase[srows] != 1)  # Inqueue gate, as _schedulable_rows
        & (cyc.j_cnt_pending[srows] > 0)
        & (cyc.j_ready_base[srows] < m.j_minav[srows])
        & (cyc.j_valid[srows] >= m.j_minav[srows])
        & (cyc.q_of_job[srows] >= 0)
    )
    return srows[mask]


def _gang_profile_table(cyc, jrow: int):
    """(gang_rows, [Up, R] init-request table) of a gang's pending
    non-best-effort tasks, profile-deduped and pow2-padded exactly as
    the rebalance planner builds it (all-zero pad rows are inert)."""
    from .fastpath import _pow2

    m = cyc.m
    Pn = cyc.Pn
    from .api import TaskStatus

    st_pending = int(TaskStatus.Pending)
    pend = np.flatnonzero(
        m.p_alive[:Pn] & (m.p_status[:Pn] == st_pending)
        & ~m.p_be[:Pn] & (cyc.jobr == jrow)
    )
    if not len(pend):
        return pend, None
    gang_rows = pend[np.argsort(m.p_create[pend], kind="stable")]
    _, first = np.unique(m.p_prof[gang_rows], return_index=True)
    urows = gang_rows[np.sort(first)]
    Up = _pow2(max(len(urows), 1), 4)
    prof_req = np.zeros((Up, cyc.R), F)
    er, si, v = m.c_init_req.gather(urows)
    prof_req[er, si] = v
    return gang_rows, prof_req


def _victim_base(cyc, gang_jrow: int) -> np.ndarray:
    """Mirror rows eligible as wave victims BEFORE tier gating: Running
    residents with requests, not critical (conformance), without
    required inter-pod terms (their drain patches resident-derived
    counts conservatively), never the starved gang itself."""
    from .api import TaskStatus

    m = cyc.m
    Pn = cyc.Pn
    st_running = int(TaskStatus.Running)
    vict = np.flatnonzero(
        cyc.resident[:Pn]
        & (m.p_status[:Pn] == st_running)
        & ~m.p_critical[:Pn]
        & ~m.p_has_ip[:Pn]
        & (cyc.jobr >= 0)
        & (cyc.jobr != gang_jrow)
    )
    if len(vict):
        vict = vict[m.c_req.lens(vict) > 0]
    return vict.astype(np.int64)


def _budget_left(cyc, groups) -> Dict[str, int]:
    """Remaining per-PodGroup disruption budget after waves already in
    flight, across EVERY action sharing the ledger."""
    from .actions.rebalance import max_unavailable_of

    m = cyc.m
    ledger = cyc.store.migrations
    out: Dict[str, int] = {}
    for uid in set(groups):
        row = m.j_row.get(uid, -1)
        pg = m.j_pg[row] if row >= 0 else None
        used = (ledger.disrupted(cyc.store, uid)
                if ledger is not None else 0)
        out[uid] = max_unavailable_of(pg) - used
    return out


# holds: _lock
def _plan_evict(cyc, action: str) -> Optional[WhatIfPlan]:
    """Plan one preempt/reclaim wave: pick the starved gang, score and
    rank victims with the jitted kernel (ops/victim.py), select under
    budgets, and return the plan for the what-if solve to prove."""
    from .ops import victim as vk

    m = cyc.m
    store = cyc.store
    # Deferred aggregate scatters (same-cycle bind charges) must land
    # before ANY queue-share read below — the overuse gate and the
    # deserved-slack selection would otherwise see understated
    # allocations for queues the allocate action just charged.
    cyc._flush_aggr()
    cand = _starved_candidates(cyc)
    is_reclaim = action == "reclaim"
    q_share_host = None
    if is_reclaim and len(cand):
        q_share_host = vk.queue_shares(cyc.q_alloc, cyc.q_deserved)
        # Reclaim serves queues still UNDER their deserved share; a
        # gang in an overused queue must preempt within it instead.
        under = q_share_host[cyc.q_of_job[cand]] <= 1.0 + vk.SHARE_TOL
        cand = cand[under]
    uids = [m.j_uid[int(r)] for r in cand]
    streaks, backoff = update_streaks(store, action, uids)
    if not len(cand):
        return None
    need_streak = 2 if cyc._pipeline_on else 1
    ledger = store.migrations
    needs = (m.j_minav[cand] - cyc.j_ready_base[cand]).astype(np.int64)
    prios = m.j_prio[cand].astype(np.int64)
    # Highest-priority gang first (the point of preemption), then the
    # largest shortfall, then the lowest row for determinism.
    order = np.lexsort((cand, -needs, -prios))
    with cyc.tracer.span(f"{action}_plan", cat="whatif"):
        for r in cand[order]:
            jrow = int(r)
            uid = m.j_uid[jrow]
            if streaks.get((action, uid), 0) < need_streak \
                    or backoff.get((action, uid), 0) > 0:
                continue
            if ledger is not None and ledger.wave_pending(store, uid):
                # A prior wave for this gang is still freeing capacity
                # (victims terminating); re-planning now would double-
                # evict for the same need.
                continue
            plan = _plan_evict_gang(cyc, action, jrow)
            if plan is not None:
                return plan
    return None


# holds: _lock
def _plan_evict_gang(cyc, action: str, jrow: int) -> Optional[WhatIfPlan]:
    import jax

    from .fastpath import _pow2
    from .ops import victim as vk

    m = cyc.m
    store = cyc.store
    is_reclaim = action == "reclaim"
    need = int(m.j_minav[jrow] - cyc.j_ready_base[jrow])
    if need <= 0:
        return None
    gang_rows, prof_req = _gang_profile_table(cyc, jrow)
    if prof_req is None:
        return None
    vict = _victim_base(cyc, jrow)
    if not len(vict):
        return None
    V = len(vict)
    Vp = _pow2(V)
    Np = _pow2(max(cyc.Nn, 1))
    Qp = _pow2(max(cyc.Qn, 1), 4)
    v_ok = np.zeros(Vp, bool)
    v_ok[:V] = True
    v_jprio = np.zeros(Vp, I)
    v_crank = np.zeros(Vp, I)
    v_tie = np.arange(Vp, dtype=I)
    v_queue = np.zeros(Vp, I)
    v_node = np.zeros(Vp, I)
    v_req = np.zeros((Vp, cyc.R), F)
    vjobs = cyc.jobr[vict].astype(np.int64)
    # A victim whose job has no known queue (q_of_job == -1: its queue
    # was deleted) has no share to gate on — exclude it at the base
    # level rather than letting the kernel's index clip alias it onto
    # queue 0 (the oracle requires 0 <= q < Q the same way).
    v_ok[:V] = cyc.q_of_job[vjobs] >= 0
    v_jprio[:V] = m.j_prio[vjobs]
    # Creation rank: larger = younger (evicted first among equals).
    v_crank[:V] = np.argsort(
        np.argsort(m.p_create[vict], kind="stable")).astype(I)
    v_queue[:V] = cyc.q_of_job[vjobs]
    v_node[:V] = m.p_node[:cyc.Pn][vict]
    er, si, vv = m.c_req.gather(vict)
    v_req[er, si] = vv
    q_alloc_p = np.zeros((Qp, cyc.R), F)
    q_des_p = np.full((Qp, cyc.R), 3.0e38, F)
    q_alloc_p[:cyc.Qn] = cyc.q_alloc
    q_des_p[:cyc.Qn] = cyc.q_deserved
    q_rec = np.zeros(Qp, bool)
    for name, qi in cyc.queue_index.items():
        q = store.queues.get(name)
        q_rec[qi] = bool(q is not None and q.reclaimable())
    gang_prio = int(m.j_prio[jrow])
    gang_queue = int(cyc.q_of_job[jrow])
    planes = vk.victim_scores(
        v_ok, v_jprio, v_crank, v_tie, v_queue, v_node, v_req,
        np.int32(gang_prio), np.int32(gang_queue),
        q_alloc_p, q_des_p, q_rec,
        np.int32(vk.RECLAIM if is_reclaim else vk.PREEMPT),
        np.zeros((Np, cyc.R), F),
    )
    eligible, order, evictable = jax.device_get(
        (planes.eligible, planes.order, planes.evictable))
    if not bool(eligible[:V].any()):
        return None
    groups = [m.j_uid[int(j)] for j in vjobs]
    v_group = groups + [""] * (Vp - V)
    budget_left = _budget_left(cyc, groups)
    qa_sel = qd_sel = None
    if is_reclaim:
        qa_sel = cyc.q_alloc.astype(F)
        qd_sel = cyc.q_deserved.astype(F)
    idle_p = np.zeros((Np, cyc.R), F)
    idle_p[:cyc.Nn] = cyc.n_idle.astype(F)
    v_job_p = np.concatenate([vjobs, np.full(Vp - V, -1, np.int64)])
    sel = vk.select_victims(
        order, eligible, v_node, v_req, v_job_p,
        v_group, v_queue, need, idle_p, evictable, prof_req,
        cyc.eps, cyc.j_ready_base, m.j_minav, budget_left,
        evict_cap(), q_alloc=qa_sel, q_deserved=qd_sel,
    )
    uid = m.j_uid[jrow]
    if not sel.feasible:
        if sel.budget_blocked:
            count_plan(cyc, action, "rejected-budget",
                       gang=uid, need=need)
        # Cooldown either way: no wave can form until the cluster
        # moves, so re-scoring every cycle is waste.
        set_backoff(store, action, uid, cyc.REBALANCE_REJECT_BACKOFF)
        return None
    chosen = np.asarray(sel.chosen, np.int64)
    victim_rows = vict[chosen]
    victim_jobs = vjobs[chosen]
    budgets: Dict[str, int] = {}
    for j in victim_jobs.tolist():
        g = m.j_uid[int(j)]
        budgets[g] = budgets.get(g, 0) + 1
    return WhatIfPlan(
        action=action, gang_job=jrow, gang_uid=uid,
        gang_rows=gang_rows, victim_rows=victim_rows,
        victim_jobs=victim_jobs,
        drain_nodes=np.zeros(0, np.int64), need=need,
        frag_before=0.0, budgets=budgets, resolve_victims=False,
    )


# holds: _lock
def run_evict_action(cyc, action: str) -> None:
    """The device-native preempt/reclaim lane body: plan, prove,
    commit (or park the proof for the next cycle's top).  One what-if
    wave is in flight at a time across ALL engine actions — the
    ``store._inflight_plan`` slot is shared."""
    store = cyc.store
    if store._inflight_plan is not None:
        return
    plan = _plan_evict(cyc, action)
    if plan is None:
        return
    dispatch_plan(cyc, plan)
