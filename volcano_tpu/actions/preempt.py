"""Preempt action (pkg/scheduler/actions/preempt/preempt.go).

Two phases: inter-job preemption within each queue (statement-wrapped;
commit iff the preemptor job reaches Pipelined, preempt.go:81-142), then
intra-job task preemption (preempt.go:144-177).  Victim selection walks
nodes in score order, filters candidate preemptees, intersects plugin
victim sets (ssn.Preemptable), validates sufficiency, and evicts
lowest-order victims until FutureIdle covers the preemptor, then pipelines
it (preempt.go:183-262).
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List

from ..api import JobInfo, PodGroupPhase, TaskInfo, TaskStatus
from ..metrics import metrics
from ..utils.priority_queue import PriorityQueue
from ..utils.scheduler_helper import (
    predicate_nodes,
    prioritize_nodes,
    sort_nodes,
    validate_victims,
)

log = logging.getLogger(__name__)


class PreemptAction:
    name = "preempt"

    def initialize(self):
        pass

    def un_initialize(self):
        pass

    def execute(self, ssn) -> None:
        preemptors_map: Dict[str, PriorityQueue] = {}
        preemptor_tasks: Dict[str, PriorityQueue] = {}
        under_request: List[JobInfo] = []
        queues: Dict[str, object] = {}

        for job in ssn.jobs.values():
            if (
                job.pod_group is not None
                and job.pod_group.status.phase == PodGroupPhase.Pending.value
            ):
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.pass_:
                continue
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            queues.setdefault(queue.uid, queue)
            pending = job.task_status_index.get(TaskStatus.Pending, {})
            if pending and not ssn.job_pipelined(job):
                preemptors_map.setdefault(
                    job.queue, PriorityQueue(ssn.job_order_fn)
                ).push(job)
                under_request.append(job)
                tq = PriorityQueue(ssn.task_order_fn)
                for task in pending.values():
                    tq.push(task)
                preemptor_tasks[job.uid] = tq

        for queue in queues.values():
            # Phase 1: inter-job preemption within the queue.
            while True:
                preemptors = preemptors_map.get(queue.uid)
                if preemptors is None or preemptors.empty():
                    break
                preemptor_job = preemptors.pop()

                stmt = ssn.statement()
                assigned = False
                while True:
                    if ssn.job_pipelined(preemptor_job):
                        break
                    tasks = preemptor_tasks.get(preemptor_job.uid)
                    if tasks is None or tasks.empty():
                        break
                    preemptor = tasks.pop()

                    def job_filter(task: TaskInfo) -> bool:
                        if task.status != TaskStatus.Running:
                            return False
                        if task.resreq.is_empty():
                            return False
                        job = ssn.jobs.get(task.job)
                        if job is None:
                            return False
                        return (
                            job.queue == preemptor_job.queue
                            and preemptor.job != task.job
                        )

                    if self._preempt(ssn, stmt, preemptor, job_filter):
                        assigned = True

                if ssn.job_pipelined(preemptor_job):
                    stmt.commit()
                else:
                    stmt.discard()
                    continue
                if assigned:
                    preemptors.push(preemptor_job)

            # Phase 2: intra-job task preemption.
            for job in under_request:
                while True:
                    tasks = preemptor_tasks.get(job.uid)
                    if tasks is None or tasks.empty():
                        break
                    preemptor = tasks.pop()
                    stmt = ssn.statement()

                    def task_filter(task: TaskInfo) -> bool:
                        if task.status != TaskStatus.Running:
                            return False
                        if task.resreq.is_empty():
                            return False
                        return preemptor.job == task.job

                    assigned = self._preempt(ssn, stmt, preemptor, task_filter)
                    stmt.commit()
                    if not assigned:
                        break

    # ------------------------------------------------------------ internals

    def _preempt(self, ssn, stmt, preemptor: TaskInfo,
                 task_filter: Callable[[TaskInfo], bool]) -> bool:
        assigned = False
        all_nodes = list(ssn.nodes.values())
        feasible, _ = predicate_nodes(preemptor, all_nodes, ssn.predicate_fn)
        node_scores = prioritize_nodes(
            preemptor, feasible, ssn.batch_node_order_fn, ssn.node_order_fn
        )
        for node in sort_nodes(node_scores):
            preemptees = [
                task.clone()
                for task in node.tasks.values()
                if task_filter(task)
            ]
            victims = ssn.preemptable(preemptor, preemptees)
            metrics.update_preemption_victim_count(len(victims))
            try:
                validate_victims(preemptor, node, victims)
            except ValueError as err:
                log.debug("No validated victims on %s: %s", node.name, err)
                continue

            # Lowest task order last -> pop lowest-priority victims first
            # (preempt.go:219-224 inverts TaskOrderFn).
            victims_queue = PriorityQueue(
                lambda l, r: not ssn.task_order_fn(l, r)
            )
            for victim in victims:
                victims_queue.push(victim)

            while not victims_queue.empty():
                if preemptor.init_resreq.less_equal(node.future_idle()):
                    break
                preemptee = victims_queue.pop()
                try:
                    stmt.evict(preemptee, "preempt")
                except Exception:
                    log.exception("Failed to preempt %s", preemptee.name)
                    continue
            metrics.register_preemption_attempt()

            if preemptor.init_resreq.less_equal(node.future_idle()):
                try:
                    stmt.pipeline(preemptor, node.name)
                except Exception:
                    log.exception("Failed to pipeline %s", preemptor.name)
                assigned = True
                break
        return assigned
