"""Rebalance action: gang-aware defragmentation with disruption budgets.

The sixth action (``actions: "enqueue, allocate, backfill, rebalance"``).
Unlike the other five it has no sequential object-path reference — the
reference family delegates defragmentation to a separate descheduler
process — so the object-session ``execute`` is a documented no-op and
the real implementation is the fast path's ``FastCycle._rebalance``
lane (plan = what-if ``solve_wave`` over a hypothetically drained
cluster, commit = evictions through the ``fastpath_evict`` machinery;
see docs/rebalance.md).

This module also owns the **migration planner** state that outlives a
single cycle:

- ``MigrationLedger`` — the store-attached record of in-flight
  migrations, shared since ISSUE 11 by every what-if engine action
  (rebalance, device-native preempt and reclaim — entries carry the
  evicting ``action`` and the beneficiary gang).  A committed plan
  registers every victim; when the evicted pod finishes terminating
  (``store.delete_pod``, driven by the simulator's graceful-termination
  ticks or a real kubelet), the ledger *restores* it: an identical
  Pending pod re-enters the store, playing the owning controller's
  recreate.  No pod is ever lost — rebalance proved a re-placement
  exists; a preempted/reclaimed pod waits its turn through the ordinary
  allocate lane.
- disruption budgets — the PDB equivalent.  ``max_unavailable_of``
  resolves a PodGroup's ceiling (``PodGroup.max_unavailable``, else the
  ``VOLCANO_TPU_REBALANCE_MAX_UNAVAIL`` default); the ledger's
  ``disrupted`` count (victims whose restored pod is not yet bound)
  is charged against it both at plan time and at commit re-check.
"""

from __future__ import annotations

import copy
import logging
import os
from typing import Dict, Optional

log = logging.getLogger(__name__)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def rebalance_enabled() -> bool:
    """Master switch (the action string is the real opt-in; this kills
    the lane without a config rollout)."""
    return os.environ.get("VOLCANO_TPU_REBALANCE", "1") != "0"


def drain_cap() -> int:
    """Max nodes one plan may hypothetically drain."""
    return max(1, _env_int("VOLCANO_TPU_REBALANCE_DRAIN_CAP", 32))


def min_gain() -> int:
    """Min starved-gang tasks a plan must newly place to commit."""
    return max(1, _env_int("VOLCANO_TPU_REBALANCE_MIN_GAIN", 1))


def default_max_unavailable() -> int:
    """Per-PodGroup disruption ceiling when the group sets none."""
    return max(0, _env_int("VOLCANO_TPU_REBALANCE_MAX_UNAVAIL", 1))


def max_unavailable_of(pg) -> int:
    """Resolve a PodGroup's disruption budget (PDB max_unavailable
    equivalent).  ``None``/missing falls back to the env default."""
    v = getattr(pg, "max_unavailable", None) if pg is not None else None
    if v is None:
        return default_max_unavailable()
    return max(0, int(v))


class _Migration:
    """One victim's evict -> restore -> rebind lifecycle."""

    __slots__ = ("uid", "group_uid", "planned_node", "restored_uid",
                 "action", "for_gang")

    def __init__(self, uid: str, group_uid: str, planned_node: str,
                 action: str = "rebalance", for_gang: str = ""):
        self.uid = uid
        self.group_uid = group_uid
        self.planned_node = planned_node
        # uid of the restored Pending pod, set when the eviction's
        # termination completes and the ledger re-creates the pod.
        self.restored_uid: Optional[str] = None
        # Which engine action evicted this victim (ISSUE 11: preempt,
        # reclaim and rebalance share one ledger and one per-PodGroup
        # disruption-budget pool) and which starved gang the wave
        # served (``wave_pending`` keys re-plan suppression on it).
        self.action = action
        self.for_gang = for_gang


class MigrationLedger:
    """Store-attached in-flight migration record (``store.migrations``).

    Called from inside the store's lock (``delete_pod``) and from the
    fast-path cycle (which holds the same re-entrant lock), so no lock
    of its own is needed.
    """

    def __init__(self):
        self.entries: Dict[str, _Migration] = {}  # victim uid -> entry
        self._restore_seq = 0
        # Monotonic counters for the flight recorder / tests.
        self.committed_plans = 0
        self.restored_pods = 0

    # ------------------------------------------------------------ commit

    def register(self, uid: str, group_uid: str, planned_node: str,
                 action: str = "rebalance", for_gang: str = "") -> None:
        self.entries[uid] = _Migration(uid, group_uid, planned_node,
                                       action=action, for_gang=for_gang)

    def cancel(self, uid: str) -> None:
        """Drop a migration whose eviction never dispatched (the
        evictor failed and the pod reverted to Running —
        ``fastpath_evict.EvictState.flush``).  The pod was never
        unavailable, so it must not pin its group's budget nor be
        "restored" when it eventually terminates for ordinary
        reasons."""
        self.entries.pop(uid, None)

    # ----------------------------------------------------------- restore

    def pod_deleted(self, store, pod) -> None:
        """``store.delete_pod`` hook: a terminating migration victim is
        restored as a fresh Pending pod (the owning controller's
        recreate, played in-process so migration e2e is hermetic).

        Only an eviction-driven termination restores: a pod deleted
        while NOT marked ``deleting`` (an operator/controller delete),
        or whose PodGroup is gone (the workload itself was removed),
        must stay deleted — resurrecting it would both override an
        explicit delete and strand an unschedulable orphan that pins
        the ledger (and with it the lane) forever.  Either way the
        entry leaves the ledger."""
        entry = self.entries.get(pod.uid)
        if entry is None or entry.restored_uid is not None:
            return
        if not pod.deleting or store.pod_groups.get(
                entry.group_uid) is None:
            del self.entries[pod.uid]
            return
        restored = copy.copy(pod)
        self._restore_seq += 1
        restored.uid = f"{pod.uid}-mig{self._restore_seq}"
        restored.node_name = None
        restored.deleting = False
        from ..api import PodPhase

        restored.phase = PodPhase.Pending
        restored.exit_code = 0
        entry.restored_uid = restored.uid
        self.restored_pods += 1
        store.add_pod(restored)
        # Journey stitch: link the fresh uid's timeline back to the
        # evicted victim's, so the migration reads as ONE pod journey.
        journey = getattr(store, "journey", None)
        if journey is not None:
            journey.pod_restored(pod.uid, restored.uid)
        planned = (f" (planned node {entry.planned_node})"
                   if entry.planned_node else "")
        store.record_event(
            f"Pod/{pod.namespace}/{pod.name}", "MigrationRestored",
            f"restored as {restored.uid} after {entry.action} "
            f"eviction{planned}",
        )

    # ----------------------------------------------------------- budgets

    def _done(self, store, entry: _Migration) -> bool:
        """A migration is complete once its restored pod is bound."""
        # The workload itself was removed mid-migration: nothing left
        # to restore or re-bind; the entry must not pin the budget (or
        # the one-wave-at-a-time gate) forever.
        if store.pod_groups.get(entry.group_uid) is None:
            return True
        if entry.restored_uid is None:
            return False
        pod = store.pods.get(entry.restored_uid)
        # Restored pod deleted again (external actor): nothing left to
        # track; the ledger must not pin the budget forever.
        if pod is None:
            return True
        return pod.node_name is not None

    def prune(self, store) -> None:
        done = [uid for uid, e in self.entries.items()
                if self._done(store, e)]
        for uid in done:
            del self.entries[uid]

    def disrupted(self, store, group_uid: str) -> int:
        """Victims of the group still unavailable (evicted / terminating
        / restored-but-unbound)."""
        self.prune(store)
        return sum(1 for e in self.entries.values()
                   if e.group_uid == group_uid)

    def active(self, store, action: Optional[str] = None) -> bool:
        """True while any migration is incomplete — the rebalance
        planner runs one migration wave at a time.  ``action`` filters
        to one engine action's entries: a preempted batch pod may stay
        Pending indefinitely (its entry pins its group's budget, which
        is correct PDB accounting), and that must not wedge the
        rebalance lane's own single-wave gate."""
        self.prune(store)
        if action is None:
            return bool(self.entries)
        return any(e.action == action for e in self.entries.values())

    def wave_pending(self, store, gang_uid: str) -> bool:
        """True while a prior wave for ``gang_uid`` is still FREEING
        capacity (victims evicted but not yet terminated): planning
        another wave for the same gang before the capacity lands would
        double-evict for the same need.  Once the victims are restored
        the gang either binds or is legitimately starved again."""
        self.prune(store)
        return any(e.for_gang == gang_uid and e.restored_uid is None
                   for e in self.entries.values())


def ledger_of(store) -> MigrationLedger:
    """The store's migration ledger, created on first use."""
    ledger = getattr(store, "migrations", None)
    if ledger is None:
        ledger = store.migrations = MigrationLedger()
    return ledger


class RebalanceAction:
    """Object-path registration for the ``rebalance`` action name.

    The device-native rebalance lane needs the array mirror, the
    profile tables and the wave solver — none of which exist on the
    object-session path.  Configurations that include ``rebalance``
    with fast-path-eligible plugins run it in ``FastCycle._rebalance``;
    on the object path the action is a no-op (matching the reference,
    where defragmentation lives in a separate descheduler, not the
    scheduler's action list).
    """

    name = "rebalance"

    def initialize(self):
        pass

    def un_initialize(self):
        pass

    def execute(self, ssn) -> None:
        log.debug(
            "rebalance is a device-native lane; the object-session "
            "path does not implement it (session %s)", ssn.uid,
        )
