"""Backfill action (pkg/scheduler/actions/backfill/backfill.go).

Places zero-request (BestEffort) pending tasks on any node passing
predicates, recording fit errors otherwise (backfill.go:39-88).
"""

from __future__ import annotations

import logging

from ..api import FitErrors, PodGroupPhase, TaskStatus

log = logging.getLogger(__name__)


class BackfillAction:
    name = "backfill"

    def initialize(self):
        pass

    def un_initialize(self):
        pass

    def execute(self, ssn) -> None:
        for job in list(ssn.jobs.values()):
            if (
                job.pod_group is not None
                and job.pod_group.status.phase == PodGroupPhase.Pending.value
            ):
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.pass_:
                continue
            pending = list(
                job.task_status_index.get(TaskStatus.Pending, {}).values()
            )
            for task in pending:
                if not task.init_resreq.is_empty():
                    continue
                allocated = False
                fe = FitErrors()
                for node in ssn.nodes.values():
                    try:
                        ssn.predicate_fn(task, node)
                    except Exception as err:
                        fe.set_node_error(node.name, err)
                        continue
                    try:
                        ssn.allocate_task(task, node.name)
                    except Exception as err:
                        fe.set_node_error(node.name, err)
                        continue
                    allocated = True
                    break
                if not allocated:
                    job.nodes_fit_errors[task.uid] = fe
