"""Reclaim action (pkg/scheduler/actions/reclaim/reclaim.go).

Cross-queue resource reclaim: for a starved (non-overused) queue's
highest-order pending task, evict Running tasks belonging to *other* queues
(only when the victim's queue is Reclaimable), chosen by the tiered
ssn.Reclaimable intersection, until the reclaimed resources cover the task;
then pipeline it (reclaim.go:40-189).  Evictions are immediate
(session-level Evict), not statement-wrapped.
"""

from __future__ import annotations

import logging
from typing import Dict

from ..api import PodGroupPhase, Resource, TaskStatus
from ..utils.priority_queue import PriorityQueue
from ..utils.scheduler_helper import validate_victims

log = logging.getLogger(__name__)


class ReclaimAction:
    name = "reclaim"

    def initialize(self):
        pass

    def un_initialize(self):
        pass

    def execute(self, ssn) -> None:
        queues = PriorityQueue(ssn.queue_order_fn)
        queue_set = set()
        preemptors_map: Dict[str, PriorityQueue] = {}
        preemptor_tasks: Dict[str, PriorityQueue] = {}

        for job in ssn.jobs.values():
            if (
                job.pod_group is not None
                and job.pod_group.status.phase == PodGroupPhase.Pending.value
            ):
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.pass_:
                continue
            queue = ssn.queues.get(job.queue)
            if queue is None:
                log.error("Failed to find queue %s for job %s/%s",
                          job.queue, job.namespace, job.name)
                continue
            if queue.uid not in queue_set:
                queue_set.add(queue.uid)
                queues.push(queue)
            pending = job.task_status_index.get(TaskStatus.Pending, {})
            if pending:
                preemptors_map.setdefault(
                    job.queue, PriorityQueue(ssn.job_order_fn)
                ).push(job)
                tq = PriorityQueue(ssn.task_order_fn)
                for task in pending.values():
                    tq.push(task)
                preemptor_tasks[job.uid] = tq

        while not queues.empty():
            queue = queues.pop()
            if ssn.overused(queue):
                log.debug("Queue %s is overused, ignore it", queue.name)
                continue
            jobs = preemptors_map.get(queue.uid)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()
            tasks = preemptor_tasks.get(job.uid)
            if tasks is None or tasks.empty():
                continue
            task = tasks.pop()

            assigned = False
            for node in ssn.nodes.values():
                try:
                    ssn.predicate_fn(task, node)
                except Exception:
                    continue
                resreq = task.init_resreq.clone()
                reclaimed = Resource.empty()

                reclaimees = []
                for resident in node.tasks.values():
                    if resident.status != TaskStatus.Running:
                        continue
                    rjob = ssn.jobs.get(resident.job)
                    if rjob is None:
                        continue
                    if rjob.queue != job.queue:
                        victim_queue = ssn.queues.get(rjob.queue)
                        if victim_queue is None or not victim_queue.reclaimable():
                            continue
                        reclaimees.append(resident.clone())
                victims = ssn.reclaimable(task, reclaimees)
                try:
                    validate_victims(task, node, victims)
                except ValueError as err:
                    log.debug("No validated victims on %s: %s",
                              node.name, err)
                    continue

                for reclaimee in victims:
                    try:
                        ssn.evict(reclaimee, "reclaim")
                    except Exception:
                        log.exception("Failed to reclaim %s", reclaimee.name)
                        continue
                    reclaimed.add(reclaimee.resreq)
                    if resreq.less_equal(reclaimed):
                        break

                if task.init_resreq.less_equal(reclaimed):
                    try:
                        ssn.pipeline(task, node.name)
                    except Exception:
                        log.exception("Failed to pipeline %s", task.name)
                    assigned = True
                    break

            if assigned:
                jobs.push(job)
            queues.push(queue)
