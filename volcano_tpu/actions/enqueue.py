"""Enqueue action (pkg/scheduler/actions/enqueue/enqueue.go).

Gates Pending PodGroups into the Inqueue phase when cluster
``total * overcommit - used`` covers the job's MinResources, consuming the
budget as jobs are admitted (enqueue.go:52-132).  The job controller only
creates pods once the PodGroup leaves Pending, so this is the cluster's
admission throttle.
"""

from __future__ import annotations

import heapq
import logging
from typing import Dict, List

from ..api import PodGroupPhase, Resource
from ..framework.arguments import get_action_args
from ..utils.priority_queue import PriorityQueue

log = logging.getLogger(__name__)

OVERCOMMIT_FACTOR_ARG = "overcommit-factor"
DEFAULT_OVERCOMMIT_FACTOR = 1.2


class EnqueueAction:
    name = "enqueue"

    def initialize(self):
        pass

    def un_initialize(self):
        pass

    def _overcommit_factor(self, ssn) -> float:
        args = get_action_args(ssn.configurations, self.name)
        if args is not None:
            return args.get_float(OVERCOMMIT_FACTOR_ARG, DEFAULT_OVERCOMMIT_FACTOR)
        return DEFAULT_OVERCOMMIT_FACTOR

    def execute(self, ssn) -> None:
        queues = PriorityQueue(ssn.queue_order_fn)
        queue_set = set()
        jobs_map: Dict[str, PriorityQueue] = {}

        for job in ssn.jobs.values():
            queue = ssn.queues.get(job.queue)
            if queue is None:
                log.error("Failed to find queue %s for job %s/%s",
                          job.queue, job.namespace, job.name)
                continue
            if queue.uid not in queue_set:
                queue_set.add(queue.uid)
                queues.push(queue)
            if (
                job.pod_group is not None
                and job.pod_group.status.phase == PodGroupPhase.Pending.value
            ):
                jobs_map.setdefault(
                    job.queue, PriorityQueue(ssn.job_order_fn)
                ).push(job)

        total = Resource.empty()
        used = Resource.empty()
        for node in ssn.nodes.values():
            total.add(node.allocatable)
            used.add(node.used)
        idle = total.clone().multi(self._overcommit_factor(ssn)).sub(used)

        while not queues.empty():
            if idle.is_empty():
                log.debug("Node idle resource is overused, stopping enqueue")
                break
            queue = queues.pop()
            jobs = jobs_map.get(queue.uid)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()

            inqueue = False
            if job.pod_group.min_resources is None:
                inqueue = True
            else:
                min_req = Resource.from_resource_list(
                    job.pod_group.min_resources
                )
                if ssn.job_enqueueable(job) and min_req.less_equal(idle):
                    idle.sub(min_req)
                    inqueue = True
            if inqueue:
                job.pod_group.status.phase = PodGroupPhase.Inqueue.value
            queues.push(queue)
