"""Scheduling actions, registered by name
(pkg/scheduler/actions/factory.go)."""

from ..framework.plugins import register_action
from .allocate import AllocateAction
from .backfill import BackfillAction
from .enqueue import EnqueueAction
from .preempt import PreemptAction
from .rebalance import RebalanceAction
from .reclaim import ReclaimAction

register_action(EnqueueAction())
register_action(AllocateAction())
register_action(BackfillAction())
register_action(PreemptAction())
register_action(ReclaimAction())
register_action(RebalanceAction())

__all__ = [
    "AllocateAction",
    "BackfillAction",
    "EnqueueAction",
    "PreemptAction",
    "RebalanceAction",
    "ReclaimAction",
]
