"""Scheduling actions, registered by name
(pkg/scheduler/actions/factory.go)."""

from ..framework.plugins import register_action
from .allocate import AllocateAction
from .backfill import BackfillAction
from .enqueue import EnqueueAction

register_action(EnqueueAction())
register_action(AllocateAction())
register_action(BackfillAction())

__all__ = ["AllocateAction", "BackfillAction", "EnqueueAction"]
