"""Allocate action: the device-backed hot path.

Replaces ``pkg/scheduler/actions/allocate/allocate.go:40-250``.  The
namespace -> queue -> job hierarchy is flattened host-side into a static
processing order (round-robin across namespaces, queues by share, jobs by
tier order, tasks by task order — the same orderings the reference applies
via its PriorityQueues), the snapshot is encoded into ``ClusterArrays``, and
one jitted solver call (``volcano_tpu.ops.allocate.solve``) performs the
predicate/score/select/capacity loop with gang commit/discard on device.
The returned assignment matrix is replayed through the Session so host
state, event handlers (DRF/proportion shares), and bind dispatch stay
consistent; a fit re-check guards against host/device divergence.

Because the fused order is fixed at encode time while the reference re-sorts
by live shares after every job, the action supports multiple solver rounds
(action argument ``rounds``, default 1): each round re-sorts by the updated
shares and solves the remaining pending tasks.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

import numpy as np

from ..api import (FitError, FitErrors, JobInfo, PodGroupPhase,
                   Resource, TaskInfo, TaskStatus)
from ..arrays import ResourceSlots, encode_affinity, encode_cluster
from ..cache.interface import VolumeBindFailure
from ..framework.arguments import get_action_args
from ..metrics import metrics
from ..utils.priority_queue import PriorityQueue

log = logging.getLogger(__name__)

ROUNDS_ARG = "rounds"
SOLVER_ARG = "solver"  # "wave" (default) or "seq" (exact sequential)


class AllocateAction:
    name = "allocate"

    def initialize(self):
        pass

    def un_initialize(self):
        pass

    # ------------------------------------------------------------- ordering

    def _schedulable_jobs(self, ssn) -> List[JobInfo]:
        jobs = []
        for job in ssn.jobs.values():
            if (
                job.pod_group is not None
                and job.pod_group.status.phase == PodGroupPhase.Pending.value
            ):
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.pass_:
                continue
            if job.queue not in ssn.queues:
                log.warning(
                    "Skip job %s/%s: queue %s not found",
                    job.namespace, job.name, job.queue,
                )
                continue
            jobs.append(job)
        return jobs

    def _job_order(self, ssn, jobs: List[JobInfo]) -> List[JobInfo]:
        """Flatten namespace round-robin x queue share x job order into a
        static sequence (allocate.go:107-153 with shares frozen at sort
        time)."""
        by_namespace: Dict[str, Dict[str, PriorityQueue]] = {}
        for job in jobs:
            by_namespace.setdefault(job.namespace, {}).setdefault(
                job.queue, PriorityQueue(ssn.job_order_fn)
            ).push(job)

        namespaces = sorted(
            by_namespace.keys(),
            key=lambda ns: 0,
        )
        # Order namespaces with the tiered comparator.
        ns_pq = PriorityQueue(ssn.namespace_order_fn)
        for ns in by_namespace:
            ns_pq.push(ns)
        namespaces = []
        while not ns_pq.empty():
            namespaces.append(ns_pq.pop())

        ordered: List[JobInfo] = []
        # Round-robin namespaces; within a namespace pick the best queue by
        # queue_order_fn among queues that still have jobs, pop one job.
        active = {ns: by_namespace[ns] for ns in namespaces}
        while active:
            for ns in list(namespaces):
                queues = active.get(ns)
                if not queues:
                    active.pop(ns, None)
                    continue
                best_q = None
                for qid in list(queues.keys()):
                    if queues[qid].empty():
                        del queues[qid]
                        continue
                    q = ssn.queues[qid]
                    if ssn.overused(q):
                        # Skip overused queues at sort time; the kernel
                        # re-checks with live allocation.
                        del queues[qid]
                        continue
                    if best_q is None or ssn.queue_order_fn(q, ssn.queues[best_q]):
                        best_q = qid
                if best_q is None:
                    active.pop(ns, None)
                    continue
                ordered.append(queues[best_q].pop())
            if not any(active.values()):
                break
        return ordered

    def _pending_tasks(self, ssn, job: JobInfo) -> List[TaskInfo]:
        tasks = PriorityQueue(ssn.task_order_fn)
        for task in job.task_status_index.get(TaskStatus.Pending, {}).values():
            # Skip BestEffort tasks in allocate (backfill handles them).
            if task.resreq.is_empty():
                continue
            tasks.push(task)
        out = []
        while not tasks.empty():
            out.append(tasks.pop())
        return out

    # ------------------------------------------------------------- execute

    def execute(self, ssn) -> None:
        from ..ops import solve, solve_inputs
        from ..ops.wave import solve_wave

        args = get_action_args(ssn.configurations, self.name)
        rounds = args.get_int(ROUNDS_ARG, 1) if args else 1
        solver = args.get_str(SOLVER_ARG, "wave") if args else "wave"
        # Wave-mode gang discards release capacity only after the solve
        # (wave.py module docs); extra rounds give discard survivors the
        # freed capacity — the sequential solver releases in-scan and
        # needs none.
        max_rounds = max(rounds, 1) + (3 if solver == "wave" else 0)

        slots = None
        retry_discards = False
        for rnd in range(max_rounds):
            if rnd >= max(rounds, 1) and not retry_discards:
                break
            jobs = self._schedulable_jobs(ssn)
            ordered_jobs = self._job_order(ssn, jobs)
            pending: List[TaskInfo] = []
            job_ids: List[str] = []
            job_tasks: Dict[str, List[TaskInfo]] = {}
            for job in ordered_jobs:
                tasks = self._pending_tasks(ssn, job)
                if not tasks:
                    continue
                job_ids.append(job.uid)
                job_tasks[job.uid] = tasks
                pending.extend(tasks)
            if not pending:
                return

            cluster = _SessionView(ssn)
            if slots is None:
                slots = ResourceSlots.for_cluster(cluster)
            arrays, maps = encode_cluster(cluster, pending, job_ids, slots)

            # Inter-pod (anti)affinity + spread: per-(term, domain) count
            # tensors, checked and updated live inside the solver.
            aff = encode_affinity(
                cluster, pending, maps.node_names,
                arrays.nodes.idle.shape[0], arrays.tasks.req.shape[0],
            )

            weights = ssn.score_weights(slots)

            Q, R = arrays.queues.capability.shape
            deserved = np.full((Q, R), 3.0e38, np.float32)
            q_alloc0 = np.zeros((Q, R), np.float32)
            for qid, res in ssn.queue_deserved.items():
                qi = maps.queue_index.get(qid)
                if qi is not None:
                    deserved[qi] = slots.vec(res)
            for qid, res in ssn.queue_allocated_open.items():
                qi = maps.queue_index.get(qid)
                if qi is not None:
                    q_alloc0[qi] = slots.vec(res)

            s_nodes, s_tasks, s_jobs, s_queues = solve_inputs(
                arrays, deserved, q_alloc0
            )
            pp = arrays.tasks.req.shape[0]
            nn = arrays.nodes.idle.shape[0]
            extra_ok = self._custom_mask(ssn, cluster, pending, maps)
            if extra_ok is not None:
                # Align to the encoder's padded task/node axes (padded
                # tasks are inert; padded nodes are not-ready): all-ones.
                full = np.ones((pp, nn), bool)
                full[:extra_ok.shape[0], :extra_ok.shape[1]] = extra_ok
                extra_ok = full
            extra_score = self._custom_score(ssn, cluster, pending, maps)
            if extra_score is not None:
                fulls = np.zeros((pp, nn), np.float32)
                fulls[:extra_score.shape[0], :extra_score.shape[1]] = \
                    extra_score
                extra_score = fulls

            t0 = time.perf_counter()
            solve_fn = solve_wave if solver == "wave" else solve
            result = solve_fn(
                s_nodes, s_tasks, s_jobs, s_queues,
                weights, arrays.eps, arrays.scalar_slot, aff,
                extra_ok=extra_ok, extra_score=extra_score,
            )
            assigned = np.asarray(result.assigned)
            pipelined = np.asarray(result.pipelined)
            never_ready = np.asarray(result.never_ready)
            fit_failed = np.asarray(result.fit_failed)
            metrics.device_solve_latency.observe(
                (time.perf_counter() - t0) * 1e3
            )
            metrics.snapshot_transfer_bytes.set(
                sum(a.nbytes for grp in (arrays.nodes, arrays.tasks,
                                         arrays.jobs, arrays.queues)
                    for a in grp)
            )

            made_progress = self._replay(
                ssn, maps, pending, assigned, pipelined, never_ready,
                fit_failed,
            )
            # Jobs discarded by the wave solver left their capacity on the
            # table this round; retry while the round also made progress
            # (so a retry can actually see different state).
            retry_discards = bool(never_ready.any()) and made_progress
            if not made_progress:
                return

    # Built-in predicate plugins whose checks are already encoded as
    # device masks; anything else registering a predicate is an
    # out-of-tree plugin evaluated host-side into the extra mask.
    BUILTIN_PREDICATE_PLUGINS = frozenset({"predicates"})

    def _custom_mask(self, ssn, cluster, pending, maps):
        """[P, N] verdicts from custom-plugin predicate callbacks and
        device-mask factories (ssn.add_predicate_fn from out-of-tree
        plugins + ssn.add_device_mask_fn).  None when only built-ins are
        registered — the overwhelmingly common case, which costs nothing.
        The host-predicate sweep is O(P x N) Python, the price the
        reference pays for EVERY predicate (scheduler_helper.go:65)."""
        custom = [
            (opt.name, ssn.predicate_fns[opt.name])
            for _, opt in ssn._tier_plugins("enabled_predicate")
            if opt.name in ssn.predicate_fns
            and opt.name not in self.BUILTIN_PREDICATE_PLUGINS
        ]
        mask_fns = [
            (nm, fn) for nm, fn in ssn.device_mask_fns.items()
            if nm not in self.BUILTIN_PREDICATE_PLUGINS
        ]
        if not custom and not mask_fns:
            return None
        n_nodes = len(maps.node_names)
        extra = np.ones((len(pending), n_nodes), bool)
        node_infos = [cluster.nodes[nm] for nm in maps.node_names]
        for _name, fn in custom:
            unexpected_logged = False
            for i, task in enumerate(pending):
                row = extra[i]
                for j, node in enumerate(node_infos):
                    if not row[j]:
                        continue
                    try:
                        fn(task, node)
                    except FitError:
                        row[j] = False
                    except Exception as err:
                        # A buggy plugin (wrong signature, attribute
                        # errors) would otherwise silently veto every
                        # node; surface the first instance.
                        if not unexpected_logged:
                            unexpected_logged = True
                            log.warning(
                                "custom predicate plugin %s raised %r "
                                "(treated as infeasible)", _name, err,
                            )
                        row[j] = False
        for _name, fn in mask_fns:
            contributed = fn(cluster, pending, maps.node_names)
            if contributed is not None:
                extra &= np.asarray(contributed, bool)
        return extra

    def _custom_score(self, ssn, cluster, pending, maps):
        """[P, N] additive scores from custom-plugin node-order callbacks
        (ssn.add_node_order_fn / add_batch_node_order_fn from out-of-tree
        plugins).  None when only built-ins are registered.  A plugin
        that registered add_score_weight_fn already scores through the
        device ScoreWeights — excluding on that signal (rather than a
        hardcoded name list) avoids double-counting and covers custom
        plugins that choose the weights route."""
        custom_map = [
            (opt.name, ssn.node_order_fns[opt.name])
            for _, opt in ssn._tier_plugins("enabled_node_order")
            if opt.name in ssn.node_order_fns
            and opt.name not in ssn.score_weight_fns
        ]
        custom_batch = [
            (opt.name, ssn.batch_node_order_fns[opt.name])
            for _, opt in ssn._tier_plugins("enabled_node_order")
            if opt.name in ssn.batch_node_order_fns
            and opt.name not in ssn.score_weight_fns
        ]
        if not custom_map and not custom_batch:
            return None
        n_nodes = len(maps.node_names)
        extra = np.zeros((len(pending), n_nodes), np.float32)
        node_infos = [cluster.nodes[nm] for nm in maps.node_names]
        col = {nm: j for j, nm in enumerate(maps.node_names)}
        for _name, fn in custom_map:
            logged = False
            for i, task in enumerate(pending):
                for j, node in enumerate(node_infos):
                    try:
                        extra[i, j] += float(fn(task, node))
                    except Exception as err:
                        if not logged:
                            logged = True
                            log.warning(
                                "custom node-order plugin %s raised %r",
                                _name, err,
                            )
        for _name, fn in custom_batch:
            logged = False
            for i, task in enumerate(pending):
                try:
                    for nm, sc in (fn(task, node_infos) or {}).items():
                        j = col.get(nm)
                        if j is not None:
                            extra[i, j] += float(sc)
                except Exception as err:
                    if not logged:
                        logged = True
                        log.warning(
                            "custom batch node-order plugin %s raised %r",
                            _name, err,
                        )
        # Defend the solver against buggy plugins: NaN poisons argmax
        # ordering and magnitudes near the infeasibility sentinel
        # (-3e38) break the progress guarantee.
        return np.clip(np.nan_to_num(extra, nan=0.0), -1e18, 1e18)

    # --------------------------------------------------------------- replay

    def _replay(self, ssn, maps, pending, assigned, pipelined, never_ready,
                fit_failed) -> bool:
        """Apply the solver's decisions to host session state in task order.

        Committed-job allocations go through session Allocate (status,
        node accounting, share events, bind dispatch once ready); pipelines
        apply unconditionally (session-level Pipeline semantics); discarded
        jobs get fit-error conditions.
        """
        progress = False
        for i, task in enumerate(pending):
            job = ssn.jobs.get(task.job)
            if job is None:
                continue
            ji = maps.job_index[task.job]
            node_idx = int(assigned[i])
            pipe_idx = int(pipelined[i])
            if node_idx >= 0 and not never_ready[ji]:
                node_name = maps.node_names[node_idx]
                node = ssn.nodes[node_name]
                # Divergence guard: host re-check of the fit decision.
                if not task.init_resreq.less_equal(node.idle):
                    log.error(
                        "Device/host divergence: task %s does not fit %s; "
                        "skipping", task.name, node_name,
                    )
                    continue
                try:
                    ssn.allocate_task(task, node_name)
                except VolumeBindFailure as e:
                    # Claim can't be allocated on the picked node: skip
                    # the task this cycle (allocate.go:226 logs the
                    # failed stmt.Allocate and moves on).
                    log.error("volume allocation failed for %s: %s",
                              task.name, e)
                    continue
                progress = True
            elif pipe_idx >= 0:
                node_name = maps.node_names[pipe_idx]
                ssn.pipeline(task, node_name)
                progress = True

        # Record fit errors for jobs that failed (gang.OnSessionClose reads
        # these to build Unschedulable conditions).
        for jid, ji in maps.job_index.items():
            job = ssn.jobs.get(jid)
            if job is None:
                continue
            if fit_failed[ji]:
                fe = FitErrors()
                fe.set_error("no feasible node for task")
                for task in job.task_status_index.get(
                    TaskStatus.Pending, {}
                ).values():
                    job.nodes_fit_errors[task.uid] = fe
        return progress


class _SessionView:
    """Adapter presenting a Session as a ClusterInfo for the encoder."""

    def __init__(self, ssn):
        self.jobs = ssn.jobs
        self.nodes = ssn.nodes
        self.queues = ssn.queues
        self.namespace_info = ssn.namespace_info
