"""In-memory cluster store: the scheduler cache.

The TPU-native equivalent of ``pkg/scheduler/cache/cache.go``: a mutex-guarded
mirror of cluster state mutated through an event API (the analog of the
reference's informer event handlers, ``cache/event_handlers.go:178-731``),
producing a deep-copied ``ClusterInfo`` snapshot per scheduling cycle
(cache.go:652-730).  It is also the system of record for the control plane:
controllers and the scheduler communicate only through this store, mirroring
how the reference's planes communicate only through the API server.

Bind/Evict mirror cache.go:439-554: they update the cached pod and dispatch to
the pluggable Binder/Evictor; failures resync the task from the store
(errTasks semantics, cache.go:627-649, simplified to synchronous resync).
"""

from __future__ import annotations

import copy
import threading
from typing import Callable, Dict, List, Optional

from ..api import (
    GROUP_NAME_ANNOTATION,
    NAMESPACE_WEIGHT_KEY,
    ClusterInfo,
    JobInfo,
    NamespaceInfo,
    Node,
    NodeInfo,
    Pod,
    PodGroup,
    PodGroupCondition,
    PodGroupPhase,
    PodPhase,
    PriorityClass,
    Queue,
    QueueInfo,
    ResourceQuota,
    TaskInfo,
    TaskStatus,
    pod_key,
)
from .interface import (
    Binder,
    Evictor,
    FakeBinder,
    FakeEvictor,
    FakeStatusUpdater,
    FakeVolumeBinder,
    StatusUpdater,
    VolumeBinder,
)

DEFAULT_QUEUE = "default"


class ClusterStore:
    """Mutex-guarded cluster state mirror + snapshotter."""

    def __init__(
        self,
        binder: Optional[Binder] = None,
        evictor: Optional[Evictor] = None,
        status_updater: Optional[StatusUpdater] = None,
        volume_binder: Optional[VolumeBinder] = None,
        default_queue: str = DEFAULT_QUEUE,
    ):
        self._lock = threading.RLock()
        self._jobs: Dict[str, JobInfo] = {}
        self._nodes: Dict[str, NodeInfo] = {}
        # The fast path (volcano_tpu.fastpath) commits directly to the pod
        # records + array mirror and marks the derived JobInfo/NodeInfo
        # object model stale; it is lazily rebuilt from pods on next access.
        self._objects_stale = False
        self.queues: Dict[str, QueueInfo] = {}
        self.priority_classes: Dict[str, PriorityClass] = {}
        self.namespace_weights: Dict[str, int] = {}
        # Raw spec objects (system of record for controllers):
        self.pods: Dict[str, Pod] = {}  # guarded-by: _lock
        self.pod_groups: Dict[str, PodGroup] = {}
        self.raw_queues: Dict[str, Queue] = {}
        # Controller-plane records (the reference stores these as CRDs /
        # core objects in the API server).
        self.batch_jobs: Dict[str, object] = {}  # key -> controllers.apis.Job
        self.commands: Dict[str, object] = {}  # name -> Command
        self.config_maps: Dict[str, Dict[str, str]] = {}  # ns/name -> data
        self.secrets: Dict[str, Dict[str, bytes]] = {}  # ns/name -> data
        self.services: Dict[str, Dict[str, object]] = {}  # ns/name -> spec
        # ns/name -> ingress-isolation spec (NetworkPolicy analog).
        self.network_policies: Dict[str, Dict[str, object]] = {}
        # Count of live pods carrying volume claims: the fast path's
        # commit gate is O(bound pods) when any exist, so claim-free
        # clusters must skip on an O(1) check that cannot miss a
        # volume-carrying pod (unlike gating on the claim registry,
        # which a custom volume binder need not use).
        self.n_volume_pods = 0  # guarded-by: _lock
        # ns/name -> persistent-volume-claim record
        # {"spec", "phase" Pending|Bound, "node", "owner_job"} — the PVC
        # store the job controller creates into (initiateJob PVCs,
        # job_controller_actions.go:394-531) and the volume binder
        # allocates/binds against (cache.go:557-564).
        self.pvcs: Dict[str, Dict[str, object]] = {}  # guarded-by: _lock

        self.binder: Binder = binder or FakeBinder()
        self.evictor: Evictor = evictor or FakeEvictor()
        self.status_updater: StatusUpdater = status_updater or FakeStatusUpdater()
        self.volume_binder: VolumeBinder = (
            volume_binder or StoreVolumeBinder(self)
        )

        # Watchers notified on spec mutations (the controllers' "informers").
        self._watchers: List[Callable[[str, str, object], None]] = []

        # Incremental struct-of-arrays mirror (the TPU-native snapshot
        # serializer's state; see cache/mirror.py).
        from .mirror import StoreMirror

        self.mirror = StoreMirror()
        self.mirror.attach(self.pods)

        # Async bind dispatch + rate-limited bind-failure resync
        # (cache.go:536-552 goroutine binds; 627-649 errTasks).  Sync by
        # default so tests observe binds immediately after a cycle;
        # production service/bench enable async.
        self.async_bind = False
        self._bind_dispatcher = None
        self._bind_fail_lock = threading.Lock()
        # Successful binds whose backoff entries the cycle thread should
        # clear at the next drain (tracked only while bind_backoff is
        # non-empty, so steady-state binds pay nothing).
        self._succeeded_bind_keys: List[str] = []  # guarded-by: _bind_fail_lock
        # [(key, pod), ...] reported by the dispatcher thread.
        self._failed_bind_keys: List[tuple] = []  # guarded-by: _bind_fail_lock
        # "ns/name" -> (consecutive fails, retry-not-before ts, pod uid).
        # Cycle-thread-owned: mutated only by drain_bind_failures and
        # delete_pod (both under _lock); the dispatcher thread queues
        # clears via _succeeded_bind_keys instead of touching it.
        self.bind_backoff: Dict[str, tuple] = {}  # guarded-by: _lock

        # Per-object user-visible event trail (the reference records
        # Kubernetes Events for Evict/Scheduled/FailedScheduling/
        # Unschedulable — cache.go:487,540,584,790).  Key: "Kind/ns/name";
        # value: list of [reason, message, count, first_ts, last_ts],
        # deduplicated k8s-style on (reason, message).
        # OrderedDict, NOT dict: FIFO eviction at MAX_EVENT_OBJECTS needs
        # O(1) popitem(last=False).  Popping a plain dict's first key via
        # next(iter(...)) re-scans the growing tombstone prefix — 53 us
        # per event at cap (quadratic overall), measured dominating the
        # config-4 close lane.
        import collections as _collections

        # guarded-by: _events_lock
        self._events: "_collections.OrderedDict[str, List[list]]" = (
            _collections.OrderedDict()
        )
        self._events_lock = threading.Lock()
        # Whole batches parked by record_events_deferred, folded into
        # the trails at the next read/record (off the cycle's clock).
        self._deferred_events: List[tuple] = []  # guarded-by: _events_lock

        # Deferred bind-record walks not yet materialized (see
        # defer_bind_records): registered at commit time so failure
        # paths can force them before reading pod records.
        self._record_walk_lock = threading.Lock()
        # guarded-by: _record_walk_lock
        self._pending_record_walks: List[list] = []

        # Parked dispatched-but-uncommitted device solve (pipeline.py
        # InflightSolve): written by the cycle thread at dispatch,
        # popped at the next cycle's top — but also reachable from
        # store.close()/Scheduler.stop() on other threads, so the slot
        # itself is lock-guarded (vclint VCL101/102 enforces this).
        self._inflight_solve = None  # guarded-by: _lock (any-receiver)
        # Parked dispatched-but-uncommitted rebalance plan (pipeline.py
        # InflightPlan): same ownership/locking contract as the solve
        # slot above.
        self._inflight_plan = None  # guarded-by: _lock (any-receiver)
        # Per-shard parked solves (shard.py, ISSUE 16): shard index ->
        # InflightSolve.  The default single-scheduler path never
        # touches this dict — it keeps using _inflight_solve above, so
        # VOLCANO_TPU_SHARDS=1 stays bitwise identical.  Same
        # any-receiver locking contract as the default slot
        # (cycle threads park/pop their own entry; close()/stop()
        # drain from other threads).
        self._shard_inflight: Dict[int, object] = {}  # guarded-by: _lock (any-receiver)
        # Shard ownership table (shard.ShardOwnershipTable), attached by
        # ShardedScheduler; None for the single-scheduler path.  The
        # table's mutable state (steal overrides + handoff epoch) is
        # itself guarded by THIS store's _lock — see shard.py contracts.
        self.shard_table = None  # guarded-by: _lock (any-receiver)
        # Mesh-path persistent plane cache (parallel/mesh.py
        # shard_wave_inputs): epoch-keyed per-device placements of the
        # epoch-stable planes the sharded devsnap does not own (e.g.
        # aff.node_dom).  Written by the cycle thread (FastCycle runs
        # under _lock), cleared by close() and pod-table compaction —
        # a declared, lock-guarded slot, not an ad-hoc attribute.
        self._mesh_plane_cache: Dict = {}  # guarded-by: _lock (any-receiver)
        # Incremental host-lane caches (ISSUE 8, fastpath.py /
        # fastpath_incr.py): content-validated results the steady-state
        # cycle reuses instead of re-deriving — the job-order rank (+
        # its key columns), the pending-task order, the encode-lane
        # profile/affinity structures, the commit path's object arrays,
        # the feed lane's unbind request gather, and the close lane's
        # gang gauge lists.  All written and read ONLY by the cycle
        # thread under the store lock (FastCycle class-holds) and
        # dropped on close(); each carries the mirror versions
        # (mutation-driven content, compact_gen/epoch keys) its entries
        # are valid under — the VCL50x keyed-cache contract.
        self._job_rank_cache = None  # guarded-by: _lock (any-receiver)
        self._pending_order_cache = None  # guarded-by: _lock (any-receiver)
        self._encode_cache = None  # guarded-by: _lock (any-receiver)
        self._objarr_cache = None  # guarded-by: _lock (any-receiver)
        self._unbind_gather_cache = None  # guarded-by: _lock (any-receiver)
        self._close_gang_cache = None  # guarded-by: _lock (any-receiver)
        # Device-lane incremental context (ISSUE 9, ops/devincr.py):
        # persistent [U, C] static planes + warm-shortlist candidates +
        # the null-delta skip proof, keyed on mirror versions
        # (epoch / compact_gen / node_liveness_gen) and content tokens
        # (class-table sig, profile generation, cnt0 hash) assembled by
        # FastCycle._devincr_prepare.  Cycle-thread only, under _lock.
        self._devincr_cache = None  # guarded-by: _lock (any-receiver)

        # Migration ledger (actions/rebalance.py MigrationLedger),
        # attached by the rebalance lane's first committed plan; the
        # delete_pod hook below restores terminating victims through it.
        self.migrations = None

        # Remote-solver client: a solver_service.RemoteSolver (single
        # connection) or a solver_pool.SolverPool (N replicas with
        # hedged dispatch / failover / what-if offload, ISSUE 15) —
        # attached by Service/bench/tests, None for local-solve stores.
        # Dispatch and fetch run only on the cycle thread; both client
        # types synchronize their own internals (each holds its own
        # lock, never the store's), so the slot needs no store-lock
        # guard beyond the cycle thread's ownership.
        self.remote_solver = None

        # Observability (obs/, ISSUE 3): the per-store span tracer and
        # the cycle flight recorder.  Both are internally synchronized
        # (the recorder's ring lock nests strictly inside _lock and is
        # never taken around store state); stdlib-only, so wiring them
        # unconditionally costs two small objects per store.
        from ..obs import (Auditor, FlightRecorder, JourneyLog,
                           SLOTracker, Tracer, journey_on)

        self.tracer = Tracer()
        self.flight = FlightRecorder()
        # Runtime conservation auditor + SLO layer (obs/audit.py,
        # obs/slo.py, ISSUE 13): internally synchronized like the
        # recorder (the auditor's lock nests strictly inside _lock and
        # is never taken around store state).  The mirror's writers
        # declare pod-count flows through mirror.audit; the fast cycle
        # reconciles + samples at cycle end.
        self.auditor = Auditor()
        self.auditor.slo = SLOTracker()
        self.mirror.audit = self.auditor
        # Pod-journey tracing (obs/journey.py, ISSUE 18): the
        # pod-centric event timeline behind /debug/pods/<uid>, the
        # per-queue time-to-bind latency feeds, and the endurance
        # conservation check.  Internally synchronized like the auditor
        # (its lock nests strictly inside _lock and is never taken
        # around store state).  Kill switch VOLCANO_TPU_JOURNEY=0
        # leaves the slot None so hot paths pay one attribute load.
        self.journey = (JourneyLog(slo=self.auditor.slo,
                                   auditor=self.auditor)
                        if journey_on() else None)
        self.mirror.journey = self.journey
        # Runtime lock enforcement (obs/lockdep.py, VOLCANO_TPU_LOCKDEP=1):
        # wraps this store's object graph so `# guarded-by:` annotations
        # are asserted live.  A no-op (one env read) when the switch is
        # off.
        from ..obs.lockdep import enable_lockdep

        enable_lockdep(self)
        # Monotonic pipelined solve-id: the flow link between a
        # dispatch span in cycle N and its commit spans in cycle N+1.
        self._solve_seq = 0  # guarded-by: _lock

        # Create the default queue at startup, weight 1 (cache.go:244-254).
        self.add_queue(Queue(name=default_queue, weight=1))

    # ------------------------------------------------------------- events

    EVENTS_PER_OBJECT = 16
    # Hyperscale guard: the event map sheds its oldest objects beyond this
    # (500k-pod snapshots would otherwise pin hundreds of MB of trails).
    MAX_EVENT_OBJECTS = 100_000

    def record_event(self, key: str, reason: str, message: str) -> None:
        """Append a user-visible event to an object's trail
        (``key`` = "Kind/ns/name", e.g. "Pod/default/job-a-0")."""
        import time as _time

        now = _time.time()
        with self._events_lock:
            self._drain_deferred_events_locked()
            self._record_event_locked(key, reason, message, now)

    def _record_event_locked(self, key, reason, message, now) -> None:
        if (key not in self._events
                and len(self._events) >= self.MAX_EVENT_OBJECTS):
            self._events.popitem(last=False)
        trail = self._events.setdefault(key, [])
        for ev in trail:
            if ev[0] == reason and ev[1] == message:
                ev[2] += 1
                ev[4] = now
                return
        trail.append([reason, message, 1, now, now])
        if len(trail) > self.EVENTS_PER_OBJECT:
            del trail[0]

    def record_events(self, items) -> None:
        """Batched ``record_event``: one lock acquisition and one clock
        read for a whole commit's worth of (key, reason, message) tuples.
        The reference's event recorder is likewise an async batcher the
        bind goroutines feed (cache.go:540); at 100k binds/cycle the
        per-call lock + clock overhead is what the batch amortizes."""
        import time as _time

        now = _time.time()
        items = items if isinstance(items, list) else list(items)
        if len(items) >= self.MAX_EVENT_OBJECTS:
            # Bulk fast path (100k bind Scheduled events): inserting N >>
            # cap distinct keys one at a time evicts every pre-existing
            # trail AND the first N-cap batch entries — identical end
            # state to clearing and keeping the batch tail.  Only taken
            # when the batch alone overflows the cap with distinct keys.
            tail: Dict[str, List[list]] = {}
            for key, reason, message in reversed(items):
                if key not in tail:
                    tail[key] = [[reason, message, 1, now, now]]
                    if len(tail) >= self.MAX_EVENT_OBJECTS:
                        break
            if len(tail) >= self.MAX_EVENT_OBJECTS:
                with self._events_lock:
                    # Parked deferred batches are older than this bulk:
                    # the clear below would evict them anyway; drop them
                    # so a later drain cannot resurrect them out of
                    # order.
                    self._deferred_events.clear()
                    self._events.clear()
                    # reversed() above collected newest-first; restore
                    # insertion order oldest-first for FIFO eviction.
                    self._events.update(reversed(tail.items()))
                return
        with self._events_lock:
            self._drain_deferred_events_locked()
            for key, reason, message in items:
                self._record_event_locked(key, reason, message, now)

    def record_events_deferred(self, items) -> None:
        """O(1) enqueue of a whole event batch; the per-event trail
        bookkeeping (~2 us each — 90 ms for a config-4 eviction cycle's
        45k events) runs at the next read/record instead of inside the
        scheduling cycle.  The reference's event recorder is likewise an
        async broadcaster the control loops feed."""
        import time as _time

        with self._events_lock:
            self._deferred_events.append((_time.time(), items))

    def _drain_deferred_events_locked(self) -> None:
        if not self._deferred_events:
            return
        batches, self._deferred_events = self._deferred_events, []
        for now, items in batches:
            for key, reason, message in items:
                self._record_event_locked(key, reason, message, now)

    def events_for(self, key: str) -> List[dict]:
        with self._events_lock:
            self._drain_deferred_events_locked()
            return [
                {"reason": r, "message": m, "count": c,
                 "first_seen": f, "last_seen": l}
                for r, m, c, f, l in self._events.get(key, [])
            ]

    # -------------------------------------------------- async bind machinery

    def defer_bind_records(self, keys_a, hosts_a, pods_a) -> list:
        """Register a deferred bind batch (numpy object arrays).  The
        100k-element tolist + pod.node_name record walk runs when the
        batch is materialized — normally on the bind dispatcher's worker
        thread, post-cycle (the reference's API-server-side NodeName
        write, cache.go:536-552) — but any failure path that is about to
        read pod RECORDS as scheduling truth must force it first via
        ``apply_pending_bind_records`` (committed-but-unnamed pods would
        read as unbound and double-schedule)."""
        entry = [keys_a, hosts_a, pods_a, False]
        with self._record_walk_lock:
            self._pending_record_walks.append(entry)
        return entry

    def _materialize_bind_entry(self, entry: list):
        """Idempotent: lists + node_name walk applied exactly once, from
        whichever thread gets here first."""
        with self._record_walk_lock:
            if not entry[3]:
                keys = entry[0].tolist()
                hosts = entry[1].tolist()
                pods = entry[2].tolist()
                for pod, hostname in zip(pods, hosts):
                    pod.node_name = hostname
                entry[0], entry[1], entry[2] = keys, hosts, pods
                entry[3] = True
                # Remove by IDENTITY, never list.remove: remove scans
                # with ==, and comparing this entry against a DIFFERENT
                # pending entry compares their numpy object arrays
                # elementwise — the ambiguous-truth ValueError that was
                # previously swallowed here left the entry stranded,
                # and apply_pending_bind_records (which loops until the
                # list drains) then never terminated.
                for i, e in enumerate(self._pending_record_walks):
                    if e is entry:
                        del self._pending_record_walks[i]
                        break
            return entry[0], entry[1], entry[2]

    def apply_pending_bind_records(self) -> None:
        """Synchronously apply every registered deferred record walk —
        called before any path that treats pod records as scheduling
        truth (mirror resync, the object-session fallback)."""
        while True:
            with self._record_walk_lock:
                if not self._pending_record_walks:
                    return
                entry = self._pending_record_walks[0]
            self._materialize_bind_entry(entry)

    def dispatch_binds(self, keys, hosts, pods,
                       entry: Optional[list] = None) -> None:
        """Queue a batch of binds on the background dispatcher (the
        goroutine analog); failures surface at the next cycle's
        ``drain_bind_failures``.  ``entry`` marks a deferred batch from
        ``defer_bind_records``: the worker materializes it at process
        time (pass keys/hosts/pods as None)."""
        if self._bind_dispatcher is None:
            from .bindqueue import BindDispatcher

            self._bind_dispatcher = BindDispatcher(
                self.binder, self._on_bind_failures,
                on_success=self._on_bind_success,
                materialize=self._materialize_bind_entry,
            )
        self._bind_dispatcher.dispatch(keys, hosts, pods, entry=entry)

    def flush_binds(self, timeout: Optional[float] = None) -> bool:
        if self._bind_dispatcher is None:
            return True
        return self._bind_dispatcher.flush(timeout)

    def close(self) -> None:
        """Stop background machinery (the bind dispatcher thread).  The
        dispatcher's callbacks pin this store, so long-lived processes
        creating many stores (benchmarks) must close them."""
        from ..pipeline import abandon_inflight, abandon_inflight_plan

        # A parked pipelined solve holds device buffers (or a remote
        # solver's reply slot); drop it with the store.  A parked
        # rebalance plan mutates nothing until committed — drop it too.
        abandon_inflight(self)
        abandon_inflight_plan(self)
        with self._lock:
            # Mesh plane cache pins per-device arrays across cycles;
            # a closed store must release them with everything else.
            self._mesh_plane_cache.clear()
            # Host-lane caches pin large arrays (and pod records, via
            # the object arrays); a closed store must not.
            self._job_rank_cache = None
            self._pending_order_cache = None
            self._encode_cache = None
            self._objarr_cache = None
            self._unbind_gather_cache = None
            self._close_gang_cache = None
            # Device-incremental planes pin device buffers (static
            # planes + shortlist candidates); release them too.
            self._devincr_cache = None
        if self._bind_dispatcher is not None:
            self._bind_dispatcher.stop()
            self._bind_dispatcher = None

    def _on_bind_failures(self, failed_pairs) -> None:
        """Dispatcher-thread hook: ``failed_pairs`` is [(key, pod), ...]."""
        with self._bind_fail_lock:
            self._failed_bind_keys.extend(failed_pairs)

    def _on_bind_success(self, keys: List[str], hosts: List[str]) -> None:
        """Dispatcher-thread hook: record Scheduled events (cache.go:540).
        Backoff clears are queued for the cycle thread (``bind_backoff``
        is cycle-thread-owned; popping it here could lose a concurrent
        ``drain_bind_failures`` increment)."""
        # vclint: disable=VCL101 -- dispatcher-thread truthiness probe
        # of the cycle-thread-owned dict; a stale read only delays when
        # clears are queued, and drain_bind_failures reconciles.  Taking
        # _lock here would block this thread for a whole cycle.
        if self.bind_backoff:
            with self._bind_fail_lock:
                self._succeeded_bind_keys.extend(keys)
        # One lock for the whole batch: this runs on the dispatcher
        # thread concurrently with the next scheduling cycle, and per-pod
        # lock churn at 100k binds starves the cycle thread of the GIL.
        self.record_events(
            (f"Pod/{key}", "Scheduled", f"bound to {host}")
            for key, host in zip(keys, hosts)
        )

    def drain_bind_failures(self) -> int:
        """Apply queued bind failures: the task re-enters Pending with an
        exponential backoff window during which the solver skips it (the
        rate-limited errTasks retry, cache.go:627-649).  Runs on the
        scheduling-cycle thread so all mirror mutation stays there."""
        import time as _time

        from .bindqueue import BACKOFF_BASE, BACKOFF_MAX

        with self._bind_fail_lock:
            failed = self._failed_bind_keys
            self._failed_bind_keys = []
            succeeded = self._succeeded_bind_keys
            self._succeeded_bind_keys = []
        if succeeded:
            with self._lock:
                for key in succeeded:
                    self.bind_backoff.pop(key, None)
        if not failed:
            return 0
        now = _time.time()
        n = 0
        with self._lock:
            for key, pod in failed:
                # Skip stale entries: the pod may have been replaced
                # (copy-on-write) or removed since the dispatch.
                if (pod is None or self.pods.get(pod.uid) is not pod
                        or pod.node_name is None):
                    continue
                fails, _, _ = self.bind_backoff.get(key, (0, 0.0, ""))
                fails += 1
                delay = min(BACKOFF_BASE * (2 ** (fails - 1)), BACKOFF_MAX)
                self.bind_backoff[key] = (fails, now + delay, pod.uid)
                pod.node_name = None
                if pod.volumes:
                    # Bind never landed: free the claims it pinned.
                    self.release_claims_for(pod)
                self.mirror.set_pod_state(
                    pod.uid, int(TaskStatus.Pending), -1
                )
                self.mark_objects_stale()
                self.record_event(
                    f"Pod/{key}", "FailedScheduling",
                    f"bind failed; retry in {delay:.0f}s "
                    f"(attempt {fails})",
                )
                # Watchers (job/podgroup controllers) must recount: the
                # commit already notified a bind for this pod before the
                # outcome was known.
                self._notify("Pod", "update", pod)
                n += 1
        return n

    # ----------------------------------------------- lazy object model

    @property
    def jobs(self) -> Dict[str, JobInfo]:
        if self._objects_stale:
            self._rebuild_objects()
        return self._jobs

    @property
    def nodes(self) -> Dict[str, NodeInfo]:
        if self._objects_stale:
            self._rebuild_objects()
        return self._nodes

    def mark_objects_stale(self) -> None:
        """Called by the fast path after a bulk commit: JobInfo/NodeInfo
        accounting will be rebuilt from the pod records on next read."""
        self._objects_stale = True

    def _rebuild_objects(self) -> None:
        """Recompute the JobInfo/NodeInfo object model from pods + pod
        groups (the same construction the informer replay performs,
        cache.go:376-417).  Job insertion order follows the mirror's row
        order = original arrival order, keeping dict-iteration behavior
        aligned with the incremental path."""
        with self._lock:
            if not self._objects_stale:
                return
            self._objects_stale = False
            self._nodes = {}
            for row, name in enumerate(self.mirror.n_name):
                if name is not None and self.mirror.n_alive[row]:
                    self._nodes[name] = NodeInfo(self.mirror.node_objs[row])
            self._jobs = {}
            for uid in self.mirror.j_uid:
                pg = self.pod_groups.get(uid) if uid else None
                if pg is None:
                    continue
                job = JobInfo(uid)
                job.set_pod_group(pg)
                if (
                    pg.priority_class
                    and pg.priority_class in self.priority_classes
                ):
                    job.priority = self.priority_classes[
                        pg.priority_class
                    ].value
                self._jobs[uid] = job
            for pod in self.pods.values():
                try:
                    self._add_task(pod)
                except (ValueError, KeyError) as err:
                    # Over-subscription here means upstream divergence;
                    # record and keep rebuilding (resync semantics).
                    import logging

                    logging.getLogger(__name__).error(
                        "rebuild: failed to re-add task %s: %s", pod.uid, err
                    )

    # ------------------------------------------------------------- watchers

    def watch(self, fn: Callable[[str, str, object], None]) -> None:
        """Register fn(kind, event, obj) called after each mutation."""
        self._watchers.append(fn)

    def _notify(self, kind: str, event: str, obj: object) -> None:
        for fn in self._watchers:
            fn(kind, event, obj)

    # ------------------------------------------------------- job bookkeeping

    def _get_or_create_job(self, job_id: str) -> JobInfo:
        job = self.jobs.get(job_id)
        if job is None:
            job = JobInfo(job_id)
            self.jobs[job_id] = job
        return job

    def _add_task(self, pod: Pod) -> None:
        ti = TaskInfo(pod)
        if ti.job:
            job = self._get_or_create_job(ti.job)
            job.add_task_info(ti)
        # Terminated pods hold no node resources (the reference filters
        # them out of node accounting, event_handlers.go isTerminated).
        if ti.status in (TaskStatus.Succeeded, TaskStatus.Failed):
            return
        if ti.node_name:
            node = self.nodes.get(ti.node_name)
            if node is None:
                # Task on an unknown node: hold a placeholder so accounting
                # catches up when the node arrives (event_handlers.go addTask).
                node = NodeInfo(None)
                node.name = ti.node_name
                self.nodes[ti.node_name] = node
            fresh = ti.clone()
            fresh.node_name = ""
            node.add_task(fresh)

    def _remove_task(self, pod: Pod) -> None:
        job_id = pod.job_id()
        job = self.jobs.get(job_id) if job_id else None
        if job is not None:
            ti = job.tasks.get(pod.uid)
            if ti is not None:
                job.delete_task_info(ti)
        if pod.node_name:
            node = self.nodes.get(pod.node_name)
            if node is not None:
                probe = TaskInfo(pod)
                if pod_key(pod) in node.tasks:
                    node.remove_task(probe)

    # --------------------------------------------------------- pod handlers

    def add_pod(self, pod: Pod) -> None:
        """Track a pod.  Ungrouped pods (no group annotation) still occupy
        node resources when bound (the reference tracks ANY pod with a
        NodeName, cache.go:320-332); they only lack a schedulable job until
        the podgroup controller wraps them."""
        with self._lock:
            self.pods[pod.uid] = pod
            if pod.volumes:
                self.n_volume_pods += 1
            self._add_task(pod)
            self.mirror.upsert_pod(pod, self.mirror.job_row)
            self._notify("Pod", "add", pod)

    def update_pod(self, pod: Pod) -> None:
        with self._lock:
            old = self.pods.get(pod.uid)
            if old is not None:
                self._remove_task(old)
                if old.volumes:
                    self.n_volume_pods -= 1
            self.pods[pod.uid] = pod
            if pod.volumes:
                self.n_volume_pods += 1
            self._add_task(pod)
            self.mirror.upsert_pod(pod, self.mirror.job_row)
            self._notify("Pod", "update", pod)

    def delete_pod(self, pod: Pod) -> None:
        with self._lock:
            old = self.pods.pop(pod.uid, None)
            if old is not None:
                self._remove_task(old)
                if old.volumes:
                    self.n_volume_pods -= 1
            if self.bind_backoff:
                # Deleted pods must not pin backoff entries forever.
                self.bind_backoff.pop(
                    f"{pod.namespace}/{pod.name}", None
                )
            gen0 = self.mirror.compact_gen
            self.mirror.remove_pod(pod.uid)
            self.mirror.maybe_compact()
            if self.mirror.compact_gen != gen0 and self._mesh_plane_cache:
                # Compaction renumbers rows and voids in-flight device
                # state wholesale; parked mesh placements resync too.
                self._mesh_plane_cache.clear()
            self._notify("Pod", "delete", pod)
            if self.migrations is not None and old is not None:
                # A terminating rebalance victim restores as a fresh
                # Pending pod (add_pod re-enters the re-entrant lock).
                self.migrations.pod_deleted(self, old)

    # -------------------------------------------------------- node handlers

    def add_node(self, node: Node) -> None:
        with self._lock:
            existing = self.nodes.get(node.name)
            if existing is not None:
                existing.set_node(node)
            else:
                self.nodes[node.name] = NodeInfo(node)
            self.mirror.upsert_node(node)
            self._notify("Node", "add", node)

    def update_node(self, node: Node) -> None:
        with self._lock:
            existing = self.nodes.get(node.name)
            if existing is None:
                self.nodes[node.name] = NodeInfo(node)
            else:
                existing.set_node(node)
            self.mirror.upsert_node(node)
            self._notify("Node", "update", node)

    def delete_node(self, name: str) -> None:
        with self._lock:
            self.nodes.pop(name, None)
            self.mirror.remove_node(name)
            self._notify("Node", "delete", name)

    # --------------------------------------------------- pod group handlers

    def add_pod_group(self, pg: PodGroup) -> None:
        with self._lock:
            self.pod_groups[pg.uid] = pg
            job = self._get_or_create_job(pg.uid)
            job.set_pod_group(pg)
            if pg.priority_class and pg.priority_class in self.priority_classes:
                job.priority = self.priority_classes[pg.priority_class].value
            self.mirror.upsert_pod_group(pg, job.priority)
            self._notify("PodGroup", "add", pg)

    def update_pod_group(self, pg: PodGroup) -> None:
        with self._lock:
            self.pod_groups[pg.uid] = pg
            job = self._get_or_create_job(pg.uid)
            job.set_pod_group(pg)
            if pg.priority_class and pg.priority_class in self.priority_classes:
                job.priority = self.priority_classes[pg.priority_class].value
            self.mirror.upsert_pod_group(pg, job.priority)
            self._notify("PodGroup", "update", pg)

    def delete_pod_group(self, uid: str) -> None:
        with self._lock:
            self.pod_groups.pop(uid, None)
            job = self.jobs.get(uid)
            if job is not None:
                job.unset_pod_group()
                if not job.tasks:
                    del self.jobs[uid]
            self.mirror.remove_pod_group(uid)
            self._notify("PodGroup", "delete", uid)

    # ------------------------------------------------------- queue handlers

    def add_queue(self, queue: Queue) -> None:
        with self._lock:
            self.raw_queues[queue.name] = queue
            self.queues[queue.name] = QueueInfo(queue)
            self._notify("Queue", "add", queue)

    def update_queue(self, queue: Queue) -> None:
        with self._lock:
            self.raw_queues[queue.name] = queue
            self.queues[queue.name] = QueueInfo(queue)
            self._notify("Queue", "update", queue)

    def delete_queue(self, name: str) -> None:
        with self._lock:
            self.raw_queues.pop(name, None)
            self.queues.pop(name, None)
            self._notify("Queue", "delete", name)

    # ------------------------------------------- priority class / quota

    def add_priority_class(self, pc: PriorityClass) -> None:
        with self._lock:
            self.priority_classes[pc.name] = pc
            self._notify("PriorityClass", "add", pc)

    def delete_priority_class(self, name: str) -> None:
        with self._lock:
            self.priority_classes.pop(name, None)
            self._notify("PriorityClass", "delete", name)

    def add_resource_quota(self, quota: ResourceQuota) -> None:
        """Track namespace weight from the quota annotation
        (event_handlers.go quota path + namespace_info.go:33-37)."""
        with self._lock:
            raw = quota.annotations.get(NAMESPACE_WEIGHT_KEY)
            if raw is not None:
                try:
                    self.namespace_weights[quota.namespace] = max(
                        self.namespace_weights.get(quota.namespace, 0), int(raw)
                    )
                except ValueError:
                    pass
            self._notify("ResourceQuota", "add", quota)

    # ---------------------------------------------------- controller plane

    def add_batch_job(self, job) -> None:
        with self._lock:
            self.batch_jobs[job.key] = job
            self._notify("Job", "add", job)

    def update_batch_job(self, job) -> None:
        with self._lock:
            self.batch_jobs[job.key] = job
            self._notify("Job", "update", job)

    def delete_batch_job(self, key: str) -> None:
        with self._lock:
            job = self.batch_jobs.pop(key, None)
            if job is not None:
                self._notify("Job", "delete", job)

    def add_command(self, command) -> None:
        with self._lock:
            self.commands[command.name] = command
            self._notify("Command", "add", command)

    def delete_command(self, name: str) -> None:
        with self._lock:
            self.commands.pop(name, None)

    def put_config_map(self, ns: str, name: str, data: Dict[str, str]) -> None:
        with self._lock:
            self.config_maps[f"{ns}/{name}"] = dict(data)

    def delete_config_map(self, ns: str, name: str) -> None:
        with self._lock:
            self.config_maps.pop(f"{ns}/{name}", None)

    def put_secret(self, ns: str, name: str, data) -> None:
        with self._lock:
            self.secrets[f"{ns}/{name}"] = dict(data)

    def delete_secret(self, ns: str, name: str) -> None:
        with self._lock:
            self.secrets.pop(f"{ns}/{name}", None)

    def put_service(self, ns: str, name: str, spec) -> None:
        with self._lock:
            self.services[f"{ns}/{name}"] = spec

    def delete_service(self, ns: str, name: str) -> None:
        with self._lock:
            self.services.pop(f"{ns}/{name}", None)

    def put_pvc(self, ns: str, name: str, spec,
                owner_job: str = "") -> None:
        """Create/replace a claim record (phase Pending until the volume
        binder binds it)."""
        with self._lock:
            self.pvcs[f"{ns}/{name}"] = {
                "spec": dict(spec) if spec else {},
                "phase": "Pending",
                "node": None,
                "owner_job": owner_job,
            }

    def delete_pvc(self, ns: str, name: str) -> None:
        with self._lock:
            self.pvcs.pop(f"{ns}/{name}", None)

    def release_claims_for(self, pod) -> None:
        """Roll back a failed bind's claim state: claims this pod
        provisioned/bound return to Pending (free to provision anywhere)
        unless another placed pod still references them.  Without this a
        bind failure would pin the claim to the failed node forever and
        the pod could never re-place elsewhere."""
        if not pod.volumes:
            return
        with self._lock:
            claims = {f"{pod.namespace}/{c}" for c, _ in pod.volumes}
            still_held = set()
            for other in self.pods.values():
                if (other.uid == pod.uid or not other.volumes
                        or other.node_name is None):
                    continue
                for c, _ in other.volumes:
                    k = f"{other.namespace}/{c}"
                    if k in claims:
                        still_held.add(k)
            for k in claims - still_held:
                rec = self.pvcs.get(k)
                if rec is not None:
                    rec["phase"] = "Pending"
                    rec["node"] = None

    def delete_pvcs_owned_by(self, job_key: str) -> int:
        """Owner-reference cleanup: claims created by the controller for
        a job die with the Job object (createPVC sets an owner ref,
        job_controller_actions.go:512-531)."""
        with self._lock:
            doomed = [k for k, rec in self.pvcs.items()
                      if rec.get("owner_job") == job_key]
            for k in doomed:
                del self.pvcs[k]
        return len(doomed)

    def put_network_policy(self, ns: str, name: str, spec) -> None:
        """Job-scoped ingress isolation record (the NetworkPolicy the
        reference svc plugin creates, svc.go:252-299)."""
        with self._lock:
            self.network_policies[f"{ns}/{name}"] = spec

    def delete_network_policy(self, ns: str, name: str) -> None:
        with self._lock:
            self.network_policies.pop(f"{ns}/{name}", None)

    # -------------------------------------------------------------- snapshot

    def snapshot(self) -> ClusterInfo:
        """Deep-copied point-in-time view (cache.go:652-730)."""
        with self._lock:
            info = ClusterInfo()
            for name, node in self.nodes.items():
                info.nodes[name] = node.clone()
            for name, queue in self.queues.items():
                info.queues[name] = queue.clone()
            namespaces = set()
            for job_id, job in self.jobs.items():
                # Jobs without a PodGroup are not schedulable yet
                # (cache.go snapshot skips jobs with missing PodGroup).
                if job.pod_group is None:
                    continue
                info.jobs[job_id] = job.clone()
                namespaces.add(job.namespace)
            for ns in namespaces:
                info.namespace_info[ns] = NamespaceInfo(
                    ns, self.namespace_weights.get(ns, 1)
                )
            return info

    # ------------------------------------------------------------ side effects

    # holds: _lock
    def _replace_pod(self, pod, **mutations):
        """Copy-on-write pod replacement: the stored Pod is replaced,
        never mutated, so snapshot TaskInfos holding the old Pod keep
        their point-in-time view.  Re-indexes the job task sets and the
        mirror; returns the new record.  Caller holds the lock."""
        self._remove_task(pod)
        pod = copy.copy(pod)
        for name, value in mutations.items():
            setattr(pod, name, value)
        self.pods[pod.uid] = pod
        self._add_task(pod)
        self.mirror.upsert_pod(pod, self.mirror.job_row)
        return pod

    def bind(self, task: TaskInfo, hostname: str) -> None:
        """Bind task's pod to a host (cache.go:492-554, synchronous
        here)."""
        with self._lock:
            pod = self.pods.get(task.uid)
            if pod is None:
                raise KeyError(f"unknown pod {task.uid}")
            self.binder.bind(task, hostname)
            pod = self._replace_pod(pod, node_name=hostname)
            self.record_event(
                f"Pod/{pod.namespace}/{pod.name}", "Scheduled",
                f"bound to {hostname}",
            )
            self._notify("Pod", "bind", pod)

    def evict(self, task: TaskInfo, reason: str) -> None:
        """Evict task's pod (cache.go:439-489, synchronous here)."""
        with self._lock:
            pod = self.pods.get(task.uid)
            if pod is None:
                raise KeyError(f"unknown pod {task.uid}")
            # Mark the cached pod as terminating: resources become
            # Releasing.
            pod = self._replace_pod(pod, deleting=True)
            try:
                self.evictor.evict(pod)
            except Exception:
                # Evict dispatch failed (EvictFailure or a transport
                # error): the pod is NOT terminating.  Revert the record
                # (cache.go:461-466 resyncTask) and let the next cycle
                # re-select victims.
                pod = self._replace_pod(pod, deleting=False)
                self.record_event(
                    f"Pod/{pod.namespace}/{pod.name}", "EvictFailed",
                    "evict dispatch failed; will retry",
                )
                self._notify("Pod", "update", pod)
                return
            self.record_event(
                f"Pod/{pod.namespace}/{pod.name}", "Evict",
                reason or "evicted by scheduler",
            )
            self._notify("Pod", "evict", pod)

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        self.volume_binder.allocate_volumes(task, hostname)

    def bind_volumes(self, task: TaskInfo) -> None:
        self.volume_binder.bind_volumes(task)

    def update_job_status(self, job: JobInfo) -> JobInfo:
        """Write PodGroup status back (interface.go UpdateJobStatus +
        job_updater.go semantics)."""
        with self._lock:
            pg = job.pod_group
            if pg is None:
                return job
            stored = self.pod_groups.get(pg.uid)
            if stored is not None:
                stored.status = pg.status
                # Keep the mirror's persistent status-snapshot columns
                # coherent: the fast path's write-back change detection
                # reads them as "last written" state.
                self.mirror.refresh_pod_group_status(stored)
                self.status_updater.update_pod_group(stored)
                self._notify("PodGroup", "status", stored)
            return job

    def record_job_condition(self, job: JobInfo, condition: PodGroupCondition) -> None:
        if job.pod_group is None:
            return
        with self._lock:
            # Write to the *stored* PodGroup (the snapshot may share or hold
            # its own reference); replace same-type condition, mirroring
            # jobUpdater behavior.
            pg = self.pod_groups.get(job.pod_group.uid, job.pod_group)
            conditions = [c for c in pg.status.conditions if c.type != condition.type]
            conditions.append(condition)
            pg.status.conditions = conditions
            self.mirror.refresh_pod_group_status(pg)

    # --------------------------------------------------------------- helpers

    def pending_pods(self) -> List[Pod]:
        with self._lock:
            return [
                p
                for p in self.pods.values()
                if p.phase == PodPhase.Pending and not p.node_name
            ]

    def task_in_store(self, uid: str) -> Optional[Pod]:
        with self._lock:
            return self.pods.get(uid)


class StoreVolumeBinder:
    """Volume binder against the store's claim registry (the
    defaultVolumeBinder of cache.go:211-222, backed by ``store.pvcs``
    instead of the upstream scheduler volume binder).

    Accepts either a TaskInfo or a bare Pod (the fast path hands pods);
    pods with no ``volumes`` cost one attribute read."""

    def __init__(self, store: "ClusterStore"):
        self._store = store

    @staticmethod
    def _pod(task):
        return getattr(task, "pod", task)

    def allocate_volumes(self, task, hostname: str) -> None:
        from .interface import VolumeBindFailure

        pod = self._pod(task)
        with self._store._lock:
            for claim, _mount in pod.volumes:
                rec = self._store.pvcs.get(f"{pod.namespace}/{claim}")
                if rec is None:
                    raise VolumeBindFailure(
                        f"claim {pod.namespace}/{claim} not found for "
                        f"{pod.name}"
                    )
                if rec["phase"] == "Pending":
                    # WaitForFirstConsumer analog: the claim provisions
                    # on the node the scheduler picked.
                    rec["node"] = hostname
                elif rec["node"] not in (None, hostname):
                    # Already provisioned elsewhere: node-local claims
                    # can't follow the pod (RWO pinned to another host).
                    raise VolumeBindFailure(
                        f"claim {pod.namespace}/{claim} is bound to "
                        f"{rec['node']}, pod placed on {hostname}"
                    )

    def bind_volumes(self, task) -> None:
        pod = self._pod(task)
        with self._store._lock:
            for claim, _mount in pod.volumes:
                rec = self._store.pvcs.get(f"{pod.namespace}/{claim}")
                if rec is not None:
                    rec["phase"] = "Bound"
        if hasattr(task, "volume_ready"):
            task.volume_ready = True
