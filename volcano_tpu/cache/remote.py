"""Remote binder: binds crossing a real process boundary.

The reference's bind side effect is an RPC to the API server from an
async goroutine (``pkg/scheduler/cache/cache.go:492-554``); the
scheduler process never shares memory with the system of record.  This
module is the demonstration that volcano_tpu's single-process design
keeps that boundary pluggable (PARITY.md deviation 5): ``HttpBinder``
implements the ``Binder`` protocol over HTTP/JSON against a second
process running ``RemoteBindService``, and drops into ``ClusterStore``
unchanged — the ``BindDispatcher`` drives it exactly like the in-process
fake, including the errTasks backoff path on failures.

Server:  ``python -m volcano_tpu.cache.remote --port 18476``
Client:  ``ClusterStore(binder=HttpBinder("http://127.0.0.1:18476"))``

The evict and status-update side effects cross the same boundary
(``cache.go:439-491`` Evict, ``:556-599`` UpdateJobStatus /
taskUnschedulable): ``HttpEvictor`` and ``HttpStatusUpdater`` are
drop-ins for the ``Evictor`` / ``StatusUpdater`` protocols against the
same second process, with failure injection driving the
EvictFailure -> revert-to-Running -> retry path.

Protocol (JSON over HTTP, stdlib only — no new dependencies):
  POST /bind   {"binds": [{"key": "ns/name", "host": "n0"}, ...]}
               -> 200 {"failed": ["ns/name", ...]}   (per-key outcomes)
  GET  /binds  -> 200 {"ns/name": "n0", ...}         (test observability)
  POST /evict  {"evicts": [{"key": "ns/name", "reason": "..."}]}
               -> 200 {"failed": ["ns/name", ...]}
  GET  /evicts -> 200 ["ns/name", ...]               (eviction channel)
  POST /podgroups      {"groups": [{"uid": ..., "phase": ...,
                        "running": N, "failed": N, "succeeded": N}]}
  GET  /podgroups      -> 200 {"uid": {...last written status...}}
  POST /podconditions  {"conditions": [{"key": "ns/name", ...}]}
  POST /chaos  {"fail_next": N, "fail_next_evicts": M}
               (exercises BindFailure/EvictFailure -> backoff/revert ->
               retry end to end)
  GET  /healthz -> 200 "ok"
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Sequence

from .interface import BindFailure, EvictFailure

log = logging.getLogger(__name__)


class _HttpTransport:
    """Shared POST/GET plumbing for the remote side-effect clients."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        # Span sink (obs/trace.py Tracer): the service wires the store's
        # tracer in (default: the shared no-op), and every side-effect
        # RPC lands in the cycle trace as an "rpc" track span.  These
        # POSTs run on the bind dispatcher / cycle threads, so they go
        # through the tracer's thread-safe timed_event() — never the
        # cycle span stack.
        from ..obs.trace import null_tracer

        self.tracer = null_tracer()

    def _post(self, path: str, payload: dict) -> dict:
        req = urllib.request.Request(
            f"{self.base_url}{path}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with self.tracer.timed_event(f"rpc:{path.lstrip('/')}",
                                     args={"url": self.base_url}):
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read() or b"{}")

    def _get(self, path: str):
        with urllib.request.urlopen(f"{self.base_url}{path}",
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read())


class HttpBinder(_HttpTransport):
    """``Binder`` over HTTP/JSON (drop-in for the in-process binder).

    ``bind_keys`` posts the whole batch in one request and raises
    ``BindFailure`` with the per-key failures the server reports;
    transport errors raise plain exceptions, which the dispatcher treats
    as indeterminate and re-drives per key via ``bind`` (idempotent:
    re-binding a landed key to the same host is a no-op server-side).
    """

    # --------------------------------------------------------------- Binder

    def bind_keys(self, keys: Sequence[str],
                  hostnames: Sequence[str]) -> None:
        out = self._post("/bind", {
            "binds": [{"key": k, "host": h}
                      for k, h in zip(keys, hostnames)],
        })
        failed = out.get("failed", [])
        if failed:
            raise BindFailure(failed)

    def bind(self, task, hostname: str) -> None:
        key = f"{task.namespace}/{task.name}"
        out = self._post("/bind", {"binds": [{"key": key,
                                              "host": hostname}]})
        if out.get("failed"):
            raise BindFailure([key])

    # ---------------------------------------------------------------- extras

    def binds(self) -> Dict[str, str]:
        """Fetch the server-side bind table (test observability)."""
        with urllib.request.urlopen(f"{self.base_url}/binds",
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def chaos_fail_next(self, n: int) -> None:
        self._post("/chaos", {"fail_next": n})


class HttpEvictor(_HttpTransport):
    """``Evictor`` over HTTP/JSON: the delete-pod API call of
    ``cache.go:439-491`` as a real RPC.  ``evict_keys`` posts a whole
    eviction batch (the fast path's flush) and raises ``EvictFailure``
    with the keys the server rejected; per-pod ``evict`` serves the
    object path's statement flush."""

    def evict_keys(self, keys: Sequence[str],
                   reason: str = "preempted") -> None:
        out = self._post("/evict", {
            "evicts": [{"key": k, "reason": reason} for k in keys],
        })
        failed = out.get("failed", [])
        if failed:
            raise EvictFailure(failed)

    def evict(self, pod) -> None:
        self.evict_keys([f"{pod.namespace}/{pod.name}"])

    def evicts(self) -> List[str]:
        """Server-side eviction channel (test observability)."""
        return self._get("/evicts")

    def chaos_fail_next(self, n: int) -> None:
        self._post("/chaos", {"fail_next_evicts": n})


class HttpStatusUpdater(_HttpTransport):
    """``StatusUpdater`` over HTTP/JSON: the PodGroup status /
    pod-condition API writes of ``cache.go:556-599`` as real RPCs.
    Updates are fire-and-forget per the reference (job_updater.go logs
    and drops failed status writes; the next cycle rewrites them)."""

    @staticmethod
    def _group_payload(pg) -> dict:
        st = pg.status
        return {
            "uid": pg.uid,
            "phase": st.phase,
            "running": int(st.running),
            "failed": int(st.failed),
            "succeeded": int(st.succeeded),
        }

    def update_pod_group(self, pg) -> None:
        try:
            self._post("/podgroups",
                       {"groups": [self._group_payload(pg)]})
        except (urllib.error.URLError, OSError) as e:
            log.warning("remote podgroup status write failed: %s", e)

    def update_pod_groups(self, pgs) -> None:
        """Batched write-back: one POST for a whole session close.  The
        fast path's _close prefers this when present — per-group round
        trips at 12k changed groups would dwarf the cycle budget.

        Raises on transport failure, unlike the per-group method: the
        fast path's close has a retry mechanism (it re-marks the batch
        dirty so the NEXT cycle rewrites it), whereas a swallowed error
        here would leave the remote permanently stale — close's change
        detection compares against the already-advanced local status."""
        self._post("/podgroups", {
            "groups": [self._group_payload(pg) for pg in pgs],
        })

    def update_pod_condition(self, pod, condition) -> None:
        try:
            self._post("/podconditions", {"conditions": [{
                "key": f"{pod.namespace}/{pod.name}",
                "type": getattr(condition, "type", str(condition)),
                "status": getattr(condition, "status", ""),
            }]})
        except (urllib.error.URLError, OSError) as e:
            log.warning("remote pod condition write failed: %s", e)

    def pod_groups(self) -> Dict[str, dict]:
        return self._get("/podgroups")

    def pod_conditions(self) -> List[dict]:
        return self._get("/podconditions")


class RemoteBindService:
    """The second process: receives binds, records them, and can inject
    failures on request (the cluster control plane of the demo)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 18476):
        self.binds: Dict[str, str] = {}
        self.evicts: List[str] = []
        self.pod_groups: Dict[str, dict] = {}
        self.pod_conditions: List[dict] = []
        self.fail_next = 0
        self.fail_next_evicts = 0
        self._lock = threading.Lock()
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                log.debug("remote-binder: " + fmt, *args)

            def _reply(self, code: int, body: bytes):
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._reply(200, b'"ok"')
                elif self.path == "/binds":
                    with service._lock:
                        body = json.dumps(service.binds).encode()
                    self._reply(200, body)
                elif self.path == "/evicts":
                    with service._lock:
                        body = json.dumps(service.evicts).encode()
                    self._reply(200, body)
                elif self.path == "/podgroups":
                    with service._lock:
                        body = json.dumps(service.pod_groups).encode()
                    self._reply(200, body)
                elif self.path == "/podconditions":
                    with service._lock:
                        body = json.dumps(service.pod_conditions).encode()
                    self._reply(200, body)
                else:
                    self._reply(404, b"{}")

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/bind":
                    failed: List[str] = []
                    with service._lock:
                        if service.fail_next > 0:
                            service.fail_next -= 1
                            failed = [b["key"]
                                      for b in payload.get("binds", [])]
                        else:
                            for b in payload.get("binds", []):
                                service.binds[b["key"]] = b["host"]
                    self._reply(200, json.dumps(
                        {"failed": failed}).encode())
                elif self.path == "/evict":
                    failed = []
                    with service._lock:
                        if service.fail_next_evicts > 0:
                            service.fail_next_evicts -= 1
                            failed = [e["key"]
                                      for e in payload.get("evicts", [])]
                        else:
                            for e in payload.get("evicts", []):
                                service.evicts.append(e["key"])
                    self._reply(200, json.dumps(
                        {"failed": failed}).encode())
                elif self.path == "/podgroups":
                    with service._lock:
                        for g in payload.get("groups", []):
                            service.pod_groups[g["uid"]] = g
                    self._reply(200, b"{}")
                elif self.path == "/podconditions":
                    with service._lock:
                        service.pod_conditions.extend(
                            payload.get("conditions", []))
                    self._reply(200, b"{}")
                elif self.path == "/chaos":
                    with service._lock:
                        if "fail_next" in payload:
                            service.fail_next = int(payload["fail_next"])
                        if "fail_next_evicts" in payload:
                            service.fail_next_evicts = int(
                                payload["fail_next_evicts"])
                    self._reply(200, b"{}")
                else:
                    self._reply(404, b"{}")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description="volcano_tpu remote binder")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=18476)
    args = ap.parse_args(argv)
    svc = RemoteBindService(args.host, args.port)
    # Readiness line for process supervisors / tests.
    print(f"remote-binder listening on {args.host}:{svc.port}",
          flush=True)
    try:
        svc.serve_forever()
    except KeyboardInterrupt:
        svc.shutdown()


if __name__ == "__main__":
    main()
