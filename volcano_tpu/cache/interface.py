"""Cache side-effect interfaces and test fakes.

Mirrors ``pkg/scheduler/cache/interface.go:27-78`` (Cache, Binder, Evictor,
StatusUpdater, VolumeBinder) and the fakes in
``pkg/scheduler/util/test_utils.go:94-170`` that the reference's action tests
are built on.  Real deployments plug in binders that talk to the cluster
control plane; tests assert on the fake channels.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Protocol

from ..api import JobInfo, PodGroup, TaskInfo


class Binder(Protocol):
    """``bind`` must be idempotent for a (task, hostname) pair: the
    dispatcher re-drives individual binds after an indeterminate batch
    failure, so a key that already landed may be bound again to the
    same host (bindqueue.py worker)."""

    def bind(self, task: TaskInfo, hostname: str) -> None: ...


class Evictor(Protocol):
    def evict(self, pod) -> None: ...


class StatusUpdater(Protocol):
    def update_pod_condition(self, pod, condition) -> None: ...

    def update_pod_group(self, pg: PodGroup) -> None: ...


class VolumeBinder(Protocol):
    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None: ...

    def bind_volumes(self, task: TaskInfo) -> None: ...


class VolumeBindFailure(Exception):
    """Raised by a volume binder when a task's claims cannot be
    allocated/bound (missing claim, conflicting node).  The commit path
    treats the task like a failed bind: it reverts to Pending and
    retries next cycle."""


class EvictFailure(Exception):
    """Raised by an evictor when some evictions could not be dispatched.

    ``failed`` holds the "ns/name" keys that did NOT evict.  Both evict
    paths revert exactly those pods to Running (deleting flag cleared,
    mirror status restored) so the next preempt/reclaim cycle re-selects
    them — the reference's Evict-RPC error path resyncs the task from
    the API server the same way (cache.go:439-491 resyncTask)."""

    def __init__(self, failed):
        super().__init__(f"{len(failed)} evictions failed")
        self.failed = list(failed)


class BindFailure(Exception):
    """Raised by a binder when some binds could not be dispatched.

    ``failed`` holds the "ns/name" keys that did NOT bind.  The fast
    path reverts exactly those tasks to Pending so the next cycle
    retries them — the errTasks resync semantics of cache.go:627-649
    (there: failed bind RPCs push the task onto a rate-limited queue
    that re-syncs it from the API server)."""

    def __init__(self, failed):
        super().__init__(f"{len(failed)} binds failed")
        self.failed = list(failed)


class FakeBinder:
    """Records binds into a map + ordered channel (test_utils.go:94-117)."""

    def __init__(self):
        self.binds: Dict[str, str] = {}
        self.channel: List[str] = []
        self._lock = threading.Lock()

    def bind(self, task: TaskInfo, hostname: str) -> None:
        with self._lock:
            key = f"{task.namespace}/{task.name}"
            self.binds[key] = hostname
            self.channel.append(key)

    def bind_batch(self, pairs) -> None:
        """Batched dispatch used by the fast path (the async-goroutine
        bind fan-out of cache.go:536-552, collapsed into one call)."""
        with self._lock:
            for task, hostname in pairs:
                key = f"{task.namespace}/{task.name}"
                self.binds[key] = hostname
                self.channel.append(key)

    def bind_keys(self, keys, hostnames) -> None:
        """Key-level batched dispatch: the caller supplies precomputed
        "ns/name" keys, so the whole batch lands via C-level dict/list
        operations."""
        with self._lock:
            self.binds.update(zip(keys, hostnames))
            self.channel.extend(keys)


class FakeEvictor:
    """Records evictions (test_utils.go:119-143)."""

    def __init__(self):
        self.evicts: List[str] = []
        self.channel: List[str] = []
        self._lock = threading.Lock()

    def evict(self, pod) -> None:
        with self._lock:
            key = f"{pod.namespace}/{pod.name}"
            self.evicts.append(key)
            self.channel.append(key)


class FakeStatusUpdater:
    """No-op status updater (test_utils.go:145-157)."""

    def __init__(self):
        self.pod_conditions: List[object] = []
        self.pod_groups: List[PodGroup] = []

    def update_pod_condition(self, pod, condition) -> None:
        self.pod_conditions.append((pod, condition))

    def update_pod_group(self, pg: PodGroup) -> None:
        self.pod_groups.append(pg)

    def update_pod_groups(self, pgs) -> None:
        """Batched write-back (one call per session close).  Delegates
        per group so instance-level overrides of ``update_pod_group``
        (a common test seam) still observe every write; true batch
        transports (HttpStatusUpdater) override this wholesale."""
        for pg in pgs:
            self.update_pod_group(pg)


class FakeVolumeBinder:
    """No-op volume binder (test_utils.go:159-170)."""

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        return None

    def bind_volumes(self, task: TaskInfo) -> None:
        return None
