"""Incremental struct-of-arrays mirror of the cluster store.

The TPU-native replacement for the reference's per-cycle deep-copied
snapshot (``pkg/scheduler/cache/cache.go:652-730``): instead of cloning
every Job/Node object and re-flattening it into device arrays each cycle
(O(cluster) Python work), the store keeps a columnar pod/node/job table
that is updated *incrementally* as objects mutate — the array analog of the
reference's informer-driven cache (``cache/event_handlers.go:178-731``).

Design:

- **Static per-pod features are encoded once, at add time.**  Resource
  requests, label selectors, tolerations, host ports, node-affinity terms
  and inter-pod affinity terms are interned against store-scoped
  *append-only* dictionaries and stored as CSR segments (flat index/value
  buffers + per-row offsets).  Because the dictionaries only grow, encoded
  rows never go stale.  The feature blob is cached on the ``Pod`` object, so
  the copy-on-write pod replacement done by ``bind``/``evict`` reuses it.
- **Dynamic per-pod state is three scalars** (status i8-equivalent, node
  row, job row) updated in place.
- **Everything aggregate is derived per cycle by vectorized reductions**
  (``np.add.at`` over the live rows): node idle/used/releasing, queue
  allocated, per-job status counts, affinity resident counts.  No
  incremental double-entry bookkeeping to drift.
- Rows are tombstoned on delete and compacted when more than half the
  table is dead.

The fast scheduling path (``volcano_tpu.fastpath``) consumes these tables
directly; the object model (``api.info``) remains the system of record for
the controllers and for the object-session path (preempt/reclaim, custom
plugins).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import (
    SYSTEM_CLUSTER_CRITICAL,
    SYSTEM_NAMESPACE,
    SYSTEM_NODE_CRITICAL,
    Pod,
    TaskStatus,
    topology_code,
)
from ..api.resource import Resource

F = np.float32
I = np.int32

HOSTNAME_KEY = "kubernetes.io/hostname"
JOB_SELECTOR = "__job__"

# PodGroup phase -> j_phase_code (fastpath._PHASE_CODE coding: 0 = no
# PodGroup, 5 = any other phase incl. "").
_PG_PHASE_CODE = {
    "Pending": 1,
    "Inqueue": 2,
    "Running": 3,
    "Unknown": 4,
}

# TaskStatus values are bit flags; keep them in int16 columns.
_OCCUPYING = (
    TaskStatus.Bound | TaskStatus.Binding | TaskStatus.Running
    | TaskStatus.Allocated | TaskStatus.Unknown
)
_TERMINATED = TaskStatus.Succeeded | TaskStatus.Failed


class CSRColumn:
    """Append-only ragged column: per-row variable-length int/float data.

    Rows are appended once and never mutated; ``gather`` materializes the
    concatenated segments of a row subset plus the local row index of every
    element (for vectorized scatters).
    """

    __slots__ = ("idx", "val", "off", "_n", "_len", "has_val")

    def __init__(self, has_val: bool = False, cap: int = 1024):
        self.idx = np.zeros(cap, I)
        self.val = np.zeros(cap, F) if has_val else None
        self.off = np.zeros(cap + 1, np.int64)
        self._n = 0  # rows
        self._len = 0  # elements
        self.has_val = has_val

    def append(self, indices, values=None) -> None:
        k = len(indices)
        if self._len + k > len(self.idx):
            grow = max(len(self.idx) * 2, self._len + k)
            self.idx = np.resize(self.idx, grow)
            if self.val is not None:
                self.val = np.resize(self.val, grow)
        if self._n + 1 >= len(self.off):
            self.off = np.resize(self.off, len(self.off) * 2)
        if k:
            self.idx[self._len:self._len + k] = indices
            if self.val is not None:
                self.val[self._len:self._len + k] = values
        self._len += k
        self._n += 1
        self.off[self._n] = self._len

    def lens(self, rows: np.ndarray) -> np.ndarray:
        return (self.off[rows + 1] - self.off[rows]).astype(np.int64)

    def gather(self, rows: np.ndarray):
        """-> (elem_row_local, indices[, values]) for the given rows."""
        lens = self.lens(rows)
        total = int(lens.sum())
        elem_row = np.repeat(np.arange(len(rows)), lens)
        if total == 0:
            pos = np.zeros(0, np.int64)
        else:
            # Flat positions: start[row] + intra-row offset.
            starts = self.off[rows]
            cum = np.concatenate(([0], np.cumsum(lens)[:-1]))
            pos = (
                np.arange(total, dtype=np.int64)
                - np.repeat(cum, lens)
                + np.repeat(starts, lens)
            )
        if self.val is not None:
            return elem_row, self.idx[pos], self.val[pos]
        return elem_row, self.idx[pos]


class Interner:
    """Append-only value -> dense index dictionary."""

    __slots__ = ("index", "items")

    def __init__(self):
        self.index: Dict[object, int] = {}
        self.items: List[object] = []

    def intern(self, key) -> int:
        i = self.index.get(key)
        if i is None:
            i = len(self.items)
            self.index[key] = i
            self.items.append(key)
        return i

    def __len__(self) -> int:
        return len(self.items)


def _grow(a: np.ndarray, n: int) -> np.ndarray:
    if n <= len(a):
        return a
    return np.resize(a, max(n, len(a) * 2))


@dataclass
class _PodFeat:
    """Static per-pod encoded features (cached on the Pod object)."""

    req: Tuple[list, list]  # (slot idxs, values)
    init_req: Tuple[list, list]
    sel: List[int]  # queried label-pair idxs (node selector)
    tol: List[int]  # toleration specs (matched lazily per cycle)
    ports: List[int]  # port idxs
    aff_alts: List[List[int]]  # required node-affinity alternatives
    pref: List[Tuple[List[int], float]]  # preferred node affinity
    ip_req_aff: List[int]  # inter-pod term idxs (required affinity)
    ip_req_anti: List[int]
    ip_soft: List[Tuple[int, float]]
    has_ip: bool
    priority: int
    create: float
    best_effort: bool
    key: tuple = ()


class StoreMirror:
    """Columnar mirror maintained by ``ClusterStore`` mutations."""

    def __init__(self):
        # -------- dictionaries (append-only; shared across the store life)
        self.scalar_slots = Interner()  # scalar resource name -> slot-2
        # Label bitset space: ONLY label pairs that appear in a selector /
        # node-affinity term occupy bits — a pod's own labels never enter
        # (they only matter for inter-pod term membership, matched against
        # raw dicts).  Without this split, per-job app labels would blow
        # the [N, LW]/[P, LW] bitset tables up quadratically at scale.
        self.labels = Interner()  # QUERIED (k, v) pairs
        self.taints = Interner()  # (key, value, effect)
        self.ports = Interner()  # port number
        self.terms = Interner()  # inter-pod term key
        self.term_info: List[tuple] = []  # (sel_items dict, topo_key, ns set|None)
        self.topo_keys = Interner()  # topology key -> column
        # Term membership: per term, a growing list of pod rows whose labels
        # match the term (resident counting + t_matches are derived).
        # Inverted indexes keep maintenance O(1)-ish per pod/term instead
        # of O(pods x terms): candidate terms for a pod come from its label
        # pairs / job id; candidate pods for a new term come from the
        # pair->rows index.
        self.term_members: List[List[int]] = []
        # Total memberships across terms: an O(1) content version for
        # the encode cache (memberships only grow between compactions).
        self.term_members_total = 0
        self._terms_by_pair: Dict[Tuple[str, str], List[int]] = {}
        self._terms_by_job: Dict[str, List[int]] = {}
        self._terms_all: List[int] = []  # empty-selector terms
        self._pods_by_pair: Dict[Tuple[str, str], List[int]] = {}
        # Task profiles: pods with identical solver-relevant features share
        # a profile id, interned once at add time (replaces the wave
        # solver's per-cycle feature hashing).  The key deliberately
        # excludes job identity; job-dependent inter-pod matches are
        # refined per cycle by the fast path.
        self.profiles = Interner()

        # ------------------------------------------------------- pod table
        cap = 1024
        self.p_uid: List[Optional[str]] = []
        self.p_key: List[str] = []  # "ns/name" bind key per row
        # Live pod record per row (kept current by upsert_pod: every
        # store.pods[uid] = pod write is paired with an upsert).  Lets the
        # fast path's bulk commit reach 100k pod objects by list indexing
        # instead of 100k string-keyed dict lookups.
        self.p_pod: List[Optional[Pod]] = []
        # Count of None entries in p_pod (tombstoned rows): lets the
        # commit path skip its defensive 100k-element None scan when no
        # pod has ever been removed (the common bench/steady case).
        self.p_pod_nones = 0
        self.p_feat: List[Optional[_PodFeat]] = []
        self.p_row: Dict[str, int] = {}
        self.p_status = np.zeros(cap, np.int16)
        self.p_node = np.full(cap, -1, I)
        # Bound hostname per row (None = unbound): written as ONE batched
        # column write at commit time (fastpath._commit) instead of a
        # 100k-iteration per-record setattr walk — the mirror-side source
        # of truth for bound placements; pod RECORDS still sync lazily
        # through the deferred bind-record walk (store.defer_bind_records).
        self.p_node_name = np.empty(cap, object)
        self.p_job = np.full(cap, -1, I)
        self.p_prio = np.zeros(cap, I)
        self.p_create = np.zeros(cap, np.float64)
        self.p_alive = np.zeros(cap, bool)
        self.p_be = np.zeros(cap, bool)  # best-effort (empty init_req)
        self.p_has_ip = np.zeros(cap, bool)  # has inter-pod terms
        self.p_has_tol = np.zeros(cap, bool)  # has tolerations
        # Critical (conformance-exempt) pods, precomputed at add time
        # (conformance.go:44-66: system priority classes / kube-system):
        # the evict machinery reads this as a column instead of walking
        # 40k pod objects per session.
        self.p_critical = np.zeros(cap, bool)
        self.p_prof = np.zeros(cap, I)  # task profile id (self.profiles)
        self.c_req = CSRColumn(has_val=True)
        self.c_init_req = CSRColumn(has_val=True)
        self.c_sel = CSRColumn()
        self.c_tol = CSRColumn()
        self.c_ports = CSRColumn()
        # Node-affinity alternatives: rows in a side table, pods reference a
        # contiguous [aff_lo, aff_hi) range of it.
        self.c_aff_alt = CSRColumn()  # one row per alternative
        self.p_aff_lo = np.zeros(cap, I)
        self.p_aff_hi = np.zeros(cap, I)
        self.c_pref = CSRColumn()  # one row per preferred term
        self.pref_w: List[float] = []
        self.p_pref_lo = np.zeros(cap, I)
        self.p_pref_hi = np.zeros(cap, I)
        self.c_ip_aff = CSRColumn()
        self.c_ip_anti = CSRColumn()
        self.c_ip_soft = CSRColumn(has_val=True)
        self.n_dead = 0

        # ------------------------------------------------------ node table
        self.n_name: List[Optional[str]] = []
        self.n_row: Dict[str, int] = {}
        ncap = 64
        self.n_ready = np.zeros(ncap, bool)
        self.n_alive = np.zeros(ncap, bool)
        self.n_maxtasks = np.zeros(ncap, I)
        self.c_n_alloc = CSRColumn(has_val=True)
        self.c_n_labels = CSRColumn()
        self.c_n_taints = CSRColumn()
        self.node_objs: List[object] = []  # Node spec per row (labels for dom)
        # Topology domains: (key column, value) -> dense domain id;
        # hostname domains are allocated per (node row).
        self.domains = Interner()
        self._node_dom_dirty = True
        self._node_dom: Optional[np.ndarray] = None

        # ------------------------------------------------- job (podgroup) table
        self.j_uid: List[Optional[str]] = []
        self.j_row: Dict[str, int] = {}
        jcap = 64
        self.j_minav = np.zeros(jcap, I)
        self.j_prio = np.zeros(jcap, I)
        self.j_create = np.zeros(jcap, np.float64)
        self.j_queue: List[str] = []
        self.j_ns: List[str] = []
        # Interned namespace/queue codes (vectorized grouping in the fast
        # path: string columns force Python loops at 10k+ jobs).
        self.ns_names = Interner()
        self.qnames = Interner()
        self.j_ns_code = np.zeros(jcap, I)
        self.j_queue_code = np.zeros(jcap, I)
        # PodGroup object ref + status snapshot columns, maintained by
        # upsert (every store add/update funnels through it) and written
        # through by the fast path's close write-back: the cycle reads
        # them as views instead of re-walking 45k PodGroup objects per
        # derive.  Phase coding matches fastpath._PHASE_CODE (0 = no
        # PodGroup, 5 = any other phase).
        self.j_pg: List[Optional[object]] = []
        self.j_phase_code = np.zeros(jcap, np.int8)
        self.j_st_run = np.zeros(jcap, I)
        self.j_st_fail = np.zeros(jcap, I)
        self.j_st_succ = np.zeros(jcap, I)
        # Process-local hash of the Unschedulable condition last written
        # (0 = none): close skips the per-object condition scan/rewrite
        # for persistently-unschedulable jobs without touching the
        # PodGroup at all.  Refreshed from the object on upsert so
        # external status writers stay coherent.
        self.j_cond_sig = np.zeros(jcap, np.int64)
        # Prebuilt per-job metric label tuple (("job_name", name),) and
        # event key ("PodGroup/ns/name"): close consumes 25k of each per
        # config-4 cycle.
        self.j_gauge_key: List[Optional[tuple]] = []
        self.j_event_key: List[str] = []
        self.j_alive = np.zeros(jcap, bool)
        # Fabric-topology constraint code per job (api.spec.topology_code:
        # 0 none, 1 prefer-contiguous, 2 require-contiguous).
        self.j_topo = np.zeros(jcap, np.int8)
        # Append-only fabric interners (ops/topology.fabric_planes):
        # (level, label value) -> code and (rack, slice) -> block id.
        # Compaction-carried so codes stay stable for the store's life.
        self._fabric_vals: Dict[tuple, int] = {}
        self._fabric_blocks: Dict[tuple, int] = {}
        # Toleration specs per pod row (matched lazily per cycle, because
        # the taint dictionary may grow after the pod was added).
        self._pod_tols: List[list] = []
        # Pods bound to nodes the mirror has not seen yet: name -> uids.
        self._orphans: Dict[str, List[str]] = {}
        # Epoch bumps force full fallback-path consumers to resync if needed.
        self.epoch = 0  # guarded-by: _lock
        # Node-LIVENESS generation: bumped only when a node row's
        # n_alive actually flips (join, rejoin, removal) — NOT on
        # content-identical upserts or label/capacity edits.  The
        # persistent cycle aggregates key on this instead of the full
        # epoch: node liveness is the only node property the resident
        # predicate reads, so routine node re-syncs/heartbeats keep the
        # delta derive alive while real membership churn still forces
        # the proven full rebuild.
        self.node_liveness_gen = 0  # guarded-by: _lock
        # Monotone pod/node mutation counter: the pipelined cycle's
        # staleness guard compares the value captured at solve dispatch
        # against the value at fetch — equality proves NO pod/node state
        # changed during the overlap, so the capacity re-validation can
        # be skipped wholesale (the steady-state case).
        self.mutation_seq = 0  # guarded-by: _lock
        # Bumped when maybe_compact renumbers pod rows: an in-flight
        # solve's row indices are void across a compaction and the whole
        # result must be dropped (rows are otherwise stable for a pod's
        # lifetime — tombstoned rows are never reused).
        self.compact_gen = 0  # guarded-by: _lock
        # Cross-shard commit gate (shard.py, ISSUE 16): bumped by every
        # sharded FastCycle._commit.  A shard captures the value at
        # solve dispatch; an advance at fetch time proves ANOTHER shard
        # committed binds during the overlap (a shard never commits
        # after its own pipelined dispatch within one cycle), so the
        # staleness guard's competing-bind / capacity-taken voids are
        # attributed to the optimistic protocol as the
        # `cross-shard-conflict` drop reason.  Correctness never rests
        # on this counter — mutation_seq already forces the
        # re-validation; this one only drives attribution + metrics.
        self.shard_commit_seq = 0  # guarded-by: _lock
        # Node rows touched since the last reset_node_delta(): lets the
        # device-resident snapshot upload per-row deltas instead of the
        # full [N, *] planes on every node-table epoch bump.
        self._node_dirty_rows: set = set()  # guarded-by: _lock
        self._node_dirty_floor = 0  # guarded-by: _lock
        # Pod rows whose DYNAMIC state (status/node/job/alive) changed
        # since the last derive consumed them (ISSUE 8): the incremental
        # host-lane machinery (fastpath_incr.CycleAggregates) turns the
        # per-cycle full-table reductions into subtract-old/add-new
        # delta scatters over exactly these rows.  Every writer of the
        # dynamic columns — the mirror's own mutators AND the fast
        # path's bulk commits/unbinds/evictions — must mark the rows it
        # touched, or the persistent aggregates silently drift; vclint's
        # VCL50x family checks the contract statically and the
        # VOLCANO_TPU_INCR_VERIFY=1 runtime guard checks it dynamically.
        self._pod_dirty_mask = np.zeros(cap, bool)  # guarded-by: _lock
        # Marked-row count with duplicates (the VOLCANO_TPU_DIRTY_CAP
        # overflow trigger is O(1) per mark batch, not O(unique)).
        self._pod_dirty_marks = 0  # guarded-by: _lock
        # Tracking gave up for this span (cap overflow, resync_status):
        # the next derive must full-rebuild, which resets it.
        self._pod_dirty_overflow = False  # guarded-by: _lock
        # Per-mirror memo of VOLCANO_TPU_DIRTY_CAP (the evict lane marks
        # per row; an env read per mark would be its own hot path).
        self._dirty_cap_memo = None  # guarded-by: _lock
        # Monotone count of mark events: the pipelined staleness guard's
        # agreement token — a dirty_seq advance between solve dispatch
        # and commit implies a mutation_seq advance (never vice-free),
        # so the guard can never skip a change the dirty set recorded.
        self.dirty_seq = 0  # guarded-by: _lock
        # Bumped whenever a pod RECORD slot changes (p_pod list writes:
        # copy-on-write replacements, removals) — the commit path's
        # object-array cache keys on it, so the 100k-element np.fromiter
        # walk reruns only when a record actually moved.
        self.pod_obj_gen = 0  # guarded-by: _lock
        # Conservation auditor (obs/audit.py, ISSUE 13), attached by
        # the owning store: the dynamic-state writers below declare
        # their pod-count flows through it (double-entry bookkeeping
        # the cycle-end reconcile balances against the census).  None
        # for bare mirrors in tests; the auditor is internally
        # synchronized, so no extra locking here.
        self.audit = None
        # Pod-journey log (obs/journey.py, ISSUE 18), attached by the
        # owning store next to the auditor: the same dynamic-state
        # writers record per-pod timeline events (enqueued /
        # status-sync / removed) through it.  None for bare mirrors and
        # under VOLCANO_TPU_JOURNEY=0; internally synchronized.
        self.journey = None

    # ================================================================ pods

    # holds: _lock
    def _feat(self, pod: Pod) -> _PodFeat:
        feat = getattr(pod, "_mirror_feat", None)
        if feat is not None:
            return feat
        req = pod.resource_request()
        init_req = pod.init_resource_request()

        def res_csr(r: Resource):
            slots, vals = [], []
            if r.milli_cpu:
                slots.append(0)
                vals.append(r.milli_cpu)
            if r.memory:
                slots.append(1)
                vals.append(r.memory)
            if r.scalars:
                for name, quant in r.scalars.items():
                    if quant:
                        slots.append(2 + self.scalar_slots.intern(name))
                        vals.append(quant)
            return slots, vals

        sel = [self._intern_queried(kv) for kv in pod.node_selector.items()]
        tol = []
        for t in pod.tolerations:
            # A toleration row gates taints; intern every (key,value,effect)
            # combination it covers that exists in the taint dict lazily at
            # cycle time instead — here we record the toleration spec items.
            tol.append(t)
        ports = [self.ports.intern(p) for p in pod.host_ports]
        aff_alts = [
            [self._intern_queried(kv) for kv in alt.items()]
            for alt in pod.required_node_affinity
        ]
        pref = [
            ([self._intern_queried(kv) for kv in sel_d.items()], float(w))
            for sel_d, w in pod.preferred_node_affinity
        ]

        ip_req_aff = [self._intern_term(t, pod.namespace) for t in pod.affinity]
        ip_req_anti = [
            self._intern_term(t, pod.namespace) for t in pod.anti_affinity
        ]
        ip_soft: List[Tuple[int, float]] = []
        for term, w in getattr(pod, "preferred_affinity", []):
            ip_soft.append((self._intern_term(term, pod.namespace), float(w)))
        for term, w in getattr(pod, "preferred_anti_affinity", []):
            ip_soft.append((self._intern_term(term, pod.namespace), -float(w)))
        for key, w in getattr(pod, "topology_spread", []):
            ip_soft.append((self._intern_job_term(pod.job_id(), key), -float(w)))

        req_pair = res_csr(req)
        init_pair = res_csr(init_req)
        feat = _PodFeat(
            req=req_pair,
            init_req=init_pair,
            sel=sel,
            tol=tol,
            ports=ports,
            aff_alts=aff_alts,
            pref=pref,
            ip_req_aff=ip_req_aff,
            ip_req_anti=ip_req_anti,
            ip_soft=ip_soft,
            has_ip=bool(ip_req_aff or ip_req_anti or ip_soft),
            priority=pod.priority if pod.priority is not None else 1,
            create=pod.creation_timestamp,
            best_effort=init_req.is_empty(),
            # NOTE: the pod's own labels/namespace are deliberately NOT part
            # of the key — they only influence inter-pod term membership
            # (t_matches), which the fast path refines per cycle.
            key=(
                tuple(zip(*req_pair)),
                tuple(zip(*init_pair)),
                tuple(sorted(sel)),
                tuple(sorted(ports)),
                tuple(tuple(sorted(a)) for a in aff_alts),
                tuple((tuple(sorted(s)), w) for s, w in pref),
                tuple(
                    (t.key, t.operator, t.value, t.effect)
                    for t in pod.tolerations
                ),
                tuple(sorted(ip_req_aff)),
                tuple(sorted(ip_req_anti)),
                tuple(sorted(ip_soft)),
            ),
        )
        try:
            pod._mirror_feat = feat
        except Exception:
            pass
        return feat

    # holds: _lock
    def _intern_queried(self, kv: Tuple[str, str]) -> int:
        """Intern a selector-queried label pair; nodes carrying a newly
        queried pair are re-encoded so their bitset row gains the bit."""
        before = len(self.labels)
        idx = self.labels.intern(kv)
        if len(self.labels) != before:
            k, v = kv
            for row, node in enumerate(self.node_objs):
                if (
                    node is not None
                    and self.n_alive[row]
                    and node.labels.get(k) == v
                ):
                    self.upsert_node(node)
        return idx

    def _intern_term(self, term, task_ns: str) -> int:
        ns = tuple(sorted(term.namespaces)) if term.namespaces else (task_ns,)
        key = (tuple(sorted(term.match_labels.items())), term.topology_key, ns)
        before = len(self.terms)
        e = self.terms.intern(key)
        if len(self.terms) != before:
            self.topo_keys.intern(term.topology_key)
            sel = dict(term.match_labels)
            self.term_info.append((sel, term.topology_key, set(ns)))
            self.term_members.append([])
            if sel:
                for kv in sel.items():
                    self._terms_by_pair.setdefault(kv, []).append(e)
            else:
                self._terms_all.append(e)
            self._backfill_term(e)
            self._node_dom_dirty = True
        return e

    def _intern_job_term(self, job_id: str, topo_key: str) -> int:
        key = (((JOB_SELECTOR, job_id),), topo_key, None)
        before = len(self.terms)
        e = self.terms.intern(key)
        if len(self.terms) != before:
            self.topo_keys.intern(topo_key)
            self.term_info.append(({JOB_SELECTOR: job_id}, topo_key, None))
            self.term_members.append([])
            self._terms_by_job.setdefault(job_id, []).append(e)
            self._backfill_term(e)
            self._node_dom_dirty = True
        return e

    def _term_matches(self, e: int, namespace: str, labels: Dict[str, str],
                      job_uid: str) -> bool:
        sel, _key, ns = self.term_info[e]
        if JOB_SELECTOR in sel:
            return job_uid == sel[JOB_SELECTOR]
        if ns is not None and namespace not in ns:
            return False
        return all(labels.get(k) == v for k, v in sel.items())

    def _backfill_term(self, e: int) -> None:
        """A new term must learn which existing pods match it — resolved
        from the inverted indexes, not a full pod scan."""
        members = self.term_members[e]
        sel, _key, _ns = self.term_info[e]
        if JOB_SELECTOR in sel:
            jrow = self.j_row.get(sel[JOB_SELECTOR])
            if jrow is None:
                return
            rows = np.flatnonzero(
                (self.p_job[:len(self.p_uid)] == jrow)
                & self.p_alive[:len(self.p_uid)]
            )
            members.extend(int(r) for r in rows)
            self.term_members_total += len(rows)
            return
        if sel:
            # Candidates: rows carrying the rarest selector pair.
            lists = [self._pods_by_pair.get(kv, []) for kv in sel.items()]
            candidates = min(lists, key=len)
        else:
            candidates = [
                r for r in range(len(self.p_uid)) if self.p_alive[r]
            ]
        pods = self._pods_ref or {}
        for row in candidates:
            if not self.p_alive[row]:
                continue
            uid = self.p_uid[row]
            pod = pods.get(uid) if uid else None
            if pod is None:
                continue
            jrow = self.p_job[row]
            juid = self.j_uid[jrow] if jrow >= 0 else ""
            if self._term_matches(e, pod.namespace, pod.labels, juid or ""):
                members.append(row)
                self.term_members_total += 1

    _pods_ref: Optional[Dict[str, Pod]] = None

    def attach(self, pods: Dict[str, Pod]) -> None:
        """Give the mirror a live reference to the store's pod dict (used
        only for rare term backfills)."""
        self._pods_ref = pods

    # holds: _lock
    def upsert_pod(self, pod: Pod, job_row_of) -> None:
        """Insert or update a pod row.  ``job_row_of(job_id) -> row``."""
        self.mutation_seq += 1
        feat = self._feat(pod)
        status = int(pod.task_status())
        node_row = -1
        if pod.node_name:
            node_row = self.n_row.get(pod.node_name, -1)
            if node_row < 0:
                # Node not seen yet: remember to adopt when it arrives
                # (the placeholder-NodeInfo analog, event_handlers.go addTask).
                self._orphans.setdefault(pod.node_name, []).append(pod.uid)
        row = self.p_row.get(pod.uid)
        if row is not None and self.p_uid[row] == pod.uid:
            self.mark_pod_dirty(row)
            self.pod_obj_gen += 1
            self.p_pod[row] = pod
            if self.p_feat[row] is feat:
                # Same spec blob (bind/evict copy-on-write carries it over):
                # update dynamic state only.  The job link is re-derived —
                # the podgroup controller back-annotates bare pods with a
                # group name after the fact (pg_controller_handler.go:72-105).
                old = int(self.p_status[row])
                if old != status:
                    if self.audit is not None:
                        self.audit.flow("pod-update", old, status)
                    if self.journey is not None:
                        self.journey.pod_event(pod.uid, "status-sync",
                                               status=status)
                self.p_status[row] = status
                self.p_node[row] = node_row
                self.p_node_name[row] = pod.node_name or None
                jid = pod.job_id()
                self.p_job[row] = job_row_of(jid) if jid else -1
                return
            # Spec changed: tombstone the old row, fall through to re-add.
            self.remove_pod(pod.uid)
        row = len(self.p_uid)
        self.mark_pod_dirty(row)
        self.p_uid.append(pod.uid)
        self.p_key.append(f"{pod.namespace}/{pod.name}")
        self.p_pod.append(pod)
        self.p_feat.append(feat)
        self.p_row[pod.uid] = row
        n = row + 1
        self.p_status = _grow(self.p_status, n)
        self.p_node = _grow(self.p_node, n)
        self.p_job = _grow(self.p_job, n)
        self.p_prio = _grow(self.p_prio, n)
        self.p_create = _grow(self.p_create, n)
        self.p_alive = _grow(self.p_alive, n)
        self.p_be = _grow(self.p_be, n)
        self.p_has_ip = _grow(self.p_has_ip, n)
        self.p_has_tol = _grow(self.p_has_tol, n)
        self.p_critical = _grow(self.p_critical, n)
        self.p_prof = _grow(self.p_prof, n)
        self.p_aff_lo = _grow(self.p_aff_lo, n)
        self.p_aff_hi = _grow(self.p_aff_hi, n)
        self.p_pref_lo = _grow(self.p_pref_lo, n)
        self.p_pref_hi = _grow(self.p_pref_hi, n)
        self.p_node_name = _grow(self.p_node_name, n)

        if self.audit is not None:
            self.audit.flow_added(status)
        self.p_status[row] = status
        self.p_node[row] = node_row
        self.p_node_name[row] = pod.node_name or None
        jid = pod.job_id()
        jrow = job_row_of(jid) if jid else -1
        self.p_job[row] = jrow
        if self.journey is not None:
            self.journey.pod_event(
                pod.uid, "enqueued", status=status,
                queue=self.j_queue[jrow] if jrow >= 0 else "",
                gang=jid)
        self.p_prio[row] = feat.priority
        self.p_create[row] = feat.create
        self.p_alive[row] = True
        self.p_be[row] = feat.best_effort
        self.p_has_ip[row] = feat.has_ip
        self.p_has_tol[row] = bool(feat.tol)
        self.p_critical[row] = (
            pod.priority_class in (SYSTEM_CLUSTER_CRITICAL,
                                   SYSTEM_NODE_CRITICAL)
            or pod.namespace == SYSTEM_NAMESPACE
        )
        self.p_prof[row] = self.profiles.intern(feat.key)

        self.c_req.append(*feat.req)
        self.c_init_req.append(*feat.init_req)
        self.c_sel.append(feat.sel)
        # Tolerations are matched lazily per cycle (taint dict may grow);
        # store toleration list on the side.
        self._pod_tols.append(feat.tol)
        self.c_ports.append(feat.ports)
        self.p_aff_lo[row] = self.c_aff_alt._n
        for alt in feat.aff_alts:
            self.c_aff_alt.append(alt)
        self.p_aff_hi[row] = self.c_aff_alt._n
        self.p_pref_lo[row] = self.c_pref._n
        for sel_idx, w in feat.pref:
            self.c_pref.append(sel_idx)
            self.pref_w.append(w)
        self.p_pref_hi[row] = self.c_pref._n
        self.c_ip_aff.append(feat.ip_req_aff)
        self.c_ip_anti.append(feat.ip_req_anti)
        if feat.ip_soft:
            si = [e for e, _ in feat.ip_soft]
            sv = [w for _, w in feat.ip_soft]
            self.c_ip_soft.append(si, sv)
        else:
            self.c_ip_soft.append([], [])
        # Inverted index + term membership via candidate lookup.
        for kv in pod.labels.items():
            self._pods_by_pair.setdefault(kv, []).append(row)
        if len(self.terms):
            juid = jid or ""
            cand: set = set(self._terms_all)
            if juid:
                cand.update(self._terms_by_job.get(juid, ()))
            for kv in pod.labels.items():
                cand.update(self._terms_by_pair.get(kv, ()))
            for e in cand:
                if self._term_matches(e, pod.namespace, pod.labels, juid):
                    self.term_members[e].append(row)
                    self.term_members_total += 1

    # holds: _lock
    def remove_pod(self, uid: str) -> None:
        row = self.p_row.pop(uid, None)
        if row is None:
            return
        self.mutation_seq += 1
        self.mark_pod_dirty(row)
        self.pod_obj_gen += 1
        if self.p_alive[row]:
            if self.audit is not None:
                self.audit.flow_removed(int(self.p_status[row]))
            if self.journey is not None:
                self.journey.pod_event(uid, "removed",
                                       status=int(self.p_status[row]))
        self.p_alive[row] = False
        self.p_uid[row] = None
        self.p_node_name[row] = None
        if self.p_pod[row] is not None:
            self.p_pod_nones += 1
        self.p_pod[row] = None
        self.n_dead += 1

    # holds: _lock
    def set_pod_state(self, uid: str, status: int, node_row: int) -> None:
        row = self.p_row.get(uid)
        if row is not None:
            self.mutation_seq += 1
            self.mark_pod_dirty(row)
            old = int(self.p_status[row])
            if old != status:
                if self.audit is not None:
                    self.audit.flow("set-pod-state", old, status)
                if self.journey is not None:
                    self.journey.pod_event(uid, "status-sync",
                                           status=status)
            self.p_status[row] = status
            self.p_node[row] = node_row
            self.p_node_name[row] = (
                self.n_name[node_row] if node_row >= 0 else None
            )

    # ================================================================ nodes

    # holds: _lock
    def upsert_node(self, node) -> int:
        row = self.n_row.get(node.name)
        new = row is None
        if new:
            row = len(self.n_name)
            self.n_name.append(node.name)
            self.n_row[node.name] = row
            n = row + 1
            self.n_ready = _grow(self.n_ready, n)
            self.n_alive = _grow(self.n_alive, n)
            self.n_maxtasks = _grow(self.n_maxtasks, n)
            self.node_objs.append(node)
        else:
            self.node_objs[row] = node
        alloc = node.allocatable_resource()
        slots, vals = [], []
        if alloc.milli_cpu:
            slots.append(0)
            vals.append(alloc.milli_cpu)
        if alloc.memory:
            slots.append(1)
            vals.append(alloc.memory)
        if alloc.scalars:
            for name, quant in alloc.scalars.items():
                if quant:
                    slots.append(2 + self.scalar_slots.intern(name))
                    vals.append(quant)
        # Only queried pairs occupy bitset space; a node label pair that no
        # selector has ever referenced carries no bit.
        lbl_index = self.labels.index
        labels = [
            lbl_index[kv] for kv in node.labels.items() if kv in lbl_index
        ]
        taints = [
            self.taints.intern((t.key, t.value, t.effect))
            for t in node.taints
            if t.effect in ("NoSchedule", "NoExecute")
        ]
        if new:
            self.c_n_alloc.append(slots, vals)
            self.c_n_labels.append(labels)
            self.c_n_taints.append(taints)
        else:
            # Node spec updates are rare: rewrite by appending a fresh row
            # and repointing (tombstone the CSR row implicitly).
            nrow = self.c_n_alloc._n
            self.c_n_alloc.append(slots, vals)
            self.c_n_labels.append(labels)
            self.c_n_taints.append(taints)
            self._node_csr_row = getattr(self, "_node_csr_row", {})
            self._node_csr_row[row] = nrow
        self.n_ready[row] = bool(node.ready) and not node.unschedulable
        if new or not self.n_alive[row]:
            self.node_liveness_gen += 1
        self.n_alive[row] = True
        self.n_maxtasks[row] = alloc.max_task_num
        self._node_dom_dirty = True
        self.epoch += 1
        self.mutation_seq += 1
        self._node_dirty_rows.add(row)
        for uid in self._orphans.pop(node.name, []):
            prow = self.p_row.get(uid)
            if prow is not None:
                self.mark_pod_dirty(prow)
                self.p_node[prow] = row
        return row

    def node_csr_rows(self, rows: np.ndarray) -> np.ndarray:
        """Map node table rows to their (possibly rewritten) CSR rows."""
        m = getattr(self, "_node_csr_row", None)
        if not m:
            return rows
        out = rows.copy()
        for i, r in enumerate(rows):
            out[i] = m.get(int(r), int(r))
        return out

    # holds: _lock
    def remove_node(self, name: str) -> None:
        row = self.n_row.get(name)
        if row is not None:
            if self.n_alive[row]:
                self.node_liveness_gen += 1
            self.n_alive[row] = False
            # Pods pointing at this node keep their row; their node col is
            # fixed up by the per-cycle liveness mask (n_alive).
            self.epoch += 1
            self.mutation_seq += 1
            self._node_dirty_rows.add(row)

    # holds: _lock
    def node_delta_rows(self, since_epoch: int) -> Optional[np.ndarray]:
        """Node rows changed since ``since_epoch``, or None when the
        dirty set cannot prove it covers that span (a second consumer
        reset it, or the caller predates the tracking floor).  Single-
        consumer contract: call ``reset_node_delta`` after applying."""
        if since_epoch < self._node_dirty_floor:
            return None
        return np.array(sorted(self._node_dirty_rows), np.int64)

    # holds: _lock
    def reset_node_delta(self) -> None:
        self._node_dirty_rows.clear()
        self._node_dirty_floor = self.epoch

    # ------------------------------------------------------ pod dirty set

    @staticmethod
    def dirty_cap() -> int:
        """VOLCANO_TPU_DIRTY_CAP (docs/tuning.md): marked-row budget per
        derive span, counted WITH duplicates so the overflow check is
        O(1) per mark batch.  Past it the tracker gives up and the next
        derive full-rebuilds — the bound on both the mask bookkeeping
        and the delta-scatter work a single derive can be handed."""
        import os

        raw = os.environ.get("VOLCANO_TPU_DIRTY_CAP", "262144")
        try:
            return max(int(raw), 0)
        except ValueError:
            return 262144

    # holds: _lock
    def mark_pods_dirty(self, rows) -> None:
        """Record pod rows whose dynamic state (status/node/job/alive)
        just changed.  Idempotent per row; vectorized for the fast
        path's bulk writers (a 100k-row commit pays one mask scatter)."""
        n = len(rows)
        if not n:
            return
        self.dirty_seq += 1
        if self._pod_dirty_overflow:
            return
        cap = self._dirty_cap_memo
        if cap is None:
            cap = self._dirty_cap_memo = self.dirty_cap()
        self._pod_dirty_marks += n
        if self._pod_dirty_marks > cap:
            self._pod_dirty_overflow = True
            return
        mask = self._pod_dirty_mask
        top = int(np.max(rows)) if not isinstance(rows, np.ndarray) \
            else int(rows.max())
        if top >= len(mask):
            mask = self._pod_dirty_mask = self._grow_mask(mask, top + 1)
        mask[rows] = True

    # holds: _lock
    def mark_pod_dirty(self, row: int) -> None:
        """Scalar ``mark_pods_dirty`` for the per-row mutators."""
        self.dirty_seq += 1
        if self._pod_dirty_overflow:
            return
        cap = self._dirty_cap_memo
        if cap is None:
            cap = self._dirty_cap_memo = self.dirty_cap()
        self._pod_dirty_marks += 1
        if self._pod_dirty_marks > cap:
            self._pod_dirty_overflow = True
            return
        mask = self._pod_dirty_mask
        if row >= len(mask):
            mask = self._pod_dirty_mask = self._grow_mask(mask, row + 1)
        mask[row] = True

    @staticmethod
    def _grow_mask(mask: np.ndarray, n: int) -> np.ndarray:
        """Zero-filled growth — np.resize TILES the old contents, which
        would plant stale True bits at rows beyond the live table."""
        out = np.zeros(max(n, len(mask) * 2), bool)
        out[:len(mask)] = mask
        return out

    # holds: _lock
    def mark_pods_overflow(self) -> None:
        """Give up tracking for this span (bulk resyncs): the next
        derive must full-rebuild."""
        self.dirty_seq += 1
        self._pod_dirty_overflow = True

    # holds: _lock
    def consume_pod_dirty(self, n_rows: int):
        """Hand the dirty rows (< ``n_rows``) to the single consumer
        (the derive-time aggregate refresh) and reset tracking.  Returns
        ``None`` when tracking overflowed — the caller must rebuild."""
        overflow = self._pod_dirty_overflow
        mask = self._pod_dirty_mask
        rows = None
        if not overflow:
            rows = np.flatnonzero(mask[:n_rows])
            mask[rows] = False
            # Rows at/beyond n_rows cannot exist: the mask only ever
            # marks rows of the live table, and compaction resets it.
        else:
            mask[:] = False
        self._pod_dirty_marks = 0
        self._pod_dirty_overflow = False
        return rows

    def node_dom(self) -> np.ndarray:
        """[Nrows, K] topology domain ids (interned, append-only)."""
        K = max(1, len(self.topo_keys))
        N = len(self.n_name)
        if (
            not self._node_dom_dirty
            and self._node_dom is not None
            and self._node_dom.shape == (N, K)
        ):
            return self._node_dom
        dom = np.full((N, K), -1, I)
        for k, key in enumerate(self.topo_keys.items):
            if key == HOSTNAME_KEY:
                for ni in range(N):
                    if self.n_alive[ni]:
                        dom[ni, k] = self.domains.intern(("__host__", ni))
                continue
            for ni in range(N):
                if not self.n_alive[ni]:
                    continue
                node = self.node_objs[ni]
                val = node.labels.get(key) if node is not None else None
                if val is not None:
                    dom[ni, k] = self.domains.intern((k, val))
        self._node_dom = dom
        self._node_dom_dirty = False
        return dom

    # ========================================================== jobs (pgs)

    def job_row(self, uid: str) -> int:
        row = self.j_row.get(uid)
        if row is None:
            row = len(self.j_uid)
            self.j_uid.append(uid)
            self.j_row[uid] = row
            n = row + 1
            self.j_minav = _grow(self.j_minav, n)
            self.j_prio = _grow(self.j_prio, n)
            self.j_create = _grow(self.j_create, n)
            self.j_alive = _grow(self.j_alive, n)
            self.j_ns_code = _grow(self.j_ns_code, n)
            self.j_queue_code = _grow(self.j_queue_code, n)
            self.j_phase_code = _grow(self.j_phase_code, n)
            self.j_st_run = _grow(self.j_st_run, n)
            self.j_st_fail = _grow(self.j_st_fail, n)
            self.j_st_succ = _grow(self.j_st_succ, n)
            self.j_cond_sig = _grow(self.j_cond_sig, n)
            self.j_topo = _grow(self.j_topo, n)
            self.j_queue.append("default")
            self.j_ns.append("default")
            self.j_pg.append(None)
            self.j_gauge_key.append(None)
            self.j_event_key.append("")
            self.j_ns_code[row] = self.ns_names.intern("default")
            self.j_queue_code[row] = self.qnames.intern("default")
            self.j_alive[row] = False
            self.j_phase_code[row] = 0
            self._j_uid_rank = None
        return row

    def job_uid_rank(self) -> np.ndarray:
        """[Jn] integer rank array that is a strictly monotone map of the
        job uid strings (the session default tie-break).  Cached until a
        new job row appears — the string argsort over tens of thousands
        of uids is too slow to pay per cycle."""
        rank = self._j_uid_rank
        Jn = len(self.j_uid)
        if rank is None or len(rank) != Jn:
            order = np.argsort(np.array(self.j_uid[:Jn]), kind="stable")
            rank = np.empty(Jn, np.int64)
            rank[order] = np.arange(Jn)
            self._j_uid_rank = rank
        return rank

    _j_uid_rank: Optional[np.ndarray] = None

    def upsert_pod_group(self, pg, priority: int) -> None:
        row = self.job_row(pg.uid)
        self.j_minav[row] = pg.min_member
        self.j_prio[row] = priority
        self.j_create[row] = pg.creation_timestamp
        self.j_queue[row] = pg.queue
        self.j_ns[row] = pg.namespace
        self.j_ns_code[row] = self.ns_names.intern(pg.namespace)
        self.j_queue_code[row] = self.qnames.intern(pg.queue)
        self.j_alive[row] = True
        self.j_pg[row] = pg
        self.j_topo[row] = topology_code(pg)
        self.j_gauge_key[row] = (("job_name", pg.name),)
        self.j_event_key[row] = f"PodGroup/{pg.namespace}/{pg.name}"
        st = pg.status
        self.j_phase_code[row] = _PG_PHASE_CODE.get(st.phase, 5)
        self.j_st_run[row] = st.running
        self.j_st_fail[row] = st.failed
        self.j_st_succ[row] = st.succeeded
        sig = 0
        for c in st.conditions:
            if c.type == "Unschedulable" and c.status == "True":
                sig = hash((c.reason, c.message)) & 0x7FFFFFFFFFFFFFFF
        self.j_cond_sig[row] = sig
        # Precompute the dense MinResources vector at add time (unknown
        # scalar names are interned like pod requests are), so enqueue's
        # budget walk never parses resource quantities in-cycle.
        if pg.min_resources is not None:
            try:
                res = Resource.from_resource_list(pg.min_resources)
                R = 2 + len(self.scalar_slots)
                if res.scalars:
                    for name in res.scalars:
                        self.scalar_slots.intern(name)
                    R = 2 + len(self.scalar_slots)
                v = np.zeros((R,), np.float32)
                v[0] = res.milli_cpu
                v[1] = res.memory
                if res.scalars:
                    for name, quant in res.scalars.items():
                        v[2 + self.scalar_slots.index[name]] = quant
                pg._minres_vec = (R, v)
            except Exception:
                pass

    def refresh_pod_group_status(self, pg) -> None:
        """Re-sync the persistent status-snapshot columns (j_phase_code /
        j_st_* / j_cond_sig) from the PodGroup object.  Every writer that
        mutates pg.status OUTSIDE the fast path's close (the object
        session's jobUpdater write-back, condition records) must call
        this, or the fast path's change detection works off stale
        'last written' state and skips real writes."""
        row = self.j_row.get(pg.uid)
        if row is None:
            return
        st = pg.status
        self.j_phase_code[row] = _PG_PHASE_CODE.get(st.phase, 5)
        self.j_st_run[row] = st.running
        self.j_st_fail[row] = st.failed
        self.j_st_succ[row] = st.succeeded
        sig = 0
        for c in st.conditions:
            if c.type == "Unschedulable" and c.status == "True":
                sig = hash((c.reason, c.message)) & 0x7FFFFFFFFFFFFFFF
        self.j_cond_sig[row] = sig

    def remove_pod_group(self, uid: str) -> None:
        row = self.j_row.get(uid)
        if row is not None:
            self.j_alive[row] = False
            self.j_pg[row] = None
            self.j_phase_code[row] = 0
            self.j_cond_sig[row] = 0
            self.j_topo[row] = 0

    # ========================================================== maintenance

    # holds: _lock
    def maybe_compact(self) -> None:
        """Rebuild the pod table without tombstones (rare, amortized)."""
        total = len(self.p_uid)
        if total < 4096 or self.n_dead * 2 < total:
            return
        live = np.flatnonzero(self.p_alive[:total])
        old = self
        fresh = StoreMirror.__new__(StoreMirror)
        fresh.__init__()
        # Dictionaries and node/job tables carry over untouched.
        for attr in ("scalar_slots", "labels", "taints", "ports", "terms",
                     "term_info", "topo_keys", "profiles",
                     "_terms_by_pair", "_terms_by_job", "_terms_all",
                     "n_name", "n_row", "n_ready",
                     "n_alive", "n_maxtasks", "c_n_alloc", "c_n_labels",
                     "c_n_taints", "node_objs", "domains", "j_uid", "j_row",
                     "j_minav", "j_prio", "j_create", "j_queue", "j_ns",
                     "ns_names", "qnames", "j_ns_code", "j_queue_code",
                     "j_pg", "j_phase_code", "j_st_run", "j_st_fail",
                     "j_st_succ", "j_cond_sig", "j_gauge_key",
                     "j_event_key", "j_topo",
                     "_fabric_vals", "_fabric_blocks",
                     "j_alive", "_pods_ref", "_orphans", "epoch",
                     "node_liveness_gen"):
            setattr(fresh, attr, getattr(old, attr))
        fresh._node_dom_dirty = True
        if hasattr(old, "_node_csr_row"):
            fresh._node_csr_row = old._node_csr_row
        remap = np.full(total, -1, I)
        remap[live] = np.arange(len(live), dtype=I)
        for r in live:
            uid = old.p_uid[r]
            fresh.p_uid.append(uid)
            fresh.p_key.append(old.p_key[r])
            fresh.p_pod.append(old.p_pod[r])
            fresh.p_feat.append(old.p_feat[r])
            fresh.p_row[uid] = len(fresh.p_uid) - 1
        n = len(live)
        for name in ("p_status", "p_node", "p_node_name", "p_job",
                     "p_prio", "p_create", "p_alive", "p_be", "p_has_ip",
                     "p_has_tol", "p_critical", "p_prof"):
            arr = getattr(old, name)[:total][live]
            setattr(fresh, name, arr.copy())
        # CSR columns: re-append per live row (vectorized gather then bulk).
        for col_name in ("c_req", "c_init_req", "c_sel", "c_ports",
                         "c_ip_aff", "c_ip_anti", "c_ip_soft"):
            oldc: CSRColumn = getattr(old, col_name)
            newc = CSRColumn(has_val=oldc.has_val)
            lens = oldc.lens(live)
            g = oldc.gather(live)
            newc.idx = g[1].astype(I).copy()
            if oldc.has_val:
                newc.val = g[2].astype(F).copy()
            newc.off = np.concatenate(
                ([0], np.cumsum(lens))
            ).astype(np.int64)
            newc._n = n
            newc._len = int(lens.sum())
            setattr(fresh, col_name, newc)
        # Ragged side tables (aff alternatives / pref terms): rebuild.
        fresh.p_aff_lo = np.zeros(max(n, 1), I)
        fresh.p_aff_hi = np.zeros(max(n, 1), I)
        fresh.p_pref_lo = np.zeros(max(n, 1), I)
        fresh.p_pref_hi = np.zeros(max(n, 1), I)
        fresh._pod_tols = []
        for new_r, r in enumerate(live):
            fresh.p_aff_lo[new_r] = fresh.c_aff_alt._n
            for alt_row in range(old.p_aff_lo[r], old.p_aff_hi[r]):
                _er, vals = old.c_aff_alt.gather(np.array([alt_row]))
                fresh.c_aff_alt.append(vals)
            fresh.p_aff_hi[new_r] = fresh.c_aff_alt._n
            fresh.p_pref_lo[new_r] = fresh.c_pref._n
            for p_row in range(old.p_pref_lo[r], old.p_pref_hi[r]):
                _er, vals = old.c_pref.gather(np.array([p_row]))
                fresh.c_pref.append(vals)
                fresh.pref_w.append(old.pref_w[p_row])
            fresh.p_pref_hi[new_r] = fresh.c_pref._n
            fresh._pod_tols.append(old._pod_tols[r])
        fresh.term_members = [
            [int(remap[m]) for m in members if remap[m] >= 0]
            for members in old.term_members
        ]
        fresh.term_members_total = sum(
            len(members) for members in fresh.term_members
        )
        fresh._pods_by_pair = {
            kv: [int(remap[r]) for r in rows if remap[r] >= 0]
            for kv, rows in old._pods_by_pair.items()
        }
        # Counters survive compaction (fresh.__init__ zeroed them):
        # row indices held by in-flight solves are void now, so bump the
        # generation; any delta consumer must also full-resync.
        seq, gen = self.mutation_seq, self.compact_gen
        dseq = self.dirty_seq
        dirty, floor = self._node_dirty_rows, self._node_dirty_floor
        audit = self.audit
        journey = self.journey
        self.__dict__.update(fresh.__dict__)
        # The auditor rides the STORE, not the table generation: row
        # renumbering preserves the per-status census exactly (only
        # tombstones drop), so conservation needs no re-anchor — the
        # attached auditor itself must just survive the swap.  Same for
        # the journey: it is uid-keyed, so timelines survive row
        # renumbering untouched; only the handle must ride the swap.
        self.audit = audit
        self.journey = journey
        self.mutation_seq = seq + 1
        self.compact_gen = gen + 1
        self._node_dirty_rows = dirty
        self._node_dirty_floor = floor
        # Row renumbering voids the pod dirty mask wholesale; the
        # compact_gen bump already forces the aggregate consumer to
        # full-rebuild (which resets tracking), so a fresh zero mask
        # (from fresh.__init__) is exactly right — only the monotone
        # agreement token must survive.
        self.dirty_seq = dseq + 1

    # holds: _lock
    def resync_status(self, pods: Dict[str, "Pod"]) -> None:
        """Re-derive every live row's dynamic state from the pod records
        (the system of record).  Recovery path: a failed fast cycle may
        leave uncommitted status mutations in the mirror."""
        self.mutation_seq += 1
        # Every live row may change: per-row marking would cost as much
        # as the rebuild it exists to avoid.
        self.mark_pods_overflow()
        if self.audit is not None:
            # Bulk re-derive: per-row flow declaration would be a scan
            # of its own; re-anchor the conservation census instead.
            self.audit.reanchor("resync-status")
        if self.journey is not None:
            # Same bulk shape journey-side: adopt the record truth in
            # one pass (missing pods get synthetic roots; pods whose
            # status says placed get a state-sync bind).
            self.journey.pod_resync(
                (uid, int(pod.task_status()))
                for uid, pod in pods.items() if uid in self.p_row)
        for uid, row in self.p_row.items():
            pod = pods.get(uid)
            if pod is None:
                continue
            self.p_status[row] = int(pod.task_status())
            self.p_node[row] = (
                self.n_row.get(pod.node_name, -1) if pod.node_name else -1
            )
            self.p_node_name[row] = pod.node_name or None

    # ---------------------------------------------------------- inspection

    @property
    def n_pods(self) -> int:
        return len(self.p_uid)

    @property
    def n_nodes(self) -> int:
        return len(self.n_name)
