"""Cluster state store (scheduler cache) and side-effect interfaces."""

from .interface import (
    Binder,
    Evictor,
    FakeBinder,
    FakeEvictor,
    FakeStatusUpdater,
    FakeVolumeBinder,
    StatusUpdater,
    VolumeBinder,
)
from .store import DEFAULT_QUEUE, ClusterStore

__all__ = [
    "Binder",
    "Evictor",
    "FakeBinder",
    "FakeEvictor",
    "FakeStatusUpdater",
    "FakeVolumeBinder",
    "StatusUpdater",
    "VolumeBinder",
    "ClusterStore",
    "DEFAULT_QUEUE",
]
