"""Wire codec for solver snapshots: numpy pytrees <-> one contiguous frame.

The remote-solver bridge (BASELINE.json north star; the reference's two
planes likewise talk only through serialized API-server state,
``pkg/scheduler/cache/cache.go:492-554``): the scheduler-store process
ships each cycle's solver inputs to the device-owning solver process as a
single frame packed by the C++ serializer (``csrc/vcsnap.cc``
``vcsnap_frame_pack``), and the assignment vectors return the same way.
Reads are zero-copy: arrays are numpy views into the received buffer.

A pure-numpy fallback keeps the codec available when the native library
cannot build; both sides produce byte-identical frames.
"""

from __future__ import annotations

import ctypes
import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..native import lib_or_none

# Wire constants + dtype <-> u8 code table (code = list index; stable
# wire contract, extend append-only).  MUST mirror csrc/vcsnap.cc
# (kVcsnapMagic / kVcsnapVersion / kVcsnapMaxDims / kVcsnapDtypes);
# tools/vclint's schema cross-checker parses both sides and fails the
# green-gate on any drift (VCL301/VCL302).
WIRE_MAGIC = 0x4E534356
WIRE_VERSION = 1
WIRE_MAX_DIMS = 8
_DTYPES = [
    np.dtype(np.float32), np.dtype(np.float64), np.dtype(np.int8),
    np.dtype(np.int16), np.dtype(np.int32), np.dtype(np.int64),
    np.dtype(np.uint8), np.dtype(np.uint16), np.dtype(np.uint32),
    np.dtype(np.uint64), np.dtype(np.bool_),
]
_DTYPE_CODE = {dt: i for i, dt in enumerate(_DTYPES)}


def _align8(v: int) -> int:
    return (v + 7) & ~7


def encode_frame(arrays: List[np.ndarray], manifest: dict) -> bytes:
    """Pack arrays + a JSON manifest into one frame."""
    man = json.dumps(manifest, separators=(",", ":")).encode()
    # ascontiguousarray promotes 0-d to 1-d; restore the scalar shape so
    # the roundtrip is exact.
    arrs = [
        np.ascontiguousarray(a).reshape(np.shape(a)) for a in arrays
    ]
    for a in arrs:
        if a.dtype not in _DTYPE_CODE:
            raise TypeError(f"unsupported wire dtype {a.dtype}")
        if a.ndim > WIRE_MAX_DIMS:
            raise ValueError(f"unsupported wire ndim {a.ndim}")
    n = len(arrs)
    dtypes = np.array([_DTYPE_CODE[a.dtype] for a in arrs], np.uint8)
    ndims = np.array([a.ndim for a in arrs], np.uint8)
    dims_flat = np.array(
        [d for a in arrs for d in a.shape], np.int64
    ) if n else np.zeros(0, np.int64)
    nbytes = np.array([a.nbytes for a in arrs], np.int64)
    lib = lib_or_none()
    if lib is not None:
        total = lib.vcsnap_frame_bytes(ndims, nbytes, n, len(man))
        out = np.zeros(int(total), np.uint8)
        src_ptrs = (ctypes.POINTER(ctypes.c_uint8) * max(n, 1))()
        for i, a in enumerate(arrs):
            src_ptrs[i] = a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        man_arr = np.frombuffer(man or b"\0", np.uint8)
        lib.vcsnap_frame_pack(
            dtypes, ndims, dims_flat, nbytes, src_ptrs, n,
            man_arr, len(man), out,
        )
        return out.tobytes()
    # NumPy fallback: byte-identical layout.
    parts = [np.frombuffer(
        np.array([WIRE_MAGIC, WIRE_VERSION, n, len(man)],
                 np.uint32).tobytes()
        + man, np.uint8
    )]
    pad = _align8(16 + len(man)) - (16 + len(man))
    parts.append(np.zeros(pad, np.uint8))
    for i, a in enumerate(arrs):
        head = bytearray(8)
        head[0] = int(dtypes[i])
        head[1] = int(ndims[i])
        head = bytes(head) + np.array(a.shape, np.int64).tobytes() \
            + np.int64(a.nbytes).tobytes()
        hpad = _align8(len(head)) - len(head)
        parts.append(np.frombuffer(head + b"\0" * hpad, np.uint8))
        parts.append(np.frombuffer(a.tobytes(), np.uint8))
        dpad = _align8(a.nbytes) - a.nbytes
        parts.append(np.zeros(dpad, np.uint8))
    return b"".join(p.tobytes() for p in parts)


def decode_frame(buf: bytes) -> Tuple[dict, List[np.ndarray]]:
    """Parse a frame into (manifest, arrays).  Arrays are zero-copy
    read-only views into ``buf``."""
    raw = np.frombuffer(buf, np.uint8)
    lib = lib_or_none()
    if lib is not None:
        moff = ctypes.c_int64()
        mlen = ctypes.c_int64()
        n = lib.vcsnap_frame_info(
            raw, len(raw), ctypes.byref(moff), ctypes.byref(mlen),
        )
        # Treat the frame as hostile until unpack validates it: a corrupt
        # header's array count must not size allocations (each array
        # needs >= 24 header+data bytes in a well-formed frame).
        if n < 0 or n > len(raw) // 24 + 1:
            raise ValueError("malformed snapshot frame")
        dtypes = np.zeros(max(n, 1), np.uint8)
        ndims = np.zeros(max(n, 1), np.uint8)
        dims_flat = np.zeros(max(n, 1) * 8, np.int64)
        data_off = np.zeros(max(n, 1), np.int64)
        nbytes = np.zeros(max(n, 1), np.int64)
        rc = lib.vcsnap_frame_unpack(
            raw, len(raw), dtypes, ndims, dims_flat, data_off, nbytes,
        )
        if rc != 0:
            raise ValueError("malformed snapshot frame")
        manifest = json.loads(
            bytes(raw[int(moff.value):int(moff.value) + int(mlen.value)])
            or b"{}"
        )
        arrays = []
        for i in range(n):
            if int(dtypes[i]) >= len(_DTYPES):
                raise ValueError("malformed snapshot frame")
            dt = _DTYPES[int(dtypes[i])]
            shape = tuple(dims_flat[i * 8:i * 8 + int(ndims[i])].tolist())
            count = int(np.prod(shape, dtype=np.int64))
            # Shape and byte length must agree or the view would bleed
            # into the next array's bytes (hostile-until-validated).
            if min(shape, default=0) < 0 or \
                    count * dt.itemsize != int(nbytes[i]):
                raise ValueError("malformed snapshot frame")
            start = int(data_off[i])
            arrays.append(
                np.frombuffer(buf, dt, count=count,
                              offset=start).reshape(shape)
            )
        return manifest, arrays
    # NumPy fallback parser.
    if len(buf) < 16:
        raise ValueError("malformed snapshot frame")
    head = np.frombuffer(buf, np.uint32, count=4)
    if int(head[0]) != WIRE_MAGIC or int(head[1]) != WIRE_VERSION:
        raise ValueError("malformed snapshot frame")
    n = int(head[2])
    mlen = int(head[3])
    manifest = json.loads(buf[16:16 + mlen] or b"{}")
    off = _align8(16 + mlen)
    arrays = []
    for _ in range(n):
        if off + 16 > len(buf):
            raise ValueError("malformed snapshot frame")
        dt_code = buf[off]
        nd = buf[off + 1]
        if nd > WIRE_MAX_DIMS or dt_code >= len(_DTYPES):
            raise ValueError("malformed snapshot frame")
        shape = tuple(np.frombuffer(buf, np.int64, count=nd,
                                    offset=off + 8).tolist())
        nb = int(np.frombuffer(buf, np.int64, count=1,
                               offset=off + 8 + 8 * nd)[0])
        off = _align8(off + 8 + 8 * nd + 8)
        if nb < 0 or off + nb > len(buf):
            raise ValueError("malformed snapshot frame")
        dt = _DTYPES[dt_code]
        count = int(np.prod(shape, dtype=np.int64))
        if min(shape, default=0) < 0 or count * dt.itemsize != nb:
            raise ValueError("malformed snapshot frame")
        arrays.append(
            np.frombuffer(buf, dt, count=count, offset=off).reshape(shape)
        )
        off = _align8(off + nb)
    return manifest, arrays


# --------------------------------------------------------------- pytrees

def flatten_tree(obj: Any, arrays: List[np.ndarray]) -> Any:
    """Recursively flatten a solver-input pytree (NamedTuples / numpy
    arrays / scalars / None / tuples) into a JSON-able spec + an array
    list.  jax arrays are materialized to numpy."""
    if obj is None:
        return {"t": "none"}
    if isinstance(obj, np.ndarray):
        arrays.append(obj)
        return {"t": "a", "i": len(arrays) - 1}
    if isinstance(obj, (bool, int, float, str)):
        return {"t": "v", "v": obj}
    if hasattr(obj, "_fields"):  # NamedTuple
        return {
            "t": "nt", "n": type(obj).__name__,
            "f": [flatten_tree(x, arrays) for x in obj],
        }
    if isinstance(obj, (tuple, list)):
        return {"t": "l", "f": [flatten_tree(x, arrays) for x in obj]}
    # jax / other array-likes
    a = np.asarray(obj)
    arrays.append(a)
    return {"t": "a", "i": len(arrays) - 1}


def unflatten_tree(spec: Any, arrays: List[np.ndarray],
                   registry: Dict[str, type]) -> Any:
    t = spec["t"]
    if t == "none":
        return None
    if t == "a":
        return arrays[spec["i"]]
    if t == "v":
        return spec["v"]
    if t == "nt":
        cls = registry[spec["n"]]
        return cls(*[unflatten_tree(f, arrays, registry)
                     for f in spec["f"]])
    if t == "l":
        return tuple(unflatten_tree(f, arrays, registry)
                     for f in spec["f"])
    raise ValueError(f"bad tree spec node {t!r}")
