"""Wire codec for solver snapshots: numpy pytrees <-> one contiguous frame.

The remote-solver bridge (BASELINE.json north star; the reference's two
planes likewise talk only through serialized API-server state,
``pkg/scheduler/cache/cache.go:492-554``): the scheduler-store process
ships each cycle's solver inputs to the device-owning solver process as a
single frame packed by the C++ serializer (``csrc/vcsnap.cc``
``vcsnap_frame_pack``), and the assignment vectors return the same way.
Reads are zero-copy: arrays are numpy views into the received buffer.

A pure-numpy fallback keeps the codec available when the native library
cannot build; both sides produce byte-identical frames.

Protocol v2 (ISSUE 10) adds two transport layers on top of the frame
container, both implemented here:

- **Zero-copy encode**: ``encode_frame_views`` produces the exact byte
  stream of ``encode_frame`` as a list of buffers — small header bytes
  plus ``memoryview``s of the array data — for ``socket.sendmsg``
  (writev), so a full frame costs ~0 extra host copies where the old
  ``tobytes()`` + ``join`` path copied the payload twice.
- **Delta records**: a solve frame may ship only the rows of an array
  that changed since the mirrored base frame the receiver already
  holds.  ``diff_rows`` computes the bitwise-exact changed-row ranges
  (conservative: bit-identity, so -0.0 vs 0.0 and NaN payload bits are
  preserved), and ``delta_check``/``delta_apply`` validate + scatter a
  delta payload into the mirror with the same hostile-until-validated
  bounds discipline as the frame parser (``csrc/vcsnap.cc``
  ``vcsnap_delta_check``/``vcsnap_delta_apply``; numpy fallback below
  is semantics-identical).  The record tags (``REC_*``) are wire
  format shared with the C++ side — vclint's VCL305 cross-checker
  fails the green-gate on any drift, like the dtype table.
"""

from __future__ import annotations

import ctypes
import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..native import lib_or_none

# Wire constants + dtype <-> u8 code table (code = list index; stable
# wire contract, extend append-only).  MUST mirror csrc/vcsnap.cc
# (kVcsnapMagic / kVcsnapVersion / kVcsnapMaxDims / kVcsnapDtypes);
# tools/vclint's schema cross-checker parses both sides and fails the
# green-gate on any drift (VCL301/VCL302).
WIRE_MAGIC = 0x4E534356
WIRE_VERSION = 1
WIRE_MAX_DIMS = 8
_DTYPES = [
    np.dtype(np.float32), np.dtype(np.float64), np.dtype(np.int8),
    np.dtype(np.int16), np.dtype(np.int32), np.dtype(np.int64),
    np.dtype(np.uint8), np.dtype(np.uint16), np.dtype(np.uint32),
    np.dtype(np.uint64), np.dtype(np.bool_),
]
_DTYPE_CODE = {dt: i for i, dt in enumerate(_DTYPES)}

# Delta-frame record tags (protocol v2; values are wire format between
# the scheduler and the solver child, extend append-only).  MUST mirror
# csrc/vcsnap.cc kVcsnapRecFull/kVcsnapRecSame/kVcsnapRecDelta —
# vclint's VCL305 cross-checker parses both sides and fails the
# green-gate on drift (same class as the dtype table).
REC_FULL = 0   # the slot's array rides the frame whole
REC_SAME = 1   # the receiver's mirrored base array is current
REC_DELTA = 2  # only changed row ranges ride (descriptor + row payload)


def _align8(v: int) -> int:
    return (v + 7) & ~7


def encode_frame(arrays: List[np.ndarray], manifest: dict) -> bytes:
    """Pack arrays + a JSON manifest into one frame."""
    lib = lib_or_none()
    if lib is None:
        # NumPy fallback: byte-identical layout via the scatter-gather
        # builder — one hand-maintained python copy of the layout, not
        # two (the byte-identity test pins both against the C packer).
        _total, parts = encode_frame_views(arrays, manifest)
        return b"".join(bytes(p) for p in parts)
    man = json.dumps(manifest, separators=(",", ":")).encode()
    # ascontiguousarray promotes 0-d to 1-d; restore the scalar shape so
    # the roundtrip is exact.
    arrs = [
        np.ascontiguousarray(a).reshape(np.shape(a)) for a in arrays
    ]
    for a in arrs:
        if a.dtype not in _DTYPE_CODE:
            raise TypeError(f"unsupported wire dtype {a.dtype}")
        if a.ndim > WIRE_MAX_DIMS:
            raise ValueError(f"unsupported wire ndim {a.ndim}")
    n = len(arrs)
    dtypes = np.array([_DTYPE_CODE[a.dtype] for a in arrs], np.uint8)
    ndims = np.array([a.ndim for a in arrs], np.uint8)
    dims_flat = np.array(
        [d for a in arrs for d in a.shape], np.int64
    ) if n else np.zeros(0, np.int64)
    nbytes = np.array([a.nbytes for a in arrs], np.int64)
    total = lib.vcsnap_frame_bytes(ndims, nbytes, n, len(man))
    out = np.zeros(int(total), np.uint8)
    src_ptrs = (ctypes.POINTER(ctypes.c_uint8) * max(n, 1))()
    for i, a in enumerate(arrs):
        src_ptrs[i] = a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    man_arr = np.frombuffer(man or b"\0", np.uint8)
    lib.vcsnap_frame_pack(
        dtypes, ndims, dims_flat, nbytes, src_ptrs, n,
        man_arr, len(man), out,
    )
    return out.tobytes()


def decode_frame(buf: bytes) -> Tuple[dict, List[np.ndarray]]:
    """Parse a frame into (manifest, arrays).  Arrays are zero-copy
    views into ``buf`` — they inherit its writability (``bytes`` in,
    read-only views out; the v2 receive path passes a ``bytearray`` so
    the solver child's mirror can patch delta rows in place)."""
    raw = np.frombuffer(buf, np.uint8)
    lib = lib_or_none()
    if lib is not None:
        moff = ctypes.c_int64()
        mlen = ctypes.c_int64()
        n = lib.vcsnap_frame_info(
            raw, len(raw), ctypes.byref(moff), ctypes.byref(mlen),
        )
        # Treat the frame as hostile until unpack validates it: a corrupt
        # header's array count must not size allocations (each array
        # needs >= 24 header+data bytes in a well-formed frame).
        if n < 0 or n > len(raw) // 24 + 1:
            raise ValueError("malformed snapshot frame")
        dtypes = np.zeros(max(n, 1), np.uint8)
        ndims = np.zeros(max(n, 1), np.uint8)
        dims_flat = np.zeros(max(n, 1) * 8, np.int64)
        data_off = np.zeros(max(n, 1), np.int64)
        nbytes = np.zeros(max(n, 1), np.int64)
        rc = lib.vcsnap_frame_unpack(
            raw, len(raw), dtypes, ndims, dims_flat, data_off, nbytes,
        )
        if rc != 0:
            raise ValueError("malformed snapshot frame")
        manifest = json.loads(
            bytes(raw[int(moff.value):int(moff.value) + int(mlen.value)])
            or b"{}"
        )
        arrays = []
        for i in range(n):
            if int(dtypes[i]) >= len(_DTYPES):
                raise ValueError("malformed snapshot frame")
            dt = _DTYPES[int(dtypes[i])]
            shape = tuple(dims_flat[i * 8:i * 8 + int(ndims[i])].tolist())
            count = int(np.prod(shape, dtype=np.int64))
            # Shape and byte length must agree or the view would bleed
            # into the next array's bytes (hostile-until-validated).
            if min(shape, default=0) < 0 or \
                    count * dt.itemsize != int(nbytes[i]):
                raise ValueError("malformed snapshot frame")
            start = int(data_off[i])
            arrays.append(
                np.frombuffer(buf, dt, count=count,
                              offset=start).reshape(shape)
            )
        return manifest, arrays
    # NumPy fallback parser.
    if len(buf) < 16:
        raise ValueError("malformed snapshot frame")
    head = np.frombuffer(buf, np.uint32, count=4)
    if int(head[0]) != WIRE_MAGIC or int(head[1]) != WIRE_VERSION:
        raise ValueError("malformed snapshot frame")
    n = int(head[2])
    mlen = int(head[3])
    manifest = json.loads(buf[16:16 + mlen] or b"{}")
    off = _align8(16 + mlen)
    arrays = []
    for _ in range(n):
        if off + 16 > len(buf):
            raise ValueError("malformed snapshot frame")
        dt_code = buf[off]
        nd = buf[off + 1]
        if nd > WIRE_MAX_DIMS or dt_code >= len(_DTYPES):
            raise ValueError("malformed snapshot frame")
        shape = tuple(np.frombuffer(buf, np.int64, count=nd,
                                    offset=off + 8).tolist())
        nb = int(np.frombuffer(buf, np.int64, count=1,
                               offset=off + 8 + 8 * nd)[0])
        off = _align8(off + 8 + 8 * nd + 8)
        if nb < 0 or off + nb > len(buf):
            raise ValueError("malformed snapshot frame")
        dt = _DTYPES[dt_code]
        count = int(np.prod(shape, dtype=np.int64))
        if min(shape, default=0) < 0 or count * dt.itemsize != nb:
            raise ValueError("malformed snapshot frame")
        arrays.append(
            np.frombuffer(buf, dt, count=count, offset=off).reshape(shape)
        )
        off = _align8(off + nb)
    return manifest, arrays


# ------------------------------------------------- zero-copy frame views


def encode_frame_views(arrays: List[np.ndarray],
                       manifest: dict) -> Tuple[int, List]:
    """The exact byte stream of ``encode_frame`` as ``(total_len,
    buffers)`` for scatter-gather sends (``socket.sendmsg``): small
    header/padding ``bytes`` objects interleaved with ``memoryview``s
    of the array data.  No array byte is copied — the caller must keep
    ``arrays`` alive and unmutated until the send completes."""
    man = json.dumps(manifest, separators=(",", ":")).encode()
    arrs = [
        np.ascontiguousarray(a).reshape(np.shape(a)) for a in arrays
    ]
    for a in arrs:
        if a.dtype not in _DTYPE_CODE:
            raise TypeError(f"unsupported wire dtype {a.dtype}")
        if a.ndim > WIRE_MAX_DIMS:
            raise ValueError(f"unsupported wire ndim {a.ndim}")
    n = len(arrs)
    head = np.array([WIRE_MAGIC, WIRE_VERSION, n, len(man)],
                    np.uint32).tobytes() + man
    pad = _align8(len(head)) - len(head)
    parts: List = [head + b"\0" * pad]
    total = len(head) + pad
    for a in arrs:
        hdr = bytearray(8)
        hdr[0] = _DTYPE_CODE[a.dtype]
        hdr[1] = a.ndim
        hdr = bytes(hdr) + np.array(a.shape, np.int64).tobytes() \
            + np.int64(a.nbytes).tobytes()
        hpad = _align8(len(hdr)) - len(hdr)
        parts.append(hdr + b"\0" * hpad)
        total += len(hdr) + hpad
        if a.nbytes:
            parts.append(memoryview(a.reshape(-1).view(np.uint8)))
            total += a.nbytes
        dpad = _align8(a.nbytes) - a.nbytes
        if dpad:
            parts.append(b"\0" * dpad)
            total += dpad
    return total, parts


# ------------------------------------------------------- delta records


def _rows_u8(a: np.ndarray) -> np.ndarray:
    """[rows, row_bytes] uint8 view of a C-contiguous array (bitwise
    row identity — float comparison would call -0.0 == 0.0 and lose
    NaN payload bits across the wire)."""
    rows = a.shape[0]
    return a.reshape(rows, -1).view(np.uint8)


def diff_rows(new: np.ndarray, old: np.ndarray) -> Optional[np.ndarray]:
    """Bitwise changed-row ranges of ``new`` vs ``old`` (same dtype +
    shape, both C-contiguous, ndim >= 1): an int64 ``[n, 2]`` array of
    half-open ``[start, stop)`` ranges in ascending, non-overlapping
    order — empty when the arrays are bit-identical.  ``None`` means
    the arrays are not row-diffable (shape/dtype drift) and the slot
    must ship whole."""
    if new.shape != old.shape or new.dtype != old.dtype or new.ndim < 1:
        return None
    if new.nbytes == 0:
        return np.zeros((0, 2), np.int64)
    neq = (_rows_u8(new) != _rows_u8(old)).any(axis=1)
    changed = np.flatnonzero(neq)
    if not len(changed):
        return np.zeros((0, 2), np.int64)
    breaks = np.flatnonzero(np.diff(changed) > 1)
    starts = np.concatenate(([changed[0]], changed[breaks + 1]))
    stops = np.concatenate((changed[breaks], [changed[-1]])) + 1
    return np.stack([starts, stops], axis=1).astype(np.int64)


def ranges_to_desc(ranges: np.ndarray) -> np.ndarray:
    """Wire descriptor of a delta record: ``[n_ranges, s0, e0, s1, e1,
    ...]`` as int64 (rides the frame as an ordinary wire array)."""
    r = np.asarray(ranges, np.int64).reshape(-1, 2)
    return np.concatenate(([np.int64(len(r))], r.reshape(-1)))


def gather_rows(a: np.ndarray, ranges: np.ndarray) -> np.ndarray:
    """The delta payload: the changed rows of ``a`` concatenated in
    range order as one flat uint8 array (a churn-proportional copy —
    the only bytes a delta record ships)."""
    au8 = _rows_u8(a)
    if not len(ranges):
        return np.zeros(0, np.uint8)
    return np.concatenate(
        [au8[int(s):int(e)].reshape(-1) for s, e in ranges]
    )


def delta_check(desc: np.ndarray, rows: int, row_bytes: int,
                payload_bytes: int, mirror_gen: int,
                base_gen: int) -> int:
    """Validate one delta record against the mirror slot it patches.
    Returns the summed payload rows (>= 0), ``-1`` on a malformed
    descriptor (truncated, out-of-bounds, unsorted / overlapping
    ranges, payload length mismatch), ``-2`` when the receiver's
    mirror generation is not the delta's base (a reconnect / restart /
    token mismatch — the caller falls back to a full frame, never a
    stale solve).  The descriptor is hostile until this validates it;
    ``rows`` / ``row_bytes`` / ``payload_bytes`` / ``mirror_gen`` come
    from the receiver's own state and are trusted."""
    desc = np.asarray(desc)
    if desc.dtype != np.int64 or desc.ndim != 1:
        return -1
    lib = lib_or_none()
    if lib is not None and hasattr(lib, "vcsnap_delta_check"):
        return int(lib.vcsnap_delta_check(
            np.ascontiguousarray(desc), len(desc), rows, row_bytes,
            payload_bytes, mirror_gen, base_gen,
        ))
    # NumPy fallback: semantics-identical (cross-checked by
    # tests/test_snapwire.py and the csrc smoke binary).
    if mirror_gen != base_gen:
        return -2
    if len(desc) < 1:
        return -1
    n = int(desc[0])
    # `2 * n` on a hostile count could overflow the C side's int64; the
    # division form rejects without arithmetic on hostile values.
    if n < 0 or n > (len(desc) - 1) // 2:
        return -1
    total = 0
    prev_stop = 0
    for i in range(n):
        s = int(desc[1 + 2 * i])
        e = int(desc[2 + 2 * i])
        # Ranges are half-open, strictly ascending, non-overlapping,
        # non-empty, within [0, rows).  Each bound is checked against
        # trusted values directly — no additive expression a hostile
        # INT64_MAX-adjacent bound could wrap.
        if s < prev_stop or s >= e or e > rows:
            return -1
        total += e - s
        prev_stop = e
    if row_bytes <= 0:
        return -1 if payload_bytes != 0 else total
    if payload_bytes % row_bytes != 0 \
            or total != payload_bytes // row_bytes:
        return -1
    return total


def delta_apply(dst: np.ndarray, desc: np.ndarray, payload: np.ndarray,
                mirror_gen: int, base_gen: int) -> None:
    """Scatter a validated delta payload into the writable mirror array
    ``dst`` at the descriptor's row ranges.  Raises ``ValueError`` on
    any ``delta_check`` rejection BEFORE touching ``dst``."""
    rows = dst.shape[0] if dst.ndim else 0
    row_bytes = dst.nbytes // rows if rows else 0
    payload = np.ascontiguousarray(np.asarray(payload, np.uint8))
    rc = delta_check(desc, rows, row_bytes, len(payload),
                     mirror_gen, base_gen)
    if rc == -2:
        raise ValueError("delta base generation mismatch")
    if rc < 0:
        raise ValueError("malformed delta record")
    lib = lib_or_none()
    if lib is not None and hasattr(lib, "vcsnap_delta_apply"):
        if lib.vcsnap_delta_apply(
                _rows_u8(dst), rows, row_bytes,
                np.ascontiguousarray(np.asarray(desc, np.int64)),
                len(desc), payload, len(payload),
                mirror_gen, base_gen) != 0:
            raise ValueError("malformed delta record")
        return
    du8 = _rows_u8(dst)
    off = 0
    n = int(desc[0])
    for i in range(n):
        s = int(desc[1 + 2 * i])
        e = int(desc[2 + 2 * i])
        nb = (e - s) * row_bytes
        du8[s:e] = payload[off:off + nb].reshape(e - s, row_bytes)
        off += nb


# --------------------------------------------------------------- pytrees

def flatten_tree(obj: Any, arrays: List[np.ndarray]) -> Any:
    """Recursively flatten a solver-input pytree (NamedTuples / numpy
    arrays / scalars / None / tuples) into a JSON-able spec + an array
    list.  jax arrays are materialized to numpy."""
    if obj is None:
        return {"t": "none"}
    if isinstance(obj, np.ndarray):
        arrays.append(obj)
        return {"t": "a", "i": len(arrays) - 1}
    if isinstance(obj, (bool, int, float, str)):
        return {"t": "v", "v": obj}
    if hasattr(obj, "_fields"):  # NamedTuple
        return {
            "t": "nt", "n": type(obj).__name__,
            "f": [flatten_tree(x, arrays) for x in obj],
        }
    if isinstance(obj, (tuple, list)):
        return {"t": "l", "f": [flatten_tree(x, arrays) for x in obj]}
    # jax / other array-likes
    a = np.asarray(obj)
    arrays.append(a)
    return {"t": "a", "i": len(arrays) - 1}


def unflatten_tree(spec: Any, arrays: List[np.ndarray],
                   registry: Dict[str, type]) -> Any:
    t = spec["t"]
    if t == "none":
        return None
    if t == "a":
        return arrays[spec["i"]]
    if t == "v":
        return spec["v"]
    if t == "nt":
        cls = registry[spec["n"]]
        return cls(*[unflatten_tree(f, arrays, registry)
                     for f in spec["f"]])
    if t == "l":
        return tuple(unflatten_tree(f, arrays, registry)
                     for f in spec["f"])
    raise ValueError(f"bad tree spec node {t!r}")
