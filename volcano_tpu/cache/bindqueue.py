"""Async bind dispatch + rate-limited bind-failure queue.

The reference dispatches every bind on a goroutine and never waits for it
in the scheduling cycle (``pkg/scheduler/cache/cache.go:536-552``); failed
binds push the task onto a rate-limited ``errTasks`` workqueue whose
resync re-derives the task from the API server with exponential backoff
(``cache.go:106-107,627-649``).  This module is that machinery for the
fast path:

- ``BindDispatcher`` owns a worker thread draining batched bind requests
  to the store's ``Binder``.  The scheduling cycle only pays the queue
  append.
- Failures land in a thread-safe failure list the scheduler drains at the
  START of the next cycle (keeping every mirror mutation on the cycle
  thread); each failure re-enters Pending with an exponential per-task
  backoff (``not_before``) during which the solver does not re-place it —
  the analog of the task sitting in the rate-limited errTasks queue.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

log = logging.getLogger(__name__)

# Reference workqueue.DefaultItemBasedRateLimiter: 5ms base, 1000s cap.
# Scheduling periods are ~1s, so sub-second delays are invisible; start
# at one period instead.
BACKOFF_BASE = 1.0
BACKOFF_MAX = 60.0


class BindDispatcher:
    """Single worker thread draining batched bind requests."""

    def __init__(self, binder,
                 on_failure: Callable[[List[Tuple[str, object]]], None],
                 on_success: Optional[Callable[[List[str], List[str]], None]] = None,
                 materialize: Optional[Callable[[list], tuple]] = None):
        self._binder = binder
        self._on_failure = on_failure
        self._on_success = on_success
        self._materialize = materialize
        self._cv = threading.Condition()
        # guarded-by: _cv
        self._q: List[Tuple[Sequence[str], Sequence[str], Sequence[object]]] = []
        self._stopped = False  # guarded-by: _cv
        self._inflight = 0  # guarded-by: _cv
        # Runtime lockdep (obs/lockdep.py): created lazily, after the
        # owning store's construction-time walk — arm before the worker
        # thread can race the wrap.  No-op when the probe is off.
        from ..obs.lockdep import attach

        attach(self)
        self._thread = threading.Thread(
            target=self._run, name="vc-bind-dispatch", daemon=True
        )
        self._thread.start()

    def dispatch(self, keys: Sequence[str], hosts: Sequence[str],
                 pods: Sequence[object],
                 entry: Optional[list] = None) -> None:
        """Deferred batches pass ``entry`` (from the store's
        ``defer_bind_records``); the worker materializes lists and
        applies the pod.node_name record walk off the scheduling
        cycle's critical path."""
        with self._cv:
            self._q.append((keys, hosts, pods, entry))
            self._inflight += 1
            self._cv.notify()

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Block until every dispatched batch has been processed."""
        deadline = None if timeout is None else time.time() + timeout
        with self._cv:
            while self._inflight > 0:
                remaining = (
                    None if deadline is None else deadline - time.time()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining)
        return True

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify()
        self._thread.join(timeout=5)

    # ------------------------------------------------------------- worker

    def _run(self) -> None:
        from .interface import BindFailure

        while True:
            with self._cv:
                while not self._q and not self._stopped:
                    self._cv.wait()
                if self._stopped and not self._q:
                    return
                keys, hosts, pods, entry = self._q.pop(0)
            if entry is not None:
                # Deferred record walk: tolist + setattr over the whole
                # batch runs here, off the scheduling cycle (idempotent
                # — a failure path may already have forced it through
                # the store's apply_pending_bind_records).
                keys, hosts, pods = self._materialize(entry)
            failed: List[str] = []
            bind_keys = getattr(self._binder, "bind_keys", None)
            batch_ok = False
            if bind_keys is not None:
                try:
                    bind_keys(list(keys), list(hosts))
                    batch_ok = True
                except BindFailure as bf:
                    failed = list(bf.failed)
                    batch_ok = True
                except Exception:
                    # Indeterminate: some binds may have taken effect.
                    # Failing the whole batch would re-queue pods that
                    # are already bound and later re-bind them — possibly
                    # to a different node — with no unbind of the first
                    # placement.  Re-drive per key instead: Bind is
                    # idempotent (key -> node assignment), so repeating a
                    # key that already landed is a no-op, and each key
                    # gets a definite outcome.
                    log.exception(
                        "bind batch indeterminate; retrying per key"
                    )
            if not batch_ok:
                for pod, host, key in zip(pods, hosts, keys):
                    try:
                        self._binder.bind(pod, host)
                    except BindFailure:
                        failed.append(key)
                    except Exception:
                        log.exception("bind failed for %s", key)
                        failed.append(key)
            if failed:
                try:
                    # Hand the pod objects back with the keys so the
                    # store's drain never re-derives key->pod over the
                    # whole pod table.
                    by_key = {k: p for k, p in zip(keys, pods)}
                    self._on_failure(
                        [(k, by_key.get(k)) for k in failed]
                    )
                except Exception:
                    log.exception("bind-failure handler failed")
            if self._on_success is not None:
                ok_pairs = None
                if failed:
                    fset = set(failed)
                    ok_pairs = (
                        [k for k in keys if k not in fset],
                        [h for k, h in zip(keys, hosts) if k not in fset],
                    )
                else:
                    ok_pairs = (list(keys), list(hosts))
                try:
                    self._on_success(*ok_pairs)
                except Exception:
                    log.exception("bind-success handler failed")
            with self._cv:
                self._inflight -= 1
                self._cv.notify_all()
