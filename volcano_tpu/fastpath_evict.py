"""Fast-path preempt + reclaim: the victim-selection actions over the
array mirror.

The object-path actions (``actions/preempt.py``, ``actions/reclaim.py``)
walk every (preemptor x node x predicate) in Python — O(P x N) Python
calls that take minutes at 10k nodes.  This module keeps the reference's
control flow at task/victim granularity (the part that is inherently
sequential: evictions change what later preemptors see) but evaluates the
node-level math — predicates, scores, future-idle checks — as [N] numpy
expressions over the FastCycle's derived arrays, exactly as SURVEY.md
section 7 (M3) prescribes: victim-selection kernels over per-node victim
prefix state.

Semantics reproduced from preempt.go:41-262 / reclaim.go:40-189 +
session_plugins.go:110-193 (tiered victim intersection):

- preempt phase 1: per queue, job-ordered preemptors, statement-wrapped;
  commit iff the job reaches Pipelined, else every eviction/pipeline of
  the statement is rolled back (an undo log over the arrays).
- preempt phase 2: intra-job task preemption, committed unconditionally.
- reclaim: queue-ordered round-robin, immediate (unwrapped) evictions,
  victims only from Reclaimable queues.
- victim sets: tier-by-tier intersection across the enabled plugins
  (priority / gang / conformance / drf for preempt; gang / proportion /
  conformance for reclaim), stopping at the first tier boundary with a
  non-empty set — including Go's nil-slice quirk (an initialized-empty
  set keeps poisoning later tiers).
- victims are evicted lowest-task-order-first until FutureIdle covers the
  preemptor; the preemptor is pipelined onto the node.

Pipelines are session-scoped (they never reach the store — the reference
recomputes them each cycle); committed evictions mark the store pods
deleting and dispatch the evictor, as ``cache.Evict`` does.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

import numpy as np

import heapq

from .api import PodGroupPhase, TaskStatus

log = logging.getLogger(__name__)

F = np.float32

ST_PENDING = int(TaskStatus.Pending)
ST_RUNNING = int(TaskStatus.Running)
ST_RELEASING = int(TaskStatus.Releasing)



class EvictState:
    """Per-cycle state for the eviction actions (lazy, built on first
    preempt/reclaim execution)."""

    # Lives inside FastCycle.run, under run_cycle_fast's store lock.
    # vclint: class-holds: _lock

    def __init__(self, cyc):
        self.cyc = cyc
        m = cyc.m
        Pn, Nn, R = cyc.Pn, cyc.Nn, cyc.R
        self.req = np.zeros((Pn, R), F)
        self.init_req = np.zeros((Pn, R), F)
        rows = np.flatnonzero(m.p_alive[:Pn])
        if len(rows):
            er, si, v = m.c_req.gather(rows)
            self.req[rows[er], si] = v
            er, si, v = m.c_init_req.gather(rows)
            self.init_req[rows[er], si] = v
        self.req_empty = (m.c_req.lens(np.arange(Pn)) == 0) if Pn else \
            np.zeros(0, bool)
        # Session-scoped node deltas.
        self.n_pipelined = np.zeros((Nn, R), F)
        # Incrementally-maintained FutureIdle = idle + releasing -
        # pipelined (node_info.go:56-58); n_idle is static while the
        # evict actions run, so only the event methods touch this.
        self.fi = cyc.n_idle + cyc.n_releasing
        self.pipelined_rows: List[int] = []  # rows pipelined this cycle
        self.pipe_node = np.full(Pn, -1, np.int64)
        self.j_waiting = np.zeros(cyc.Jn, np.int64)
        # Critical (conformance-exempt) pods, resident rows only — read
        # from the mirror's precomputed column instead of a 40k-object
        # walk per session (conformance.go:44-66 semantics encoded at
        # pod add time).
        self.critical = m.p_critical[:Pn] & cyc.resident
        # Residents grouped per node, in row order (NodeInfo.tasks
        # iteration order == pod arrival order).
        self.node_rows: List[List[int]] = [[] for _ in range(Nn)]
        node = m.p_node[:Pn]
        for r in np.flatnonzero(cyc.resident):
            self.node_rows[node[r]].append(int(r))
        # Victim base vectors (resident, non-empty-request rows): the
        # aggregate evictable caches build from these with numpy masks.
        vr = np.flatnonzero(cyc.resident & ~self.req_empty[:Pn])
        self.v_rows = vr
        self.v_node = m.p_node[:Pn][vr].astype(np.int64)
        self.v_job = m.p_job[:Pn][vr].astype(np.int64)
        self.v_qi = np.where(
            self.v_job >= 0, cyc.q_of_job[np.maximum(self.v_job, 0)], -1
        )
        self.v_req = self.req[vr]
        # Committed evictions (flushed to the store at cycle end).
        self.evicted_rows: List[int] = []
        # Monotonic state version: bumped by every evict/unevict/
        # pipeline/unpipeline; memoized shares key off it.
        self.version = 0
        # Callback (set by FastEvictor) keeping aggregate evictable-
        # capacity caches incremental: on_change(row, sign).
        self.on_change = None
        # Callback (set by FastEvictor) invalidating per-node derived
        # masks: on_node_change(n) after ANY event touching node n's
        # fi / evictable state.
        self.on_node_change = None
        # Per-job mutation stamps (DRF share memoization granularity).
        self.j_version = np.zeros(cyc.Jn, np.int64)
        # Per-queue mutation stamps (queue-share memoization): bumped
        # whenever q_alloc[qi] changes.
        self.q_version = np.zeros(
            cyc.q_alloc.shape[0] if cyc.q_alloc is not None else 0,
            np.int64,
        )

    # ------------------------------------------------------------ futures

    def future_idle(self, n: int) -> np.ndarray:
        return self.fi[n]

    # ------------------------------------------------------------- events

    def evict(self, row: int, log_: Optional[list]) -> None:
        """Session-level evict (session.go:334-380): Running -> Releasing;
        node releasing grows; shares shrink."""
        c = self.cyc
        m = c.m
        n = int(m.p_node[row])
        req = self.req[row]
        c._audit_flow(int(m.p_status[row]), ST_RELEASING, "evict")
        c._journey_event(row, "evicted")
        m.p_status[row] = ST_RELEASING
        # Direct mirror status write: the incremental derive's dirty set
        # must see it (the action stamps mutation_seq at its end).
        m.mark_pod_dirty(row)
        c.n_releasing[n] += req
        self.fi[n] += req
        jr = int(m.p_job[row])
        if jr >= 0:
            self.j_version[jr] += 1
            c.j_cnt_alloc[jr] -= 1
            c.j_cnt_run[jr] -= 1
            c.j_cnt_releasing[jr] += 1
            c.j_ready_base[jr] -= 1
            c.j_alloc_res[jr] -= req
            qi = c.q_of_job[jr]
            if qi >= 0:
                c.q_alloc[qi] -= req
                self.q_version[qi] += 1
        self.version += 1
        if self.on_change is not None:
            self.on_change(row, -1)
        if self.on_node_change is not None:
            self.on_node_change(n)
        if log_ is not None:
            log_.append(("evict", row, n, jr))

    def unevict(self, row: int, n: int, jr: int) -> None:
        c = self.cyc
        m = c.m
        req = self.req[row]
        c._audit_flow(int(m.p_status[row]), ST_RUNNING, "evict-revert")
        c._journey_event(row, "evict-reverted")
        m.p_status[row] = ST_RUNNING
        m.mark_pod_dirty(row)
        c.n_releasing[n] -= req
        self.fi[n] -= req
        if jr >= 0:
            self.j_version[jr] += 1
            c.j_cnt_alloc[jr] += 1
            c.j_cnt_run[jr] += 1
            c.j_cnt_releasing[jr] -= 1
            c.j_ready_base[jr] += 1
            c.j_alloc_res[jr] += req
            qi = c.q_of_job[jr]
            if qi >= 0:
                c.q_alloc[qi] += req
                self.q_version[qi] += 1
        self.version += 1
        if self.on_change is not None:
            self.on_change(row, 1)
        if self.on_node_change is not None:
            self.on_node_change(n)

    def pipeline(self, row: int, n: int, log_: Optional[list]) -> None:
        """Session-level pipeline: future capacity claim + share growth
        (session.go:207-249)."""
        c = self.cyc
        m = c.m
        req = self.req[row]
        self.n_pipelined[n] += req
        self.fi[n] -= req
        self.pipe_node[row] = n
        c.n_ntasks[n] += 1
        jr = int(m.p_job[row])
        if jr >= 0:
            self.j_version[jr] += 1
            self.j_waiting[jr] += 1
            c.j_cnt_pending[jr] -= 1
            c.j_alloc_res[jr] += req
            qi = c.q_of_job[jr]
            if qi >= 0:
                c.q_alloc[qi] += req
                self.q_version[qi] += 1
        self.version += 1
        self.pipelined_rows.append(row)
        self.node_rows[n].append(row)
        if self.on_node_change is not None:
            self.on_node_change(n)
        if log_ is not None:
            log_.append(("pipeline", row, n, jr))

    def unpipeline(self, row: int, n: int, jr: int) -> None:
        c = self.cyc
        m = c.m
        req = self.req[row]
        self.n_pipelined[n] -= req
        self.fi[n] += req
        self.pipe_node[row] = -1
        c.n_ntasks[n] -= 1
        if jr >= 0:
            self.j_version[jr] += 1
            self.j_waiting[jr] -= 1
            c.j_cnt_pending[jr] += 1
            c.j_alloc_res[jr] -= req
            qi = c.q_of_job[jr]
            if qi >= 0:
                c.q_alloc[qi] -= req
                self.q_version[qi] += 1
        self.version += 1
        self.pipelined_rows.remove(row)
        try:
            self.node_rows[n].remove(row)
        except ValueError:
            pass
        if self.on_node_change is not None:
            self.on_node_change(n)

    def rollback(self, log_: list) -> None:
        for op in reversed(log_):
            if op[0] == "evict":
                _, row, n, jr = op
                self.unevict(row, n, jr)
            else:
                _, row, n, jr = op
                self.unpipeline(row, n, jr)

    def commit(self, log_: list) -> None:
        for op in log_:
            if op[0] == "evict":
                self.evicted_rows.append(op[1])

    # -------------------------------------------------------- commit/store

    def flush(self) -> None:
        """Apply committed evictions to the store (cache.Evict semantics:
        pod marked deleting, evictor dispatched — one batch when the
        evictor supports it).  Evictor failures revert exactly the
        failed pods to Running, the cache.go:461-466 resyncTask analog:
        the next preempt/reclaim cycle re-selects a victim set."""
        if not self.evicted_rows:
            return
        c = self.cyc
        m = c.m
        store = c.store
        from .cache.interface import EvictFailure

        evictor = store.evictor
        evict_keys = getattr(evictor, "evict_keys", None)
        # Object-array gathers over the mirror's pod/key columns: the
        # 20k-victim dict-lookup + f-string walk costs ~60 ms at
        # config-4 scale.
        rows_arr = np.asarray(self.evicted_rows, np.int64)
        pod_a, key_a, _ = c._obj_arrays()
        pods_l = pod_a[rows_arr].tolist()
        keys_l = key_a[rows_arr].tolist()
        entries = []  # (row, "ns/name", pod)
        for row, pod, key in zip(self.evicted_rows, pods_l, keys_l):
            if pod is None:
                continue
            pod.deleting = True
            entries.append((row, key, pod))
        failed = set()
        if evict_keys is not None:
            try:
                evict_keys([k for _, k, _ in entries])
            except EvictFailure as ef:
                failed = set(ef.failed)
            except Exception:
                # Transport-level error (connection reset, timeout):
                # indeterminate — re-drive per key so each gets a
                # definite outcome (evictions are idempotent: deleting
                # an already-terminating pod is a no-op), mirroring the
                # bind dispatcher's indeterminate-batch handling.
                log.exception("evict batch indeterminate; "
                              "retrying per key")
                for row, key, pod in entries:
                    try:
                        evictor.evict(pod)
                    except Exception:
                        failed.add(key)
        else:
            for row, key, pod in entries:
                try:
                    evictor.evict(pod)
                except Exception:
                    failed.add(key)
        events = []
        ledger = getattr(store, "migrations", None)
        for row, key, pod in entries:
            if key in failed:
                # The pod is NOT terminating.  unevict restores the
                # mirror status AND the cycle's job/queue counters so
                # the session-close status write-back matches reality.
                pod.deleting = False
                self.unevict(row, int(m.p_node[row]), int(m.p_job[row]))
                if ledger is not None:
                    # A rebalance victim whose eviction never dispatched
                    # must leave the migration ledger too: a stranded
                    # entry would pin its group's disruption budget and
                    # block every future plan (ledger.active), and the
                    # pod's EVENTUAL normal deletion would wrongly
                    # "restore" (resurrect) it.
                    ledger.cancel(pod.uid)
                events.append((f"Pod/{key}", "EvictFailed",
                               "evict dispatch failed; will retry"))
            else:
                events.append((f"Pod/{key}", "Evict",
                               "evicted by scheduler (preempt/reclaim)"))
                if store._watchers:
                    store._notify("Pod", "evict", pod)
        if failed:
            log.warning("%d evictions failed; pods revert to Running",
                        len(failed))
            # The unevict reverts above flipped p_status AFTER the
            # action loop already stamped the mutation counter: without
            # a fresh stamp the pipelined staleness guard (and the
            # cross-shard commit gate) would judge an in-flight solve
            # against pre-revert state and happily commit onto rows
            # that moved back to Running.  One stamp covers the batch.
            m.mutation_seq += 1
        if ledger is not None:
            # Ledgered victims whose eviction actually dispatched
            # (failed ones were cancelled above): the counters must
            # reflect evictions that happened, not plans that intended
            # them.  Preempt, reclaim and rebalance waves share the
            # ledger (ISSUE 11); each counts in its own series.
            by_action: Dict[str, int] = {}
            for _row, key, pod in entries:
                if key in failed:
                    continue
                entry = ledger.entries.get(pod.uid)
                if entry is not None:
                    a = getattr(entry, "action", "rebalance")
                    by_action[a] = by_action.get(a, 0) + 1
            if by_action:
                from .metrics import metrics

                n_reb = by_action.pop("rebalance", 0)
                if n_reb:
                    metrics.rebalance_evictions.inc(n_reb)
                for a, n in by_action.items():
                    metrics.preempt_evictions.inc(n, action=a)
        store.record_events_deferred(events)
        store.mark_objects_stale()


class _LazyHeap:
    """Priority queue over live keys without Python comparator callbacks.

    Entries carry the key frozen at push time (heap sifts are then C-level
    tuple compares); pop re-derives the key and re-pushes when it went
    stale, so the element actually returned is ordered by its CURRENT key
    — at least as fresh as the comparator-driven heap it replaces, whose
    sift decisions also mix pre- and post-mutation views."""

    __slots__ = ("key_fn", "h")

    def __init__(self, key_fn):
        self.key_fn = key_fn
        self.h: list = []

    def push(self, item) -> None:
        heapq.heappush(self.h, (self.key_fn(item), item))

    def pop(self):
        h = self.h
        while True:
            key, item = heapq.heappop(h)
            fresh = self.key_fn(item)
            if fresh == key:
                return item
            heapq.heappush(h, (fresh, item))

    def empty(self) -> bool:
        return not self.h


class FastEvictor:
    """Shared machinery for fast preempt + reclaim over one FastCycle."""

    # Lives inside FastCycle.run, under run_cycle_fast's store lock.
    # vclint: class-holds: _lock

    def __init__(self, cyc):
        self.cyc = cyc
        self.st = EvictState(cyc)
        self._score_w = self._collect_score_args()
        self._share_cache: Dict[int, tuple] = {}
        self._qshare_cache: Dict[int, tuple] = {}
        self._profile_scores: Dict[int, np.ndarray] = {}
        self._profile_static: Dict[int, np.ndarray] = {}
        self._evictable: Dict[tuple, np.ndarray] = {}
        self._rq_keys: List[tuple] = []
        self._qorder_has_prop = None
        self._zero_nr: Optional[np.ndarray] = None
        self._total_list = None
        self.st.on_change = self._evictable_update
        # Node-prefilter caches for queue-scoped evict scopes ("pq"/"rq"),
        # maintained per-node on events:
        # evict_key -> [N] bool "node has any in-scope evictable capacity"
        # (evict_key, init_req bytes) -> (init_req, [N] fi+ev fit mask).
        # Preemptors/reclaimers dedupe by request profile, so the O(N)
        # prefilter builds once per (scope, profile) instead of per task.
        # Job-scoped ("job", jr) prefilters are NOT cached (one per job);
        # they get an O(1) j_cnt_run guard instead.
        self._ev_any: Dict[tuple, np.ndarray] = {}
        self._ev_feas: Dict[tuple, tuple] = {}
        # Pod-count predicate column, maintained per-node (n_ntasks only
        # changes via pipeline/unpipeline).
        self._slots_mask: Optional[np.ndarray] = None
        # Nodes whose fi/evictable/ntasks changed since the cached masks
        # were last read; fixups are applied in batch at read time
        # (_apply_dirty) instead of once per event.
        self._dirty: set = set()
        self.st.on_node_change = self._dirty.add
        # Reclaim walk cursors: (evict_key, profile, pred-profile) ->
        # first node index not yet permanently ruled out.  Valid because
        # every prefilter component is monotone False-ward within an
        # evict action (see reclaim()); _apply_dirty rewinds the cursor
        # on the rare False->True flip (cross-queue victim of a
        # reclaiming queue).
        self._walk_cursor: Dict[tuple, int] = {}
        # Tier-ordered plugin-name lists per victim registry (precomputed:
        # the per-victim intersection walks these thousands of times).
        self._tiers_preempt = [
            [o.name for o in t.plugins if o.enabled_preemptable]
            for t in cyc.conf.tiers
        ]
        self._tiers_reclaim = [
            [o.name for o in t.plugins if o.enabled_reclaimable]
            for t in cyc.conf.tiers
        ]
        # Comparator hot-path constants (config is static for the cycle).
        self._job_order_names = [
            o.name for o in cyc._tier_opts("enabled_job_order")
        ]
        self._task_prio_enabled = any(
            o.name == "priority" for o in cyc._tier_opts("enabled_task_order")
        )
        # Per-job pending rows, task-ordered, built in one grouped pass
        # (replaces a full pod-axis scan per job).
        self._job_pending: Dict[int, List[int]] = {}
        c = cyc
        m = c.m
        rows = np.flatnonzero(
            m.p_alive[:c.Pn] & (m.p_status[:c.Pn] == ST_PENDING)
            & ~self.st.req_empty[:c.Pn] & (self.st.pipe_node[:c.Pn] < 0)
        )
        if len(rows):
            prio = (-m.p_prio[rows] if self._task_prio_enabled
                    else np.zeros(len(rows)))
            uids = np.array([m.p_uid[r] for r in rows])
            order = np.lexsort((uids, m.p_create[rows], prio))
            for r in rows[order]:
                self._job_pending.setdefault(
                    int(c.jobr[r]), []
                ).append(int(r))

    # -------------------------------------------------------------- session

    def resync(self) -> None:
        """Re-derive caches of FastCycle state that an allocate/backfill
        action may have mutated since the last evict action: fi snapshots
        n_idle, the slot mask snapshots n_ntasks, the share memos key off
        versions allocate never bumps, and node_rows misses pods the
        allocate action bound."""
        st = self.st
        c = self.cyc
        m = c.m
        st.fi = c.n_idle + c.n_releasing - st.n_pipelined
        self._slots_mask = None
        self._ev_any.clear()
        self._ev_feas.clear()
        self._walk_cursor.clear()
        self._dirty.clear()
        self._share_cache.clear()
        self._qshare_cache.clear()
        if hasattr(self, "_jkey_cache"):
            self._jkey_cache.clear()
        self._reclaim_poss_cache = None
        # Rebuild the per-node resident lists (allocate binds appear as
        # new residents; the host-port predicate walks these).  Session
        # pipelines re-append in pipelined order, as pipeline() did.
        st.node_rows = [[] for _ in range(c.Nn)]
        node = m.p_node[:c.Pn]
        for r in np.flatnonzero(c.resident):
            st.node_rows[node[r]].append(int(r))
        for r in st.pipelined_rows:
            if st.pipe_node[r] >= 0:
                st.node_rows[st.pipe_node[r]].append(int(r))

    def job_pipelined(self, jr: int) -> bool:
        """Gang JobPipelined veto (gang.go: waiting + ready >= min)."""
        c = self.cyc
        if not c._has("gang"):
            return True
        return bool(
            self.st.j_waiting[jr] + c.j_ready_base[jr] >= c.m.j_minav[jr]
        )

    # ------------------------------------------------------------ ordering

    def _job_key(self, jr: int) -> tuple:
        """Live tier-ordered job sort key (shares move during the action,
        so _LazyHeap re-derives this on pop).  Lexicographic order of the
        tuple == the reference's tiered job-order comparator.  Memoized
        per (job, j_version) — every live input is versioned by the same
        events that bump j_version."""
        cache = getattr(self, "_jkey_cache", None)
        if cache is None:
            cache = self._jkey_cache = {}
        jv = self.st.j_version[jr]
        hit = cache.get(jr)
        if hit is not None and hit[0] == jv:
            return hit[1]
        c = self.cyc
        m = c.m
        parts = []
        for name in self._job_order_names:
            if name == "priority":
                parts.append(-int(m.j_prio[jr]))
            elif name == "gang":
                # Non-ready jobs order first.
                parts.append(
                    1 if c.j_ready_base[jr] >= m.j_minav[jr] else 0
                )
            elif name == "drf":
                parts.append(self._drf_share(jr))
        parts.append(m.j_create[jr])
        parts.append(m.j_uid[jr])
        key = tuple(parts)
        cache[jr] = (jv, key)
        return key

    def _drf_share(self, jr: int) -> float:
        cache = self._share_cache
        hit = cache.get(jr)
        if hit is not None and hit[0] == self.st.j_version[jr]:
            return hit[1]
        c = self.cyc
        totals = self._total_list
        if totals is None:
            totals = self._total_list = [float(t) for t in c.total_res]
        alloc = c.j_alloc_res[jr]
        out = 0.0
        for k, t in enumerate(totals):
            a = float(alloc[k])
            v = a / t if t > 0.0 else (1.0 if a > 0.0 else 0.0)
            if v > out:
                out = v
        cache[jr] = (self.st.j_version[jr], out)
        return out

    def _queue_share(self, qi: int) -> float:
        cache = self._qshare_cache
        hit = cache.get(qi)
        qv = self.st.q_version[qi] if qi < len(self.st.q_version) else -1
        if hit is not None and hit[0] == qv:
            return hit[1]
        c = self.cyc
        des = c.q_deserved_res.get(qi)
        if des is None:
            return 0.0
        alloc = c._res(c.q_alloc[qi])
        s = 0.0
        from .api.resource import share as _share

        for rn in des.resource_names():
            v = _share(alloc.get(rn), des.get(rn))
            if v > s:
                s = v
        self._qshare_cache[qi] = (qv, s)
        return s

    def _queue_key(self, qname: str) -> tuple:
        """Live queue sort key (see _job_key)."""
        c = self.cyc
        has_prop = self._qorder_has_prop
        if has_prop is None:
            has_prop = self._qorder_has_prop = c._has("proportion") and any(
                opt.name == "proportion"
                for opt in c._tier_opts("enabled_queue_order")
            )
        q = c.store.queues[qname]
        if has_prop:
            return (self._queue_share(c.queue_index.get(qname, -1)),
                    q.queue.creation_timestamp, q.uid)
        return (q.queue.creation_timestamp, q.uid)

    def _task_rows_sorted(self, jr: int) -> List[int]:
        """Pending task rows of a job, task-ordered (from the grouped
        index; rows pipelined since init are filtered live)."""
        m = self.cyc.m
        pipe = self.st.pipe_node
        return [
            r for r in self._job_pending.get(jr, ())
            if pipe[r] < 0 and m.p_status[r] == ST_PENDING
        ]

    # ---------------------------------------------------------- predicates

    def feasible_mask(self, row: int) -> np.ndarray:
        """[N] host-predicate feasibility for one pending task
        (predicates.go:144-293 minus resource fit).  Static parts
        (selector / node affinity / taints) are cached per profile;
        pod-count, ports, and inter-pod terms are live."""
        c = self.cyc
        m = c.m
        N = c.Nn
        if not c._has("predicates"):
            return c.n_alive.copy()
        feat = m.p_feat[row]
        pod = c.store.pods.get(m.p_uid[row])
        if pod is None:
            return np.zeros(N, bool)
        pidr = int(m.p_prof[row])
        static = self._profile_static.get(pidr)
        if static is None:
            static = self._static_mask(feat)
            self._profile_static[pidr] = static
        self._apply_dirty()
        slots = self._slots_mask
        if slots is None:
            slots = self._slots_mask = (
                (c.n_maxtasks <= 0) | (c.n_ntasks < c.n_maxtasks)
            )
        ok = static & slots
        # Host ports.
        if feat.ports:
            myports = set(feat.ports)
            for n in range(N):
                if not ok[n]:
                    continue
                for r in self.st.node_rows[n]:
                    f = m.p_feat[r]
                    if f is not None and myports & set(f.ports):
                        ok[n] = False
                        break
        # Inter-pod required affinity (domain-count based, live counts
        # maintained by the allocate/preempt events this cycle are NOT
        # consulted here: matches the host path, which checks resident
        # node.tasks — evicted residents still count until deleted).
        if feat.ip_req_aff or feat.ip_req_anti:
            ok &= self._interpod_ok(row, feat)
        return ok

    def _static_mask(self, feat) -> np.ndarray:
        c = self.cyc
        m = c.m
        ok = c.n_ready.copy()
        labels_tbl = self._node_labels()
        if feat.sel:
            ok &= self._nodes_with_all(feat.sel, labels_tbl)
        if feat.aff_alts:
            any_alt = np.zeros(c.Nn, bool)
            for alt in feat.aff_alts:
                any_alt |= self._nodes_with_all(alt, labels_tbl)
            ok &= any_alt
        if len(m.taints):
            tol_idx = self._tolerated(feat)
            for k in range(len(m.taints.items)):
                if k not in tol_idx:
                    ok &= ~self._nodes_with_taint(k)
        return ok

    def _node_labels(self):
        cache = getattr(self, "_labels_cache", None)
        if cache is None:
            m = self.cyc.m
            cache = self._labels_cache = [
                (m.node_objs[n].labels if m.node_objs[n] is not None else {})
                for n in range(self.cyc.Nn)
            ]
        return cache

    def _nodes_with_all(self, sel_idx: List[int], labels_tbl) -> np.ndarray:
        m = self.cyc.m
        key = ("sel", tuple(sorted(sel_idx)))
        cache = getattr(self, "_mask_cache", None)
        if cache is None:
            cache = self._mask_cache = {}
        hit = cache.get(key)
        if hit is not None:
            return hit
        pairs = [m.labels.items[i] for i in sel_idx]
        out = np.fromiter(
            (all(lbl.get(k) == v for k, v in pairs) for lbl in labels_tbl),
            bool, count=len(labels_tbl),
        )
        cache[key] = out
        return out

    def _nodes_with_taint(self, k: int) -> np.ndarray:
        cache = getattr(self, "_taint_cache", None)
        if cache is None:
            cache = self._taint_cache = {}
        hit = cache.get(k)
        if hit is not None:
            return hit
        m = self.cyc.m
        tkey, tval, teff = m.taints.items[k]
        out = np.fromiter(
            (
                any(t.key == tkey and t.value == tval and t.effect == teff
                    for t in (m.node_objs[n].taints
                              if m.node_objs[n] is not None else []))
                for n in range(self.cyc.Nn)
            ),
            bool, count=self.cyc.Nn,
        )
        cache[k] = out
        return out

    def _tolerated(self, feat) -> set:
        m = self.cyc.m
        idx = set()
        for k, (tkey, tval, teff) in enumerate(m.taints.items):
            for tol in feat.tol:
                if tol.operator == "Exists":
                    key_ok = tol.key == "" or tol.key == tkey
                else:
                    key_ok = tol.key == tkey and tol.value == tval
                if key_ok and (tol.effect == "" or tol.effect == teff):
                    idx.add(k)
                    break
        return idx

    def _interpod_ok(self, row: int, feat) -> np.ndarray:
        """Required inter-pod (anti)affinity per node for one task, from
        the term membership lists (resident pods incl. Releasing +
        session pipelines, matching the host predicate)."""
        c = self.cyc
        m = c.m
        N = c.Nn
        node_dom = m.node_dom()
        ok = np.ones(N, bool)
        for e in feat.ip_req_aff:
            dom_col = m.topo_keys.index.get(m.term_info[e][1], 0)
            doms = node_dom[:N, dom_col]
            counts = self._term_node_counts(e, row)
            total = counts.sum()
            if total == 0:
                # self-match rule
                jr = int(m.p_job[row])
                juid = m.j_uid[jr] if jr >= 0 else ""
                pod = c.store.pods.get(m.p_uid[row])
                if pod is not None and m._term_matches(
                    e, pod.namespace, pod.labels, juid or ""
                ):
                    continue
                ok &= False
                continue
            ok &= np.where(doms >= 0, counts[np.maximum(doms, 0)] > 0, False)
        for e in feat.ip_req_anti:
            dom_col = m.topo_keys.index.get(m.term_info[e][1], 0)
            doms = node_dom[:N, dom_col]
            counts = self._term_node_counts(e, row)
            ok &= ~np.where(doms >= 0, counts[np.maximum(doms, 0)] > 0,
                            False)
        return ok

    def _term_node_counts(self, e: int, skip_row: int) -> np.ndarray:
        """[D] resident-match counts per domain for term e (incl.
        session pipelines, excl. the task itself)."""
        c = self.cyc
        m = c.m
        D = max(1, len(m.domains))
        counts = np.zeros(D, np.int64)
        node_dom = m.node_dom()
        dom_col = m.topo_keys.index.get(m.term_info[e][1], 0)
        for r in m.term_members[e]:
            if r == skip_row or r >= c.Pn:
                continue
            n = int(m.p_node[r]) if self.st.pipe_node[r] < 0 else \
                int(self.st.pipe_node[r])
            if n < 0:
                continue
            if not (c.resident[r] or self.st.pipe_node[r] >= 0):
                continue
            d = node_dom[n, dom_col]
            if d >= 0:
                counts[d] += 1
        return counts

    # -------------------------------------------------------------- scores

    def _collect_score_args(self):
        from .framework.arguments import Arguments

        c = self.cyc
        out = {"binpack": None, "nodeorder": None}
        for opt in c._tier_opts("enabled_node_order"):
            if opt.name in out and out[opt.name] is None:
                out[opt.name] = Arguments(opt.arguments)
        return out

    def scores(self, row: int) -> np.ndarray:
        """[N] additive node-order score (binpack.go:200-260 +
        nodeorder.go:38-84), vectorized.  Cached per task profile:
        node used/allocatable never change during preempt/reclaim
        (evictions move resources to Releasing, not back to idle)."""
        pidr = int(self.cyc.m.p_prof[row])
        hit = self._profile_scores.get(pidr)
        if hit is not None:
            return hit
        out = self._scores_uncached(row)
        self._profile_scores[pidr] = out
        return out

    def _scores_uncached(self, row: int) -> np.ndarray:
        c = self.cyc
        N = c.Nn
        req = self.st.req[row]
        s = np.zeros(N, F)
        bp = self._score_w.get("binpack")
        if bp is not None:
            weight = max(bp.get_int("binpack.weight", 1), 1)
            w = np.zeros(c.R, F)
            w[0] = max(bp.get_int("binpack.cpu", 1), 0)
            w[1] = max(bp.get_int("binpack.memory", 1), 0)
            for name in (bp.get("binpack.resources") or "").split(","):
                name = name.strip()
                idx = c.m.scalar_slots.index.get(name) if name else None
                if idx is not None:
                    w[2 + idx] = max(
                        bp.get_int(f"binpack.resources.{name}", 1), 0
                    )
            used_f = c.n_used + req[None, :]
            with np.errstate(divide="ignore", invalid="ignore"):
                per = np.where(
                    (req[None, :] > 0) & (c.n_alloc > 0)
                    & (used_f <= c.n_alloc) & (w[None, :] > 0),
                    used_f * w[None, :] / np.where(c.n_alloc > 0,
                                                   c.n_alloc, 1.0),
                    0.0,
                )
            # weight_sum counts weights of requested-and-known resources.
            wsum = float(w[req > 0].sum())
            if wsum > 0:
                s += per.sum(axis=1) / wsum * 10.0 * weight
        no = self._score_w.get("nodeorder")
        if no is not None:
            least = no.get_int("leastrequested.weight", 1)
            most = no.get_int("mostrequested.weight", 0)
            balanced = no.get_int("balancedresource.weight", 1)
            cap_cpu = c.n_alloc[:, 0]
            cap_mem = c.n_alloc[:, 1]
            req_cpu = c.n_used[:, 0] + req[0]
            req_mem = c.n_used[:, 1] + req[1]
            with np.errstate(divide="ignore", invalid="ignore"):
                if least:
                    pc = np.where(cap_cpu > 0,
                                  np.maximum(cap_cpu - req_cpu, 0)
                                  * 10.0 / np.where(cap_cpu > 0, cap_cpu, 1),
                                  0.0)
                    pm = np.where(cap_mem > 0,
                                  np.maximum(cap_mem - req_mem, 0)
                                  * 10.0 / np.where(cap_mem > 0, cap_mem, 1),
                                  0.0)
                    s += (pc + pm) / 2.0 * least
                if most:
                    pc = np.where((cap_cpu > 0) & (req_cpu <= cap_cpu),
                                  req_cpu * 10.0
                                  / np.where(cap_cpu > 0, cap_cpu, 1), 0.0)
                    pm = np.where((cap_mem > 0) & (req_mem <= cap_mem),
                                  req_mem * 10.0
                                  / np.where(cap_mem > 0, cap_mem, 1), 0.0)
                    s += (pc + pm) / 2.0 * most
                if balanced:
                    cf = np.where(cap_cpu > 0, req_cpu
                                  / np.where(cap_cpu > 0, cap_cpu, 1), 1.0)
                    mf = np.where(cap_mem > 0, req_mem
                                  / np.where(cap_mem > 0, cap_mem, 1), 1.0)
                    bal = np.where((cf > 1.0) | (mf > 1.0), 0.0,
                                   (1.0 - np.abs(cf - mf)) * 10.0)
                    s += bal * balanced
        return s

    # ----------------------------------------------- evictable prefilter

    def _le_rows(self, l: np.ndarray, a: np.ndarray,
                 b: Optional[np.ndarray] = None) -> np.ndarray:
        """Row-wise epsilon Resource.less_equal: l [R] vs a(+b) [N, R].

        (l < r) | (|l - r| < eps) is equivalent to r > l - eps, and
        scalar slots with l <= eps pass unconditionally, so only the
        remaining columns need the comparison.  The per-column loop
        (R is 2-4) avoids materializing any [N, R] temporary — this
        runs once per preemptor task over 10k+ nodes."""
        c = self.cyc
        cols = np.flatnonzero(~(c.scalar_slot & (l <= c.eps)))
        out = np.ones(a.shape[0], bool)
        thresh = l - c.eps
        for k in cols:
            col = a[:, k] if b is None else a[:, k] + b[:, k]
            out &= col > thresh[k]
        return out

    def _vjob_group(self, jr: int) -> np.ndarray:
        """Indices into the victim base vectors for one job (grouped once;
        a per-job O(#victims) mask scan repeated for thousands of jobs in
        preempt phase 2 dominated the action otherwise)."""
        groups = getattr(self, "_vjob_groups", None)
        if groups is None:
            st = self.st
            groups = self._vjob_groups = {}
            order = np.argsort(st.v_job, kind="stable")
            uniq, starts = np.unique(st.v_job[order], return_index=True)
            bounds = list(starts) + [len(order)]
            for i, j in enumerate(uniq):
                groups[int(j)] = order[bounds[i]:bounds[i + 1]]
        return groups.get(jr, np.empty(0, np.int64))

    def _evictable_for(self, key: tuple) -> np.ndarray:
        arr = self._evictable.get(key)
        if arr is not None:
            return arr
        c = self.cyc
        m = c.m
        st = self.st
        kind = key[0]
        if kind == "job":
            sel = self._vjob_group(int(key[1]))
            if len(sel):
                sel = sel[m.p_status[:c.Pn][st.v_rows[sel]] == ST_RUNNING]
        else:
            mask = (m.p_status[:c.Pn][st.v_rows] == ST_RUNNING) \
                & (st.v_job >= 0)
            if kind == "pq":
                qi = c.queue_index.get(key[1], -1)
                mask &= st.v_qi == qi
            elif kind == "rq":
                qi = c.queue_index.get(key[1], -1)
                reclaimable = np.zeros(c.Qn + 1, bool)
                for name, i in c.queue_index.items():
                    q = c.store.queues.get(name)
                    reclaimable[i] = bool(q is not None and q.reclaimable())
                mask &= (st.v_qi != qi) & (st.v_qi >= 0) \
                    & reclaimable[np.maximum(st.v_qi, 0)]
            sel = np.flatnonzero(mask)
        if not len(sel):
            # Copy-on-write zero: thousands of "job" keys (one per
            # under-request job in preempt phase 2) have no Running
            # victims at all; share one read-only zero array for them.
            arr = self._zero_nr
            if arr is None:
                arr = np.zeros((c.Nn, c.R), F)
                arr.flags.writeable = False
                self._zero_nr = arr
        else:
            arr = np.zeros((c.Nn, c.R), F)
            np.add.at(arr, st.v_node[sel], st.v_req[sel])
        self._evictable[key] = arr
        if kind == "rq":
            self._rq_keys.append(key)
        return arr

    def _apply_dirty(self) -> None:
        """Apply queued per-node fixups to every cached prefilter mask
        (O(#dirty x #cached entries); dirty is typically 1-2 nodes).
        A False->True flip rewinds affected walk cursors."""
        dirty = self._dirty
        if not dirty:
            return
        c = self.cyc
        st = self.st
        ev = self._evictable
        slots = self._slots_mask
        for n in dirty:
            if slots is not None:
                slots[n] = (
                    c.n_maxtasks[n] <= 0
                    or c.n_ntasks[n] < c.n_maxtasks[n]
                )
            for key, anym in self._ev_any.items():
                arr = ev.get(key)
                new = bool((arr[n] > 1e-6).any()) if arr is not None \
                    else False
                if new and not anym[n]:
                    self._rewind_cursors(key, n)
                anym[n] = new
            if self._ev_feas:
                fi_n = st.fi[n]
                for (key, _), (init_req, mask) in self._ev_feas.items():
                    arr = ev.get(key)
                    tot = fi_n + arr[n] if arr is not None else fi_n
                    ok = (init_req < tot) \
                        | (np.abs(init_req - tot) < c.eps) \
                        | (c.scalar_slot & (init_req <= c.eps))
                    new = bool(ok.all())
                    if new and not mask[n]:
                        self._rewind_cursors(key, n)
                    mask[n] = new
        dirty.clear()

    def _rewind_cursors(self, evict_key: tuple, n: int) -> None:
        for wkey, cur in self._walk_cursor.items():
            if wkey[0] == evict_key and cur > n:
                self._walk_cursor[wkey] = n

    def _prefilter(self, evict_key: tuple, init_req: np.ndarray,
                   ev: np.ndarray) -> np.ndarray:
        """[N] cached necessary-condition mask for a queue-scoped evict
        scope: node has in-scope victims AND fi + evictable covers the
        request.  Built once per (scope, request-profile); per-node
        fixups applied lazily (_apply_dirty)."""
        self._apply_dirty()
        anym = self._ev_any.get(evict_key)
        if anym is None:
            anym = self._ev_any[evict_key] = (ev > 1e-6).any(axis=1)
        fkey = (evict_key, init_req.tobytes())
        ent = self._ev_feas.get(fkey)
        if ent is None:
            ent = (init_req.copy(),
                   self._le_rows(init_req, self.st.fi, ev))
            self._ev_feas[fkey] = ent
        return anym & ent[1]

    def _evictable_update(self, row: int, sign: int) -> None:
        """Direct-addressed cache update: a Running victim row counts
        toward at most its own ("pq", queue) key (an upper bound — own-job
        and higher-priority victims stay included; the exact walk filters
        them, so one cache serves every preemptor of the queue), its own
        ("job", job) key, and the "rq" keys of OTHER queues when the
        victim's queue is reclaimable — O(1 + #rq keys) instead of a scan
        over every cached key.  Gang caps and conformance are checked
        exactly downstream."""
        c = self.cyc
        m = c.m
        jr = int(m.p_job[row])
        if jr < 0:
            return
        n = int(m.p_node[row])
        req = self.st.req[row]
        ev = self._evictable
        jq = m.j_queue[jr]
        sreq = sign * req
        for key in (("pq", jq), ("job", jr)):
            arr = ev.get(key)
            if arr is not None:
                if arr is self._zero_nr:  # copy-on-write
                    arr = ev[key] = np.zeros((c.Nn, c.R), F)
                arr[n] += sreq
        if self._rq_keys:
            vq = c.store.queues.get(jq)
            if vq is not None and vq.reclaimable():
                for key in self._rq_keys:
                    if key[1] != jq:
                        arr = ev[key]
                        if arr is self._zero_nr:
                            arr = ev[key] = np.zeros((c.Nn, c.R), F)
                        arr[n] += sreq

    # -------------------------------------------------------------- victims

    def _victims(self, preemptor_row: int, cand: List[int],
                 registry: str) -> List[int]:
        """Tiered victim intersection (session_plugins.go:110-193)."""
        c = self.cyc
        victims: List[int] = []
        init = False
        tiers = (self._tiers_preempt if registry == "preempt"
                 else self._tiers_reclaim)
        for tier in tiers:
            for pname in tier:
                sel = self._plugin_victims(pname, preemptor_row, cand,
                                           registry)
                if sel is None:
                    continue
                if not init:
                    victims = list(sel)
                    init = True
                else:
                    keep = set(sel)
                    victims = [v for v in victims if v in keep]
            if victims:
                return victims
            if init:
                return victims
        return victims

    def _plugin_victims(self, name: str, prow: int, cand: List[int],
                        registry: str) -> Optional[List[int]]:
        c = self.cyc
        m = c.m
        st = self.st
        if name == "priority" and registry == "preempt":
            pj = int(m.p_job[prow])
            ppri = m.j_prio[pj] if pj >= 0 else 0
            return [r for r in cand
                    if m.j_prio[max(int(m.p_job[r]), 0)] < ppri
                    and int(m.p_job[r]) >= 0]
        if name == "gang":
            occupied: Dict[int, int] = {}
            out = []
            for r in cand:
                jr = int(m.p_job[r])
                if jr < 0:
                    continue
                cnt = occupied.get(jr)
                if cnt is None:
                    cnt = int(c.j_ready_base[jr])
                min_av = int(m.j_minav[jr])
                if min_av <= cnt - 1 or min_av == 1:
                    occupied[jr] = cnt - 1
                    out.append(r)
                else:
                    occupied[jr] = cnt
            return out
        if name == "conformance":
            return [r for r in cand if not st.critical[r]]
        if name == "drf" and registry == "preempt":
            pj = int(m.p_job[prow])
            total = c.total_res
            l_alloc = c.j_alloc_res[pj] + st.req[prow]
            ls = self._share_of(l_alloc, total)
            allocations: Dict[int, np.ndarray] = {}
            out = []
            for r in cand:
                jr = int(m.p_job[r])
                if jr not in allocations:
                    allocations[jr] = c.j_alloc_res[jr].copy()
                allocations[jr] = allocations[jr] - st.req[r]
                rs = self._share_of(allocations[jr], total)
                if ls < rs or abs(ls - rs) <= 1e-6:
                    out.append(r)
            return out
        if name == "proportion" and registry == "reclaim":
            from .api.resource import Resource

            allocations: Dict[int, object] = {}
            out = []
            for r in cand:
                jr = int(m.p_job[r])
                qi = int(c.q_of_job[jr]) if jr >= 0 else -1
                if qi < 0:
                    continue
                des = c.q_deserved_res.get(qi)
                if des is None:
                    continue
                if qi not in allocations:
                    allocations[qi] = c._res(c.q_alloc[qi])
                allocated = allocations[qi]
                victim_req = c._res(st.req[r])
                if allocated.less(victim_req):
                    continue
                allocated.sub(victim_req)
                if des.less_equal_strict(allocated):
                    out.append(r)
            return out
        return None

    @staticmethod
    def _share_of(alloc: np.ndarray, total: np.ndarray) -> float:
        with np.errstate(divide="ignore", invalid="ignore"):
            ratio = np.where(total > 0, alloc / np.where(total > 0, total, 1),
                             np.where(alloc > 0, 1.0, 0.0))
        return float(ratio.max()) if len(ratio) else 0.0

    # ------------------------------------------------------------- preempt

    def _try_preempt(self, prow: int, cand_filter, stmt: Optional[list],
                     evict_key: tuple) -> bool:
        """One preemptor against all nodes (preempt.go:183-262)."""
        c = self.cyc
        m = c.m
        st = self.st
        eps = c.eps
        scalar = c.scalar_slot
        from .fastpath import _vec_le

        init_req = st.init_req[prow]
        # Necessary-condition prefilter first (cheaper than the full
        # predicate mask): the node must HOLD in-scope victims (an empty
        # candidate list just `continue`s below) and its future idle
        # plus ALL its in-scope victims' resources must cover the
        # preemptor — otherwise the exact walk cannot succeed there.
        if evict_key[0] == "job":
            # Intra-job scope: no running members -> no victims anywhere
            # (O(1), avoids scoring nodes for hopeless preemptors).
            if c.j_cnt_run[int(evict_key[1])] <= 0:
                return False
            ev = self._evictable_for(evict_key)
            feasible = (ev > 1e-6).any(axis=1) \
                & self._le_rows(init_req, st.fi, ev) & c.n_alive
        else:
            ev = self._evictable_for(evict_key)
            feasible = self._prefilter(evict_key, init_req, ev) \
                & c.n_alive
        if not feasible.any():
            return False
        feasible &= self.feasible_mask(prow)
        rows_f = np.flatnonzero(feasible)
        if not len(rows_f):
            return False
        sc = self.scores(prow)[rows_f]
        order = rows_f[np.argsort(-sc, kind="stable")]
        for n in order:
            cand = [r for r in st.node_rows[n]
                    if m.p_status[r] == ST_RUNNING
                    and not st.req_empty[r] and cand_filter(r)]
            if not cand:
                continue
            victims = self._victims(prow, cand, "preempt")
            if not victims:
                continue
            # validate_victims: victims' resources must suffice.
            fut = st.future_idle(n)
            vsum = st.req[victims].sum(axis=0)
            if not _vec_le(init_req, fut + vsum, eps, scalar):
                continue
            # Evict lowest task order first: inverse of task_order.
            prio_enabled = self._task_prio_enabled
            vp = [(-int(m.p_prio[r]) if prio_enabled else 0,
                   m.p_create[r], m.p_uid[r], r) for r in victims]
            vp.sort(reverse=True)  # lowest order popped first
            for _pk, _ck, _uk, r in vp:
                if _vec_le(init_req, st.future_idle(n), eps, scalar):
                    break
                st.evict(r, stmt)
            if _vec_le(init_req, st.future_idle(n), eps, scalar):
                st.pipeline(prow, int(n), stmt)
                return True
        return False

    def preempt(self) -> None:
        """preempt.go:41-177."""
        c = self.cyc
        m = c.m
        st = self.st
        preemptors_map: Dict[str, _LazyHeap] = {}
        tasks_map: Dict[int, List[int]] = {}
        under_request: List[int] = []
        queue_seq: List[str] = []
        seen_q = set()
        for jr in self._schedulable_jobs():
            qname = m.j_queue[jr]
            if qname not in seen_q:
                seen_q.add(qname)
                queue_seq.append(qname)
            pending = self._task_rows_sorted(jr)
            if pending and not self.job_pipelined(jr):
                preemptors_map.setdefault(
                    qname, _LazyHeap(self._job_key)
                ).push(jr)
                under_request.append(jr)
                tasks_map[jr] = pending
        for qname in queue_seq:
            preemptors = preemptors_map.get(qname)
            # Phase 1 can only evict RUNNING same-queue victims
            # (job_filter below; no victims -> _try_preempt never
            # pipelines, preempt.go's empty-preemptees continue).  A
            # queue with no running tasks at all makes every phase-1
            # turn a no-op whose only observable effect is draining the
            # preemptor task lists — do exactly that, wholesale.
            if preemptors is not None and not preemptors.empty():
                qi = c.queue_index.get(qname)
                if qi is not None:
                    has_running = bool(np.any(
                        (c.q_of_job[:c.Jn] == qi)
                        & (c.j_cnt_run[:c.Jn] > 0)
                    ))
                    if not has_running:
                        for _k, jr0 in preemptors.h:
                            lst = tasks_map.get(jr0)
                            if lst:
                                lst.clear()
                        preemptors.h.clear()
            # Phase 1: inter-job preemption within the queue.
            while preemptors is not None and not preemptors.empty():
                jr = preemptors.pop()
                stmt: list = []
                assigned = False
                tasks = tasks_map.get(jr, [])
                while True:
                    if self.job_pipelined(jr):
                        break
                    if not tasks:
                        break
                    prow = tasks.pop(0)
                    pq = m.j_queue[jr]

                    def job_filter(r: int) -> bool:
                        vjr = int(m.p_job[r])
                        if vjr < 0:
                            return False
                        return (m.j_queue[vjr] == pq) and vjr != jr

                    if self._try_preempt(prow, job_filter, stmt,
                                          ("pq", pq)):
                        assigned = True
                if self.job_pipelined(jr):
                    st.commit(stmt)
                else:
                    st.rollback(stmt)
                    continue
                if assigned:
                    preemptors.push(jr)
            # Phase 2: intra-job task preemption (the reference iterates
            # ALL under-request jobs inside each queue pass; the shared
            # task lists make it drain once).
            for jr in under_request:
                tasks = tasks_map.get(jr, [])
                while tasks:
                    prow = tasks.pop(0)
                    stmt2: list = []

                    def task_filter(r: int) -> bool:
                        return int(m.p_job[r]) == jr

                    assigned = self._try_preempt(
                        prow, task_filter, stmt2, ("job", jr)
                    )
                    st.commit(stmt2)
                    if not assigned:
                        break

    def _schedulable_jobs(self) -> List[int]:
        c = self.cyc
        m = c.m
        srows = np.asarray(c.session_jobs, np.int64)
        if not len(srows):
            return []
        # Vectorized over the derive-time snapshot: j_phase code 1 =
        # Pending-with-PodGroup (enqueue's in-place Inqueue transitions
        # update the same array); q_of_job < 0 <=> queue unknown.
        keep = c.j_phase[srows] != 1
        if c._has("gang"):
            keep &= c.j_valid[srows] >= m.j_minav[srows]
        keep &= c.q_of_job[srows] >= 0
        return srows[keep].tolist()

    # ------------------------------------------------------------- reclaim

    def _reclaim_prop_gated(self) -> bool:
        """True when proportion sits in the FIRST tier containing any
        reclaimable-registered plugin: only then does its queue-slack
        veto gate the walk (an earlier tier producing victims stops
        before proportion is consulted — session_plugins.go tier-
        boundary semantics).  Shared by the Python veto and the C
        engine's reclaim_gated flag."""
        registered = {"gang", "conformance", "proportion"}
        first = next(
            (t for t in self._tiers_reclaim if registered & set(t)), None
        )
        return bool(first is not None and "proportion" in first)

    def _reclaim_possible(self, qname: str) -> bool:
        """True when some OTHER reclaimable queue still has slack above
        its deserved share (necessary for any proportion-admitted victim;
        trivially true when proportion is not in the reclaim tiers)."""
        c = self.cyc
        if not self._reclaim_prop_gated():
            return True
        cache = getattr(self, "_reclaim_poss_cache", None)
        if cache is not None and cache[0] == self.st.version:
            verdicts = cache[1]
        else:
            verdicts = {}
            self._reclaim_poss_cache = (self.st.version, verdicts)
        hit = verdicts.get(qname)
        if hit is not None:
            return hit
        out = False
        for name, qi in c.queue_index.items():
            if name == qname:
                continue
            q = c.store.queues.get(name)
            if q is None or not q.reclaimable():
                continue
            des = c.q_deserved_res.get(qi)
            if des is None:
                continue
            if des.less_equal_strict(c._res(c.q_alloc[qi])):
                out = True
                break
        verdicts[qname] = out
        return out

    def reclaim(self) -> None:
        """reclaim.go:40-189: cross-queue eviction, immediate."""
        c = self.cyc
        m = c.m
        st = self.st
        from .fastpath import _vec_le

        queues_pq = _LazyHeap(self._queue_key)
        seen_q = set()
        jobs_map: Dict[str, _LazyHeap] = {}
        tasks_map: Dict[int, List[int]] = {}
        for jr in self._schedulable_jobs():
            qname = m.j_queue[jr]
            if qname not in seen_q:
                seen_q.add(qname)
                queues_pq.push(qname)
            pending = self._task_rows_sorted(jr)
            if pending:
                jobs_map.setdefault(
                    qname, _LazyHeap(self._job_key)
                ).push(jr)
                tasks_map[jr] = pending

        overused = c._overused_fn()
        nat = self._native_reclaim_setup()
        try:
            if nat is None or not self._native_reclaim_drive(
                    nat, jobs_map, tasks_map):
                seed = self.__dict__.pop("_reclaim_over_seed", None)
                if seed:
                    # Verdicts the C drive already froze stay frozen in
                    # the fallback (first-evaluation semantics span the
                    # whole pass).
                    base_overused = overused

                    def overused(qinfo, _b=base_overused, _s=seed):
                        v = _s.get(qinfo.name)
                        return bool(v) if v is not None else _b(qinfo)
                self._reclaim_loop(queues_pq, jobs_map, tasks_map,
                                   overused, nat)
        finally:
            if nat is not None:
                nat["lib"].vcreclaim_ctx_free(nat["ctx"])

    def _reclaim_loop(self, queues_pq, jobs_map, tasks_map, overused,
                      nat) -> None:
        c = self.cyc
        m = c.m
        st = self.st
        while not queues_pq.empty():
            qname = queues_pq.pop()
            if overused(c.store.queues[qname]):
                continue
            jobs = jobs_map.get(qname)
            if jobs is None or jobs.empty():
                continue
            jr = jobs.pop()
            tasks = tasks_map.get(jr, [])
            if not tasks:
                continue
            prow = tasks.pop(0)

            assigned = False
            if not self._reclaim_possible(qname):
                # Necessary condition: proportion only admits a victim
                # while its queue stays at/above deserved after the
                # eviction; once no reclaimable queue has slack, no node
                # can yield victims (proportion.go:209-211) — skip the
                # node walk wholesale.
                queues_pq.push(qname)
                continue
            init_req = st.init_req[prow]
            # Node prefilter = validate_victims (scheduler_helper.go:
            # 224-239): FutureIdle + victim capacity must cover the
            # task.  NOT evictable-alone: reclaim.go's victim loop runs
            # on any validated node and its evictions stand even when
            # the reclaimed sum never covers the task (the pipeline
            # check `resreq.less_equal(reclaimed)` gates only the
            # pipeline, reclaim.go:166-175) — an evictable-only filter
            # would skip those collateral evictions and diverge
            # (caught by tests/test_evict_oracle.py fuzz seed 0).
            ev = self._evictable_for(("rq", qname))
            # Victim-less nodes drop out entirely (validate_victims
            # raises "no victims" there); exhausted nodes thus stop
            # costing their Python candidate walk as victims deplete.
            # Cached per (scope, request-profile), maintained per-node.
            comb = self._prefilter(("rq", qname), init_req, ev)
            # Reclaim walks nodes in insertion (= index) order
            # (reclaim.go `for _, n := range ssn.Nodes`).  Every cheap
            # prefilter component only flips False-ward while the action
            # runs (evicting an in-scope victim keeps fi+ev constant;
            # pipelines shrink fi; pod-count only grows; static masks
            # are constant), so nodes ruled out by THESE masks are ruled
            # out for every later reclaimer of the same (scope, profile)
            # — a persistent cursor skips them once instead of scanning
            # [N] per task.  _apply_dirty rewinds it on the rare
            # False->True flip.  Nodes failing only the exact per-node
            # walk (victim narrowing) are NOT skipped by the cursor.
            feat = m.p_feat[prow]
            pidr = int(m.p_prof[prow])
            has_pred = c._has("predicates")
            static = None
            if has_pred:
                static = self._profile_static.get(pidr)
                if static is None:
                    static = self._static_mask(feat)
                    self._profile_static[pidr] = static
            plain_feat = not (feat.ports or feat.ip_req_aff
                              or feat.ip_req_anti)
            if has_pred and c.store.pods.get(m.p_uid[prow]) is None:
                # feasible_mask's ghost-task guard: a pending row with no
                # live pod record schedules nowhere.
                queues_pq.push(qname)
                continue
            if plain_feat:
                wkey = (("rq", qname), init_req.tobytes(), pidr)
                slots = self._slots_mask
                if slots is None and has_pred:
                    slots = self._slots_mask = (
                        (c.n_maxtasks <= 0) | (c.n_ntasks < c.n_maxtasks)
                    )
                qid = c.queue_index.get(qname, -1)
                if nat is not None and qid >= 0:
                    assigned = self._native_reclaim_step(
                        nat, prow, qid, init_req, wkey, static, slots,
                        comb, qname,
                    )
                else:
                    assigned = self._python_reclaim_walk(
                        prow, init_req, qname, wkey, comb, static, slots,
                    )
            else:
                feasible = comb
                if feasible.any():
                    feasible = feasible & self.feasible_mask(prow)
                for n in np.flatnonzero(feasible & c.n_alive):
                    if self._reclaim_node(prow, init_req, qname,
                                          int(n)):
                        assigned = True
                        break
            if assigned:
                jobs.push(jr)
            queues_pq.push(qname)

    def _python_reclaim_walk(self, prow: int, init_req: np.ndarray,
                             qname: str, wkey, comb, static,
                             slots) -> bool:
        """Cursor walk over nodes in index order (the exact fallback for
        the C engine; identical semantics)."""
        c = self.cyc
        n = self._walk_cursor.get(wkey, 0)
        advancing = True
        n_alive = c.n_alive
        Nn = c.Nn
        while n < Nn:
            if not (comb[n] and n_alive[n]
                    and (static is None or (static[n] and slots[n]))):
                n += 1
                if advancing:
                    self._walk_cursor[wkey] = n
                continue
            advancing = False
            if self._reclaim_node(prow, init_req, qname, n):
                return True
            n += 1
        return False

    def _reclaim_node(self, prow: int, init_req: np.ndarray,
                      qname: str, n: int) -> bool:
        """The exact per-node reclaim walk (reclaim.go:136-175): collect
        cross-queue Running candidates of reclaimable queues, narrow via
        the tiered Reclaimable intersection, validate, evict victims in
        order until the reclaimed sum covers the task, pipeline iff it
        does.  Returns True when the task pipelined on this node."""
        c = self.cyc
        m = c.m
        st = self.st
        from .fastpath import _vec_le

        cand = []
        for r in st.node_rows[n]:
            if m.p_status[r] != ST_RUNNING or st.req_empty[r]:
                continue
            vjr = int(m.p_job[r])
            if vjr < 0 or m.j_queue[vjr] == qname:
                continue
            vq = c.store.queues.get(m.j_queue[vjr])
            if vq is None or not vq.reclaimable():
                continue
            cand.append(r)
        victims = self._victims(prow, cand, "reclaim")
        if not victims:
            return False
        fut = st.future_idle(n)
        vsum = st.req[victims].sum(axis=0)
        if not _vec_le(init_req, fut + vsum, c.eps, c.scalar_slot):
            return False
        reclaimed = np.zeros(c.R, F)
        for r in victims:
            st.evict(r, None)
            st.evicted_rows.append(r)
            reclaimed += st.req[r]
            if _vec_le(init_req, reclaimed, c.eps, c.scalar_slot):
                break
        if _vec_le(init_req, reclaimed, c.eps, c.scalar_slot):
            st.pipeline(prow, n, None)
            return True
        return False

    # ------------------------------------------------- native reclaim core

    _NATIVE_MAX_CAND = 512  # VC_MAX_CAND in csrc/vcsnap.cc

    def _native_reclaim_setup(self):
        """Prepare the dense context for the C reclaim step
        (csrc/vcsnap.cc vcreclaim_step) — or None to use the Python
        walk.  The C side mutates the SAME numpy buffers the Python
        bookkeeping uses, so the two paths are interchangeable
        per-reclaimer."""
        c = self.cyc
        st = self.st
        m = c.m
        if c.R > 8:
            return None
        from .native import reclaim_lib

        lib = reclaim_lib()
        if lib is None:
            return None
        # Degenerate nodes (> C scratch capacity) use the Python walk
        # for the whole action to keep mid-walk state exact.
        max_res = max((len(r) for r in st.node_rows), default=0)
        if max_res > self._NATIVE_MAX_CAND:
            return None
        # Contiguity: some cycle arrays are views; the C engine needs
        # C-order buffers, and replacing the attribute keeps them live
        # for the Python side too.
        for name in ("j_cnt_alloc", "j_cnt_run", "j_cnt_releasing",
                     "j_ready_base", "j_cnt_pending", "q_of_job",
                     "n_ntasks", "n_maxtasks"):
            arr = getattr(c, name)
            if not arr.flags["C_CONTIGUOUS"] or arr.dtype != np.int32:
                setattr(c, name, np.ascontiguousarray(arr, np.int32))
        if not c.j_alloc_res.flags["C_CONTIGUOUS"]:
            c.j_alloc_res = np.ascontiguousarray(c.j_alloc_res)
        if not c.q_alloc.flags["C_CONTIGUOUS"]:
            c.q_alloc = np.ascontiguousarray(c.q_alloc)
        if not st.fi.flags["C_CONTIGUOUS"]:
            st.fi = np.ascontiguousarray(st.fi)
        if not c.n_releasing.flags["C_CONTIGUOUS"]:
            c.n_releasing = np.ascontiguousarray(c.n_releasing)
        # Resident CSR (row order = NodeInfo.tasks iteration order).
        counts = [len(r) for r in st.node_rows]
        node_ptr = np.zeros(c.Nn + 1, np.int64)
        np.cumsum(counts, out=node_ptr[1:])
        flat = np.fromiter(
            (r for rows in st.node_rows for r in rows),
            np.int64, count=int(node_ptr[-1]),
        )
        Q = len(c.queue_names)
        q_rec = np.zeros(Q, np.uint8)
        for qi, qname in enumerate(c.queue_names):
            q = c.store.queues.get(qname)
            q_rec[qi] = bool(q is not None and q.reclaimable())
        q_des = np.zeros((Q, c.R), np.float32)
        q_has = np.zeros(Q, np.uint8)
        for qi, res in c.q_deserved_res.items():
            q_has[qi] = 1
            q_des[qi] = c._slots_vec(res)
        tiers = []
        ids = {"gang": 0, "conformance": 1, "proportion": 2}
        for tier in self._tiers_reclaim:
            for pname in tier:
                if pname in ids:
                    tiers.append(ids[pname])
            tiers.append(-1)
        # Keep references to every array the C context captures: the
        # context holds raw pointers, so anything here being collected
        # or reallocated would leave it dangling.
        nat = {
            "lib": lib,
            "node_ptr": node_ptr,
            "node_rows": flat,
            "p_status": m.p_status,
            "p_job": np.ascontiguousarray(m.p_job, np.int32),
            "req": st.req,
            "req_empty": np.ascontiguousarray(
                st.req_empty.view(np.uint8)),
            "critical": np.ascontiguousarray(st.critical.view(np.uint8)),
            "j_minav": np.ascontiguousarray(m.j_minav, np.int32),
            "q_rec": q_rec,
            "q_des": q_des,
            "q_has": q_has,
            "tiers": np.asarray(tiers, np.int32),
            "eps": np.ascontiguousarray(c.eps, np.float32),
            "scalar_slot": np.ascontiguousarray(
                c.scalar_slot.view(np.uint8)),
            "alive": np.ascontiguousarray(c.n_alive.view(np.uint8)),
            "init_req_base": st.init_req,
            "ones": np.ones(c.Nn, np.uint8),
            "cursor_buf": np.zeros(1, np.int64),
            # Sized so one step can never overflow it: a step evicts a
            # row at most once, and rows < Pn.
            "out_rows": np.zeros(max(c.Pn, 1), np.int64),
            "out_n": np.zeros(1, np.int64),
            # Mutable cycle arrays the ctx points into (pin them too).
            "pins": (c.j_ready_base, c.j_cnt_alloc, c.j_cnt_run,
                     c.j_cnt_releasing, c.j_alloc_res, c.q_of_job,
                     c.q_alloc, st.fi, c.n_releasing),
        }
        # Batch-mode inputs: job-order encoding, (create, uid) rank,
        # and the pipeline-side arrays the C batch mutates.
        Jn = c.Jn
        uids = np.array([m.j_uid[j] for j in range(Jn)])
        order = np.lexsort((uids, m.j_create[:Jn]))
        j_rank = np.empty(Jn, np.int32)
        j_rank[order] = np.arange(Jn, dtype=np.int32)
        order_ids = {"priority": 0, "gang": 1, "drf": 2}
        job_order = np.asarray(
            [order_ids[n] for n in self._job_order_names
             if n in order_ids], np.int32,
        )
        reclaim_gated = self._reclaim_prop_gated()
        nat_extra = {
            "j_rank": j_rank,
            "j_prio": np.ascontiguousarray(m.j_prio, np.int32),
            "p_node": np.ascontiguousarray(m.p_node, np.int32),
            "job_order": job_order,
            "total_res": np.ascontiguousarray(c.total_res, np.float32),
            "out_pipe_rows": np.zeros(max(c.Pn, 1), np.int64),
            "out_pipe_nodes": np.zeros(max(c.Pn, 1), np.int64),
            "out_n_pipe": np.zeros(1, np.int64),
            "out_touched": np.zeros(2 * max(c.Pn, 1), np.int64),
            "out_n_touched": np.zeros(1, np.int64),
            "reclaim_gated": reclaim_gated,
        }
        d = lambda a: a.ctypes.data
        (j_ready_base, j_cnt_alloc, j_cnt_run, j_cnt_releasing,
         j_alloc_res, q_of_job, q_alloc, fi, n_releasing) = nat["pins"]
        if not st.pipe_node.flags["C_CONTIGUOUS"] \
                or st.pipe_node.dtype != np.int64:
            st.pipe_node = np.ascontiguousarray(st.pipe_node, np.int64)
        nat["pins2"] = (st.n_pipelined, c.n_ntasks, c.n_maxtasks,
                        st.pipe_node, c.j_cnt_pending, st.j_waiting,
                        st.j_version, st.q_version)
        nat.update(nat_extra)
        nat["ctx"] = lib.vcreclaim_ctx_new(
            d(node_ptr), d(flat),
            d(nat["p_status"]), d(nat["p_job"]),
            d(nat["req"]), d(nat["req_empty"]), d(nat["critical"]),
            d(nat["j_minav"]), d(j_ready_base),
            d(j_cnt_alloc), d(j_cnt_run), d(j_cnt_releasing),
            d(j_alloc_res), d(q_of_job),
            d(q_rec), d(q_alloc), d(q_des), d(q_has),
            d(fi), d(n_releasing),
            d(nat["tiers"]), len(nat["tiers"]),
            d(nat["eps"]), d(nat["scalar_slot"]),
            d(nat["alive"]), d(nat["init_req_base"]),
            c.Nn, c.R, ST_RUNNING, ST_RELEASING,
            d(st.n_pipelined), d(c.n_ntasks), d(c.n_maxtasks),
            d(st.pipe_node), d(c.j_cnt_pending), d(st.j_waiting),
            d(st.j_version), d(st.q_version),
            int(len(st.q_version)),
            d(nat["j_prio"]), d(nat["j_rank"]), d(nat["p_node"]),
            d(nat["total_res"]), d(nat["job_order"]),
            len(nat["job_order"]), int(reclaim_gated),
        )
        nat["step"] = lib.vcreclaim_step
        nat["cur_addr"] = nat["cursor_buf"].ctypes.data
        nat["out_addr"] = nat["out_rows"].ctypes.data
        nat["out_n_addr"] = nat["out_n"].ctypes.data
        return nat

    def _native_reclaim_drive(self, nat, jobs_map, tasks_map) -> bool:
        """Run the ENTIRE reclaim round-robin in C — any number of
        pending queues (vcreclaim_drive_mq: a lazy QUEUE heap with live
        share/create/uid keys over per-queue lazy job heaps, the
        per-turn proportion veto, overused verdicts frozen at first
        evaluation, cursor node walks, pipeline bookkeeping).  Tasks the
        C side cannot handle exactly (inter-pod terms / host ports /
        ghost pods) yield back here, are run through the exact Python
        turn, and the drive resumes.  Returns False to fall back to the
        Python loop."""
        c = self.cyc
        st = self.st
        m = c.m
        live = [(q, h) for q, h in jobs_map.items() if not h.empty()]
        if not live:
            return True
        has_pred = c._has("predicates")
        pods = c.store.pods
        lib = nat["lib"]
        # Queue-key components (the share component is derived live in
        # C; creation/uid tie-breaks are static per pass).
        has_prop_order = c._has("proportion") and any(
            opt.name == "proportion"
            for opt in c._tier_opts("enabled_queue_order")
        )
        # Deserved-NAMED slots per global queue (cpu/memory always;
        # scalars the deserved dict carries, zero-valued included) —
        # _queue_share iterates exactly these.
        q_named = np.zeros((max(c.Qn, 1), c.R), np.uint8)
        for qi, res in c.q_deserved_res.items():
            q_named[qi, 0] = q_named[qi, 1] = 1
            if res.scalars:
                for name in res.scalars:
                    idx = m.scalar_slots.index.get(name)
                    if idx is not None:
                        q_named[qi, 2 + idx] = 1
        # Per-queue active job lists + overused memo (persists across
        # yield re-entries, mirroring the Python closure's per-pass
        # cache).
        active_by_q: Dict[str, List[int]] = {
            q: [it for (_k, it) in h.h] for q, h in live
        }
        over_memo: Dict[str, int] = {}
        n_yields = 0
        while True:
            qnames = [q for q in active_by_q
                      if active_by_q[q] and c.queue_index.get(q, -1) >= 0]
            if not qnames:
                for _q, h in live:
                    h.h.clear()
                return True
            qids = np.asarray(
                [c.queue_index[q] for q in qnames], np.int64
            )
            q_create = np.asarray(
                [c.store.queues[q].queue.creation_timestamp
                 for q in qnames], np.float64,
            )
            uid_order = sorted(
                range(len(qnames)),
                key=lambda i: c.store.queues[qnames[i]].uid,
            )
            q_rank = np.empty(len(qnames), np.int32)
            for rk, i in enumerate(uid_order):
                q_rank[i] = rk
            q_over = np.asarray(
                [over_memo.get(q, -1) for q in qnames], np.int8
            )
            q_dropped = np.zeros(len(qnames), np.uint8)

            task_ptr = [0]
            flat: List[int] = []
            job_list: List[int] = []
            job_qslot: List[int] = []
            for slot, q in enumerate(qnames):
                for jr in active_by_q[q]:
                    job_list.append(jr)
                    job_qslot.append(slot)
                    flat.extend(tasks_map.get(jr, []))
                    task_ptr.append(len(flat))
            if not flat:
                for _q, h in live:
                    h.h.clear()
                return True
            if n_yields and n_yields * 4 > len(flat):
                # Many yielding (port/inter-pod/ghost) reclaimers: each
                # yield re-registers O(pending) state, so the Python
                # loop's linear walk is cheaper past this ratio.
                # Evictions/pipelines already landed, so the fallback
                # loop must see the drive's CURRENT state: rebuild the
                # job heaps minus dropped/consumed jobs (an emptied heap
                # drops the queue on pop, the round-robin's own drop
                # path) and hand the frozen overused verdicts to the
                # caller — re-evaluating them at post-eviction state
                # would diverge from the object path.
                for q, h in live:
                    h.h.clear()
                    for jr in active_by_q.get(q, ()):
                        h.push(jr)
                self._reclaim_over_seed = dict(over_memo)
                return False
            row_maskidx = np.full(c.Pn, -1, np.int32)
            regs: List[dict] = []
            seen_prof: Dict[tuple, int] = {}
            for slot, q in enumerate(qnames):
                scope = ("rq", q)
                ev = self._evictable_for(scope)
                qid_g = int(qids[slot])
                for jr in active_by_q[q]:
                    for r in tasks_map.get(jr, ()):
                        feat = m.p_feat[r]
                        if feat.ports or feat.ip_req_aff or feat.ip_req_anti:
                            continue
                        if has_pred and pods.get(m.p_uid[r]) is None:
                            continue
                        key = (q, int(m.p_prof[r]),
                               st.init_req[r].tobytes())
                        mi = seen_prof.get(key)
                        if mi is None:
                            init_req = st.init_req[r]
                            self._prefilter(scope, init_req, ev)
                            static = None
                            if has_pred:
                                static = self._profile_static.get(key[1])
                                if static is None:
                                    static = self._static_mask(feat)
                                    self._profile_static[key[1]] = static
                            slots = self._slots_mask
                            if slots is None and has_pred:
                                slots = self._slots_mask = (
                                    (c.n_maxtasks <= 0)
                                    | (c.n_ntasks < c.n_maxtasks)
                                )
                            wkey = (scope, key[2], key[1])
                            mi = len(regs)
                            seen_prof[key] = mi
                            regs.append({
                                "wkey": wkey,
                                "qid": qid_g,
                                "anym": self._ev_any[scope],
                                "feas": self._ev_feas[(scope, key[2])][1],
                                "static": static if static is not None
                                else nat["ones"],
                                "slots": slots if slots is not None
                                else nat["ones"],
                                "init_req": np.ascontiguousarray(
                                    init_req, np.float32),
                            })
                        row_maskidx[r] = mi
            M = len(regs)
            d = lambda a: a.ctypes.data
            anym_p = np.asarray([d(g["anym"]) for g in regs], np.uint64)
            feas_p = np.asarray([d(g["feas"]) for g in regs], np.uint64)
            stat_p = np.asarray([d(g["static"]) for g in regs],
                                np.uint64)
            slot_p = np.asarray([d(g["slots"]) for g in regs], np.uint64)
            ireq_p = np.asarray([d(g["init_req"]) for g in regs],
                                np.uint64)
            mask_cur = np.asarray(
                [self._walk_cursor.get(g["wkey"], 0) for g in regs],
                np.int64,
            )
            mask_qid = np.asarray([g["qid"] for g in regs], np.int64)
            job_arr = np.asarray(job_list, np.int64)
            jq_arr = np.asarray(job_qslot, np.int64)
            ptr_arr = np.asarray(task_ptr, np.int64)
            flat_arr = np.asarray(flat, np.int64)
            task_cur = np.zeros(max(len(job_list), 1), np.int64)
            j_dropped = np.zeros(max(len(job_list), 1), np.uint8)
            yield_job = np.zeros(1, np.int64)
            out_n_ev = nat["out_n"]
            out_n_ev[0] = 0
            nat["out_n_pipe"][0] = 0
            nat["out_n_touched"][0] = 0
            rc = lib.vcreclaim_drive_mq(
                nat["ctx"], 1 if has_pred else 0,
                qids.ctypes.data, len(qnames),
                q_create.ctypes.data, q_rank.ctypes.data,
                q_named.ctypes.data, 1 if has_prop_order else 0,
                q_over.ctypes.data, q_dropped.ctypes.data,
                job_arr.ctypes.data, len(job_list),
                jq_arr.ctypes.data,
                ptr_arr.ctypes.data, flat_arr.ctypes.data,
                task_cur.ctypes.data,
                row_maskidx.ctypes.data,
                M,
                anym_p.ctypes.data, feas_p.ctypes.data,
                stat_p.ctypes.data, slot_p.ctypes.data,
                ireq_p.ctypes.data,
                mask_qid.ctypes.data,
                mask_cur.ctypes.data,
                nat["out_addr"], out_n_ev.ctypes.data,
                len(nat["out_rows"]),
                nat["out_pipe_rows"].ctypes.data,
                nat["out_pipe_nodes"].ctypes.data,
                nat["out_n_pipe"].ctypes.data,
                nat["out_touched"].ctypes.data,
                nat["out_n_touched"].ctypes.data,
                len(nat["out_touched"]),
                yield_job.ctypes.data,
                j_dropped.ctypes.data,
            )
            # ---- replay the store-facing bookkeeping
            n_ev = int(out_n_ev[0])
            if n_ev:
                st.version += n_ev
                for r in nat["out_rows"][:n_ev].tolist():
                    st.evicted_rows.append(r)
                    vjr = int(m.p_job[r])
                    if vjr >= 0:
                        st.j_version[vjr] += 1
                        qi = int(c.q_of_job[vjr])
                        if 0 <= qi < len(st.q_version):
                            st.q_version[qi] += 1
                    self._evictable_update(r, -1)
            n_pipe = int(nat["out_n_pipe"][0])
            if n_pipe:
                st.version += n_pipe
                for row, node in zip(
                        nat["out_pipe_rows"][:n_pipe].tolist(),
                        nat["out_pipe_nodes"][:n_pipe].tolist()):
                    st.pipelined_rows.append(row)
                    st.node_rows[node].append(row)
            n_t = int(nat["out_n_touched"][0])
            if n_t:
                self._dirty.update(
                    int(x) for x in nat["out_touched"][:n_t].tolist())
            for g, cur in zip(regs, mask_cur.tolist()):
                self._walk_cursor[g["wkey"]] = int(cur)
            for i, jr in enumerate(job_list):
                k = int(task_cur[i])
                if k:
                    del tasks_map[jr][:k]
            # Persist overused verdicts + dropped queues across
            # re-entries (the Python closure's per-pass memo / the
            # missing queue re-push).
            for slot, q in enumerate(qnames):
                if q_over[slot] >= 0:
                    over_memo[q] = int(q_over[slot])
                if q_dropped[slot]:
                    active_by_q[q] = []
            if rc == -4:
                # Key buffer bound exceeded (very long job-order config):
                # nothing was mutated — use the Python loop.
                return False
            if rc == 0:
                for _q, h in live:
                    h.h.clear()
                return True
            # rc == -3: one exact Python turn for the yielded job.
            # rc == -5: the turn's veto already ran in C and the walk
            # bailed mid-node; resume walk-only (re-running the veto
            # here could diverge after the turn's partial evictions).
            n_yields += 1
            ji = int(yield_job[0])
            jr_y = job_list[ji]
            q_y = qnames[job_qslot[ji]]
            keep = self._drive_python_turn(jr_y, tasks_map, q_y,
                                           walk_only=(rc == -5))
            dropped_set = {
                jr for jr, dr in zip(job_list, j_dropped[:len(job_list)])
                if dr
            }
            for q in qnames:
                active_by_q[q] = [
                    jr for jr in active_by_q[q]
                    if jr not in dropped_set and jr != jr_y
                ]
            if keep:
                active_by_q[q_y].append(jr_y)

    def _drive_python_turn(self, jr: int, tasks_map, qname: str,
                           walk_only: bool = False) -> bool:
        """One exact reclaim turn for a task the C driver yielded
        (mirror of the _reclaim_loop body for one (job, task)).
        ``walk_only`` resumes a turn whose veto/guards already ran in C
        before its walk bailed."""
        c = self.cyc
        st = self.st
        m = c.m
        tasks = tasks_map.get(jr, [])
        if not tasks:
            return False
        prow = tasks.pop(0)
        if not walk_only:
            if not self._reclaim_possible(qname):
                return False
            if c._has("predicates") \
                    and c.store.pods.get(m.p_uid[prow]) is None:
                return False
        init_req = st.init_req[prow]
        ev = self._evictable_for(("rq", qname))
        comb = self._prefilter(("rq", qname), init_req, ev)
        feasible = comb
        if feasible.any():
            feasible = feasible & self.feasible_mask(prow)
        for n in np.flatnonzero(feasible & c.n_alive):
            if self._reclaim_node(prow, init_req, qname, int(n)):
                return True
        return False

    def _native_reclaim_step(self, nat, prow: int, qid: int,
                             init_req: np.ndarray, wkey, static, slots,
                             comb, qname: str) -> bool:
        """Run one reclaimer through the C engine; apply the Python-side
        bookkeeping the C core does not own (evicted-row caches, event
        versioning, dirty marking, the pipeline)."""
        c = self.cyc
        st = self.st
        m = c.m
        cur = nat["cursor_buf"]
        cur[0] = self._walk_cursor.get(wkey, 0)
        out_n = nat["out_n"]
        out_n[0] = 0
        # Mask addresses are stable per (scope, profile); resolve once.
        addrs = nat.setdefault("addrs", {})
        ap = addrs.get(wkey)
        if ap is None:
            ap = (
                self._ev_any[wkey[0]].ctypes.data,
                self._ev_feas[(wkey[0], wkey[1])][1].ctypes.data,
                (static if static is not None
                 else nat["ones"]).ctypes.data,
                (slots if slots is not None
                 else nat["ones"]).ctypes.data,
            )
            addrs[wkey] = ap
        node = nat["step"](
            nat["ctx"], prow, qid, nat["cur_addr"],
            ap[0], ap[1], ap[2], ap[3],
            nat["out_addr"], nat["out_n_addr"], len(nat["out_rows"]),
        )
        self._walk_cursor[wkey] = int(cur[0])
        n_ev = int(nat["out_n"][0])
        if n_ev:
            rows = nat["out_rows"][:n_ev]
            st.version += n_ev
            for r in rows.tolist():
                st.evicted_rows.append(r)
                jr = int(m.p_job[r])
                if jr >= 0:
                    st.j_version[jr] += 1
                    qi = int(c.q_of_job[jr])
                    if 0 <= qi < len(st.q_version):
                        st.q_version[qi] += 1
                self._evictable_update(r, -1)
                self._dirty.add(int(m.p_node[r]))
        if node == -2:
            # C scratch overflow (should be prevented by setup): finish
            # this reclaimer on the exact Python walk.
            return self._python_reclaim_walk(prow, init_req, qname,
                                             wkey, comb, static, slots)
        if node >= 0:
            st.pipeline(prow, int(node), None)
            return True
        return False
