"""Device mesh + sharded allocate solve.

The framework's scale axis is the NODES dimension of the cluster arrays
(the reference scales with goroutine fan-out + adaptive node sampling,
scheduler_helper.go:43-118; we scale by sharding nodes over chips).  The
solver is pure SPMD-friendly: per-step work is elementwise over [N, R] with
one argmax reduction, so annotating the N-axis sharding lets GSPMD partition
the fori_loop body and insert the cross-chip reductions (the argmax becomes
a pmax tree over ICI).

Task/job/queue state stays replicated — it is tiny (O(P + J + Q) scalars)
next to the [N, R] node state, and every chip needs the winner of each step
anyway.

``dryrun_multichip`` in __graft_entry__.py drives this on a virtual CPU mesh;
the same code runs unchanged on a real multi-chip TPU slice.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger(__name__)

NODES_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, axis: str = NODES_AXIS,
              platform: Optional[str] = None) -> Mesh:
    """Build a 1-D device mesh over the nodes axis.

    ``platform`` pins the backend explicitly ("cpu", "tpu"); default is
    jax's default backend.  Callers that need the virtual CPU mesh (the
    multi-chip dryrun, the test suite) must force the platform first —
    ``volcano_tpu.virtualcpu.force_virtual_cpu_platform`` — and pass
    ``platform="cpu"``.
    """
    devices = jax.devices(platform) if platform is not None else jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise RuntimeError(
                f"mesh needs {n_devices} devices, backend has {len(devices)}"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def mesh_from_env(store) -> Optional[Mesh]:
    """The store's solve mesh, or one built from ``VOLCANO_TPU_MESH=<n>``
    (the deploy-time enable knob: ``store.solve_mesh`` set explicitly
    always wins; unset/0/1 keeps the single-device path).  A backend
    with fewer than n devices logs once and stays single-device instead
    of failing the cycle — the knob must be safe to bake into a config
    that also runs on one chip."""
    mesh = getattr(store, "solve_mesh", None)
    if mesh is not None:
        return mesh
    if getattr(store, "_mesh_env_checked", False):
        return None
    store._mesh_env_checked = True
    raw = os.environ.get("VOLCANO_TPU_MESH", "")
    try:
        n = int(raw)
    except ValueError:
        if raw:
            log.warning("VOLCANO_TPU_MESH=%r is not an integer; "
                        "staying single-device", raw)
        return None
    if n < 2:
        return None
    try:
        mesh = make_mesh(n)
    except RuntimeError as e:
        log.warning("VOLCANO_TPU_MESH=%s but %s; staying single-device",
                    raw, e)
        return None
    store.solve_mesh = mesh
    return mesh


def shard_solve_args(mesh: Mesh, solve_args: Sequence, axis: str = NODES_AXIS):
    """Place solve() args on the mesh: every field of the SolveNodes group
    (and AffinityArgs.node_dom) is sharded on its leading N axis; task/job/
    queue state, weights, and the affinity count tensors are replicated
    (they are O(P + J + Q + E*D) scalars next to the [N, R] node state, and
    every chip needs the winner of each step anyway).

    solve()'s signature (ops/allocate.py): (nodes, tasks, jobs, queues,
    weights, eps, scalar_slot, aff).
    """
    node_sharded = NamedSharding(mesh, P(axis))  # leading dim = N
    replicated = NamedSharding(mesh, P())

    def rep(tree):
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(np.asarray(x), replicated), tree
        )

    node_bias = solve_args[8] if len(solve_args) > 8 else None
    nodes, tasks, jobs, queues, weights, eps, scalar_slot, aff = \
        solve_args[:8]
    nodes = type(nodes)(*[
        jax.device_put(np.asarray(x), node_sharded) for x in nodes
    ])
    aff = type(aff)(
        node_dom=jax.device_put(np.asarray(aff.node_dom), node_sharded),
        term_key=jax.device_put(np.asarray(aff.term_key), replicated),
        cnt0=jax.device_put(np.asarray(aff.cnt0), replicated),
        t_req_aff=jax.device_put(np.asarray(aff.t_req_aff), replicated),
        t_req_anti=jax.device_put(np.asarray(aff.t_req_anti), replicated),
        t_matches=jax.device_put(np.asarray(aff.t_matches), replicated),
        t_soft=jax.device_put(np.asarray(aff.t_soft), replicated),
    )
    out = (
        nodes, rep(tasks), rep(jobs), rep(queues), rep(weights),
        jax.device_put(np.asarray(eps), replicated),
        jax.device_put(np.asarray(scalar_slot), replicated),
        aff,
    )
    if node_bias is not None:
        out = out + (
            jax.device_put(np.asarray(node_bias), node_sharded),
        )
    return out


def sharded_solve(mesh: Mesh, solve_args: Sequence, axis: str = NODES_AXIS):
    """Run the sequential allocate solver with node state sharded over
    the mesh."""
    from ..ops.allocate import solve

    # Input shardings drive GSPMD partitioning; no explicit mesh context is
    # needed for jit with device_put-committed arguments.
    args = shard_solve_args(mesh, solve_args, axis)
    return solve(*args)


def sharded_solve_wave(mesh: Mesh, solve_args: Sequence,
                       axis: str = NODES_AXIS, wave: Optional[int] = None):
    """Run the production wave solver with node state sharded over the
    mesh: the per-attempt [UM, N] feasibility/score tensors partition on
    N, the top-k ranking becomes a cross-chip top-k over ICI, and the
    [W, W] prefix-acceptance matmuls stay replicated (W is mesh-size
    independent)."""
    from ..ops.wave import solve_wave

    args = shard_solve_args(mesh, solve_args, axis)
    kw = {} if wave is None else {"wave": wave}
    return solve_wave(*args, mesh_shards=int(mesh.devices.size), **kw)


# SolveNodes fields that move only with the NODE table (the mirror's
# epoch key), not per cycle.  On the fast path these now arrive as
# committed mesh-sharded arrays from the sharded devsnap
# (ops/devsnap.py — per-shard resident planes with shard-local delta
# scatters) and pass straight through; the plane cache below remains
# the fallback for direct callers and VOLCANO_TPU_DEVSNAP=0, where it
# still skips the per-cycle device_put on an epoch hit.
_EPOCH_STABLE_NODE_FIELDS = frozenset(
    {"allocatable", "max_tasks", "ready", "label_bits", "taint_bits"}
)


def shard_wave_inputs(mesh: Mesh, solve_args: Sequence, pid, profiles,
                      axis: str = NODES_AXIS,
                      plane_cache: Optional[dict] = None,
                      epoch: Optional[int] = None,
                      node_classes=None):
    """Mesh placement for the fast path's pre-profiled wave inputs.

    Beyond the node-axis sharding of ``shard_solve_args``, the affinity
    COUNT tENSORS shard too — they are the hyperscale memory wall
    (an [E, D] int32 pair with D ~ N reaches GBs at 50k nodes; round-4
    root cause of the 16 GB-chip OOM), so replicating them would cap the
    cluster size one chip can hold regardless of mesh width:

    - ``aff.cnt0`` [E, D] shards on the DOMAIN axis (hostname domains
      are per-node, so D scales with N; XLA pads uneven shards),
    - the profile term tables (``t_req_aff``/``t_req_anti``/
      ``t_matches``/``t_soft`` [U, E]) shard on the TERM axis,
    - ``pid`` and the remaining profile rows are replicated (profile
      counts are tiny next to [*, N] and [E, D] state).

    The kernel's count-window contraction (cnt @ dom_ohT over D) then
    runs as partial products with an XLA-inserted reduce over ICI.

    ``plane_cache`` (with ``epoch``) keeps the epoch-stable node planes
    and ``aff.node_dom`` resident on the mesh across cycles: a hit skips
    their host->device transfer entirely (pass the same dict every
    cycle; the fast path parks one on the store).
    """
    node_sharded = NamedSharding(mesh, P(axis))
    replicated = NamedSharding(mesh, P())
    col_sharded = NamedSharding(mesh, P(None, axis))

    # The slim fast path appends a 9th element (the [N] f32 topology
    # node-order bias, ops/topology.contig_bias) only when a fabric
    # constraint is live; it shards with the node axis like any other
    # node plane.  The 8-tuple form stays byte-identical to before.
    node_bias = solve_args[8] if len(solve_args) > 8 else None
    nodes, tasks, jobs, queues, weights, eps, scalar_slot, aff = \
        solve_args[:8]
    idle_in = nodes.idle
    n_nodes = int(idle_in.shape[0] if hasattr(idle_in, "shape")
                  else np.asarray(idle_in).shape[0])

    def put_node(x):
        # Mesh-resident planes (the sharded devsnap, ops/devsnap.py)
        # arrive committed with the node-axis sharding already: hand
        # them straight through — np.asarray here would be a full
        # device->host->device round trip of every plane every cycle,
        # exactly the re-shipping this path exists to remove.
        if isinstance(x, jax.Array) and not isinstance(x, np.ndarray):
            return x
        # The slim fast path ships [1, R] broadcast dummies for
        # releasing/pipelined; those replicate (a 1-row axis cannot
        # shard over the mesh).
        a = np.asarray(x)
        sh = node_sharded if (a.ndim and a.shape[0] == n_nodes
                              and a.shape[0] % mesh.devices.size == 0) \
            else replicated
        return jax.device_put(a, sh)

    def put_node_cached(name, x):
        # Committed mesh arrays (sharded devsnap) ARE the persistent
        # per-device planes — no cache entry needed.
        if isinstance(x, jax.Array) and not isinstance(x, np.ndarray):
            return x
        # Persistent per-device plane: re-ship only when the node table
        # (epoch) or the padded shape moved.  The mesh IDENTITY is part
        # of the key (not just its size): a store whose solve_mesh is
        # replaced by a different same-sized mesh must not hand the jit
        # arrays committed to the old mesh's sharding — the composed
        # profile swaps meshes within one process.
        if plane_cache is None or epoch is None:
            return put_node(x)
        a = np.asarray(x)
        key = (epoch, a.shape, a.dtype.str, mesh.devices.size, id(mesh))
        hit = plane_cache.get(name)
        if hit is not None and hit[0] == key:
            return hit[1]
        arr = put_node(a)
        plane_cache[name] = (key, arr)
        return arr

    n_mesh = mesh.devices.size

    def put_cols(x):
        # Shard axis 1, zero-padding it up to a mesh multiple (padded
        # domain/term columns are inert: domain ids and term windows
        # only ever index the original range).  Tables too small to
        # split stay replicated.
        a = np.asarray(x)
        if a.ndim < 2 or a.shape[1] < n_mesh:
            return jax.device_put(a, replicated)
        pad = (-a.shape[1]) % n_mesh
        if pad:
            a = np.concatenate(
                [a, np.zeros((a.shape[0], pad, *a.shape[2:]), a.dtype)],
                axis=1,
            )
        return jax.device_put(a, col_sharded)

    nodes = type(nodes)(*[
        put_node_cached(name, x)
        if name in _EPOCH_STABLE_NODE_FIELDS else put_node(x)
        for name, x in zip(type(nodes)._fields, nodes)
    ])
    aff = type(aff)(
        node_dom=put_node_cached("node_dom", aff.node_dom),
        term_key=jax.device_put(np.asarray(aff.term_key), replicated),
        cnt0=put_cols(aff.cnt0),
        t_req_aff=jax.device_put(np.asarray(aff.t_req_aff), replicated),
        t_req_anti=jax.device_put(np.asarray(aff.t_req_anti), replicated),
        t_matches=jax.device_put(np.asarray(aff.t_matches), replicated),
        t_soft=jax.device_put(np.asarray(aff.t_soft), replicated),
    )
    rep = lambda tree: jax.tree_util.tree_map(
        lambda x: jax.device_put(np.asarray(x), replicated), tree
    )
    profiles = type(profiles)(
        req=jax.device_put(np.asarray(profiles.req), replicated),
        init_req=jax.device_put(np.asarray(profiles.init_req), replicated),
        ports=jax.device_put(np.asarray(profiles.ports), replicated),
        sel_bits=jax.device_put(np.asarray(profiles.sel_bits), replicated),
        aff_bits=jax.device_put(np.asarray(profiles.aff_bits), replicated),
        aff_terms=jax.device_put(np.asarray(profiles.aff_terms),
                                 replicated),
        tol_bits=jax.device_put(np.asarray(profiles.tol_bits), replicated),
        pref_bits=jax.device_put(np.asarray(profiles.pref_bits),
                                 replicated),
        pref_w=jax.device_put(np.asarray(profiles.pref_w), replicated),
        t_req_aff=put_cols(profiles.t_req_aff),
        t_req_anti=put_cols(profiles.t_req_anti),
        t_matches=put_cols(profiles.t_matches),
        t_soft=put_cols(profiles.t_soft),
    )
    args = (
        nodes, rep(tasks), rep(jobs), rep(queues), rep(weights),
        jax.device_put(np.asarray(eps), replicated),
        jax.device_put(np.asarray(scalar_slot), replicated),
        aff,
    )
    if node_bias is not None:
        args = args + (put_node(node_bias),)
    pid = jax.device_put(np.asarray(pid), replicated)
    if node_classes is not None:
        # Two-phase planes: the [N] class_id shards with the node axis
        # (it IS a node column); the [C, *] class tables and the [U, S]
        # shortlists the solver derives from them stay replicated —
        # they are the COMPACTED representations (C, S << N), which is
        # exactly why the mesh no longer has to move full [UM, N]
        # planes between chips per attempt.  class_id is epoch-stable,
        # so it rides the persistent plane cache.
        node_classes = type(node_classes)(
            class_id=put_node_cached("class_id", node_classes.class_id),
            label_bits=put_node_cached("cls_label_bits",
                                       node_classes.label_bits),
            taint_bits=put_node_cached("cls_taint_bits",
                                       node_classes.taint_bits),
            ready=put_node_cached("cls_ready", node_classes.ready),
        )
    return args, pid, profiles, node_classes


def sharded_solve_wave_cycle(mesh: Mesh, solve_args: Sequence, pid,
                             profiles, axis: str = NODES_AXIS,
                             wave: Optional[int] = None,
                             plane_cache: Optional[dict] = None,
                             epoch: Optional[int] = None,
                             taint_any=None,
                             node_classes=None,
                             devincr=None):
    """The fast path's solve dispatch on a mesh (FastCycle._allocate when
    ``store.solve_mesh`` is set): pre-profiled inputs, node axis + count
    tensors sharded per ``shard_wave_inputs``; epoch-stable planes
    (including the two-phase class planes) stay mesh-resident across
    cycles via ``plane_cache``.  ``devincr`` (ISSUE 9) threads the
    store's device-incremental context through — its persistent static
    planes and warm-shortlist candidates live replicated on this mesh
    (``DeviceIncremental.set_mesh``, called by the fast path before the
    dispatch), so a mesh change voids them via the placement token."""
    from ..ops.wave import solve_wave

    args, pid, profiles, node_classes = shard_wave_inputs(
        mesh, solve_args, pid, profiles, axis,
        plane_cache=plane_cache, epoch=epoch, node_classes=node_classes,
    )
    kw = {} if wave is None else {"wave": wave}
    return solve_wave(*args, pid=pid, profiles=profiles,
                      taint_any=taint_any, node_classes=node_classes,
                      mesh_shards=int(mesh.devices.size),
                      devincr=devincr, **kw)
