"""Device mesh + sharded allocate solve.

The framework's scale axis is the NODES dimension of the cluster arrays
(the reference scales with goroutine fan-out + adaptive node sampling,
scheduler_helper.go:43-118; we scale by sharding nodes over chips).  The
solver is pure SPMD-friendly: per-step work is elementwise over [N, R] with
one argmax reduction, so annotating the N-axis sharding lets GSPMD partition
the fori_loop body and insert the cross-chip reductions (the argmax becomes
a pmax tree over ICI).

Task/job/queue state stays replicated — it is tiny (O(P + J + Q) scalars)
next to the [N, R] node state, and every chip needs the winner of each step
anyway.

``dryrun_multichip`` in __graft_entry__.py drives this on a virtual CPU mesh;
the same code runs unchanged on a real multi-chip TPU slice.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODES_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, axis: str = NODES_AXIS) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def shard_solve_args(mesh: Mesh, solve_args: Sequence, axis: str = NODES_AXIS):
    """Place solve() positional args on the mesh: node-major arrays sharded
    on the nodes axis, everything else replicated.

    solve()'s signature (ops/allocate.py): the first 7 args are node state
    ([N, R] / [N] / [N, PW]), then task/job/queue arrays (replicated), the
    [P, N] static mask and static score (sharded on their N axis), weights,
    eps, scalar_slot.
    """
    node_sharded = NamedSharding(mesh, P(axis))  # leading dim = N
    replicated = NamedSharding(mesh, P())
    mask_sharded = NamedSharding(mesh, P(None, axis))  # [P, N]

    out = []
    n_node_args = 7
    for i, arg in enumerate(solve_args):
        if i < n_node_args:
            out.append(jax.device_put(arg, node_sharded))
        elif i in (17, 18):  # static_mask, static_score [P, N]
            out.append(jax.device_put(arg, mask_sharded))
        elif i == 19:  # ScoreWeights NamedTuple
            out.append(
                type(arg)(*[
                    jax.device_put(np.asarray(x, np.float32), replicated)
                    for x in arg
                ])
            )
        elif i == 22:  # AffinityArgs: node_dom is [N, K], rest replicated
            out.append(
                type(arg)(
                    node_dom=jax.device_put(arg.node_dom, node_sharded),
                    term_key=jax.device_put(arg.term_key, replicated),
                    cnt0=jax.device_put(arg.cnt0, replicated),
                    t_req_aff=jax.device_put(arg.t_req_aff, replicated),
                    t_req_anti=jax.device_put(arg.t_req_anti, replicated),
                    t_matches=jax.device_put(arg.t_matches, replicated),
                    t_soft=jax.device_put(arg.t_soft, replicated),
                )
            )
        else:
            out.append(jax.device_put(arg, replicated))
    return out


def sharded_solve(mesh: Mesh, solve_args: Sequence, axis: str = NODES_AXIS):
    """Run the allocate solver with node state sharded over the mesh."""
    from ..ops.allocate import solve

    # Input shardings drive GSPMD partitioning; no explicit mesh context is
    # needed for jit with device_put-committed arguments.
    args = shard_solve_args(mesh, solve_args, axis)
    return solve(*args)
