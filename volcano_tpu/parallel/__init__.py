"""Multi-chip scaling: device mesh + sharded solve."""

from .mesh import (make_mesh, shard_solve_args, sharded_solve,
                   sharded_solve_wave)

__all__ = ["make_mesh", "shard_solve_args", "sharded_solve",
           "sharded_solve_wave"]
