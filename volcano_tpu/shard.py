"""Sharded scheduler control plane (ISSUE 16).

Every lane below the cycle thread scales out (mesh-sharded solve, delta
wire frames, solver replica pool), leaving the cycle thread itself as
the last single-threaded bottleneck: one scheduler owns every queue, so
bind throughput is capped at one box no matter how fast the device lane
gets.  This module runs N ``FastCycle`` shards over ONE logical
``ClusterStore``:

- **Ownership** is queue-partitioned: a stable hash of the queue name
  maps each queue to a home shard (``ShardOwnershipTable``), so the
  partition survives restarts and queue churn without coordination.
  Each shard's cycle sees the SHARED node planes but only its owned
  queues' jobs — the existing ``session_jobs`` seam is the single
  filter point (``ShardContext.filter_session_jobs``); every downstream
  consumer (``_pending_rows``, enqueue, backfill, close) derives from
  it.
- **Commits are optimistic.**  Shards never lock queues against each
  other; each dispatches its pipelined solve against a point-in-time
  snapshot and commits at the top of its next cycle.  Commits serialize
  under ``store._lock`` and every commit bumps ``mirror.mutation_seq``,
  so of two racing shards the SECOND to commit always re-validates
  (fastpath's staleness guard) against node planes that already include
  the first shard's binds: the loser's conflicting rows are voided
  row-wise — never a double-bind — and re-place next cycle — never a
  lost pod.  The new ``mirror.shard_commit_seq`` + the table's handoff
  epoch (captured on ``InflightSolve.shard_seq`` at dispatch) tell the
  guard the race was CROSS-SHARD, so those voids are attributed as the
  ``cross-shard-conflict`` drop reason and counted in
  ``volcano_shard_conflicts_total{outcome}``.  The conservation auditor
  referees at runtime: pod flows stay balanced across shards or it
  raises an anomaly.
- **Work stealing** (phase b): an idle shard — zero pending rows across
  its owned queues — claims the most-starved foreign queue via an
  epoch-bumped handoff token (``ShardOwnershipTable.steal_queue``), but
  only when the donor retains at least one other pending queue, which
  makes the handoff ping-pong-stable.  A steal race (donor's in-flight
  solve covering the stolen queue) is resolved by the same optimistic
  machinery: whichever commit lands second re-validates and drops the
  conflicting rows.

``VOLCANO_TPU_SHARDS=1`` (the default) bypasses all of this —
``make_scheduler`` returns the plain single ``Scheduler`` and no shard
state is ever attached to the store, keeping the pre-sharding path
bind-for-bind and wire-byte identical.
"""

from __future__ import annotations

import logging
import os
import zlib
from typing import Dict, List, Optional

import numpy as np

from .api import TaskStatus
from .metrics import metrics
from .scheduler import Scheduler

log = logging.getLogger(__name__)

ST_PENDING = int(TaskStatus.Pending)


def shards_from_env() -> int:
    """The ``VOLCANO_TPU_SHARDS`` knob (docs/tuning.md): number of cycle
    threads over the one logical cluster.  1 (default) = the unsharded
    single-scheduler path."""
    raw = os.environ.get("VOLCANO_TPU_SHARDS", "1")
    try:
        return max(int(raw), 1)
    except ValueError:
        log.warning("VOLCANO_TPU_SHARDS=%r is not an integer; using 1", raw)
        return 1


def stable_shard(name: str, n_shards: int) -> int:
    """Stable queue-name -> home-shard hash (crc32: deterministic across
    processes and restarts, unlike ``hash()`` under PYTHONHASHSEED)."""
    return zlib.crc32(name.encode("utf-8")) % max(n_shards, 1)


class ShardOwnershipTable:
    """Queue -> shard ownership: a stable base hash plus a (small) steal
    override map.  Attached to the store (``store.shard_table``); the
    mutable state is guarded by the OWNING STORE's ``_lock`` — cycles
    read it under the cycle lock, and steals mutate it under the same
    lock, so a cycle can never observe a half-applied handoff."""

    def __init__(self, n_shards: int):
        self.n_shards = max(int(n_shards), 1)
        # Handoff token: bumped by every steal.  Captured (together with
        # mirror.shard_commit_seq) on InflightSolve.shard_seq at
        # dispatch; an advance at fetch time forces the full
        # re-validation even when nothing else moved, so a donor's
        # in-flight solve covering a just-stolen queue can never commit
        # unchecked.
        self.epoch = 0  # guarded-by: _lock
        # Steal overrides: queue name -> owning shard, for queues living
        # away from their base hash.  Empty in steady state.
        self._overrides: Dict[str, int] = {}  # guarded-by: _lock
        # Immutable snapshot for lock-free /debug/shards reads (replaced
        # wholesale on every steal; readers see old or new, never torn).
        self._debug = {"epoch": 0, "overrides": {}}
        # Runtime lockdep (obs/lockdep.py): arm this table when the
        # probe is active — the table outlives any one store walk.
        from .obs.lockdep import attach

        attach(self)

    # holds: _lock
    def owner_of(self, name: str) -> int:
        got = self._overrides.get(name)
        if got is not None:
            return got
        return stable_shard(name, self.n_shards)

    # holds: _lock
    def owners_of(self, names: List[str]) -> np.ndarray:
        """Vector of owning shard per queue name ([Q] int32)."""
        if not names:
            return np.zeros(0, np.int32)
        return np.fromiter(
            (self.owner_of(n) for n in names), np.int32, count=len(names)
        )

    # holds: _lock
    def steal_queue(self, name: str, to_shard: int) -> int:
        """Hand ``name`` to ``to_shard``; returns the new handoff epoch.
        Moving a queue back to its base owner clears the override so the
        table converges to empty under balanced load."""
        if stable_shard(name, self.n_shards) == to_shard:
            self._overrides.pop(name, None)
        else:
            self._overrides[name] = int(to_shard)
        self.epoch += 1
        self._debug = {
            "epoch": self.epoch, "overrides": dict(self._overrides),
        }
        return self.epoch

    def snapshot(self) -> dict:
        """Lock-free debug view (the immutable ``_debug`` replacement
        makes this safe from HTTP handler threads — /debug endpoints
        must never take the store lock)."""
        return self._debug


class ShardContext:
    """One shard's identity + per-shard cycle state, passed into
    ``Scheduler``/``FastCycle``.  Counters are plain ints written only
    by the owning cycle thread (under the store lock) and read
    lock-free by /debug/shards — single-writer, so torn reads are
    impossible."""

    def __init__(self, index: int, table: ShardOwnershipTable):
        self.index = int(index)
        self.table = table
        # Optional per-shard solver client (RemoteSolver/SolverPool):
        # overrides store.remote_solver so each shard can own its own
        # device lane.  Same ownership contract as the store slot —
        # dispatch/fetch only on this shard's cycle thread.
        self.remote_solver = None
        # Single-writer telemetry (the shard's own cycle thread).
        self.cycles = 0
        self.conflicts = 0
        self.steals = 0
        self.owned_pending = 0

    @property
    def count(self) -> int:
        return self.table.n_shards

    @property
    def runs_evictions(self) -> bool:
        """Evict actions (preempt/reclaim/rebalance) reason over the
        WHOLE cluster's victims, so exactly one shard may run them or
        two shards would plan overlapping evictions; shard 0 is the
        designated evictor."""
        return self.index == 0

    # ------------------------------------------------------ cycle filter

    # holds: _lock
    def filter_session_jobs(self, cycle, session_jobs: np.ndarray) -> np.ndarray:
        """Restrict a FastCycle's session job set to this shard's owned
        queues — the single seam the per-shard mirror view hangs off:
        ``_schedulable_rows``/``_pending_rows``/enqueue/backfill/close
        all derive from ``session_jobs``.  Jobs with an unknown queue
        (``q_of_job`` < 0) stay on shard 0 so their error-log semantics
        fire exactly once."""
        if self.table.n_shards <= 1 or len(session_jobs) == 0:
            return session_jobs
        owned_q = self.table.owners_of(cycle.queue_names) == self.index
        q = cycle.q_of_job[session_jobs]
        keep = np.zeros(len(session_jobs), bool)
        has_q = q >= 0
        keep[has_q] = owned_q[q[has_q]]
        if self.index == 0:
            keep[~has_q] = True
        return session_jobs[keep]

    # ---------------------------------------------------- work stealing

    def maybe_steal(self, store) -> bool:
        """Work stealing (tentpole phase b): when this shard has no
        pending work across its owned queues, claim the most-starved
        foreign queue so a hot queue cannot strand an idle cycle
        thread's capacity.  Runs on this shard's cycle thread just
        before its cycle.  Returns True when a queue was claimed."""
        if self.table.n_shards <= 1:
            return False
        with store._lock:
            return self._steal_starved(store)

    # holds: _lock
    def _steal_starved(self, store) -> bool:
        m = store.mirror
        Pn = m.n_pods
        if not Pn:
            return False
        jr = m.p_job[:Pn]
        pend = (
            m.p_alive[:Pn] & (m.p_status[:Pn] == ST_PENDING) & (jr >= 0)
        )
        if not pend.any():
            return False
        jrows = jr[pend]
        jrows = jrows[m.j_alive[jrows]]
        if not len(jrows):
            return False
        qcodes = m.j_queue_code[jrows]
        qcodes = qcodes[qcodes >= 0]
        if not len(qcodes):
            return False
        counts = np.bincount(qcodes, minlength=len(m.qnames.items))
        pending_codes = np.flatnonzero(counts)
        names = m.qnames.items
        owners = {
            int(c): self.table.owner_of(names[int(c)])
            for c in pending_codes
        }
        own_backlog = sum(
            int(counts[c]) for c, o in owners.items() if o == self.index
        )
        self.owned_pending = own_backlog
        if own_backlog:
            return False  # not idle: nothing to steal for
        # Pending-queue count per donor: a donor must RETAIN at least
        # one other pending queue or the steal just relocates the
        # starvation (and two idle shards would ping-pong the last
        # queue between them forever).
        donor_load: Dict[int, int] = {}
        for _c, o in owners.items():
            donor_load[o] = donor_load.get(o, 0) + 1
        order = sorted(
            (int(c) for c in pending_codes),
            key=lambda c: -int(counts[c]),
        )
        for c in order:
            donor = owners[c]
            if donor == self.index or donor_load.get(donor, 0) < 2:
                continue
            qname = names[c]
            epoch = self.table.steal_queue(qname, self.index)
            self.steals += 1
            metrics.shard_steals.inc(1)
            log.info(
                "shard %d stole starved queue %r from shard %d "
                "(backlog %d rows, handoff epoch %d)",
                self.index, qname, donor, int(counts[c]), epoch,
            )
            return True
        return False

    def debug_snapshot(self) -> dict:
        return {
            "index": self.index,
            "cycles": self.cycles,
            "conflicts": self.conflicts,
            "steals": self.steals,
            "owned_pending": self.owned_pending,
        }


class ShardedScheduler:
    """N per-shard ``Scheduler`` loops over one store: the drop-in
    front-end ``service.make_scheduler`` returns when
    ``VOLCANO_TPU_SHARDS`` > 1.  Mirrors the single ``Scheduler``'s
    lifecycle surface (run / run_once / stop / healthy) so Service and
    bench drive either interchangeably."""

    def __init__(self, store, conf_path: Optional[str] = None,
                 conf_str: Optional[str] = None,
                 schedule_period: float = 1.0, gate=None,
                 shards: int = 2):
        n = max(int(shards), 1)
        self.store = store
        with store._lock:
            table = getattr(store, "shard_table", None)
            if table is None or table.n_shards != n:
                table = ShardOwnershipTable(n)
                store.shard_table = table
        self.table = table
        self.shards = [ShardContext(i, table) for i in range(n)]
        self.schedulers = [
            Scheduler(
                store, conf_path=conf_path, conf_str=conf_str,
                schedule_period=schedule_period, gate=gate, shard=ctx,
            )
            for ctx in self.shards
        ]

    @property
    def n_shards(self) -> int:
        return self.table.n_shards

    def run(self) -> None:
        """Start every shard's periodic cycle thread."""
        for s in self.schedulers:
            s.run()

    def run_once(self) -> None:
        """One synchronous cycle per shard, in shard order (tests and
        bench drive this for determinism; the optimistic commit
        protocol engages all the same, because each shard's pipelined
        dispatch from call K commits during call K+1, AFTER its
        siblings' intervening commits)."""
        for s in self.schedulers:
            s.run_once()

    def stop(self, timeout: Optional[float] = None) -> None:
        for s in self.schedulers:
            s.stop(timeout)

    def healthy(self) -> bool:
        return all(s.healthy() for s in self.schedulers)

    def debug_snapshot(self) -> dict:
        """Lock-free state for /debug/shards."""
        return {
            "shards": self.n_shards,
            "table": self.table.snapshot(),
            "per_shard": [ctx.debug_snapshot() for ctx in self.shards],
        }


def make_scheduler(store, conf_path: Optional[str] = None,
                   conf_str: Optional[str] = None,
                   schedule_period: float = 1.0, gate=None,
                   shards: Optional[int] = None):
    """Scheduler factory honouring ``VOLCANO_TPU_SHARDS``.  The default
    (1) constructs the plain single ``Scheduler`` — not a 1-shard
    ShardedScheduler — so the kill switch is the pre-sharding code
    path itself, bitwise identical."""
    n = shards_from_env() if shards is None else max(int(shards), 1)
    if n <= 1:
        return Scheduler(
            store, conf_path=conf_path, conf_str=conf_str,
            schedule_period=schedule_period, gate=gate,
        )
    return ShardedScheduler(
        store, conf_path=conf_path, conf_str=conf_str,
        schedule_period=schedule_period, gate=gate, shards=n,
    )
