"""Active/passive leader election.

The reference runs scheduler and controller-manager as active/passive
replicas coordinated by a resource-lock lease in the API server (15 s
lease, 10 s renew deadline, 5 s retry — ``cmd/scheduler/app/server.go``
leaderelection block).  Without a Kubernetes API server, the rebuild's
shared lock is a lease file on storage all replicas can reach (the same
role the ConfigMap lock plays): the holder refreshes a (holder-id,
expiry) record; a standby acquires when the record expires.

Atomicity relies on ``os.rename`` within one filesystem plus re-reading
the record after writing — the same optimistic concurrency the reference
gets from resourceVersion-checked updates.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Callable, Optional

LEASE_DURATION = 15.0  # seconds (leaseDuration in the reference)
RENEW_DEADLINE = 10.0  # renewDeadline
RETRY_PERIOD = 5.0  # retryPeriod


class LeaderElector:
    """File-lease active/passive election.

    ``run(on_started_leading, on_stopped_leading)`` blocks, retrying
    acquisition every ``retry_period`` until elected, then renews every
    ``renew_deadline/2``; losing the lease invokes ``on_stopped_leading``
    and re-enters the acquire loop (the reference exits the process;
    embedders may do the same from the callback).
    """

    def __init__(
        self,
        lease_path: str,
        identity: Optional[str] = None,
        lease_duration: float = LEASE_DURATION,
        renew_deadline: float = RENEW_DEADLINE,
        retry_period: float = RETRY_PERIOD,
    ):
        self.lease_path = lease_path
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self._stop = threading.Event()
        self.is_leader = False

    # ------------------------------------------------------------- lease io

    def _read(self) -> Optional[dict]:
        try:
            with open(self.lease_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write(self, record: dict) -> bool:
        tmp = f"{self.lease_path}.{self.identity}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(record, f)
            os.replace(tmp, self.lease_path)
        except OSError:
            return False
        # Optimistic concurrency: verify our write won.
        check = self._read()
        return bool(check and check.get("holder") == self.identity
                    and check.get("acquired") == record["acquired"])

    # ------------------------------------------------------------ election

    def try_acquire(self) -> bool:
        now = time.time()
        rec = self._read()
        if rec and rec.get("holder") != self.identity:
            if now < float(rec.get("expiry", 0)):
                return False  # held by a live leader
        record = {
            "holder": self.identity,
            "acquired": now,
            "expiry": now + self.lease_duration,
        }
        if not self._write(record):
            return False
        # Double-check after a short settle: two standbys racing the same
        # expiry can both see their own write momentarily; the later
        # writer wins, so re-read once more before claiming leadership.
        time.sleep(min(0.05, self.retry_period / 10))
        check = self._read()
        return bool(check and check.get("holder") == self.identity)

    def renew(self) -> bool:
        rec = self._read()
        if not rec or rec.get("holder") != self.identity:
            return False
        now = time.time()
        record = {
            "holder": self.identity,
            "acquired": rec["acquired"],
            "expiry": now + self.lease_duration,
        }
        return self._write(record)

    def release(self) -> None:
        rec = self._read()
        if rec and rec.get("holder") == self.identity:
            try:
                os.unlink(self.lease_path)
            except OSError:
                pass
        self.is_leader = False

    def run(
        self,
        on_started_leading: Callable[[], None],
        on_stopped_leading: Callable[[], None],
        once: bool = False,
    ) -> None:
        """Acquire -> lead -> (lose) -> reacquire loop.  ``once`` returns
        after the first leadership loss (reference semantics: the process
        exits on lost leadership, server.go OnStoppedLeading)."""
        while not self._stop.is_set():
            while not self._stop.is_set() and not self.try_acquire():
                self._stop.wait(self.retry_period)
            if self._stop.is_set():
                return
            self.is_leader = True
            on_started_leading()
            deadline = time.time() + self.renew_deadline
            while not self._stop.is_set():
                self._stop.wait(self.renew_deadline / 2)
                if self._stop.is_set():
                    break
                if self.renew():
                    deadline = time.time() + self.renew_deadline
                    continue
                rec = self._read()
                if rec and rec.get("holder") != self.identity:
                    # Another replica holds the lease: demote NOW —
                    # continuing to act until the deadline would run two
                    # leaders concurrently.
                    break
                if time.time() > deadline:
                    break
            self.is_leader = False
            on_stopped_leading()
            if once or self._stop.is_set():
                return

    def stop(self) -> None:
        self._stop.set()
        self.release()
