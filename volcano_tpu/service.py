"""The framework daemon: store + scheduler + controllers + HTTP API.

Bundles what the reference deploys as three binaries (vc-scheduler,
vc-controller-manager, vc-webhook-manager) into one service for
single-process deployments: the admission-wrapped store is the API surface,
the scheduler loop and controller pump run on threads, and a small HTTP
server exposes the job/queue API (consumed by the vtpuctl CLI), the
Prometheus metrics endpoint (:8080/metrics in the reference), and healthz
(:11251).
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import asdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from .api import GROUP_NAME_ANNOTATION, Node, Queue
from .cache import ClusterStore
from .controllers import Action, Command, ControllerManager, Job, LifecyclePolicy, TaskSpec
from .metrics import metrics
from .scheduler import Scheduler
from .sim import ClusterSimulator
from .webhooks import AdmissionError, AdmittedStore

log = logging.getLogger(__name__)


def job_from_dict(data: dict) -> Job:
    from .api import Toleration

    tasks = [
        TaskSpec(
            name=t["name"],
            replicas=int(t.get("replicas", 1)),
            containers=t.get("containers", []),
            init_containers=t.get("initContainers", []),
            labels=t.get("labels", {}),
            node_selector=t.get("nodeSelector", {}),
            tolerations=[
                Toleration(
                    key=tol.get("key", ""),
                    operator=tol.get("operator", "Equal"),
                    value=tol.get("value", ""),
                    effect=tol.get("effect", ""),
                )
                for tol in t.get("tolerations", [])
            ],
            host_ports=t.get("hostPorts", []),
            env=t.get("env", {}),
            policies=[_policy_from_dict(p) for p in t.get("policies", [])],
        )
        for t in data.get("tasks", [])
    ]
    from .controllers import VolumeSpec

    volumes = [
        VolumeSpec(
            mount_path=v.get("mountPath", ""),
            volume_claim_name=v.get("volumeClaimName", ""),
            volume_claim=v.get("volumeClaim"),
        )
        for v in data.get("volumes", [])
    ]
    return Job(
        name=data["name"],
        namespace=data.get("namespace", "default"),
        min_available=int(data.get("minAvailable", 0)),
        tasks=tasks,
        volumes=volumes,
        policies=[_policy_from_dict(p) for p in data.get("policies", [])],
        plugins=data.get("plugins", {}),
        queue=data.get("queue", "default"),
        max_retry=int(data.get("maxRetry", 3)),
        ttl_seconds_after_finished=data.get("ttlSecondsAfterFinished"),
        priority_class=data.get("priorityClassName", ""),
    )


def _policy_from_dict(p: dict) -> LifecyclePolicy:
    return LifecyclePolicy(
        action=p.get("action", ""),
        event=p.get("event", ""),
        events=p.get("events", []),
        exit_code=p.get("exitCode"),
        timeout_seconds=p.get("timeout"),
    )


def job_to_dict(job: Job) -> dict:
    return {
        "name": job.name,
        "namespace": job.namespace,
        "minAvailable": job.min_available,
        "queue": job.queue,
        "tasks": [
            {"name": t.name, "replicas": t.replicas} for t in job.tasks
        ],
        "status": {
            "phase": job.status.state.phase,
            "pending": job.status.pending,
            "running": job.status.running,
            "succeeded": job.status.succeeded,
            "failed": job.status.failed,
            "terminating": job.status.terminating,
            "version": job.status.version,
            "retryCount": job.status.retry_count,
            "minAvailable": job.status.min_available,
        },
    }


class Service:
    def __init__(
        self,
        store: Optional[ClusterStore] = None,
        conf_path: Optional[str] = None,
        schedule_period: float = 1.0,
        controller_period: float = 0.2,
        simulate: bool = False,
        state_path: Optional[str] = None,
        checkpoint_period: float = 30.0,
        lease_path: Optional[str] = None,
        remote_binder: Optional[str] = None,
        remote_evictor: Optional[str] = None,
        remote_status_updater: Optional[str] = None,
        remote_solver: Optional[str] = None,
        pipeline: Optional[bool] = None,
    ):
        # Remote side-effect boundaries (cache/remote.py): binds
        # (cache.go:492-554), evictions (:439-491), and status writes
        # (:556-599) as RPCs to a second process.  Each probes /healthz
        # so a permanently wrong URL fails at startup (transient outages
        # still ride the per-interface retry paths: errTasks backoff for
        # binds, EvictFailure -> Running revert for evictions,
        # fire-and-forget rewrite-next-cycle for status).
        def _remote_client(url: str, cls_name: str):
            import urllib.request

            from .cache import remote as remote_mod

            with urllib.request.urlopen(
                f"{url.rstrip('/')}/healthz", timeout=10
            ):
                pass
            return getattr(remote_mod, cls_name)(url)

        if remote_binder:
            binder = _remote_client(remote_binder, "HttpBinder")
            if store is None:
                store = ClusterStore(binder=binder)
            else:
                store.binder = binder
                # An existing BindDispatcher captured the old binder at
                # first dispatch; stop it so the next dispatch rebuilds
                # against the remote one.
                store.close()
        if remote_evictor:
            store = store or ClusterStore()
            store.evictor = _remote_client(remote_evictor, "HttpEvictor")
        if remote_status_updater:
            store = store or ClusterStore()
            store.status_updater = _remote_client(
                remote_status_updater, "HttpStatusUpdater"
            )
        self.store = store or ClusterStore()
        if remote_solver:
            # Remote-solver split (the north-star bridge): this process
            # keeps the store/controllers/encode/commit; the wave solver
            # runs in the device-owning process(es) at this address
            # spec, fed one C++-packed snapshot frame per solve
            # (solver_service.py).  A comma-separated address list, or
            # VOLCANO_TPU_SOLVER_POOL=<n> over one address, builds a
            # replica POOL (solver_pool.py, ISSUE 15): health-scored
            # routing, hedged dispatch, one-cycle failover, what-if
            # offload.  The default (one address, pool knob 1) is the
            # plain single-connection RemoteSolver, byte-identical to
            # the pre-pool wire.
            from .solver_pool import make_solver_client

            client = make_solver_client(remote_solver)
            client.ping()  # fail fast on a permanently wrong address
            client.tracer = self.store.tracer
            self.store.remote_solver = client
        # Side-effect RPC clients record into the store's cycle trace.
        for client in (self.store.binder, self.store.evictor,
                       self.store.status_updater):
            if hasattr(client, "tracer"):
                client.tracer = self.store.tracer
        if pipeline is not None:
            # Pipelined sessions (double-buffered cycles, ISSUE 1): the
            # device solve dispatches asynchronously and commits at the
            # top of the next cycle.  None defers to VOLCANO_TPU_PIPELINE.
            self.store.pipeline = bool(pipeline)
        # Production binds dispatch on the background worker with
        # errTasks-style failure backoff (cache.go:536-552, 627-649);
        # opt out with VOLCANO_TPU_ASYNC_BIND=0 (tests that assert binds
        # synchronously construct their own ClusterStore instead).
        import os as _os

        if _os.environ.get("VOLCANO_TPU_ASYNC_BIND", "1") != "0":
            self.store.async_bind = True
        self.state_path = state_path
        self.checkpoint_period = checkpoint_period
        if state_path:
            import os

            if os.path.exists(state_path):
                from .persistence import load_store

                load_store(state_path, self.store)
        self.admitted = AdmittedStore(self.store)
        self.controllers = ControllerManager(self.store)
        # Sharded control plane (shard.py, ISSUE 16): VOLCANO_TPU_SHARDS
        # > 1 runs N queue-partitioned cycle threads with optimistic
        # cross-shard commits; the default (1) is the plain single
        # Scheduler, bitwise identical to the pre-sharding path.
        from .shard import make_scheduler

        self.scheduler = make_scheduler(
            self.store, conf_path=conf_path, schedule_period=schedule_period,
            gate=self.is_leader,
        )
        self.simulator = ClusterSimulator(self.store) if simulate else None
        self.controller_period = controller_period
        self._stop = threading.Event()
        self._threads = []
        self._httpd: Optional[ThreadingHTTPServer] = None
        # Active/passive HA: with a lease path, the control loops only run
        # while this replica holds the lease (cmd/scheduler/app/server.go
        # leaderelection semantics); the HTTP endpoint always serves.
        self.elector = None
        if lease_path:
            from .ha import LeaderElector

            self.elector = LeaderElector(lease_path)
        self._leading = threading.Event()
        if self.elector is None:
            self._leading.set()

    # ----------------------------------------------------------------- loops

    def start(self, http_port: int = 11250,
              bind_address: str = "127.0.0.1") -> int:
        self.scheduler.run()
        t = threading.Thread(target=self._controller_loop, daemon=True)
        t.start()
        self._threads.append(t)
        if self.state_path:
            ct = threading.Thread(target=self._checkpoint_loop, daemon=True)
            ct.start()
            self._threads.append(ct)
        if self.elector is not None:
            et = threading.Thread(
                target=lambda: self.elector.run(
                    self._leading.set, self._leading.clear
                ),
                daemon=True,
            )
            et.start()
            self._threads.append(et)
        port = self._start_http(http_port, bind_address)
        return port

    def is_leader(self) -> bool:
        return self._leading.is_set()

    def _controller_loop(self):
        while not self._stop.is_set():
            try:
                if self._leading.is_set():
                    self.controllers.process()
                    if self.simulator is not None:
                        self.simulator.step()
            except Exception:
                log.exception("controller pump failed")
            self._stop.wait(self.controller_period)

    def _checkpoint_loop(self):
        from .persistence import save_store

        while not self._stop.wait(self.checkpoint_period):
            # Only the active replica checkpoints: a standby's store is
            # stale and must never clobber the leader's snapshot.
            if not self._leading.is_set():
                continue
            try:
                save_store(self.store, self.state_path)
            except Exception:
                log.exception("checkpoint failed")

    def stop(self):
        self._stop.set()
        self.scheduler.stop()
        self.store.flush_binds(timeout=5)
        self.store.close()
        if self.elector is not None:
            self.elector.stop()
        if self.state_path and self._leading.is_set():
            from .persistence import save_store

            try:
                save_store(self.store, self.state_path)
            except Exception:
                log.exception("final checkpoint failed")
        if self._httpd is not None:
            self._httpd.shutdown()

    # ------------------------------------------------------------------ http

    def _start_http(self, port: int,
                    bind_address: str = "127.0.0.1") -> int:
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                log.debug(fmt, *args)

            def _send(self, code: int, body: str,
                      content_type: str = "application/json"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _json(self, code: int, obj):
                self._send(code, json.dumps(obj))

            def do_GET(self):
                url = urlparse(self.path)
                parts = [p for p in url.path.split("/") if p]
                try:
                    if url.path == "/healthz":
                        sched = getattr(service, "scheduler", None)
                        if sched is not None and not sched.healthy():
                            # Repeated cycle failures (e.g. a crashed TPU
                            # runtime, unrecoverable in-process): report
                            # unhealthy so the supervisor/HA standby
                            # takes over.
                            self._send(503, "unhealthy: scheduler cycles "
                                       "failing", "text/plain")
                        else:
                            self._send(200, "ok", "text/plain")
                    elif url.path == "/metrics":
                        self._send(200, metrics.expose_text(), "text/plain")
                    elif parts[:2] == ["debug", "cycles"] and len(parts) == 2:
                        # Recent flight-recorder ring as JSON (newest
                        # last); ?n=K limits the count.
                        n_raw = parse_qs(url.query).get("n", [None])[0]
                        n = int(n_raw) if n_raw is not None else None
                        self._json(200, [
                            rec.to_dict()
                            for rec in service.store.flight.recent(n)
                        ])
                    elif parts[:2] == ["debug", "cycles"] and len(parts) == 3:
                        rec = service.store.flight.get(int(parts[2]))
                        if rec is None:
                            self._json(404, {"error": "no such cycle"})
                        else:
                            self._json(200, rec.to_dict(include_spans=True))
                    elif parts[:2] == ["debug", "health"]:
                        # Runtime-auditor verdict + armed verifiers +
                        # SLO state (ISSUE 13).  Reads only the
                        # auditor's own lock-guarded snapshots — NEVER
                        # the store lock — so a scrape cannot block
                        # the cycle thread (tests/test_audit.py pins
                        # this under churn).
                        auditor = getattr(service.store, "auditor",
                                          None)
                        if auditor is None:
                            body = {"status": "no-auditor"}
                        else:
                            body = auditor.health()
                        # Solver-pool replica health (ISSUE 15): the
                        # pool snapshot reads only the pool's own
                        # lock, so — like the auditor — this can
                        # never block the cycle thread on store work.
                        snap = getattr(
                            getattr(service.store, "remote_solver",
                                    None),
                            "health_snapshot", None)
                        if snap is not None:
                            body["solver_pool"] = snap()
                        # Pod-journey queue rollup (ISSUE 18): per-
                        # queue time-to-bind percentiles; reads only
                        # the journey's own lock.
                        journey = getattr(service.store, "journey",
                                          None)
                        if journey is not None:
                            body["journey"] = journey.queue_rollup()
                        self._json(200, body)
                    elif parts[:2] == ["debug", "anomalies"]:
                        # The anomaly ring, oldest first; ?n=K limits.
                        auditor = getattr(service.store, "auditor",
                                          None)
                        n_raw = parse_qs(url.query).get("n", [None])[0]
                        n = int(n_raw) if n_raw is not None else None
                        self._json(200, [
                            a.to_dict()
                            for a in (auditor.anomalies(n)
                                      if auditor is not None else [])
                        ])
                    elif parts[:2] == ["debug", "shards"]:
                        # Sharded control plane state (shard.py, ISSUE
                        # 16): ownership table + per-shard counters.
                        # Reads only immutable snapshots and
                        # single-writer ints — NEVER the store lock —
                        # so a scrape cannot block any cycle thread.
                        snap = getattr(service.scheduler,
                                       "debug_snapshot", None)
                        self._json(200, snap() if snap is not None
                                   else {"shards": 1})
                    elif parts[:2] == ["debug", "pods"] and len(parts) == 3:
                        # Pod-journey timeline + why-pending verdict
                        # (obs/journey.py, ISSUE 18).  The journey is
                        # internally locked and uid-keyed: the stitched
                        # cross-shard view, never the store lock.
                        journey = getattr(service.store, "journey",
                                          None)
                        if journey is None:
                            self._json(404, {
                                "error": "journey disabled "
                                         "(VOLCANO_TPU_JOURNEY=0)"})
                        else:
                            body = journey.timeline(parts[2])
                            if body is None:
                                self._json(404, {
                                    "error": "no journey for pod",
                                    "uid": parts[2]})
                            else:
                                self._json(200, body)
                    elif parts[:2] == ["debug", "trace"]:
                        # Perfetto/chrome://tracing trace of the last K
                        # cycles (?cycles=K, default the whole ring),
                        # with pod journeys as async tracks.
                        from .obs import export as obs_export

                        k_raw = parse_qs(url.query).get(
                            "cycles", [None])[0]
                        k = int(k_raw) if k_raw is not None else None
                        journey = getattr(service.store, "journey",
                                          None)
                        self._json(200, obs_export.perfetto_trace(
                            service.store.flight.recent(k),
                            journey=(journey.trace_rows()
                                     if journey is not None else None),
                        ))
                    elif parts[:2] == ["apis", "jobs"] and len(parts) == 2:
                        ns = parse_qs(url.query).get("namespace", [None])[0]
                        jobs = [
                            job_to_dict(j)
                            for j in service.store.batch_jobs.values()
                            if ns is None or j.namespace == ns
                        ]
                        self._json(200, jobs)
                    elif parts[:2] == ["apis", "jobs"] and len(parts) == 4:
                        jk = f"{parts[2]}/{parts[3]}"
                        job = service.store.batch_jobs.get(jk)
                        if job is None:
                            self._json(404, {"error": "not found"})
                        else:
                            d = job_to_dict(job)
                            # Per-object event trails (Scheduled / Evict /
                            # FailedScheduling / Unschedulable — the
                            # reference's kubectl-visible Events,
                            # cache.go:487,540,584,790).
                            evs = {}
                            st = service.store
                            pgnames = set()
                            # Snapshot under the store lock: scheduler
                            # threads mutate st.pods concurrently.
                            with st._lock:
                                job_pods = [
                                    p for p in st.pods.values()
                                    if getattr(p, "owner_job", None) == jk
                                ]
                            for p in job_pods:
                                trail = st.events_for(
                                    f"Pod/{p.namespace}/{p.name}"
                                )
                                if trail:
                                    evs[f"Pod/{p.name}"] = trail
                                g = (p.annotations or {}).get(
                                    GROUP_NAME_ANNOTATION
                                )
                                if g:
                                    pgnames.add(g)
                            for g in pgnames:
                                trail = st.events_for(
                                    f"PodGroup/{parts[2]}/{g}"
                                )
                                if trail:
                                    evs[f"PodGroup/{g}"] = trail
                            if evs:
                                d["events"] = evs
                            self._json(200, d)
                    elif parts[:2] == ["apis", "placements"]:
                        # Bound placements straight from the mirror's
                        # batched p_node_name column (one vectorized
                        # mask + gather) — the scheduler's authoritative
                        # view, current even while the async bind
                        # dispatcher's 100k pod-record walks are still
                        # deferred (records lag the commit by design).
                        import numpy as _np

                        from .api import TaskStatus

                        limit = int(parse_qs(url.query).get(
                            "limit", [1000])[0])
                        st = service.store
                        m = st.mirror
                        with st._lock:
                            n = len(m.p_uid)
                            rows = _np.flatnonzero(
                                m.p_alive[:n]
                                & (m.p_status[:n]
                                   == int(TaskStatus.Bound))
                            )
                            total = int(len(rows))
                            rows = rows[:max(limit, 0)]
                            hosts = m.p_node_name[rows].tolist()
                            keys = [m.p_key[r] for r in rows.tolist()]
                        self._json(200, {
                            "bound": total,
                            "placements": dict(zip(keys, hosts)),
                        })
                    elif parts[:2] == ["apis", "queues"]:
                        self._json(
                            200,
                            [
                                {"name": q.name, "weight": q.weight,
                                 "state": q.state,
                                 "reclaimable": q.reclaimable}
                                for q in service.store.raw_queues.values()
                            ],
                        )
                    else:
                        self._json(404, {"error": "unknown path"})
                except Exception as err:  # pragma: no cover
                    self._json(500, {"error": str(err)})

            def do_POST(self):
                url = urlparse(self.path)
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                parts = [p for p in url.path.split("/") if p]
                try:
                    if parts[:2] == ["apis", "jobs"]:
                        job = job_from_dict(body)
                        service.admitted.add_batch_job(job)
                        self._json(201, job_to_dict(job))
                    elif parts[:2] == ["apis", "commands"]:
                        service.store.add_command(
                            Command(
                                action=body["action"],
                                target_kind=body.get("targetKind", "Job"),
                                target_name=body["targetName"],
                                target_namespace=body.get(
                                    "targetNamespace", "default"
                                ),
                            )
                        )
                        self._json(201, {"ok": True})
                    elif parts[:2] == ["apis", "queues"]:
                        service.admitted.add_queue(
                            Queue(
                                name=body["name"],
                                weight=int(body.get("weight", 1)),
                                capability=body.get("capability", {}),
                                reclaimable=body.get("reclaimable", True),
                            )
                        )
                        self._json(201, {"ok": True})
                    elif parts[:2] == ["apis", "nodes"]:
                        service.store.add_node(
                            Node(
                                name=body["name"],
                                allocatable=body.get("allocatable", {}),
                                labels=body.get("labels", {}),
                                topology=body.get("topology", {}),
                            )
                        )
                        self._json(201, {"ok": True})
                    else:
                        self._json(404, {"error": "unknown path"})
                except AdmissionError as err:
                    self._json(400, {"error": str(err)})
                except Exception as err:  # pragma: no cover
                    self._json(500, {"error": str(err)})

            def do_DELETE(self):
                url = urlparse(self.path)
                parts = [p for p in url.path.split("/") if p]
                try:
                    if parts[:2] == ["apis", "jobs"] and len(parts) == 4:
                        service.store.delete_batch_job(
                            f"{parts[2]}/{parts[3]}"
                        )
                        self._json(200, {"ok": True})
                    elif parts[:2] == ["apis", "queues"] and len(parts) == 3:
                        service.admitted.delete_queue(parts[2])
                        self._json(200, {"ok": True})
                    else:
                        self._json(404, {"error": "unknown path"})
                except AdmissionError as err:
                    self._json(400, {"error": str(err)})
                except Exception as err:  # pragma: no cover
                    self._json(500, {"error": str(err)})

        self._httpd = ThreadingHTTPServer((bind_address, port), Handler)
        actual_port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        return actual_port


def main(argv=None) -> int:
    """Daemon entry point (the vc-scheduler + vc-controller-manager pair in
    one process; flags mirror cmd/scheduler/app/options/options.go)."""
    import argparse
    import signal

    p = argparse.ArgumentParser(prog="vtpu-service")
    p.add_argument("--scheduler-conf", default=None,
                   help="scheduler YAML config path (hot-reloaded per cycle)")
    p.add_argument("--schedule-period", type=float, default=1.0)
    p.add_argument("--listen-port", type=int, default=11250)
    p.add_argument("--bind-address", default="127.0.0.1",
                   help="HTTP bind address (0.0.0.0 for containers)")
    p.add_argument("--state-path", default=None,
                   help="checkpoint file; loaded on start, saved periodically")
    p.add_argument("--checkpoint-period", type=float, default=30.0)
    p.add_argument("--lease-path", default=None,
                   help="leader-election lease file for active/passive HA")
    p.add_argument("--simulate", action="store_true",
                   help="run the built-in cluster simulator (dev mode)")
    p.add_argument("--remote-binder", default=None,
                   help="URL of a remote bind service (cache/remote.py); "
                        "binds then cross a process boundary like the "
                        "reference's API-server bind RPCs")
    p.add_argument("--remote-evictor", default=None,
                   help="URL of a remote evict service (cache/remote.py); "
                        "evictions cross a process boundary like the "
                        "reference's delete-pod RPCs (cache.go:439-491)")
    p.add_argument("--remote-status-updater", default=None,
                   help="URL of a remote status service (cache/remote.py); "
                        "PodGroup status writes cross a process boundary "
                        "like the reference's API writes (cache.go:556-599)")
    p.add_argument("--remote-solver", default=None,
                   help="host:port of a vtpu-solver process "
                        "(solver_service.py), or a comma-separated list "
                        "for a replica pool (solver_pool.py: hedged "
                        "dispatch, one-cycle failover, what-if offload; "
                        "VOLCANO_TPU_SOLVER_POOL=<n> pools n "
                        "connections to a single address).  The "
                        "scheduler then never touches an accelerator: "
                        "each cycle's solver inputs ship as one "
                        "C++-packed snapshot frame and the assignment "
                        "vectors return — the north-star store<->solver "
                        "bridge (cache.go:492-554 analog)")
    p.add_argument("--pipeline", action="store_true",
                   help="pipelined scheduler cycles: dispatch the device "
                        "solve asynchronously and commit it at the top of "
                        "the next cycle, hiding the device round trip "
                        "behind the host lanes (a staleness guard drops "
                        "rows invalidated during the overlap).  Also "
                        "reachable via VOLCANO_TPU_PIPELINE=1")
    args = p.parse_args(argv)

    svc = Service(
        conf_path=args.scheduler_conf,
        schedule_period=args.schedule_period,
        simulate=args.simulate,
        state_path=args.state_path,
        checkpoint_period=args.checkpoint_period,
        lease_path=args.lease_path,
        remote_binder=args.remote_binder,
        remote_evictor=args.remote_evictor,
        remote_status_updater=args.remote_status_updater,
        remote_solver=args.remote_solver,
        pipeline=args.pipeline or None,
    )
    port = svc.start(http_port=args.listen_port,
                     bind_address=args.bind_address)
    log.info("vtpu-service listening on %s:%d", args.bind_address, port)
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    signal.signal(signal.SIGINT, lambda *_: done.set())
    try:
        done.wait()
    finally:
        svc.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
