"""Admission validators and mutators.

Rules ported from the reference's admission webhooks:
- job create: ``admit_job.go:106-196`` (minAvailable > 0, maxRetry >= 0,
  ttl >= 0, tasks non-empty, DNS-label task names, no duplicate task names,
  replicas >= 0, total replicas >= minAvailable, policy validation, known
  plugins, queue exists and is Open)
- job update: ``admit_job.go:198-240`` (only minAvailable and
  tasks[*].replicas may change; no task add/remove)
- policies: ``admission/jobs/validate/util.go`` (event xor exitCode, no
  exit code 0, no duplicate events, externally-usable events/actions only)
- queue: ``validate_queue.go:64-128`` (state Open/Closed; default queue
  undeletable)
- pod: ``admission/pods/admit_pod.go:67-130`` (gate pod creation until its
  PodGroup is non-pending)
"""

from __future__ import annotations

import re
from dataclasses import replace
from typing import List, Optional

from ..api import GROUP_NAME_ANNOTATION, Pod, PodGroupPhase, QueueState
from ..controllers.apis import Action, Event, Job, LifecyclePolicy

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")

# Which events/actions users may reference in policies (util.go:33-53).
EXTERNAL_EVENTS = {
    Event.Any.value,
    Event.PodFailed.value,
    Event.PodEvicted.value,
    Event.Unknown.value,
    Event.TaskCompleted.value,
    Event.DeviceUnhealthy.value,
}
EXTERNAL_ACTIONS = {
    Action.AbortJob.value,
    Action.RestartJob.value,
    Action.RestartTask.value,
    Action.TerminateJob.value,
    Action.CompleteJob.value,
    Action.ResumeJob.value,
}


class AdmissionError(ValueError):
    """Request rejected by admission."""


def _validate_policies(policies: List[LifecyclePolicy], where: str) -> List[str]:
    msgs: List[str] = []
    seen_events = set()
    seen_exit_codes = set()
    for policy in policies:
        has_event = bool(policy.event or policy.events)
        if has_event and policy.exit_code is not None:
            msgs.append(
                f"{where}: must not specify event and exitCode simultaneously"
            )
            break
        if not has_event and policy.exit_code is None:
            msgs.append(f"{where}: either event or exitCode should be specified")
            break
        if policy.action not in EXTERNAL_ACTIONS:
            msgs.append(f"{where}: invalid policy action {policy.action}")
            break
        if has_event:
            ok = True
            for event in policy.event_list():
                if event not in EXTERNAL_EVENTS:
                    msgs.append(f"{where}: invalid policy event {event}")
                    ok = False
                    break
                if event in seen_events:
                    msgs.append(
                        f"{where}: duplicate event {event} across policies"
                    )
                    ok = False
                    break
                seen_events.add(event)
            if not ok:
                break
        else:
            if policy.exit_code == 0:
                msgs.append(f"{where}: 0 is not a valid error code")
                break
            if policy.exit_code in seen_exit_codes:
                msgs.append(
                    f"{where}: duplicate exitCode {policy.exit_code}"
                )
                break
            seen_exit_codes.add(policy.exit_code)
    # "if there's * here, no other policy should be here" (util.go).
    if "*" in seen_events and len(seen_events) > 1:
        msgs.append(
            f"{where}: if there's * here, no other policy should be here"
        )
    return msgs


def _validate_io(volumes) -> List[str]:
    """VolumeSpec rules (admit_job.go validateIO, util.go:161-183)."""
    msgs: List[str] = []
    paths = set()
    for vol in volumes:
        if not vol.mount_path:
            msgs.append("mountPath is required")
            continue
        if vol.mount_path in paths:
            msgs.append(f"duplicated mountPath: {vol.mount_path}")
        paths.add(vol.mount_path)
        if vol.volume_claim is None and not vol.volume_claim_name:
            msgs.append(
                "either volumeClaim or volumeClaimName must be specified"
            )
        elif vol.volume_claim_name:
            if vol.volume_claim is not None:
                msgs.append(
                    "conflict: if you want to use an existing PVC, just "
                    "specify volumeClaimName; to create a new PVC, do "
                    "not specify volumeClaimName"
                )
            elif not _DNS1123.match(vol.volume_claim_name):
                msgs.append(
                    f"invalid volumeClaimName {vol.volume_claim_name!r} "
                    "(must be DNS-1123)"
                )
    return msgs


def validate_job_create(job: Job, store) -> None:
    msgs: List[str] = []
    if job.min_available <= 0:
        raise AdmissionError("'minAvailable' must be > 0.")
    if job.max_retry < 0:
        raise AdmissionError("'maxRetry' cannot be less than zero.")
    if (
        job.ttl_seconds_after_finished is not None
        and job.ttl_seconds_after_finished < 0
    ):
        raise AdmissionError("'ttlSecondsAfterFinished' cannot be less than zero.")
    if not job.tasks:
        raise AdmissionError("No task specified in job spec")

    task_names = set()
    total_replicas = 0
    for task in job.tasks:
        if task.replicas < 0:
            msgs.append(f"'replicas' < 0 in task: {task.name}")
        total_replicas += task.replicas
        if not _DNS1123.match(task.name or ""):
            msgs.append(f"invalid task name {task.name!r} (must be DNS-1123)")
        if task.name in task_names:
            msgs.append(f"duplicated task name {task.name}")
            break
        task_names.add(task.name)
        msgs.extend(_validate_policies(task.policies, f"task {task.name}"))
        if not task.containers:
            msgs.append(f"task {task.name} has no containers")

    if total_replicas < job.min_available:
        msgs.append(
            "'minAvailable' should not be greater than total replicas in tasks"
        )
    msgs.extend(_validate_policies(job.policies, "job"))
    msgs.extend(_validate_io(job.volumes))

    from ..controllers.job_plugins import PLUGIN_BUILDERS

    for name in job.plugins:
        if name not in PLUGIN_BUILDERS:
            msgs.append(f"unable to find job plugin: {name}")

    queue = store.raw_queues.get(job.queue)
    if queue is None:
        msgs.append(f"unable to find job queue: {job.queue}")
    elif queue.state != QueueState.Open.value:
        msgs.append(
            "can only submit job to queue with state `Open`, "
            f"queue `{queue.name}` status is `{queue.state}`"
        )
    if msgs:
        raise AdmissionError("; ".join(msgs))


def validate_job_update(old: Job, new: Job) -> None:
    total_replicas = 0
    for task in new.tasks:
        if task.replicas < 0:
            raise AdmissionError(
                f"'replicas' must be >= 0 in task: {task.name}"
            )
        total_replicas += task.replicas
    if new.min_available > total_replicas:
        raise AdmissionError(
            "'minAvailable' must not be greater than total replicas"
        )
    if new.min_available <= 0:
        raise AdmissionError("'minAvailable' must be > 0")
    if len(old.tasks) != len(new.tasks):
        raise AdmissionError("job updates may not add or remove tasks")
    # Only minAvailable and tasks[*].replicas may mutate.
    for old_task, new_task in zip(old.tasks, new.tasks):
        if (
            old_task.name != new_task.name
            or old_task.containers != new_task.containers
            or old_task.policies != new_task.policies
        ):
            raise AdmissionError(
                "job updates may not change fields other than "
                "`minAvailable`, `tasks[*].replicas` under spec"
            )
    # Volumes may not change; controller-generated claim names are
    # normalized away before comparing (admit_job.go:224-236).
    def _norm_vols(vols):
        return [
            (v.mount_path,
             "" if v.volume_claim is not None else v.volume_claim_name,
             v.volume_claim)
            for v in vols
        ]

    if (
        old.queue != new.queue
        or old.policies != new.policies
        or old.plugins != new.plugins
        or old.priority_class != new.priority_class
        or _norm_vols(old.volumes) != _norm_vols(new.volumes)
    ):
        raise AdmissionError(
            "job updates may not change fields other than "
            "`minAvailable`, `tasks[*].replicas` under spec"
        )


def mutate_job(job: Job) -> Job:
    """Defaulting (mutate_job.go:74-111): default queue + scheduler name."""
    if not job.queue:
        job.queue = "default"
    if not job.scheduler_name:
        job.scheduler_name = "volcano-tpu"
    if job.max_retry == 0:
        job.max_retry = 3
    return job


def validate_queue(queue) -> None:
    if queue.state and queue.state not in (
        QueueState.Open.value, QueueState.Closed.value
    ):
        raise AdmissionError(
            f"queue state must be in ['Open', 'Closed'], got {queue.state}"
        )
    if queue.weight < 0:
        raise AdmissionError("queue weight must be >= 0")


def validate_queue_delete(name: str) -> None:
    if name == "default":
        raise AdmissionError("`default` queue can not be deleted")


def validate_pod_create(pod: Pod, store) -> None:
    """Gate pod creation until its PodGroup is schedulable
    (admit_pod.go:67-130)."""
    group = pod.annotations.get(GROUP_NAME_ANNOTATION)
    if not group:
        return
    pg = store.pod_groups.get(f"{pod.namespace}/{group}")
    if pg is None:
        raise AdmissionError(
            f"failed to get PodGroup for pod <{pod.namespace}/{pod.name}>"
        )
    if pg.status.phase in ("", PodGroupPhase.Pending.value):
        raise AdmissionError(
            f"failed to create pod <{pod.namespace}/{pod.name}>, "
            f"because the podgroup phase is {pg.status.phase or 'Pending'}"
        )


class AdmittedStore:
    """A ClusterStore facade applying admission rules on mutations — the
    framework's submission API surface."""

    def __init__(self, store):
        self.store = store

    def __getattr__(self, name):
        return getattr(self.store, name)

    def add_batch_job(self, job: Job) -> None:
        job = mutate_job(job)
        validate_job_create(job, self.store)
        self.store.add_batch_job(job)

    def update_batch_job(self, job: Job) -> None:
        old = self.store.batch_jobs.get(job.key)
        if old is not None and old is not job:
            validate_job_update(old, job)
        self.store.update_batch_job(job)

    def add_queue(self, queue) -> None:
        validate_queue(queue)
        self.store.add_queue(queue)

    def update_queue(self, queue) -> None:
        validate_queue(queue)
        self.store.update_queue(queue)

    def delete_queue(self, name: str) -> None:
        validate_queue_delete(name)
        self.store.delete_queue(name)

    def add_pod(self, pod: Pod) -> None:
        validate_pod_create(pod, self.store)
        self.store.add_pod(pod)
