"""Admission validation/mutation (pkg/webhooks).

With no kube-apiserver, the reference's webhook services become request
validators at the framework's submission API: ``AdmittedStore`` wraps a
ClusterStore and applies /jobs/validate, /jobs/mutate, /queues/validate and
/pods rules before letting mutations through.
"""

from .admission import (
    AdmissionError,
    AdmittedStore,
    mutate_job,
    validate_job_create,
    validate_job_update,
    validate_pod_create,
    validate_queue,
    validate_queue_delete,
)

__all__ = [
    "AdmissionError",
    "AdmittedStore",
    "mutate_job",
    "validate_job_create",
    "validate_job_update",
    "validate_pod_create",
    "validate_queue",
    "validate_queue_delete",
]
