"""Pure-NumPy Go-semantics oracle for the allocate solver.

This is the rebuild's CPU-reference parity harness (SURVEY.md section 7 /
M5): an *independent*, deliberately naive reimplementation of the reference
allocate loop (``pkg/scheduler/actions/allocate/allocate.go:40-250``) written
the way the Go code is written — object-at-a-time, explicit statement
rollback — over the exact same dense arrays the JAX solver consumes
(``volcano_tpu.ops.allocate.solve``).  Tests feed randomized snapshots to
both and require identical assignment matrices; any divergence is a solver
bug (or a documented deviation).

Semantics mirrored, with allocate.go anchors:
- queue-overuse skip at job open (allocate.go:126-133)
- per-task: static predicates AND InitResreq <= FutureIdle (allocate.go:98-105)
  AND pod-count AND host-port availability; no feasible node aborts the
  remaining tasks of the job (allocate.go:189-193)
- additive node scoring on live node state, best node = lowest index among
  maxima (deterministic stand-in for SelectBestNode's random-among-max,
  scheduler_helper.go:201-212)
- fits Idle -> stmt.Allocate; else -> ssn.Pipeline (session-level: survives
  statement discard, allocate.go:224-232)
- gang commit/discard at job end: roll back allocation-side effects iff the
  job never reached ready (statement.go:324-367; allocate.go:241-245)
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

MAX_PRIORITY = 10.0


def np_less_equal(l, r, eps, scalar_slot):
    """Epsilon-tolerant Resource.LessEqual (resource_info.go:286-320)."""
    l = np.asarray(l, np.float32)
    r = np.asarray(r, np.float32)
    per_slot = (l < r) | (np.abs(l - r) < eps)
    per_slot = per_slot | (scalar_slot & (l <= eps))
    return bool(np.all(per_slot, axis=-1)) if per_slot.ndim == 1 else np.all(
        per_slot, axis=-1
    )


def _binpack(req, allocatable, used, w):
    requested = req[None, :]
    used_finally = used + requested
    valid = (
        (requested > 0)
        & (allocatable > 0)
        & (np.asarray(w.binpack_res)[None, :] > 0)
        & (used_finally <= allocatable)
    )
    safe_alloc = np.where(allocatable > 0, allocatable, 1.0)
    per_res = np.where(
        valid, used_finally * np.asarray(w.binpack_res)[None, :] / safe_alloc, 0.0
    )
    counted = (requested > 0) & (np.asarray(w.binpack_res)[None, :] > 0)
    weight_sum = np.sum(
        np.where(counted, np.asarray(w.binpack_res)[None, :], 0.0), axis=-1
    )
    score = np.sum(per_res, axis=-1)
    score = np.where(weight_sum > 0, score / np.where(weight_sum > 0, weight_sum, 1.0), score)
    return score * MAX_PRIORITY * w.binpack_weight


def _least_requested(req, allocatable, used, w):
    requested = used[:, :2] + req[None, :2]
    cap = allocatable[:, :2]
    safe = np.where(cap > 0, cap, 1.0)
    per = np.where(cap > 0, np.clip(cap - requested, 0.0, None) * MAX_PRIORITY / safe, 0.0)
    return per.mean(axis=-1) * w.least_req_weight


def _most_requested(req, allocatable, used, w):
    requested = used[:, :2] + req[None, :2]
    cap = allocatable[:, :2]
    safe = np.where(cap > 0, cap, 1.0)
    per = np.where((cap > 0) & (requested <= cap), requested * MAX_PRIORITY / safe, 0.0)
    return per.mean(axis=-1) * w.most_req_weight


def _balanced(req, allocatable, used, w):
    requested = used[:, :2] + req[None, :2]
    cap = allocatable[:, :2]
    safe = np.where(cap > 0, cap, 1.0)
    frac = np.where(cap > 0, requested / safe, 1.0)
    diff = np.abs(frac[:, 0] - frac[:, 1])
    score = np.where(np.any(frac > 1.0, axis=-1), 0.0, (1.0 - diff) * MAX_PRIORITY)
    return score * w.balanced_weight


def _node_score(req, allocatable, idle, w):
    used = allocatable - idle
    return (
        _binpack(req, allocatable, used, w)
        + _least_requested(req, allocatable, used, w)
        + _most_requested(req, allocatable, used, w)
        + _balanced(req, allocatable, used, w)
    )


class OracleResult(NamedTuple):
    assigned: np.ndarray  # [P] node index or -1 (committed only)
    pipelined: np.ndarray  # [P]
    never_ready: np.ndarray  # [J] bool
    fit_failed: np.ndarray  # [J] bool
    idle: np.ndarray  # [N, R]
    q_alloc: np.ndarray  # [Q, R] allocated + pipelined


def _subset_np(bits_row, table):
    """[..., W] & [N, W] -> [..., N]: row bits all present in table rows."""
    missing = bits_row[..., None, :] & ~table
    return np.all(missing == 0, axis=-1)


def solve_oracle(
    nodes,
    tasks,
    jobs,
    queues,
    weights,
    eps,
    scalar_slot,
    aff=None,
) -> OracleResult:
    """Run the Go-shaped sequential loop over the dense snapshot (same
    grouped inputs as ops.allocate.solve)."""
    to_np = lambda a: np.array(a, copy=True)
    idle = to_np(nodes.idle).astype(np.float32)
    allocatable = to_np(nodes.allocatable).astype(np.float32)
    releasing = to_np(nodes.releasing).astype(np.float32)
    pipelined0 = to_np(nodes.pipelined).astype(np.float32)
    ntasks = to_np(nodes.ntasks).astype(np.int64)
    max_tasks = to_np(nodes.max_tasks).astype(np.int64)
    nports = to_np(nodes.ports).astype(np.uint32)
    n_ready = np.asarray(nodes.ready, bool)
    n_labels = np.asarray(nodes.label_bits, np.uint32)
    n_taints = np.asarray(nodes.taint_bits, np.uint32)
    req = to_np(tasks.req).astype(np.float32)
    init_req = to_np(tasks.init_req).astype(np.float32)
    task_job = to_np(tasks.job).astype(np.int64)
    task_real = to_np(tasks.real).astype(bool)
    task_ports = to_np(tasks.ports).astype(np.uint32)
    t_sel = np.asarray(tasks.sel_bits, np.uint32)
    t_aff_bits = np.asarray(tasks.aff_bits, np.uint32)
    t_aff_terms = np.asarray(tasks.aff_terms, np.int64)
    t_tol = np.asarray(tasks.tol_bits, np.uint32)
    t_pref = np.asarray(tasks.pref_bits, np.uint32)
    t_prefw = np.asarray(tasks.pref_w, np.float32)
    job_queue = to_np(jobs.queue).astype(np.int64)
    min_available = to_np(jobs.min_available).astype(np.int64)
    ready_base = to_np(jobs.ready_base).astype(np.int64)
    deserved = to_np(queues.deserved).astype(np.float32)
    q_alloc = to_np(queues.allocated).astype(np.float32)
    eps = np.asarray(eps, np.float32)
    scalar_slot = np.asarray(scalar_slot, bool)

    P = req.shape[0]
    J = min_available.shape[0]

    if aff is None:
        from .arrays.affinity import empty_affinity

        aff = empty_affinity(idle.shape[0], P)
    node_dom = np.asarray(aff.node_dom, np.int64)
    term_key = np.asarray(aff.term_key, np.int64)
    cnt_alloc = np.array(aff.cnt0, np.int64, copy=True)
    cnt_pip = np.zeros_like(cnt_alloc)
    t_req_aff = np.asarray(aff.t_req_aff, bool)
    t_req_anti = np.asarray(aff.t_req_anti, bool)
    t_matches = np.asarray(aff.t_matches, bool)
    t_soft = np.asarray(aff.t_soft, np.float32)
    E = cnt_alloc.shape[0]
    term_ar = np.arange(E)

    pip_extra = np.zeros_like(idle)
    pip_ntasks = np.zeros_like(ntasks)
    pip_nports = np.zeros_like(nports)
    q_pip = np.zeros_like(q_alloc)

    assigned = np.full((P,), -1, np.int32)
    pipelined = np.full((P,), -1, np.int32)
    never_ready = np.zeros((J,), bool)
    fit_failed = np.zeros((J,), bool)

    # Group task rows by job preserving encode order (jobs are contiguous).
    job_rows = []
    cur_job, cur = None, []
    for t in range(P):
        if not task_real[t]:
            continue
        j = int(task_job[t])
        if j != cur_job:
            if cur:
                job_rows.append((cur_job, cur))
            cur_job, cur = j, []
        cur.append(t)
    if cur:
        job_rows.append((cur_job, cur))

    for j, rows in job_rows:
        qj = int(job_queue[j])
        q_total = q_alloc[qj] + q_pip[qj]
        if not np_less_equal(q_total, deserved[qj], eps, scalar_slot):
            continue  # overused queue: job skipped, no statement opened

        # Open a statement: checkpoint allocation-side state.
        ck_idle = idle.copy()
        ck_ntasks = ntasks.copy()
        ck_nports = nports.copy()
        ck_cnt = cnt_alloc.copy()
        ck_q_alloc = q_alloc.copy()
        ck_assigned = assigned.copy()
        job_ready = ready_base[j] >= min_available[j]
        alloc_cnt = 0

        for t in rows:
            # Static predicates from the bitset tables (selector, required
            # node affinity OR-terms, taints, node readiness).
            stat = n_ready & _subset_np(t_sel[t], n_labels)
            term_ok = _subset_np(t_aff_bits[t], n_labels)  # [A, N]
            A = t_aff_bits.shape[1]
            term_real = np.arange(A) < t_aff_terms[t]
            stat &= (
                np.any(term_ok & term_real[:, None], axis=0)
                | (t_aff_terms[t] == 0)
            )
            untol = n_taints & ~t_tol[t][None, :]
            stat &= np.all(untol == 0, axis=-1)

            future_idle = idle + releasing - pipelined0 - pip_extra
            fit_future = np_less_equal(
                init_req[t][None, :], future_idle, eps, scalar_slot
            )
            total_ntasks = ntasks + pip_ntasks
            pods_ok = (max_tasks <= 0) | (total_ntasks < max_tasks)
            ports_used = nports | pip_nports
            ports_ok = np.all((task_ports[t][None, :] & ports_used) == 0, axis=-1)

            cnt = cnt_alloc + cnt_pip  # [E, D]
            dome = node_dom[:, term_key]  # [N, E]
            cval = cnt[term_ar[None, :], np.maximum(dome, 0)]
            cval = np.where(dome >= 0, cval, 0)
            total = cnt.sum(axis=-1)  # [E]
            aff_term_ok = (cval > 0) | ((total == 0) & t_matches[t])[None, :]
            aff_ok = np.all(~t_req_aff[t][None, :] | aff_term_ok, axis=-1)
            anti_ok = np.all(~t_req_anti[t][None, :] | (cval == 0), axis=-1)

            feasible = stat & fit_future & pods_ok & ports_ok
            feasible = feasible & aff_ok & anti_ok
            if not feasible.any():
                fit_failed[j] = True
                break  # abort the rest of this job's tasks

            score = _node_score(req[t], allocatable, idle, weights)
            pref_match = _subset_np(t_pref[t], n_labels)  # [AP, N]
            score = score + np.float32(weights.node_affinity_weight) * np.sum(
                pref_match * t_prefw[t][:, None], axis=0, dtype=np.float32
            )
            score = score + np.sum(
                t_soft[t][None, :] * cval.astype(np.float32), axis=-1
            )
            score = np.where(feasible, score, np.float32(-3.0e38))
            best = int(np.argmax(score))

            dom_t = node_dom[best, term_key]  # [E]
            inc = t_matches[t] & (dom_t >= 0)
            if np_less_equal(init_req[t], idle[best], eps, scalar_slot):
                idle[best] -= req[t]
                ntasks[best] += 1
                nports[best] |= task_ports[t]
                np.add.at(cnt_alloc, (term_ar, np.maximum(dom_t, 0)),
                          inc.astype(np.int64))
                q_alloc[qj] += req[t]
                assigned[t] = best
                alloc_cnt += 1
                if ready_base[j] + alloc_cnt >= min_available[j]:
                    job_ready = True
            else:
                pip_extra[best] += req[t]
                pip_ntasks[best] += 1
                pip_nports[best] |= task_ports[t]
                np.add.at(cnt_pip, (term_ar, np.maximum(dom_t, 0)),
                          inc.astype(np.int64))
                q_pip[qj] += req[t]
                pipelined[t] = best

        if not job_ready:
            # stmt.Discard: roll back allocation-side effects; pipelines stay.
            idle = ck_idle
            ntasks = ck_ntasks
            nports = ck_nports
            cnt_alloc = ck_cnt
            q_alloc = ck_q_alloc
            assigned = ck_assigned
            never_ready[j] = True

    return OracleResult(
        assigned=assigned,
        pipelined=pipelined,
        never_ready=never_ready,
        fit_failed=fit_failed,
        idle=idle,
        q_alloc=q_alloc + q_pip,
    )


# ---------------------------------------------------------------------------
# Eviction-side oracles (preempt/reclaim/enqueue/backfill): the Go-shaped
# references for the victim-selection machinery in fastpath_evict.py and
# the device victim kernel.  Deliberately naive, sequential NumPy.
# ---------------------------------------------------------------------------


class VictimSelection(NamedTuple):
    evicted: np.ndarray  # indices into the victims arrays, eviction order
    satisfied: bool  # preemptor fits the resulting future idle
    future_idle: np.ndarray  # [R] after the evictions


def oracle_victims(demand, future_idle, victims_res, victims_order,
                   eps, scalar_slot) -> VictimSelection:
    """Per-node victim pop loop (preempt.go:228-242): victims leave in
    inverted task-order (lowest order first — preempt.go:219-224) until
    the preemptor's init request fits the accumulating future idle; the
    preemptor pipelines iff the final fit holds.

    ``victims_order``: sort key per victim, ascending = evicted first
    (the caller encodes task_order_fn: priority asc, creation desc, ...
    inverted).  Ties broken by input index (stable), matching the
    deterministic heap replay of the fast path."""
    demand = np.asarray(demand, np.float32)
    fi = np.array(future_idle, np.float32, copy=True)
    victims_res = np.asarray(victims_res, np.float32)
    order = np.argsort(np.asarray(victims_order), kind="stable")
    evicted = []
    for i in order:
        if np_less_equal(demand, fi, eps, scalar_slot):
            break
        fi = fi + victims_res[i]
        evicted.append(int(i))
    return VictimSelection(
        evicted=np.asarray(evicted, np.int64),
        satisfied=bool(np_less_equal(demand, fi, eps, scalar_slot)),
        future_idle=fi,
    )


def oracle_gang_protection(min_available, ready_counts, victim_jobs):
    """gang.go:74-98 as a mask: walking the candidate victims in order,
    a victim is allowed iff its job's remaining occupancy stays >= its
    MinAvailable after this eviction, or MinAvailable == 1."""
    occupied = {int(j): int(ready_counts[int(j)])
                for j in set(int(j) for j in victim_jobs)}
    allowed = np.zeros(len(victim_jobs), bool)
    for i, j in enumerate(int(j) for j in victim_jobs):
        cnt = occupied[j]
        ma = int(min_available[j])
        if ma <= cnt - 1 or ma == 1:
            occupied[j] = cnt - 1
            allowed[i] = True
    return allowed


def oracle_enqueue(min_res, queue_of_group, group_order, idle_budget,
                   queue_caps, queue_alloc, eps, scalar_slot):
    """enqueue.go:52-132 over dense vectors: groups in (queue order,
    job order) charge MinResources against the overcommitted idle
    budget; the walk stops for everyone once the budget goes empty.

    ``min_res``: [G, R] (NaN row = MinResources nil: charges nothing,
    always accepted while the walk lives); ``queue_caps``: [Q, R] with
    +inf rows for capability-less queues (proportion JobEnqueueable).
    Returns [G] bool inqueue mask."""
    G = len(group_order)
    idle = np.array(idle_budget, np.float32, copy=True)
    q_alloc = np.array(queue_alloc, np.float32, copy=True)
    inqueue = np.zeros(G, bool)
    for g in group_order:
        if bool(np.all(idle < eps)):
            break
        row = min_res[g]
        if np.any(np.isnan(row)):
            inqueue[g] = True
            continue
        q = int(queue_of_group[g])
        if not np_less_equal(row + q_alloc[q], queue_caps[q], eps,
                             scalar_slot):
            continue
        if np_less_equal(row, idle, eps, scalar_slot):
            idle = idle - row
            q_alloc[q] = q_alloc[q] + row
            inqueue[g] = True
    return inqueue


class RebalanceVerdict(NamedTuple):
    frag: np.ndarray          # [N] f32 fragmentation score
    fit_now: np.ndarray       # [N] i64 gang tasks idle holds now
    fit_freed: np.ndarray     # [N] i64 gang tasks after draining
    drain_nodes: np.ndarray   # [K] chosen node indices (selection order)
    feasible: bool            # drain set covers the need within budgets
    budget_blocked: bool      # budgets (not capacity) blocked the plan


def oracle_rebalance(idle, allocatable, ready, evictable, prof_req, eps,
                     need, victims_by_node, victim_group, budget_left,
                     drain_cap) -> RebalanceVerdict:
    """Go-shaped reference for the rebalance planner's scoring +
    drain-set selection (``ops/rebalance.py``): object-at-a-time loops
    over nodes, profiles and victims, no vectorization.  The fast
    planner must agree exactly on ``frag``/``fit_*`` and on the chosen
    drain set (tests/test_rebalance.py parity).

    Definitions (shared spec with ``ops.rebalance.frag_scores`` /
    ``select_drain_set``):

    - per (node, profile) fit = min over requested slots of
      ``floor((plane + eps) / req)``; a profile requesting nothing fits
      0; the node's fit is the max over profiles.
    - frag = mean idle fraction over provisioned slots, zero unless the
      node is ready, holds idle, and fits no gang task as-is.
    - selection: candidates (gain > 0, frag > 0, has victims) sorted by
      ``(victim count, -gain, node)``; each charged against per-group
      budgets; stop at ``need`` covered or ``drain_cap`` taken; an
      uncoverable need returns an empty set.
    """
    idle = np.asarray(idle, np.float32)
    alloc = np.asarray(allocatable, np.float32)
    ev = np.asarray(evictable, np.float32)
    req = np.asarray(prof_req, np.float32)
    eps = np.asarray(eps, np.float32)
    ready = np.asarray(ready, bool)
    N, R = idle.shape
    U = req.shape[0]

    def fit_one(plane_row, req_row):
        cnt = None
        any_req = False
        for r in range(R):
            if req_row[r] <= eps[r]:
                continue
            any_req = True
            c = int(np.floor((plane_row[r] + eps[r]) / max(req_row[r], 1e-9)))
            cnt = c if cnt is None else min(cnt, c)
        if not any_req:
            return 0
        return max(cnt, 0)

    fit_now = np.zeros(N, np.int64)
    fit_freed = np.zeros(N, np.int64)
    frag = np.zeros(N, np.float32)
    for n in range(N):
        best_now = 0
        best_freed = 0
        for u in range(U):
            best_now = max(best_now, fit_one(idle[n], req[u]))
            best_freed = max(best_freed, fit_one(idle[n] + ev[n], req[u]))
        fit_now[n] = best_now
        fit_freed[n] = best_freed
        prov = [r for r in range(R) if alloc[n][r] > eps[r]]
        if not prov:
            idle_frac = 0.0
        else:
            idle_frac = sum(
                min(max(idle[n][r] / max(alloc[n][r], 1e-9), 0.0), 1.0)
                for r in prov
            ) / len(prov)
        has_idle = any(idle[n][r] > eps[r] for r in range(R))
        if ready[n] and has_idle and best_now == 0:
            frag[n] = np.float32(idle_frac)

    # Selection, re-derived independently of select_drain_set's
    # sort-then-walk: repeatedly SCAN all remaining candidates for the
    # best next node by the shared key spec (victim count asc, gain
    # desc, index asc), charging budgets per victim as it goes.  A
    # defect in either formulation (sort order, skip handling, budget
    # charge) diverges here instead of being cloned.
    gain = fit_freed - fit_now

    def is_cand(n):
        return gain[n] > 0 and frag[n] > 0.0 and bool(victims_by_node[n])

    def best_next(taken):
        best = None
        for n in range(N):
            if n in taken or not is_cand(n):
                continue
            key = (len(victims_by_node[n]), -int(gain[n]), n)
            if best is None or key < best[0]:
                best = (key, n)
        return None if best is None else best[1]

    left = dict(budget_left)
    chosen = []
    taken = set()
    acc = 0
    skipped = False
    while acc < need and len(chosen) < drain_cap:
        n = best_next(taken)
        if n is None:
            break
        taken.add(n)
        overdraw = False
        charges = {}
        for row in victims_by_node[n]:
            g = victim_group[row]
            charges[g] = charges.get(g, 0) + 1
        for g, c in charges.items():
            if left.get(g, 0) < c:
                overdraw = True
        if overdraw:
            skipped = True
            continue
        for g, c in charges.items():
            left[g] = left.get(g, 0) - c
        chosen.append(n)
        acc += int(gain[n])
    feasible = acc >= need
    if not feasible:
        # Budget-blocked only when the same greedy with unlimited
        # budgets, under the same cap, would have covered the need —
        # again re-derived as a scan loop.
        taken2 = set()
        unbudgeted = 0
        while len(taken2) < drain_cap:
            n = best_next(taken2)
            if n is None:
                break
            taken2.add(n)
            unbudgeted += int(gain[n])
        return RebalanceVerdict(
            frag=frag, fit_now=fit_now, fit_freed=fit_freed,
            drain_nodes=np.zeros(0, np.int64), feasible=False,
            budget_blocked=bool(skipped and unbudgeted >= need),
        )
    return RebalanceVerdict(
        frag=frag, fit_now=fit_now, fit_freed=fit_freed,
        drain_nodes=np.asarray(chosen, np.int64), feasible=True,
        budget_blocked=False,
    )


class TopologyVerdict(NamedTuple):
    """``oracle_topology`` output: per-block gang-fit planes and the
    deterministic target-block pick, re-derived naively."""

    cfit: np.ndarray      # [B, U] int gang tasks of profile u per block
    whole: np.ndarray     # [B] bool block hosts the WHOLE gang
    score: np.ndarray     # [B] partial-fit score
    frag: np.ndarray      # [B] stranded-partial-slice score
    selected: int         # target block (-1 = none)


def oracle_topology(idle, ready, ntasks, max_tasks, block_id, prof_req,
                    prof_cnt, eps, require) -> TopologyVerdict:
    """Go-shaped reference for the contiguous-block gang scorer
    (``ops/topology.gang_block_fit`` / ``fabric_frag`` /
    ``select_block``): object-at-a-time loops over nodes, profiles and
    blocks, no vectorization.  The kernel must agree exactly
    (tests/test_topology.py parity on seeded fragmented fabrics).

    Definitions (shared spec with ``ops.topology``):

    - per (node, profile) capacity = min over requested slots of
      ``floor((idle + eps) / req)``; a profile requesting nothing
      caps 0; not-ready nodes cap 0; ``max_tasks > 0`` caps by the
      node's remaining pod slots;
    - ``cfit[b, u]`` = sum of the capacity over the block's nodes
      (block -1 nodes belong to no block);
    - ``whole[b]`` = every profile's ``cfit[b, u] >= prof_cnt[u]``;
    - ``score[b]`` = sum of ``min(cfit[b, u], cnt[u])``;
    - ``frag[b]`` = 0 when whole, else ``score[b] / total task count``;
    - selection = max score among candidates (all blocks, or
      whole-gang blocks when ``require``), tie -> lowest block id,
      -1 when no candidate.
    """
    idle = np.asarray(idle, np.float32)
    req = np.asarray(prof_req, np.float32)
    eps = np.asarray(eps, np.float32)
    cnt = np.asarray(prof_cnt, np.int64)
    ready = np.asarray(ready, bool)
    ntasks = np.asarray(ntasks, np.int64)
    max_tasks = np.asarray(max_tasks, np.int64)
    block_id = np.asarray(block_id, np.int64)
    N, R = idle.shape
    U = req.shape[0]
    B = int(block_id.max()) + 1 if len(block_id) else 0

    def cap_one(n, u):
        if not ready[n]:
            return 0
        c = None
        for r in range(R):
            if req[u][r] <= eps[r]:
                continue
            per = int(np.floor(
                np.float32(idle[n][r] + eps[r])
                / np.float32(max(req[u][r], 1e-9))
            ))
            c = per if c is None else min(c, per)
        if c is None:
            return 0
        c = max(c, 0)
        if max_tasks[n] > 0:
            c = min(c, max(int(max_tasks[n] - ntasks[n]), 0))
        return c

    cfit = np.zeros((B, U), np.int64)
    for n in range(N):
        b = int(block_id[n])
        if b < 0:
            continue
        for u in range(U):
            cfit[b][u] += cap_one(n, u)

    whole = np.zeros(B, bool)
    score = np.zeros(B, np.float64)
    frag = np.zeros(B, np.float32)
    total = max(int(cnt.sum()), 1)
    for b in range(B):
        whole[b] = all(cfit[b][u] >= cnt[u] for u in range(U))
        score[b] = sum(min(int(cfit[b][u]), int(cnt[u])) for u in range(U))
        # f32 division to match the kernel's rounding exactly (an f64
        # divide + cast can differ by 1 ulp).
        frag[b] = (np.float32(0.0) if whole[b]
                   else np.float32(score[b]) / np.float32(total))

    selected = -1
    best = None
    for b in range(B):
        if require and not whole[b]:
            continue
        if best is None or score[b] > best:
            best = score[b]
            selected = b
    return TopologyVerdict(cfit=cfit, whole=whole, score=score,
                           frag=frag, selected=selected)


def oracle_backfill(be_feasible, group_inqueue, task_group):
    """backfill.go:39-88: zero-request pending tasks of Inqueue groups
    place on the first feasible node in index order (no resource charge
    — BestEffort).  ``be_feasible``: [T, N] bool.  Returns [T] node or
    -1."""
    T, N = be_feasible.shape
    out = np.full(T, -1, np.int64)
    for t in range(T):
        if not group_inqueue[int(task_group[t])]:
            continue
        feas = np.flatnonzero(be_feasible[t])
        if len(feas):
            out[t] = int(feas[0])
    return out


class EvictWaveVerdict(NamedTuple):
    """``oracle_preempt``/``oracle_reclaim`` output: the victim planes
    and the selected wave, re-derived naively."""

    eligible: np.ndarray   # [V] bool tier-gated victim mask
    order: np.ndarray      # [V] eviction order (eligible first)
    q_share: np.ndarray    # [Q] queue share = max alloc/deserved
    chosen: np.ndarray     # selected victim indices, eviction order
    feasible: bool         # freed capacity covers the need
    budget_blocked: bool   # budgets (not capacity/cap) blocked it
    gain: int              # gang tasks the chosen wave frees


def _oracle_victim_wave(mode, v_ok, v_jprio, v_crank, v_tie, v_queue,
                        v_node, v_req, p_prio, p_queue, q_alloc,
                        q_deserved, q_reclaimable, idle, prof_req, eps,
                        need, v_job, v_group, j_ready, j_minav,
                        budget_left, cap) -> EvictWaveVerdict:
    """Go-shaped reference for the device victim kernel + greedy
    selection (``ops/victim.py``): object-at-a-time loops, the order
    re-derived as a repeated best-next scan instead of a lexsort, the
    fit/slack arithmetic as per-slot loops.  ``mode``: 0 = preempt,
    1 = reclaim.  Shared spec (tests require exact agreement):

    - queue share = max over capped slots (deserved < 1e30) of
      allocated/deserved, 0 with no capped slot.
    - preempt eligibility: base-valid AND same queue as the preemptor
      AND victim job priority strictly lower.
    - reclaim eligibility: base-valid AND a DIFFERENT queue that is
      Reclaimable and overused (share > 1 + 1e-6).
    - eviction order: job priority asc, creation rank desc (youngest
      first), tie asc; ineligible rows order last.
    - selection: victims in order; skip nodes whose full drain gains no
      gang capacity; gang floor (job stays >= minAvailable unless
      minAvailable == 1); one budget charge per victim per PodGroup;
      reclaim keeps the victim queue's share >= 1 - 1e-6 after each
      eviction; stop at need covered or cap victims; prune victims on
      nodes whose final fit never improved; budget_blocked iff the same
      walk with unlimited budgets covers the need.
    """
    v_ok = np.asarray(v_ok, bool)
    v_jprio = np.asarray(v_jprio, np.int64)
    v_crank = np.asarray(v_crank, np.int64)
    v_tie = np.asarray(v_tie, np.int64)
    v_queue = np.asarray(v_queue, np.int64)
    v_node = np.asarray(v_node, np.int64)
    v_req = np.asarray(v_req, np.float32)
    q_alloc = np.asarray(q_alloc, np.float32)
    q_deserved = np.asarray(q_deserved, np.float32)
    idle = np.asarray(idle, np.float32)
    prof_req = np.asarray(prof_req, np.float32)
    eps = np.asarray(eps, np.float32)
    V = len(v_ok)
    Q, R = q_alloc.shape
    U = prof_req.shape[0]

    def share_of(alloc_row, des_row):
        s = np.float32(0.0)
        for r in range(R):
            if des_row[r] < np.float32(1.0e30):
                ratio = np.float32(alloc_row[r]) / np.float32(
                    max(des_row[r], np.float32(1e-9)))
                if ratio > s:
                    s = ratio
        return np.float32(s)

    q_share = np.array([share_of(q_alloc[q], q_deserved[q])
                        for q in range(Q)], np.float32)

    eligible = np.zeros(V, bool)
    for i in range(V):
        if not v_ok[i]:
            continue
        q = int(v_queue[i])
        if mode == 0:
            eligible[i] = (q == int(p_queue)
                           and int(v_jprio[i]) < int(p_prio))
        else:
            eligible[i] = (q != int(p_queue) and 0 <= q < Q
                           and bool(q_reclaimable[q])
                           and float(q_share[q]) > 1.0 + 1e-6)

    # Eviction order: repeated best-next scan by the shared key spec.
    remaining = list(range(V))
    order = []
    while remaining:
        best = None
        for i in remaining:
            # Ineligible rows share one sentinel priority key (the
            # kernel masks their priority before sorting), so their
            # relative order still follows (-crank, tie).
            prio_key = (int(v_jprio[i]) if eligible[i]
                        else np.iinfo(np.int32).max)
            key = (0 if eligible[i] else 1, prio_key,
                   -int(v_crank[i]), int(v_tie[i]))
            if best is None or key < best[0]:
                best = (key, i)
        order.append(best[1])
        remaining.remove(best[1])
    order = np.asarray(order, np.int64)

    def fit_one(plane_row):
        best = 0
        for u in range(U):
            cnt = None
            any_req = False
            for r in range(R):
                if prof_req[u][r] <= eps[r]:
                    continue
                any_req = True
                c = int(np.floor((plane_row[r] + eps[r])
                                 / max(prof_req[u][r], 1e-9)))
                cnt = c if cnt is None else min(cnt, c)
            if any_req:
                best = max(best, max(cnt, 0))
        return best

    evictable = np.zeros_like(idle)
    for i in range(V):
        if eligible[i]:
            evictable[int(v_node[i])] += v_req[i]
    fit0 = {}
    gain_ok = {}
    for n in set(int(v_node[i]) for i in range(V) if eligible[i]):
        fit0[n] = fit_one(idle[n])
        gain_ok[n] = fit_one(idle[n] + evictable[n]) > fit0[n]

    def walk(budgets):
        freed = {}
        cur_fit = {}
        occupancy = {}
        qa = np.array(q_alloc, np.float32)
        chosen = []
        gain = 0
        skipped = False
        for i in order.tolist():
            if not eligible[i]:
                break
            if gain >= need or len(chosen) >= cap:
                break
            n = int(v_node[i])
            if not gain_ok.get(n, False):
                continue
            j = int(v_job[i])
            cnt = occupancy.get(j)
            if cnt is None:
                cnt = int(j_ready[j]) if 0 <= j < len(j_ready) else 0
            minav = int(j_minav[j]) if 0 <= j < len(j_minav) else 1
            if not (minav <= cnt - 1 or minav == 1):
                continue
            g = v_group[i]
            if budgets.get(g, 0) < 1:
                skipped = True
                continue
            if mode == 1:
                q = int(v_queue[i])
                after = share_of(qa[q] - v_req[i], q_deserved[q])
                if float(after) < 1.0 - 1e-6:
                    continue
                qa[q] = qa[q] - v_req[i]
            occupancy[j] = cnt - 1
            budgets[g] = budgets.get(g, 0) - 1
            f = freed.get(n)
            if f is None:
                f = freed[n] = np.zeros(R, np.float32)
            old = cur_fit.get(n, fit0[n])
            f += v_req[i]
            new = fit_one(idle[n] + f)
            cur_fit[n] = new
            gain += new - old
            chosen.append(i)
        dead = {n for n in freed if cur_fit.get(n, fit0[n]) <= fit0[n]}
        if dead:
            chosen = [i for i in chosen if int(v_node[i]) not in dead]
        return chosen, gain, skipped

    chosen, gain, skipped = walk(dict(budget_left))
    if gain >= need:
        return EvictWaveVerdict(
            eligible=eligible, order=order, q_share=q_share,
            chosen=np.asarray(chosen, np.int64), feasible=True,
            budget_blocked=False, gain=gain)
    blocked = False
    if skipped:
        inf = {g: 1 << 30 for g in set(v_group)}
        _, ugain, _ = walk(inf)
        blocked = ugain >= need
    return EvictWaveVerdict(
        eligible=eligible, order=order, q_share=q_share,
        chosen=np.zeros(0, np.int64), feasible=False,
        budget_blocked=blocked, gain=gain)


def oracle_preempt(v_ok, v_jprio, v_crank, v_tie, v_queue, v_node,
                   v_req, p_prio, p_queue, q_alloc, q_deserved,
                   q_reclaimable, idle, prof_req, eps, need, v_job,
                   v_group, j_ready, j_minav, budget_left,
                   cap) -> EvictWaveVerdict:
    """Preempt-mode victim wave (same-queue, strictly lower priority)."""
    return _oracle_victim_wave(
        0, v_ok, v_jprio, v_crank, v_tie, v_queue, v_node, v_req,
        p_prio, p_queue, q_alloc, q_deserved, q_reclaimable, idle,
        prof_req, eps, need, v_job, v_group, j_ready, j_minav,
        budget_left, cap)


def oracle_reclaim(v_ok, v_jprio, v_crank, v_tie, v_queue, v_node,
                   v_req, p_prio, p_queue, q_alloc, q_deserved,
                   q_reclaimable, idle, prof_req, eps, need, v_job,
                   v_group, j_ready, j_minav, budget_left,
                   cap) -> EvictWaveVerdict:
    """Reclaim-mode victim wave (cross-queue, Reclaimable + overused,
    never below deserved)."""
    return _oracle_victim_wave(
        1, v_ok, v_jprio, v_crank, v_tie, v_queue, v_node, v_req,
        p_prio, p_queue, q_alloc, q_deserved, q_reclaimable, idle,
        prof_req, eps, need, v_job, v_group, j_ready, j_minav,
        budget_left, cap)
