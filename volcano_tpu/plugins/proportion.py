"""Proportion (weighted fair-share queue) plugin
(pkg/scheduler/plugins/proportion/proportion.go).

Computes each queue's ``deserved`` resources by iterative water-filling over
queue weights (proportion.go:117-173), orders queues by share, marks queues
Overused when allocated exceeds deserved, gates JobEnqueueable on queue
capability, and admits reclaim victims only while the victim queue stays at
or above its deserved share (proportion.go:190-215).

TPU-native: the final deserved matrix is exported to the session
(``ssn.queue_deserved``) so the allocate kernel's overuse gate consumes it
as a dense [Q, R] array.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from ..api import (
    JobInfo,
    QueueInfo,
    Resource,
    TaskInfo,
    TaskStatus,
    allocated_status,
    res_min,
    share,
)
from ..metrics import metrics

PLUGIN_NAME = "proportion"


@dataclass
class _QueueAttr:
    queue_id: str
    name: str
    weight: int
    share: float = 0.0
    deserved: Resource = field(default_factory=Resource.empty)
    allocated: Resource = field(default_factory=Resource.empty)
    request: Resource = field(default_factory=Resource.empty)


class ProportionPlugin:
    def __init__(self, arguments):
        self.arguments = arguments
        self.total_resource = Resource.empty()
        self.queue_opts: Dict[str, _QueueAttr] = {}

    @property
    def name(self) -> str:
        return PLUGIN_NAME

    def _update_share(self, attr: _QueueAttr):
        res = 0.0
        for rn in attr.deserved.resource_names():
            s = share(attr.allocated.get(rn), attr.deserved.get(rn))
            if s > res:
                res = s
        attr.share = res
        metrics.queue_share.set(attr.share, queue_name=attr.name)

    def on_session_open(self, ssn) -> None:
        for node in ssn.nodes.values():
            self.total_resource.add(node.allocatable)

        # Build per-queue attributes from jobs (proportion.go:71-103).
        for job in ssn.jobs.values():
            if job.queue not in self.queue_opts:
                queue = ssn.queues.get(job.queue)
                if queue is None:
                    continue
                self.queue_opts[job.queue] = _QueueAttr(
                    queue_id=queue.uid, name=queue.name, weight=queue.weight
                )
            attr = self.queue_opts[job.queue]
            for status, tasks in job.task_status_index.items():
                if allocated_status(status):
                    for t in tasks.values():
                        attr.allocated.add(t.resreq)
                        attr.request.add(t.resreq)
                elif status == TaskStatus.Pending:
                    for t in tasks.values():
                        attr.request.add(t.resreq)

        for attr in self.queue_opts.values():
            metrics.queue_allocated_milli_cpu.set(
                attr.allocated.milli_cpu, queue_name=attr.name
            )
            metrics.queue_allocated_memory_bytes.set(
                attr.allocated.memory, queue_name=attr.name
            )
            metrics.queue_request_milli_cpu.set(
                attr.request.milli_cpu, queue_name=attr.name
            )
            metrics.queue_request_memory_bytes.set(
                attr.request.memory, queue_name=attr.name
            )
            metrics.queue_weight.set(attr.weight, queue_name=attr.name)

        # Iterative water-filling (proportion.go:117-173).
        remaining = self.total_resource.clone()
        meet: Dict[str, bool] = {}
        while True:
            total_weight = sum(
                attr.weight
                for attr in self.queue_opts.values()
                if attr.queue_id not in meet
            )
            if total_weight == 0:
                break
            increased = Resource.empty()
            decreased = Resource.empty()
            for attr in self.queue_opts.values():
                if attr.queue_id in meet:
                    continue
                old_deserved = attr.deserved.clone()
                attr.deserved.add(
                    remaining.clone().multi(attr.weight / float(total_weight))
                )
                if attr.request.less(attr.deserved):
                    attr.deserved = res_min(attr.deserved, attr.request)
                    meet[attr.queue_id] = True
                self._update_share(attr)
                inc, dec = attr.deserved.diff(old_deserved)
                increased.add(inc)
                decreased.add(dec)
                metrics.queue_deserved_milli_cpu.set(
                    attr.deserved.milli_cpu, queue_name=attr.name
                )
                metrics.queue_deserved_memory_bytes.set(
                    attr.deserved.memory, queue_name=attr.name
                )
            remaining.sub(increased).add(decreased)
            if remaining.is_empty():
                break

        # TPU-native export: the allocate kernel's overuse gate compares
        # queue allocation (at open + in-kernel updates) against deserved.
        ssn.queue_deserved = {
            qid: attr.deserved.clone() for qid, attr in self.queue_opts.items()
        }
        ssn.queue_allocated_open = {
            qid: attr.allocated.clone() for qid, attr in self.queue_opts.items()
        }

        def queue_order_fn(l: QueueInfo, r: QueueInfo) -> int:
            la = self.queue_opts.get(l.uid)
            ra = self.queue_opts.get(r.uid)
            ls = la.share if la else 0.0
            rs = ra.share if ra else 0.0
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        ssn.add_queue_order_fn(self.name, queue_order_fn)

        def reclaimable_fn(reclaimer: TaskInfo,
                           reclaimees: List[TaskInfo]) -> List[TaskInfo]:
            victims = []
            allocations: Dict[str, Resource] = {}
            for reclaimee in reclaimees:
                job = ssn.jobs.get(reclaimee.job)
                if job is None:
                    continue
                attr = self.queue_opts.get(job.queue)
                if attr is None:
                    continue
                if job.queue not in allocations:
                    allocations[job.queue] = attr.allocated.clone()
                allocated = allocations[job.queue]
                if allocated.less(reclaimee.resreq):
                    continue
                allocated.sub(reclaimee.resreq)
                # Victim only while the queue stays at/above deserved
                # (proportion.go:209-211).
                if attr.deserved.less_equal_strict(allocated):
                    victims.append(reclaimee)
            return victims

        ssn.add_reclaimable_fn(self.name, reclaimable_fn)

        def overused_fn(queue: QueueInfo) -> bool:
            attr = self.queue_opts.get(queue.uid)
            if attr is None:
                return False
            over = not attr.allocated.less_equal(attr.deserved)
            metrics.queue_overused.set(1.0 if over else 0.0,
                                       queue_name=attr.name)
            return over

        ssn.add_overused_fn(self.name, overused_fn)

        def job_enqueueable_fn(job: JobInfo) -> bool:
            queue = ssn.queues.get(job.queue)
            attr = self.queue_opts.get(job.queue)
            if queue is None:
                return True
            # No capability set -> always enqueue (proportion.go:237-241).
            if not queue.queue.capability:
                return True
            if job.pod_group is None or job.pod_group.min_resources is None:
                return True
            min_req = Resource.from_resource_list(job.pod_group.min_resources)
            allocated = attr.allocated if attr else Resource.empty()
            return min_req.add(allocated).less_equal(
                Resource.from_resource_list(queue.queue.capability)
            )

        ssn.add_job_enqueueable_fn(self.name, job_enqueueable_fn)

        from ..framework.session import EventHandler

        def on_allocate(event):
            job = ssn.jobs.get(event.task.job)
            if job is None:
                return
            attr = self.queue_opts.get(job.queue)
            if attr is None:
                return
            attr.allocated.add(event.task.resreq)
            metrics.queue_allocated_milli_cpu.set(
                attr.allocated.milli_cpu, queue_name=attr.name
            )
            self._update_share(attr)

        def on_deallocate(event):
            job = ssn.jobs.get(event.task.job)
            if job is None:
                return
            attr = self.queue_opts.get(job.queue)
            if attr is None:
                return
            attr.allocated.sub(event.task.resreq)
            metrics.queue_allocated_milli_cpu.set(
                attr.allocated.milli_cpu, queue_name=attr.name
            )
            self._update_share(attr)

        ssn.add_event_handler(
            EventHandler(allocate_func=on_allocate,
                         deallocate_func=on_deallocate)
        )

    def on_session_close(self, ssn) -> None:
        self.total_resource = Resource.empty()
        self.queue_opts = {}
