"""Conformance plugin (pkg/scheduler/plugins/conformance/conformance.go).

Exempts critical pods (system priority classes / kube-system namespace) from
preempt and reclaim victim lists (conformance.go:44-66).
"""

from __future__ import annotations

from typing import List

from ..api import (
    SYSTEM_CLUSTER_CRITICAL,
    SYSTEM_NAMESPACE,
    SYSTEM_NODE_CRITICAL,
    TaskInfo,
)

PLUGIN_NAME = "conformance"


class ConformancePlugin:
    def __init__(self, arguments):
        self.arguments = arguments

    @property
    def name(self) -> str:
        return PLUGIN_NAME

    def on_session_open(self, ssn) -> None:
        def evictable_fn(evictor: TaskInfo,
                         evictees: List[TaskInfo]) -> List[TaskInfo]:
            victims = []
            for evictee in evictees:
                pc = evictee.pod.priority_class
                if (
                    pc in (SYSTEM_CLUSTER_CRITICAL, SYSTEM_NODE_CRITICAL)
                    or evictee.namespace == SYSTEM_NAMESPACE
                ):
                    continue
                victims.append(evictee)
            return victims

        ssn.add_preemptable_fn(self.name, evictable_fn)
        ssn.add_reclaimable_fn(self.name, evictable_fn)

    def on_session_close(self, ssn) -> None:
        pass
