"""Gang scheduling plugin (pkg/scheduler/plugins/gang/gang.go).

JobValid vetoes jobs with fewer valid tasks than MinAvailable (gang.go:51-72);
victims are protected so a job never drops below MinAvailable (gang.go:74-98);
job order boosts non-ready jobs (gang.go:104-129); JobReady/JobPipelined come
from the job counters (gang.go:130-137); session close writes Unschedulable
conditions and metrics (gang.go:140-183).
"""

from __future__ import annotations

from typing import Dict, List

from ..api import (
    JobInfo,
    PodGroupCondition,
    TaskInfo,
    TaskStatus,
    ValidateResult,
)
from ..framework.framework import POD_GROUP_UNSCHEDULABLE
from ..metrics import metrics

PLUGIN_NAME = "gang"
NOT_ENOUGH_PODS = "NotEnoughPods"
NOT_ENOUGH_RESOURCES = "NotEnoughResources"


class GangPlugin:
    def __init__(self, arguments):
        self.arguments = arguments

    @property
    def name(self) -> str:
        return PLUGIN_NAME

    def on_session_open(self, ssn) -> None:
        def valid_job_fn(obj) -> ValidateResult:
            job: JobInfo = obj
            vtn = job.valid_task_num()
            if vtn < job.min_available:
                return ValidateResult(
                    pass_=False,
                    reason=NOT_ENOUGH_PODS,
                    message=(
                        "Not enough valid tasks for gang-scheduling, "
                        f"valid: {vtn}, min: {job.min_available}"
                    ),
                )
            return None

        ssn.add_job_valid_fn(self.name, valid_job_fn)

        def preemptable_fn(preemptor: TaskInfo,
                           preemptees: List[TaskInfo]) -> List[TaskInfo]:
            victims: List[TaskInfo] = []
            occupied: Dict[str, int] = {}
            for preemptee in preemptees:
                job = ssn.jobs.get(preemptee.job)
                if job is None:
                    continue
                if job.uid not in occupied:
                    occupied[job.uid] = job.ready_task_num()
                cnt = occupied[job.uid]
                preemptable = job.min_available <= cnt - 1 or job.min_available == 1
                if preemptable:
                    occupied[job.uid] = cnt - 1
                    victims.append(preemptee)
            return victims

        ssn.add_reclaimable_fn(self.name, preemptable_fn)
        ssn.add_preemptable_fn(self.name, preemptable_fn)

        def job_order_fn(l: JobInfo, r: JobInfo) -> int:
            l_ready, r_ready = l.ready(), r.ready()
            if l_ready and r_ready:
                return 0
            if l_ready:
                return 1
            if r_ready:
                return -1
            return 0

        ssn.add_job_order_fn(self.name, job_order_fn)
        ssn.add_job_ready_fn(self.name, lambda job: job.ready())
        ssn.add_job_pipelined_fn(self.name, lambda job: job.pipelined())

    def on_session_close(self, ssn) -> None:
        unready_task_count = 0
        unschedulable_jobs = 0
        for job in ssn.jobs.values():
            if job.ready():
                continue
            unready_task_count = job.min_available - job.ready_task_num()
            msg = (
                f"{job.min_available - job.ready_task_num()}/{len(job.tasks)} "
                f"tasks in gang unschedulable: {job.fit_error()}"
            )
            job.job_fit_errors = msg
            unschedulable_jobs += 1
            metrics.unschedule_task_count.set(
                unready_task_count, job_name=job.name
            )
            metrics.job_retry_counts.inc(job_name=job.name)
            ssn.update_job_condition(
                job,
                PodGroupCondition(
                    type=POD_GROUP_UNSCHEDULABLE,
                    status="True",
                    transition_id=ssn.uid,
                    reason=NOT_ENOUGH_RESOURCES,
                    message=msg,
                ),
            )
        metrics.unschedule_job_count.set(unschedulable_jobs)
