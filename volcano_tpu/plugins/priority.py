"""Priority plugin (pkg/scheduler/plugins/priority/priority.go).

Task/job order by priority value; victims only from lower-priority jobs
(priority.go:44-104).
"""

from __future__ import annotations

from typing import List

from ..api import JobInfo, TaskInfo

PLUGIN_NAME = "priority"


class PriorityPlugin:
    def __init__(self, arguments):
        self.arguments = arguments

    @property
    def name(self) -> str:
        return PLUGIN_NAME

    def on_session_open(self, ssn) -> None:
        def task_order_fn(l: TaskInfo, r: TaskInfo) -> int:
            if l.priority == r.priority:
                return 0
            return -1 if l.priority > r.priority else 1

        ssn.add_task_order_fn(self.name, task_order_fn)

        def job_order_fn(l: JobInfo, r: JobInfo) -> int:
            if l.priority > r.priority:
                return -1
            if l.priority < r.priority:
                return 1
            return 0

        ssn.add_job_order_fn(self.name, job_order_fn)

        def preemptable_fn(preemptor: TaskInfo,
                           preemptees: List[TaskInfo]) -> List[TaskInfo]:
            preemptor_job = ssn.jobs.get(preemptor.job)
            if preemptor_job is None:
                return []
            victims = []
            for preemptee in preemptees:
                preemptee_job = ssn.jobs.get(preemptee.job)
                if preemptee_job is None:
                    continue
                if preemptee_job.priority < preemptor_job.priority:
                    victims.append(preemptee)
            return victims

        ssn.add_preemptable_fn(self.name, preemptable_fn)

    def on_session_close(self, ssn) -> None:
        pass
