"""Binpack (best-fit) plugin (pkg/scheduler/plugins/binpack/binpack.go).

Score = sum over requested resources of weight_r * (used_r + request_r) /
capacity_r, normalized by the weight sum to [0, 10] and scaled by the global
binpack weight (binpack.go:200-260).  Per-resource weights (including
extended resources) come from plugin arguments (binpack.go:94-151).
"""

from __future__ import annotations

from typing import Dict

from ..api import CPU, MEMORY, NodeInfo, TaskInfo
from ..ops.scoring import MAX_PRIORITY

PLUGIN_NAME = "binpack"

BINPACK_WEIGHT = "binpack.weight"
BINPACK_CPU = "binpack.cpu"
BINPACK_MEMORY = "binpack.memory"
BINPACK_RESOURCES = "binpack.resources"  # comma-separated extended names
# per-resource: binpack.resources.<name>


class BinpackPlugin:
    def __init__(self, arguments):
        self.arguments = arguments
        self.weight = max(arguments.get_int(BINPACK_WEIGHT, 1), 1)
        self.cpu_weight = max(arguments.get_int(BINPACK_CPU, 1), 0)
        self.memory_weight = max(arguments.get_int(BINPACK_MEMORY, 1), 0)
        self.resource_weights: Dict[str, int] = {}
        for name in (arguments.get(BINPACK_RESOURCES) or "").split(","):
            name = name.strip()
            if not name:
                continue
            self.resource_weights[name] = max(
                arguments.get_int(f"{BINPACK_RESOURCES}.{name}", 1), 0
            )

    @property
    def name(self) -> str:
        return PLUGIN_NAME

    def _resource_weight(self, resource: str):
        if resource == CPU:
            return self.cpu_weight, True
        if resource == MEMORY:
            return self.memory_weight, True
        if resource in self.resource_weights:
            return self.resource_weights[resource], True
        return 0, False

    def binpack_score(self, task: TaskInfo, node: NodeInfo) -> float:
        score = 0.0
        weight_sum = 0
        requested = task.resreq
        allocatable = node.allocatable
        used = node.used
        for resource in requested.resource_names():
            request = requested.get(resource)
            if request == 0:
                continue
            weight, found = self._resource_weight(resource)
            if not found:
                continue
            capacity = allocatable.get(resource)
            node_used = used.get(resource)
            if capacity > 0 and weight > 0:
                used_finally = request + node_used
                if used_finally <= capacity:
                    score += used_finally * weight / capacity
            weight_sum += weight
        if weight_sum > 0:
            score /= weight_sum
        return score * MAX_PRIORITY * self.weight

    def on_session_open(self, ssn) -> None:
        if self.weight == 0:
            return
        ssn.add_node_order_fn(
            self.name, lambda task, node: self.binpack_score(task, node)
        )

        def weights_fn():
            # Dense per-slot weights are resolved by the action against the
            # session's resource-slot layout.
            return {
                "binpack_weight": float(self.weight),
                "binpack_res": {
                    CPU: float(self.cpu_weight),
                    MEMORY: float(self.memory_weight),
                    **{k: float(v) for k, v in self.resource_weights.items()},
                },
            }

        ssn.add_score_weight_fn(self.name, weights_fn)

    def on_session_close(self, ssn) -> None:
        pass
