"""Predicates plugin (pkg/scheduler/plugins/predicates/predicates.go).

Host-side per-(task, node) checks mirroring the wrapped upstream predicates:
pod-count, node unschedulable/ready, node selector + required node affinity,
taints/tolerations, host ports, and inter-pod (anti)affinity by topology
domain (predicates.go:144-293).  The device path evaluates the same checks
as [P, N] bitset kernels (``volcano_tpu.ops.predicates``); this plugin flags
the session so the allocate action includes the static mask, and provides the
host fallback used by preempt/reclaim/backfill.
"""

from __future__ import annotations

from typing import Dict, List

from ..api import AffinityTerm, FitError, NodeInfo, TaskInfo

PLUGIN_NAME = "predicates"


def _labels_match(selector: Dict[str, str], labels: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def _tolerates(task: TaskInfo, taint) -> bool:
    for tol in task.pod.tolerations:
        if tol.operator == "Exists":
            key_ok = tol.key == "" or tol.key == taint.key
        else:
            key_ok = tol.key == taint.key and tol.value == taint.value
        eff_ok = tol.effect == "" or tol.effect == taint.effect
        if key_ok and eff_ok:
            return True
    return False


def _term_matches_anywhere(term: AffinityTerm, task: TaskInfo,
                           all_nodes) -> bool:
    """True when any resident pod in the term's namespaces matches its
    selector (used by the upstream self-match rule: a required affinity term
    with no match anywhere passes iff the incoming pod matches itself)."""
    namespaces = term.namespaces or [task.namespace]
    for other in all_nodes.values():
        for resident in other.tasks.values():
            if resident.namespace not in namespaces:
                continue
            if resident.uid == task.uid:
                continue
            if _labels_match(term.match_labels, resident.pod.labels):
                return True
    return False


def _affinity_domain_match(term: AffinityTerm, task: TaskInfo,
                           node: NodeInfo, all_nodes) -> bool:
    """True when some pod matching ``term`` runs in the same topology domain
    as ``node``."""
    if node.node is None:
        return False
    domain_value = node.node.labels.get(term.topology_key)
    namespaces = term.namespaces or [task.namespace]
    for other in all_nodes.values():
        if other.node is None:
            continue
        if term.topology_key == "kubernetes.io/hostname":
            same_domain = other.name == node.name
        else:
            same_domain = (
                domain_value is not None
                and other.node.labels.get(term.topology_key) == domain_value
            )
        if not same_domain:
            continue
        for resident in other.tasks.values():
            if resident.namespace not in namespaces:
                continue
            if resident.uid == task.uid:
                continue
            if _labels_match(term.match_labels, resident.pod.labels):
                return True
    return False


class PredicatesPlugin:
    def __init__(self, arguments):
        self.arguments = arguments

    @property
    def name(self) -> str:
        return PLUGIN_NAME

    def on_session_open(self, ssn) -> None:
        all_nodes = ssn.nodes

        def predicate_fn(task: TaskInfo, node: NodeInfo) -> None:
            if not node.ready():
                raise FitError(task.name, node.name,
                               f"node not ready: {node.state.reason}")
            spec = node.node
            if spec is not None and spec.unschedulable:
                raise FitError(task.name, node.name, "node unschedulable")
            # Pod count (CheckNodePodNumber).
            if node.allocatable.max_task_num > 0 and (
                len(node.tasks) >= node.allocatable.max_task_num
            ):
                raise FitError(task.name, node.name, "node pod number exceeded")
            # Node selector (PodMatchNodeSelector).
            if task.pod.node_selector and (
                spec is None
                or not _labels_match(task.pod.node_selector, spec.labels)
            ):
                raise FitError(task.name, node.name, "node selector mismatch")
            # Required node affinity: OR over alternative terms.
            terms = task.pod.required_node_affinity
            if terms:
                if spec is None or not any(
                    _labels_match(t, spec.labels) for t in terms
                ):
                    raise FitError(task.name, node.name,
                                   "node affinity mismatch")
            # Taints (PodToleratesNodeTaints): NoSchedule/NoExecute gate.
            if spec is not None:
                for taint in spec.taints:
                    if taint.effect not in ("NoSchedule", "NoExecute"):
                        continue
                    if not _tolerates(task, taint):
                        raise FitError(task.name, node.name,
                                       f"untolerated taint {taint.key}")
            # Host ports (PodFitsHostPorts).
            if task.pod.host_ports:
                used = {
                    p
                    for resident in node.tasks.values()
                    for p in resident.pod.host_ports
                }
                if any(p in used for p in task.pod.host_ports):
                    raise FitError(task.name, node.name, "host port conflict")
            # Inter-pod affinity / anti-affinity (topology-domain matching).
            for term in task.pod.affinity:
                if _affinity_domain_match(term, task, node, all_nodes):
                    continue
                # Self-match rule (upstream InterPodAffinityMatches): a term
                # with no matching pod anywhere passes iff the incoming pod
                # matches its own selector.
                self_ns = term.namespaces or [task.namespace]
                if not _term_matches_anywhere(term, task, all_nodes) and (
                    task.namespace in self_ns
                    and _labels_match(term.match_labels, task.pod.labels)
                ):
                    continue
                raise FitError(task.name, node.name,
                               "pod affinity not satisfied")
            for term in task.pod.anti_affinity:
                if _affinity_domain_match(term, task, node, all_nodes):
                    raise FitError(task.name, node.name,
                                   "pod anti-affinity violated")

        ssn.add_predicate_fn(self.name, predicate_fn)

        # Device contribution: the allocate action builds the [P,N] static
        # mask (ops.predicates.static_predicate_mask) when this plugin is
        # enabled — encoded directly from the snapshot arrays, so no
        # device-mask factory is registered here (that registry carries
        # OUT-OF-TREE mask contributions, session.add_device_mask_fn).

    def on_session_close(self, ssn) -> None:
        pass
