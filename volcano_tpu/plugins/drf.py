"""Dominant Resource Fairness plugin (pkg/scheduler/plugins/drf/drf.go).

Per-job share = max over resources of allocated/total (drf.go:317-329); job
order by share; optional weighted namespace DRF (namespace weight from the
quota annotation); preemptable when the preemptor's share stays below the
victim's post-eviction share (drf.go:121-200); event handlers keep shares
incremental during the cycle (drf.go:261-300).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List

from ..api import JobInfo, Resource, TaskInfo, allocated_status, share
from ..metrics import metrics

PLUGIN_NAME = "drf"
SHARE_DELTA = 0.000001


@dataclass
class _Attr:
    share: float = 0.0
    dominant_resource: str = ""
    allocated: Resource = field(default_factory=Resource.empty)


class DrfPlugin:
    def __init__(self, arguments):
        self.arguments = arguments
        self.total_resource = Resource.empty()
        self.job_attrs: Dict[str, _Attr] = {}
        self.namespace_opts: Dict[str, _Attr] = {}

    @property
    def name(self) -> str:
        return PLUGIN_NAME

    # ------------------------------------------------------------- helpers

    def _calculate_share(self, allocated: Resource, total: Resource):
        res = 0.0
        dominant = ""
        for rn in total.resource_names():
            s = share(allocated.get(rn), total.get(rn))
            if s > res:
                res = s
                dominant = rn
        return dominant, res

    def _update_share(self, attr: _Attr):
        attr.dominant_resource, attr.share = self._calculate_share(
            attr.allocated, self.total_resource
        )

    def _namespace_order_enabled(self, ssn) -> bool:
        for tier in ssn.tiers:
            for opt in tier.plugins:
                if opt.name == PLUGIN_NAME:
                    return bool(opt.enabled_namespace_order)
        return False

    # -------------------------------------------------------------- session

    def on_session_open(self, ssn) -> None:
        for node in ssn.nodes.values():
            self.total_resource.add(node.allocatable)

        ns_enabled = self._namespace_order_enabled(ssn)

        for job in ssn.jobs.values():
            attr = _Attr()
            for status, tasks in job.task_status_index.items():
                if allocated_status(status):
                    for t in tasks.values():
                        attr.allocated.add(t.resreq)
            self._update_share(attr)
            metrics.job_share.set(
                attr.share, job_ns=job.namespace, job_id=job.name
            )
            self.job_attrs[job.uid] = attr

            if ns_enabled:
                ns_opt = self.namespace_opts.setdefault(job.namespace, _Attr())
                ns_opt.allocated.add(attr.allocated)
                self._update_share(ns_opt)

        def preemptable_fn(preemptor: TaskInfo,
                           preemptees: List[TaskInfo]) -> List[TaskInfo]:
            victims: List[TaskInfo] = []

            if ns_enabled:
                l_weight = ssn.namespace_info.get(
                    preemptor.namespace
                ).get_weight() if preemptor.namespace in ssn.namespace_info else 1
                l_ns_att = self.namespace_opts.get(preemptor.namespace, _Attr())
                l_ns_alloc = l_ns_att.allocated.clone().add(preemptor.resreq)
                _, l_ns_share = self._calculate_share(
                    l_ns_alloc, self.total_resource
                )
                l_weighted = l_ns_share / float(l_weight)

                ns_allocations: Dict[str, Resource] = {}
                undecided: List[TaskInfo] = []
                for preemptee in preemptees:
                    if preemptor.namespace == preemptee.namespace:
                        undecided.append(preemptee)
                        continue
                    if preemptee.namespace not in ns_allocations:
                        r_att = self.namespace_opts.get(
                            preemptee.namespace, _Attr()
                        )
                        ns_allocations[preemptee.namespace] = (
                            r_att.allocated.clone()
                        )
                    r_weight = ssn.namespace_info.get(
                        preemptee.namespace
                    ).get_weight() if preemptee.namespace in ssn.namespace_info else 1
                    r_ns_alloc = ns_allocations[preemptee.namespace].sub(
                        preemptee.resreq
                    )
                    _, r_ns_share = self._calculate_share(
                        r_ns_alloc, self.total_resource
                    )
                    r_weighted = r_ns_share / float(r_weight)
                    # Avoid ping-pong: victim namespace must keep the higher
                    # weighted share after preemption (drf.go:162-173).
                    if l_weighted < r_weighted:
                        victims.append(preemptee)
                    if l_weighted - r_weighted > SHARE_DELTA:
                        continue
                    undecided.append(preemptee)
                preemptees = undecided

            l_att = self.job_attrs.get(preemptor.job, _Attr())
            l_alloc = l_att.allocated.clone().add(preemptor.resreq)
            _, ls = self._calculate_share(l_alloc, self.total_resource)

            allocations: Dict[str, Resource] = {}
            for preemptee in preemptees:
                if preemptee.job not in allocations:
                    r_att = self.job_attrs.get(preemptee.job, _Attr())
                    allocations[preemptee.job] = r_att.allocated.clone()
                r_alloc = allocations[preemptee.job].sub(preemptee.resreq)
                _, rs = self._calculate_share(r_alloc, self.total_resource)
                if ls < rs or abs(ls - rs) <= SHARE_DELTA:
                    victims.append(preemptee)
            return victims

        ssn.add_preemptable_fn(self.name, preemptable_fn)

        def job_order_fn(l: JobInfo, r: JobInfo) -> int:
            ls = self.job_attrs[l.uid].share
            rs = self.job_attrs[r.uid].share
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        ssn.add_job_order_fn(self.name, job_order_fn)

        def namespace_order_fn(l: str, r: str) -> int:
            l_opt = self.namespace_opts.get(l, _Attr())
            r_opt = self.namespace_opts.get(r, _Attr())
            l_weight = (
                ssn.namespace_info[l].get_weight()
                if l in ssn.namespace_info else 1
            )
            r_weight = (
                ssn.namespace_info[r].get_weight()
                if r in ssn.namespace_info else 1
            )
            lw = l_opt.share / float(l_weight)
            rw = r_opt.share / float(r_weight)
            metrics.namespace_weight.set(l_weight, namespace=l)
            metrics.namespace_weight.set(r_weight, namespace=r)
            metrics.namespace_weighted_share.set(lw, namespace=l)
            metrics.namespace_weighted_share.set(rw, namespace=r)
            if lw == rw:
                return 0
            return -1 if lw < rw else 1

        if ns_enabled:
            ssn.add_namespace_order_fn(self.name, namespace_order_fn)

        from ..framework.session import EventHandler

        def on_allocate(event):
            attr = self.job_attrs.get(event.task.job)
            if attr is None:
                return
            attr.allocated.add(event.task.resreq)
            self._update_share(attr)
            if ns_enabled:
                ns_opt = self.namespace_opts.setdefault(
                    event.task.namespace, _Attr()
                )
                ns_opt.allocated.add(event.task.resreq)
                self._update_share(ns_opt)

        def on_deallocate(event):
            attr = self.job_attrs.get(event.task.job)
            if attr is None:
                return
            attr.allocated.sub(event.task.resreq)
            self._update_share(attr)
            if ns_enabled:
                ns_opt = self.namespace_opts.setdefault(
                    event.task.namespace, _Attr()
                )
                ns_opt.allocated.sub(event.task.resreq)
                self._update_share(ns_opt)

        ssn.add_event_handler(
            EventHandler(allocate_func=on_allocate,
                         deallocate_func=on_deallocate)
        )

    def on_session_close(self, ssn) -> None:
        self.total_resource = Resource.empty()
        self.job_attrs = {}
