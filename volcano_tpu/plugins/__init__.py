"""Policy plugins, registered by name (pkg/scheduler/plugins/factory.go)."""

from ..framework.plugins import register_plugin_builder
from .binpack import BinpackPlugin
from .conformance import ConformancePlugin
from .drf import DrfPlugin
from .gang import GangPlugin
from .nodeorder import NodeOrderPlugin
from .predicates import PredicatesPlugin
from .priority import PriorityPlugin
from .proportion import ProportionPlugin

register_plugin_builder("gang", GangPlugin)
register_plugin_builder("priority", PriorityPlugin)
register_plugin_builder("drf", DrfPlugin)
register_plugin_builder("proportion", ProportionPlugin)
register_plugin_builder("predicates", PredicatesPlugin)
register_plugin_builder("nodeorder", NodeOrderPlugin)
register_plugin_builder("binpack", BinpackPlugin)
register_plugin_builder("conformance", ConformancePlugin)

__all__ = [
    "BinpackPlugin",
    "ConformancePlugin",
    "DrfPlugin",
    "GangPlugin",
    "NodeOrderPlugin",
    "PredicatesPlugin",
    "PriorityPlugin",
    "ProportionPlugin",
]
