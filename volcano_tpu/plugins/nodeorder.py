"""Node-order plugin (pkg/scheduler/plugins/nodeorder/nodeorder.go).

Wraps the classic priorities — LeastRequested, BalancedResourceAllocation,
NodeAffinity (preferred terms), MostRequested — with the 5 weight knobs
(nodeorder.go:95-124; defaults least=1, most=0, nodeaffinity=1,
podaffinity=1, balanced=1).  Registers host NodeOrderFn for the preempt path
and contributes the additive device ScoreWeights the allocate kernel uses.
"""

from __future__ import annotations

from ..api import NodeInfo, TaskInfo
from ..ops.scoring import MAX_PRIORITY

PLUGIN_NAME = "nodeorder"

NODE_AFFINITY_WEIGHT = "nodeaffinity.weight"
POD_AFFINITY_WEIGHT = "podaffinity.weight"
LEAST_REQUESTED_WEIGHT = "leastrequested.weight"
BALANCED_RESOURCE_WEIGHT = "balancedresource.weight"
MOST_REQUESTED_WEIGHT = "mostrequested.weight"


class NodeOrderPlugin:
    def __init__(self, arguments):
        self.arguments = arguments
        self.least_req = arguments.get_int(LEAST_REQUESTED_WEIGHT, 1)
        self.most_req = arguments.get_int(MOST_REQUESTED_WEIGHT, 0)
        self.node_affinity = arguments.get_int(NODE_AFFINITY_WEIGHT, 1)
        self.pod_affinity = arguments.get_int(POD_AFFINITY_WEIGHT, 1)
        self.balanced = arguments.get_int(BALANCED_RESOURCE_WEIGHT, 1)

    @property
    def name(self) -> str:
        return PLUGIN_NAME

    def on_session_open(self, ssn) -> None:
        def node_order_fn(task: TaskInfo, node: NodeInfo) -> float:
            score = 0.0
            cap_cpu = node.allocatable.milli_cpu
            cap_mem = node.allocatable.memory
            req_cpu = node.used.milli_cpu + task.resreq.milli_cpu
            req_mem = node.used.memory + task.resreq.memory
            # LeastRequested: (cap - req) * 10 / cap averaged over cpu+mem.
            if self.least_req:
                per = []
                for req, cap in ((req_cpu, cap_cpu), (req_mem, cap_mem)):
                    per.append(
                        max(cap - req, 0.0) * MAX_PRIORITY / cap if cap > 0 else 0.0
                    )
                score += (sum(per) / 2.0) * self.least_req
            # MostRequested.
            if self.most_req:
                per = []
                for req, cap in ((req_cpu, cap_cpu), (req_mem, cap_mem)):
                    per.append(
                        req * MAX_PRIORITY / cap if cap > 0 and req <= cap else 0.0
                    )
                score += (sum(per) / 2.0) * self.most_req
            # BalancedResourceAllocation.
            if self.balanced:
                cf = req_cpu / cap_cpu if cap_cpu > 0 else 1.0
                mf = req_mem / cap_mem if cap_mem > 0 else 1.0
                if cf > 1.0 or mf > 1.0:
                    bal = 0.0
                else:
                    bal = (1.0 - abs(cf - mf)) * MAX_PRIORITY
                score += bal * self.balanced
            # Preferred node affinity (CalculateNodeAffinityPriorityMap):
            # sum of weights of matching preferred terms, normalized later
            # by the reduce step in upstream; here scaled to [0,10] by the
            # task's total preference weight.
            if self.node_affinity and task.pod.preferred_node_affinity:
                total = sum(w for _, w in task.pod.preferred_node_affinity)
                got = 0
                labels = node.node.labels if node.node else {}
                for sel, w in task.pod.preferred_node_affinity:
                    if all(labels.get(k) == v for k, v in sel.items()):
                        got += w
                if total > 0:
                    score += (got / total) * MAX_PRIORITY * self.node_affinity
            return score

        ssn.add_node_order_fn(self.name, node_order_fn)

        # Device score weights for the allocate kernel.
        ssn.add_score_weight_fn(
            self.name,
            lambda: {
                "least_req_weight": float(self.least_req),
                "most_req_weight": float(self.most_req),
                "balanced_weight": float(self.balanced),
                "node_affinity_weight": float(self.node_affinity),
            },
        )

    def on_session_close(self, ssn) -> None:
        pass
