"""Thin Python client for the vtpu-service HTTP API.

The reference ships generated clientsets/informers/listers per CRD group
(pkg/client/, SURVEY.md section 2.3); since this framework owns its own
store and API, the equivalent is this small typed client plus
``FakeClient``, an in-process double that drives a ``ClusterStore``
directly (the analog of the generated fake clientsets used throughout the
reference's unit tests).

Usage::

    from volcano_tpu.client import Client
    c = Client("http://127.0.0.1:11250")
    c.create_job({"name": "train", "minAvailable": 2, "tasks": [...]})
    for j in c.jobs():
        print(j["name"], j["status"]["state"])
    c.suspend_job("train")
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Dict, List, Optional


class ApiError(Exception):
    """Non-2xx response from the service (carries the HTTP status)."""

    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class Client:
    """HTTP client mirroring vcctl's verbs (cmd/cli/job.go:11-67)."""

    def __init__(self, server: str = "http://127.0.0.1:11250",
                 timeout: float = 10.0):
        self.server = server.rstrip("/")
        self.timeout = timeout

    # ------------------------------------------------------------ plumbing

    def _request(self, method: str, path: str, body: Optional[dict] = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.server + path, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as err:
            try:
                msg = json.loads(err.read() or b"{}").get("error", str(err))
            except Exception:
                msg = str(err)
            raise ApiError(err.code, msg) from None
        if not payload:
            return None
        if payload.startswith(b"[") or payload.startswith(b"{"):
            return json.loads(payload)
        return payload.decode()

    # ---------------------------------------------------------------- jobs

    def jobs(self, namespace: Optional[str] = None) -> List[dict]:
        q = f"?namespace={namespace}" if namespace else ""
        return self._request("GET", f"/apis/jobs{q}")

    def get_job(self, name: str, namespace: str = "default") -> dict:
        return self._request("GET", f"/apis/jobs/{namespace}/{name}")

    def create_job(self, job: dict) -> dict:
        return self._request("POST", "/apis/jobs", job)

    def delete_job(self, name: str, namespace: str = "default") -> None:
        self._request("DELETE", f"/apis/jobs/{namespace}/{name}")

    def _command(self, action: str, name: str, namespace: str,
                 kind: str = "Job") -> None:
        self._request("POST", "/apis/commands", {
            "action": action, "targetKind": kind, "targetName": name,
            "targetNamespace": namespace,
        })

    def suspend_job(self, name: str, namespace: str = "default") -> None:
        self._command("AbortJob", name, namespace)

    def resume_job(self, name: str, namespace: str = "default") -> None:
        self._command("ResumeJob", name, namespace)

    # -------------------------------------------------------------- queues

    def queues(self) -> List[dict]:
        return self._request("GET", "/apis/queues")

    def create_queue(self, name: str, weight: int = 1,
                     capability: Optional[Dict[str, object]] = None,
                     reclaimable: bool = True) -> None:
        self._request("POST", "/apis/queues", {
            "name": name, "weight": weight,
            "capability": capability or {}, "reclaimable": reclaimable,
        })

    def delete_queue(self, name: str) -> None:
        self._request("DELETE", f"/apis/queues/{name}")

    def operate_queue(self, name: str, action: str) -> None:
        """action: OpenQueue | CloseQueue (bus/v1alpha1 actions)."""
        self._command(action, name, "default", kind="Queue")

    # --------------------------------------------------------------- nodes

    def add_node(self, name: str, allocatable: Dict[str, object],
                 labels: Optional[Dict[str, str]] = None,
                 topology: Optional[Dict[str, str]] = None) -> None:
        self._request("POST", "/apis/nodes", {
            "name": name, "allocatable": allocatable,
            "labels": labels or {}, "topology": topology or {},
        })

    # --------------------------------------------------------------- misc

    def healthz(self) -> bool:
        try:
            return self._request("GET", "/healthz") == "ok"
        except (ApiError, OSError):
            return False

    def metrics_text(self) -> str:
        return self._request("GET", "/metrics")


class FakeClient:
    """In-process Client double over a ClusterStore (the analog of the
    reference's generated fake clientsets, pkg/client/.../fake).  Accepts
    the same dict payloads as Client; command routing requires controllers
    (ControllerManager.process) to run, exactly as with the real service."""

    def __init__(self, store=None):
        from .cache import ClusterStore
        from .service import job_from_dict, job_to_dict
        from .webhooks.admission import AdmittedStore

        self.store = store if store is not None else ClusterStore()
        self.admitted = AdmittedStore(self.store)
        self._from_dict = job_from_dict
        self._to_dict = job_to_dict

    def jobs(self, namespace: Optional[str] = None) -> List[dict]:
        return [
            self._to_dict(j) for j in self.store.batch_jobs.values()
            if namespace is None or j.namespace == namespace
        ]

    def get_job(self, name: str, namespace: str = "default") -> dict:
        job = self.store.batch_jobs.get(f"{namespace}/{name}")
        if job is None:
            raise ApiError(404, "not found")
        return self._to_dict(job)

    def create_job(self, job: dict) -> dict:
        obj = self._from_dict(job)
        self.admitted.add_batch_job(obj)
        return self._to_dict(obj)

    def delete_job(self, name: str, namespace: str = "default") -> None:
        self.store.delete_batch_job(f"{namespace}/{name}")

    def _command(self, action: str, name: str, namespace: str,
                 kind: str = "Job") -> None:
        from .controllers import Command

        self.store.add_command(Command(
            action=action, target_kind=kind, target_name=name,
            target_namespace=namespace,
        ))

    def suspend_job(self, name: str, namespace: str = "default") -> None:
        self._command("AbortJob", name, namespace)

    def resume_job(self, name: str, namespace: str = "default") -> None:
        self._command("ResumeJob", name, namespace)

    def queues(self) -> List[dict]:
        return [
            {"name": q.name, "weight": q.weight, "state": q.state,
             "reclaimable": q.reclaimable}
            for q in self.store.raw_queues.values()
        ]

    def create_queue(self, name: str, weight: int = 1,
                     capability: Optional[Dict[str, object]] = None,
                     reclaimable: bool = True) -> None:
        from .api import Queue

        self.admitted.add_queue(Queue(
            name=name, weight=weight, capability=capability or {},
            reclaimable=reclaimable,
        ))

    def delete_queue(self, name: str) -> None:
        self.admitted.delete_queue(name)

    def operate_queue(self, name: str, action: str) -> None:
        self._command(action, name, "default", kind="Queue")

    def add_node(self, name: str, allocatable: Dict[str, object],
                 labels: Optional[Dict[str, str]] = None,
                 topology: Optional[Dict[str, str]] = None) -> None:
        from .api import Node

        self.store.add_node(Node(
            name=name, allocatable=allocatable, labels=labels or {},
            topology=topology or {},
        ))

    def healthz(self) -> bool:
        return True

    def metrics_text(self) -> str:
        from .metrics import metrics

        return metrics.expose_text()
