"""Synthetic cluster generators + solver-arg builder.

Drives the BASELINE benchmark configurations (BASELINE.md: 1k x 10k binpack,
5k DRF multi-queue, 10k preempt, 50k x 500k hyperscale) and the graft
entry's example inputs.  This is the rebuild's equivalent of the reference's
e2e fixture builders (test/e2e/util.go) at synthetic scale.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .api import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup, Queue, TaskStatus
from .arrays import ResourceSlots, encode_cluster
from .cache import ClusterStore


def synthetic_cluster(
    n_nodes: int = 1000,
    n_pods: int = 10000,
    gang_size: int = 4,
    n_queues: int = 1,
    node_cpu: str = "64",
    node_mem: str = "256Gi",
    pod_cpu_choices: Sequence[str] = ("1", "2", "4"),
    pod_mem_choices: Sequence[str] = ("2Gi", "4Gi", "8Gi"),
    seed: int = 0,
) -> ClusterStore:
    """A cluster of identical nodes and gang jobs with mixed pod sizes."""
    rng = np.random.default_rng(seed)
    store = ClusterStore()
    for i in range(n_nodes):
        store.add_node(
            Node(
                name=f"node-{i:06d}",
                allocatable={"cpu": node_cpu, "memory": node_mem, "pods": 256},
            )
        )
    for q in range(1, n_queues):
        store.add_queue(Queue(name=f"queue-{q}", weight=int(rng.integers(1, 9))))
    queues = ["default"] + [f"queue-{q}" for q in range(1, n_queues)]

    n_gangs = n_pods // gang_size
    for g in range(n_gangs):
        queue = queues[g % len(queues)]
        pg = PodGroup(name=f"pg-{g:06d}", min_member=gang_size, queue=queue)
        store.add_pod_group(pg)
        cpu = str(rng.choice(pod_cpu_choices))
        mem = str(rng.choice(pod_mem_choices))
        for k in range(gang_size):
            store.add_pod(
                Pod(
                    name=f"pg-{g:06d}-{k}",
                    annotations={GROUP_NAME_ANNOTATION: pg.name},
                    containers=[{"cpu": cpu, "memory": mem}],
                )
            )
    return store


def solve_args_from_store(
    store: ClusterStore,
    binpack: bool = True,
    nodeorder: bool = False,
) -> Tuple[tuple, object]:
    """Encode a store snapshot into the positional args of ops.allocate.solve.

    Returns (args, maps).  Orders jobs by id and tasks by creation; applies
    infinite deserved shares (no proportion gating).
    """
    import jax.numpy as jnp

    from .arrays.affinity import encode_affinity
    from .ops import default_weights, static_predicate_mask

    snap = store.snapshot()
    job_ids = sorted(snap.jobs.keys())
    pending = []
    kept_job_ids = []
    for jid in job_ids:
        job = snap.jobs[jid]
        tasks = sorted(
            job.task_status_index.get(TaskStatus.Pending, {}).values(),
            key=lambda t: (-t.priority, t.pod.creation_timestamp),
        )
        tasks = [t for t in tasks if not t.resreq.is_empty()]
        if not tasks:
            continue
        kept_job_ids.append(jid)
        pending.extend(tasks)
    arrays, maps = encode_cluster(snap, pending, kept_job_ids)
    mask = static_predicate_mask(arrays)
    aff = encode_affinity(
        snap, pending, maps.node_names,
        arrays.nodes.idle.shape[0], arrays.tasks.req.shape[0],
    )
    Q, R = arrays.queues.capability.shape
    args = (
        arrays.nodes.idle,
        arrays.nodes.allocatable,
        arrays.nodes.releasing,
        arrays.nodes.pipelined,
        arrays.nodes.num_tasks,
        arrays.nodes.max_tasks,
        arrays.nodes.port_bits,
        arrays.tasks.req,
        arrays.tasks.init_req,
        arrays.tasks.job,
        arrays.tasks.real,
        arrays.tasks.port_bits,
        arrays.jobs.queue,
        arrays.jobs.min_available,
        arrays.jobs.ready_base,
        jnp.full((Q, R), 3.0e38, jnp.float32),
        arrays.queues.allocated,
        mask,
        jnp.zeros(mask.shape, jnp.float32),
        default_weights(maps.slots.width, binpack_enabled=binpack,
                        nodeorder_enabled=nodeorder),
        jnp.asarray(arrays.eps),
        jnp.asarray(arrays.scalar_slot),
        aff,
    )
    return args, maps
