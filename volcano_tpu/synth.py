"""Synthetic cluster generators + solver-arg builder.

Drives the BASELINE benchmark configurations (BASELINE.md: 1k x 10k binpack,
5k DRF multi-queue, 10k preempt, 50k x 500k hyperscale) and the graft
entry's example inputs.  This is the rebuild's equivalent of the reference's
e2e fixture builders (test/e2e/util.go) at synthetic scale.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .api import (
    FABRIC_HOST,
    FABRIC_RACK,
    FABRIC_SLICE,
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    Queue,
    TaskStatus,
)
from .arrays import ResourceSlots, encode_cluster
from .cache import ClusterStore


def fabric_labels(
    i: int,
    *,
    nodes_per_host: int = 2,
    hosts_per_slice: int = 8,
    slices_per_rack: int = 4,
) -> dict:
    """Deterministic fabric-coordinate labels for node index ``i``.

    Maps the flat node index onto a rack/slice/host hierarchy (ISSUE
    20: ``fabric.volcano-tpu/*``) — nodes_per_host chips per host
    board, hosts_per_slice hosts per ICI slice, slices_per_rack slices
    per rack.  Slice and host ids are GLOBAL (not per-rack), so every
    (rack, slice) pair the mirror interns is unique and the block
    table stays 1:1 with physical slices.
    """
    host = i // max(nodes_per_host, 1)
    slc = host // max(hosts_per_slice, 1)
    rack = slc // max(slices_per_rack, 1)
    return {
        FABRIC_RACK: f"rack-{rack}",
        FABRIC_SLICE: f"slice-{slc}",
        FABRIC_HOST: f"host-{host}",
    }


def synthetic_cluster(
    n_nodes: int = 1000,
    n_pods: int = 10000,
    gang_size: int = 4,
    n_queues: int = 1,
    node_cpu: str = "64",
    node_mem: str = "256Gi",
    pod_cpu_choices: Sequence[str] = ("1", "2", "4"),
    pod_mem_choices: Sequence[str] = ("2Gi", "4Gi", "8Gi"),
    seed: int = 0,
    zones: int = 0,
    affinity_fraction: float = 0.0,
    anti_affinity_fraction: float = 0.0,
    spread_fraction: float = 0.0,
    queue_weights: Optional[Sequence[int]] = None,
    gang_sizes: Optional[Sequence[int]] = None,
) -> ClusterStore:
    """A cluster of identical nodes and gang jobs with mixed pod sizes.

    ``zones`` > 0 labels nodes round-robin with zone labels;
    ``affinity_fraction``/``anti_affinity_fraction``/``spread_fraction``
    give that share of gangs required zone affinity to their own app label,
    required hostname anti-affinity, or soft zone topology spread
    (BASELINE config 5's inter-pod affinity / topology-spread mix).
    ``gang_sizes`` draws each gang's size from the sequence (config 3's
    mixed TF/MPI shapes) instead of the fixed ``gang_size``.
    """
    from .api import AffinityTerm

    rng = np.random.default_rng(seed)
    store = ClusterStore()
    for i in range(n_nodes):
        labels = {}
        if zones > 0:
            labels["zone"] = f"zone-{i % zones}"
        store.add_node(
            Node(
                name=f"node-{i:06d}",
                allocatable={"cpu": node_cpu, "memory": node_mem, "pods": 256},
                labels=labels,
            )
        )
    for q in range(1, n_queues):
        weight = (
            queue_weights[q % len(queue_weights)]
            if queue_weights else int(rng.integers(1, 9))
        )
        store.add_queue(Queue(name=f"queue-{q}", weight=weight))
    queues = ["default"] + [f"queue-{q}" for q in range(1, n_queues)]

    g = 0
    pods_made = 0
    while pods_made < n_pods:
        size = (
            int(rng.choice(gang_sizes)) if gang_sizes else gang_size
        )
        size = min(size, n_pods - pods_made) or 1
        queue = queues[g % len(queues)]
        pg = PodGroup(name=f"pg-{g:06d}", min_member=size, queue=queue)
        store.add_pod_group(pg)
        cpu = str(rng.choice(pod_cpu_choices))
        mem = str(rng.choice(pod_mem_choices))
        app = f"app-{g:06d}"
        r = rng.random()
        affinity = anti_affinity = None
        spread = None
        if zones > 0 and r < affinity_fraction:
            affinity = [AffinityTerm(match_labels={"app": app},
                                     topology_key="zone")]
        elif r < affinity_fraction + anti_affinity_fraction:
            anti_affinity = [AffinityTerm(
                match_labels={"app": app},
                topology_key="kubernetes.io/hostname",
            )]
        elif zones > 0 and r < (affinity_fraction + anti_affinity_fraction
                                + spread_fraction):
            spread = [("zone", 10)]
        for k in range(size):
            store.add_pod(
                Pod(
                    name=f"pg-{g:06d}-{k}",
                    labels={"app": app},
                    annotations={GROUP_NAME_ANNOTATION: pg.name},
                    containers=[{"cpu": cpu, "memory": mem}],
                    affinity=affinity or [],
                    anti_affinity=anti_affinity or [],
                    topology_spread=spread or [],
                )
            )
            pods_made += 1
        g += 1
    return store


def tier_cluster(
    n_nodes: int = 100_000,
    n_pods: int = 1_000_000,
    gang_size: int = 8,
    zones: int = 32,
    n_queues: int = 4,
    node_cpu: str = "64",
    node_mem: str = "256Gi",
    pod_cpu_choices: Sequence[str] = ("1", "2", "4"),
    pod_mem_choices: Sequence[str] = ("2Gi", "4Gi", "8Gi"),
    seed: int = 0,
    chunk_pods: int = 50_000,
) -> ClusterStore:
    """The 100k-node x 1M-pod scale tier, built memory-frugally.

    ``synthetic_cluster`` allocates one containers list, one labels
    dict, and one annotations dict PER POD — ~5 host objects per row,
    which at 1M pods costs gigabytes of Python-object overhead before
    the first solve runs.  This builder fills the pod table in chunks
    with shared sub-objects so the big shape is buildable on CI-class
    hosts:

    - one containers list per distinct (cpu, mem) shape, shared by
      reference across every pod of that shape (the store treats pod
      specs as immutable — nothing mutates a containers list);
    - one annotations dict per GANG (the group-name annotation is the
      only entry and it is per-gang, not per-pod);
    - explicit uids/creation timestamps (skips the per-pod uuid and
      clock reads, and keeps task order deterministic);
    - ``chunk_pods``-sized fill chunks with a GC pass between chunks,
      bounding the transient allocation spike of the builder itself.

    Pods carry no labels/affinity — the tier measures the solve's
    scale envelope (fit/score/ranking over 100k nodes x 1M rows); the
    affinity mix rides the existing hyperscale config.  Nodes spread
    over ``zones`` zone labels so node classes stay > 1, and carry
    deterministic ``fabric.volcano-tpu/*`` coordinates (ISSUE 20) so
    the tier and the endurance harness exercise the topology planes.
    Fabric labels are never *queried* by any pod, so they add no label
    bits and leave node classes untouched.
    """
    import gc

    rng = np.random.default_rng(seed)
    store = ClusterStore()
    zone_labels = [{"zone": f"zone-{z}"} for z in range(max(zones, 1))]
    for i in range(n_nodes):
        labels = dict(fabric_labels(i))
        if zones:
            labels.update(zone_labels[i % len(zone_labels)])
        store.add_node(
            Node(
                name=f"node-{i:06d}",
                allocatable={"cpu": node_cpu, "memory": node_mem,
                             "pods": 256},
                labels=labels,
            )
        )
    for q in range(1, n_queues):
        store.add_queue(Queue(name=f"queue-{q}",
                              weight=int(rng.integers(1, 9))))
    queues = ["default"] + [f"queue-{q}" for q in range(1, n_queues)]

    # Shared containers lists: one per distinct pod shape.
    shapes = [
        [{"cpu": cpu, "memory": mem}]
        for cpu in pod_cpu_choices for mem in pod_mem_choices
    ]
    shape_ids = rng.integers(0, len(shapes),
                             size=(n_pods // gang_size) + 1)
    g = 0
    pods_made = 0
    ts = 1.0
    while pods_made < n_pods:
        chunk_end = min(pods_made + chunk_pods, n_pods)
        while pods_made < chunk_end:
            size = min(gang_size, n_pods - pods_made) or 1
            pg = PodGroup(name=f"pg-{g:07d}", min_member=size,
                          queue=queues[g % len(queues)])
            store.add_pod_group(pg)
            anno = {GROUP_NAME_ANNOTATION: pg.name}  # shared per gang
            containers = shapes[int(shape_ids[g])]
            for k in range(size):
                ts += 1.0
                store.add_pod(
                    Pod(
                        name=f"pg-{g:07d}-{k}",
                        uid=f"tier-{g:07d}-{k}",
                        annotations=anno,
                        containers=containers,
                        creation_timestamp=ts,
                    )
                )
            pods_made += size
            g += 1
        gc.collect()
    return store


def fabric_cluster(
    racks: int = 2,
    slices_per_rack: int = 2,
    nodes_per_slice: int = 16,
    hosts_per_slice: int = 8,
    node_cpu: str = "4",
    node_mem: str = "16Gi",
    filler_cpu: str = "3",
    filler_mem: str = "1Gi",
    fillers_per_slice: int = 2,
    gang_tasks: int = 32,
    gang_cpu: str = "2",
    gang_mem: str = "1Gi",
    topology: str = "require-contiguous",
    binder=None,
) -> ClusterStore:
    """A fragmented fabric no single block can host a gang on (ISSUE 20).

    ``racks x slices_per_rack`` ICI slices of ``nodes_per_slice`` nodes
    each, labeled with deterministic ``fabric.volcano-tpu/*``
    coordinates.  Every slice carries ``fillers_per_slice`` Running
    single-member filler pods (each its own PodGroup, so disruption
    budgets bite per filler) sized to strand their nodes for the gang's
    profile; the pending gang carries the ``topology`` constraint.

    At the defaults the arithmetic is the acceptance shape: each slice
    has 14 free 4-cpu nodes -> 28 two-cpu task slots < 32, so a
    require-contiguous 32-task gang is topology-infeasible everywhere,
    while total free capacity (4 x 28 = 112) would place it scattered.
    Draining one slice's two fillers frees the full 16-node block; the
    evicted fillers re-place on any other slice's free nodes.
    """
    from .api import PodPhase, PriorityClass

    store = ClusterStore(binder=binder)
    store.add_priority_class(PriorityClass(name="fabric-high", value=100))
    nodes_per_host = max(nodes_per_slice // max(hosts_per_slice, 1), 1)
    n_nodes = racks * slices_per_rack * nodes_per_slice
    for i in range(n_nodes):
        store.add_node(
            Node(
                name=f"fab-{i:04d}",
                allocatable={"cpu": node_cpu, "memory": node_mem,
                             "pods": 110},
                labels=fabric_labels(
                    i,
                    nodes_per_host=nodes_per_host,
                    hosts_per_slice=hosts_per_slice,
                    slices_per_rack=slices_per_rack,
                ),
            )
        )
    # Running fillers: the first fillers_per_slice nodes of EVERY
    # slice, pre-bound so fragmentation is deterministic.
    f = 0
    for s in range(racks * slices_per_rack):
        for k in range(fillers_per_slice):
            ni = s * nodes_per_slice + k
            store.add_pod_group(PodGroup(name=f"filler-{f:04d}",
                                         min_member=1))
            store.add_pod(
                Pod(
                    name=f"filler-{f:04d}-0",
                    annotations={GROUP_NAME_ANNOTATION: f"filler-{f:04d}"},
                    containers=[{"cpu": filler_cpu, "memory": filler_mem}],
                    phase=PodPhase.Running,
                    node_name=f"fab-{ni:04d}",
                )
            )
            f += 1
    pg = PodGroup(name="fabgang", min_member=gang_tasks,
                  topology=topology, priority_class="fabric-high")
    store.add_pod_group(pg)
    for k in range(gang_tasks):
        store.add_pod(
            Pod(
                name=f"fabgang-{k:03d}",
                annotations={GROUP_NAME_ANNOTATION: pg.name},
                containers=[{"cpu": gang_cpu, "memory": gang_mem}],
                priority_class="fabric-high",
                priority=100,
            )
        )
    return store


def preempt_cluster(
    n_nodes: int = 10000,
    fill_per_node: int = 4,
    n_pending: int = 20000,
    gang_size: int = 4,
    node_cpu: str = "64",
    node_mem: str = "256Gi",
    seed: int = 0,
) -> ClusterStore:
    """BASELINE config 4: oversubscribed queues with PriorityClass.

    A weight-1 "victim" queue holds running low-priority gangs filling
    ``fill_per_node`` x 16-cpu slots per node (all of a 64-cpu node); a
    weight-9 "premium" queue holds pending high-priority gangs that only fit
    by reclaiming from the victim queue (cross-queue) or preempting
    low-priority jobs (in-queue).
    """
    from .api import PodPhase, PriorityClass

    rng = np.random.default_rng(seed)
    store = ClusterStore()
    store.add_priority_class(PriorityClass(name="low", value=100))
    store.add_priority_class(PriorityClass(name="high", value=10000))
    store.add_queue(Queue(name="victim", weight=1))
    store.add_queue(Queue(name="premium", weight=9))
    for i in range(n_nodes):
        store.add_node(
            Node(
                name=f"node-{i:06d}",
                allocatable={"cpu": node_cpu, "memory": node_mem, "pods": 256},
            )
        )
    # Running low-priority filler gangs, one per node slot.
    g = 0
    for i in range(n_nodes):
        for s in range(fill_per_node):
            pg = PodGroup(name=f"filler-{g:07d}", min_member=1,
                          queue="victim")
            store.add_pod_group(pg)
            store.add_pod(
                Pod(
                    name=f"filler-{g:07d}-0",
                    annotations={GROUP_NAME_ANNOTATION: pg.name},
                    containers=[{"cpu": "16", "memory": "48Gi"}],
                    phase=PodPhase.Running,
                    node_name=f"node-{i:06d}",
                    priority_class="low",
                    priority=100,
                )
            )
            g += 1
    # Pending high-priority gangs in the premium queue.
    for j in range(n_pending // gang_size):
        pg = PodGroup(name=f"hi-{j:06d}", min_member=gang_size,
                      queue="premium")
        store.add_pod_group(pg)
        for k in range(gang_size):
            store.add_pod(
                Pod(
                    name=f"hi-{j:06d}-{k}",
                    annotations={GROUP_NAME_ANNOTATION: pg.name},
                    containers=[{"cpu": "8", "memory": "16Gi"}],
                    priority_class="high",
                    priority=10000,
                )
            )
    return store


def solve_args_from_store(
    store: ClusterStore,
    binpack: bool = True,
    nodeorder: bool = False,
) -> Tuple[tuple, object]:
    """Encode a store snapshot into the positional args of ops.allocate.solve.

    Returns (args, maps).  Orders jobs by id and tasks by creation; applies
    infinite deserved shares (no proportion gating).
    """
    from .arrays.affinity import encode_affinity
    from .ops import default_weights, solve_inputs

    snap = store.snapshot()
    job_ids = sorted(snap.jobs.keys())
    pending = []
    kept_job_ids = []
    for jid in job_ids:
        job = snap.jobs[jid]
        tasks = sorted(
            job.task_status_index.get(TaskStatus.Pending, {}).values(),
            key=lambda t: (-t.priority, t.pod.creation_timestamp),
        )
        tasks = [t for t in tasks if not t.resreq.is_empty()]
        if not tasks:
            continue
        kept_job_ids.append(jid)
        pending.extend(tasks)
    arrays, maps = encode_cluster(snap, pending, kept_job_ids)
    aff = encode_affinity(
        snap, pending, maps.node_names,
        arrays.nodes.idle.shape[0], arrays.tasks.req.shape[0],
    )
    nodes, tasks, jobs, queues = solve_inputs(arrays)
    args = (
        nodes, tasks, jobs, queues,
        default_weights(maps.slots.width, binpack_enabled=binpack,
                        nodeorder_enabled=nodeorder),
        arrays.eps,
        arrays.scalar_slot,
        aff,
    )
    return args, maps
