"""Node-class compaction: the coarse axis of the two-phase device solve.

Production clusters are overwhelmingly *homogeneous in the static planes*:
10k TPU nodes share a handful of (capacity, label set, taint set,
readiness) combinations even when their dynamic state (idle, ports,
pod counts) differs per node.  The reference never exploits this — it
samples nodes instead (``scheduler_helper.go:37-62``); the TPU-native
equivalent is to collapse the node table into *node classes* and evaluate
every static per-(profile x node) predicate once per
(profile x class), then expand the verdicts back through a [N] gather.

A class is the set of nodes with byte-identical static signature:

- label bit plane row (node-selector / node-affinity / preferred terms),
- taint bit plane row (toleration gating),
- readiness (ready & schedulable & real),
- capacity bucket (allocatable vector + max-task count — not consumed by
  the static masks themselves, but keeping capacity in the signature
  makes class membership meaningful for mixed-hardware fleets and keeps
  the class axis aligned with how operators reason about node pools).

Classes are ordered by *sorted signature bytes*, NOT first occurrence:
the ordering is then a pure function of the signature SET, so a node
mutation that does not add/remove a signature leaves every other node's
class id untouched — which is what lets ``ops/devsnap.py`` ship the
``class_id`` plane as a dirty-row delta scatter (the class tables
themselves re-upload only when ``tables_sig`` moves).

The class count axis is padded to a power-of-two bucket (inert rows:
not-ready, zero bits) so the coarse kernel compiles per bucket, not per
distinct class count.
"""

from __future__ import annotations

import hashlib
from typing import NamedTuple, Tuple

import numpy as np


class NodeClasses(NamedTuple):
    """Device inputs of the class axis ([N] nodes -> [C] classes).

    ``class_id`` maps every (padded) node row to its class; the three
    tables carry one row per class (padded classes are inert:
    ``ready=False``, zero bit rows — never referenced by ``class_id``).
    """

    class_id: np.ndarray  # [N] int32
    label_bits: np.ndarray  # [C, LW] uint32
    taint_bits: np.ndarray  # [C, TW] uint32
    ready: np.ndarray  # [C] bool


def _np(a) -> np.ndarray:
    return np.ascontiguousarray(a)


def build_node_classes(
    label_bits: np.ndarray,
    taint_bits: np.ndarray,
    ready: np.ndarray,
    allocatable: np.ndarray,
    max_tasks: np.ndarray,
) -> Tuple[NodeClasses, int, str]:
    """Group nodes into classes (host, numpy, exact).

    Returns ``(classes, n_classes, tables_sig)`` — ``n_classes`` the
    real (pre-padding) class count, ``tables_sig`` a content digest of
    the padded class tables (devsnap keys its table upload on it, and
    the delta path for ``class_id`` is valid exactly while it holds
    still — see module doc on the sorted-signature ordering).
    """
    from .wave import bucket_pow2

    N = int(np.asarray(label_bits).shape[0])
    lb = _np(label_bits)
    tb = _np(taint_bits)
    rd = _np(ready).astype(np.uint8).reshape(N, 1)
    al = _np(np.asarray(allocatable, np.float32))
    mt = _np(np.asarray(max_tasks, np.int32)).reshape(N, -1)
    sig = np.concatenate(
        [
            lb.view(np.uint8).reshape(N, -1),
            tb.view(np.uint8).reshape(N, -1),
            rd,
            al.view(np.uint8).reshape(N, -1),
            mt.view(np.uint8).reshape(N, -1),
        ],
        axis=1,
    )
    sig = np.ascontiguousarray(sig)
    # np.unique over the structured row view sorts lexicographically —
    # exactly the signature-set-stable ordering the delta path needs.
    rows = sig.view([("", np.uint8)] * sig.shape[1]).ravel()
    _, rep, inv = np.unique(rows, return_index=True, return_inverse=True)
    C = len(rep)
    Cp = bucket_pow2(C, floor=8)

    def pad_rows(a, n_pad):
        return np.concatenate(
            [a, np.zeros((n_pad, *a.shape[1:]), a.dtype)]
        )

    cls_label = pad_rows(lb[rep], Cp - C)
    cls_taint = pad_rows(tb[rep], Cp - C)
    cls_ready = np.concatenate(
        [_np(ready)[rep], np.zeros(Cp - C, bool)]
    )
    digest = hashlib.blake2b(digest_size=16)
    digest.update(cls_label.tobytes())
    digest.update(cls_taint.tobytes())
    digest.update(cls_ready.tobytes())
    classes = NodeClasses(
        class_id=inv.reshape(N).astype(np.int32),
        label_bits=cls_label,
        taint_bits=cls_taint,
        ready=cls_ready,
    )
    return classes, C, digest.hexdigest()
