"""Device-lane incrementality: cross-cycle plane reuse + warm shortlists.

ISSUE 9.  PR 7 made the host lanes incremental over the mirror's dirty
set, but the device lane still recomputed everything from scratch each
solve: ``_class_static`` re-evaluated every static predicate/pref plane
per (profile x class) and ``_coarse_shortlist`` re-ranked all N nodes
once per solve — even in a steady-state cycle where the dirty set says
a few hundred rows changed.  ``DeviceIncremental`` is the device analog
of ``fastpath_incr``: the same subtract-old/add-new discipline, applied
to the two-phase solve's coarse machinery.

Three pieces (all bit-for-bit equal to a fresh solve, with a proven
fallback and the ``VOLCANO_TPU_DEVINCR`` kill switch):

1. **Persistent static planes** — ``ops.wave._static_planes`` (its own
   jit) produces the [U, C] per-(profile x class) feasibility/score
   planes ONCE; they stay device-resident here, keyed on (class-table
   content sig, profile content generation, epoch-relevant bits), and
   pass into ``solve_wave`` as params — steady-state solves skip static
   evaluation entirely, in the coarse pass AND per wave.  Any key
   component moving (class-set change, profile-set change, node churn)
   rebuilds them wholesale.

2. **Warm-started shortlists** — the coarse pass retains per-block
   (score, global node id) candidate lists ([U, B, klb], the
   ``_topk_nodes`` two-stage structure at block granularity); on the
   next solve only blocks containing a dirty node row re-rank
   (``_warm_shortlist``), and the winners merge exactly like the full
   pass.  The caller proves the dirty superset via ``begin_solve``;
   any invalidation that can't be proven (cache key drift, dirty
   overflow, affinity-count content change — the cnt0 token rides the
   warm key) re-ranks fully, and the fine phase's full-N fallback still
   guarantees no binding is ever lost to pruning.

3. **Null-delta fast cycles** — ``skip_token`` (written by the fast
   path at dispatch) proves a later cycle's solve would see bit-equal
   inputs and produce the identical (empty) outcome, so the cycle skips
   the dispatch wholesale (``fastpath.FastCycle._allocate``).

The same object serves the local, mesh (replicated placement via
``set_mesh``), and remote paths — the solver child keeps one per
connection, keyed by the cache-generation tokens the scheduler sends in
the solve frame's manifest (``solver_service.py``).
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)


def devincr_on() -> bool:
    """The device-incremental kill switch (read per call so bench.py
    can A/B inside one process)."""
    return os.environ.get("VOLCANO_TPU_DEVINCR", "1") != "0"


def warm_blocks() -> int:
    """Node-axis block count of the warm-shortlist candidate retention
    (pow2; clamped to the padded node axis and raised to the mesh shard
    count by the caller)."""
    try:
        b = int(os.environ.get("VOLCANO_TPU_WARM_BLOCKS", 16))
    except ValueError:
        b = 16
    p = 1
    while p * 2 <= max(1, b):
        p *= 2
    return p


def warm_block_rows() -> int:
    """Upper bound on node rows per warm block (pow2).  At the 100k-node
    tier the fixed default block count would leave 8k+ rows per block —
    one dirty node then re-ranks 8k rows; bounding rows/block instead
    keeps the warm re-rank cost proportional to churn, and the
    block->shard->global merge (ops.wave._merge_block_cands) keeps the
    extra blocks' reduce shard-local."""
    try:
        r = int(os.environ.get("VOLCANO_TPU_WARM_BLOCK_ROWS", 8192))
    except ValueError:
        r = 8192
    p = 1
    while p * 2 <= max(1, r):
        p *= 2
    return p


# Past this fraction of blocks dirty, a full re-rank beats the gather +
# scatter machinery (and seeds fresh candidates anyway).
WARM_MAX_BLOCK_FRACTION = 0.5


class DeviceIncremental:
    """Persistent device-side caches for one solve stream (one per
    store on the scheduler side, one per connection in the solver
    child).  Not thread-safe by itself: the scheduler accesses it on
    the cycle thread under the store lock; the child on its single
    connection thread."""

    def __init__(self):
        # --- persistent static planes -------------------------------
        self._static_key = None
        self._static: Optional[Tuple] = None  # (ok [U,C], score [U,C])
        # --- warm shortlist candidates ------------------------------
        self._warm_key = None
        self._cand: Optional[Tuple] = None  # (cand_s, cand_i, sl)
        # --- host info for the CURRENT solve (begin_solve) ----------
        self._pend_static = None
        self._pend_warm = None
        self._pend_dirty: Optional[np.ndarray] = None
        # --- dirty-node accumulator between solves ------------------
        # Node rows whose derive-visible dynamic state changed since
        # the previous solve's inputs were built; None = poisoned
        # (a full derive ran, or nothing accumulated yet).
        self._acc_dirty: Optional[list] = None
        self._dirty_consumed = False
        # --- null-delta skip ----------------------------------------
        # Solve-input token captured at the previous dispatch; equality
        # at the next allocate proves the solve would reproduce the
        # previous (empty) outcome, so the dispatch is skipped.
        self.skip_token = None
        # --- mesh placement -----------------------------------------
        self._rep_shd = None
        self._place_tok = ("single",)
        # --- telemetry ----------------------------------------------
        self.last_mode = "off"  # warm | full | off (per solve)
        self.last_static = "off"  # hit | build | off
        self.last_blocks = (0, 0)  # (dirty blocks, total blocks)
        self.counts = {"warm": 0, "full": 0, "skip": 0}
        self.static_hits = 0
        self.static_builds = 0

    # ------------------------------------------------------- placement

    def set_mesh(self, mesh) -> None:
        """Replicated placement for the host-built delta inputs under a
        mesh (committed jit args must share a device set).  Changing
        the mesh voids both caches via the placement token."""
        if mesh is None:
            tok = ("single",)
            if tok != self._place_tok:
                self.invalidate()
            self._rep_shd = None
            self._place_tok = tok
            return
        from jax.sharding import NamedSharding, PartitionSpec

        tok = ("mesh", id(mesh), int(mesh.devices.size))
        if tok != self._place_tok:
            self.invalidate()
        self._rep_shd = NamedSharding(mesh, PartitionSpec())
        self._place_tok = tok

    def _place(self, a: np.ndarray):
        import jax

        if self._rep_shd is not None:
            return jax.device_put(a, self._rep_shd)
        return a

    # ------------------------------------------------- host-side state

    def accumulate_dirty(self, nodes: Optional[np.ndarray]) -> None:
        """Fold one derive's changed-node capture into the accumulator
        (the warm diff is against the previous SOLVE, which may be
        several derives back).  ``None`` poisons the accumulator — the
        next solve re-ranks fully and resets it."""
        if nodes is None:
            self._acc_dirty = None
            return
        if self._acc_dirty is None:
            # Poisoned: stays poisoned until the next solve resets the
            # anchor (take_dirty) — that solve re-ranks fully.
            return
        if len(nodes):
            self._acc_dirty.append(np.asarray(nodes, np.int64))

    def take_dirty(self, extra: Optional[np.ndarray]):
        """The dirty-node superset for the solve being dispatched
        (accumulated derive captures + the caller's still-unconsumed
        rows), or None when unprovable.  The accumulator reset is
        DEFERRED to ``end_solve``: a solve that crashes before its
        shortlist ran must not consume the set (the candidates were
        never updated, so the next solve still has to cover it)."""
        self._dirty_consumed = True
        acc = self._acc_dirty
        if acc is None or extra is None:
            return None
        parts = acc + [np.asarray(extra, np.int64)]
        cat = np.concatenate(parts) if parts else np.zeros(0, np.int64)
        cat = cat[cat >= 0]
        return np.unique(cat)

    def begin_solve(self, static_key, warm_key,
                    dirty_nodes: Optional[np.ndarray]) -> None:
        """Host-side validity info for the next ``solve_wave`` call:
        ``static_key`` pins the static-plane cache, ``warm_key`` the
        shortlist candidates, ``dirty_nodes`` the node rows whose
        dynamic state may have changed since the previous solve (None =
        unprovable -> full re-rank)."""
        self._pend_static = static_key
        self._pend_warm = warm_key
        self._pend_dirty = (None if dirty_nodes is None
                            else np.asarray(dirty_nodes, np.int64))

    def anchor_dirty(self) -> None:
        """Anchor the accumulator on a solve that demonstrably consumed
        the dirty superset: called by ``end_solve`` for in-process
        solves, and by the fast path after a SUCCESSFUL remote send
        (the child solves every frame it receives, so the frame's
        tokens+dirty list anchor the child's caches whether or not the
        reply survives; a failed send must NOT anchor — the child never
        saw the set)."""
        self._acc_dirty = []
        self._dirty_consumed = False

    def end_solve(self) -> None:
        """Consume the pending host info (a solve_wave call without a
        fresh ``begin_solve`` — e.g. the rebalance what-if — must not
        reuse a stale proof), and anchor the dirty accumulator on the
        solve that just COMPLETED (see ``take_dirty``)."""
        self._pend_static = None
        self._pend_warm = None
        self._pend_dirty = None
        if self._dirty_consumed:
            self.anchor_dirty()

    def invalidate(self) -> None:
        """Drop every cached plane and proof (close, compaction void,
        mesh change)."""
        self._static_key = None
        self._static = None
        self._warm_key = None
        self._cand = None
        self.skip_token = None
        self._dirty_consumed = False
        self.end_solve()
        self._acc_dirty = None

    def solve_info(self) -> dict:
        return {
            "mode": self.last_mode,
            "static": self.last_static,
            "blocks": self.last_blocks,
        }

    # -------------------------------------------------- solve services

    # Both methods below are called from inside solve_wave's
    # default_matmul_precision("float32") context — the producers must
    # trace under the same precision the in-kernel evaluation uses.

    def static_planes(self, nodes, prof, cls, naff_weight, chunk,
                      has_taints: bool, cls_identity: bool):
        """The persistent [U, C] static planes for this solve, produced
        on miss and reused on key match; None when the driver supplied
        no static key (kill switch / unprovable)."""
        if self._pend_static is None:
            self.last_static = "off"
            return None
        key = (self._pend_static, self._place_tok, bool(has_taints),
               bool(cls_identity), int(prof.sel_bits.shape[0]))
        if self._static is not None and self._static_key == key:
            self.static_hits += 1
            self.last_static = "hit"
            return self._static
        from .wave import _static_planes

        ok, sc = _static_planes(
            nodes, prof, cls, naff_weight, chunk=chunk,
            has_taints=bool(has_taints),
            cls_identity=bool(cls_identity),
        )
        self._static = (ok, sc)
        self._static_key = key
        self.static_builds += 1
        self.last_static = "build"
        return self._static

    def shortlist(self, nodes, prof, extra_prof, score_prof, cls, aff,
                  weights, eps, scalar_slot, sl_k: int, chunk: int,
                  features: tuple, cnt0_any: bool, cls_identity: bool,
                  mesh_shards: int, stat):
        """The solve's [U, sl_k] shortlists: warm-started when the warm
        key held and the dirty-block fraction is low, full re-rank
        (seeding fresh candidates) otherwise.  Bit-identical to
        ``_coarse_shortlist`` either way."""
        from . import wave as _w

        N = int(nodes.idle.shape[0])
        U = int(prof.req.shape[0])
        n_sh = max(1, int(mesh_shards))
        B = max(warm_blocks(), n_sh)
        # Scale-tier growth: bound rows per block so the per-dirty-node
        # re-rank cost stays fixed as N grows (the merge stays cheap —
        # block->shard->global, ops.wave._merge_block_cands).  Doubling
        # from max(warm_blocks, n_sh) keeps B a multiple of the shard
        # count, so blocks always subdivide shards.
        max_rows = warm_block_rows()
        while N % (B * 2) == 0 and N // B > max_rows:
            B *= 2
        B = min(B, N)
        while N % B:  # N is pow2-padded in practice; belt and braces
            B //= 2
        B = max(B, 1)
        nlb = N // B
        klb = min(sl_k, nlb)
        meta = (self._place_tok, U, N, B, klb, int(sl_k),
                tuple(features), bool(cnt0_any), bool(cls_identity),
                n_sh, stat is not None)
        key = ((self._pend_warm, meta)
               if self._pend_warm is not None else None)
        stat_ok, stat_sc = stat if stat is not None else (None, None)
        dirty = self._pend_dirty
        if (key is not None and self._cand is not None
                and self._warm_key == key and dirty is not None):
            db = np.unique(
                dirty[(dirty >= 0) & (dirty < N)].astype(np.int64)
                // nlb
            ).astype(np.int32)
            if len(db) == 0:
                # Null delta at shortlist granularity: every input is
                # byte-identical to the previous solve's — its
                # shortlist (and candidates) stand as-is.
                cand_s, cand_i, sl = self._cand
                self.last_mode = "warm"
                self.last_blocks = (0, B)
                self.counts["warm"] += 1
                return sl
            if len(db) <= max(1, int(B * WARM_MAX_BLOCK_FRACTION)):
                k = 1
                while k < len(db):
                    k *= 2
                if k > len(db):
                    db = np.concatenate(
                        [db, np.full(k - len(db), db[0], np.int32)]
                    )
                cand_s, cand_i, _sl = self._cand
                sl, cand_s, cand_i = _w._warm_shortlist(
                    nodes, prof, extra_prof, score_prof, cls, aff,
                    weights, eps, scalar_slot, stat_ok, stat_sc,
                    self._place(db), cand_s, cand_i,
                    sl_k=int(sl_k), klb=klb, nlb=nlb, chunk=chunk,
                    features=tuple(features), cnt0_any=bool(cnt0_any),
                    cls_identity=bool(cls_identity),
                    static_ext=stat is not None, mesh_shards=n_sh,
                )
                self._cand = (cand_s, cand_i, sl)
                self.last_mode = "warm"
                self.last_blocks = (int(len(np.unique(db))), B)
                self.counts["warm"] += 1
                return sl
        # Full re-rank — also seeds the candidates for the next solve.
        sl, cand_s, cand_i = _w._coarse_shortlist(
            nodes, prof, extra_prof, score_prof, cls, aff, weights,
            eps, scalar_slot, sl_k=int(sl_k), chunk=chunk,
            features=tuple(features), cnt0_any=bool(cnt0_any),
            cls_identity=bool(cls_identity), mesh_shards=n_sh,
            n_blocks=B, with_cand=True, static_ext=stat is not None,
            stat_ok=stat_ok, stat_score=stat_sc,
        )
        self._cand = (cand_s, cand_i, sl)
        self._warm_key = key
        self.last_mode = "full"
        self.last_blocks = (B, B)
        self.counts["full"] += 1
        return sl


def of_store(store) -> DeviceIncremental:
    """The store's device-incremental context, created on first use
    (``store._devincr_cache`` — a declared lock-guarded cache slot,
    cleared by ``store.close()``; see tools/vclint aggcheck's
    CACHE_REGISTRY for its invalidation contract)."""
    dv = getattr(store, "_devincr_cache", None)
    if dv is None:
        dv = store._devincr_cache = DeviceIncremental()
    return dv
