"""The allocate solver: Volcano's hot loop as one jitted XLA program.

Replaces the namespace->queue->job->task object loop of
``pkg/scheduler/actions/allocate/allocate.go:40-250`` (predicate fan-out,
score fan-out, best-node selection, capacity update, gang commit/discard)
with a single sequential scan over pre-ordered tasks carrying dense cluster
state.  Semantics preserved per task step:

- predicate  = static mask (labels/taints/ports/ready) AND InitResreq fits
  FutureIdle (allocate.go:98-105) AND pod-count fits AND no port clash AND
  inter-pod (anti)affinity on live per-(term, domain) count tensors
  (the dynamic parts of the predicates plugin, updated as the solver assigns;
  predicates.go:111-136,272-291)
- score      = additive scorers on current node state (allocate.go:202)
- selection  = masked argmax (SelectBestNode; first-index tie-break instead
  of random-among-max)
- fits Idle  -> allocate: idle/queue/pod-count/ports updated (stmt.Allocate)
- else       -> pipeline: FutureIdle reduced, effects NOT rolled back on
  discard (ssn.Pipeline is session-level; statement.go records only
  stmt ops; allocate.go:224-232)
- a task with no feasible node aborts the remaining tasks of its job
  (allocate.go:189-193 break)
- gang       = job-boundary checkpoint/rollback: a job that never reaches
  ready (ready_base + newly_allocated >= min_available) has all its
  allocations rolled back (stmt.Discard, allocate.go:241-245); once ready,
  every further allocation commits immediately (the reference re-opens a
  fresh statement per task after readiness)
- overused   = a job whose queue is overused vs its deserved share at the
  job's start is skipped entirely (allocate.go:126-133)

The step body is branchless (masked jnp.where updates) so XLA compiles one
tight loop body; the only control flow is the fori_loop itself.

Deviations from the reference (documented):
- the reference re-picks the next <namespace, queue, job> after every job
  using *live* DRF/share orderings; the fused solver processes jobs in the
  order fixed at encode time.  The host action can run the solver in
  multiple rounds with re-sorted order to recover the dynamic behavior
  (actions/allocate.py).
- tie-break is deterministic (lowest node index) instead of the reference's
  random-among-max (scheduler_helper.go:201-212).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..arrays.affinity import AffinityArgs
from .resreq import less_equal
from .scoring import ScoreWeights, node_score

NEG = jnp.float32(-3.0e38)


class AllocState(NamedTuple):
    """Carry of the sequential scan.  Allocation-side state (idle, ntasks,
    nports, q_alloc, cnt_alloc) is checkpointed at job boundaries for gang
    rollback; pipeline-side state (pip_*) survives rollback (session-level
    Pipeline)."""

    idle: jnp.ndarray  # [N, R]
    pip_extra: jnp.ndarray  # [N, R] pipelined additions this cycle
    ntasks: jnp.ndarray  # [N]
    pip_ntasks: jnp.ndarray  # [N]
    nports: jnp.ndarray  # [N, PW] uint32
    pip_nports: jnp.ndarray  # [N, PW]
    cnt_alloc: jnp.ndarray  # [E, D] affinity-term counts from allocations
    cnt_pip: jnp.ndarray  # [E, D] affinity-term counts from pipelines
    q_alloc: jnp.ndarray  # [Q, R]
    q_pip: jnp.ndarray  # [Q, R]
    assigned: jnp.ndarray  # [P] node index or -1
    pipelined: jnp.ndarray  # [P] node index or -1
    alloc_cnt: jnp.ndarray  # [J]
    never_ready: jnp.ndarray  # [J] bool
    fit_failed: jnp.ndarray  # [J] bool
    ckpt_idle: jnp.ndarray
    ckpt_ntasks: jnp.ndarray
    ckpt_nports: jnp.ndarray
    ckpt_cnt: jnp.ndarray
    ckpt_q_alloc: jnp.ndarray
    prev_job: jnp.ndarray  # scalar int32
    job_ready: jnp.ndarray  # scalar bool
    job_skip: jnp.ndarray  # scalar bool (overused-skip OR fit-failure abort)
    job_overskip: jnp.ndarray  # scalar bool: skipped for queue overuse only


class AllocResult(NamedTuple):
    assigned: jnp.ndarray  # [P] committed node index or -1
    pipelined: jnp.ndarray  # [P] pipelined node index or -1
    never_ready: jnp.ndarray  # [J] bool (gang discard happened)
    fit_failed: jnp.ndarray  # [J] bool
    idle: jnp.ndarray  # [N, R] final idle
    q_alloc: jnp.ndarray  # [Q, R] final queue allocated (incl. pipelines)


def _sel(c, a, b):
    """Scalar-cond select matching array rank."""
    return jnp.where(c, a, b)


@jax.jit
def solve(
    # node state
    idle0,  # [N, R]
    allocatable,  # [N, R]
    releasing,  # [N, R]
    pipelined0,  # [N, R]
    ntasks0,  # [N]
    max_tasks,  # [N]
    nports0,  # [N, PW]
    # tasks (pre-ordered, job-contiguous)
    req,  # [P, R]
    init_req,  # [P, R]
    task_job,  # [P]
    task_real,  # [P]
    task_ports,  # [P, PW]
    # jobs
    job_queue,  # [J]
    min_available,  # [J]
    ready_base,  # [J]
    # queues
    deserved,  # [Q, R] from the proportion plugin (+inf when disabled)
    q_alloc0,  # [Q, R] allocated at session open
    # predicate + scoring
    static_mask,  # [P, N]
    static_score,  # [P, N] per-(task,node) score computed at encode time
    # (preferred node affinity, topology bonuses); added to the dynamic score
    weights: ScoreWeights,
    eps,  # [R]
    scalar_slot,  # [R]
    aff: AffinityArgs,  # inter-pod affinity/spread count block
) -> AllocResult:
    P, _ = req.shape
    J = min_available.shape[0]
    E, _D = aff.cnt0.shape
    cnt0 = aff.cnt0.astype(jnp.int32)
    term_arange = jnp.arange(E)

    state = AllocState(
        idle=idle0,
        pip_extra=jnp.zeros_like(idle0),
        ntasks=ntasks0,
        pip_ntasks=jnp.zeros_like(ntasks0),
        nports=nports0,
        pip_nports=jnp.zeros_like(nports0),
        cnt_alloc=cnt0,
        cnt_pip=jnp.zeros_like(cnt0),
        q_alloc=q_alloc0,
        q_pip=jnp.zeros_like(q_alloc0),
        assigned=jnp.full((P,), -1, jnp.int32),
        pipelined=jnp.full((P,), -1, jnp.int32),
        alloc_cnt=jnp.zeros((J,), jnp.int32),
        never_ready=jnp.zeros((J,), bool),
        fit_failed=jnp.zeros((J,), bool),
        ckpt_idle=idle0,
        ckpt_ntasks=ntasks0,
        ckpt_nports=nports0,
        ckpt_cnt=cnt0,
        ckpt_q_alloc=q_alloc0,
        prev_job=jnp.int32(-1),
        job_ready=jnp.bool_(True),
        job_skip=jnp.bool_(True),
        job_overskip=jnp.bool_(True),
    )

    def step(t, s: AllocState) -> AllocState:
        tt = jnp.minimum(t, P - 1)
        is_pad = (t >= P) | ~task_real[tt]
        jt = jnp.where(is_pad, jnp.int32(-1), task_job[tt])
        jt_c = jnp.maximum(jt, 0)

        # ---- job boundary: finalize previous job, open new one ----------
        new_job = jt != s.prev_job
        # Discard when the previous job never reached ready — including
        # jobs aborted mid-way by a fit failure (Go breaks the task loop,
        # then commit/discard still runs; allocate.go:189-245).  Jobs that
        # were only *skipped* for queue overuse were never processed: no
        # statement existed, so no discard is reported for them.
        discard = new_job & (s.prev_job >= 0) & ~s.job_ready & ~s.job_overskip
        pj_c = jnp.maximum(s.prev_job, 0)

        idle = _sel(discard, s.ckpt_idle, s.idle)
        ntasks = _sel(discard, s.ckpt_ntasks, s.ntasks)
        nports = _sel(discard, s.ckpt_nports, s.nports)
        cnt_alloc = _sel(discard, s.ckpt_cnt, s.cnt_alloc)
        q_alloc = _sel(discard, s.ckpt_q_alloc, s.q_alloc)
        never_ready = s.never_ready.at[pj_c].set(
            s.never_ready[pj_c] | discard
        )

        # New-job bookkeeping: checkpoint, overuse check, base readiness.
        ckpt_idle = _sel(new_job, idle, s.ckpt_idle)
        ckpt_ntasks = _sel(new_job, ntasks, s.ckpt_ntasks)
        ckpt_nports = _sel(new_job, nports, s.ckpt_nports)
        ckpt_cnt = _sel(new_job, cnt_alloc, s.ckpt_cnt)
        ckpt_q_alloc = _sel(new_job, q_alloc, s.ckpt_q_alloc)
        qj = job_queue[jt_c]
        q_total = q_alloc[qj] + s.q_pip[qj]
        overused = ~less_equal(q_total, deserved[qj], eps, scalar_slot)
        job_skip = _sel(new_job, (jt < 0) | overused, s.job_skip)
        job_overskip = _sel(new_job, (jt < 0) | overused, s.job_overskip)
        job_ready = _sel(
            new_job,
            (jt >= 0) & (ready_base[jt_c] >= min_available[jt_c]),
            s.job_ready,
        )
        prev_job = _sel(new_job, jt, s.prev_job)

        # ---- per-task processing (fully masked) -------------------------
        active = ~is_pad & ~job_skip

        future_idle = idle + releasing - pipelined0 - s.pip_extra
        fit_future = less_equal(
            init_req[tt][None, :], future_idle, eps, scalar_slot
        )
        total_ntasks = ntasks + s.pip_ntasks
        pods_ok = (max_tasks <= 0) | (total_ntasks < max_tasks)
        ports_used = nports | s.pip_nports
        ports_ok = jnp.all((task_ports[tt][None, :] & ports_used) == 0, axis=-1)

        # Inter-pod affinity/anti-affinity + soft spread on the live counts.
        # cval[N, E]: matching-pod count in each node's domain for each term;
        # -1 domains (node lacks the topology label) read as 0.
        cnt = cnt_alloc + s.cnt_pip  # [E, D]
        dome = aff.node_dom[:, aff.term_key]  # [N, E]
        cval = cnt[term_arange[None, :], jnp.maximum(dome, 0)]
        cval = jnp.where(dome >= 0, cval, 0)
        total = jnp.sum(cnt, axis=-1)  # [E]
        req_a = aff.t_req_aff[tt]  # [E]
        req_n = aff.t_req_anti[tt]
        # Upstream self-match rule: an affinity term with no matching pod
        # anywhere passes iff the incoming pod matches its own selector.
        aff_term_ok = (cval > 0) | ((total == 0) & aff.t_matches[tt])[None, :]
        aff_ok = jnp.all(~req_a[None, :] | aff_term_ok, axis=-1)
        anti_ok = jnp.all(~req_n[None, :] | (cval == 0), axis=-1)

        feasible = static_mask[tt] & fit_future & pods_ok & ports_ok
        feasible = feasible & aff_ok & anti_ok
        any_feasible = jnp.any(feasible)

        score = node_score(req[tt], allocatable, idle, weights) + static_score[tt]
        score = score + jnp.sum(
            aff.t_soft[tt][None, :] * cval.astype(jnp.float32), axis=-1
        )
        score = jnp.where(feasible, score, NEG)
        best = jnp.argmax(score).astype(jnp.int32)
        fits_idle = less_equal(init_req[tt], idle[best], eps, scalar_slot)

        do_alloc = active & any_feasible & fits_idle
        do_pipeline = active & any_feasible & ~fits_idle
        no_node = active & ~any_feasible

        # Allocation-side updates (stmt.Allocate).
        radd = jnp.where(do_alloc, req[tt], jnp.zeros_like(req[tt]))
        idle = idle.at[best].add(-radd)
        ntasks = ntasks.at[best].add(do_alloc.astype(jnp.int32))
        nports = nports.at[best].set(
            jnp.where(do_alloc, nports[best] | task_ports[tt], nports[best])
        )
        q_alloc = q_alloc.at[qj].add(radd)
        # Affinity-count update: the placed pod becomes "resident" for every
        # term its labels/job match (predicates plugin Allocate event).
        dom_t = aff.node_dom[best, aff.term_key]  # [E]
        inc_base = aff.t_matches[tt] & (dom_t >= 0)
        cnt_alloc = cnt_alloc.at[term_arange, jnp.maximum(dom_t, 0)].add(
            (inc_base & do_alloc).astype(jnp.int32)
        )
        assigned = s.assigned.at[tt].set(
            jnp.where(do_alloc, best, s.assigned[tt])
        )
        alloc_cnt = s.alloc_cnt.at[jt_c].add(do_alloc.astype(jnp.int32))
        job_ready = job_ready | (
            do_alloc & (ready_base[jt_c] + alloc_cnt[jt_c] >= min_available[jt_c])
        )

        # Once ready, every allocation commits immediately: advance the
        # checkpoint so later rollbacks are no-ops.
        commit = do_alloc & job_ready
        ckpt_idle = _sel(commit, idle, ckpt_idle)
        ckpt_ntasks = _sel(commit, ntasks, ckpt_ntasks)
        ckpt_nports = _sel(commit, nports, ckpt_nports)
        ckpt_cnt = _sel(commit, cnt_alloc, ckpt_cnt)
        ckpt_q_alloc = _sel(commit, q_alloc, ckpt_q_alloc)

        # Pipeline-side updates (ssn.Pipeline; survive discard).
        padd = jnp.where(do_pipeline, req[tt], jnp.zeros_like(req[tt]))
        pip_extra = s.pip_extra.at[best].add(padd)
        pip_ntasks = s.pip_ntasks.at[best].add(do_pipeline.astype(jnp.int32))
        pip_nports = s.pip_nports.at[best].set(
            jnp.where(
                do_pipeline,
                s.pip_nports[best] | task_ports[tt],
                s.pip_nports[best],
            )
        )
        cnt_pip = s.cnt_pip.at[term_arange, jnp.maximum(dom_t, 0)].add(
            (inc_base & do_pipeline).astype(jnp.int32)
        )
        q_pip = s.q_pip.at[qj].add(padd)
        pipelined = s.pipelined.at[tt].set(
            jnp.where(do_pipeline, best, s.pipelined[tt])
        )

        # Fit failure aborts the rest of the job (allocate.go:189-193).
        fit_failed = s.fit_failed.at[jt_c].set(s.fit_failed[jt_c] | no_node)
        job_skip = job_skip | no_node

        return AllocState(
            idle=idle,
            pip_extra=pip_extra,
            ntasks=ntasks,
            pip_ntasks=pip_ntasks,
            nports=nports,
            pip_nports=pip_nports,
            cnt_alloc=cnt_alloc,
            cnt_pip=cnt_pip,
            q_alloc=q_alloc,
            q_pip=q_pip,
            assigned=assigned,
            pipelined=pipelined,
            alloc_cnt=alloc_cnt,
            never_ready=never_ready,
            fit_failed=fit_failed,
            ckpt_idle=ckpt_idle,
            ckpt_ntasks=ckpt_ntasks,
            ckpt_nports=ckpt_nports,
            ckpt_cnt=ckpt_cnt,
            ckpt_q_alloc=ckpt_q_alloc,
            prev_job=prev_job,
            job_ready=job_ready,
            job_skip=job_skip,
            job_overskip=job_overskip,
        )

    state = jax.lax.fori_loop(0, P + 1, step, state)

    # Clear assignments of discarded jobs (their capacity was already
    # restored in-scan at the job boundary).
    jt = jnp.maximum(task_job, 0)
    discarded = state.never_ready[jt] & task_real
    assigned = jnp.where(discarded, -1, state.assigned)

    return AllocResult(
        assigned=assigned,
        pipelined=state.pipelined,
        never_ready=state.never_ready,
        fit_failed=state.fit_failed,
        idle=state.idle,
        q_alloc=state.q_alloc + state.q_pip,
    )
