"""The allocate solver: Volcano's hot loop as one jitted XLA program.

Replaces the namespace->queue->job->task object loop of
``pkg/scheduler/actions/allocate/allocate.go:40-250`` (predicate fan-out,
score fan-out, best-node selection, capacity update, gang commit/discard)
with a single sequential scan over pre-ordered tasks carrying dense cluster
state.  Semantics preserved per task step:

- predicate  = bitset predicates evaluated in-loop against the node tables
  (selector / required node-affinity / taints / ready — the predicates
  plugin, predicates.go:144-293) AND InitResreq fits FutureIdle
  (allocate.go:98-105) AND pod-count AND host-port AND inter-pod
  (anti)affinity on live per-(term, domain) count tensors
  (predicates.go:111-136,272-291)
- score      = additive scorers on current node state (allocate.go:202)
  plus preferred node affinity and soft pod-affinity/spread terms
- selection  = masked argmax (SelectBestNode; first-index tie-break instead
  of random-among-max)
- fits Idle  -> allocate: idle/queue/pod-count/ports updated (stmt.Allocate)
- else       -> pipeline: FutureIdle reduced, effects NOT rolled back on
  discard (ssn.Pipeline is session-level; allocate.go:224-232)
- a task with no feasible node aborts the remaining tasks of its job
  (allocate.go:189-193 break)
- gang       = job-boundary rollback: a job that never reaches ready
  (ready_base + newly_allocated >= min_available) has all its allocations
  undone (stmt.Discard, allocate.go:241-245).  Rollback replays the job's
  own task rows backwards (an undo log over at most the job's size) instead
  of checkpointing full [N, R] arrays — the difference between O(job) work
  on the rare discard and O(N*R) copies on EVERY step.

Nothing of size [P, N] is ever materialized: predicates and scores for one
task row are computed in-loop from [N, *]-sized tables, so the solver
scales to 50k nodes x 500k tasks (BASELINE config 5) where a dense mask
alone would be 2.5e10 entries.

Deviations from the reference (documented):
- the reference re-picks the next <namespace, queue, job> after every job
  using *live* DRF/share orderings; the fused solver processes jobs in the
  order fixed at encode time.  The host action can run the solver in
  multiple rounds with re-sorted order to recover the dynamic behavior
  (actions/allocate.py).
- tie-break is deterministic (lowest node index) instead of the reference's
  random-among-max (scheduler_helper.go:201-212).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..arrays.affinity import AffinityArgs
from .resreq import less_equal
from .scoring import ScoreWeights, node_score

NEG = jnp.float32(-3.0e38)


class SolveNodes(NamedTuple):
    """Node-side solver inputs (all leading dim N)."""

    idle: jnp.ndarray  # [N, R]
    allocatable: jnp.ndarray  # [N, R]
    releasing: jnp.ndarray  # [N, R]
    pipelined: jnp.ndarray  # [N, R]
    ntasks: jnp.ndarray  # [N] int32
    max_tasks: jnp.ndarray  # [N] int32 (0 = unlimited)
    ports: jnp.ndarray  # [N, PW] uint32
    ready: jnp.ndarray  # [N] bool (ready & schedulable & real)
    label_bits: jnp.ndarray  # [N, LW] uint32
    taint_bits: jnp.ndarray  # [N, TW] uint32


class SolveTasks(NamedTuple):
    """Task-side solver inputs (leading dim P, job-contiguous order)."""

    req: jnp.ndarray  # [P, R]
    init_req: jnp.ndarray  # [P, R]
    job: jnp.ndarray  # [P] int32
    real: jnp.ndarray  # [P] bool
    ports: jnp.ndarray  # [P, PW] uint32
    sel_bits: jnp.ndarray  # [P, LW] node-selector label pairs (AND)
    aff_bits: jnp.ndarray  # [P, A, LW] required node-affinity alternatives
    aff_terms: jnp.ndarray  # [P] int32 number of alternatives (0 = none)
    tol_bits: jnp.ndarray  # [P, TW] tolerated taints
    pref_bits: jnp.ndarray  # [P, AP, LW] preferred node-affinity terms
    pref_w: jnp.ndarray  # [P, AP] float32 term scores (pre-normalized *10)


class SolveJobs(NamedTuple):
    queue: jnp.ndarray  # [J] int32
    min_available: jnp.ndarray  # [J] int32
    ready_base: jnp.ndarray  # [J] int32


class SolveQueues(NamedTuple):
    deserved: jnp.ndarray  # [Q, R] (+inf when proportion disabled)
    allocated: jnp.ndarray  # [Q, R] at session open


class AllocState(NamedTuple):
    """Carry of the sequential scan.  Pipeline-side state (pip_*) survives
    gang rollback (session-level Pipeline); allocation-side state is undone
    via the per-task undo log at discard."""

    idle: jnp.ndarray  # [N, R]
    pip_extra: jnp.ndarray  # [N, R] pipelined additions this cycle
    ntasks: jnp.ndarray  # [N]
    pip_ntasks: jnp.ndarray  # [N]
    nports: jnp.ndarray  # [N, PW] uint32
    pip_nports: jnp.ndarray  # [N, PW]
    cnt_alloc: jnp.ndarray  # [E, D] affinity-term counts from allocations
    cnt_pip: jnp.ndarray  # [E, D] affinity-term counts from pipelines
    q_alloc: jnp.ndarray  # [Q, R]
    q_pip: jnp.ndarray  # [Q, R]
    assigned: jnp.ndarray  # [P] node index or -1
    pipelined: jnp.ndarray  # [P] node index or -1
    alloc_cnt: jnp.ndarray  # [J]
    never_ready: jnp.ndarray  # [J] bool
    fit_failed: jnp.ndarray  # [J] bool
    job_start: jnp.ndarray  # scalar int32: first task row of current job
    prev_job: jnp.ndarray  # scalar int32
    job_ready: jnp.ndarray  # scalar bool
    job_skip: jnp.ndarray  # scalar bool (overused-skip OR fit-failure abort)
    job_overskip: jnp.ndarray  # scalar bool: skipped for queue overuse only


class AllocResult(NamedTuple):
    assigned: jnp.ndarray  # [P] committed node index or -1
    pipelined: jnp.ndarray  # [P] pipelined node index or -1
    never_ready: jnp.ndarray  # [J] bool (gang discard happened)
    fit_failed: jnp.ndarray  # [J] bool
    idle: jnp.ndarray  # [N, R] final idle
    q_alloc: jnp.ndarray  # [Q, R] final queue allocated (incl. pipelines)
    iters: jnp.ndarray = None  # [] total attempt iterations (diagnostics)
    # Two-phase wave solve only (ops/wave.py): shortlist-fallback
    # rescore counts by reason — profiles whose candidate shortlist ran
    # dry (exhausted) vs required-(anti)affinity profiles whose live
    # domain landscape drifted from the solve-start counts the
    # shortlist was built on.  None from the sequential solver.
    fb_exhausted: jnp.ndarray = None  # [] int32
    fb_affinity: jnp.ndarray = None  # [] int32


def _subset(bits_row, table):
    """[..., W] & [N, W] -> [..., N]: row bits all present in table rows."""
    missing = bits_row[..., None, :] & ~table
    return jnp.all(missing == 0, axis=-1)


def solve_inputs(arrays, deserved=None, q_alloc0=None):
    """Build the (nodes, tasks, jobs, queues) solver groups from encoded
    ClusterArrays.  ``deserved`` defaults to +inf (proportion gating off)."""
    import numpy as np

    n, t, j, q = arrays.nodes, arrays.tasks, arrays.jobs, arrays.queues
    Q, R = q.capability.shape
    if deserved is None:
        deserved = np.full((Q, R), 3.0e38, np.float32)
    if q_alloc0 is None:
        q_alloc0 = q.allocated
    return (
        SolveNodes(
            idle=n.idle,
            allocatable=n.allocatable,
            releasing=n.releasing,
            pipelined=n.pipelined,
            ntasks=n.num_tasks,
            max_tasks=n.max_tasks,
            ports=n.port_bits,
            ready=n.ready & n.real,
            label_bits=n.label_bits,
            taint_bits=n.taint_bits,
        ),
        SolveTasks(
            req=t.req,
            init_req=t.init_req,
            job=t.job,
            real=t.real,
            ports=t.port_bits,
            sel_bits=t.sel_bits,
            aff_bits=t.aff_bits,
            aff_terms=t.aff_terms,
            tol_bits=t.tol_bits,
            pref_bits=t.pref_bits,
            pref_w=t.pref_w,
        ),
        SolveJobs(
            queue=j.queue,
            min_available=j.min_available,
            ready_base=j.ready_base,
        ),
        SolveQueues(
            deserved=np.asarray(deserved, np.float32),
            allocated=np.asarray(q_alloc0, np.float32),
        ),
    )


@jax.jit
def solve(
    nodes: SolveNodes,
    tasks: SolveTasks,
    jobs: SolveJobs,
    queues: SolveQueues,
    weights: ScoreWeights,
    eps,  # [R]
    scalar_slot,  # [R]
    aff: AffinityArgs,  # inter-pod affinity/spread count block
    extra_ok=None,  # optional [P, N] bool: custom-plugin predicate verdicts
    extra_score=None,  # optional [P, N] f32: custom-plugin node scores
) -> AllocResult:
    P, _ = tasks.req.shape
    J = jobs.min_available.shape[0]
    A = tasks.aff_bits.shape[1]
    E, _D = aff.cnt0.shape
    cnt0 = aff.cnt0.astype(jnp.int32)
    term_arange = jnp.arange(E)
    node_dom_t = aff.node_dom[:, aff.term_key]  # [N, E]

    state = AllocState(
        idle=nodes.idle,
        pip_extra=jnp.zeros_like(nodes.idle),
        ntasks=nodes.ntasks,
        pip_ntasks=jnp.zeros_like(nodes.ntasks),
        nports=nodes.ports,
        pip_nports=jnp.zeros_like(nodes.ports),
        cnt_alloc=cnt0,
        cnt_pip=jnp.zeros_like(cnt0),
        q_alloc=queues.allocated,
        q_pip=jnp.zeros_like(queues.allocated),
        assigned=jnp.full((P,), -1, jnp.int32),
        pipelined=jnp.full((P,), -1, jnp.int32),
        alloc_cnt=jnp.zeros((J,), jnp.int32),
        never_ready=jnp.zeros((J,), bool),
        fit_failed=jnp.zeros((J,), bool),
        job_start=jnp.int32(0),
        prev_job=jnp.int32(-1),
        job_ready=jnp.bool_(True),
        job_skip=jnp.bool_(True),
        job_overskip=jnp.bool_(True),
    )

    def _undo_job(start, end, pj_c, s: AllocState):
        """Roll back the allocations of job rows [start, end) (stmt.Discard,
        statement.go:324-367).  O(job size), touching only assigned rows."""
        qj = jobs.queue[pj_c]

        def body(u, carry):
            idle, ntasks, nports, cnt_alloc, q_alloc = carry
            n = s.assigned[u]
            did = n >= 0
            n_c = jnp.maximum(n, 0)
            radd = jnp.where(did, tasks.req[u], jnp.zeros_like(tasks.req[u]))
            idle = idle.at[n_c].add(radd)
            ntasks = ntasks.at[n_c].add(jnp.where(did, -1, 0))
            # Port bits were disjoint from pre-existing at allocate time, so
            # AND-NOT is an exact inverse of the OR.
            nports = nports.at[n_c].set(
                jnp.where(did, nports[n_c] & ~tasks.ports[u], nports[n_c])
            )
            dom_u = node_dom_t[n_c]  # [E]
            dec = aff.t_matches[u] & (dom_u >= 0) & did
            cnt_alloc = cnt_alloc.at[
                term_arange, jnp.maximum(dom_u, 0)
            ].add(-dec.astype(jnp.int32))
            q_alloc = q_alloc.at[qj].add(-radd)
            return (idle, ntasks, nports, cnt_alloc, q_alloc)

        return jax.lax.fori_loop(
            start, end, body,
            (s.idle, s.ntasks, s.nports, s.cnt_alloc, s.q_alloc),
        )

    def step(t, s: AllocState) -> AllocState:
        tt = jnp.minimum(t, P - 1)
        is_pad = (t >= P) | ~tasks.real[tt]
        jt = jnp.where(is_pad, jnp.int32(-1), tasks.job[tt])
        jt_c = jnp.maximum(jt, 0)

        # ---- job boundary: finalize previous job, open new one ----------
        new_job = jt != s.prev_job
        # Discard when the previous job never reached ready — including
        # jobs aborted mid-way by a fit failure (Go breaks the task loop,
        # then commit/discard still runs; allocate.go:189-245).  Jobs that
        # were only *skipped* for queue overuse were never processed: no
        # statement existed, so no discard is reported for them.
        discard = new_job & (s.prev_job >= 0) & ~s.job_ready & ~s.job_overskip
        pj_c = jnp.maximum(s.prev_job, 0)

        idle, ntasks, nports, cnt_alloc, q_alloc = jax.lax.cond(
            discard,
            lambda: _undo_job(s.job_start, t, pj_c, s),
            lambda: (s.idle, s.ntasks, s.nports, s.cnt_alloc, s.q_alloc),
        )
        never_ready = s.never_ready.at[pj_c].set(
            s.never_ready[pj_c] | discard
        )

        # New-job bookkeeping: overuse check, base readiness, undo-log start.
        job_start = jnp.where(new_job, t, s.job_start)
        qj = jobs.queue[jt_c]
        q_total = q_alloc[qj] + s.q_pip[qj]
        overused = ~less_equal(q_total, queues.deserved[qj], eps, scalar_slot)
        job_skip = jnp.where(new_job, (jt < 0) | overused, s.job_skip)
        job_overskip = jnp.where(
            new_job, (jt < 0) | overused, s.job_overskip
        )
        job_ready = jnp.where(
            new_job,
            (jt >= 0) & (jobs.ready_base[jt_c] >= jobs.min_available[jt_c]),
            s.job_ready,
        )
        prev_job = jnp.where(new_job, jt, s.prev_job)

        # ---- per-task processing (fully masked) -------------------------
        active = ~is_pad & ~job_skip

        # Static predicates, in-loop from the bitset tables ([N]-sized).
        ok = nodes.ready & _subset(tasks.sel_bits[tt], nodes.label_bits)
        term_ok = _subset(tasks.aff_bits[tt], nodes.label_bits)  # [A, N]
        n_terms = tasks.aff_terms[tt]
        term_real = jnp.arange(A) < n_terms  # [A]
        ok &= jnp.any(term_ok & term_real[:, None], axis=0) | (n_terms == 0)
        untol = nodes.taint_bits & ~tasks.tol_bits[tt][None, :]
        ok &= jnp.all(untol == 0, axis=-1)

        future_idle = idle + nodes.releasing - nodes.pipelined - s.pip_extra
        fit_future = less_equal(
            tasks.init_req[tt][None, :], future_idle, eps, scalar_slot
        )
        total_ntasks = ntasks + s.pip_ntasks
        pods_ok = (nodes.max_tasks <= 0) | (total_ntasks < nodes.max_tasks)
        ports_used = nports | s.pip_nports
        ports_ok = jnp.all(
            (tasks.ports[tt][None, :] & ports_used) == 0, axis=-1
        )

        # Inter-pod affinity/anti-affinity + soft spread on the live counts.
        # cval[N, E]: matching-pod count in each node's domain for each term;
        # -1 domains (node lacks the topology label) read as 0.
        cnt = cnt_alloc + s.cnt_pip  # [E, D]
        cval = cnt[term_arange[None, :], jnp.maximum(node_dom_t, 0)]
        cval = jnp.where(node_dom_t >= 0, cval, 0)
        total = jnp.sum(cnt, axis=-1)  # [E]
        req_a = aff.t_req_aff[tt]  # [E]
        req_n = aff.t_req_anti[tt]
        # Upstream self-match rule: an affinity term with no matching pod
        # anywhere passes iff the incoming pod matches its own selector.
        aff_term_ok = (cval > 0) | ((total == 0) & aff.t_matches[tt])[None, :]
        aff_ok = jnp.all(~req_a[None, :] | aff_term_ok, axis=-1)
        anti_ok = jnp.all(~req_n[None, :] | (cval == 0), axis=-1)

        feasible = ok & fit_future & pods_ok & ports_ok & aff_ok & anti_ok
        if extra_ok is not None:
            # Custom-plugin predicate verdicts (session add_predicate_fn /
            # add_device_mask_fn contributions from out-of-tree plugins).
            feasible &= extra_ok[tt]
        any_feasible = jnp.any(feasible)

        score = node_score(tasks.req[tt], nodes.allocatable, idle, weights)
        if extra_score is not None:
            score = score + extra_score[tt]
        # Preferred node affinity (CalculateNodeAffinityPriority): term
        # scores are pre-normalized to *10 at encode; the weight knob is
        # applied here so config controls it.
        pref_match = _subset(tasks.pref_bits[tt], nodes.label_bits)  # [AP, N]
        score = score + weights.node_affinity_weight * jnp.sum(
            pref_match * tasks.pref_w[tt][:, None], axis=0
        )
        score = score + jnp.sum(
            aff.t_soft[tt][None, :] * cval.astype(jnp.float32), axis=-1
        )
        score = jnp.where(feasible, score, NEG)
        best = jnp.argmax(score).astype(jnp.int32)
        fits_idle = less_equal(tasks.init_req[tt], idle[best], eps, scalar_slot)

        do_alloc = active & any_feasible & fits_idle
        do_pipeline = active & any_feasible & ~fits_idle
        no_node = active & ~any_feasible

        # Allocation-side updates (stmt.Allocate).
        radd = jnp.where(
            do_alloc, tasks.req[tt], jnp.zeros_like(tasks.req[tt])
        )
        idle = idle.at[best].add(-radd)
        ntasks = ntasks.at[best].add(do_alloc.astype(jnp.int32))
        nports = nports.at[best].set(
            jnp.where(do_alloc, nports[best] | tasks.ports[tt], nports[best])
        )
        # Affinity-count update: the placed pod becomes "resident" for every
        # term its labels/job match (predicates plugin Allocate event).
        dom_t = node_dom_t[best]  # [E]
        inc_base = aff.t_matches[tt] & (dom_t >= 0)
        cnt_alloc = cnt_alloc.at[term_arange, jnp.maximum(dom_t, 0)].add(
            (inc_base & do_alloc).astype(jnp.int32)
        )
        q_alloc = q_alloc.at[qj].add(radd)
        assigned = s.assigned.at[tt].set(
            jnp.where(do_alloc, best, s.assigned[tt])
        )
        alloc_cnt = s.alloc_cnt.at[jt_c].add(do_alloc.astype(jnp.int32))
        job_ready = job_ready | (
            do_alloc
            & (jobs.ready_base[jt_c] + alloc_cnt[jt_c]
               >= jobs.min_available[jt_c])
        )

        # Pipeline-side updates (ssn.Pipeline; survive discard).
        padd = jnp.where(
            do_pipeline, tasks.req[tt], jnp.zeros_like(tasks.req[tt])
        )
        pip_extra = s.pip_extra.at[best].add(padd)
        pip_ntasks = s.pip_ntasks.at[best].add(do_pipeline.astype(jnp.int32))
        pip_nports = s.pip_nports.at[best].set(
            jnp.where(
                do_pipeline,
                s.pip_nports[best] | tasks.ports[tt],
                s.pip_nports[best],
            )
        )
        cnt_pip = s.cnt_pip.at[term_arange, jnp.maximum(dom_t, 0)].add(
            (inc_base & do_pipeline).astype(jnp.int32)
        )
        q_pip = s.q_pip.at[qj].add(padd)
        pipelined = s.pipelined.at[tt].set(
            jnp.where(do_pipeline, best, s.pipelined[tt])
        )

        # Fit failure aborts the rest of the job (allocate.go:189-193).
        fit_failed = s.fit_failed.at[jt_c].set(s.fit_failed[jt_c] | no_node)
        job_skip = job_skip | no_node

        return AllocState(
            idle=idle,
            pip_extra=pip_extra,
            ntasks=ntasks,
            pip_ntasks=pip_ntasks,
            nports=nports,
            pip_nports=pip_nports,
            cnt_alloc=cnt_alloc,
            cnt_pip=cnt_pip,
            q_alloc=q_alloc,
            q_pip=q_pip,
            assigned=assigned,
            pipelined=pipelined,
            alloc_cnt=alloc_cnt,
            never_ready=never_ready,
            fit_failed=fit_failed,
            job_start=job_start,
            prev_job=prev_job,
            job_ready=job_ready,
            job_skip=job_skip,
            job_overskip=job_overskip,
        )

    state = jax.lax.fori_loop(0, P + 1, step, state)

    # Clear assignments of discarded jobs (their capacity was already
    # restored in-scan at the job boundary).
    jt = jnp.maximum(tasks.job, 0)
    discarded = state.never_ready[jt] & tasks.real
    assigned = jnp.where(discarded, -1, state.assigned)

    return AllocResult(
        assigned=assigned,
        pipelined=state.pipelined,
        never_ready=state.never_ready,
        fit_failed=state.fit_failed,
        idle=state.idle,
        q_alloc=state.q_alloc + state.q_pip,
    )
