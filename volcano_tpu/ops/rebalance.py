"""Rebalance planner kernels: gang-aware defragmentation scoring.

The forward-packing lanes (allocate/backfill) and the priority-triggered
eviction lanes (preempt/reclaim) never *un*-fragment a cluster: once
small pods are sprinkled across every node, a large gang can be starved
forever even though the cluster-wide idle sum would cover it many times
over.  The reference family solves this with a descheduler; Gavel
(PAPERS.md, arXiv:2008.09213) recomputes whole-cluster placements each
round and treats the implied migrations as first-class.  This module is
the TPU-native version of that lever's *scoring* half:

- ``frag_scores`` — one jitted pass over the node planes producing, per
  node: a fragmentation score (idle-rich but unable to host any task of
  the starved gang's profiles), the gang-task capacity of the node's
  idle as-is, and the capacity after hypothetically draining the node's
  migratable pods.  Runs on the same device-resident planes the wave
  solver consumes (idle / allocatable and the evictable plane built
  from the mirror), so scoring 50k nodes is one kernel dispatch, not a
  host walk.
- ``select_drain_set`` — the deterministic host-side greedy over the
  fetched score vectors: cheapest-to-drain nodes first, per-PodGroup
  disruption budgets charged as nodes are taken, stopping as soon as
  the freed capacity covers the gang's outstanding need (or the drain
  cap is hit).

The *placement* half of the plan is not re-derived here: the fast path
runs a what-if ``solve_wave`` over the hypothetically drained cluster
(``fastpath.FastCycle._rebalance``), so the plan solve rides the exact
jit (two-phase shortlists included) the live allocate lane uses.

``oracle.oracle_rebalance`` is the deliberately naive Go-shaped
re-implementation of both halves; tests require agreement.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

F = np.float32
I = np.int32


class FragScores(NamedTuple):
    """Per-node planner vectors (device arrays until fetched)."""

    frag: jnp.ndarray       # [N] f32 fragmentation score in [0, 1]
    fit_now: jnp.ndarray    # [N] i32 gang tasks the node's idle holds now
    fit_freed: jnp.ndarray  # [N] i32 gang tasks after draining evictables


# The plan container lives with the engine since ISSUE 11:
# ``volcano_tpu.whatif.WhatIfPlan`` (action-agnostic — rebalance builds
# it with ``resolve_victims=True`` so victims re-enter the solve).


@partial(jax.jit, static_argnames=())
def frag_scores(idle, allocatable, ready, evictable, prof_req, eps):
    """Fragmentation planes for one starved gang.

    ``idle``/``allocatable``/``evictable``: [N, R] f32 node planes
    (evictable = summed requests of the node's migratable Running pods);
    ``ready``: [N] bool; ``prof_req``: [U, R] f32 per-profile init
    requests of the gang's pending tasks; ``eps``: [R] f32 tolerance.

    Returns ``FragScores``.  Definitions (mirrored exactly by
    ``oracle.oracle_rebalance``):

    - per (node, profile) fit count = min over requested slots of
      ``floor((plane + eps) / req)``, 0 when any requested slot is
      absent; ``fit_*`` takes the MAX over profiles (the planner frees
      whole nodes, so "how many of the easiest profile fit" is the
      capacity that matters).
    - ``frag`` = mean idle fraction over provisioned slots, zeroed on
      nodes that are not ready, hold no idle, or can already host a
      gang task (their idle is not stranded).
    """
    idle = idle.astype(jnp.float32)
    alloc = allocatable.astype(jnp.float32)
    ev = evictable.astype(jnp.float32)
    req = prof_req.astype(jnp.float32)
    eps = eps.astype(jnp.float32)

    requested = req > eps[None, :]  # [U, R]

    def fit_of(plane):
        # [N, U, R] per-slot counts; non-requested slots are inert.
        per = jnp.floor(
            (plane[:, None, :] + eps[None, None, :])
            / jnp.maximum(req[None, :, :], 1e-9)
        )
        per = jnp.where(requested[None, :, :], per, jnp.float32(2 ** 30))
        cnt = jnp.min(per, axis=-1)  # [N, U]
        cnt = jnp.where(jnp.any(requested, axis=-1)[None, :], cnt, 0.0)
        return jnp.max(jnp.maximum(cnt, 0.0), axis=-1).astype(jnp.int32)

    fit_now = fit_of(idle)
    fit_freed = fit_of(idle + ev)

    provisioned = alloc > eps[None, :]
    frac = jnp.where(provisioned,
                     jnp.clip(idle / jnp.maximum(alloc, 1e-9), 0.0, 1.0),
                     0.0)
    nprov = jnp.maximum(provisioned.sum(axis=-1), 1)
    idle_frac = frac.sum(axis=-1) / nprov
    has_idle = jnp.any(idle > eps[None, :], axis=-1)
    frag = jnp.where(ready & has_idle & (fit_now == 0), idle_frac, 0.0)
    return FragScores(frag=frag.astype(jnp.float32),
                      fit_now=fit_now, fit_freed=fit_freed)


def select_drain_set(
    frag: np.ndarray,
    fit_now: np.ndarray,
    fit_freed: np.ndarray,
    need: int,
    victims_by_node: Sequence[Sequence[int]],
    victim_group: Dict[int, str],
    budget_left: Dict[str, int],
    drain_cap: int,
) -> Tuple[List[int], bool]:
    """Deterministic greedy drain-set selection over fetched planes.

    ``victims_by_node[n]``: migratable Running rows resident on node n;
    ``victim_group[row]``: PodGroup uid of a victim row;
    ``budget_left[uid]``: remaining disruption budget per group (plans
    in flight already subtracted).  Mutates nothing.

    A node is a candidate iff draining it gains gang capacity
    (``fit_freed > fit_now``) and it holds at least one victim.
    Candidates are taken cheapest-first — key ``(len(victims), -gain,
    node)`` — each charged against its victims' group budgets; a node
    whose victims would overdraw any budget is skipped.  Selection
    stops when the accumulated gain covers ``need`` or ``drain_cap``
    nodes are taken.

    Returns ``(nodes, budget_blocked)``: the chosen node list (empty
    when the need cannot be covered) and whether budget exhaustion —
    rather than capacity or the drain cap — is what blocked an
    otherwise sufficient plan (i.e. the same greedy with unlimited
    budgets, under the same cap, would have covered the need).
    """
    gain = fit_freed.astype(np.int64) - fit_now.astype(np.int64)
    cand = [
        int(n) for n in np.flatnonzero((gain > 0) & (frag > 0.0))
        if victims_by_node[int(n)]
    ]
    cand.sort(key=lambda n: (len(victims_by_node[n]), -int(gain[n]), n))
    left = dict(budget_left)
    chosen: List[int] = []
    acc = 0
    skipped_for_budget = False
    for n in cand:
        if acc >= need or len(chosen) >= drain_cap:
            break
        charges: Dict[str, int] = {}
        for row in victims_by_node[n]:
            g = victim_group[row]
            charges[g] = charges.get(g, 0) + 1
        if any(left.get(g, 0) < c for g, c in charges.items()):
            skipped_for_budget = True
            continue
        for g, c in charges.items():
            left[g] = left.get(g, 0) - c
        chosen.append(n)
        acc += int(gain[n])
    if acc < need:
        # Distinguish "budgets blocked it" from "capacity / drain cap
        # cannot cover" for the plans_total outcome label: re-run the
        # same greedy with unlimited budgets under the same cap.
        unbudgeted = int(sum(int(gain[n]) for n in cand[:drain_cap]))
        return [], bool(skipped_for_budget and unbudgeted >= need)
    return chosen, False
