"""Victim-selection kernels: device-native preempt + reclaim scoring.

The host-side eviction walk (``fastpath_evict.py``) reproduces the
reference's sequential victim semantics exactly, but pays O(preemptor x
node) Python per cycle — the last hot lanes with no device lane at all.
This module is the planning half of the device-native alternative
(ISSUE 11, docs/preempt_reclaim.md): the what-if engine
(``volcano_tpu/whatif.py``) proves the resulting plan with the exact
allocate jit before anything is evicted.

- ``victim_scores`` — one jitted pass over the solver's existing planes
  (job priority, queue share = allocated/deserved, per-victim request
  rows, node ids) producing the tier-gated eligibility mask, the
  deterministic eviction order (an integer lexsort: job priority
  ascending, youngest victim first, input index tie-break — the same
  inverted task-order the host walk pops), and the per-node
  evictable-capacity plane (a scatter-add of eligible requests).
  Preempt gates victims to the preemptor's queue at strictly lower job
  priority; reclaim gates to OTHER queues that are ``Reclaimable`` and
  currently over their deserved share.  Critical (conformance-exempt)
  pods are excluded on both paths.
- ``select_victims`` — the deterministic host-side greedy over the
  fetched planes: victims taken in kernel order, each charged against
  its PodGroup's remaining disruption budget and its job's gang floor
  (a victim whose eviction would push its job below ``minAvailable``
  is skipped unless ``minAvailable == 1``), reclaim victims
  additionally bounded by their queue's deserved-share slack
  (proportion semantics: a queue is never reclaimed below deserved).
  Selection stops once the freed capacity covers the starved gang's
  outstanding need (measured in whole gang tasks via the shared
  ``fit_counts`` spec) or the wave cap is hit.

``oracle.oracle_preempt`` / ``oracle.oracle_reclaim`` are the
deliberately naive Go-shaped re-derivations of both halves; tests
require exact agreement (tests/test_whatif_preempt.py).
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

F = np.float32
I = np.int32

# Sentinel above which a deserved slot means "uncapped" (matches the
# 3.0e38 fill FastCycle._proportion writes for capless queues/slots).
DESERVED_UNCAPPED = 1.0e30
# Relative tolerance on the overuse test (f32 share arithmetic).
SHARE_TOL = 1e-6

PREEMPT = 0
RECLAIM = 1


class VictimPlanes(NamedTuple):
    """Fetched-together kernel outputs (device arrays until fetched)."""

    eligible: jnp.ndarray   # [V] bool tier-gated victim mask
    order: jnp.ndarray      # [V] i32 eviction order (eligible first)
    evictable: jnp.ndarray  # [N, R] f32 per-node eligible request sum
    q_share: jnp.ndarray    # [Q] f32 queue share = max alloc/deserved


def queue_shares(q_alloc: np.ndarray, q_deserved: np.ndarray) -> np.ndarray:
    """[Q] share plane from the cycle's queue planes: max over capped
    slots of allocated/deserved (0 when no slot is capped).  Host-side
    mirror of the kernel's formula so planners can pre-gate targets
    without a device round trip."""
    q_alloc = np.asarray(q_alloc, F)
    q_des = np.asarray(q_deserved, F)
    capped = q_des < DESERVED_UNCAPPED
    with np.errstate(divide="ignore", invalid="ignore"):
        ratio = np.where(capped, q_alloc / np.maximum(q_des, 1e-9), 0.0)
    return ratio.max(axis=-1).astype(F) if ratio.size else \
        np.zeros(len(q_alloc), F)


@jax.jit
def victim_scores(v_ok, v_jprio, v_crank, v_tie, v_queue, v_node, v_req,
                  p_prio, p_queue, q_alloc, q_deserved, q_reclaimable,
                  mode, node_zero):
    """Tier-gated victim eligibility + eviction order + evictable plane.

    ``v_ok``: [V] bool base validity (Running resident, non-empty
    request, not critical, job known, not the starved gang itself —
    the conformance tier and the structural filters, precomputed
    host-side); ``v_jprio``/``v_crank``/``v_tie``: [V] i32 job
    priority, creation rank (larger = younger) and deterministic
    tie-break; ``v_queue``/``v_node``: [V] i32; ``v_req``: [V, R] f32;
    ``p_prio``/``p_queue``: scalars for the preemptor gang;
    ``q_alloc``/``q_deserved``: [Q, R] f32 queue planes (the share is
    derived in-kernel — the queue-share tier reads the same planes the
    proportion plugin gates on); ``q_reclaimable``: [Q] bool;
    ``mode``: 0 = preempt, 1 = reclaim; ``node_zero``: [N, R] f32 zeros
    template fixing the scatter shape.

    Ineligible rows sort to the tail of ``order``; within the eligible
    prefix the order is (job priority asc, creation rank desc, tie
    asc) — lowest-priority youngest victims evict first, matching the
    host walk's inverted task-order pop.
    """
    v_ok = v_ok.astype(bool)
    v_jprio = v_jprio.astype(jnp.int32)
    capped = q_deserved < jnp.float32(DESERVED_UNCAPPED)
    ratio = jnp.where(capped,
                      q_alloc / jnp.maximum(q_deserved, 1e-9), 0.0)
    q_share = jnp.max(ratio, axis=-1).astype(jnp.float32)  # [Q]
    vq = jnp.clip(v_queue, 0, q_share.shape[0] - 1)
    same_q = v_queue == p_queue
    lower_prio = v_jprio < p_prio
    overused = q_share[vq] > jnp.float32(1.0 + SHARE_TOL)
    eligible = jnp.where(
        mode == PREEMPT,
        v_ok & same_q & lower_prio,
        v_ok & ~same_q & q_reclaimable[vq] & overused,
    )
    big = jnp.int32(np.iinfo(np.int32).max)
    prio_key = jnp.where(eligible, v_jprio, big)
    order = jnp.lexsort(
        (v_tie, -v_crank, prio_key, (~eligible).astype(jnp.int32))
    ).astype(jnp.int32)
    evictable = node_zero.at[jnp.clip(v_node, 0, node_zero.shape[0] - 1)]\
        .add(jnp.where(eligible[:, None], v_req, 0.0))
    return VictimPlanes(eligible=eligible, order=order,
                        evictable=evictable, q_share=q_share)


def fit_counts(plane: np.ndarray, prof_req: np.ndarray,
               eps: np.ndarray) -> np.ndarray:
    """[N] whole gang tasks each node row of ``plane`` can host: per
    (node, profile) the min over requested slots of
    ``floor((plane + eps) / req)`` (0 when the profile requests
    nothing), max over profiles — the same fit spec as
    ``ops.rebalance.frag_scores`` so the two planners agree on what "a
    freed slot" means."""
    plane = np.atleast_2d(np.asarray(plane, F))
    req = np.asarray(prof_req, F)
    eps = np.asarray(eps, F)
    requested = req > eps[None, :]  # [U, R]
    per = np.floor(
        (plane[:, None, :] + eps[None, None, :])
        / np.maximum(req[None, :, :], 1e-9)
    )
    per = np.where(requested[None, :, :], per, np.float32(2 ** 30))
    cnt = per.min(axis=-1)
    cnt = np.where(requested.any(axis=-1)[None, :], cnt, 0.0)
    return np.maximum(cnt, 0.0).max(axis=-1).astype(np.int64)


class VictimSelection(NamedTuple):
    """``select_victims`` verdict (host-side, deterministic)."""

    chosen: List[int]      # indices into the victim arrays, evict order
    feasible: bool         # freed capacity covers the need
    budget_blocked: bool   # budgets (not capacity/cap) blocked the plan
    gain: int              # gang tasks the chosen drain frees


def select_victims(
    order: np.ndarray,
    eligible: np.ndarray,
    v_node: np.ndarray,
    v_req: np.ndarray,
    v_job: np.ndarray,
    v_group: Sequence[str],
    v_queue: np.ndarray,
    need: int,
    idle: np.ndarray,
    evictable: np.ndarray,
    prof_req: np.ndarray,
    eps: np.ndarray,
    j_ready: np.ndarray,
    j_minav: np.ndarray,
    budget_left: Dict[str, int],
    cap: int,
    q_alloc: Optional[np.ndarray] = None,
    q_deserved: Optional[np.ndarray] = None,
) -> VictimSelection:
    """Greedy ranked-victim selection under disruption budgets.

    Walks victims in kernel ``order``; a victim is taken iff its node
    can gain gang capacity at all (draining every eligible victim there
    beats the node's as-is fit), its job stays at/above
    ``minAvailable`` after the eviction (or ``minAvailable == 1`` —
    the gang tier), its PodGroup's remaining budget covers one more
    disruption, and (reclaim, ``q_alloc``/``q_deserved`` given) its
    queue's share stays at/above deserved after the eviction — a queue
    is never reclaimed below its deserved share.  Gain is
    measured in whole gang tasks (``fit_counts``); selection stops at
    ``need`` covered or ``cap`` victims.  Victims on nodes whose final
    fit never improved are pruned (their slot never completed — the
    eviction would free nothing the gang can use).  Mutates none of its
    inputs.
    """
    order = np.asarray(order, np.int64)
    eligible = np.asarray(eligible, bool)
    v_node = np.asarray(v_node, np.int64)
    v_req = np.asarray(v_req, F)
    v_job = np.asarray(v_job, np.int64)
    idle = np.asarray(idle, F)
    ev = np.asarray(evictable, F)

    touched = np.unique(v_node[eligible]) if eligible.any() else \
        np.zeros(0, np.int64)
    fit0: Dict[int, int] = {}
    gain_ok: Dict[int, bool] = {}
    if len(touched):
        base = fit_counts(idle[touched], prof_req, eps)
        drained = fit_counts(idle[touched] + ev[touched], prof_req, eps)
        for i, n in enumerate(touched.tolist()):
            fit0[n] = int(base[i])
            gain_ok[n] = bool(drained[i] > base[i])

    def walk(budgets: Dict[str, int]):
        freed: Dict[int, np.ndarray] = {}
        cur_fit: Dict[int, int] = {}
        occupancy: Dict[int, int] = {}
        qa = None if q_alloc is None else np.array(q_alloc, F)
        chosen: List[int] = []
        gain = 0
        skipped_budget = False
        for idx in order.tolist():
            if not eligible[idx]:
                break  # ineligible rows are sorted to the tail
            if gain >= need or len(chosen) >= cap:
                break
            n = int(v_node[idx])
            if not gain_ok.get(n, False):
                continue
            j = int(v_job[idx])
            cnt = occupancy.get(j)
            if cnt is None:
                cnt = int(j_ready[j]) if 0 <= j < len(j_ready) else 0
            minav = int(j_minav[j]) if 0 <= j < len(j_minav) else 1
            if not (minav <= cnt - 1 or minav == 1):
                continue  # gang tier: job would drop below minAvailable
            g = v_group[idx]
            if budgets.get(g, 0) < 1:
                skipped_budget = True
                continue
            if qa is not None:
                # Proportion tier: the victim queue must stay AT or
                # ABOVE its deserved share after the eviction — the
                # same share metric the kernel's overuse gate reads.
                # Unknown queues (defensive: eligibility already
                # excludes them) are never reclaimable.
                q = int(v_queue[idx])
                if not 0 <= q < len(qa):
                    continue
                after = queue_shares(
                    (qa[q] - v_req[idx])[None, :],
                    q_deserved[q][None, :])[0]
                if after < 1.0 - SHARE_TOL:
                    continue  # queue would drop below deserved
                qa[q] = qa[q] - v_req[idx]
            occupancy[j] = cnt - 1
            budgets[g] = budgets.get(g, 0) - 1
            f = freed.get(n)
            if f is None:
                f = freed[n] = np.zeros(v_req.shape[1], F)
            old = cur_fit.get(n, fit0[n])
            f += v_req[idx]
            new = int(fit_counts(idle[n] + f, prof_req, eps)[0])
            cur_fit[n] = new
            gain += new - old
            chosen.append(idx)
        # Prune whole nodes whose fit never improved: every victim
        # taken there freed a partial slot the gang cannot use.
        dead = {n for n in freed
                if cur_fit.get(n, fit0[n]) <= fit0[n]}
        if dead:
            chosen = [i for i in chosen if int(v_node[i]) not in dead]
        return chosen, gain, skipped_budget

    chosen, gain, skipped = walk(dict(budget_left))
    if gain >= need:
        return VictimSelection(chosen=chosen, feasible=True,
                               budget_blocked=False, gain=gain)
    blocked = False
    if skipped:
        # Label the outcome honestly: budgets blocked the plan only if
        # the same greedy with unlimited budgets (same cap, same gang
        # floors, same queue slack) would have covered the need.
        inf = {g: 1 << 30 for g in set(v_group)}
        _, ugain, _ = walk(inf)
        blocked = ugain >= need
    return VictimSelection(chosen=[], feasible=False,
                           budget_blocked=blocked, gain=gain)
