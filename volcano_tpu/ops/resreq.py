"""Vectorized resource-fit kernels.

Device mirror of ``Resource.LessEqual`` / ``IsEmpty``
(pkg/scheduler/api/resource_info.go:96-108,286-320).  These are the innermost
predicates of the allocate/preempt hot loops; they must agree bit-for-bit with
the host model in ``volcano_tpu.api.resource`` (cross-checked by
tests/test_ops.py against randomized Resource pairs).

Shapes follow the convention: ``l``/``r`` are [..., R] resource vectors,
``eps`` is the [R] per-slot quantum, ``scalar_slot`` the [R] bool mask of
extended-resource slots.
"""

from __future__ import annotations

import jax.numpy as jnp


def less_equal(l, r, eps, scalar_slot):
    """Epsilon-tolerant fit: per-slot ``l < r or |l-r| < eps``; extended
    scalar slots requesting <= one quantum always pass.  Reduces over the
    trailing resource axis.  Broadcasts l and r."""
    per_slot = (l < r) | (jnp.abs(l - r) < eps)
    per_slot = per_slot | (scalar_slot & (l <= eps))
    return jnp.all(per_slot, axis=-1)


def less_equal_strict(l, r):
    """Plain elementwise <= reduction (LessEqualStrict)."""
    return jnp.all(l <= r, axis=-1)


def less(l, r, eps, scalar_slot):
    """Strict elementwise < (resource_info.go:226-261) on dense vectors.

    The host model maps empty scalars to zero slots, so the Go nil-map edge
    becomes: a zero scalar slot on the left passes only when the right side
    exceeds one quantum (mirrors "if rrQuant <= min: return false" for a
    nil-scalar receiver); nonzero slots use plain strict less."""
    per_slot = l < r
    # Absent-vs-absent is vacuously fine; absent-vs-sub-quantum fails.
    zero_left_ok = scalar_slot & (l == 0) & ((r == 0) | (r > eps))
    nonzero = ~scalar_slot | (l > 0)
    return jnp.all((per_slot & nonzero) | zero_left_ok, axis=-1)


def is_empty(v, eps):
    """All slots below their quantum (IsEmpty)."""
    return jnp.all(v < eps, axis=-1)
