"""Static predicate-mask kernels: the [P, N] boolean gate.

Device replacement for the per-(task, node) predicate fan-out
(``pkg/scheduler/util/scheduler_helper.go:43-118`` running the predicates
plugin, ``pkg/scheduler/plugins/predicates/predicates.go:144-293``): node
readiness/schedulability, node-selector and required node-affinity label
matching, taint/toleration, host-port conflicts.  Resource fit and pod-count
are *dynamic* (they change as the solver assigns) and live in the allocate
kernel; everything here is constant within one session.

All label/taint/port predicates are bitset algebra over the session
dictionaries built by ``volcano_tpu.arrays.encode_cluster``.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..arrays.schema import ClusterArrays


def selector_mask(sel_bits, has_selector, node_label_bits):
    """[P,N] node-selector match: task's required label pairs must be a
    subset of the node's label pairs."""
    # sel_bits [P, LW], node_label_bits [N, LW] -> [P, N]
    missing = sel_bits[:, None, :] & ~node_label_bits[None, :, :]
    ok = jnp.all(missing == 0, axis=-1)
    return ok | ~has_selector[:, None]


def affinity_mask(aff_bits, aff_terms, node_label_bits):
    """[P,N] required node-affinity: node matches ANY of the task's
    alternative terms (k8s nodeSelectorTerms OR semantics)."""
    # aff_bits [P, A, LW], node_label_bits [N, LW] -> [P, A, N]
    missing = aff_bits[:, :, None, :] & ~node_label_bits[None, None, :, :]
    term_ok = jnp.all(missing == 0, axis=-1)  # [P, A, N]
    A = aff_bits.shape[1]
    term_real = jnp.arange(A)[None, :] < aff_terms[:, None]  # [P, A]
    any_ok = jnp.any(term_ok & term_real[:, :, None], axis=1)  # [P, N]
    return any_ok | (aff_terms == 0)[:, None]


def taint_mask(tol_bits, node_taint_bits):
    """[P,N] taint/toleration: every gating (NoSchedule/NoExecute) taint on
    the node must be tolerated by the task."""
    untolerated = node_taint_bits[None, :, :] & ~tol_bits[:, None, :]
    return jnp.all(untolerated == 0, axis=-1)


def port_mask(task_port_bits, node_port_bits):
    """[P,N] host-port conflict: requested ports must be disjoint from the
    ports already used on the node."""
    clash = task_port_bits[:, None, :] & node_port_bits[None, :, :]
    return jnp.all(clash == 0, axis=-1)


def static_predicate_mask(arrays: ClusterArrays):
    """Combine all static predicates into one [P, N] mask.

    Port state is seeded from the snapshot; the allocate kernel keeps its own
    dynamic copy for ports/pod-counts as it assigns.
    """
    t, n = arrays.tasks, arrays.nodes
    mask = n.ready[None, :] & n.real[None, :] & t.real[:, None]
    mask &= selector_mask(t.sel_bits, t.has_selector, n.label_bits)
    mask &= affinity_mask(t.aff_bits, t.aff_terms, n.label_bits)
    mask &= taint_mask(t.tol_bits, n.taint_bits)
    return mask
