"""Node-scoring kernels: binpack, least-requested, balanced allocation.

Device replacements for the NodeOrderFn score loops
(``pkg/scheduler/util/scheduler_helper.go:121-183`` running
``pkg/scheduler/plugins/binpack/binpack.go:200-260`` and
``pkg/scheduler/plugins/nodeorder/nodeorder.go:172-235`` which wrap the
upstream LeastRequested / BalancedResourceAllocation priorities).  Scores are
additive across enabled scorers, exactly like Session.NodeOrderFn
(session_plugins.go:448-468).

Each scorer takes per-task request vectors and the *current* node state
(used = allocatable - idle evolves as the solver assigns), returning [N]
scores for a single task row; the allocate kernel evaluates them per step,
and ``score_matrix`` vmaps them for batch uses (preempt node ranking).

MAX_PRIORITY mirrors schedulerapi.MaxPriority (=10).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

MAX_PRIORITY = 10.0


class ScoreWeights(NamedTuple):
    """Enable/weight knobs for the additive scorers.

    binpack_* mirrors binpack.go:94-151 (per-resource weights, [R] vector);
    nodeorder weights mirror nodeorder.go:95-124.  A weight of 0 disables a
    scorer.
    """

    binpack_weight: float  # BinPackingWeight
    binpack_res: jnp.ndarray  # [R] per-resource weights (cpu=1, mem=1, ...)
    least_req_weight: float  # leastrequested.weight (default 1)
    most_req_weight: float  # mostrequested.weight (default 0)
    balanced_weight: float  # balancedresource.weight (default 1)
    node_affinity_weight: float  # nodeaffinity.weight (default 1)


def binpack_score(req, allocatable, used, weights: ScoreWeights):
    """Best-fit: sum_r weight_r * (used_r + req_r) / capacity_r over the
    resources the task requests, normalized to [0, 10] * BinPackingWeight
    (binpack.go:200-260)."""
    requested = req[None, :]  # [1, R] vs [N, R] nodes
    used_finally = used + requested
    valid = (
        (requested > 0)
        & (allocatable > 0)
        & (weights.binpack_res[None, :] > 0)
        & (used_finally <= allocatable)
    )
    per_res = jnp.where(
        valid,
        used_finally * weights.binpack_res[None, :] / jnp.where(allocatable > 0, allocatable, 1.0),
        0.0,
    )
    # weightSum counts every requested resource with a configured weight,
    # even when the over-capacity guard zeroed its score (binpack.go:227-236).
    counted = (requested > 0) & (weights.binpack_res[None, :] > 0)
    weight_sum = jnp.sum(
        jnp.where(counted, weights.binpack_res[None, :], 0.0), axis=-1
    )
    score = jnp.sum(per_res, axis=-1)
    score = jnp.where(weight_sum > 0, score / weight_sum, score)
    return score * MAX_PRIORITY * weights.binpack_weight


def least_requested_score(req, allocatable, used, weights: ScoreWeights):
    """((capacity - requested) * 10 / capacity) averaged over cpu+mem
    (upstream LeastRequestedPriorityMap wrapped at nodeorder.go:188-194)."""
    requested = used[:, :2] + req[None, :2]
    cap = allocatable[:, :2]
    per = jnp.where(
        cap > 0, jnp.clip(cap - requested, min=0.0) * MAX_PRIORITY / jnp.where(cap > 0, cap, 1.0), 0.0
    )
    return per.mean(axis=-1) * weights.least_req_weight


def most_requested_score(req, allocatable, used, weights: ScoreWeights):
    """(requested * 10 / capacity) averaged over cpu+mem (upstream
    MostRequestedPriorityMap; enabled when mostrequested.weight > 0)."""
    requested = used[:, :2] + req[None, :2]
    cap = allocatable[:, :2]
    per = jnp.where(
        (cap > 0) & (requested <= cap),
        requested * MAX_PRIORITY / jnp.where(cap > 0, cap, 1.0),
        0.0,
    )
    return per.mean(axis=-1) * weights.most_req_weight


def balanced_score(req, allocatable, used, weights: ScoreWeights):
    """10 - |cpuFraction - memFraction| * 10; zero when any fraction > 1
    (upstream BalancedResourceAllocationMap wrapped at nodeorder.go:196-202)."""
    requested = used[:, :2] + req[None, :2]
    cap = allocatable[:, :2]
    frac = jnp.where(cap > 0, requested / jnp.where(cap > 0, cap, 1.0), 1.0)
    diff = jnp.abs(frac[:, 0] - frac[:, 1])
    score = jnp.where(
        jnp.any(frac > 1.0, axis=-1), 0.0, (1.0 - diff) * MAX_PRIORITY
    )
    return score * weights.balanced_weight


def node_score(req, allocatable, idle, weights: ScoreWeights):
    """Additive score for one task over all nodes ([N]); used = alloc-idle."""
    used = allocatable - idle
    s = binpack_score(req, allocatable, used, weights)
    s = s + least_requested_score(req, allocatable, used, weights)
    s = s + most_requested_score(req, allocatable, used, weights)
    s = s + balanced_score(req, allocatable, used, weights)
    return s


def default_weights(width: int, binpack_enabled: bool = False,
                    nodeorder_enabled: bool = True) -> ScoreWeights:
    """Weights matching the reference defaults: nodeorder on (least=1,
    balanced=1), binpack per helm config (cpu=1, mem=1, weight=1)."""
    return ScoreWeights(
        binpack_weight=1.0 if binpack_enabled else 0.0,
        binpack_res=jnp.ones((width,), jnp.float32),
        least_req_weight=1.0 if nodeorder_enabled else 0.0,
        most_req_weight=0.0,
        balanced_weight=1.0 if nodeorder_enabled else 0.0,
        node_affinity_weight=1.0 if nodeorder_enabled else 0.0,
    )
