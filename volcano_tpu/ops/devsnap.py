"""Device-resident snapshot planes with delta uploads.

The synchronous cycle re-shipped every solver input each solve, although
most node-side planes — allocatable capacity, label/taint bit planes,
max-task counts, readiness, topology domains — change only when the NODE
table changes (the mirror's epoch key), not per cycle.  Through a
remote-TPU tunnel (~35 MB/s effective into-execution bandwidth,
BASELINE.md) those re-uploads sit on the dispatch path of every cycle.

``DeviceSnapshot`` keeps one persistent per-device array per plane,
keyed by the mirror epoch + plane shape:

- key unchanged  -> the cached device array is handed straight to the
  jit call: zero upload, zero host copy;
- epoch advanced with shapes intact -> only the rows the mirror recorded
  dirty (``StoreMirror.node_delta_rows``) are uploaded and scattered
  into the DONATED persistent buffer (``donate_argnums`` on the scatter
  carry, so steady-state updates allocate nothing device-side);
- shape changed / delta unprovable -> full re-upload.

One snapshot instance lives per store (``store.device_snapshot``),
created by the fast path on first use.  It serves the single-process
wave path AND the mesh path: a mesh store's snapshot commits every node
plane with the node-axis ``NamedSharding`` (each chip holds only its
node shard) and the delta scatter then runs SHARD-LOCAL — node churn
costs one small scatter on the owning chip instead of a full
host->device re-upload of every plane on every chip.  Only the remote
split stays out (it ships numpy frames; the child process owns its own
device state).
"""

from __future__ import annotations

import logging
import os
from functools import partial
from typing import Callable, Dict, Optional, Tuple

import jax
import numpy as np

log = logging.getLogger(__name__)

# Above this fraction of rows dirty, a full re-upload beats the scatter.
DELTA_MAX_FRACTION = 0.25


def budget_bytes() -> int:
    """Per-scatter host-staging budget for delta uploads
    (``VOLCANO_TPU_DEVSNAP_BUDGET_MB``, default 256 MB).

    The delta path materializes one host values array per plane before
    the device scatter; at the 100k-node tier a churn burst can mark a
    quarter of the table dirty, and building every plane's full delta
    at once would spike the host (and transfer-staging) footprint by
    the sum of the planes.  Chunking each plane's delta to this budget
    bounds the peak at (largest single chunk) instead — the same
    degrade-the-burst discipline as the affinity chunk budget
    (fastpath._solve_chunks)."""
    try:
        mb = float(os.environ.get("VOLCANO_TPU_DEVSNAP_BUDGET_MB", 256))
    except ValueError:
        mb = 256.0
    # Fractional MB are accepted so tests can force the chunked path at
    # toy shapes; the 4 KB floor keeps a hostile/typo'd value from
    # degenerating to row-at-a-time scatters.
    return max(4096, int(mb * 1_000_000))


def _chunk_rows_for(row_nbytes: int) -> int:
    """Rows per delta-scatter chunk under the budget (pow2 so repeated
    bursts reuse one compiled scatter per plane instead of one per
    distinct chunk length)."""
    rows = max(1, budget_bytes() // max(1, row_nbytes))
    p = 1
    while p * 2 <= rows:
        p *= 2
    return p


@partial(jax.jit, donate_argnums=(0,))
def _scatter_rows(buf, rows, vals):
    """Write ``vals`` into ``buf`` at ``rows`` (leading axis), reusing the
    donated buffer in place.  Padded duplicate rows rewrite the same
    value — idempotent."""
    return buf.at[rows].set(vals)


def _pad_delta(rows: np.ndarray, vals: np.ndarray):
    """Pad a delta to a headroomed pow2 bucket (ops.wave.bucket_pow2:
    +25% so dirty-row counts hovering at a power of two don't flip
    buckets cycle-to-cycle — each flip recompiles the scatter) so the
    jit compiles per bucket, not per distinct dirty-row count
    (duplicates of row 0 are idempotent rewrites)."""
    from .wave import bucket_pow2

    k = bucket_pow2(len(rows), floor=8)
    pad = k - len(rows)
    if pad:
        rows = np.concatenate([rows, np.full(pad, rows[0], rows.dtype)])
        vals = np.concatenate(
            [vals, np.repeat(vals[:1], pad, axis=0)], axis=0
        )
    return rows.astype(np.int32), vals


class DeviceSnapshot:
    """Persistent per-device plane set for one store (see module doc).

    ``mesh`` (optional ``jax.sharding.Mesh``) makes the snapshot
    mesh-native: node planes commit with the node-axis NamedSharding
    (replicated only when the padded node axis does not divide the mesh
    — tiny clusters), the class tables replicate, and the dirty-row
    delta scatter inherits the sharded donated buffer, so each update
    touches only the owning shard.
    """

    def __init__(self, mesh=None):
        self.mesh = mesh
        self._node_shd = None
        self._rep_shd = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from ..parallel.mesh import NODES_AXIS

            self._node_shd = NamedSharding(mesh,
                                           PartitionSpec(NODES_AXIS))
            self._rep_shd = NamedSharding(mesh, PartitionSpec())
        # name -> device array, all planes sharing self._key.
        self._planes: Dict[str, object] = {}
        self._key: Optional[Tuple] = None
        # Two-phase class tables ([C, *], tiny), content-addressed.
        self._cls_planes: Dict[str, object] = {}
        self._cls_key: Optional[Tuple] = None
        # Telemetry for tests/bench: full vs delta vs hit counts.
        self.full_uploads = 0
        self.delta_uploads = 0
        self.hits = 0
        self.class_uploads = 0
        self.class_hits = 0
        # Extra scatter passes taken because a delta exceeded the
        # per-scatter staging budget (see budget_bytes).
        self.delta_chunks = 0

    # ------------------------------------------------------------ placement

    def _put_plane(self, a: np.ndarray):
        """Commit one full node plane: node-axis sharded on a mesh
        (when the axis divides), single default device otherwise."""
        if self._node_shd is not None:
            n_dev = self.mesh.devices.size
            if a.ndim and a.shape[0] % n_dev == 0:
                return jax.device_put(a, self._node_shd)
            return jax.device_put(a, self._rep_shd)
        return jax.device_put(a)

    def _put_delta(self, rows: np.ndarray, vals: np.ndarray):
        """Commit a padded delta (replicated on a mesh: every chip needs
        the row ids to decide ownership; the values are tiny)."""
        if self._rep_shd is not None:
            return (jax.device_put(rows, self._rep_shd),
                    jax.device_put(vals, self._rep_shd))
        return rows, vals

    # ------------------------------------------------------------- planes

    # Called only from FastCycle._solve_inputs, inside the cycle's
    # ``with store._lock`` (holds: _lock) — the mirror delta reads and
    # resets below mutate store-guarded state.
    # holds: _lock
    def node_planes(self, m, key: Tuple,
                    build: Dict[str, Callable[[], np.ndarray]]):
        """Return ``{name: device_array}`` for the node-side planes.

        ``key`` is ``(epoch, shape components...)`` with the epoch FIRST;
        ``build[name](rows)`` returns the full padded host plane when
        ``rows`` is None, or just those rows' values for a delta scatter
        (only called on upload — a key hit touches no host memory).  All
        planes move together under one key."""
        if self._key == key and self._planes.keys() == build.keys():
            self.hits += 1
            return self._planes
        delta_rows = None
        if (
            self._key is not None
            and self._key[1:] == key[1:]
            and self._planes.keys() == build.keys()
        ):
            delta_rows = m.node_delta_rows(self._key[0])
            n_rows = key[1] if len(key) > 1 else 0
            if delta_rows is not None and (
                len(delta_rows) == 0
                or len(delta_rows) > max(1, int(n_rows))
                * DELTA_MAX_FRACTION
            ):
                delta_rows = None if len(delta_rows) else delta_rows
        if delta_rows is not None and len(delta_rows) == 0:
            # Epoch moved but no node rows recorded dirty (defensive —
            # epoch bumps outside the node table); planes are current.
            m.reset_node_delta()
            self._key = key
            self.hits += 1
            return self._planes
        if delta_rows is not None:
            for name, fn in build.items():
                # One-row probe sizes the plane's delta chunks (and
                # detects the delta-unprovable answer) without
                # materializing the full values array first.
                probe = fn(delta_rows[:1])
                if probe is None:
                    # Plane-level delta unprovable — a build fn returns
                    # None when its rows cannot be patched in place
                    # (class ids after the class SET changed: unrelated
                    # rows' ids shift under the sorted-signature
                    # ordering).  Re-upload just this plane; the others
                    # keep the scatter path.
                    self._planes[name] = self._put_plane(
                        np.asarray(fn(None))
                    )
                    continue
                # Chunked delta scatter (the scale-tier memory budget):
                # each chunk's host values stay under budget_bytes(),
                # so a churn burst at 100k nodes peaks at one chunk of
                # staging memory per plane, not the whole delta.
                row_nb = max(1, np.asarray(probe).nbytes)
                chunk = _chunk_rows_for(row_nb)
                if len(delta_rows) <= chunk:
                    dvals = probe if len(delta_rows) == 1 \
                        else fn(delta_rows)
                    rows, vals = _pad_delta(delta_rows,
                                            np.asarray(dvals))
                    rows, vals = self._put_delta(rows, vals)
                    self._planes[name] = _scatter_rows(
                        self._planes[name], rows, vals
                    )
                    continue
                # Multi-chunk: pad every chunk (incl. the last) to
                # exactly ``chunk`` rows with idempotent duplicates —
                # one compiled scatter per plane shape AND the staging
                # footprint stays AT the budget (_pad_delta's +25%
                # headroom bucket would double a full pow2 chunk past
                # it).
                n_chunks = 0
                for lo in range(0, len(delta_rows), chunk):
                    crows = delta_rows[lo:lo + chunk]
                    vals = np.asarray(fn(crows))
                    pad = chunk - len(crows)
                    if pad:
                        crows = np.concatenate(
                            [crows, np.full(pad, crows[0], crows.dtype)]
                        )
                        vals = np.concatenate(
                            [vals, np.repeat(vals[:1], pad, axis=0)],
                            axis=0,
                        )
                    rows, vals = self._put_delta(
                        crows.astype(np.int32), vals
                    )
                    self._planes[name] = _scatter_rows(
                        self._planes[name], rows, vals
                    )
                    n_chunks += 1
                self.delta_chunks += max(0, n_chunks - 1)
            m.reset_node_delta()
            self._key = key
            self.delta_uploads += 1
            return self._planes
        self._planes = {
            name: self._put_plane(np.asarray(fn(None)))
            for name, fn in build.items()
        }
        m.reset_node_delta()
        self._key = key
        self.full_uploads += 1
        return self._planes

    def resident_bytes(self) -> int:
        """Modeled device-resident footprint of the snapshot: the sum
        of every committed plane's (and class table's) nbytes.  The
        scale-tier budget test asserts this stays within the modeled
        envelope at 100k nodes, and peak TRANSIENT staging adds at most
        one ``budget_bytes()`` chunk on top (the chunked delta
        scatter)."""
        total = 0
        for group in (self._planes, self._cls_planes):
            for arr in group.values():
                size = int(np.prod(getattr(arr, "shape", ()) or (1,)))
                total += size * int(
                    np.dtype(getattr(arr, "dtype", np.uint8)).itemsize
                )
        return total

    def class_tables(self, key: Tuple,
                     build: Dict[str, Callable[[], np.ndarray]]):
        """Device-resident node-class tables for the two-phase solve
        ([C, *] rows — tiny next to the node planes).

        ``key`` is content-addressed (the nodeclass tables_sig digest +
        shape components), so epoch churn that leaves the class SET
        intact re-uploads nothing; a changed signature set re-uploads
        the tables wholesale.  The [N] ``class_id`` plane is NOT here:
        it rides ``node_planes``' dirty-row delta machinery, whose
        build fn answers None (-> single-plane full upload) whenever
        the signature set moved — the condition under which per-row
        class_id deltas would be unsound (see ops/nodeclass.py on the
        sorted-signature class ordering)."""
        if self._cls_key == key:
            self.class_hits += 1
            return self._cls_planes
        # Class tables are the COMPACTED [C, *] representation — tiny,
        # so a mesh replicates them (every chip classifies its own node
        # shard against the full table set).
        _put = (jax.device_put if self._rep_shd is None
                else (lambda a: jax.device_put(a, self._rep_shd)))
        self._cls_planes = {
            name: _put(np.asarray(fn()))
            for name, fn in build.items()
        }
        self._cls_key = key
        self.class_uploads += 1
        return self._cls_planes


def for_store(store, mesh=None) -> DeviceSnapshot:
    """The store's snapshot, created on first use.  ``mesh`` (the
    store's ``solve_mesh``) selects the mesh-sharded placement; a
    snapshot built for a different mesh (or none) is replaced wholesale
    — its planes live on the wrong device set."""
    snap = getattr(store, "device_snapshot", None)
    if snap is None or getattr(snap, "mesh", None) is not mesh:
        snap = store.device_snapshot = DeviceSnapshot(mesh=mesh)
    return snap
